/*
 * spfft_tpu C API — native entry points for C/C++/Fortran callers.
 *
 * Role-equivalent of the reference C API (reference: include/spfft/grid.h,
 * transform.h, errors.h): opaque plan handles, integer error codes, and
 * interleaved-complex buffers. The compute path behind these calls is the
 * JAX/XLA pipeline of the spfft_tpu Python package, hosted by an embedded
 * CPython interpreter inside libspfft_tpu.so (see native/capi.cpp).
 *
 * Buffer conventions (identical to the Python API, and to the reference's
 * space-domain layout (z*Ny + y)*Nx + x, docs/source/details.rst "Indexing"):
 *   - frequency values: interleaved complex, 2*num_values reals
 *   - C2C space domain: interleaved complex, 2*dimX*dimY*dimZ reals
 *   - R2C space domain: dimX*dimY*dimZ reals
 *   - element type: float for SPFFT_TPU_PREC_SINGLE, double for DOUBLE
 *
 * Thread-safety: calls may come from any thread; the library serialises on
 * the embedded interpreter's GIL. A plan handle must not be used after
 * spfft_tpu_plan_destroy.
 */

#ifndef SPFFT_TPU_H
#define SPFFT_TPU_H

#ifdef __cplusplus
extern "C" {
#endif

/* Error codes, matching spfft_tpu.ErrorCode (Python) which mirrors the
 * reference SpfftError enum (reference: include/spfft/errors.h:33-126). */
typedef enum SpfftTpuError {
  SPFFT_TPU_SUCCESS = 0,
  SPFFT_TPU_UNKNOWN_ERROR = 1,
  SPFFT_TPU_INVALID_HANDLE_ERROR = 2,
  SPFFT_TPU_OVERFLOW_ERROR = 3,
  SPFFT_TPU_ALLOCATION_ERROR = 4,
  SPFFT_TPU_INVALID_PARAMETER_ERROR = 5,
  SPFFT_TPU_DUPLICATE_INDICES_ERROR = 6,
  SPFFT_TPU_INVALID_INDICES_ERROR = 7,
  SPFFT_TPU_DISTRIBUTED_SUPPORT_ERROR = 8,
  SPFFT_TPU_DISTRIBUTED_ERROR = 9,
  SPFFT_TPU_PARAMETER_MISMATCH_ERROR = 10,
  SPFFT_TPU_HOST_EXECUTION_ERROR = 11,
  SPFFT_TPU_FFT_ERROR = 12,
  SPFFT_TPU_DEVICE_ERROR = 13,
  SPFFT_TPU_DEVICE_SUPPORT_ERROR = 15,
  SPFFT_TPU_DEVICE_ALLOCATION_ERROR = 16,
  SPFFT_TPU_DEVICE_FFT_ERROR = 22,
  /* C-layer-only: the embedded interpreter could not be started or the
   * spfft_tpu package could not be imported. */
  SPFFT_TPU_RUNTIME_INIT_ERROR = 100
} SpfftTpuError;

/* Transform type (reference: types.h:85-95). */
typedef enum SpfftTpuTransformType {
  SPFFT_TPU_TRANS_C2C = 0,
  SPFFT_TPU_TRANS_R2C = 1
} SpfftTpuTransformType;

/* Forward-transform scaling (reference: types.h:97-106). */
typedef enum SpfftTpuScalingType {
  SPFFT_TPU_NO_SCALING = 0,
  SPFFT_TPU_FULL_SCALING = 1
} SpfftTpuScalingType;

/* Element precision (reference float twins GridFloat/TransformFloat). */
typedef enum SpfftTpuPrecision {
  SPFFT_TPU_PREC_SINGLE = 0,
  SPFFT_TPU_PREC_DOUBLE = 1
} SpfftTpuPrecision;

/* Distributed exchange algorithm (reference: SpfftExchangeType,
 * types.h:33-62 — same order/meaning; FLOAT variants halve on-wire
 * precision). */
typedef enum SpfftTpuExchangeType {
  SPFFT_TPU_EXCH_DEFAULT = 0,
  SPFFT_TPU_EXCH_BUFFERED = 1,
  SPFFT_TPU_EXCH_BUFFERED_FLOAT = 2,
  SPFFT_TPU_EXCH_COMPACT_BUFFERED = 3,
  SPFFT_TPU_EXCH_COMPACT_BUFFERED_FLOAT = 4,
  SPFFT_TPU_EXCH_UNBUFFERED = 5
} SpfftTpuExchangeType;

/* Compression-kernel routing: AUTO picks the Pallas windowed-gather kernel
 * when it is expected to win (TPU backend, single precision, coherent
 * value order); ON forces it (error if unsupported); OFF forces the plain
 * XLA gather path. */
typedef enum SpfftTpuPallasMode {
  SPFFT_TPU_PALLAS_AUTO = -1,
  SPFFT_TPU_PALLAS_OFF = 0,
  SPFFT_TPU_PALLAS_ON = 1
} SpfftTpuPallasMode;

/*
 * ABI version of this header. Incremented whenever an exported signature
 * changes (ABI 2: plan-create entry points gained trailing use_pallas /
 * exchange_type ints). A caller compiled against an older header keeps
 * linking but passes garbage for new trailing arguments — check
 *   spfft_tpu_abi_version() == SPFFT_TPU_ABI_VERSION
 * once at startup to fail loudly instead (the reference pins
 * compatibility the CMake-package way; a C macro plus runtime probe is
 * the plain-linker equivalent).
 */
#define SPFFT_TPU_ABI_VERSION 2

/* Opaque plan handle (reference: SpfftTransform, transform.h). */
typedef void* SpfftTpuPlan;

/* The ABI version the loaded library was BUILT with (compare against
 * SPFFT_TPU_ABI_VERSION from the header you compiled against). */
int spfft_tpu_abi_version(void);

/*
 * Start the embedded interpreter and import the spfft_tpu package.
 * package_path may name a directory to prepend to the module search path
 * (pass NULL if spfft_tpu is already importable). Safe to call more than
 * once; implicit on first plan creation.
 */
int spfft_tpu_init(const char* package_path);

/*
 * Create a plan for a local sparse 3D FFT (reference:
 * spfft_grid_create + spfft_transform_create collapsed into one call —
 * XLA owns buffer pooling, so the Grid layer's pre-allocation role is
 * moot in C; see Python Grid for the API-parity wrapper).
 *
 * index_triplets: num_values x 3 ints (x, y, z per value), centered
 * (negative) or storage indexing (reference: types.h SPFFT_INDEX_TRIPLETS).
 * use_pallas: an SpfftTpuPallasMode value (pass SPFFT_TPU_PALLAS_AUTO).
 */
int spfft_tpu_plan_create(SpfftTpuPlan* plan, int transform_type, int dim_x,
                          int dim_y, int dim_z, long long num_values,
                          const int* index_triplets, int precision,
                          int use_pallas);

/*
 * Distributed plan over num_shards devices of this process (reference:
 * spfft_grid_create_distributed + spfft_transform_create, grid.h — the MPI
 * communicator is replaced by the local device mesh; one process drives
 * all shards SPMD-style).
 *
 * values_per_shard: num_shards counts; index_triplets: the per-shard
 * triplet lists concatenated in shard order (sum(values_per_shard) x 3
 * ints); planes_per_shard: slab heights, summing to dim_z. A z-stick must
 * live wholly on one shard.
 *
 * I/O convention for backward/forward on a distributed plan: values are
 * the per-shard value arrays concatenated in shard order (interleaved
 * reals); space is the FULL (dim_z, dim_y, dim_x) cube in global z order
 * (slabs concatenated), interleaved complex for C2C / real for R2C.
 *
 * exchange_type: an SpfftTpuExchangeType value (the reference's
 * distributed-grid exchangeType parameter, grid.h:60-118).
 * use_pallas: an SpfftTpuPallasMode value (pass SPFFT_TPU_PALLAS_AUTO).
 */
int spfft_tpu_plan_create_distributed(SpfftTpuPlan* plan, int transform_type,
                                      int dim_x, int dim_y, int dim_z,
                                      int num_shards,
                                      const long long* values_per_shard,
                                      const int* index_triplets,
                                      const int* planes_per_shard,
                                      int precision, int exchange_type,
                                      int use_pallas);

int spfft_tpu_plan_destroy(SpfftTpuPlan plan);

/*
 * Frequency -> space (reference: spfft_transform_backward, transform.h).
 * values: 2*num_values reals (interleaved). space: the full local cube in
 * the layout documented above. Unnormalised inverse DFT.
 */
int spfft_tpu_backward(SpfftTpuPlan plan, const void* values, void* space);

/*
 * Space -> frequency (reference: spfft_transform_forward, transform.h).
 * scaling: SPFFT_TPU_NO_SCALING or SPFFT_TPU_FULL_SCALING (1/(Nx*Ny*Nz)).
 */
int spfft_tpu_forward(SpfftTpuPlan plan, const void* space, int scaling,
                      void* values);

/*
 * Fused round trip: backward, then forward with the given scaling, as ONE
 * device program — the plane-wave-code inner loop (the reference
 * benchmark's repeated backward+forward pair, tests/programs/benchmark.cpp
 * :84-96), without the two dispatch round trips and four marshalling
 * copies of calling spfft_tpu_backward + spfft_tpu_forward.
 *
 * values_in/values_out: 2*num_values reals each (interleaved; per-shard
 * arrays concatenated in shard order for distributed plans). In-place
 * operation (values_out == values_in) is allowed. With
 * SPFFT_TPU_FULL_SCALING the pair is the identity up to roundoff.
 */
int spfft_tpu_execute_pair(SpfftTpuPlan plan, const void* values_in,
                           int scaling, void* values_out);

/*
 * Batched execution of num_transforms independent transforms (reference:
 * spfft_multi_transform_backward / _forward, multi_transform.h:37-72).
 * plans/values/spaces are arrays of num_transforms entries; buffer layouts
 * per entry are exactly those of spfft_tpu_backward / spfft_tpu_forward.
 * Passing the SAME plan handle for every entry (local or distributed)
 * executes the batch as one fused device program (the TPU-native form of
 * the reference's comm/compute overlap schedule). Distinct handles
 * dispatch every local transform before the first synchronisation;
 * distinct DISTRIBUTED handles synchronise per transform (their
 * host-side marshalling is inherently synchronous).
 */
int spfft_tpu_multi_backward(int num_transforms, const SpfftTpuPlan* plans,
                             const void* const* values, void* const* spaces);
int spfft_tpu_multi_forward(int num_transforms, const SpfftTpuPlan* plans,
                            const void* const* spaces, int scaling,
                            void* const* values);

/* Getters (reference: spfft_transform_get_* accessors, transform.h:84-245).
 * Each writes one value and returns an error code. */
int spfft_tpu_plan_dim_x(SpfftTpuPlan plan, int* out);
int spfft_tpu_plan_dim_y(SpfftTpuPlan plan, int* out);
int spfft_tpu_plan_dim_z(SpfftTpuPlan plan, int* out);
int spfft_tpu_plan_num_values(SpfftTpuPlan plan, long long* out);
int spfft_tpu_plan_transform_type(SpfftTpuPlan plan, int* out);
/* 1 for local plans, the mesh size for distributed plans. */
int spfft_tpu_plan_num_shards(SpfftTpuPlan plan, int* out);
/* dim_x * dim_y * dim_z (reference: Transform::global_size). */
int spfft_tpu_plan_global_size(SpfftTpuPlan plan, long long* out);
/* Total sparse elements across shards (== num_values; reference:
 * num_global_elements). */
int spfft_tpu_plan_num_global_elements(SpfftTpuPlan plan, long long* out);
/* Per-shard accessors (reference per-rank getters: local_z_offset,
 * local_z_length, local_slice_size, num_local_elements — transform.h).
 * shard must be in [0, num_shards); local plans accept shard 0 only. */
int spfft_tpu_plan_local_z_offset(SpfftTpuPlan plan, int shard, int* out);
int spfft_tpu_plan_local_z_length(SpfftTpuPlan plan, int shard, int* out);
int spfft_tpu_plan_local_slice_size(SpfftTpuPlan plan, int shard,
                                    long long* out);
int spfft_tpu_plan_num_local_elements(SpfftTpuPlan plan, int shard,
                                      long long* out);
/* The SpfftTpuExchangeType of a distributed plan (DEFAULT for local). */
int spfft_tpu_plan_exchange_type(SpfftTpuPlan plan, int* out);
/* 1 when the Pallas compression kernel is active for this plan. */
int spfft_tpu_plan_pallas_active(SpfftTpuPlan plan, int* out);

/* Static message for an error code (never NULL). */
const char* spfft_tpu_error_string(int code);

#ifdef __cplusplus
}
#endif

#endif /* SPFFT_TPU_H */

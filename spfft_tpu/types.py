"""Public enums for spfft_tpu.

Mirrors the reference's ``SpfftExchangeType`` / ``SpfftProcessingUnitType`` /
``SpfftIndexFormatType`` / ``SpfftTransformType`` / ``SpfftScalingType``
(reference: include/spfft/types.h:33-106), re-expressed for a TPU runtime:

* The reference's six MPI exchange algorithms (Alltoall / Alltoallv / Alltoallw,
  each optionally with a single-precision wire format) collapse on TPU to one
  XLA ``all_to_all`` collective over the ICI mesh on a padded block layout (the
  natural fit for XLA's fixed-shape collectives — reference BUFFERED variant,
  types.h:40-46).  The enum is kept so the wire-precision option remains
  selectable: the ``*_FLOAT`` variants cast the exchanged block to the next
  lower precision around the collective, halving ICI bytes exactly as the
  reference halves MPI bytes (docs/source/details.rst "MPI Exchange").
* ``ProcessingUnit`` keeps the HOST=1 / DEVICE=2 bitmask values
  (types.h:67-76, SPFFT_PU_HOST/SPFFT_PU_GPU) so call sites translate 1:1.
  On TPU, DEVICE means "arrays stay committed to TPU HBM"; HOST means numpy
  in/out with implicit transfer.
"""

from __future__ import annotations

import enum


class ExchangeType(enum.Enum):
    """Distributed exchange algorithm selector (reference: types.h:33-62).

    Three mechanically distinct exchanges exist on TPU, mirroring the
    reference's Alltoall / Alltoallv / Alltoallw trio:

    * DEFAULT / BUFFERED — one fused ``lax.all_to_all`` on the padded
      ``(shards, max_sticks, max_planes)`` block layout (the natural fit
      for XLA's fixed-shape collectives — reference BUFFERED,
      types.h:40-46).
    * COMPACT_BUFFERED — the exact-count schedule
      (exchange.CompactSchedule): per-hop exact-size ``ppermute`` buffers
      sized from the plan-time per-pair stick×plane counts, so padding
      bytes stay off the wire on non-uniform distributions (reference
      COMPACT_BUFFERED / MPI_Alltoallv,
      transpose_mpi_compact_buffered_host.cpp:183-200).
    * UNBUFFERED — S-1 single-hop ``ppermute`` ring steps on the padded
      block layout (exchange.ring_exchange_blocks), pipelinable with
      surrounding compute (reference UNBUFFERED / MPI_Alltoallw).

    The ``*_FLOAT`` variants additionally reduce the on-wire precision
    around the exchange, halving ICI bytes exactly as the reference halves
    MPI bytes (docs/source/details.rst "MPI Exchange").

    DEFAULT here maps to the padded BUFFERED mechanism — a documented
    deviation from the reference's COMPACT_BUFFERED default
    (grid_internal.cpp:176-179), justified by the recorded 8/16/32-shard
    comparison in docs/scaling_r04.json: equal busiest-link bytes on
    uniform/mild-skew distributions, ONE fused collective instead of a
    multi-op schedule, and XLA overlap. Pass COMPACT_BUFFERED explicitly
    for strongly skewed caller-chosen distributions (docs/details.md
    "Exchange").
    """

    DEFAULT = "default"
    BUFFERED = "buffered"
    BUFFERED_FLOAT = "buffered_float"
    COMPACT_BUFFERED = "compact_buffered"
    COMPACT_BUFFERED_FLOAT = "compact_buffered_float"
    UNBUFFERED = "unbuffered"

    @property
    def float_wire(self) -> bool:
        """True if the on-wire precision is reduced (reference: types.h:43-57)."""
        return self in (ExchangeType.BUFFERED_FLOAT,
                        ExchangeType.COMPACT_BUFFERED_FLOAT)

    @property
    def compact(self) -> bool:
        """True if the exact-count (ragged) schedule is selected."""
        return self in (ExchangeType.COMPACT_BUFFERED,
                        ExchangeType.COMPACT_BUFFERED_FLOAT)


class ProcessingUnit(enum.IntFlag):
    """Where transform I/O lives (reference: types.h:67-76)."""

    HOST = 1    # SPFFT_PU_HOST
    DEVICE = 2  # SPFFT_PU_GPU — on this framework: TPU HBM


class IndexFormat(enum.Enum):
    """Sparse frequency-index format (reference: types.h:78-83)."""

    TRIPLETS = "triplets"  # SPFFT_INDEX_TRIPLETS: interleaved x,y,z


class TransformType(enum.Enum):
    """Transform kind (reference: types.h:85-95)."""

    C2C = "c2c"
    R2C = "r2c"


class Scaling(enum.Enum):
    """Forward-transform scaling (reference: types.h:97-106; normalization
    spec docs/source/details.rst "Normalization")."""

    NONE = "none"   # SPFFT_NO_SCALING
    FULL = "full"   # SPFFT_FULL_SCALING: multiply forward output by 1/(Nx*Ny*Nz)

"""spfft_tpu — a TPU-native sparse 3D FFT framework.

A from-scratch rebuild of the capabilities of SpFFT (reference mounted at
/root/reference) on JAX/XLA: sparse frequency-domain 3D FFTs (spherical-cutoff
plane-wave sets), C2C and R2C with hermitian-symmetry exploitation, positive
and centered indexing, single/double precision, batched multi-transform
execution, and distributed slab<->pencil decomposition over a TPU device mesh
via ``shard_map`` + ``lax.all_to_all``.
"""

from .errors import (AllocationError, DeadlineExpiredError,
                     DeviceAllocationError, DeviceError,
                     DeviceFFTError, DeviceSupportError, DistributedError,
                     DistributedSupportError, DuplicateIndicesError, ErrorCode,
                     FFTError, GenericError, HostExecutionError, InternalError,
                     InvalidIndicesError, InvalidParameterError, OverflowError_,
                     ParameterMismatchError, PrecisionContractError,
                     QueueFullError, ServeError)
from .indexing import IndexPlan, build_index_plan, check_stick_duplicates
from .parallel import (DistributedIndexPlan, DistributedTransformPlan,
                       build_distributed_plan,
                       build_distributed_plan_multihost,
                       initialize_multihost, make_distributed_plan,
                       make_mesh, plan_fingerprint, validate_consistent)
from . import obs, timing
from .grid import Grid, Transform
from .multi import multi_transform_backward, multi_transform_forward
from .plan import (PlanTables, TransformPlan, make_local_plan,
                   predicted_rel_error, restore_plan)
from .types import (ExchangeType, IndexFormat, ProcessingUnit, Scaling,
                    TransformType)

__version__ = "0.1.0"

__all__ = [
    "ErrorCode", "GenericError", "AllocationError", "OverflowError_",
    "InvalidParameterError",
    "DuplicateIndicesError", "InvalidIndicesError", "DistributedSupportError",
    "DistributedError", "ParameterMismatchError", "HostExecutionError",
    "FFTError", "InternalError", "DeviceError", "DeviceSupportError",
    "DeviceAllocationError", "DeviceFFTError",
    "ServeError", "QueueFullError", "DeadlineExpiredError",
    "ExchangeType", "ProcessingUnit", "IndexFormat", "TransformType",
    "Scaling",
    "IndexPlan", "build_index_plan", "check_stick_duplicates",
    "TransformPlan", "make_local_plan", "predicted_rel_error",
    "PlanTables", "restore_plan",
    "PrecisionContractError",
    "DistributedIndexPlan", "DistributedTransformPlan",
    "build_distributed_plan", "build_distributed_plan_multihost",
    "initialize_multihost", "make_distributed_plan", "make_mesh",
    "plan_fingerprint", "validate_consistent",
    "Grid", "Transform",
    "multi_transform_backward", "multi_transform_forward",
    "timing", "obs",
]

"""Distributed sparse 3D FFT plans over a 1-D device mesh.

The reference's distributed layout (README.md:8, SURVEY.md §5.7): space domain
split into z-plane *slabs* per shard, frequency domain into z-stick *pencils*;
a collective exchange re-localises z between the two (reference:
src/parameters/parameters.cpp:43-140 builds the per-rank distribution plan,
src/execution/execution_host.cpp:249-352 runs the phases around the MPI
alltoall).

TPU-native realisation: one ``shard_map`` over a 1-D mesh whose body is the
whole per-shard pipeline —

  backward:  decompress -> [stick symmetry] -> z-IFFT -> pack ->
             all_to_all -> unpack -> [plane symmetry] -> xy-IFFT
  forward:   xy-FFT -> pack -> all_to_all -> unpack -> z-FFT -> compress

with all per-shard index tables padded to common maxima and passed as sharded
arrays (an SPMD body is traced once, so shard-varying data must be data, not
Python branches). Plan-time validation reproduces the reference's collective
consistency checks centrally: sum-of-planes == dim_z and sum-of-sticks bounds
(parameters.cpp:103-109), global duplicate-stick detection
(indices.hpp:105-117).

Caller-visible array layouts (per shard r, stacked over the shard axis and
sharded with ``PartitionSpec('shards')``):

* frequency values: ``(num_shards, max_values, 2)`` interleaved, shard r's
  values first, zero-padded;
* space domain: ``(num_shards, max_planes, dim_y, dim_x[, 2])`` — shard r's
  slab is rows ``[0, num_planes(r))`` of its block (zero-padded after), the
  global z order being ``plane_offsets(r) + p``.

Helpers convert between these padded device layouts and per-shard numpy lists.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import faults as _faults
from ..errors import InvalidParameterError, ParameterMismatchError
from ..indexing import (build_index_plan, check_stick_duplicates,
                        occupied_x_window, window_sub_cols)
from ..ops import stages
from ..timing import timed_transform
from ..types import ExchangeType, Scaling, TransformType
from ..utils.dtypes import (as_interleaved, complex_dtype,
                            complex_to_interleaved, interleaved_to_complex,
                            real_dtype)
from .exchange import (all_to_all_blocks, build_compact_schedule,
                       build_ragged_schedule, compact_exchange,
                       ragged_exchange, pack_freq_to_blocks,
                       pack_space_to_blocks, ring_exchange_blocks,
                       unpack_blocks_to_grid, unpack_blocks_to_sticks)
from .mesh import make_mesh, shard_map
from .overlap import build_overlap_schedule

#: Environment default for the plan's ``overlap_chunks`` knob: split the
#: distributed exchange into K destination-balanced chunks so the z/xy
#: FFT stages software-pipeline with the collectives (parallel/overlap.py).
#: K=1 (the default) is today's monolithic single-collective path.
OVERLAP_CHUNKS_ENV = "SPFFT_TPU_OVERLAP_CHUNKS"

#: The wire-compression ladder (docs/distributed.md "Compressed wire"):
#: rung index == ``wire_precision`` knob value. Rung 0 ships the payload
#: at transform precision; 1/2 are the typed float downcasts the legacy
#: ``*_FLOAT`` exchange variants hard-coded; 3 quantizes to int8 with
#: per-stick absmax scales packed alongside the payload.
WIRE_RUNGS = ("full", "f32", "bf16", "int8")
WIRE_PRECISION_ENV = "SPFFT_TPU_WIRE_PRECISION"
WIRE_ERROR_BUDGET_ENV = "SPFFT_TPU_WIRE_ERROR_BUDGET"

logger = logging.getLogger("spfft_tpu")


@dataclasses.dataclass(frozen=True)
class DistributedIndexPlan:
    """The global distribution plan: per-shard stick sets + slab split.

    Equivalent of the reference ``Parameters`` in distributed mode
    (reference: parameters.cpp:43-140): per-rank stick counts and xy indices,
    per-rank plane counts and offsets, with the same validation.
    """

    transform_type: TransformType
    dim_x: int
    dim_y: int
    dim_z: int
    shard_plans: tuple
    num_planes: tuple
    plane_offsets: tuple

    @property
    def num_shards(self) -> int:
        return len(self.shard_plans)

    @property
    def max_sticks(self) -> int:
        return max(p.num_sticks for p in self.shard_plans)

    @property
    def max_planes(self) -> int:
        return max(self.num_planes)

    @property
    def max_values(self) -> int:
        return max(p.num_values for p in self.shard_plans)

    @property
    def dim_x_freq(self) -> int:
        return self.shard_plans[0].dim_x_freq

    @property
    def hermitian(self) -> bool:
        return self.transform_type == TransformType.R2C

    @property
    def num_global_elements(self) -> int:
        """Total sparse values across shards (reference:
        transform.hpp:145 num_global_elements)."""
        return sum(p.num_values for p in self.shard_plans)


def build_distributed_plan(transform_type: TransformType,
                           dim_x: int, dim_y: int, dim_z: int,
                           triplets_per_shard: Sequence[np.ndarray],
                           planes_per_shard: Sequence[int],
                           ) -> DistributedIndexPlan:
    """Build and validate the global distribution plan.

    ``triplets_per_shard[r]`` is shard r's sparse triplet list (a z-stick must
    live wholly on one shard — enforced by the duplicate check);
    ``planes_per_shard[r]`` its slab height. The distribution is caller-chosen
    and may be arbitrary/non-uniform including empty shards, exactly like the
    reference (tests/mpi_tests/test_transform.cpp:110-165).
    """
    transform_type = TransformType(transform_type)
    if len(triplets_per_shard) != len(planes_per_shard):
        raise InvalidParameterError(
            "triplets_per_shard and planes_per_shard length mismatch")
    if len(triplets_per_shard) == 0:
        raise InvalidParameterError("need at least one shard")
    planes = tuple(int(p) for p in planes_per_shard)
    if any(p < 0 for p in planes):
        raise InvalidParameterError("negative plane count")
    if sum(planes) != dim_z:
        # reference: parameters.cpp:107-109 (MPIParameterMismatchError)
        raise ParameterMismatchError(
            f"sum of planes per shard ({sum(planes)}) != dim_z ({dim_z})")
    shard_plans = tuple(
        build_index_plan(transform_type, dim_x, dim_y, dim_z,
                         np.asarray(t).reshape(-1, 3))
        for t in triplets_per_shard)
    check_stick_duplicates([p.stick_keys for p in shard_plans])
    total_sticks = sum(p.num_sticks for p in shard_plans)
    if total_sticks > dim_x * dim_y:
        # reference: parameters.cpp:103-106
        raise ParameterMismatchError(
            f"total sticks ({total_sticks}) exceed xy plane size")
    offsets = tuple(int(o) for o in np.concatenate(
        [[0], np.cumsum(planes)[:-1]]))
    return DistributedIndexPlan(
        transform_type=transform_type, dim_x=dim_x, dim_y=dim_y, dim_z=dim_z,
        shard_plans=shard_plans, num_planes=planes, plane_offsets=offsets)


class DistributedTransformPlan:
    """A compiled distributed sparse 3D FFT over a device mesh.

    Equivalent of a distributed reference ``Transform``
    (reference: transform.hpp:56-227 with an MPI communicator).
    """

    def __init__(self, dist_plan: DistributedIndexPlan,
                 mesh: Optional[Mesh] = None, precision: str = "single",
                 exchange: ExchangeType = ExchangeType.DEFAULT,
                 use_pallas: Optional[bool] = None,
                 overlap_chunks: Optional[int] = None,
                 wire_precision: Optional[int] = None,
                 wire_error_budget: Optional[float] = None):
        from ..utils.platform import enable_persistent_compilation_cache
        enable_persistent_compilation_cache()
        _t0_build = time.perf_counter()
        self.dist_plan = dist_plan
        self.precision = precision
        self.exchange = ExchangeType(exchange)
        self.mesh = mesh if mesh is not None else make_mesh(
            dist_plan.num_shards)
        (self.axis_name,) = self.mesh.axis_names
        if self.mesh.devices.size != dist_plan.num_shards:
            raise InvalidParameterError(
                f"mesh has {self.mesh.devices.size} devices but plan has "
                f"{dist_plan.num_shards} shards")
        self._rdt = real_dtype(precision)
        self._cdt = complex_dtype(precision)
        # Reduced wire precision (reference *_FLOAT exchanges, types.h:43-57):
        if precision == "double" and (jax.default_backend() == "tpu"
                                      or not jax.config.jax_enable_x64):
            logger.warning(
                "spfft_tpu: distributed precision='double' without jax "
                "x64 runs at FLOAT32 device precision (x64 is "
                "unavailable on TPU, and off by default on CPU; the "
                "on-device double-single mode covers local plans only) "
                "— use the CPU backend with JAX_ENABLE_X64=1 for true "
                "f64 (docs/precision.md)")
        # Wire rung resolution (``self._wire_dtype``) is deferred to
        # _resolve_wire_rung below: int8 eligibility depends on the
        # exchange mechanism selected next, and the legacy *_FLOAT
        # variants map onto the ladder there (one rung down).
        self._wire_dtype = None
        self._init_split_x()
        # UNBUFFERED selects the ppermute-ring mechanism; COMPACT_BUFFERED
        # the exact-count exchange — ONE ragged_all_to_all per direction
        # (exchange.RaggedSchedule, the true Alltoallv; launch count is
        # shard-count-invariant, replacing the round-4 ppermute schedule
        # that paid up to 416 collectives at S=32). Off-TPU the ragged
        # collective is EMULATED (all_gather + plan-time gather — XLA:CPU
        # has no ragged-all-to-all kernel), so the CPU suite and the
        # virtual-device dryrun execute the same tables end-to-end.
        # SPFFT_TPU_COMPACT_PPERMUTE=1 restores the ppermute schedule
        # (also used at S=1, where no collective exists to batch). Every
        # other variant runs the single fused all_to_all (exchange.py).
        import os as _os
        self._compact = None
        self._ragged = None
        # Compute/communication overlap (parallel/overlap.py): split the
        # exchange into K destination-balanced chunks so chunk i's z/xy
        # FFT stage runs while chunk i-1's collective is in flight
        # (issue early, unpack late). K=1 keeps the monolithic path —
        # bit-identical to a plan built without the knob. The knob
        # composes with EVERY exchange mechanism: ragged/compact get
        # chunked exact-count sub-schedules, the padded block layouts
        # (buffered/ring, float-wire included) chunk by static row
        # slices with no extra tables.
        if overlap_chunks is None:
            env = _os.environ.get(OVERLAP_CHUNKS_ENV)
            if env:
                overlap_chunks = int(env)
            else:
                # round 11: the knob's default lives in the typed
                # control-plane config (boot artifact / auto-tuner
                # recommendation), not a hard-coded constant
                from ..control.config import global_config
                overlap_chunks = int(global_config().overlap_chunks)
        if int(overlap_chunks) < 1:
            raise InvalidParameterError(
                f"overlap_chunks must be >= 1, got {overlap_chunks}")
        k_eff = min(int(overlap_chunks), dist_plan.max_sticks,
                    dist_plan.max_planes)
        if dist_plan.num_shards == 1:
            k_eff = 1  # comm-size-1: no collective to overlap
        if k_eff != int(overlap_chunks):
            logger.info(
                "spfft_tpu: overlap_chunks clamped %s -> %d (bounded by "
                "max_sticks/max_planes; 1 on a single shard)",
                overlap_chunks, k_eff)
        self.overlap_chunks = k_eff
        self._overlap = None
        # Fused-plausible plans snap the backward chunk bounds to
        # super-tile multiples (overlap.chunk_bounds_aligned) so the
        # per-chunk fused decompress+z-DFT launches waste no partial
        # super-tile at chunk seams. Cheap pre-check only — the full
        # gate runs in _init_fused_dist once the schedule exists; the
        # per-chunk table sets handle unaligned bounds too, so a
        # later decline costs nothing.
        from ..ops import fused_kernel as _fkm
        stick_align = 1
        if (k_eff > 1 and _fkm.enabled()
                and (jax.default_backend() == "tpu"
                     or _fkm.interpret_forced())
                and use_pallas is not False
                and self.precision == "single"
                and _fkm.eligible_dim(dist_plan.dim_z) is None):
            stick_align = _fkm.super_tile_geometry(dist_plan.dim_z)[0]
        use_ppermute_compact = _os.environ.get(
            "SPFFT_TPU_COMPACT_PPERMUTE") == "1"
        if self.exchange.compact:
            if dist_plan.num_shards > 1 and not use_ppermute_compact:
                if k_eff > 1:
                    self._overlap = build_overlap_schedule(
                        dist_plan, k_eff, "ragged",
                        x_window=self._split_x,
                        stick_align=stick_align)
                else:
                    self._ragged = build_ragged_schedule(
                        dist_plan, x_window=self._split_x)
            elif k_eff > 1 and dist_plan.num_shards > 1:
                self._overlap = build_overlap_schedule(
                    dist_plan, k_eff, "compact", x_window=self._split_x,
                    stick_align=stick_align)
            else:
                self._compact = build_compact_schedule(
                    dist_plan, x_window=self._split_x)
        elif k_eff > 1:
            self._overlap = build_overlap_schedule(dist_plan, k_eff,
                                                   "block",
                                                   stick_align=stick_align)
        # SPFFT_TPU_FORCE_RAGGED_OP=1 lowers the REAL ragged op off-TPU
        # (XLA:CPU can lower it but not execute it) — used by the HLO
        # launch-count checks in tests and scripts/scaling_model.py.
        self._ragged_emulate = (jax.default_backend() != "tpu"
                                and _os.environ.get(
                                    "SPFFT_TPU_FORCE_RAGGED_OP") != "1")
        if (self._compact is not None or self._ragged is not None
                or (self._overlap is not None
                    and self._overlap.kind != "block")):
            self._exchange_fn = None
        elif self.exchange == ExchangeType.UNBUFFERED:
            self._exchange_fn = ring_exchange_blocks
        else:
            self._exchange_fn = all_to_all_blocks
        # Error-budgeted wire ladder: pick the rung (and _wire_dtype) now
        # that the mechanism is known — the int8 rung needs the padded
        # block layout, and the measured probe must run BEFORE the
        # comm-size-1 local delegation so every plan records its rung.
        self._resolve_wire_rung(wire_precision, wire_error_budget)
        self._build_tables()
        self._init_pallas(use_pallas)
        self._sharded = NamedSharding(self.mesh, P(self.axis_name))
        self._replicated = NamedSharding(self.mesh, P())
        # Commit the static tables to device once, at plan time (never on the
        # hot path — SURVEY.md §3.1's plan/execute split).
        self._device_tables = (
            jax.device_put(self._vi, self._sharded),
            jax.device_put(self._slot_src, self._sharded),
            jax.device_put(self._onehot, self._sharded),
            jax.device_put(self._cols_flat, self._replicated),
            jax.device_put(self._col_inv, self._replicated),
            jax.device_put(self._zmap, self._replicated),
            jax.device_put(self._z_src, self._replicated),
            jax.device_put(self._conj_mult, self._sharded))
        if self._pallas_dist is not None:
            self._device_tables = self._device_tables + tuple(
                jax.device_put(a, self._sharded)
                for a in self._pallas_dist["stacked"])
        self._n_ptables = (len(self._pallas_dist["stacked"])
                           if self._pallas_dist is not None else 0)
        # Exact-count exchange tables (all sharded): per-hop pack tables +
        # the unpack table, both directions. Overlap schedules ship one
        # table set PER CHUNK plus the two late global unpack tables
        # (overlap.OverlapSchedule.device_tables); block-kind overlap
        # needs no tables at all (static slice bounds only).
        self._n_ctables = 0
        self._ov_slices = None
        if self._overlap is not None and self._overlap.kind != "block":
            ctables = self._overlap.device_tables()
            self._ov_slices = self._overlap.chunk_table_slices()
            self._n_ctables = len(ctables)
            self._device_tables = self._device_tables + tuple(
                jax.device_put(a, self._sharded) for a in ctables)
        elif self._compact is not None:
            ctables = (list(self._compact.bwd_pack)
                       + [self._compact.bwd_unpack]
                       + list(self._compact.fwd_pack)
                       + [self._compact.fwd_unpack])
            self._n_ctables = len(ctables)
            self._device_tables = self._device_tables + tuple(
                jax.device_put(a, self._sharded) for a in ctables)
        elif self._ragged is not None:
            ctables = self._ragged.device_tables()
            self._n_ctables = len(ctables)
            self._device_tables = self._device_tables + tuple(
                jax.device_put(a, self._sharded) for a in ctables)
        # Fused local-stage twins (ops/fused_kernel.py): the backward
        # decompress+z-DFT (one table set PER OVERLAP CHUNK) and the
        # forward z-DFT+compress. Tables appended LAST — backward set
        # then forward set — so the bodies keep slicing
        # ptables/ctables by the existing counts.
        self._init_fused_dist(use_pallas)
        self._init_fused_dist_fwd(use_pallas)
        self._n_fb = 0
        self._n_ff = 0
        fused_specs = ()
        if self._fused_dist is not None:
            fd = self._fused_dist
            self._n_fb = len(fd["stacked"]) + len(fd["mats"])
            fused_specs += ((P(self.axis_name),) * len(fd["stacked"])
                            + (P(),) * len(fd["mats"]))
            self._device_tables = self._device_tables + tuple(
                jax.device_put(a, self._sharded)
                for a in fd["stacked"]) + tuple(
                jax.device_put(m, self._replicated) for m in fd["mats"])
        if self._fused_dist_fwd is not None:
            ff = self._fused_dist_fwd
            self._n_ff = len(ff["stacked"]) + len(ff["mats"])
            fused_specs += ((P(self.axis_name),) * len(ff["stacked"])
                            + (P(),) * len(ff["mats"]))
            self._device_tables = self._device_tables + tuple(
                jax.device_put(a, self._sharded)
                for a in ff["stacked"]) + tuple(
                jax.device_put(m, self._replicated) for m in ff["mats"])
        self._n_ftables = self._n_fb + self._n_ff
        # Comm-size-1 collapse (reference: grid_internal.cpp:182 treats a
        # size-1 communicator as local): single-shard plans EXECUTE
        # through the local pipeline (planar T-layout matmul-DFT, stick
        # padding, no pack/exchange/unpack round trip — measured 1.65x
        # faster at 256^3), while the distributed API surface (padded
        # (1, ...) layouts, shard helpers, getters, wire-byte model)
        # stays. Large pair-layout plans keep the SPMD path (the local
        # boundary would transpose the values through the host).
        from ..plan import PAIR_IO_THRESHOLD, TransformPlan
        self._local1 = None
        self._local1_fns = {}
        if (dist_plan.num_shards == 1 and jax.process_count() == 1
                and use_pallas is not True  # explicit force keeps the
                # SPMD kernel path (interpret-mode semantics on CPU)
                and dist_plan.shard_plans[0].num_values
                < PAIR_IO_THRESHOLD):
            # device_double=False: the delegate must keep the distributed
            # API contract (sharded f32 jax.Array outputs, pointwise fns)
            # — the on-device double mode changes both (review r5)
            self._local1 = TransformPlan(dist_plan.shard_plans[0],
                                         precision=precision,
                                         use_pallas=use_pallas,
                                         device_double=False)
        self._base_in_specs = (
            (P(self.axis_name),                       # data
             P(self.axis_name), P(self.axis_name),    # vi, slot_src
             P(self.axis_name),                       # onehot
             P(), P(), P(), P(),      # cols, col_inv, zmap, z_src
             P(self.axis_name))                       # conj_mult
            + (P(self.axis_name),) * (self._n_ptables + self._n_ctables)
            + fused_specs)
        # pallas_call outputs carry no varying-mesh-axes metadata, so the
        # vma consistency check must be off when the kernel is in the body;
        # XLA-path plans keep the check (specs pin every sharding anyway)
        self._check_vma = (self._pallas_dist is None
                           and self._fused_dist is None
                           and self._fused_dist_fwd is None)
        shmap = functools.partial(
            shard_map, mesh=self.mesh, in_specs=self._base_in_specs,
            out_specs=P(self.axis_name), check_vma=self._check_vma)
        self._pair_jits = {}
        self._batched = None
        self._backward_jit = jax.jit(shmap(self._backward_body))
        self._forward_jit = {
            s: jax.jit(shmap(functools.partial(self._forward_body,
                                               scaled=(s == Scaling.FULL))))
            for s in (Scaling.NONE, Scaling.FULL)
        }
        # exchange observability (spfft_tpu.obs): plan-build span plus
        # the exact wire/busiest-link byte accounting — per chunk when
        # the overlap pipeline is active — surfaced as metrics so
        # distributed rounds stop hand-rolling them into bench JSON
        from .. import obs as _obs
        _dt = time.perf_counter() - _t0_build
        _obs.record_plan_build(self, _dt, _t0_build)
        _obs.record_exchange_plan(self, _dt, _t0_build)

    # -- wire precision ladder ----------------------------------------------
    def _resolve_wire_rung(self, wire_precision, wire_error_budget) -> None:
        """Resolve the wire-compression rung (docs/distributed.md
        "Compressed wire"): walk DOWN from the requested rung, declining
        any rung the plan cannot carry (int8 needs the padded block
        layout for its scale sidecar) or whose MEASURED probe error
        exceeds the declared l2 budget, until one fits — rung 0 ("full")
        always does. Each decline is recorded with a reason
        (``spfft_wire_rung_declined_total{reason}`` + ``wire_declines``)
        so a refusal is observable, never silent. Legacy ``*_FLOAT``
        exchange variants map onto the ladder here (requested rung 1 for
        double, 2 for single) so their one-rung downcast keeps working
        unchanged under the same budget gate."""
        import os as _os
        from ..control.config import global_config
        if wire_precision is None:
            env = _os.environ.get(WIRE_PRECISION_ENV)
            wire_precision = (int(env) if env
                              else int(global_config().wire_precision))
        if wire_error_budget is None:
            env = _os.environ.get(WIRE_ERROR_BUDGET_ENV)
            wire_error_budget = (
                float(env) if env
                else float(global_config().wire_error_budget))
        requested = int(wire_precision)
        if not 0 <= requested < len(WIRE_RUNGS):
            raise InvalidParameterError(
                f"wire_precision must be in [0, {len(WIRE_RUNGS) - 1}], "
                f"got {requested}")
        if float(wire_error_budget) <= 0:
            raise InvalidParameterError(
                f"wire_error_budget must be > 0, got {wire_error_budget}")
        if requested == 0 and self.exchange.float_wire:
            requested = 1 if self.precision == "double" else 2
        # int8 packs per-stick scales alongside the padded block payload;
        # the exact-count layouts (ragged/compact and their overlap
        # kinds) address individual elements, leaving no room on the
        # wire for the scale sidecar in one collective round.
        int8_ok = (self._compact is None and self._ragged is None
                   and (self._overlap is None
                        or self._overlap.kind == "block"))
        self.wire_rung_requested = requested
        self.wire_error_budget = float(wire_error_budget)
        declines = []
        rung = requested
        probe_err = 0.0
        from .. import obs as _obs
        while rung > 0:
            if rung == 3 and not int8_ok:
                reason = "exact_count_layout"
            else:
                try:
                    probe_err = self._probe_wire_error(rung)
                except _faults.InjectedFault:
                    reason = "fault_injected"
                else:
                    if probe_err <= self.wire_error_budget:
                        break
                    reason = "over_budget"
            declines.append((WIRE_RUNGS[rung], reason))
            _obs.GLOBAL_COUNTERS.inc("spfft_wire_rung_declined_total",
                                     reason=reason)
            _obs.record_event("wire.decline", rung=WIRE_RUNGS[rung],
                              reason=reason)
            rung -= 1
        if rung == 0:
            probe_err = 0.0
        _obs.record_event("wire.resolve",
                          requested=WIRE_RUNGS[requested],
                          resolved=WIRE_RUNGS[rung],
                          probe_error=float(probe_err))
        self.wire_rung = rung
        self.wire_rung_name = WIRE_RUNGS[rung]
        self.wire_probe_error = float(probe_err)
        self.wire_declines = tuple(declines)
        self._wire_dtype = {0: None, 1: np.float32, 2: jnp.bfloat16,
                            3: jnp.int8}[rung]
        if declines:
            logger.info(
                "spfft_tpu: wire rung %s declined to %s (%s; budget %g, "
                "probe err %g)", WIRE_RUNGS[requested],
                self.wire_rung_name,
                ", ".join(f"{n}:{r}" for n, r in declines),
                self.wire_error_budget, self.wire_probe_error)

    def _probe_wire_error(self, rung: int) -> float:
        """Measured rel-l2 round-trip error of ``rung`` on an adversarial
        host-side probe spectrum: seeded gaussian stick rows with a huge
        per-row dynamic range (10^±6 magnitudes) — the shape the int8
        per-stick scales exist to survive. The reference signal is the
        device payload (probe cast to the transform's real dtype), so
        rung 1 under single precision measures exactly 0. Runs once at
        plan build, never on the hot path; the int8 twin mirrors
        ``exchange.quantize_blocks_int8`` in numpy, with the
        ``exchange.quantize`` fault seam guarding the scale
        computation."""
        rng = np.random.default_rng(0x51F8)
        dp = self.dist_plan
        rows = int(min(max(dp.max_sticks, 1), 64))
        cols = int(min(max(dp.dim_z, 1), 64))
        mags = 10.0 ** rng.uniform(-6.0, 6.0, size=(rows, 1, 1))
        il = (rng.standard_normal((rows, cols, 2)) * mags)
        ref = il.astype(self._rdt).astype(np.float64)
        if rung == 3:
            _faults.check_site("exchange.quantize")
            absmax = np.max(np.abs(ref), axis=(1, 2), keepdims=True)
            scale = np.where(absmax > 0, absmax / 127.0, 1.0)
            q = np.clip(np.rint(ref / scale), -127, 127).astype(np.int8)
            back = q.astype(np.float64) * scale
        else:
            wdt = np.float32 if rung == 1 else jnp.bfloat16
            back = ref.astype(wdt).astype(np.float64)
        denom = float(np.linalg.norm(ref))
        return float(np.linalg.norm(back - ref) / denom) if denom else 0.0

    # -- static tables -------------------------------------------------------
    def _init_split_x(self) -> None:
        """Global sparse-x xy-stage (the distributed form of the reference's
        y-over-non-empty-rows optimization, execution_host.cpp:139-145):
        when the union of all shards' occupied x columns spans under 70% of
        the x extent, every shard's plane grid — and both exchange unpack
        layouts — shrink to the occupied window, and the y-FFT runs only on
        it. Cyclic (wrapped) window for C2C centered sets; linear window of
        the half spectrum for R2C."""
        dp = self.dist_plan
        self._split_x = None
        self._xf_eff = dp.dim_x_freq
        cols = [p.scatter_cols for p in dp.shard_plans if p.num_sticks]
        if not cols:
            return
        xs = np.concatenate(cols) % dp.dim_x_freq
        x0, w = occupied_x_window(xs, dp.dim_x_freq,
                                  allow_wrap=not dp.hermitian)
        if w > 0.7 * dp.dim_x_freq:
            return
        self._split_x = (x0, w)
        self._xf_eff = w

    def _sub_cols(self, cols: np.ndarray) -> np.ndarray:
        """Map full-grid plane columns to occupied-window columns."""
        if self._split_x is None:
            return cols
        x0, w = self._split_x
        return window_sub_cols(cols, self.dist_plan.dim_x_freq, x0, w)

    def _build_tables(self) -> None:
        dp = self.dist_plan
        S, ms, mp_, mv = (dp.num_shards, dp.max_sticks, dp.max_planes,
                          dp.max_values)
        dim_z = dp.dim_z
        # Per-shard value indices, padded with an out-of-range sentinel
        # (gathers route sentinels to an appended zero row). All data
        # movement is gather-based with plan-time inverse maps — runtime
        # scatters lower near-serially on TPU (see indexing.inverse_slot_map).
        pad_vi = ms * dim_z
        vi = np.full((S, mv), pad_vi, np.int32)
        for r, p in enumerate(dp.shard_plans):
            vi[r, :p.num_values] = p.value_indices
        # Per-shard inverse slot map for the gather-based decompress
        # (sharded): slot -> local value position, sentinel mv.
        slot_src = np.full((S, ms * dim_z), mv, np.int32)
        for r, p in enumerate(dp.shard_plans):
            slot_src[r, :p.num_sticks * dim_z] = \
                np.where(p.slot_src == p.num_values, mv, p.slot_src)
        # Every shard's scatter columns (replicated): the global stick table,
        # the analogue of the reference's plan-time stick-list exchange
        # (indices.hpp:58-102 create_distributed_transform_indices). When
        # the split-x window is active, columns index the occupied window
        # (width _xf_eff), not the full plane.
        pad_col = dp.dim_y * self._xf_eff
        cols = np.full((S, ms), pad_col, np.int32)
        for r, p in enumerate(dp.shard_plans):
            cols[r, :p.num_sticks] = self._sub_cols(p.scatter_cols)
        # Global inverse column map (replicated): plane column -> global
        # padded stick index shard*ms + i, sentinel S*ms.
        col_inv = np.full(dp.dim_y * self._xf_eff, S * ms, np.int32)
        for r, p in enumerate(dp.shard_plans):
            col_inv[self._sub_cols(p.scatter_cols)] = \
                r * ms + np.arange(p.num_sticks)
        # z index owned by each shard's p-th plane (replicated), sentinel
        # dim_z for slab padding — drives the backward pack.
        zmap = np.full((S, mp_), dim_z, np.int32)
        for r in range(S):
            n = dp.num_planes[r]
            zmap[r, :n] = dp.plane_offsets[r] + np.arange(n)
        # Inverse: global z -> owner_shard * mp_ + plane (total map) — drives
        # the forward unpack gather.
        z_src = np.empty(dim_z, np.int32)
        for r in range(S):
            n = dp.num_planes[r]
            z_src[dp.plane_offsets[r]:dp.plane_offsets[r] + n] = \
                r * mp_ + np.arange(n)
        # One-hot mask of the (0,0) stick per shard (sharded) — drives the
        # R2C stick-symmetry fixup without per-shard Python branches
        # (reference: parameters.cpp:133-139 locates the stick; the owner is
        # shard-dependent but the SPMD body is traced once).
        onehot = np.zeros((S, ms), np.float32)
        for r, p in enumerate(dp.shard_plans):
            if p.zero_stick_id is not None:
                onehot[r, p.zero_stick_id] = 1.0
        # Hermitian x < 0 folding (indexing.canonicalize_hermitian_triplets):
        # per-shard ±1 multiplier on the interleaved value lanes, -1 on the
        # imaginary lane of folded conjugate mirrors. Static _has_conj keeps
        # unfolded plans byte-identical (the multiply is never traced); the
        # table stays a (S, 1, 2) ones placeholder then, so the extra pytree
        # leaf ships nothing per call.
        self._has_conj = any(
            p.value_conj is not None and bool(p.value_conj.any())
            for p in dp.shard_plans)
        if self._has_conj:
            conj_mult = np.ones((S, mv, 2), self._rdt)
            for r, p in enumerate(dp.shard_plans):
                if p.value_conj is not None:
                    conj_mult[r, :p.num_values, 1] = np.where(
                        p.value_conj, -1.0, 1.0)
        else:
            conj_mult = np.ones((S, 1, 2), self._rdt)
        self._conj_mult = conj_mult
        self._vi = vi
        self._slot_src = slot_src
        self._cols_flat = cols.reshape(-1)
        self._col_inv = col_inv
        self._zmap = zmap
        self._z_src = z_src
        self._onehot = onehot

    def _init_pallas(self, use_pallas: Optional[bool]) -> None:
        """Build per-shard Pallas windowed-gather tables for the compression
        stages, stacked into SPMD-sharded arrays (the same kernel the local
        plan uses; see ops/gather_kernel.py).

        Per-shard chunk counts differ, so each shard's tables are padded to
        the maximum with no-op chunks targeting a dummy output tile
        (gather_kernel.pad_tables_to); the DMA window height K and source
        rows are unified across shards (the SPMD body is one program).
        The kernel handles any value order (stick-major/z-ascending is
        optimal); a shard whose order is too scattered for the chunk
        decomposition drops ALL shards to the XLA path with a logged
        notice. Active in single precision on TPU; ``use_pallas=True`` on
        a non-TPU backend runs the kernel in interpret mode (testing) —
        note the asymmetry with the local ``TransformPlan``, whose
        ``use_pallas=True`` on non-TPU builds tables but executes the XLA
        path (interpret mode per value-array would dominate local
        runtimes; here the SPMD body must be one program)."""
        from ..ops import gather_kernel as gk

        dp = self.dist_plan
        self._pallas_dist = None
        self._pallas_interpret = False
        backend_ok = jax.default_backend() == "tpu"
        if use_pallas is True and self.precision != "single":
            raise InvalidParameterError(
                "the Pallas compression kernel is single-precision only")
        if use_pallas is False or (use_pallas is None and not backend_ok):
            return
        if use_pallas is None and self.precision != "single":
            return
        if use_pallas is None and dp.max_values < 200_000:
            # Same measured crossover as the local plan (plan._init_pallas,
            # round-3 sweep): below ~200k per-shard values the XLA gather
            # beats the kernel's fixed launch overhead (64^3 1-shard:
            # XLA 1.35 vs kernel 3.6 ms; 96^3: kernel 1.5 vs XLA 5.4).
            return
        ms, mv, dim_z = dp.max_sticks, dp.max_values, dp.dim_z
        num_slots = ms * dim_z
        if mv == 0 or num_slots == 0:
            return

        per_shard = [gk.compression_gather_inputs(
            p.value_indices, num_slots, pad_values_to=mv)
            for p in dp.shard_plans]

        def build_uniform(which, num_src, num_out, builder, pad_fn,
                          geom_keys, extra):
            """Two passes: discover each shard's preferred geometry, then
            rebuild with the common (max of each ``geom_keys`` attribute)
            forced so the SPMD program is uniform; pad chunk counts to the
            max and stack. Returns None if any shard declines (caller
            falls through to the next kind / the XLA path)."""
            tables = [builder(idx, valid, num_src, allow_segments=False)
                      for (idx, valid) in (s[which] for s in per_shard)]
            if any(t is None for t in tables):
                return None
            forced = {kw: max(getattr(t, attr) for t in tables)
                      for attr, kw in geom_keys.items()}
            tables = [t if all(getattr(t, a) == forced[kw]
                               for a, kw in geom_keys.items()) else
                      builder(per_shard[r][which][0],
                              per_shard[r][which][1], num_src,
                              allow_segments=False, **forced)
                      for r, t in enumerate(tables)]
            if any(t is None for t in tables):
                return None  # a forced rebuild crossed the chunk ceiling
            c_max = max(t.row0.shape[0] for t in tables)
            padded = [pad_fn(t, c_max) for t in tables]
            stacked = [np.stack([p[i] for p in padded])
                       for i in range(len(padded[0]))]
            out = {"stacked": stacked, "num_out": num_out,
                   "src_rows": max(t.src_rows for t in tables),
                   "k": forced["k_rows"]}
            out.update(extra(tables[0]))
            return out

        def build_all(which, num_src, num_out):
            # num_super / num_tiles are identical across shards already
            # (the idx length is the padded uniform max_values /
            # max_sticks * dim_z on every shard).
            return build_uniform(
                which, num_src, num_out, gk.build_wide_gather_tables,
                gk.pad_wide_tables_to,
                {"kp_rows": "kp_rows", "span_rows": "k_rows"},
                lambda t0: {"kind": "wide", "kp": t0.kp_rows,
                            "p_tiles": t0.p_tiles,
                            "super_p1": t0.num_super + 1},
            ) or build_uniform(
                which, num_src, num_out, gk.build_monotone_gather_tables,
                gk.pad_tables_to, {"span_rows": "k_rows"},
                lambda t0: {"kind": "narrow",
                            "tiles_p1": t0.num_tiles + 1},
            )

        dec = build_all(0, num_src=mv, num_out=num_slots)
        cmp_ = build_all(1, num_src=num_slots, num_out=mv)
        if dec is None or cmp_ is None:
            logger.warning(
                "spfft_tpu: a shard's value order is too scattered for the "
                "Pallas compression kernel — using the slower XLA gather "
                "path (sort triplets with utils.workloads."
                "sort_triplets_stick_major for the fast path)")
            return
        self._pallas_dist = {
            "dec": dec, "cmp": cmp_,
            "stacked": dec["stacked"] + cmp_["stacked"],
            "n_dec": len(dec["stacked"]),  # wide = 5 tables, narrow = 4
        }
        self._pallas_interpret = not backend_ok

    def _pallas_gather(self, flat_il, t, tables):
        """Run the windowed gather (wide or narrow kernel) on one shard's
        (N, 2) interleaved data."""
        from ..ops import gather_kernel as gk
        shard_tabs = tuple(a[0] for a in tables)
        re, im = gk.planar_from_interleaved(
            flat_il.astype(np.float32), t["src_rows"])
        if t["kind"] == "wide":
            out_re, out_im = gk.wide_gather(
                re, im, *shard_tabs, span_rows=t["k"], kp_rows=t["kp"],
                p_tiles=t["p_tiles"], src_rows=t["src_rows"],
                num_super=t["super_p1"], interpret=self._pallas_interpret)
        else:
            out_re, out_im = gk.monotone_gather(
                re, im, *shard_tabs, span_rows=t["k"],
                src_rows=t["src_rows"], num_tiles=t["tiles_p1"],
                interpret=self._pallas_interpret)
        return gk.interleaved_from_planar(out_re, out_im, t["num_out"])

    def _fused_inactive_why(self, use_pallas: Optional[bool]) -> Optional[str]:
        """Shared activation envelope for BOTH distributed fused local
        stages (backward decompress+z-DFT, forward z-DFT+compress):
        returns the ``inactive:<why>`` introspection value when the
        fused kernels were never in play for this configuration — a
        by-design inactivity, reported through the fallback-reason
        properties but NOT counted as a plan fallback — or None when
        the builds should proceed to the real eligibility gates."""
        from ..ops import fused_kernel as fkm
        dp = self.dist_plan
        if not fkm.enabled():
            return "inactive:env_disabled"
        if not (jax.default_backend() == "tpu" or fkm.interpret_forced()):
            return "inactive:backend"
        if use_pallas is False:
            return "inactive:use_pallas_false"
        if self.precision != "single":
            return "inactive:precision"
        if dp.max_values == 0 or dp.max_sticks == 0:
            return "inactive:empty"
        if (use_pallas is None and not fkm.interpret_forced()
                and dp.max_values < 200_000):
            # below the kernel-vs-XLA crossover (_init_pallas)
            return "inactive:below_crossover"
        return None

    def _init_fused_dist(self, use_pallas: Optional[bool]) -> None:
        """Fused decompress + z-DFT tables for the distributed backward's
        local pre-exchange stage: one ``run_decompress_zdft`` launch
        replaces the decompress gather, the r2c (0,0)-stick hermitian
        completion AND ``stages.z_backward`` — the dense raw stick array
        never round-trips through HBM (the same fusion the local plan
        runs, ops/fused_kernel.py). Shape-uniform per-shard tables (a
        common DMA window height, chunk counts padded with no-op chunks
        routed to one dummy output super-tile) keep the SPMD body a
        single program. With ``overlap_chunks > 1`` one table set is
        built PER OVERLAP CHUNK (restricted to that chunk's stick rows)
        so the pipeline keeps one fused launch per chunk with each
        chunk's collective issued as its sticks emerge — the monolithic
        plan is simply the single-chunk case of the same build. Gated by
        the same eligibility/cost model as the local fusion; every
        decline that keeps an otherwise-kernel-ready plan on the
        two-launch path is recorded as a ``dist_fused_decompress_zdft``
        fallback reason."""
        from .. import obs as _obs
        from ..ops import dft as _dft
        from ..ops import fused_kernel as fkm
        from ..ops import gather_kernel as gk

        dp = self.dist_plan
        self._fused_dist = None
        self._fused_dist_reason = None
        self._fused_dist_inactive = self._fused_inactive_why(use_pallas)
        if self._fused_dist_inactive is not None:
            return
        backend_ok = jax.default_backend() == "tpu"
        ms, mv, dim_z = dp.max_sticks, dp.max_values, dp.dim_z

        def decline(reason: str) -> None:
            self._fused_dist_reason = reason
            _obs.record_plan_fallback("dist_fused_decompress_zdft", reason)
            logger.info(
                "spfft_tpu: distributed fused decompress+z-DFT kernel "
                "unavailable (%s) — keeping the two-launch backward",
                reason)

        if not _dft.use_matmul_dft(dim_z, np.dtype(np.complex64)):
            return decline("no_matmul_dft")
        reason = fkm.eligible_dim(dim_z)
        if reason:
            return decline(reason)
        num_slots = ms * dim_z
        per = [gk.compression_gather_inputs(p.value_indices, num_slots,
                                            pad_values_to=mv)[0]
               for p in dp.shard_plans]
        # One table set per overlap chunk, each restricted to the
        # chunk's stick rows [s0, s1). A chunk slice of a stick-major
        # monotone index sequence is itself monotone, and every chunk
        # launch reads from the SAME full-height planar value source,
        # so num_src stays mv throughout.
        bounds = (self._overlap.stick_bounds()
                  if self._overlap is not None else ((0, ms),))

        def build(r, s0, s1, k_rows=0):
            idx, valid = per[r]
            return gk.build_monotone_gather_tables(
                idx[s0 * dim_z:s1 * dim_z], valid[s0 * dim_z:s1 * dim_z],
                mv, k_rows=k_rows, allow_segments=False)

        chunk_tabs = []
        for s0, s1 in bounds:
            tabs = [build(r, s0, s1) for r in range(dp.num_shards)]
            if any(t is None for t in tabs):
                return decline("value_order")
            chunk_tabs.append(tabs)
        # force one DMA window height K across shards AND chunks
        # (selector words encode (row, lane, valid) independent of K, so
        # rebuilding the smaller-span sets under the max is exact)
        k_u = max(t.span_rows for tabs in chunk_tabs for t in tabs)
        chunk_tabs = [
            [t if t.span_rows == k_u else build(r, s0, s1, k_rows=k_u)
             for r, t in enumerate(tabs)]
            for (s0, s1), tabs in zip(bounds, chunk_tabs)]
        if any(t is None for tabs in chunk_tabs for t in tabs):
            return decline("value_order")
        # one padded planar source height feeds every chunk's launch
        src_rows = max(t.src_rows for tabs in chunk_tabs for t in tabs)
        chunks = []
        stacked_all: list = []
        for (s0, s1), tabs in zip(bounds, chunk_tabs):
            fused = []
            for r, t in enumerate(tabs):
                zid = (dp.shard_plans[r].zero_stick_id
                       if dp.hermitian else None)
                # hermitian completion is within-stick, so the zero
                # stick completes inside whichever chunk slices it
                zc = (zid - s0 if zid is not None and s0 <= zid < s1
                      else None)
                ft = fkm.build_fused_decompress_tables(
                    t, dim_z, s1 - s0, zero_stick_id=zc)
                if isinstance(ft, str):
                    return decline(ft)
                fused.append(ft)
            # num_super/p_tiles/r_sticks are uniform across shards (the
            # chunk's slot count (s1-s0)*dim_z is common); the
            # zero-stick owner differs, so non-owners get the
            # never-matching (-1) zinfo sentinel and the static
            # `complete` flag stays shard-invariant.
            complete = any(f.zinfo is not None for f in fused)
            num_super = fused[0].num_super
            c_max = max(f.row0.shape[0] for f in fused)

            def pad(f):
                p_ = c_max - f.row0.shape[0]
                # no-op padding chunks: all-invalid selector words gather
                # zeros, never first/last, and target the DUMMY
                # super-tile ``num_super`` so the flush-on-block-change
                # at the real->pad boundary lands outside the sliced
                # result.
                return (np.concatenate([f.row0, np.zeros(p_, np.int32)]),
                        np.concatenate([f.pos, np.zeros(p_, np.int32)]),
                        np.concatenate([f.sfirst, np.zeros(p_, np.int32)]),
                        np.concatenate([f.slast, np.zeros(p_, np.int32)]),
                        np.concatenate([f.sup,
                                        np.full(p_, num_super, np.int32)]),
                        np.concatenate([f.packed,
                                        np.zeros((p_, 8, 128), np.int32)]))

            padded = [pad(f) for f in fused]
            stacked = [np.stack([p_[i] for p_ in padded])
                       for i in range(6)]
            if complete:
                stacked.append(np.stack([
                    f.zinfo if f.zinfo is not None
                    else np.array([-1, 0], np.int32) for f in fused]))
            rep = dataclasses.replace(
                fused[0], row0=padded[0][0], pos=padded[0][1],
                sfirst=padded[0][2], slast=padded[0][3], sup=padded[0][4],
                packed=padded[0][5], num_super=num_super + 1,
                src_rows=src_rows, span_rows=k_u, num_sticks=s1 - s0,
                zinfo=(np.array([-1, 0], np.int32) if complete else None))
            chunks.append({"rep": rep, "t0": len(stacked_all),
                           "t1": len(stacked_all) + len(stacked),
                           "n_sticks": s1 - s0})
            stacked_all.extend(stacked)
        self._fused_dist = {
            "chunks": chunks, "stacked": stacked_all,
            "n_tabs": len(stacked_all), "src_rows": src_rows,
            "mats": fkm.commit_mats(_dft.c2c_mats(dim_z, _dft.BACKWARD)),
            "interpret": not backend_ok,
        }

    def _fused_bwd_chunk_sticks(self, vals, xtables):
        """Per-shard fused decompress + (0,0)-stick completion + z-IFFT,
        ONE ``run_decompress_zdft`` launch per overlap chunk (one total
        for monolithic plans): the drop-in for ``_decompress_shard``
        followed by ``_bwd_pre_exchange``. ``vals`` is (mv, 2)
        interleaved — or batched (B, mv, 2) through the batched kernel
        grid. Returns the list of per-chunk complex z-transformed stick
        arrays (..., stick_hi - stick_lo, dim_z), chunk order matching
        ``self._overlap.chunks``."""
        from ..ops import fused_kernel as fkm
        from ..ops import gather_kernel as gk
        fd = self._fused_dist
        ft = xtables[self._n_ptables + self._n_ctables:]
        tabs = ft[:fd["n_tabs"]]
        mats = ft[fd["n_tabs"]:fd["n_tabs"] + 3]      # replicated, as-is
        re, im = gk.planar_from_interleaved(vals.astype(np.float32),
                                            fd["src_rows"])
        out = []
        for ch in fd["chunks"]:
            # drop the shard axis on this chunk's table slice
            dev = tuple(a[0] for a in tabs[ch["t0"]:ch["t1"]])
            sr, si = fkm.run_decompress_zdft(re, im, dev, mats, ch["rep"],
                                             interpret=fd["interpret"])
            n = ch["n_sticks"]
            out.append((sr[..., :n, :]
                        + 1j * si[..., :n, :]).astype(self._cdt))
        return out

    def _fused_dec_zdft_shard(self, vals, xtables):
        """Monolithic (no-overlap) fused backward local stage: the
        single-chunk case of :meth:`_fused_bwd_chunk_sticks`."""
        return self._fused_bwd_chunk_sticks(vals, xtables)[0]

    def _init_fused_dist_fwd(self, use_pallas: Optional[bool]) -> None:
        """Fused z-DFT + compress tables for the distributed forward's
        local post-exchange stage: one ``run_zdft_compress`` launch
        replaces ``stages.z_forward`` + the compress gather — the dense
        z-transformed stick array never round-trips through HBM (the
        forward twin of :meth:`_init_fused_dist`, built with the same
        shape-uniform per-shard machinery: a common DMA window height,
        chunk counts padded with no-op chunks storing zeros into one
        dummy output tile). A z-stick needs exchanged planes from EVERY
        chunk, so this launch runs once, post-exchange; the chunked
        overlap pipeline upstream (xy + exchange) keeps its
        one-launch-per-chunk structure either way, which is why there is
        no ``overlap_chunks`` decline here. Declines that keep an
        otherwise-kernel-ready plan on the two-launch forward are
        recorded as ``dist_fused_zdft_compress`` fallback reasons."""
        from .. import obs as _obs
        from ..ops import dft as _dft
        from ..ops import fused_kernel as fkm
        from ..ops import gather_kernel as gk

        dp = self.dist_plan
        self._fused_dist_fwd = None
        self._fused_dist_fwd_reason = None
        if self._fused_inactive_why(use_pallas) is not None:
            return  # shared envelope, reported via _fused_dist_inactive
        backend_ok = jax.default_backend() == "tpu"
        ms, mv, dim_z = dp.max_sticks, dp.max_values, dp.dim_z

        def decline(reason: str) -> None:
            self._fused_dist_fwd_reason = reason
            _obs.record_plan_fallback("dist_fused_zdft_compress", reason)
            logger.info(
                "spfft_tpu: distributed fused z-DFT+compress kernel "
                "unavailable (%s) — keeping the two-launch forward",
                reason)

        if not _dft.use_matmul_dft(dim_z, np.dtype(np.complex64)):
            return decline("no_matmul_dft")
        reason = fkm.eligible_dim(dim_z)
        if reason:
            return decline(reason)
        num_slots = ms * dim_z
        per = [gk.compression_gather_inputs(p.value_indices, num_slots,
                                            pad_values_to=mv)[1]
               for p in dp.shard_plans]
        tables = [gk.build_monotone_gather_tables(idx, valid, num_slots,
                                                  allow_segments=False)
                  for idx, valid in per]
        if any(t is None for t in tables):
            return decline("value_order")
        # force one DMA window height K across shards (exact rebuild,
        # same argument as the backward build)
        k_u = max(t.span_rows for t in tables)
        tables = [t if t.span_rows == k_u else
                  gk.build_monotone_gather_tables(
                      per[r][0], per[r][1], num_slots, k_rows=k_u,
                      allow_segments=False)
                  for r, t in enumerate(tables)]
        if any(t is None for t in tables):
            return decline("value_order")
        fused = []
        for t in tables:
            ft = fkm.build_fused_compress_tables(t, dim_z, ms)
            if isinstance(ft, str):
                return decline(ft)
            fused.append(ft)
        # num_tiles and win_sticks are uniform (mv and the forced window
        # height are common); src_sticks and chunk counts differ, so pad
        # to the maxima with no-op chunks that store zeros into the
        # DUMMY output tile ``num_tiles`` (all-invalid selector words
        # gather zeros; first=1 so nothing accumulates onto garbage).
        num_tiles = fused[0].num_tiles
        c_max = max(f.s0.shape[0] for f in fused)
        src_sticks = max(f.src_sticks for f in fused)

        def pad(f):
            p_ = c_max - f.s0.shape[0]
            return (np.concatenate([f.s0, np.zeros(p_, np.int32)]),
                    np.concatenate([f.off, np.zeros(p_, np.int32)]),
                    np.concatenate([f.out_tile,
                                    np.full(p_, num_tiles, np.int32)]),
                    np.concatenate([f.first, np.ones(p_, np.int32)]),
                    np.concatenate([f.packed,
                                    np.zeros((p_, 8, 128), np.int32)]))

        padded = [pad(f) for f in fused]
        stacked = [np.stack([p_[i] for p_ in padded]) for i in range(5)]
        rep = dataclasses.replace(
            fused[0], s0=padded[0][0], off=padded[0][1],
            out_tile=padded[0][2], first=padded[0][3], packed=padded[0][4],
            num_tiles=num_tiles + 1, src_sticks=src_sticks,
            span_rows=k_u, num_out=mv)
        # UNSCALED forward matrices: Scaling.FULL stays the same
        # post-gather multiply the unfused _compress_shard applies, so
        # the fused forward is bit-identical to the unfused
        # z_forward+gather+scale path (folding the scale into the
        # matrix values would not be).
        self._fused_dist_fwd = {
            "rep": rep, "stacked": stacked, "n_tabs": len(stacked),
            "mats": fkm.commit_mats(_dft.c2c_mats(dim_z, _dft.FORWARD)),
            "interpret": not backend_ok,
        }

    def _fused_zdft_cmp_shard(self, sticks, xtables, scaled: bool):
        """Per-shard fused z-FFT + compress gather: the drop-in for
        ``stages.z_forward`` followed by ``_compress_shard`` after the
        forward exchange. ``sticks`` are RAW (un-z-transformed) complex
        local sticks (..., max_sticks, dim_z); the exchange unpack fills
        padding rows with zeros, satisfying the kernel's
        rows-past-num_sticks-are-zero contract. Returns (..., mv, 2)
        interleaved real values."""
        from ..ops import fused_kernel as fkm
        from ..ops import gather_kernel as gk
        ff = self._fused_dist_fwd
        rep = ff["rep"]
        base = self._n_ptables + self._n_ctables + self._n_fb
        seg = xtables[base:base + self._n_ff]
        dev = tuple(a[0] for a in seg[:ff["n_tabs"]])  # drop the shard axis
        mats = seg[ff["n_tabs"]:ff["n_tabs"] + 3]      # replicated, as-is
        sr = jnp.real(sticks).astype(jnp.float32)
        si = jnp.imag(sticks).astype(jnp.float32)
        sr, si = fkm.pad_sticks_planar(sr, si, rep.src_sticks)
        out_re, out_im = fkm.run_zdft_compress(sr, si, dev, mats, rep,
                                               interpret=ff["interpret"])
        values = gk.interleaved_from_planar(out_re, out_im, rep.num_out)
        if scaled:
            values = values * jnp.asarray(1.0 / self.global_size,
                                          self._rdt)
        return values.astype(self._rdt)

    # -- SPMD bodies ---------------------------------------------------------
    def _exchange_freq_to_grid(self, sticks, zmap, col_inv, ctables):
        """z-sticks -> local plane grid across the mesh, via the selected
        exchange mechanism."""
        _faults.check_site("exchange.collective")  # trace time: per compile
        dp = self.dist_plan
        if self._ragged is not None:
            # sticks: (max_sticks, dim_z) or batched (B, max_sticks, dim_z)
            batch = sticks.shape[:-2]
            flat = sticks.reshape(batch + (-1,))
            buf = jnp.take(flat, ctables[0][0], axis=-1, mode="fill",
                           fill_value=0)
            offs = tuple(t[0] for t in ctables[4:8])
            recv = ragged_exchange(buf, offs, ctables[12][0],
                                   self._ragged.recv_cap, self.axis_name,
                                   self._ragged_emulate, self._wire_dtype)
            grid_flat = jnp.take(recv, ctables[1][0], axis=-1,
                                 mode="fill", fill_value=0)
            return grid_flat.reshape(batch + (dp.max_planes, dp.dim_y,
                                              self._xf_eff))
        if self._compact is not None:
            nb = len(self._compact.hop_sizes)
            flat = sticks.reshape(-1)
            bufs = [jnp.take(flat, t[0], mode="fill", fill_value=0)
                    for t in ctables[:nb]]
            recv = compact_exchange(bufs, self._compact.ops,
                                    dp.num_shards, self.axis_name,
                                    reverse=False,
                                    wire_real_dtype=self._wire_dtype)
            return jnp.take(recv, ctables[nb][0], mode="fill",
                            fill_value=0).reshape(dp.max_planes, dp.dim_y,
                                                  self._xf_eff)
        blocks = pack_freq_to_blocks(sticks, zmap)
        if dp.num_shards > 1:
            # comm-size-1 skips the collective entirely, like the
            # reference treating a 1-rank communicator as local
            # (grid_internal.cpp:182); the block transposes on a size-1
            # leading axis are layout no-ops (256^3 dist1 pair:
            # 20.2 -> 17.5 ms).
            blocks = self._exchange_fn(blocks, self.axis_name,
                                       self._wire_dtype, quant_axis=1)
        return unpack_blocks_to_grid(blocks, col_inv, dp.dim_y,
                                     self._xf_eff)

    def _exchange_grid_to_sticks(self, grid, cols_flat, z_src, ctables):
        """Local plane grid -> z-sticks across the mesh (forward mirror)."""
        _faults.check_site("exchange.collective")  # trace time: per compile
        dp = self.dist_plan
        if self._ragged is not None:
            batch = grid.shape[:-3]
            flat = grid.reshape(batch + (-1,))
            buf = jnp.take(flat, ctables[2][0], axis=-1, mode="fill",
                           fill_value=0)
            offs = tuple(t[0] for t in ctables[8:12])
            recv = ragged_exchange(buf, offs, ctables[13][0],
                                   self._ragged.recv_cap, self.axis_name,
                                   self._ragged_emulate, self._wire_dtype)
            sticks_flat = jnp.take(recv, ctables[3][0], axis=-1,
                                   mode="fill", fill_value=0)
            return sticks_flat.reshape(batch + (dp.max_sticks, dp.dim_z))
        if self._compact is not None:
            nb = len(self._compact.hop_sizes)
            flat = grid.reshape(-1)
            bufs = [jnp.take(flat, t[0], mode="fill", fill_value=0)
                    for t in ctables[nb + 1:2 * nb + 1]]
            recv = compact_exchange(bufs, self._compact.ops,
                                    dp.num_shards, self.axis_name,
                                    reverse=True,
                                    wire_real_dtype=self._wire_dtype)
            return jnp.take(recv, ctables[2 * nb + 1][0], mode="fill",
                            fill_value=0).reshape(dp.max_sticks, dp.dim_z)
        blocks = pack_space_to_blocks(grid, cols_flat, dp.num_shards,
                                      dp.max_sticks)
        if dp.num_shards > 1:
            # comm-size-1 local collapse (see _exchange_freq_to_grid)
            blocks = self._exchange_fn(blocks, self.axis_name,
                                       self._wire_dtype, quant_axis=2)
        return unpack_blocks_to_sticks(blocks, z_src)

    # -- chunk-pipelined exchange (compute/communication overlap) -----------
    def _overlap_bwd_to_grid(self, sticks_raw, onehot_row, col_inv, zmap,
                             ctables, pre_chunks=None):
        """Backward overlap pipeline: per chunk, run stick symmetry +
        z-IFFT on the chunk's stick rows and ISSUE its collective
        immediately; unpack once, after every chunk's exchange has been
        issued (issue early, unpack late). The loop builds K independent
        compute->collective chains — the dependence structure XLA's
        latency-hiding scheduler needs to split each collective into an
        async start/done pair and run chunk i's z-stage during chunk
        i-1's wire time. Batch-aware for the ragged kind only (batch
        dims lead, collectives carry them trailing); block/compact
        batched callers vmap the whole per-example tail instead.

        ``pre_chunks`` (the fused pipeline) supplies the per-chunk
        z-transformed stick arrays directly — one fused
        decompress+z-DFT launch per chunk has already replaced the
        slice + stick symmetry + z-IFFT — so the loop only packs and
        issues each chunk's collective."""
        ov = self._overlap
        dp = self.dist_plan
        batch = (pre_chunks[0].shape[:-2] if pre_chunks is not None
                 else sticks_raw.shape[:-2])
        recvs = []
        for c, ch in enumerate(ov.chunks):
            _faults.check_site("exchange.chunk")  # trace: once per chunk
            if pre_chunks is not None:
                s_c = pre_chunks[c]
            else:
                s_c = sticks_raw[..., ch.stick_lo:ch.stick_hi, :]
                oh_c = onehot_row[ch.stick_lo:ch.stick_hi]
                if batch:
                    s_c = jax.vmap(
                        lambda s, oh=oh_c:
                        self._bwd_pre_exchange(s, oh))(s_c)
                else:
                    s_c = self._bwd_pre_exchange(s_c, oh_c)
            if ov.kind == "block":
                blocks = pack_freq_to_blocks(s_c, zmap)
                if dp.num_shards > 1:
                    # int8 quant rows = sticks (axis 1): the chunk slice
                    # axis, so per-chunk scale sidecars partition the
                    # monolithic one exactly at every K
                    blocks = self._exchange_fn(blocks, self.axis_name,
                                               self._wire_dtype,
                                               quant_axis=1)
                recvs.append(blocks)
                continue
            flat = s_c.reshape(batch + (-1,))
            sl = self._ov_slices[c]
            if ov.kind == "ragged":
                buf = jnp.take(flat, ctables[sl["bwd_pack"]][0], axis=-1,
                               mode="fill", fill_value=0)
                offs = tuple(t[0] for t in
                             ctables[sl["offs_b"][0]:sl["offs_b"][1]])
                recvs.append(ragged_exchange(
                    buf, offs, ctables[sl["emu_bwd"]][0], ch.recv_cap,
                    self.axis_name, self._ragged_emulate,
                    self._wire_dtype))
            else:  # compact ppermute chunk (unbatched by contract)
                lo, hi = sl["bwd_ops"]
                bufs = [jnp.take(flat, ctables[i][0], mode="fill",
                                 fill_value=0) for i in range(lo, hi)]
                recvs.append(compact_exchange(
                    bufs, ch.bwd_ops, dp.num_shards, self.axis_name,
                    reverse=False, wire_real_dtype=self._wire_dtype))
        if ov.kind == "block":
            # received chunk blocks are contiguous stick-row slices of
            # the monolithic (S, max_sticks, max_planes) block
            blocks = jnp.concatenate(recvs, axis=1)
            return unpack_blocks_to_grid(blocks, col_inv, dp.dim_y,
                                         self._xf_eff)
        recv = jnp.concatenate(recvs, axis=-1)
        grid_flat = jnp.take(recv, ctables[-2][0], axis=-1, mode="fill",
                             fill_value=0)
        return grid_flat.reshape(batch + (dp.max_planes, dp.dim_y,
                                          self._xf_eff))

    def _overlap_fwd_to_sticks(self, space, cols_flat, z_src, ctables):
        """Forward overlap pipeline (the backward's mirror): per chunk,
        xy-FFT the chunk's plane rows and issue its collective; one late
        unpack reassembles the full-z local sticks. Batch-aware for the
        ragged kind only, like :meth:`_overlap_bwd_to_grid`."""
        ov = self._overlap
        dp = self.dist_plan
        nd_slab = 3 if dp.hermitian else 4   # (planes, Y, X[, 2])
        batch = space.shape[:-nd_slab]
        axis = space.ndim - nd_slab
        recvs = []
        for c, ch in enumerate(ov.chunks):
            _faults.check_site("exchange.chunk")  # trace: once per chunk
            s_c = jax.lax.slice_in_dim(space, ch.plane_lo, ch.plane_hi,
                                       axis=axis)
            g_c = (jax.vmap(self._fwd_pre_exchange)(s_c) if batch
                   else self._fwd_pre_exchange(s_c))
            if ov.kind == "block":
                blocks = pack_space_to_blocks(g_c, cols_flat,
                                              dp.num_shards,
                                              dp.max_sticks)
                if dp.num_shards > 1:
                    # forward chunks slice planes (axis 2) — the int8
                    # quant axis follows, keeping scale-sidecar bytes
                    # conserved at every K (mirror of the backward)
                    blocks = self._exchange_fn(blocks, self.axis_name,
                                               self._wire_dtype,
                                               quant_axis=2)
                recvs.append(blocks)
                continue
            flat = g_c.reshape(batch + (-1,))
            sl = self._ov_slices[c]
            if ov.kind == "ragged":
                buf = jnp.take(flat, ctables[sl["fwd_pack"]][0], axis=-1,
                               mode="fill", fill_value=0)
                offs = tuple(t[0] for t in
                             ctables[sl["offs_f"][0]:sl["offs_f"][1]])
                recvs.append(ragged_exchange(
                    buf, offs, ctables[sl["emu_fwd"]][0], ch.recv_cap,
                    self.axis_name, self._ragged_emulate,
                    self._wire_dtype))
            else:
                lo, hi = sl["fwd_ops"]
                bufs = [jnp.take(flat, ctables[i][0], mode="fill",
                                 fill_value=0) for i in range(lo, hi)]
                recvs.append(compact_exchange(
                    bufs, ch.fwd_ops, dp.num_shards, self.axis_name,
                    reverse=False, wire_real_dtype=self._wire_dtype))
        if ov.kind == "block":
            # chunk blocks are contiguous plane slices of the monolithic
            # (S, max_sticks, max_planes) block
            blocks = jnp.concatenate(recvs, axis=2)
            return unpack_blocks_to_sticks(blocks, z_src)
        recv = jnp.concatenate(recvs, axis=-1)
        sticks_flat = jnp.take(recv, ctables[-1][0], axis=-1,
                               mode="fill", fill_value=0)
        return sticks_flat.reshape(batch + (dp.max_sticks, dp.dim_z))

    def _decompress_shard(self, values_il, slot_src, ptables):
        """Per-shard decompress: (mv, 2) -> (max_sticks, dim_z) sticks —
        or batched (B, mv, 2) -> (B, max_sticks, dim_z) through the same
        kernel tables (batched pallas grid / vmapped XLA gather)."""
        dp = self.dist_plan
        if self._pallas_dist is not None:
            dec_il = self._pallas_gather(
                values_il, self._pallas_dist["dec"],
                ptables[:self._pallas_dist["n_dec"]])
            flat = dec_il[..., 0] + 1j * dec_il[..., 1]
            return flat.reshape(values_il.shape[:-2]
                                + (dp.max_sticks, dp.dim_z))
        dec = lambda v: stages.decompress(v.astype(self._rdt), slot_src[0],
                                          dp.max_sticks, dp.dim_z)
        if values_il.ndim == 3:
            return jax.vmap(dec)(values_il)
        return dec(values_il)

    def _bwd_pre_exchange(self, sticks, onehot_row):
        """Stick symmetry + z-IFFT (the per-example half before the
        exchange; batched callers vmap this). ``onehot_row`` is the
        per-shard (max_sticks,) mask row — the overlap pipeline passes
        chunk SLICES of both arguments (the stages are per-stick
        independent, so a row slice is exact)."""
        _faults.check_site("exchange.pack")  # trace time: per compile
        dp = self.dist_plan
        if dp.hermitian:
            # Complete every stick, then blend by the one-hot (0,0)-stick
            # mask — SPMD-safe stand-in for the reference's "owner rank
            # applies StickSymmetry" branch (execution_host.cpp:306-308).
            completed = jax.vmap(stages.complete_stick_hermitian)(sticks)
            oh = onehot_row[:, None].astype(self._rdt)
            sticks = sticks * (1 - oh) + completed * oh
        return stages.z_backward(sticks)

    def _bwd_post_exchange(self, grid):
        """Plane symmetry + xy-IFFT (after the exchange)."""
        _faults.check_site("exchange.unpack")  # trace time: per compile
        dp = self.dist_plan
        if dp.hermitian:
            if self._split_x is not None:
                x0, _ = self._split_x
                if x0 == 0:
                    grid = stages.complete_plane_hermitian(grid)
                return stages.xy_backward_r2c_split(
                    grid, x0, dp.dim_x, dp.dim_x_freq)
            grid = stages.complete_plane_hermitian(grid)
            return stages.xy_backward_r2c(grid, dp.dim_x)
        if self._split_x is not None:
            x0, _ = self._split_x
            return complex_to_interleaved(
                stages.xy_backward_c2c_split(grid, x0, dp.dim_x))
        return complex_to_interleaved(stages.xy_backward_c2c(grid))

    def _backward_tail(self, sticks, onehot, col_inv, zmap, ctables):
        """Per-shard pipeline after decompress: symmetry, z-IFFT, exchange,
        plane symmetry, xy-IFFT. Input (max_sticks, dim_z); output the
        per-shard space slab (unbatched — batched callers vmap the
        pre/post halves and run the exchange batch-natively, see
        _backward_body_batched). With ``overlap_chunks > 1`` the z-stage
        and exchange run CHUNK-PIPELINED instead (parallel/overlap.py)."""
        if self._overlap is not None:
            grid = self._overlap_bwd_to_grid(sticks, onehot[0], col_inv,
                                             zmap, ctables)
        else:
            sticks = self._bwd_pre_exchange(sticks, onehot[0])
            grid = self._exchange_freq_to_grid(sticks, zmap, col_inv,
                                               ctables)
        return self._bwd_post_exchange(grid)

    def _backward_body(self, values_il, vi, slot_src, onehot, cols_flat,
                       col_inv, zmap, z_src, conj_mult, *xtables):
        ptables = xtables[:self._n_ptables]
        ctables = xtables[self._n_ptables:self._n_ptables + self._n_ctables]
        vals = values_il[0]
        if self._has_conj:  # conjugate the folded hermitian mirrors
            vals = vals * conj_mult[0]
        if self._fused_dist is not None:
            # decompress + stick symmetry + z-IFFT in ONE kernel launch
            # per overlap chunk (one total for monolithic plans), each
            # chunk's collective issued as its fused launch completes
            if self._overlap is not None:
                pre = self._fused_bwd_chunk_sticks(vals, xtables)
                grid = self._overlap_bwd_to_grid(None, None, col_inv,
                                                 zmap, ctables,
                                                 pre_chunks=pre)
            else:
                sticks_z = self._fused_dec_zdft_shard(vals, xtables)
                grid = self._exchange_freq_to_grid(sticks_z, zmap,
                                                   col_inv, ctables)
            return self._bwd_post_exchange(grid)[None]
        sticks = self._decompress_shard(vals, slot_src, ptables)
        return self._backward_tail(sticks, onehot, col_inv, zmap,
                                   ctables)[None]

    def _backward_body_batched(self, values_il, vi, slot_src, onehot,
                               cols_flat, col_inv, zmap, z_src, conj_mult,
                               *xtables):
        """Batched SPMD body: data carries a per-shard batch axis
        (1, B, ...); compression runs ONE batched-grid kernel launch, the
        rest of the pipeline (collectives included) is vmapped over B —
        the distributed analogue of the local plan's fused batch
        (reference interleaves N transforms by hand,
        multi_transform_internal.hpp:47-94)."""
        ptables = xtables[:self._n_ptables]
        ctables = xtables[self._n_ptables:self._n_ptables + self._n_ctables]
        vals_b = values_il[0]
        if self._has_conj:  # (B, mv, 2) * (mv, 2) broadcasts over B
            vals_b = vals_b * conj_mult[0]
        if self._fused_dist is not None:
            # one batched-grid fused launch per chunk covers decompress
            # + symmetry + z-IFFT for the whole batch
            if self._overlap is not None:
                pre_b = self._fused_bwd_chunk_sticks(vals_b, xtables)
                if self._overlap.kind == "ragged":
                    # ragged collectives carry the batch trailing
                    grid_b = self._overlap_bwd_to_grid(
                        None, None, col_inv, zmap, ctables,
                        pre_chunks=pre_b)
                else:
                    # block/compact exchange per example: batched fused
                    # launches first, then the pack/exchange/unpack tail
                    # vmapped over the per-chunk stick arrays
                    grid_b = jax.vmap(
                        lambda *cs: self._overlap_bwd_to_grid(
                            None, None, col_inv, zmap, ctables,
                            pre_chunks=cs))(*pre_b)
                return jax.vmap(self._bwd_post_exchange)(grid_b)[None]
            sticks_zb = self._fused_dec_zdft_shard(vals_b, xtables)
            if self._ragged is not None:
                grid_b = self._exchange_freq_to_grid(sticks_zb, zmap,
                                                     col_inv, ctables)
            else:
                grid_b = jax.vmap(
                    lambda s: self._exchange_freq_to_grid(
                        s, zmap, col_inv, ctables))(sticks_zb)
            return jax.vmap(self._bwd_post_exchange)(grid_b)[None]
        sticks_b = self._decompress_shard(vals_b, slot_src, ptables)
        if self._overlap is not None and self._overlap.kind == "ragged":
            # chunk loop identical to the unbatched path; each chunk's
            # collective carries the batch as trailing dims
            # (_overlap_bwd_to_grid is batch-aware for the ragged kind)
            grid_b = self._overlap_bwd_to_grid(sticks_b, onehot[0],
                                               col_inv, zmap, ctables)
            return jax.vmap(self._bwd_post_exchange)(grid_b)[None]
        if self._ragged is not None:
            # ragged_all_to_all has no vmap batching rule: vmap the
            # per-example halves, run ONE collective with the batch as a
            # trailing dimension (exchange.ragged_exchange)
            s2 = jax.vmap(
                lambda s: self._bwd_pre_exchange(s, onehot[0]))(sticks_b)
            grid_b = self._exchange_freq_to_grid(s2, zmap, col_inv,
                                                 ctables)
            return jax.vmap(self._bwd_post_exchange)(grid_b)[None]
        # block/compact overlap flows through the vmapped per-example
        # tail (a2a/ppermute have batching rules), like the monolithic
        # non-ragged mechanisms
        return jax.vmap(
            lambda s: self._backward_tail(s, onehot, col_inv, zmap,
                                          ctables))(sticks_b)[None]

    def _fwd_pre_exchange(self, space):
        """xy-FFT (the per-example half before the forward exchange)."""
        _faults.check_site("exchange.pack")  # trace time: per compile
        dp = self.dist_plan
        if dp.hermitian:
            if self._split_x is not None:
                x0, w = self._split_x
                return stages.xy_forward_r2c_split(
                    space.astype(self._rdt), x0, w)
            return stages.xy_forward_r2c(space.astype(self._rdt))
        if self._split_x is not None:
            x0, w = self._split_x
            return stages.xy_forward_c2c_split(
                interleaved_to_complex(space).astype(self._cdt), x0, w)
        return stages.xy_forward_c2c(
            interleaved_to_complex(space).astype(self._cdt))

    def _forward_head_raw(self, space, cols_flat, z_src, ctables):
        """Per-shard forward pipeline up to (not including) the z-stage:
        xy-FFT + exchange, output RAW (un-z-transformed) local sticks
        (max_sticks, dim_z) — the seam the fused z-DFT+compress kernel
        joins at. With ``overlap_chunks > 1`` the xy-stage and exchange
        run chunk-pipelined (the forward mirror of the backward
        overlap)."""
        if self._overlap is not None:
            return self._overlap_fwd_to_sticks(space, cols_flat, z_src,
                                               ctables)
        grid = self._fwd_pre_exchange(space)
        return self._exchange_grid_to_sticks(grid, cols_flat, z_src,
                                             ctables)

    def _forward_head(self, space, cols_flat, z_src, ctables):
        """Per-shard pipeline before compress: xy-FFT, exchange, z-FFT.
        Input the per-shard space slab; output (max_sticks, dim_z)."""
        return stages.z_forward(
            self._forward_head_raw(space, cols_flat, z_src, ctables))

    def _compress_shard(self, sticks, vi, ptables, scaled: bool):
        """Per-shard compress: (max_sticks, dim_z) -> (mv, 2) values —
        or batched (B, ...) -> (B, mv, 2)."""
        scale = 1.0 / self.global_size if scaled else None
        batch = sticks.shape[:-2]
        # vi carries the sentinel max_sticks*dim_z for value padding
        flat = jnp.stack([jnp.real(sticks).reshape(batch + (-1,)),
                          jnp.imag(sticks).reshape(batch + (-1,))], axis=-1)
        if self._pallas_dist is not None:
            values = self._pallas_gather(
                flat, self._pallas_dist["cmp"],
                ptables[self._pallas_dist["n_dec"]:])
        elif flat.ndim == 3:
            values = jax.vmap(
                lambda f: stages.gather_rows_with_sentinel(f, vi[0]))(flat)
        else:
            values = stages.gather_rows_with_sentinel(flat, vi[0])
        if scale is not None:
            values = values * jnp.asarray(scale, self._rdt)
        return values

    def _forward_body(self, space, vi, slot_src, onehot, cols_flat, col_inv,
                      zmap, z_src, conj_mult, *xtables, scaled: bool):
        ptables = xtables[:self._n_ptables]
        ctables = xtables[self._n_ptables:self._n_ptables + self._n_ctables]
        if self._fused_dist_fwd is not None:
            # post-exchange z-FFT + compress gather in ONE kernel launch
            raw = self._forward_head_raw(space[0], cols_flat, z_src,
                                         ctables)
            values = self._fused_zdft_cmp_shard(raw, xtables, scaled)
        else:
            sticks = self._forward_head(space[0], cols_flat, z_src,
                                        ctables)
            values = self._compress_shard(sticks, vi, ptables, scaled)
        if self._has_conj:  # folded mirrors leave conjugated
            values = values * conj_mult[0]
        return values[None]

    def _forward_body_batched(self, space, vi, slot_src, onehot, cols_flat,
                              col_inv, zmap, z_src, conj_mult, *xtables,
                              scaled: bool):
        ptables = xtables[:self._n_ptables]
        ctables = xtables[self._n_ptables:self._n_ptables + self._n_ctables]
        if self._fused_dist_fwd is not None:
            # raw sticks assembled batched through the same exchange
            # structure as the unfused branches below, then ONE
            # batched-grid fused z-FFT+compress launch replaces
            # z_forward + the gather
            if (self._overlap is not None
                    and self._overlap.kind == "ragged"):
                raw_b = self._overlap_fwd_to_sticks(space[0], cols_flat,
                                                    z_src, ctables)
            elif self._ragged is not None:
                grid_b = jax.vmap(self._fwd_pre_exchange)(space[0])
                raw_b = self._exchange_grid_to_sticks(grid_b, cols_flat,
                                                      z_src, ctables)
            else:
                raw_b = jax.vmap(
                    lambda s: self._forward_head_raw(
                        s, cols_flat, z_src, ctables))(space[0])
            values_b = self._fused_zdft_cmp_shard(raw_b, xtables, scaled)
            if self._has_conj:
                values_b = values_b * conj_mult[0]
            return values_b[None]
        if self._overlap is not None and self._overlap.kind == "ragged":
            # chunked forward with the batch on the collectives'
            # trailing dims (_overlap_fwd_to_sticks is batch-aware)
            sticks_b = stages.z_forward(self._overlap_fwd_to_sticks(
                space[0], cols_flat, z_src, ctables))
        elif self._ragged is not None:
            # batch rides the collective's trailing dims (see
            # _backward_body_batched)
            grid_b = jax.vmap(self._fwd_pre_exchange)(space[0])
            sticks_b = stages.z_forward(self._exchange_grid_to_sticks(
                grid_b, cols_flat, z_src, ctables))
        else:
            sticks_b = jax.vmap(
                lambda s: self._forward_head(s, cols_flat, z_src,
                                             ctables))(space[0])
        values_b = self._compress_shard(sticks_b, vi, ptables, scaled)
        if self._has_conj:
            values_b = values_b * conj_mult[0]
        return values_b[None]

    def _pair_shmap(self, n_fn_args: int):
        """shard_map wrapper for the fused-pair entry points: base specs
        plus one sharded spec per fn_arg."""
        return functools.partial(
            shard_map, mesh=self.mesh,
            in_specs=self._base_in_specs
            + (P(self.axis_name),) * n_fn_args,
            out_specs=P(self.axis_name), check_vma=self._check_vma)

    def _pair_body(self, values_il, vi, slot_src, onehot, cols_flat,
                   col_inv, zmap, z_src, conj_mult, *rest, scaled: bool, fn):
        n_tab = self._n_ptables + self._n_ctables + self._n_ftables
        xtables, fn_args = rest[:n_tab], rest[n_tab:]
        space = self._backward_body(values_il, vi, slot_src, onehot,
                                    cols_flat, col_inv, zmap, z_src,
                                    conj_mult, *xtables)
        if fn is not None:
            space = fn(space, *fn_args)
        return self._forward_body(space, vi, slot_src, onehot, cols_flat,
                                  col_inv, zmap, z_src, conj_mult, *xtables,
                                  scaled=scaled)

    def apply_pointwise(self, values, fn=None, *fn_args,
                        scaling: Scaling = Scaling.NONE):
        """backward → ``fn(space, *fn_args)`` → forward as ONE fused SPMD
        executable (both collectives inside a single program, so XLA can
        overlap the exchanges with neighbouring compute).

        ``fn`` runs *per shard inside shard_map* on the padded local slab
        — shape ``(1, max_planes, dim_y, dim_x, 2)`` interleaved for C2C,
        ``(1, max_planes, dim_y, dim_x)`` real for R2C; rows at and beyond
        the shard's true ``num_planes`` are padding and whatever ``fn``
        writes there is ignored (the z-selection tables read true planes
        only — tested in test_distributed.py). Each ``fn_args`` entry is a
        sharded array over the mesh axis (leading dim ``num_shards``),
        split like the data — the way to feed a shard-dependent operator
        (e.g. a potential field laid out as padded slabs) or step-varying
        data without recompiling.

        The compiled SPMD program is cached per ``(fn, scaling)`` by object
        identity: pass a stable callable, not a fresh lambda per call.
        Returns the padded sharded values array."""
        scaling = Scaling(scaling)
        if not isinstance(values, jax.Array):
            values = self.shard_values(values)
        if self._local1 is not None:
            with timed_transform("apply_pointwise") as box:
                box.value = self._local1.apply_pointwise(
                    values[0], self._local1_fn(fn), *fn_args,
                    scaling=scaling)[None]
            return box.value
        key = (fn, scaling, len(fn_args))
        jitted = self._pair_jits.get(key)
        if jitted is None:
            jitted = jax.jit(self._pair_shmap(len(fn_args))(
                functools.partial(self._pair_body,
                                  scaled=(scaling is Scaling.FULL), fn=fn)))
            self._pair_jits[key] = jitted
        with timed_transform("apply_pointwise") as box:
            box.value = jitted(values, *self._device_tables, *fn_args)
        return box.value

    def iterate_pointwise(self, values, fn, *fn_args, steps: int,
                          scaling: Scaling = Scaling.FULL):
        """``steps`` fused distributed round trips as ONE SPMD executable
        (``lax.scan`` inside shard_map — 2·steps collectives in a single
        program, one dispatch). Semantics as :meth:`apply_pointwise`;
        ``scaling`` defaults to FULL so the iteration is a fixed-point map.
        Returns the final padded sharded values array."""
        scaling = Scaling(scaling)
        if not isinstance(values, jax.Array):
            values = self.shard_values(values)
        if self._local1 is not None:
            with timed_transform("iterate_pointwise") as box:
                box.value = self._local1.iterate_pointwise(
                    values[0], self._local1_fn(fn), *fn_args, steps=steps,
                    scaling=scaling)[None]
            return box.value
        # scan carry dtype must match the step output (_rdt)
        values = values.astype(self._rdt)
        key = (fn, scaling, int(steps), "scan", len(fn_args))
        jitted = self._pair_jits.get(key)
        if jitted is None:
            scaled = scaling is Scaling.FULL

            def run_body(values_il, vi, slot_src, onehot, cols_flat,
                         col_inv, zmap, z_src, *rest):
                def step(v, _):
                    return self._pair_body(
                        v, vi, slot_src, onehot, cols_flat, col_inv, zmap,
                        z_src, *rest, scaled=scaled, fn=fn), None
                out, _ = jax.lax.scan(step, values_il, None,
                                      length=int(steps))
                return out

            jitted = jax.jit(self._pair_shmap(len(fn_args))(run_body))
            self._pair_jits[key] = jitted
        with timed_transform("iterate_pointwise") as box:
            box.value = jitted(values, *self._device_tables, *fn_args)
        return box.value

    # -- getters (reference transform.hpp:91-171) ---------------------------
    @property
    def transform_type(self) -> TransformType:
        return self.dist_plan.transform_type

    @property
    def dim_x(self) -> int:
        return self.dist_plan.dim_x

    @property
    def dim_y(self) -> int:
        return self.dist_plan.dim_y

    @property
    def dim_z(self) -> int:
        return self.dist_plan.dim_z

    @property
    def global_size(self) -> int:
        return self.dim_x * self.dim_y * self.dim_z

    @property
    def num_global_elements(self) -> int:
        return self.dist_plan.num_global_elements

    def local_z_length(self, shard: int) -> int:
        return self.dist_plan.num_planes[shard]

    def local_z_offset(self, shard: int) -> int:
        return self.dist_plan.plane_offsets[shard]

    def local_slice_size(self, shard: int) -> int:
        return self.dim_x * self.dim_y * self.local_z_length(shard)

    def num_local_elements(self, shard: int) -> int:
        return self.dist_plan.shard_plans[shard].num_values

    @property
    def fused_dist_active(self) -> bool:
        """True when BOTH distributed fused local stages run: the
        backward decompress + r2c stick symmetry + z-IFFT (one Pallas
        launch per overlap chunk) AND the forward z-FFT + compress
        gather (one post-exchange launch)."""
        return (self._fused_dist is not None
                and self._fused_dist_fwd is not None)

    @property
    def fused_dist_bwd_active(self) -> bool:
        """True when the backward's local pre-exchange stage (decompress
        + r2c stick symmetry + z-IFFT) runs as fused Pallas launches
        (one per overlap chunk)."""
        return self._fused_dist is not None

    @property
    def fused_dist_fwd_active(self) -> bool:
        """True when the forward's local post-exchange stage (z-FFT +
        compress gather) runs as ONE fused Pallas launch."""
        return self._fused_dist_fwd is not None

    @property
    def fused_dist_fallback_reason(self) -> Optional[str]:
        """Why the fused backward pre-exchange stage is not running:
        None when active; a decline reason (also recorded under
        ``dist_fused_decompress_zdft`` in obs) on an
        otherwise-kernel-ready plan; an ``inactive:<why>`` value when
        the fused kernels were never in play for this configuration
        (by design — not a fallback, so not counted in obs)."""
        if self._fused_dist is not None:
            return None
        return self._fused_dist_reason or self._fused_dist_inactive

    @property
    def fused_dist_fwd_fallback_reason(self) -> Optional[str]:
        """Forward-twin analogue of :attr:`fused_dist_fallback_reason`
        (decline reasons recorded under ``dist_fused_zdft_compress``)."""
        if self._fused_dist_fwd is not None:
            return None
        return self._fused_dist_fwd_reason or self._fused_dist_inactive

    def _wire_elem_bytes(self) -> int:
        elem = np.dtype(self._cdt).itemsize
        if self._wire_dtype is not None:
            # int8 rung: 2 bytes per complex element (re+im quantized);
            # the per-stick scale sidecar is counted separately
            # (_wire_scale_bytes), not folded into the element size.
            elem = 2 * np.dtype(self._wire_dtype).itemsize
        return elem

    def _wire_scale_bytes(self, forward: bool, busiest: bool = False) -> int:
        """int8 scale-sidecar bytes for ONE exchange: one f32 absmax
        scale per (destination slot, quant row), quant rows being sticks
        backward / planes forward — the overlap chunk-slice axes, so the
        total is conserved at every K (OverlapSchedule.scale_rows is the
        per-chunk decomposition). Zero on every other rung."""
        if (self._wire_dtype is None
                or np.dtype(self._wire_dtype) != np.dtype(np.int8)):
            return 0
        dp = self.dist_plan
        rows = dp.max_planes if forward else dp.max_sticks
        links = ((dp.num_shards - 1) if busiest
                 else dp.num_shards * (dp.num_shards - 1))
        return links * rows * 4

    def exchange_wire_bytes(self, forward: bool = False) -> int:
        """TOTAL off-shard bytes (summed over all shards) for ONE exchange
        under the selected mechanism — the aggregate-ICI-traffic model (the
        quantity the reference's Alltoallv layout exists to minimise,
        transpose_mpi_compact_buffered_host.cpp:83-105). Padded layouts
        ship ``S * (S-1) * max_sticks * max_planes`` complex elements
        regardless of the distribution; the compact schedule's size-classed
        ops track the true per-pair counts. See
        :meth:`exchange_busiest_link_bytes` for the bottleneck-link view."""
        dp = self.dist_plan
        elem = self._wire_elem_bytes()
        if self._overlap is not None and self._overlap.kind != "block":
            # chunking conserves wire elements exactly (overlap.py);
            # block-kind overlap ships the padded rows and falls through
            return self._overlap.wire_elements() * elem
        if self._ragged is not None:
            return self._ragged.wire_elements() * elem  # exact Alltoallv
        if self._compact is not None:
            return self._compact.wire_elements() * elem
        return (dp.num_shards * (dp.num_shards - 1)
                * dp.max_sticks * dp.max_planes * elem
                + self._wire_scale_bytes(forward))

    def exchange_busiest_link_bytes(self, forward: bool = False) -> int:
        """Max over shards of max(sent, received) off-shard bytes for ONE
        exchange — the bottleneck-link model. A shard that genuinely owns
        most of the slab receives that payload under ANY exact layout, so
        plane-skew savings show up in :meth:`exchange_wire_bytes`
        (aggregate), not here; stick-skew savings show up in both."""
        dp = self.dist_plan
        elem = self._wire_elem_bytes()
        if self._overlap is not None and self._overlap.kind != "block":
            return self._overlap.busiest_link_elements() * elem
        if self._ragged is not None:
            return self._ragged.busiest_link_elements() * elem
        if self._compact is not None:
            return self._compact.busiest_link_elements() * elem
        return ((dp.num_shards - 1) * dp.max_sticks * dp.max_planes * elem
                + self._wire_scale_bytes(forward, busiest=True))

    def estimated_device_bytes(self) -> int:
        """Approximate resident bytes this plan pins for its lifetime:
        the committed device tables (sharded across the mesh, counted
        whole). Same contract as the local plan's method — the serving
        plan registry's byte-aware LRU reads it on ``put`` (even though
        distributed plans are rejected at ``submit``; see
        errors.DistributedPlanUnsupportedError)."""
        leaves = jax.tree_util.tree_leaves(self._device_tables)
        return sum(int(getattr(leaf, "nbytes", 0)) for leaf in leaves)

    # -- data movement helpers ----------------------------------------------
    def shard_values(self, values_per_shard: Sequence) -> jax.Array:
        """Per-shard numpy value arrays -> padded sharded device array."""
        dp = self.dist_plan
        if len(values_per_shard) != dp.num_shards:
            raise InvalidParameterError("one value array per shard required")
        out = np.zeros((dp.num_shards, dp.max_values, 2), self._rdt)
        for r, v in enumerate(values_per_shard):
            il = as_interleaved(v, self.precision)
            if il.shape != (dp.shard_plans[r].num_values, 2):
                raise InvalidParameterError(
                    f"shard {r}: expected {dp.shard_plans[r].num_values} "
                    f"values, got {il.shape[:-1]}")
            out[r, :il.shape[0]] = il
        return jax.device_put(out, self._sharded)

    def unshard_values(self, values: jax.Array):
        """Padded sharded values -> per-shard numpy complex arrays."""
        dp = self.dist_plan
        arr = np.asarray(values)
        return [arr[r, :dp.shard_plans[r].num_values, 0]
                + 1j * arr[r, :dp.shard_plans[r].num_values, 1]
                for r in range(dp.num_shards)]

    def shard_space(self, slabs: Sequence) -> jax.Array:
        """Per-shard space-domain slabs -> padded sharded device array."""
        dp = self.dist_plan
        if len(slabs) != dp.num_shards:
            raise InvalidParameterError("one slab per shard required")
        if dp.hermitian:
            out = np.zeros((dp.num_shards, dp.max_planes, dp.dim_y,
                            dp.dim_x), self._rdt)
        else:
            out = np.zeros((dp.num_shards, dp.max_planes, dp.dim_y, dp.dim_x,
                            2), self._rdt)
        for r, slab in enumerate(slabs):
            n = dp.num_planes[r]
            expect = (n, dp.dim_y, dp.dim_x)
            if dp.hermitian:
                arr = np.asarray(slab, self._rdt)
                if arr.shape != expect:
                    raise InvalidParameterError(
                        f"shard {r}: expected real slab {expect}, "
                        f"got {arr.shape}")
            else:
                arr = as_interleaved(slab, self.precision)
                if arr.shape != expect + (2,):
                    raise InvalidParameterError(
                        f"shard {r}: expected complex slab {expect}, "
                        f"got {arr.shape[:-1]}")
            out[r, :n] = arr
        return jax.device_put(out, self._sharded)

    def unshard_space(self, space: jax.Array):
        """Padded sharded space array -> per-shard numpy slabs (complex for
        C2C, real for R2C), trimmed to each shard's true slab height."""
        dp = self.dist_plan
        arr = np.asarray(space)
        out = []
        for r in range(dp.num_shards):
            slab = arr[r, :dp.num_planes[r]]
            if not dp.hermitian:
                slab = slab[..., 0] + 1j * slab[..., 1]
            out.append(slab)
        return out

    # -- execution -----------------------------------------------------------
    def _local1_fn(self, fn):
        """Adapter for the comm-size-1 local delegate: the distributed
        pointwise contract hands ``fn`` the padded (1, planes, ...) slab;
        the local pipeline produces the bare slab. Cached per fn so the
        delegate's executable cache keys stay stable."""
        if fn is None:
            return None
        w = self._local1_fns.get(fn)
        if w is None:
            def w(s, *a, _fn=fn):
                return _fn(s[None], *a)[0]
            self._local1_fns[fn] = w
        return w

    def backward(self, values) -> jax.Array:
        """Frequency -> space across the mesh. ``values``: a per-shard list
        (numpy) or the padded sharded device array. Returns the padded
        sharded space array."""
        if not isinstance(values, jax.Array):
            values = self.shard_values(values)
        if self._local1 is not None:
            with timed_transform("backward") as box:
                box.value = self._local1.backward(values[0])[None]
            return box.value
        with timed_transform("backward") as box:
            box.value = self._backward_jit(values, *self._device_tables)
        return box.value

    def forward(self, space, scaling: Scaling = Scaling.NONE) -> jax.Array:
        """Space -> frequency across the mesh. Returns the padded sharded
        values array."""
        scaling = Scaling(scaling)
        if not isinstance(space, jax.Array):
            space = self.shard_space(space)
        if self._local1 is not None:
            with timed_transform("forward") as box:
                box.value = self._local1.forward(space[0], scaling)[None]
            return box.value
        with timed_transform("forward") as box:
            box.value = self._forward_jit[scaling](space,
                                                   *self._device_tables)
        return box.value

    # -- batched execution ---------------------------------------------------
    def _batched_jits(self):
        """Lazily-built fused batch executables: one SPMD program with a
        per-shard batch axis (S, B, ...) — N shared-plan transforms become
        one program with B× larger FFT batches, one batched-grid kernel
        launch per compression stage and vmapped collectives, instead of N
        dispatches (the reference's hand-interleaved multi-transform
        overlap, multi_transform_internal.hpp:47-94)."""
        if self._batched is None:
            shmap = functools.partial(
                shard_map, mesh=self.mesh, in_specs=self._base_in_specs,
                out_specs=P(self.axis_name), check_vma=self._check_vma)
            self._batched = {
                "backward": jax.jit(shmap(self._backward_body_batched)),
                Scaling.NONE: jax.jit(shmap(functools.partial(
                    self._forward_body_batched, scaled=False))),
                Scaling.FULL: jax.jit(shmap(functools.partial(
                    self._forward_body_batched, scaled=True))),
            }
        return self._batched

    def shard_values_batch(self, values_batch: Sequence) -> jax.Array:
        """B per-transform value sets (each a per-shard list or a padded
        sharded (S, mv, 2) array) -> one (S, B, mv, 2) sharded array."""
        arrs = [v if isinstance(v, jax.Array) else self.shard_values(v)
                for v in values_batch]
        return jnp.stack(arrs, axis=1)

    def unshard_values_batch(self, values: jax.Array):
        """(S, B, mv, 2) -> list of B per-shard numpy complex value lists."""
        arr = np.asarray(values)
        return [self.unshard_values(arr[:, b]) for b in range(arr.shape[1])]

    def backward_batched(self, values_batch) -> jax.Array:
        """Backward-execute a shared-plan batch as ONE fused SPMD program.
        ``values_batch``: a (S, B, mv, 2) sharded array or a sequence of B
        value sets. Returns the (S, B, planes, ...) sharded space array."""
        if not (isinstance(values_batch, jax.Array)
                and values_batch.ndim == 4):
            values_batch = self.shard_values_batch(values_batch)
        with timed_transform("backward_batched") as box:
            box.value = self._batched_jits()["backward"](
                values_batch, *self._device_tables)
        return box.value

    def forward_batched(self, space_batch,
                        scaling: Scaling = Scaling.NONE) -> jax.Array:
        """Forward-execute a shared-plan batch as ONE fused SPMD program.
        ``space_batch``: a (S, B, planes, ...) sharded array or a sequence
        of B per-shard slab lists. Returns the (S, B, mv, 2) values."""
        scaling = Scaling(scaling)
        nd = 4 if self.dist_plan.hermitian else 5
        if not (isinstance(space_batch, jax.Array)
                and space_batch.ndim == nd + 1):
            space_batch = jnp.stack(
                [s if isinstance(s, jax.Array) else self.shard_space(s)
                 for s in space_batch], axis=1)
        with timed_transform("forward_batched") as box:
            box.value = self._batched_jits()[scaling](
                space_batch, *self._device_tables)
        return box.value

    # -- cross-request coalescing --------------------------------------------
    def coalesce_backward(self, values_list: Sequence):
        """Backward-execute N independent requests' value sets as ONE fused
        SPMD program and demux: one exchange collective round moves all N
        payloads (the Grid amortization, resurrected for the pod lane).
        ``values_list``: N value sets (each a per-shard list or padded
        (S, mv, 2) array). Returns a list of N per-request (S, planes, ...)
        space arrays, each identical to ``self.backward(values_list[i])``."""
        if self._local1 is not None or len(values_list) == 1:
            # comm-size-1 delegates have no batched body; a batch of one
            # gains nothing — run the serial path per request.
            return [self.backward(v) for v in values_list]
        stacked = self.backward_batched(values_list)
        return [stacked[:, b] for b in range(len(values_list))]

    def coalesce_forward(self, space_list: Sequence,
                         scaling: Scaling = Scaling.NONE):
        """Forward twin of :meth:`coalesce_backward`: N space slabs through
        one batched SPMD program, demuxed to N per-request (S, mv, 2) value
        arrays, each identical to ``self.forward(space_list[i], scaling)``."""
        scaling = Scaling(scaling)
        if self._local1 is not None or len(space_list) == 1:
            return [self.forward(s, scaling) for s in space_list]
        stacked = self.forward_batched(space_list, scaling)
        return [stacked[:, b] for b in range(len(space_list))]


def make_distributed_plan(transform_type: TransformType,
                          dim_x: int, dim_y: int, dim_z: int,
                          triplets_per_shard: Sequence[np.ndarray],
                          planes_per_shard: Sequence[int],
                          mesh: Optional[Mesh] = None,
                          precision: str = "single",
                          exchange: ExchangeType = ExchangeType.DEFAULT,
                          use_pallas: Optional[bool] = None,
                          overlap_chunks: Optional[int] = None,
                          wire_precision: Optional[int] = None,
                          wire_error_budget: Optional[float] = None,
                          ) -> DistributedTransformPlan:
    """Plan a distributed transform in one call (the distributed analogue of
    ``Grid::create_transform``, reference grid.hpp:138-141). Under
    ``jax.distributed`` (multi-process), cross-checks that every process
    built the identical plan, like the reference's plan-time allreduce
    mismatch detection (grid_internal.cpp:148-167)."""
    dist = build_distributed_plan(TransformType(transform_type), dim_x, dim_y,
                                  dim_z, triplets_per_shard, planes_per_shard)
    if jax.process_count() > 1:  # pragma: no cover - multi-host only
        from .multihost import validate_consistent
        validate_consistent(dist)
    return DistributedTransformPlan(dist, mesh=mesh, precision=precision,
                                    exchange=exchange, use_pallas=use_pallas,
                                    overlap_chunks=overlap_chunks,
                                    wire_precision=wire_precision,
                                    wire_error_budget=wire_error_budget)

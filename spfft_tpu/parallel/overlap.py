"""Chunked, software-pipelined distributed exchange (compute/comm overlap).

The reference hides its MPI exchange behind compute: the buffered exchange
is issued as a start/finalize pair bracketing the z-stick FFT stage, so
wire time and FFT time overlap (reference src/execution/execution_host.cpp
— SURVEY.md §2.5's overlap structure). The TPU realisation of that
structure is DATAFLOW, not explicit start/finalize calls: the exchange
schedule is split into K destination-balanced sub-schedules ("chunks") and
the SPMD body runs chunk i's pre-exchange FFT stage while chunk i-1's
collective is already issued (issue early, unpack late). That dependence
shape — K independent collectives, each consumed only after every chunk's
compute has been emitted — is exactly what XLA's latency-hiding scheduler
needs to split each collective into an asynchronous start/done pair and
overlap the wire with the surrounding compute
(utils/hlo_inspect.py:collective_async_split asserts the split on lowered
modules; scripts/bench_overlap_ab.py records the measured A/B).

Chunking axes (static slices of the padded per-shard layouts, so the SPMD
body stays one program):

* backward — local STICK rows ``[0, max_sticks)``: chunk c z-IFFTs stick
  rows ``[stick_lo, stick_hi)`` and ships only those rows' segments;
* forward — local PLANE rows ``[0, max_planes)``: chunk c xy-FFTs plane
  rows ``[plane_lo, plane_hi)`` and ships only those planes' segments.

Chunk boundaries come from :func:`chunk_bounds`, which balances the TRUE
row count (sticks/planes actually populated, summed over shards) per
chunk rather than slicing the padded extent evenly — with that split,
every destination's ingress is divided proportionally across chunks
(destination d receives ``num_planes(d) * true_sticks(chunk)`` elements
per backward chunk), i.e. the sub-schedules are destination-balanced by
construction.

Three chunk kinds mirror the three exchange mechanisms (exchange.py):

* ``"block"`` — the padded ``all_to_all`` / ppermute-ring layouts: a chunk
  is a contiguous row/plane slice of the ``(S, max_sticks, max_planes)``
  block; received chunk blocks concatenate back into the full block, so
  no new tables are needed — only the static bounds.
* ``"ragged"`` — the one-collective exact-count exchange: each chunk is a
  complete :class:`~.exchange.RaggedSchedule`-style table set (offset
  vectors, pack tables, CPU-emulation gathers) over the chunk's rows,
  with ONE global unpack table per direction indexing the concatenation
  of all chunk receive buffers (unpack runs once, late).
* ``"compact"`` — the exact-size ppermute op schedule: per-chunk op lists
  built by the same size-classing as the monolithic schedule, again with
  one late global unpack per direction.

Invariants (property-tested in tests/test_overlap_exchange.py):

* union — the chunks' (src, dst, element) sets partition the monolithic
  schedule's exactly, per direction;
* conservation — per-chunk exact wire elements sum to the monolithic
  exact total (:meth:`OverlapSchedule.wire_elements`);
* no hot-spot — no chunk's busiest link exceeds the monolithic
  schedule's busiest link.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..errors import InvalidParameterError
from .exchange import _ragged_direction_tables, _size_classes


def chunk_bounds(true_counts, padded: int, num_chunks: int,
                 skew_weight: float = 1.0) -> tuple:
    """Split the padded row range ``[0, padded)`` into ``num_chunks``
    contiguous slices, SKEW-AWARE: balance per-destination ingress
    within each chunk, not just true-row totals.

    ``true_counts[r]`` is shard r's populated row count (``<= padded``;
    rows are always a prefix of the padded extent). Two normalised
    weights are summed per padded row and the bounds slice at equal
    cumulative weight:

    * the INGRESS weight ``#{r : true_counts[r] > i} / total`` — every
      populated row of every shard ships the same per-destination
      element count (``num_planes(d)`` sticks backward /
      ``num_sticks(d)`` planes forward), so equal cumulative population
      per chunk equalises every destination's per-chunk ingress;
    * the BUSIEST-SOURCE weight ``[i < max(true_counts)] / max`` —
      within one chunk the heaviest (src, dst) link belongs to the
      shard with the most populated rows there, and prefix-populated
      rows make that ``clip(max(true_counts), lo, hi)`` for any slice,
      so equal cumulative share of the largest shard's rows equalises
      the per-chunk busiest link.

    Balancing only the first (the pre-round-13 behavior,
    ``skew_weight=0``) lets one dominant shard concentrate in a chunk
    under skewed stick ownership: ``true_counts=[10, 100]`` at K=2 cut
    the total 55/55 but the dominant shard's link 45/55 — the pipeline
    then stalls on the uneven chunk exactly where overlap was supposed
    to hide the wire. The combined weight splits the difference;
    perfectly uniform shards reproduce the old bounds (both weights
    are then proportional). Bounds are strictly increasing and cover
    ``[0, padded)`` exactly, so the union/conservation/no-hot-spot
    schedule invariants hold for every ``skew_weight``.
    """
    K = int(num_chunks)
    if K < 1:
        raise InvalidParameterError("num_chunks must be >= 1")
    if K > padded:
        raise InvalidParameterError(
            f"num_chunks ({K}) exceeds padded rows ({padded})")
    w = np.zeros(padded, np.float64)
    for c in true_counts:
        w[: int(c)] += 1.0
    total = w.sum()
    if total > 0:
        w /= total
    cmax = int(max(true_counts, default=0))
    if skew_weight and cmax > 0:
        w[:cmax] += float(skew_weight) / cmax
    cum = np.concatenate([[0.0], np.cumsum(w)])
    bounds = [0]
    for c in range(1, K):
        target = cum[-1] * c / K
        j = int(np.searchsorted(cum, target, side="left"))
        j = max(j, bounds[-1] + 1)     # strictly increasing
        j = min(j, padded - (K - c))   # leave >= 1 row per later chunk
        bounds.append(j)
    bounds.append(padded)
    return tuple(zip(bounds[:-1], bounds[1:]))


def chunk_bounds_aligned(true_counts, padded: int, num_chunks: int,
                         align: int, skew_weight: float = 1.0) -> tuple:
    """Super-tile-aligned variant of :func:`chunk_bounds`: every
    INTERIOR bound snaps to the nearest multiple of ``align`` (the
    fused backward kernel's ``r_sticks`` super-tile height), so a
    chunk-sliced fused launch wastes no partial super-tile at chunk
    seams — only the final chunk may end unaligned (``padded`` itself
    need not be a multiple). Falls back to the unaligned bounds when
    the padded extent cannot give every chunk at least one full
    super-tile (``padded < align * num_chunks``); the per-chunk table
    sets handle arbitrary bounds, alignment is purely a waste
    reduction. Same strict-increase / exact-cover invariants as
    :func:`chunk_bounds`."""
    base = chunk_bounds(true_counts, padded, num_chunks, skew_weight)
    a, K = int(align), int(num_chunks)
    if a <= 1 or padded < a * K:
        return base
    bounds = [0]
    for lo, hi in base[:-1]:
        snapped = int(round(hi / a)) * a
        snapped = max(snapped, bounds[-1] + a)
        snapped = min(snapped, padded - a * (K - len(bounds)))
        bounds.append(snapped)
    bounds.append(padded)
    return tuple(zip(bounds[:-1], bounds[1:]))


def _clip_count(count: int, lo: int, hi: int) -> int:
    """Rows of a populated prefix ``[0, count)`` falling in ``[lo, hi)``."""
    return max(0, min(int(count), hi) - lo)


@dataclasses.dataclass(frozen=True)
class BlockChunk:
    """One chunk of the padded block exchange: pure static bounds."""

    stick_lo: int
    stick_hi: int
    plane_lo: int
    plane_hi: int
    n_bwd: np.ndarray    # (S, S) exact backward pair elements
    n_fwd: np.ndarray    # (S, S) exact forward pair elements


@dataclasses.dataclass(frozen=True)
class RaggedChunk:
    """One chunk of the exact-count (ragged) exchange — a complete
    RaggedSchedule-shaped table set over the chunk's stick/plane rows,
    with pack tables indexing CHUNK-LOCAL flat layouts (the pipelined
    body FFTs exactly the chunk's rows, so the pack gather addresses the
    chunk's output, not the full local array)."""

    stick_lo: int
    stick_hi: int
    plane_lo: int
    plane_hi: int
    send_cap: int
    recv_cap: int
    bwd_offsets: tuple       # (input_offsets, send_sizes, output_offsets,
                             #  recv_sizes), each (S, S) int32
    fwd_offsets: tuple
    bwd_pack: np.ndarray     # (S, send_cap) into chunk-local flat sticks
    fwd_pack: np.ndarray     # (S, send_cap) into chunk-local flat grid
    emu_bwd: np.ndarray      # (S, recv_cap) into allgathered flat sends
    emu_fwd: np.ndarray

    @property
    def n_bwd(self) -> np.ndarray:
        return np.asarray(self.bwd_offsets[1], np.int64)

    @property
    def n_fwd(self) -> np.ndarray:
        return np.asarray(self.fwd_offsets[1], np.int64)


@dataclasses.dataclass(frozen=True)
class CompactChunk:
    """One chunk of the exact-size ppermute op schedule. Unlike the
    monolithic :class:`~.exchange.CompactSchedule` (whose one op list
    serves both directions with pairs reversed), backward chunks slice
    STICKS and forward chunks slice PLANES, so each direction gets its
    own op list; pairs are stored in SEND orientation (src, dst) and
    both directions run ``compact_exchange(..., reverse=False)``."""

    stick_lo: int
    stick_hi: int
    plane_lo: int
    plane_hi: int
    bwd_ops: tuple           # (k, L, pairs) — pairs (src, dst)
    fwd_ops: tuple
    bwd_pack: tuple          # per-op (S, L) into chunk-local flat sticks
    fwd_pack: tuple          # per-op (S, L) into chunk-local flat grid
    n_bwd: np.ndarray        # (S, S) exact pair elements
    n_fwd: np.ndarray

    @property
    def bwd_total(self) -> int:
        return int(sum(L for _, L, _ in self.bwd_ops))

    @property
    def fwd_total(self) -> int:
        return int(sum(L for _, L, _ in self.fwd_ops))


@dataclasses.dataclass(frozen=True)
class OverlapSchedule:
    """K destination-balanced sub-schedules plus the late global unpack
    tables. ``kind`` is ``"block"`` / ``"ragged"`` / ``"compact"``;
    block chunks need no tables (received blocks concatenate back into
    the monolithic layout). Accounting here is EXACT per-pair elements
    (no padding, no 1.25x bucket charge) — for ragged that matches the
    monolithic schedule's accounting; for compact it lower-bounds the
    bucket-charged monolithic numbers."""

    kind: str
    num_shards: int
    chunks: tuple
    bwd_unpack: Optional[np.ndarray]   # (S, mp*Y*Xe) into concat'd recvs
    fwd_unpack: Optional[np.ndarray]   # (S, ms*dz)

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)

    # -- schedule introspection (fused-dist per-chunk table builds) ---------
    def stick_bounds(self) -> tuple:
        """Per-chunk backward stick-row slices ``((lo, hi), ...)`` of
        the padded local stick extent — the slices a chunk-sliced fused
        decompress+z-DFT build restricts its gather tables to."""
        return tuple((ch.stick_lo, ch.stick_hi) for ch in self.chunks)

    def plane_bounds(self) -> tuple:
        """Per-chunk forward plane-row slices ``((lo, hi), ...)`` of
        the padded local plane extent."""
        return tuple((ch.plane_lo, ch.plane_hi) for ch in self.chunks)

    # -- exact accounting ---------------------------------------------------
    def _chunk_links(self, c: int, forward: bool):
        n = np.asarray(self.chunks[c].n_fwd if forward
                       else self.chunks[c].n_bwd, np.int64).copy()
        np.fill_diagonal(n, 0)
        return n.sum(axis=1), n.sum(axis=0)

    def chunk_wire_elements(self, c: int, forward: bool = False) -> int:
        """Exact off-shard complex elements chunk ``c`` ships."""
        send, _ = self._chunk_links(c, forward)
        return int(send.sum())

    def chunk_busiest_link_elements(self, c: int,
                                    forward: bool = False) -> int:
        """Max over shards of max(sent, received) for chunk ``c``."""
        send, recv = self._chunk_links(c, forward)
        both = np.maximum(send, recv)
        return int(both.max()) if self.num_shards else 0

    def wire_elements(self) -> int:
        """TOTAL exact off-shard elements per exchange (all chunks) —
        chunking moves no extra bytes, so this equals the monolithic
        exact total (tests assert the conservation)."""
        return sum(self.chunk_wire_elements(c)
                   for c in range(self.num_chunks))

    def busiest_link_elements(self) -> int:
        """Bottleneck-link elements for ONE whole exchange: per-shard
        send/recv summed over all chunks, then max — every chunk's data
        still crosses the same links."""
        send = np.zeros(self.num_shards, np.int64)
        recv = np.zeros(self.num_shards, np.int64)
        for c in range(self.num_chunks):
            s, r = self._chunk_links(c, False)
            send += s
            recv += r
        both = np.maximum(send, recv)
        return int(both.max()) if self.num_shards else 0

    def chunk_scale_rows(self, c: int, forward: bool = False) -> int:
        """int8-wire scale rows chunk ``c`` carries: one f32 absmax
        scale per (destination slot, quant row), quant rows being the
        chunk's stick slice backward / plane slice forward — exactly
        the chunk-bound axes, so the per-chunk sidecars partition the
        monolithic one. Only the padded block kind carries the int8
        rung (exact-count kinds decline it), so other kinds report 0."""
        if self.kind != "block":
            return 0
        ch = self.chunks[c]
        return (ch.plane_hi - ch.plane_lo if forward
                else ch.stick_hi - ch.stick_lo)

    def scale_rows(self, forward: bool = False) -> int:
        """TOTAL int8 scale rows per exchange (all chunks). The chunk
        bounds partition ``[0, max_sticks)`` / ``[0, max_planes)``, so
        this is conserved at every K — the sidecar analogue of the
        :meth:`wire_elements` conservation the tests assert."""
        return sum(self.chunk_scale_rows(c, forward)
                   for c in range(self.num_chunks))

    # -- device-table plumbing ----------------------------------------------
    def device_tables(self) -> list:
        """The (S, ...) arrays the SPMD bodies consume, flattened in a
        fixed order: every chunk's tables, then the two global late
        unpack tables (see :meth:`chunk_table_slices` for the per-chunk
        positions). Block kind needs no tables."""
        if self.kind == "block":
            return []
        out = []
        for ch in self.chunks:
            if self.kind == "ragged":
                out.extend([ch.bwd_pack, ch.fwd_pack])
                out.extend(ch.bwd_offsets)
                out.extend(ch.fwd_offsets)
                out.extend([ch.emu_bwd, ch.emu_fwd])
            else:
                out.extend(ch.bwd_pack)
                out.extend(ch.fwd_pack)
        out.extend([self.bwd_unpack, self.fwd_unpack])
        return out

    def chunk_table_slices(self) -> tuple:
        """Per-chunk index map into :meth:`device_tables`'s flat list.
        Ragged: ``{"bwd_pack", "fwd_pack", "offs_b", "offs_f",
        "emu_bwd", "emu_fwd"}``; compact: ``{"bwd_ops", "fwd_ops"}``
        ((start, stop) ranges). The two global unpack tables always sit
        at positions -2 (backward) and -1 (forward)."""
        maps, pos = [], 0
        for ch in self.chunks:
            if self.kind == "ragged":
                maps.append({
                    "bwd_pack": pos, "fwd_pack": pos + 1,
                    "offs_b": (pos + 2, pos + 6),
                    "offs_f": (pos + 6, pos + 10),
                    "emu_bwd": pos + 10, "emu_fwd": pos + 11})
                pos += 12
            elif self.kind == "compact":
                nb, nf = len(ch.bwd_ops), len(ch.fwd_ops)
                maps.append({"bwd_ops": (pos, pos + nb),
                             "fwd_ops": (pos + nb, pos + nb + nf)})
                pos += nb + nf
            else:
                maps.append({})
        return tuple(maps)

    # -- element introspection (tests: union == monolithic) -----------------
    def bwd_pair_elements(self, c: int) -> dict:
        """Chunk ``c``'s backward payload as ``{(src, dst): sorted array
        of GLOBAL flat local-stick indices (i * dim_z + z)}`` — derived
        from the actual pack tables (not the count matrices), so the
        union test exercises what the wire really carries."""
        ch = self.chunks[c]
        out = {}
        rebase = ch.stick_lo * self._dz_cached
        if self.kind == "ragged":
            io = np.asarray(ch.bwd_offsets[0], np.int64)
            n = np.asarray(ch.bwd_offsets[1], np.int64)
            for j in range(self.num_shards):
                for d in range(self.num_shards):
                    if n[j, d]:
                        seg = ch.bwd_pack[j, io[j, d]:io[j, d] + n[j, d]]
                        out[(j, d)] = np.sort(seg.astype(np.int64)
                                              + rebase)
            return out
        if self.kind == "compact":
            loc = (ch.stick_hi - ch.stick_lo) * self._dz_cached
            for oi, (k, L, pairs) in enumerate(ch.bwd_ops):
                tbl = ch.bwd_pack[oi]
                for j, d in pairs:
                    seg = tbl[j].astype(np.int64)
                    out[(j, d)] = np.sort(seg[seg < loc] + rebase)
            return out
        raise InvalidParameterError(
            "element introspection applies to ragged/compact kinds")

    def fwd_pair_elements(self, c: int) -> dict:
        """Chunk ``c``'s forward payload as ``{(src, dst): sorted array
        of GLOBAL flat local-grid indices (p * dim_y * dim_x_eff +
        col)}`` — same table-derived contract as
        :meth:`bwd_pair_elements`."""
        ch = self.chunks[c]
        out = {}
        rebase = ch.plane_lo * self._grid_row_cached
        if self.kind == "ragged":
            io = np.asarray(ch.fwd_offsets[0], np.int64)
            n = np.asarray(ch.fwd_offsets[1], np.int64)
            for j in range(self.num_shards):
                for d in range(self.num_shards):
                    if n[j, d]:
                        seg = ch.fwd_pack[j, io[j, d]:io[j, d] + n[j, d]]
                        out[(j, d)] = np.sort(seg.astype(np.int64)
                                              + rebase)
            return out
        if self.kind == "compact":
            loc = (ch.plane_hi - ch.plane_lo) * self._grid_row_cached
            for oi, (k, L, pairs) in enumerate(ch.fwd_ops):
                tbl = ch.fwd_pack[oi]
                for j, d in pairs:
                    seg = tbl[j].astype(np.int64)
                    out[(j, d)] = np.sort(seg[seg < loc] + rebase)
            return out
        raise InvalidParameterError(
            "element introspection applies to ragged/compact kinds")

    # dz / grid-row extents are stashed by the builder
    # (object.__setattr__ on the frozen dataclass) purely for the
    # introspection helpers above.
    _dz_cached: int = dataclasses.field(default=0, compare=False)
    _grid_row_cached: int = dataclasses.field(default=0, compare=False)


def _chunk_geometry(dp, num_chunks: int, stick_align: int = 1):
    S = dp.num_shards
    ns = [p.num_sticks for p in dp.shard_plans]
    npl = list(dp.num_planes)
    if stick_align > 1:
        sb = chunk_bounds_aligned(ns, dp.max_sticks, num_chunks,
                                  stick_align)
    else:
        sb = chunk_bounds(ns, dp.max_sticks, num_chunks)
    pb = chunk_bounds(npl, dp.max_planes, num_chunks)
    return S, ns, npl, list(dp.plane_offsets), sb, pb


def _pair_counts(S, ns, npl, ns_c, npl_c):
    n_bwd = np.asarray([[ns_c[j] * npl[d] for d in range(S)]
                        for j in range(S)], np.int64)
    n_fwd = np.asarray([[ns[d] * npl_c[j] for d in range(S)]
                        for j in range(S)], np.int64)
    return n_bwd, n_fwd


def build_overlap_schedule(dp, num_chunks: int, kind: str,
                           x_window=None,
                           stick_align: int = 1) -> OverlapSchedule:
    """Build the K-chunk overlap schedule from a ``DistributedIndexPlan``
    (same duck-typed contract and x-window composition as the monolithic
    builders in exchange.py). ``stick_align > 1`` snaps the backward
    stick bounds to super-tile multiples via
    :func:`chunk_bounds_aligned` (best effort — unaligned fallback when
    the extent is too small) for the chunk-sliced fused launches."""
    from ..indexing import window_sub_cols

    if kind not in ("block", "ragged", "compact"):
        raise InvalidParameterError(f"unknown overlap kind {kind!r}")
    S, ns, npl, off, sb, pb = _chunk_geometry(dp, num_chunks, stick_align)
    ms, mp_ = dp.max_sticks, dp.max_planes
    dz, Y, Xf = dp.dim_z, dp.dim_y, dp.dim_x_freq
    Xe = Xf if x_window is None else x_window[1]

    def grid_cols(cols):
        if x_window is None:
            return np.asarray(cols, np.int64)
        return window_sub_cols(cols, Xf, *x_window).astype(np.int64)

    if kind == "block":
        chunks = []
        for (s0, s1), (p0, p1) in zip(sb, pb):
            ns_c = [_clip_count(n, s0, s1) for n in ns]
            npl_c = [_clip_count(n, p0, p1) for n in npl]
            n_bwd, n_fwd = _pair_counts(S, ns, npl, ns_c, npl_c)
            chunks.append(BlockChunk(s0, s1, p0, p1, n_bwd, n_fwd))
        sched = OverlapSchedule(kind, S, tuple(chunks), None, None)
        object.__setattr__(sched, "_dz_cached", dz)
        object.__setattr__(sched, "_grid_row_cached", Y * Xe)
        return sched

    # -- z ownership (forward unpack shares it across kinds) ---------------
    z_owner = np.empty(dz, np.int64)
    z_plane = np.empty(dz, np.int64)
    for s in range(S):
        z_owner[off[s]:off[s] + npl[s]] = s
        z_plane[off[s]:off[s] + npl[s]] = np.arange(npl[s])
    # chunk index of each global z (by its owner-local plane row)
    z_chunk = np.empty(dz, np.int64)
    for c, (p0, p1) in enumerate(pb):
        sel = (z_plane >= p0) & (z_plane < p1)
        z_chunk[sel] = c

    if kind == "ragged":
        chunks, roffs = [], []
        for (s0, s1), (p0, p1) in zip(sb, pb):
            ns_c = [_clip_count(n, s0, s1) for n in ns]
            npl_c = [_clip_count(n, p0, p1) for n in npl]
            n_bwd, n_fwd = _pair_counts(S, ns, npl, ns_c, npl_c)
            bwd_offs, s_b, r_b, roff_b = _ragged_direction_tables(S, n_bwd)
            fwd_offs, s_f, r_f, roff_f = _ragged_direction_tables(S, n_fwd)
            send_cap, recv_cap = max(s_b, s_f), max(r_b, r_f)
            io_b = bwd_offs[0].astype(np.int64)
            io_f = fwd_offs[0].astype(np.int64)
            loc_sticks = (s1 - s0) * dz
            loc_grid = (p1 - p0) * Y * Xe
            bwd_pack = np.full((S, send_cap), loc_sticks, np.int32)
            fwd_pack = np.full((S, send_cap), loc_grid, np.int32)
            emu_bwd = np.full((S, recv_cap), S * send_cap, np.int32)
            emu_fwd = np.full((S, recv_cap), S * send_cap, np.int32)
            for j in range(S):
                for d in range(S):
                    n = ns_c[j] * npl[d]
                    if n:
                        i = np.arange(ns_c[j])[:, None]   # chunk-local
                        z = off[d] + np.arange(npl[d])[None, :]
                        bwd_pack[j, io_b[j, d]:io_b[j, d] + n] = \
                            (i * dz + z).reshape(-1)
                        emu_bwd[d, roff_b[d, j]:roff_b[d, j] + n] = \
                            j * send_cap + io_b[j, d] + np.arange(n)
                    m = ns[d] * npl_c[j]
                    if m:
                        cols = grid_cols(dp.shard_plans[d].scatter_cols)
                        p = np.arange(npl_c[j])[None, :]  # chunk-local
                        fwd_pack[j, io_f[j, d]:io_f[j, d] + m] = \
                            (p * (Y * Xe) + cols[:, None]).reshape(-1)
                        emu_fwd[d, roff_f[d, j]:roff_f[d, j] + m] = \
                            j * send_cap + io_f[j, d] + np.arange(m)
            chunks.append(RaggedChunk(
                s0, s1, p0, p1, send_cap, recv_cap, bwd_offs, fwd_offs,
                bwd_pack, fwd_pack, emu_bwd, emu_fwd))
            roffs.append((roff_b, roff_f))
        # late unpack: positions in the chunk-ordered recv concatenation
        # (both directions share the per-chunk recv_cap layout)
        coff = np.concatenate(
            [[0], np.cumsum([ch.recv_cap for ch in chunks])]).astype(
                np.int64)
        total = int(coff[-1])
        bwd_unpack = np.full((S, mp_ * Y * Xe), total, np.int32)
        for r in range(S):
            if npl[r] == 0:
                continue
            for s in range(S):
                for c, ((s0, s1), (roff_b, _)) in enumerate(zip(sb, roffs)):
                    nsc = _clip_count(ns[s], s0, s1)
                    if nsc == 0:
                        continue
                    cols = grid_cols(
                        dp.shard_plans[s].scatter_cols)[s0:s0 + nsc]
                    i = np.arange(nsc)[:, None]
                    p = np.arange(npl[r])[None, :]
                    pos = coff[c] + roff_b[r, s] + i * npl[r] + p
                    flat_idx = p * (Y * Xe) + cols[:, None]
                    bwd_unpack[r][flat_idx.reshape(-1)] = pos.reshape(-1)
        fwd_unpack = np.full((S, ms * dz), total, np.int32)
        npl_cz = np.asarray(  # planes of z's owner inside z's chunk
            [_clip_count(npl[o], *pb[c])
             for o, c in zip(z_owner, z_chunk)], np.int64)
        for d in range(S):
            if ns[d] == 0:
                continue
            base = np.asarray(
                [coff[z_chunk[z]] + roffs[z_chunk[z]][1][d, z_owner[z]]
                 + (z_plane[z] - pb[z_chunk[z]][0]) for z in range(dz)],
                np.int64)
            i = np.arange(ns[d])[:, None]
            idx = base[None, :] + i * npl_cz[None, :]
            fwd_unpack[d, :ns[d] * dz] = idx.reshape(-1)
        sched = OverlapSchedule(kind, S, tuple(chunks), bwd_unpack,
                                fwd_unpack)
        object.__setattr__(sched, "_dz_cached", dz)
        object.__setattr__(sched, "_grid_row_cached", Y * Xe)
        return sched

    # kind == "compact": per-direction exact-size op schedules per chunk
    chunks, meta = [], []
    for (s0, s1), (p0, p1) in zip(sb, pb):
        ns_c = [_clip_count(n, s0, s1) for n in ns]
        npl_c = [_clip_count(n, p0, p1) for n in npl]
        n_bwd, n_fwd = _pair_counts(S, ns, npl, ns_c, npl_c)
        loc_sticks = (s1 - s0) * dz
        loc_grid = (p1 - p0) * Y * Xe

        def build_ops(sizes_of):
            ops = []
            for k in range(S):
                sizes = {j: sizes_of(j, (j + k) % S) for j in range(S)
                         if sizes_of(j, (j + k) % S) > 0}
                for L, js in _size_classes(sizes):
                    ops.append((k, int(L),
                                tuple((j, (j + k) % S) for j in js)))
            return ops or [(0, 1, ())]

        bwd_ops = build_ops(lambda j, d: ns_c[j] * npl[d])
        fwd_ops = build_ops(lambda j, d: ns[d] * npl_c[j])
        bwd_pack = []
        for k, L, pairs in bwd_ops:
            tbl = np.full((S, L), loc_sticks, np.int32)
            for j, d in pairs:
                n = ns_c[j] * npl[d]
                i = np.arange(ns_c[j])[:, None]
                z = off[d] + np.arange(npl[d])[None, :]
                tbl[j, :n] = (i * dz + z).reshape(-1)
            bwd_pack.append(tbl)
        fwd_pack = []
        for k, L, pairs in fwd_ops:
            tbl = np.full((S, L), loc_grid, np.int32)
            for j, d in pairs:
                m = ns[d] * npl_c[j]
                cols = grid_cols(dp.shard_plans[d].scatter_cols)
                p = np.arange(npl_c[j])[None, :]
                tbl[j, :m] = (p * (Y * Xe) + cols[:, None]).reshape(-1)
            fwd_pack.append(tbl)

        def op_index(ops):
            offs = np.concatenate(
                [[0], np.cumsum([L for _, L, _ in ops])]).astype(np.int64)
            op_of = {}
            for oi, (k, _, pairs) in enumerate(ops):
                for pr in pairs:
                    op_of[pr] = oi
            return offs, op_of

        chunks.append(CompactChunk(s0, s1, p0, p1, tuple(bwd_ops),
                                   tuple(fwd_ops), tuple(bwd_pack),
                                   tuple(fwd_pack), n_bwd, n_fwd))
        meta.append((op_index(bwd_ops), op_index(fwd_ops)))
    coff_b = np.concatenate(
        [[0], np.cumsum([ch.bwd_total for ch in chunks])]).astype(np.int64)
    coff_f = np.concatenate(
        [[0], np.cumsum([ch.fwd_total for ch in chunks])]).astype(np.int64)
    bwd_unpack = np.full((S, mp_ * Y * Xe), int(coff_b[-1]), np.int32)
    for r in range(S):
        if npl[r] == 0:
            continue
        for s in range(S):
            for c, ((s0, s1), ((offs_b, op_b), _)) in enumerate(
                    zip(sb, meta)):
                nsc = _clip_count(ns[s], s0, s1)
                if nsc == 0:
                    continue
                cols = grid_cols(
                    dp.shard_plans[s].scatter_cols)[s0:s0 + nsc]
                i = np.arange(nsc)[:, None]
                p = np.arange(npl[r])[None, :]
                pos = (coff_b[c] + offs_b[op_b[(s, r)]]
                       + i * npl[r] + p)
                flat_idx = p * (Y * Xe) + cols[:, None]
                bwd_unpack[r][flat_idx.reshape(-1)] = pos.reshape(-1)
    fwd_unpack = np.full((S, ms * dz), int(coff_f[-1]), np.int32)
    npl_cz = np.asarray([_clip_count(npl[o], *pb[c])
                         for o, c in zip(z_owner, z_chunk)], np.int64)
    for d in range(S):
        if ns[d] == 0:
            continue
        base = np.empty(dz, np.int64)
        for z in range(dz):
            c = int(z_chunk[z])
            (offs_f, op_f) = meta[c][1]
            base[z] = (coff_f[c] + offs_f[op_f[(int(z_owner[z]), d)]]
                       + (z_plane[z] - pb[c][0]))
        i = np.arange(ns[d])[:, None]
        idx = base[None, :] + i * npl_cz[None, :]
        fwd_unpack[d, :ns[d] * dz] = idx.reshape(-1)
    sched = OverlapSchedule(kind, S, tuple(chunks), bwd_unpack, fwd_unpack)
    object.__setattr__(sched, "_dz_cached", dz)
    object.__setattr__(sched, "_grid_row_cached", Y * Xe)
    return sched

"""Device-mesh helpers.

The reference scales over MPI ranks with a duplicated communicator per Grid
(reference: src/mpi_util/mpi_communicator_handle.hpp:47-56). The TPU-native
equivalent of the communicator is a 1-D ``jax.sharding.Mesh`` over the shard
axis; collectives ride ICI within a pod slice and DCN across slices, chosen by
XLA from the device order — there is no NCCL/MPI analogue to manage.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..errors import InvalidParameterError

SHARD_AXIS = "shards"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: newer JAX exposes
    ``jax.shard_map`` (replication checking spelled ``check_vma``);
    0.4.x ships it as ``jax.experimental.shard_map.shard_map`` with the
    same check spelled ``check_rep``. One wrapper so every SPMD entry
    point in this library works on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def make_mesh(num_shards: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              axis_name: str = SHARD_AXIS) -> Mesh:
    """Create a 1-D mesh over ``num_shards`` devices (default: all)."""
    if devices is None:
        devices = jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise InvalidParameterError(
            f"requested {num_shards} shards but only {len(devices)} devices "
            "are available")
    return Mesh(np.asarray(devices[:num_shards]), (axis_name,))

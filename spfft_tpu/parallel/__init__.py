"""Distributed slab<->pencil sparse FFT over a JAX device mesh."""

from .mesh import make_mesh  # noqa: F401
from .dist import (DistributedIndexPlan, DistributedTransformPlan,
                   build_distributed_plan, make_distributed_plan)  # noqa: F401
from .overlap import (OverlapSchedule, build_overlap_schedule,  # noqa: F401
                      chunk_bounds)
from .multihost import (build_distributed_plan_multihost,  # noqa: F401
                        initialize as initialize_multihost,
                        plan_fingerprint, validate_consistent)

"""Multi-host (multi-process) support.

The reference's multi-node story is MPI: every rank calls the collective Grid
and Transform constructors, which (a) duplicate the communicator, (b) cross-
check constructor parameters with an ``MPI_Allreduce`` so a rank passing
different dims fails fast with ``MPIParameterMismatchError`` (reference:
src/spfft/grid_internal.cpp:148-167), and (c) exchange every rank's z-stick
list point-to-point so all ranks hold the full distribution plan (reference:
src/compression/indices.hpp:58-102, src/parameters/parameters.cpp:81-109).

The TPU-native counterpart runs one Python process per host under
``jax.distributed``; collectives ride ICI within a slice and DCN across
slices. This module reproduces the three plan-time behaviours:

* :func:`initialize` — process-group bring-up (the communicator analogue).
* :func:`validate_consistent` — cross-host parameter-mismatch detection via
  an allgathered digest of the plan's global parameters.
* :func:`build_distributed_plan_multihost` — each process contributes the
  triplet lists / plane counts of the shards it owns; a process-level
  allgather makes the global distribution plan identical everywhere (the
  stick-list exchange of indices.hpp:58-102, as one fixed-shape collective).

Everything degenerates to a no-op / local computation with one process, so
the logic is testable single-host; the driver's multi-chip dry-run exercises
the sharded execution path itself.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import jax
import numpy as np

from ..errors import DistributedError, ParameterMismatchError
from ..types import TransformType
from .dist import DistributedIndexPlan, build_distributed_plan


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX process group (no-op if already initialized or
    single-process). The moral equivalent of ``MPI_Init`` +
    communicator setup (reference: src/mpi_util/mpi_init_handle.hpp:39-59);
    afterwards ``jax.devices()`` spans all hosts."""
    if coordinator_address is None:
        return  # single-process mode
    # Must not touch jax.devices()/process_count() here: any backend query
    # initializes XLA, after which jax.distributed.initialize refuses to
    # run. Detect prior bring-up via the distributed client state — a
    # private JAX module, so probe it defensively: if the internals moved,
    # fall through and let initialize() itself report double bring-up.
    try:
        from jax._src import distributed as _dist_state
        already = getattr(_dist_state.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - depends on JAX version
        already = False
    if already:
        return  # already initialized
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except (RuntimeError, ValueError) as e:  # pragma: no cover - env-dep.
        msg = str(e).lower()
        # jax's double-init message (JAX 0.9: RuntimeError "...should only
        # be called once"; some versions raise ValueError): match loosely in
        # case the wording shifts again. The state probe above catches the
        # common case even if these strings rot.
        if "already initialized" in msg or "only be called once" in msg:
            return
        raise DistributedError(f"jax.distributed initialization failed: {e}")
    except Exception as e:  # pragma: no cover - environment-dependent
        raise DistributedError(f"jax.distributed initialization failed: {e}")


def plan_fingerprint(dist_plan: DistributedIndexPlan) -> bytes:
    """A 16-byte digest of everything that must agree across processes:
    dims, transform type, per-shard plane counts/offsets and the full
    per-shard stick tables (the fields of the reference's allgathered
    ``TransposeParameter`` struct plus its exchanged stick lists,
    parameters.cpp:81-109)."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.asarray([dist_plan.dim_x, dist_plan.dim_y, dist_plan.dim_z,
                         int(dist_plan.transform_type is TransformType.R2C)],
                        np.int64).tobytes())
    h.update(np.asarray(dist_plan.num_planes, np.int64).tobytes())
    h.update(np.asarray(dist_plan.plane_offsets, np.int64).tobytes())
    for sp in dist_plan.shard_plans:
        h.update(b"|")
        h.update(np.ascontiguousarray(sp.stick_keys, np.int64).tobytes())
        h.update(np.ascontiguousarray(sp.value_indices, np.int64).tobytes())
    return h.digest()


def _check_digests(digests: np.ndarray, local: bytes,
                   process_index: Optional[int] = None) -> None:
    """Compare per-process digests (rows of a (P, 16) uint8 array); raise
    naming the mismatching processes. Split out for unit testing."""
    if process_index is None:
        process_index = jax.process_index()
    rows = np.asarray(digests, np.uint8).reshape(-1, len(local))
    local_row = np.frombuffer(local, np.uint8)
    bad = [p for p in range(rows.shape[0])
           if not np.array_equal(rows[p], local_row)]
    if bad:
        raise ParameterMismatchError(
            "distributed plan parameters differ across processes: "
            f"process(es) {bad} disagree with process {process_index} "
            "(all hosts must construct the plan with identical dims, "
            "transform type, plane split and stick sets)")


def _default_collective():
    """(allgather, process_count, process_index) from the live JAX process
    group — the production collective behind the injectable seam."""
    from jax.experimental import multihost_utils
    return (multihost_utils.process_allgather, jax.process_count(),
            jax.process_index())


def _resolve_collective(collective):
    """An injected collective triple wins; otherwise the live process group
    (queried only when multi-process, so single-process callers never touch
    the backend here)."""
    if collective is not None:
        return collective
    if jax.process_count() > 1:
        return _default_collective()
    return (None, 1, 0)


def validate_consistent(dist_plan: DistributedIndexPlan, *,
                        collective=None) -> None:
    """Cross-host parameter-mismatch detection (reference:
    grid_internal.cpp:148-167 allreduce check). Collective: every process
    must call it with its locally-built plan; raises
    ``ParameterMismatchError`` on any process whose plan differs.

    ``collective`` is an injectable ``(allgather, process_count,
    process_index)`` triple (default: the live ``jax.distributed`` process
    group via ``multihost_utils.process_allgather``) so the multi-process
    logic is unit-testable without a real cluster."""
    allgather, process_count, process_index = _resolve_collective(collective)
    if process_count == 1:
        return
    local = plan_fingerprint(dist_plan)
    gathered = allgather(np.frombuffer(local, np.uint8))
    _check_digests(gathered, local, process_index)


def _pad_gather_triplets(triplets: Sequence[np.ndarray], max_rows: int):
    """Stack variable-length (n_i, 3) triplet arrays into a fixed
    (len, max_rows, 4) block whose 4th column is a validity flag — the
    fixed-shape layout a process-level allgather needs."""
    out = np.zeros((len(triplets), max_rows, 4), np.int64)
    for i, t in enumerate(triplets):
        t = np.asarray(t, np.int64).reshape(-1, 3)
        out[i, :len(t), :3] = t
        out[i, :len(t), 3] = 1
    return out


def build_distributed_plan_multihost(
        transform_type: TransformType, dim_x: int, dim_y: int, dim_z: int,
        local_triplets: Sequence[np.ndarray],
        local_planes: Sequence[int],
        shards_per_process: Optional[int] = None, *,
        collective=None) -> DistributedIndexPlan:
    """Build the global distribution plan when each process only knows its
    own shards' sparse indices.

    ``local_triplets[i]`` / ``local_planes[i]`` describe the i-th shard owned
    by *this* process; every process must own the same number of shards
    (``shards_per_process``, defaulting to ``len(local_triplets)``, must
    match across processes — checked via the plan digest afterwards). The
    stick lists are exchanged with one process-level allgather, mirroring
    the reference's P2P stick-list exchange (indices.hpp:58-102), and the
    identical global plan is built and validated on every process.

    ``collective`` is an injectable ``(allgather, process_count,
    process_index)`` triple (default: the live ``jax.distributed`` process
    group) — see :func:`validate_consistent`.
    """
    if shards_per_process is None:
        shards_per_process = len(local_triplets)
    if shards_per_process < 1:
        raise ParameterMismatchError(
            "shards_per_process must be >= 1: every process must own at "
            "least one shard (an empty shard is a valid owner of zero "
            "sticks/planes, a shardless process is not)")
    if len(local_triplets) != shards_per_process \
            or len(local_planes) != shards_per_process:
        raise ParameterMismatchError(
            f"expected {shards_per_process} local shards, got "
            f"{len(local_triplets)} triplet lists / {len(local_planes)} "
            "plane counts")
    allgather, process_count, process_index = _resolve_collective(collective)
    if process_count == 1:
        return build_distributed_plan(transform_type, dim_x, dim_y, dim_z,
                                      local_triplets, local_planes)
    # Fail fast on unequal shard counts BEFORE any shaped collective: a
    # (2,) vs (3,) allgather mismatch would hang or die opaquely inside XLA.
    all_nshards = np.asarray(
        allgather(np.int64(shards_per_process))).reshape(-1)
    if not (all_nshards == shards_per_process).all():
        raise ParameterMismatchError(
            "shards_per_process differs across processes: "
            f"{all_nshards.tolist()}")
    # Cross-check the scalar constructor parameters BEFORE building anything
    # (the reference's first allreduce, grid_internal.cpp:148-167): a dim
    # mismatch must raise on EVERY process in the same collective round —
    # discovering it later through a local Σplanes!=dim_z failure would
    # leave the agreeing processes hanging in the next collective.
    params = np.asarray([dim_x, dim_y, dim_z,
                         int(TransformType(transform_type) is
                             TransformType.R2C)], np.int64)
    all_params = np.asarray(allgather(params)).reshape(-1, 4)
    if not (all_params == params).all():
        bad = [p for p in range(all_params.shape[0])
               if not np.array_equal(all_params[p], params)]
        raise ParameterMismatchError(
            "transform parameters differ across processes: process(es) "
            f"{bad} disagree with process {process_index} on "
            "(dim_x, dim_y, dim_z, transform_type): "
            f"{all_params.tolist()}")
    counts = np.asarray([len(np.asarray(t).reshape(-1, 3))
                         for t in local_triplets], np.int64)
    all_counts = allgather(counts)
    max_rows = max(1, int(np.asarray(all_counts).max()))
    block = _pad_gather_triplets(local_triplets, max_rows)
    all_blocks = allgather(block)
    all_planes = allgather(np.asarray(local_planes, np.int64))
    all_blocks = np.asarray(all_blocks).reshape(-1, max_rows, 4)
    all_planes = np.asarray(all_planes).reshape(-1)
    triplets_per_shard = [b[b[:, 3] == 1][:, :3] for b in all_blocks]
    plan = build_distributed_plan(transform_type, dim_x, dim_y, dim_z,
                                  triplets_per_shard, list(all_planes))
    validate_consistent(
        plan, collective=(allgather, process_count, process_index))
    return plan

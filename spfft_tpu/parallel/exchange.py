"""The slab<->pencil exchange: pack, all-to-all, unpack.

TPU-native rebuild of the reference transpose/exchange engine
(reference: src/transpose/ — eight MPI/local variants, SURVEY.md §2.5). On a
TPU mesh all variants collapse to one ``lax.all_to_all`` on a padded
``(num_shards, max_sticks, max_planes)`` complex block — the analogue of the
reference's BUFFERED MPI_Alltoall layout (transpose_mpi_buffered_host.cpp),
which is the natural fit for XLA's fixed-shape collectives. Data stays in HBM
end-to-end, i.e. the reference's GPUDirect mode (SPFFT_GPU_DIRECT,
transpose_mpi_buffered_gpu.cpp:171-199) is implicit and always on.

Pack/unpack are gathers/scatters with plan-time index tables and sentinel
padding:

* pack (freq side): restrict each local stick to the z-planes owned by each
  target shard (reference pack_backward,
  transpose_mpi_compact_buffered_host.cpp:109-125);
* unpack (space side): scatter every source shard's sticks into the local
  plane grid by xy index (reference unpack_backward, :128-175).

The reference's reduced-precision wire option (``*_FLOAT`` exchange types,
docs/source/details.rst "MPI Exchange") maps to casting the interleaved block
to the next lower real dtype around the collective: f64 -> f32 on the wire for
double transforms, f32 -> bf16 for single. The bottom rung of the wire ladder
(docs/distributed.md "Compressed wire") quantizes the interleaved block to
int8 with one float32 absmax scale per (target-slot, stick-row) — the scales
are bitcast to int8 and concatenated after the payload on each slot's row, so
payload and scales ride the SAME collective and one round still suffices.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import stages
from ..utils.dtypes import complex_to_interleaved, interleaved_to_complex


def pack_freq_to_blocks(sticks, z_map):
    """Split z-FFT'ed local sticks into per-target-shard plane blocks.

    Args:
      sticks: (max_sticks, dim_z) complex — full-z local sticks.
      z_map: (num_shards, max_planes) int32 — global z index of each target
        shard's p-th plane, sentinel ``dim_z`` for padding rows.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    blocks = jnp.take(sticks, z_map, axis=1, mode="fill", fill_value=0)
    return jnp.transpose(blocks, (1, 0, 2))


def unpack_blocks_to_grid(blocks, global_col_inv, dim_y: int,
                          dim_x_freq: int):
    """Place received stick segments into the local frequency plane grid —
    as a row *gather* through the plan-time inverse column map (runtime
    scatters lower near-serially on TPU; see indexing.inverse_col_map).

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex — blocks[s] holds
        shard s's sticks restricted to this shard's planes.
      global_col_inv: (dim_y * dim_x_freq,) int32 — plane column -> global
        padded stick index ``shard * max_sticks + i``, sentinel
        ``num_shards * max_sticks`` for empty columns.
    Returns:
      (max_planes, dim_y, dim_x_freq) complex.
    """
    num_shards, max_sticks, max_planes = blocks.shape
    rows = blocks.reshape(num_shards * max_sticks, max_planes)
    grid_t = stages.gather_rows_with_sentinel(rows, global_col_inv)
    return grid_t.T.reshape(max_planes, dim_y, dim_x_freq)


def pack_space_to_blocks(grid, all_scatter_cols, num_shards: int,
                         max_sticks: int):
    """Forward-direction pack: gather every shard's stick columns out of the
    local plane grid (reference pack_forward,
    transpose_mpi_compact_buffered_host.cpp:203-242).

    Args:
      grid: (max_planes, dim_y, dim_x_freq) complex.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    max_planes = grid.shape[0]
    flat = grid.reshape(max_planes, -1)
    cols = jnp.take(flat, all_scatter_cols, axis=1, mode="fill",
                    fill_value=0)  # (max_planes, S * max_sticks)
    blocks = cols.reshape(max_planes, num_shards, max_sticks)
    return jnp.transpose(blocks, (1, 2, 0))


def unpack_blocks_to_sticks(blocks, z_src):
    """Forward-direction unpack: reassemble full-z local sticks from received
    per-source-shard plane blocks (reference unpack_forward,
    transpose_mpi_compact_buffered_host.cpp:245-266) — as a column gather
    through the total map ``z_src`` (every z plane has exactly one owner).

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex — blocks[s] holds
        this shard's sticks restricted to shard s's planes.
      z_src: (dim_z,) int32 — global z -> ``owner_shard * max_planes + p``.
    Returns:
      (max_sticks, dim_z) complex.
    """
    num_shards, max_sticks, max_planes = blocks.shape
    flat = jnp.transpose(blocks, (1, 0, 2)).reshape(max_sticks,
                                                    num_shards * max_planes)
    return flat[:, z_src]


def is_int8_wire(wire_real_dtype) -> bool:
    """True when ``wire_real_dtype`` selects the int8-quantized wire rung
    (the other rungs are plain real dtypes the interleaved block casts to)."""
    return wire_real_dtype is not None \
        and np.dtype(wire_real_dtype) == np.dtype(np.int8)


def quantize_blocks_int8(blocks, quant_axis: int):
    """Quantize a padded complex block to the int8 wire layout.

    The block is viewed as interleaved reals and quantized with one
    float32 absmax scale per row of ``quant_axis`` (axis 1 = stick rows
    for the backward exchange, axis 2 = plane rows for the forward —
    matching the axis the overlap pipeline chunks, so per-chunk scale
    bytes sum exactly to the monolithic total at every K). Scales are
    bitcast to int8 and concatenated after the payload on each slot's
    row: ``packed[s] = [payload(rows * planes * 2 int8), scales(rows *
    4 int8)]``, so one collective moves both.

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex.
      quant_axis: 1 (per-stick scales) or 2 (per-plane scales).
    Returns:
      (num_shards, payload + scale bytes) int8.
    """
    il = complex_to_interleaved(blocks).astype(jnp.float32)
    reduce_axes = tuple(a for a in (1, 2, 3) if a != quant_axis)
    absmax = jnp.max(jnp.abs(il), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0,
                      jnp.ones_like(absmax))
    q = jnp.clip(jnp.round(il / scale), -127, 127).astype(jnp.int8)
    num_shards = il.shape[0]
    payload = q.reshape(num_shards, -1)
    scales8 = jax.lax.bitcast_convert_type(
        scale.reshape(num_shards, -1), jnp.int8
    ).reshape(num_shards, -1)
    return jnp.concatenate([payload, scales8], axis=1)


def dequantize_blocks_int8(packed, shape, quant_axis: int, real_dtype):
    """Invert :func:`quantize_blocks_int8` after the collective.

    Args:
      packed: (num_shards, payload + scale bytes) int8 — each slot row
        carries the SENDER's payload and its scales (rows travel intact
        through both block collectives, so slot r's scales are always
        the ones slot r's payload was quantized with).
      shape: the (num_shards, max_sticks, max_planes) block shape.
      quant_axis: must match the quantize call.
      real_dtype: the transform's real dtype to cast back to.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    num_shards, max_sticks, max_planes = shape
    n_payload = max_sticks * max_planes * 2
    q = packed[:, :n_payload].reshape(
        num_shards, max_sticks, max_planes, 2).astype(jnp.float32)
    rows = max_sticks if quant_axis == 1 else max_planes
    scale = jax.lax.bitcast_convert_type(
        packed[:, n_payload:].reshape(num_shards, rows, 4), jnp.float32)
    bshape = [num_shards, 1, 1, 1]
    bshape[quant_axis] = rows
    il = q * scale.reshape(bshape)
    return interleaved_to_complex(il.astype(real_dtype))


def ring_exchange_blocks(blocks, axis_name: str,
                         wire_real_dtype: Optional[jnp.dtype] = None,
                         quant_axis: int = 1):
    """All-to-all block exchange as S-1 ``ppermute`` ring steps.

    Mechanically distinct alternative to the single fused ``all_to_all``
    (the reference likewise ships three mechanically different exchange
    algorithms, Alltoall/Alltoallv/Alltoallw — SURVEY.md §2.5): each step k
    sends exactly one peer block to the shard k hops away, so XLA can
    software-pipeline the steps with surrounding compute, and each transfer
    rides a single ICI hop on a ring topology. Semantically identical to
    :func:`all_to_all_blocks`; selected via ``ExchangeType.UNBUFFERED``
    (the reference variant that also trades fewer big copies for more
    transfer operations).
    """
    num_shards = blocks.shape[0]
    if num_shards == 1:
        return blocks
    if is_int8_wire(wire_real_dtype):
        rdt = blocks.real.dtype
        packed = quantize_blocks_int8(blocks, quant_axis)
        out = ring_exchange_blocks(packed, axis_name, None)
        return dequantize_blocks_int8(out, blocks.shape, quant_axis, rdt)
    if wire_real_dtype is not None:
        rdt = blocks.real.dtype
        il = complex_to_interleaved(blocks).astype(wire_real_dtype)
        out = ring_exchange_blocks(il, axis_name, None)
        return interleaved_to_complex(out.astype(rdt))
    idx = jax.lax.axis_index(axis_name)
    # received[k] = source shard (r - k)'s block addressed to r
    received = [blocks[idx]]
    for k in range(1, num_shards):
        perm = [(j, (j + k) % num_shards) for j in range(num_shards)]
        send = blocks[(idx + k) % num_shards]
        received.append(jax.lax.ppermute(send, axis_name, perm))
    stacked = jnp.stack(received, axis=0)
    # out[s] must be shard s's block = received[(r - s) % S]; as a function
    # of s that is a reversal followed by a roll of r + 1.
    return jnp.roll(stacked[::-1], idx + 1, axis=0)


@dataclasses.dataclass(frozen=True)
class CompactSchedule:
    """Plan-time schedule for the exact-count (ragged) exchange — the
    Alltoallv analogue (reference:
    src/transpose/transpose_mpi_compact_buffered_host.cpp:83-105 computes
    per-rank counts/displacements at plan time; :183-200 runs the
    MPI_Alltoallv).

    XLA collectives are fixed-shape, so "ragged" becomes a static schedule
    of exact-size ``ppermute`` ops: the (stick-owner ``j`` -> plane-owner
    ``d``) pairs of each hop distance ``k = (d - j) % S`` are grouped into
    *size classes* (exact element count ``ns(j) * np(d)``, a plan-time
    constant; BUCKET_FACTOR=1.25 buckets when a hop has more than
    MAX_EXACT_CLASSES distinct sizes),
    and each (hop, class) becomes one ppermute carrying ONLY its member
    pairs — a ppermute transfers nothing along pairs absent from its
    permutation, so a pair never pays for a bigger pair in the same hop.
    Total off-shard wire elements per shard therefore track the true
    per-pair counts (the padded layout ships
    ``(S-1) * max_sticks * max_planes`` regardless — the padding waste
    SURVEY.md §7.3 flags as the scaling risk); with a skewed PLANE
    distribution, a plain per-hop-max schedule would pad every hop to the
    big destination's size and save nothing. The same ops serve both
    directions (the pairs flow reversed).

    Pack/unpack are element gathers through plan-time index tables with
    out-of-range sentinels (``jnp.take`` fill mode), sharded over the mesh
    axis. Layout of an op's flat buffer, sent by shard ``j`` to ``d``
    (backward; forward reverses): element ``i * np(d) + p`` is stick ``i``,
    plane ``p`` of shard ``d``'s slab.
    """

    num_shards: int
    ops: tuple                       # (k, L, pairs) — hop distance, buffer
                                     # elements, tuple of (src, dst) pairs
                                     # carried (backward direction)
    bwd_pack: tuple                  # per-op (S, L) into flat sticks
    bwd_unpack: np.ndarray           # (S, mp*Y*Xf) into concat recv buffer
    fwd_pack: tuple                  # per-op (S, L) into flat grid
    fwd_unpack: np.ndarray           # (S, ms*dz) into concat recv buffer

    @property
    def hop_sizes(self) -> tuple:
        """Buffer elements per op (kept name: op count == len(hop_sizes))."""
        return tuple(L for _, L, _ in self.ops)

    @property
    def total_recv(self) -> int:
        return int(sum(self.hop_sizes))

    def _send_recv_per_shard(self):
        send = np.zeros(self.num_shards, np.int64)
        recv = np.zeros(self.num_shards, np.int64)
        for k, L, pairs in self.ops:
            if k == 0:
                continue
            for j, d in pairs:
                send[j] += L
                recv[d] += L
        return send, recv

    def wire_elements(self) -> int:
        """TOTAL off-shard complex elements per exchange, summed over all
        shards (hop 0 is local). The aggregate-ICI-traffic metric; compare
        with the padded layout's ``S * (S-1) * max_sticks * max_planes``.

        Counts what the ppermute ops actually ship: each pair is charged
        its op's full buffer size L — exact when the hop has <=
        MAX_EXACT_CLASSES distinct sizes, and under BUCKET_FACTOR (1.25x)
        of exact otherwise (tests/test_compact_exchange.py asserts the
        bound on random skews)."""
        send, _ = self._send_recv_per_shard()
        return int(send.sum())

    def busiest_link_elements(self) -> int:
        """Max over shards of max(sent, received) off-shard complex
        elements per exchange — the bottleneck-link metric. On a skewed
        PLANE distribution the big plane-owner's ingress is real payload
        (a true Alltoallv ships the same bytes), so this metric does NOT
        shrink the way the aggregate does; capacity planning should read
        this one. Bucketed ops are counted at bucket size, as in
        :meth:`wire_elements` (same <= 1.25x-of-exact bound)."""
        send, recv = self._send_recv_per_shard()
        both = np.maximum(send, recv)
        return int(both.max()) if self.num_shards else 0


#: Bucket growth factor when a hop has more distinct payload sizes than
#: MAX_EXACT_CLASSES: a pair is charged at most this multiple of its
#: exact payload (asserted against random skews in
#: tests/test_compact_exchange.py). 1.25 replaces the round-3 factor-2
#: buckets — VERDICT r3 weak #5: the 32-rank claim rested on a 2x-worst
#: accounting.
BUCKET_FACTOR = 1.25
MAX_EXACT_CLASSES = 8


def _bucket_ladder(max_size: int) -> list:
    """Ascending bucket sizes 1, ..., <= max_size with ratio <=
    BUCKET_FACTOR between consecutive entries (each step also advances by
    >= 1 so the ladder terminates)."""
    ladder = [1]
    while ladder[-1] < max_size:
        ladder.append(min(max_size,
                          max(ladder[-1] + 1,
                              int(ladder[-1] * BUCKET_FACTOR))))
    return ladder


def _size_classes(sizes_by_src: dict, max_exact: int = MAX_EXACT_CLASSES
                  ) -> list:
    """Group a hop's pairs by exact payload size; if more than ``max_exact``
    distinct sizes, merge into BUCKET_FACTOR-spaced buckets clamped to the
    hop's max exact size — every pair is charged < BUCKET_FACTOR times its
    exact payload (and never more than the per-hop max, so the compact
    layout never exceeds the padded one; op count <= log_1.25 of the hop's
    size range). Returns [(L, [srcs])] sorted by L."""
    groups: dict = {}
    for j, e in sizes_by_src.items():
        groups.setdefault(int(e), []).append(j)
    if len(groups) > max_exact:
        ladder = _bucket_ladder(max(groups))
        buckets: dict = {}
        for e, js in groups.items():
            b = next(v for v in ladder if v >= e)
            buckets.setdefault(b, []).extend(js)
        groups = buckets
    return sorted((L, sorted(js)) for L, js in groups.items())


def build_compact_schedule(dp, x_window=None) -> CompactSchedule:
    """Build the exact-count exchange schedule from a
    ``DistributedIndexPlan`` (duck-typed to avoid a circular import).

    ``x_window=(x0, w)`` composes the schedule with the split-x grid: the
    unpack/pack grid tables then index the occupied-x window (width ``w``)
    instead of the full plane (see dist._init_split_x).
    """
    from ..indexing import window_sub_cols

    S = dp.num_shards
    ms, mp_ = dp.max_sticks, dp.max_planes
    dz, Y, Xf = dp.dim_z, dp.dim_y, dp.dim_x_freq
    Xe = Xf if x_window is None else x_window[1]

    def grid_cols(cols):
        if x_window is None:
            return np.asarray(cols, np.int64)
        return window_sub_cols(cols, Xf, *x_window).astype(np.int64)
    ns = [p.num_sticks for p in dp.shard_plans]
    npl = list(dp.num_planes)
    off = list(dp.plane_offsets)

    ops = []  # (k, L, pairs)
    for k in range(S):
        sizes = {j: ns[j] * npl[(j + k) % S] for j in range(S)
                 if ns[j] * npl[(j + k) % S] > 0}
        for L, js in _size_classes(sizes):
            ops.append((k, int(L), tuple((j, (j + k) % S) for j in js)))
    if not ops:  # degenerate: no sticks anywhere — keep one dummy slot
        ops = [(0, 1, ())]
    L = [o[1] for o in ops]
    offs = np.concatenate([[0], np.cumsum(L)]).astype(np.int64)
    total = int(offs[-1])
    # recv-buffer offset of each pair's op
    op_of_pair = {}
    for oi, (k, _, pairs) in enumerate(ops):
        for pr in pairs:
            op_of_pair[pr] = oi

    bwd_pack = []
    for oi, (k, Lo, pairs) in enumerate(ops):
        tbl = np.full((S, Lo), ms * dz, np.int32)  # sentinel: off-range
        for j, d in pairs:
            n = ns[j] * npl[d]
            i = np.arange(ns[j])[:, None]
            z = off[d] + np.arange(npl[d])[None, :]
            tbl[j, :n] = (i * dz + z).reshape(-1)
        bwd_pack.append(tbl)

    # backward unpack: grid flat index p*Y*Xe + col -> recv position
    bwd_unpack = np.full((S, mp_ * Y * Xe), total, np.int32)
    for r in range(S):
        if npl[r] == 0:
            continue
        for s in range(S):
            if ns[s] == 0:
                continue
            cols = grid_cols(dp.shard_plans[s].scatter_cols)
            i = np.arange(ns[s])[:, None]
            p = np.arange(npl[r])[None, :]
            pos = offs[op_of_pair[(s, r)]] + i * npl[r] + p
            flat_idx = p * (Y * Xe) + cols[:, None]
            bwd_unpack[r][flat_idx.reshape(-1)] = pos.reshape(-1)

    # forward pack: for backward pair (d, j) the forward sender is j,
    # receiver d, payload = (ns(d), np(j)) gathered from j's local grid
    fwd_pack = []
    for oi, (k, Lo, pairs) in enumerate(ops):
        tbl = np.full((S, Lo), mp_ * Y * Xe, np.int32)
        for d, j in pairs:  # backward (src=d, dst=j): forward j sends to d
            n = ns[d] * npl[j]
            cols = grid_cols(dp.shard_plans[d].scatter_cols)
            p = np.arange(npl[j])[None, :]
            tbl[j, :n] = (p * (Y * Xe) + cols[:, None]).reshape(-1)
        fwd_pack.append(tbl)

    # forward unpack: stick flat index i*dz + z -> recv position
    fwd_unpack = np.full((S, ms * dz), total, np.int32)
    z_owner = np.empty(dz, np.int64)
    z_plane = np.empty(dz, np.int64)
    for s in range(S):
        z_owner[off[s]:off[s] + npl[s]] = s
        z_plane[off[s]:off[s] + npl[s]] = np.arange(npl[s])
    for r in range(S):
        if ns[r] == 0:
            continue
        # stick-owner r receives from plane-owner o = z_owner[z]; that is
        # backward pair (r, o)
        base = np.asarray([offs[op_of_pair[(r, int(o))]] for o in z_owner],
                          np.int64) + z_plane
        npl_z = np.asarray(npl)[z_owner]      # (dz,)
        i = np.arange(ns[r])[:, None]
        idx = base[None, :] + i * npl_z[None, :]
        fwd_unpack[r, :ns[r] * dz] = idx.reshape(-1)

    return CompactSchedule(num_shards=S, ops=tuple(ops),
                           bwd_pack=tuple(bwd_pack),
                           bwd_unpack=bwd_unpack, fwd_pack=tuple(fwd_pack),
                           fwd_unpack=fwd_unpack)


@dataclasses.dataclass(frozen=True)
class RaggedSchedule:
    """Plan-time tables for the ONE-COLLECTIVE exact-count exchange — the
    true Alltoallv (reference MPI_Alltoallv,
    transpose_mpi_compact_buffered_host.cpp:183-200), built on
    ``jax.lax.ragged_all_to_all``: per-pair element counts ride offset
    vectors into one fixed-capacity buffer, so the launch count is 1 per
    direction at ANY shard count (the round-4 ppermute schedule paid up
    to 416 collectives at S=32 — its launch-scalability gap) and the
    wire carries EXACTLY the per-pair counts (no 1.25x bucket factor).

    Backward direction: stick-owner ``j`` sends ``ns(j) * np(d)``
    elements to plane-owner ``d``; forward reverses (counts transpose).
    Send buffers are laid out destination-major, receive buffers
    source-major, both with static capacity = the max total over shards
    (the ragged op needs one static shape; the capacity slack stays in
    HBM and off the wire — unlike the padded layout, which ships it).

    XLA:CPU has no ragged-all-to-all kernel, so off-TPU execution (the
    CPU test suite, the driver's virtual-device dryrun) EMULATES the
    collective with one ``all_gather`` + a plan-time gather table
    (``emu_*``) — identical numerics through the same pack/unpack
    tables, wire economics obviously not preserved. The real op lowers
    and is HLO-verified at S=8/16/32 (scripts/scaling_model.py); it
    cannot *execute* in this container (one TPU chip), which is exactly
    the class of gap the on-TPU CI lane documents.
    """

    num_shards: int
    send_cap: int                 # static send-buffer elements per shard
    recv_cap: int                 # static recv-buffer elements per shard
    # per-direction offset vectors, each (S, S) int32, row = this shard:
    bwd_offsets: tuple            # (input_offsets, send_sizes,
                                  #  output_offsets, recv_sizes)
    fwd_offsets: tuple
    bwd_pack: np.ndarray          # (S, send_cap) into flat local sticks
    bwd_unpack: np.ndarray        # (S, mp*Y*Xe) into the recv buffer
    fwd_pack: np.ndarray          # (S, send_cap) into the flat local grid
    fwd_unpack: np.ndarray        # (S, ms*dz) into the recv buffer
    emu_bwd: np.ndarray           # (S, recv_cap) into allgathered sends
    emu_fwd: np.ndarray           # (S, recv_cap)

    def _counts(self):
        """Backward per-pair element counts n[j, d] (forward is n.T)."""
        io, ss, oo, rs = self.bwd_offsets
        return ss

    def wire_elements(self) -> int:
        """TOTAL off-shard complex elements per exchange (exact — the
        ragged op ships per-pair counts with no padding or buckets)."""
        n = np.asarray(self._counts(), np.int64)
        return int(n.sum() - np.trace(n))

    def busiest_link_elements(self) -> int:
        """Max over shards of max(sent, received) off-shard elements."""
        n = np.asarray(self._counts(), np.int64).copy()
        np.fill_diagonal(n, 0)
        send = n.sum(axis=1)
        recv = n.sum(axis=0)
        both = np.maximum(send, recv)
        return int(both.max()) if self.num_shards else 0

    def device_tables(self) -> list:
        """The (S, ...) tables the SPMD bodies consume, in a fixed order
        (see dist.TransformPlan's ctables plumbing)."""
        io_b, ss_b, oo_b, rs_b = self.bwd_offsets
        io_f, ss_f, oo_f, rs_f = self.fwd_offsets
        return [self.bwd_pack, self.bwd_unpack, self.fwd_pack,
                self.fwd_unpack, io_b, ss_b, oo_b, rs_b,
                io_f, ss_f, oo_f, rs_f, self.emu_bwd, self.emu_fwd]


def _ragged_direction_tables(S: int, counts: np.ndarray):
    """Offset vectors + emulation table layout for one direction.
    ``counts[j, d]`` = elements shard j sends shard d. Returns
    ((input_offsets, send_sizes, output_offsets, recv_sizes), send_cap,
    recv_cap, recv_offsets)."""
    counts = np.asarray(counts, np.int64)
    input_offsets = np.concatenate(
        [np.zeros((S, 1), np.int64), np.cumsum(counts, axis=1)[:, :-1]],
        axis=1)
    recv_counts = counts.T                      # row d: from each j
    recv_offsets = np.concatenate(
        [np.zeros((S, 1), np.int64), np.cumsum(recv_counts, axis=1)[:, :-1]],
        axis=1)
    # sender j's chunk lands at receiver d's recv_offsets[d, j]
    output_offsets = recv_offsets.T
    send_cap = int(counts.sum(axis=1).max()) if S else 1
    recv_cap = int(recv_counts.sum(axis=1).max()) if S else 1
    offs = tuple(a.astype(np.int32) for a in
                 (input_offsets, counts, output_offsets, recv_counts))
    return offs, max(send_cap, 1), max(recv_cap, 1), recv_offsets


def build_ragged_schedule(dp, x_window=None) -> RaggedSchedule:
    """Build the one-collective exact-count schedule from a
    ``DistributedIndexPlan`` (same duck-typed contract and x-window
    composition as :func:`build_compact_schedule`)."""
    from ..indexing import window_sub_cols

    S = dp.num_shards
    ms, mp_ = dp.max_sticks, dp.max_planes
    dz, Y, Xf = dp.dim_z, dp.dim_y, dp.dim_x_freq
    Xe = Xf if x_window is None else x_window[1]

    def grid_cols(cols):
        if x_window is None:
            return np.asarray(cols, np.int64)
        return window_sub_cols(cols, Xf, *x_window).astype(np.int64)

    ns = [p.num_sticks for p in dp.shard_plans]
    npl = list(dp.num_planes)
    off = list(dp.plane_offsets)
    n_bwd = np.asarray([[ns[j] * npl[d] for d in range(S)]
                        for j in range(S)], np.int64)
    bwd_offs, s_cap_b, r_cap_b, roff_b = _ragged_direction_tables(S, n_bwd)
    fwd_offs, s_cap_f, r_cap_f, roff_f = _ragged_direction_tables(S, n_bwd.T)
    send_cap = max(s_cap_b, s_cap_f)
    recv_cap = max(r_cap_b, r_cap_f)
    io_b = bwd_offs[0].astype(np.int64)
    io_f = fwd_offs[0].astype(np.int64)

    bwd_pack = np.full((S, send_cap), ms * dz, np.int32)
    emu_bwd = np.full((S, recv_cap), S * send_cap, np.int32)
    fwd_pack = np.full((S, send_cap), mp_ * Y * Xe, np.int32)
    emu_fwd = np.full((S, recv_cap), S * send_cap, np.int32)
    bwd_unpack = np.full((S, mp_ * Y * Xe), recv_cap, np.int32)
    fwd_unpack = np.full((S, ms * dz), recv_cap, np.int32)

    for j in range(S):
        for d in range(S):
            n = ns[j] * npl[d]
            if n:
                # backward send j -> d: stick-major block (ns[j], npl[d])
                i = np.arange(ns[j])[:, None]
                z = off[d] + np.arange(npl[d])[None, :]
                bwd_pack[j, io_b[j, d]:io_b[j, d] + n] = \
                    (i * dz + z).reshape(-1)
                emu_bwd[d, roff_b[d, j]:roff_b[d, j] + n] = \
                    j * send_cap + io_b[j, d] + np.arange(n)
            m = ns[d] * npl[j]
            if m:
                # forward send j -> d: d's sticks restricted to j's planes
                cols = grid_cols(dp.shard_plans[d].scatter_cols)
                p = np.arange(npl[j])[None, :]
                fwd_pack[j, io_f[j, d]:io_f[j, d] + m] = \
                    (p * (Y * Xe) + cols[:, None]).reshape(-1)
                emu_fwd[d, roff_f[d, j]:roff_f[d, j] + m] = \
                    j * send_cap + io_f[j, d] + np.arange(m)

    for d in range(S):
        if npl[d]:
            for j in range(S):
                if ns[j]:
                    cols = grid_cols(dp.shard_plans[j].scatter_cols)
                    i = np.arange(ns[j])[:, None]
                    p = np.arange(npl[d])[None, :]
                    pos = roff_b[d, j] + i * npl[d] + p
                    flat_idx = p * (Y * Xe) + cols[:, None]
                    bwd_unpack[d][flat_idx.reshape(-1)] = pos.reshape(-1)
        if ns[d]:
            for j in range(S):
                if npl[j]:
                    i = np.arange(ns[d])[:, None]
                    p = np.arange(npl[j])[None, :]
                    pos = roff_f[d, j] + i * npl[j] + p
                    flat_idx = i * dz + (off[j] + p)
                    fwd_unpack[d][flat_idx.reshape(-1)] = pos.reshape(-1)

    return RaggedSchedule(
        num_shards=S, send_cap=send_cap, recv_cap=recv_cap,
        bwd_offsets=bwd_offs, fwd_offsets=fwd_offs, bwd_pack=bwd_pack,
        bwd_unpack=bwd_unpack, fwd_pack=fwd_pack, fwd_unpack=fwd_unpack,
        emu_bwd=emu_bwd, emu_fwd=emu_fwd)


def ragged_exchange(buf, offsets, emu_table, recv_cap: int,
                    axis_name: str, emulate: bool,
                    wire_real_dtype: Optional[jnp.dtype] = None):
    """Run one direction of the exact-count exchange.

    Args:
      buf: (send_cap,) complex — or (B, send_cap) batched — the packed
        send buffer (destination-major layout of the schedule).
      offsets: per-shard (input_offsets, send_sizes, output_offsets,
        recv_sizes), each (S,) int32 (this shard's row).
      emu_table: (recv_cap,) int32 into the allgathered flat sends —
        the CPU-emulation gather (sentinel = S * send_cap).
      emulate: True off-TPU (no XLA:CPU ragged-all-to-all kernel).
    Returns:
      (recv_cap,) complex — or (B, recv_cap).

    The collective runs on interleaved reals with the batch as a
    TRAILING dimension: ``ragged_all_to_all`` sizes address dim 0 and
    the op has no vmap batching rule, so the batched fused path moves
    B inside instead of vmapping (dist._backward_body_batched).
    """
    batched = buf.ndim == 2
    rdt = buf.real.dtype
    il = jnp.stack([jnp.real(buf), jnp.imag(buf)], axis=-1)
    if wire_real_dtype is not None:
        il = il.astype(wire_real_dtype)
    if emulate:
        gathered = jax.lax.all_gather(il, axis_name)  # (S, [B,] cap, 2)
        flat = jnp.moveaxis(gathered, 1, 0).reshape(
            (il.shape[0],) + (-1, 2)) if batched \
            else gathered.reshape(-1, 2)
        recv = jnp.take(flat, emu_table, axis=-2, mode="fill",
                        fill_value=0)
    else:
        io, ss, oo, rs = offsets
        op = jnp.moveaxis(il, 0, -2) if batched else il  # (cap, [B,] 2)
        out = jnp.zeros((recv_cap,) + op.shape[1:], op.dtype)
        recv = jax.lax.ragged_all_to_all(op, out, io, ss, oo, rs,
                                         axis_name=axis_name)
        if batched:
            recv = jnp.moveaxis(recv, -2, 0)  # (B, recv_cap, 2)
    recv = recv.astype(rdt)
    return recv[..., 0] + 1j * recv[..., 1]


def compact_exchange(bufs, ops, num_shards: int, axis_name: str,
                     reverse: bool,
                     wire_real_dtype: Optional[jnp.dtype] = None):
    """Run the exact-size op schedule: each op is one ``ppermute`` of a
    ``(L,)`` complex buffer along ONLY its member pairs (backward:
    ``j -> d`` as stored; forward ``reverse=True``: ``d -> j``). Pairs
    absent from an op's permutation transfer nothing (their shards receive
    zeros, which the sentinel unpack tables never read). Hop-0 ops are the
    shard's own block and never cross the wire. Returns the op buffers
    concatenated in schedule order — the layout the unpack tables of
    :class:`CompactSchedule` index into.
    """
    out = []
    for b, (k, _, pairs) in zip(bufs, ops):
        if k == 0 or not pairs:
            out.append(b)
            continue
        perm = [((d, j) if reverse else (j, d)) for j, d in pairs]
        if wire_real_dtype is not None:
            rdt = b.real.dtype
            il = complex_to_interleaved(b).astype(wire_real_dtype)
            il = jax.lax.ppermute(il, axis_name, perm)
            b = interleaved_to_complex(il.astype(rdt))
        else:
            b = jax.lax.ppermute(b, axis_name, perm)
        out.append(b)
    return jnp.concatenate(out) if len(out) > 1 else out[0]


def all_to_all_blocks(blocks, axis_name: str,
                      wire_real_dtype: Optional[jnp.dtype] = None,
                      quant_axis: int = 1):
    """Exchange blocks between shards; block (r -> s) lands at (s, slot r).

    One XLA all-to-all over the mesh axis — the whole distributed backbone
    (reference: MPI_(I)Alltoall(v/w), SURVEY.md §5.8). ``wire_real_dtype``
    enables the reduced-precision wire mode: the complex block is viewed as
    interleaved reals, cast down for the collective, and cast back after
    (reference float-exchange conversion in pack/unpack,
    transpose_mpi_compact_buffered_host.cpp:60-63). The int8 rung instead
    quantizes each slot row with per-``quant_axis``-row absmax scales
    packed alongside the payload (:func:`quantize_blocks_int8`) — still a
    single collective round.
    """
    if wire_real_dtype is None:
        return jax.lax.all_to_all(blocks, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    if is_int8_wire(wire_real_dtype):
        rdt = blocks.real.dtype
        packed = quantize_blocks_int8(blocks, quant_axis)
        out = jax.lax.all_to_all(packed, axis_name, split_axis=0,
                                 concat_axis=0, tiled=True)
        return dequantize_blocks_int8(out, blocks.shape, quant_axis, rdt)
    rdt = blocks.real.dtype
    il = complex_to_interleaved(blocks).astype(wire_real_dtype)
    il = jnp.asarray(jax.lax.all_to_all(
        il, axis_name, split_axis=0, concat_axis=0, tiled=True))
    return interleaved_to_complex(il.astype(rdt))

"""The slab<->pencil exchange: pack, all-to-all, unpack.

TPU-native rebuild of the reference transpose/exchange engine
(reference: src/transpose/ — eight MPI/local variants, SURVEY.md §2.5). On a
TPU mesh all variants collapse to one ``lax.all_to_all`` on a padded
``(num_shards, max_sticks, max_planes)`` complex block — the analogue of the
reference's BUFFERED MPI_Alltoall layout (transpose_mpi_buffered_host.cpp),
which is the natural fit for XLA's fixed-shape collectives. Data stays in HBM
end-to-end, i.e. the reference's GPUDirect mode (SPFFT_GPU_DIRECT,
transpose_mpi_buffered_gpu.cpp:171-199) is implicit and always on.

Pack/unpack are gathers/scatters with plan-time index tables and sentinel
padding:

* pack (freq side): restrict each local stick to the z-planes owned by each
  target shard (reference pack_backward,
  transpose_mpi_compact_buffered_host.cpp:109-125);
* unpack (space side): scatter every source shard's sticks into the local
  plane grid by xy index (reference unpack_backward, :128-175).

The reference's reduced-precision wire option (``*_FLOAT`` exchange types,
docs/source/details.rst "MPI Exchange") maps to casting the interleaved block
to the next lower real dtype around the collective: f64 -> f32 on the wire for
double transforms, f32 -> bf16 for single.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops import stages
from ..utils.dtypes import complex_to_interleaved, interleaved_to_complex


def pack_freq_to_blocks(sticks, z_map):
    """Split z-FFT'ed local sticks into per-target-shard plane blocks.

    Args:
      sticks: (max_sticks, dim_z) complex — full-z local sticks.
      z_map: (num_shards, max_planes) int32 — global z index of each target
        shard's p-th plane, sentinel ``dim_z`` for padding rows.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    blocks = jnp.take(sticks, z_map, axis=1, mode="fill", fill_value=0)
    return jnp.transpose(blocks, (1, 0, 2))


def unpack_blocks_to_grid(blocks, global_col_inv, dim_y: int,
                          dim_x_freq: int):
    """Place received stick segments into the local frequency plane grid —
    as a row *gather* through the plan-time inverse column map (runtime
    scatters lower near-serially on TPU; see indexing.inverse_col_map).

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex — blocks[s] holds
        shard s's sticks restricted to this shard's planes.
      global_col_inv: (dim_y * dim_x_freq,) int32 — plane column -> global
        padded stick index ``shard * max_sticks + i``, sentinel
        ``num_shards * max_sticks`` for empty columns.
    Returns:
      (max_planes, dim_y, dim_x_freq) complex.
    """
    num_shards, max_sticks, max_planes = blocks.shape
    rows = blocks.reshape(num_shards * max_sticks, max_planes)
    grid_t = stages.gather_rows_with_sentinel(rows, global_col_inv)
    return grid_t.T.reshape(max_planes, dim_y, dim_x_freq)


def pack_space_to_blocks(grid, all_scatter_cols, num_shards: int,
                         max_sticks: int):
    """Forward-direction pack: gather every shard's stick columns out of the
    local plane grid (reference pack_forward,
    transpose_mpi_compact_buffered_host.cpp:203-242).

    Args:
      grid: (max_planes, dim_y, dim_x_freq) complex.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    max_planes = grid.shape[0]
    flat = grid.reshape(max_planes, -1)
    cols = jnp.take(flat, all_scatter_cols, axis=1, mode="fill",
                    fill_value=0)  # (max_planes, S * max_sticks)
    blocks = cols.reshape(max_planes, num_shards, max_sticks)
    return jnp.transpose(blocks, (1, 2, 0))


def unpack_blocks_to_sticks(blocks, z_src):
    """Forward-direction unpack: reassemble full-z local sticks from received
    per-source-shard plane blocks (reference unpack_forward,
    transpose_mpi_compact_buffered_host.cpp:245-266) — as a column gather
    through the total map ``z_src`` (every z plane has exactly one owner).

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex — blocks[s] holds
        this shard's sticks restricted to shard s's planes.
      z_src: (dim_z,) int32 — global z -> ``owner_shard * max_planes + p``.
    Returns:
      (max_sticks, dim_z) complex.
    """
    num_shards, max_sticks, max_planes = blocks.shape
    flat = jnp.transpose(blocks, (1, 0, 2)).reshape(max_sticks,
                                                    num_shards * max_planes)
    return flat[:, z_src]


def ring_exchange_blocks(blocks, axis_name: str,
                         wire_real_dtype: Optional[jnp.dtype] = None):
    """All-to-all block exchange as S-1 ``ppermute`` ring steps.

    Mechanically distinct alternative to the single fused ``all_to_all``
    (the reference likewise ships three mechanically different exchange
    algorithms, Alltoall/Alltoallv/Alltoallw — SURVEY.md §2.5): each step k
    sends exactly one peer block to the shard k hops away, so XLA can
    software-pipeline the steps with surrounding compute, and each transfer
    rides a single ICI hop on a ring topology. Semantically identical to
    :func:`all_to_all_blocks`; selected via ``ExchangeType.UNBUFFERED``
    (the reference variant that also trades fewer big copies for more
    transfer operations).
    """
    num_shards = blocks.shape[0]
    if num_shards == 1:
        return blocks
    if wire_real_dtype is not None:
        rdt = blocks.real.dtype
        il = complex_to_interleaved(blocks).astype(wire_real_dtype)
        out = ring_exchange_blocks(il, axis_name, None)
        return interleaved_to_complex(out.astype(rdt))
    idx = jax.lax.axis_index(axis_name)
    # received[k] = source shard (r - k)'s block addressed to r
    received = [blocks[idx]]
    for k in range(1, num_shards):
        perm = [(j, (j + k) % num_shards) for j in range(num_shards)]
        send = blocks[(idx + k) % num_shards]
        received.append(jax.lax.ppermute(send, axis_name, perm))
    stacked = jnp.stack(received, axis=0)
    # out[s] must be shard s's block = received[(r - s) % S]; as a function
    # of s that is a reversal followed by a roll of r + 1.
    return jnp.roll(stacked[::-1], idx + 1, axis=0)


def all_to_all_blocks(blocks, axis_name: str,
                      wire_real_dtype: Optional[jnp.dtype] = None):
    """Exchange blocks between shards; block (r -> s) lands at (s, slot r).

    One XLA all-to-all over the mesh axis — the whole distributed backbone
    (reference: MPI_(I)Alltoall(v/w), SURVEY.md §5.8). ``wire_real_dtype``
    enables the reduced-precision wire mode: the complex block is viewed as
    interleaved reals, cast down for the collective, and cast back after
    (reference float-exchange conversion in pack/unpack,
    transpose_mpi_compact_buffered_host.cpp:60-63).
    """
    if wire_real_dtype is None:
        return jax.lax.all_to_all(blocks, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    rdt = blocks.real.dtype
    il = complex_to_interleaved(blocks).astype(wire_real_dtype)
    il = jax.lax.all_to_all(il, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    return interleaved_to_complex(il.astype(rdt))

"""The slab<->pencil exchange: pack, all-to-all, unpack.

TPU-native rebuild of the reference transpose/exchange engine
(reference: src/transpose/ — eight MPI/local variants, SURVEY.md §2.5). On a
TPU mesh all variants collapse to one ``lax.all_to_all`` on a padded
``(num_shards, max_sticks, max_planes)`` complex block — the analogue of the
reference's BUFFERED MPI_Alltoall layout (transpose_mpi_buffered_host.cpp),
which is the natural fit for XLA's fixed-shape collectives. Data stays in HBM
end-to-end, i.e. the reference's GPUDirect mode (SPFFT_GPU_DIRECT,
transpose_mpi_buffered_gpu.cpp:171-199) is implicit and always on.

Pack/unpack are gathers/scatters with plan-time index tables and sentinel
padding:

* pack (freq side): restrict each local stick to the z-planes owned by each
  target shard (reference pack_backward,
  transpose_mpi_compact_buffered_host.cpp:109-125);
* unpack (space side): scatter every source shard's sticks into the local
  plane grid by xy index (reference unpack_backward, :128-175).

The reference's reduced-precision wire option (``*_FLOAT`` exchange types,
docs/source/details.rst "MPI Exchange") maps to casting the interleaved block
to the next lower real dtype around the collective: f64 -> f32 on the wire for
double transforms, f32 -> bf16 for single.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import stages
from ..utils.dtypes import complex_to_interleaved, interleaved_to_complex


def pack_freq_to_blocks(sticks, z_map):
    """Split z-FFT'ed local sticks into per-target-shard plane blocks.

    Args:
      sticks: (max_sticks, dim_z) complex — full-z local sticks.
      z_map: (num_shards, max_planes) int32 — global z index of each target
        shard's p-th plane, sentinel ``dim_z`` for padding rows.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    blocks = jnp.take(sticks, z_map, axis=1, mode="fill", fill_value=0)
    return jnp.transpose(blocks, (1, 0, 2))


def unpack_blocks_to_grid(blocks, global_col_inv, dim_y: int,
                          dim_x_freq: int):
    """Place received stick segments into the local frequency plane grid —
    as a row *gather* through the plan-time inverse column map (runtime
    scatters lower near-serially on TPU; see indexing.inverse_col_map).

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex — blocks[s] holds
        shard s's sticks restricted to this shard's planes.
      global_col_inv: (dim_y * dim_x_freq,) int32 — plane column -> global
        padded stick index ``shard * max_sticks + i``, sentinel
        ``num_shards * max_sticks`` for empty columns.
    Returns:
      (max_planes, dim_y, dim_x_freq) complex.
    """
    num_shards, max_sticks, max_planes = blocks.shape
    rows = blocks.reshape(num_shards * max_sticks, max_planes)
    grid_t = stages.gather_rows_with_sentinel(rows, global_col_inv)
    return grid_t.T.reshape(max_planes, dim_y, dim_x_freq)


def pack_space_to_blocks(grid, all_scatter_cols, num_shards: int,
                         max_sticks: int):
    """Forward-direction pack: gather every shard's stick columns out of the
    local plane grid (reference pack_forward,
    transpose_mpi_compact_buffered_host.cpp:203-242).

    Args:
      grid: (max_planes, dim_y, dim_x_freq) complex.
    Returns:
      (num_shards, max_sticks, max_planes) complex.
    """
    max_planes = grid.shape[0]
    flat = grid.reshape(max_planes, -1)
    cols = jnp.take(flat, all_scatter_cols, axis=1, mode="fill",
                    fill_value=0)  # (max_planes, S * max_sticks)
    blocks = cols.reshape(max_planes, num_shards, max_sticks)
    return jnp.transpose(blocks, (1, 2, 0))


def unpack_blocks_to_sticks(blocks, z_src):
    """Forward-direction unpack: reassemble full-z local sticks from received
    per-source-shard plane blocks (reference unpack_forward,
    transpose_mpi_compact_buffered_host.cpp:245-266) — as a column gather
    through the total map ``z_src`` (every z plane has exactly one owner).

    Args:
      blocks: (num_shards, max_sticks, max_planes) complex — blocks[s] holds
        this shard's sticks restricted to shard s's planes.
      z_src: (dim_z,) int32 — global z -> ``owner_shard * max_planes + p``.
    Returns:
      (max_sticks, dim_z) complex.
    """
    num_shards, max_sticks, max_planes = blocks.shape
    flat = jnp.transpose(blocks, (1, 0, 2)).reshape(max_sticks,
                                                    num_shards * max_planes)
    return flat[:, z_src]


def ring_exchange_blocks(blocks, axis_name: str,
                         wire_real_dtype: Optional[jnp.dtype] = None):
    """All-to-all block exchange as S-1 ``ppermute`` ring steps.

    Mechanically distinct alternative to the single fused ``all_to_all``
    (the reference likewise ships three mechanically different exchange
    algorithms, Alltoall/Alltoallv/Alltoallw — SURVEY.md §2.5): each step k
    sends exactly one peer block to the shard k hops away, so XLA can
    software-pipeline the steps with surrounding compute, and each transfer
    rides a single ICI hop on a ring topology. Semantically identical to
    :func:`all_to_all_blocks`; selected via ``ExchangeType.UNBUFFERED``
    (the reference variant that also trades fewer big copies for more
    transfer operations).
    """
    num_shards = blocks.shape[0]
    if num_shards == 1:
        return blocks
    if wire_real_dtype is not None:
        rdt = blocks.real.dtype
        il = complex_to_interleaved(blocks).astype(wire_real_dtype)
        out = ring_exchange_blocks(il, axis_name, None)
        return interleaved_to_complex(out.astype(rdt))
    idx = jax.lax.axis_index(axis_name)
    # received[k] = source shard (r - k)'s block addressed to r
    received = [blocks[idx]]
    for k in range(1, num_shards):
        perm = [(j, (j + k) % num_shards) for j in range(num_shards)]
        send = blocks[(idx + k) % num_shards]
        received.append(jax.lax.ppermute(send, axis_name, perm))
    stacked = jnp.stack(received, axis=0)
    # out[s] must be shard s's block = received[(r - s) % S]; as a function
    # of s that is a reversal followed by a roll of r + 1.
    return jnp.roll(stacked[::-1], idx + 1, axis=0)


@dataclasses.dataclass(frozen=True)
class CompactSchedule:
    """Plan-time schedule for the exact-count (ragged) exchange — the
    Alltoallv analogue (reference:
    src/transpose/transpose_mpi_compact_buffered_host.cpp:83-105 computes
    per-rank counts/displacements at plan time; :183-200 runs the
    MPI_Alltoallv).

    XLA collectives are fixed-shape, so "ragged" becomes a *per-hop* static
    schedule: hop ``k`` moves the (stick-owner ``j`` -> plane-owner
    ``(j+k) % S``) blocks, whose exact element count
    ``ns(j) * np((j+k) % S)`` is a plan-time constant; the hop buffer is
    sized to the max over ``j`` only. Total off-shard wire elements are
    ``sum_k L_k`` instead of the padded layout's
    ``(S-1) * max_sticks * max_planes`` — on non-uniform distributions the
    difference is the padding waste SURVEY.md §7.3 flags as the scaling
    risk. The same hop widths serve both directions (the same
    (stick-owner, plane-owner) pairs flow, reversed).

    Pack/unpack are element gathers through plan-time index tables with
    out-of-range sentinels (``jnp.take`` fill mode), sharded over the mesh
    axis. Layout of hop ``k``'s flat buffer, sent by shard ``j`` to
    ``d = (j+k) % S`` (backward; forward reverses the direction): element
    ``i * np(d) + p`` is stick ``i``, plane ``p`` of shard ``d``'s slab.
    """

    num_shards: int
    hops: tuple                      # kept hop distances k (zero-count hops
                                     # are dropped at plan time; no dummy
                                     # collectives on skewed distributions)
    hop_sizes: tuple                 # L_k per kept hop
    bwd_pack: tuple                  # per-hop (S, L_k) into flat sticks
    bwd_unpack: np.ndarray           # (S, mp*Y*Xf) into concat recv buffer
    fwd_pack: tuple                  # per-hop (S, L_k) into flat grid
    fwd_unpack: np.ndarray           # (S, ms*dz) into concat recv buffer

    @property
    def total_recv(self) -> int:
        return int(sum(self.hop_sizes))

    def wire_elements(self) -> int:
        """Off-shard complex elements per shard per exchange (hop 0 is
        local)."""
        return int(sum(sz for k, sz in zip(self.hops, self.hop_sizes)
                       if k != 0))


def build_compact_schedule(dp, x_window=None) -> CompactSchedule:
    """Build the exact-count exchange schedule from a
    ``DistributedIndexPlan`` (duck-typed to avoid a circular import).

    ``x_window=(x0, w)`` composes the schedule with the split-x grid: the
    unpack/pack grid tables then index the occupied-x window (width ``w``)
    instead of the full plane (see dist._init_split_x).
    """
    from ..indexing import window_sub_cols

    S = dp.num_shards
    ms, mp_ = dp.max_sticks, dp.max_planes
    dz, Y, Xf = dp.dim_z, dp.dim_y, dp.dim_x_freq
    Xe = Xf if x_window is None else x_window[1]

    def grid_cols(cols):
        if x_window is None:
            return np.asarray(cols, np.int64)
        return window_sub_cols(cols, Xf, *x_window).astype(np.int64)
    ns = [p.num_sticks for p in dp.shard_plans]
    npl = list(dp.num_planes)
    off = list(dp.plane_offsets)
    L_raw = [max(ns[j] * npl[(j + k) % S] for j in range(S))
             for k in range(S)]
    hops = [k for k in range(S) if L_raw[k] > 0]
    if not hops:  # degenerate: no sticks anywhere — keep one dummy slot
        hops, L_raw = [0], [1] + L_raw[1:]
    L = [L_raw[k] for k in hops]
    offs = np.concatenate([[0], np.cumsum(L)]).astype(np.int64)
    total = int(offs[-1])
    # recv-buffer offset of each hop distance (only kept hops referenced)
    offs_by_k = np.zeros(S, np.int64)
    offs_by_k[hops] = offs[:-1]

    bwd_pack = []
    for m, k in enumerate(hops):
        tbl = np.full((S, L[m]), ms * dz, np.int32)  # sentinel: off-range
        for j in range(S):
            d = (j + k) % S
            n = ns[j] * npl[d]
            if n:
                i = np.arange(ns[j])[:, None]
                z = off[d] + np.arange(npl[d])[None, :]
                tbl[j, :n] = (i * dz + z).reshape(-1)
        bwd_pack.append(tbl)

    # backward unpack: grid flat index p*Y*Xe + col -> recv position
    bwd_unpack = np.full((S, mp_ * Y * Xe), total, np.int32)
    for r in range(S):
        if npl[r] == 0:
            continue
        for s in range(S):
            if ns[s] == 0:
                continue
            k = (r - s) % S
            cols = grid_cols(dp.shard_plans[s].scatter_cols)
            i = np.arange(ns[s])[:, None]
            p = np.arange(npl[r])[None, :]
            pos = offs_by_k[k] + i * npl[r] + p
            flat_idx = p * (Y * Xe) + cols[:, None]
            bwd_unpack[r][flat_idx.reshape(-1)] = pos.reshape(-1)

    # forward pack: shard j sends to d = (j-k) % S the block
    # (ns(d), np(j)) gathered from its local grid
    fwd_pack = []
    for m, k in enumerate(hops):
        tbl = np.full((S, L[m]), mp_ * Y * Xe, np.int32)
        for j in range(S):
            d = (j - k) % S
            n = ns[d] * npl[j]
            if n:
                cols = grid_cols(dp.shard_plans[d].scatter_cols)
                p = np.arange(npl[j])[None, :]
                tbl[j, :n] = (p * (Y * Xe) + cols[:, None]).reshape(-1)
        fwd_pack.append(tbl)

    # forward unpack: stick flat index i*dz + z -> recv position
    fwd_unpack = np.full((S, ms * dz), total, np.int32)
    z_owner = np.empty(dz, np.int64)
    z_plane = np.empty(dz, np.int64)
    for s in range(S):
        z_owner[off[s]:off[s] + npl[s]] = s
        z_plane[off[s]:off[s] + npl[s]] = np.arange(npl[s])
    for r in range(S):
        if ns[r] == 0:
            continue
        k_z = (z_owner - r) % S
        base = offs_by_k[k_z] + z_plane       # (dz,)
        npl_z = np.asarray(npl)[z_owner]      # (dz,)
        i = np.arange(ns[r])[:, None]
        idx = base[None, :] + i * npl_z[None, :]
        fwd_unpack[r, :ns[r] * dz] = idx.reshape(-1)

    return CompactSchedule(num_shards=S, hops=tuple(hops),
                           hop_sizes=tuple(L), bwd_pack=tuple(bwd_pack),
                           bwd_unpack=bwd_unpack, fwd_pack=tuple(fwd_pack),
                           fwd_unpack=fwd_unpack)


def compact_exchange(bufs, hops, num_shards: int, axis_name: str,
                     reverse: bool,
                     wire_real_dtype: Optional[jnp.dtype] = None):
    """Run the per-hop exact-size exchange: each kept hop distance ``k`` is
    one ``ppermute`` of a ``(L_k,)`` complex buffer to the shard ``k`` hops
    away (backward: ``j -> (j+k) % S``; forward ``reverse=True``:
    ``j -> (j-k) % S``). Hop 0 is the shard's own block and never crosses
    the wire. Returns the hop buffers concatenated in schedule order — the
    layout the unpack tables of :class:`CompactSchedule` index into.
    """
    S = num_shards
    out = []
    for b, k in zip(bufs, hops):
        if k == 0:
            out.append(b)
            continue
        perm = [(j, (j - k) % S if reverse else (j + k) % S)
                for j in range(S)]
        if wire_real_dtype is not None:
            rdt = b.real.dtype
            il = complex_to_interleaved(b).astype(wire_real_dtype)
            il = jax.lax.ppermute(il, axis_name, perm)
            b = interleaved_to_complex(il.astype(rdt))
        else:
            b = jax.lax.ppermute(b, axis_name, perm)
        out.append(b)
    return jnp.concatenate(out) if len(out) > 1 else out[0]


def all_to_all_blocks(blocks, axis_name: str,
                      wire_real_dtype: Optional[jnp.dtype] = None):
    """Exchange blocks between shards; block (r -> s) lands at (s, slot r).

    One XLA all-to-all over the mesh axis — the whole distributed backbone
    (reference: MPI_(I)Alltoall(v/w), SURVEY.md §5.8). ``wire_real_dtype``
    enables the reduced-precision wire mode: the complex block is viewed as
    interleaved reals, cast down for the collective, and cast back after
    (reference float-exchange conversion in pack/unpack,
    transpose_mpi_compact_buffered_host.cpp:60-63).
    """
    if wire_real_dtype is None:
        return jax.lax.all_to_all(blocks, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    rdt = blocks.real.dtype
    il = complex_to_interleaved(blocks).astype(wire_real_dtype)
    il = jax.lax.all_to_all(il, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    return interleaved_to_complex(il.astype(rdt))

"""Jit-traceable computation stages of the sparse 3D FFT pipeline.

Each function here is one phase of the reference execution pipeline
(reference: src/execution/execution_host.cpp:249-352), re-expressed as a pure
JAX function over complex arrays:

* decompress / compress  — sparse value scatter/gather
  (reference: src/compression/compression_host.hpp:50-93)
* z_backward / z_forward — batched 1D FFT along z over sticks
  (reference: src/fft/transform_1d_host.hpp, transform_1d_gpu.hpp)
* sticks_to_grid / grid_to_sticks — the local stick<->plane transpose
  (reference: src/transpose/transpose_host.hpp:94-154)
* xy_* — batched 1D/2D FFTs over planes
* complete_stick_hermitian / complete_plane_hermitian — R2C fixups
  (reference: src/symmetry/symmetry_host.hpp:38-95)

Transform convention (docs/source/details.rst "Transform Definition"): the
backward transform is the *unnormalised* inverse DFT (sum with e^{+2πikn/N}),
i.e. ``ifft * N``; the forward transform is the plain DFT with optional
1/(Nx·Ny·Nz) scaling applied at compression time.

Everything here is meant to run *inside* ``jax.jit``: complex dtypes are not
reliably materialisable on the TPU host boundary, so plan objects convert
to/from interleaved real arrays at the edges (see plan.py) and XLA fuses these
stages into a handful of kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import dft


def _cdft_mid(x, mats):
    """Complex matmul-DFT along axis -2: swap to minor, contract, swap
    back. The pair of transposes replaces XLA fft2's internal layout
    copies (same traffic class, fewer total passes — probe_r4_hlo)."""
    y = dft.cdft_last(jnp.swapaxes(x, -1, -2), mats)
    return jnp.swapaxes(y, -1, -2)


#: xy-stage gate — the shared routing predicate lives in ops.dft so the
#: plan pipeline and these per-stage gates cannot drift
_mdft_axes = dft.mdft_axes


# ---------------------------------------------------------------------------
# Compression: sparse values <-> packed z-stick array
# ---------------------------------------------------------------------------

def gather_rows_with_sentinel(rows, idx):
    """Gather ``rows[idx]`` where index ``rows.shape[0]`` (the sentinel of
    the plan-time inverse maps) selects a zero row. The shared idiom of every
    gather-based placement stage: append one zero row, then gather."""
    zero = jnp.zeros((1,) + rows.shape[1:], rows.dtype)
    return jnp.concatenate([rows, zero], axis=0)[idx]


def decompress(values_il, slot_src, num_sticks: int, dim_z: int):
    """Fill the packed stick array from sparse values — as a *gather*.

    Same semantics as the reference decompress scatter
    (compression_host.hpp:76-93: zero sticks, place each value at its flat
    ``stick_id * dim_z + z`` slot), but expressed through the plan-time
    inverse map ``slot_src`` (indexing.inverse_slot_map): XLA lowers
    arbitrary-index scatters on TPU to near-serial updates, an order of
    magnitude slower than this gather. Duplicate triplets resolve to the
    last occurrence (unspecified order in the reference).

    Args:
      values_il: (num_values, 2) real interleaved sparse values.
      slot_src: (num_sticks * dim_z,) int32; sentinel num_values -> zero.
    Returns:
      (num_sticks, dim_z) complex stick array.
    """
    flat = gather_rows_with_sentinel(values_il, slot_src)
    return (flat[:, 0] + 1j * flat[:, 1]).reshape(num_sticks, dim_z)


def compress(sticks, value_indices, scale=None):
    """Gather sparse values out of the packed stick array, optionally scaled
    (reference: compression_host.hpp:50-72). Gathers interleaved real rows —
    element gathers of complex dtype lower poorly on TPU.

    Returns (num_values, 2) real interleaved values.
    """
    flat = jnp.stack([jnp.real(sticks).reshape(-1),
                      jnp.imag(sticks).reshape(-1)], axis=-1)
    values = flat[value_indices]
    if scale is not None:
        values = values * jnp.asarray(scale, values.dtype)
    return values


# ---------------------------------------------------------------------------
# z-stage: batched 1D FFT over sticks
# ---------------------------------------------------------------------------

#: FFT operands above this many elements get an optimization barrier.
#: Known-good without barrier: every 256^3 operand (13.2-16.8M, compiles
#: ~16 s); known-pathological: the 320^3 stick array (25.7M, ~560 s) —
#: the threshold sits at the top of the verified-good range.
_MAT_THRESHOLD = 1 << 24


def _mat(x):
    """Materialise a LARGE FFT operand behind an optimization barrier.

    XLA's TPU FFT compile time explodes when a big operand is a *computed*
    value rather than a materialised buffer: a (80379, 320) c64 ifft
    compiles in ~13 s from a parameter but ~560 s when fed by the
    decompress gather (or even a bare complex construction) — the 320^3
    "stall" of round 1. The barrier forces a materialised operand and
    restores O(10 s) compiles with no runtime cost at those sizes. Below
    the threshold the barrier is skipped: small-grid compiles were always
    fine and the forced materialisation costs real time there (64^3 XLA
    pair: 6.6 ms with barrier vs 4.7 ms without).
    Probe: scripts/probe_fftcompile.py.
    """
    if x.size > _MAT_THRESHOLD:
        return jax.lax.optimization_barrier(x)
    return x


def z_backward(sticks):
    """Unnormalised inverse DFT along z for every stick:
    ``ifft * dim_z`` (reference backward z, execution_host.cpp:311-315).
    TPU single-precision routes through the matmul DFT (ops.dft)."""
    dim_z = sticks.shape[-1]
    if dft.use_matmul_dft(dim_z, sticks.dtype):
        return dft.cdft_last(sticks, dft.c2c_mats(dim_z, dft.BACKWARD))
    return jnp.fft.ifft(_mat(sticks), axis=-1) \
        * sticks.real.dtype.type(dim_z)


def z_forward(sticks):
    """Forward DFT along z for every stick (reference forward z,
    execution_host.cpp:283-290)."""
    dim_z = sticks.shape[-1]
    if dft.use_matmul_dft(dim_z, sticks.dtype):
        return dft.cdft_last(sticks, dft.c2c_mats(dim_z, dft.FORWARD))
    return jnp.fft.fft(_mat(sticks), axis=-1)


# ---------------------------------------------------------------------------
# Local transpose: packed sticks <-> frequency-domain planes
# ---------------------------------------------------------------------------

def sticks_to_grid(sticks, col_inv, dim_y: int, dim_x_freq: int):
    """Place z-transformed sticks into the plane grid — as a row *gather*.

    Same semantics as the reference backward unpack scatter
    (transpose_host.hpp:132-154: zero the grid, place each stick at its xy
    index), via the plan-time inverse column map (indexing.inverse_col_map).
    Each gathered row is a whole stick (contiguous), which XLA lowers to
    fast slice gathers.

    Args:
      sticks: (num_sticks, num_planes) complex — stick-major, z-restricted.
      col_inv: (dim_y * dim_x_freq,) int32; sentinel num_sticks -> zero row.
    Returns:
      (num_planes, dim_y, dim_x_freq) complex.
    """
    num_planes = sticks.shape[1]
    grid_t = gather_rows_with_sentinel(sticks, col_inv)
    return grid_t.T.reshape(num_planes, dim_y, dim_x_freq)


def sticks_to_grid_padded(sticks, col_inv, dim_y: int, dim_x_freq: int):
    """:func:`sticks_to_grid` for stick arrays that already carry >= 1
    trailing ZERO pad row (plans with compression tables — see
    plan._s_pad): the sentinel ``num_sticks`` in ``col_inv`` selects a
    pad row directly, so the zero-row concatenation (a full copy of the
    stick array) disappears."""
    num_planes = sticks.shape[1]
    return sticks[col_inv].T.reshape(num_planes, dim_y, dim_x_freq)


def grid_to_sticks(grid, scatter_cols):
    """Gather sticks out of the plane grid (reference forward pack,
    transpose_host.hpp:94-116).

    Returns (num_sticks, num_planes) complex.
    """
    num_planes = grid.shape[0]
    flat = grid.reshape(num_planes, -1)
    return flat[:, scatter_cols].T


# ---------------------------------------------------------------------------
# Hermitian symmetry completion (R2C backward only;
# reference applies stick symmetry before the z-FFT and plane symmetry after
# the exchange — execution_host.cpp:306-308, 340-342)
# ---------------------------------------------------------------------------

def complete_stick_hermitian(stick):
    """Complete the (x=0, y=0) z-stick: missing entries become the conjugate
    of their mirror, provided entries win.

    Functional form of reference symmetry_host.hpp:69-91 (nonzero-guarded
    ``stick[N-i] = conj(stick[i])``); identical on valid inputs where each
    (+z, -z) pair has at least one consistent value supplied
    (docs/source/details.rst "Real-To-Complex Transforms").
    """
    mirror = jnp.roll(stick[::-1], 1)  # mirror[i] = stick[(N - i) % N]
    return jnp.where(stick != 0, stick, jnp.conj(mirror))


def complete_plane_hermitian_t(grid_t):
    """Transposed-layout variant of :func:`complete_plane_hermitian`:
    ``grid_t`` is (planes, dim_x_freq, dim_y), so the x=0 column is the
    contiguous sub-plane ``grid_t[:, 0, :]`` (the matmul-DFT pipeline's
    plane layout — ops/dft.py)."""
    col = grid_t[:, 0, :]
    mirror = jnp.roll(col[:, ::-1], 1, axis=-1)
    col = jnp.where(col != 0, col, jnp.conj(mirror))
    return grid_t.at[:, 0, :].set(col)


def complete_plane_hermitian(grid):
    """Complete the x=0 column of every z-plane along y: missing ±y entries
    become the conjugate of their mirror (reference symmetry_host.hpp:41-64;
    tolerates either +y or -y being supplied).

    Args:
      grid: (planes, dim_y, dim_x_freq) complex.
    """
    col = grid[:, :, 0]
    mirror = jnp.roll(col[:, ::-1], 1, axis=1)
    col = jnp.where(col != 0, col, jnp.conj(mirror))
    return grid.at[:, :, 0].set(col)


# ---------------------------------------------------------------------------
# xy-stage: batched FFTs over planes
# ---------------------------------------------------------------------------

def xy_backward_c2c(grid):
    """Unnormalised inverse DFT over (y, x) per plane:
    ``ifft2 * (dim_y * dim_x)``.

    The dense path, used when the occupied x columns span most of the
    extent. Narrow-x sets use the split variants below, which implement
    the reference's y-over-non-empty-rows optimization
    (execution_host.cpp:139-145, 328-352). TPU single precision runs the
    matmul DFT per axis; other configurations use the XLA Fft HLO.
    """
    dim_y, dim_x = grid.shape[-2], grid.shape[-1]
    scale = grid.real.dtype.type(dim_y * dim_x)
    if _mdft_axes(grid.dtype, dim_y, dim_x):
        return dft.cdft2_xy(grid, dft.c2c_mats(dim_x, dft.BACKWARD),
                            dft.c2c_mats(dim_y, dft.BACKWARD))
    return jnp.fft.ifft2(_mat(grid), axes=(-2, -1)) * scale


def xy_forward_c2c(grid):
    """Forward DFT over (y, x) per plane."""
    dim_y, dim_x = grid.shape[-2], grid.shape[-1]
    if _mdft_axes(grid.dtype, dim_y, dim_x):
        return dft.cdft2_xy(grid, dft.c2c_mats(dim_x, dft.FORWARD),
                            dft.c2c_mats(dim_y, dft.FORWARD))
    return jnp.fft.fft2(_mat(grid), axes=(-2, -1))


def _expand_x_window(sub, x0: int, dim_x: int):
    """Zero-pad the occupied-x window ``[x0, x0+w) mod dim_x`` back to the
    full x extent. Centered frequency sets occupy a *wrapped* window
    (negative indices store high), so the window may straddle the x
    boundary — then the pad lands at the front and the columns roll into
    place."""
    w = sub.shape[-1]
    pad = [(0, 0)] * (sub.ndim - 1)
    if x0 + w <= dim_x:
        return jnp.pad(sub, pad + [(x0, dim_x - x0 - w)])
    return jnp.roll(jnp.pad(sub, pad + [(0, dim_x - w)]), x0, axis=-1)


def _extract_x_window(grid, x0: int, w: int):
    """Take the occupied-x window ``[x0, x0+w) mod dim_x`` out of a full
    grid (mirror of :func:`_expand_x_window`)."""
    dim_x = grid.shape[-1]
    if x0 + w <= dim_x:
        return grid[..., x0:x0 + w]
    return jnp.concatenate([grid[..., x0:], grid[..., :x0 + w - dim_x]],
                           axis=-1)


def xy_backward_c2c_split(sub, x0: int, dim_x: int):
    """Backward xy-stage exploiting x-row sparsity (the reference's
    "y transform over non-empty x-rows only", execution_host.cpp:139-145,
    328-352): ``sub`` holds only the occupied x columns
    ``[x0, x0+w) mod dim_x`` of the plane grid, (planes, dim_y, w) complex
    — possibly a wrapped window (centered sets). The y-IFFT runs only on
    those w columns (all other columns are zero, and ifft(0)=0), the
    result is zero-expanded back to full x extent, and the x-IFFT runs
    dense (the space-domain output is dense). Returns
    (planes, dim_y, dim_x).

    Matmul-DFT form: the x-stage contracts the w occupied rows of the
    DFT matrix directly — a wrapped window is just a non-contiguous row
    selection, no roll/pad stage."""
    dim_y, w = sub.shape[-2], sub.shape[-1]
    if _mdft_axes(sub.dtype, dim_y, dim_x, direct=(dim_x,)):
        sub = _cdft_mid(sub, dft.c2c_mats(dim_y, dft.BACKWARD))
        rows = tuple(int(r) for r in (x0 + np.arange(w)) % dim_x)
        return dft.cdft_last(
            sub, dft.sub_rows_mats(dim_x, dft.BACKWARD, rows))
    scale = sub.real.dtype.type(dim_y * dim_x)
    sub = jnp.fft.ifft(_mat(sub), axis=-2)
    return jnp.fft.ifft(_mat(_expand_x_window(sub, x0, dim_x)), axis=-1) * scale


def xy_forward_c2c_split(space, x0: int, w: int):
    """Forward mirror of :func:`xy_backward_c2c_split`: dense x-DFT, then
    the y-DFT only on the occupied x columns ``[x0, x0+w) mod dim_x`` —
    the only columns the stick gather reads. Returns (planes, dim_y, w)."""
    dim_y, dim_x = space.shape[-2], space.shape[-1]
    if _mdft_axes(space.dtype, dim_y, dim_x, direct=(dim_x,)):
        cols = tuple(int(c) for c in (x0 + np.arange(w)) % dim_x)
        grid = dft.cdft_last(
            space, dft.sub_cols_mats(dim_x, dft.FORWARD, cols))
        return _cdft_mid(grid, dft.c2c_mats(dim_y, dft.FORWARD))
    grid = jnp.fft.fft(_mat(space), axis=-1)
    return jnp.fft.fft(_mat(_extract_x_window(grid, x0, w)), axis=-2)


def _irfft_last(x, n: int):
    """irfft along the last axis with the batch dims COLLAPSED to one.

    XLA's TPU C2R silently corrupts rank-3 operands once the collapsed
    batch exceeds ~2^16 rows (measured 2026-07-30: irfft of (256, 384, 193)
    -> rel error 0.32, while the identical data as (98304, 193) and every
    rank-2 batch size is exact; rfft, C2C ffts and 2D ffts are unaffected).
    Collapsing to rank 2 is a free reshape (leading dims, row-major) and
    sidesteps the bug for every shape this library produces.
    """
    batch = x.shape[:-1]
    flat = jnp.fft.irfft(x.reshape(-1, x.shape[-1]), n=n, axis=-1)
    return flat.reshape(batch + (n,))


def xy_backward_r2c_split(sub, x0: int, dim_x: int, dim_x_freq: int):
    """R2C backward xy-stage on the occupied half-spectrum window
    ``[x0, x0+w)`` (no wrap — the half spectrum has no negative x): y-IFFT
    on the w occupied columns, zero-pad to the full half extent, then the
    dense c2r x-IFFT. ``sub`` is (planes, dim_y, w) complex; returns real
    (planes, dim_y, dim_x). Reference: the per-selected-row vertical plan,
    transform_1d_host.hpp:137-196."""
    dim_y, w = sub.shape[-2], sub.shape[-1]
    if _mdft_axes(sub.dtype, dim_y, dim_x, direct_any=(dim_x,)):
        sub = _cdft_mid(sub, dft.c2c_mats(dim_y, dft.BACKWARD))
        rows = tuple(range(x0, x0 + w))
        return dft.pirdft_last(jnp.real(sub), jnp.imag(sub),
                               dft.sub_rows_c2r_mats(dim_x, rows))
    rdtype = sub.real.dtype
    sub = jnp.fft.ifft(_mat(sub), axis=-2) * rdtype.type(dim_y)
    full = jnp.pad(sub, ((0, 0), (0, 0), (x0, dim_x_freq - x0 - w)))
    return _irfft_last(_mat(full), dim_x) * rdtype.type(dim_x)


def xy_forward_r2c_split(space, x0: int, w: int):
    """Forward mirror of :func:`xy_backward_r2c_split`: dense r2c x-DFT,
    then the y-DFT only on the occupied half-spectrum columns. ``space``
    is real (planes, dim_y, dim_x); returns (planes, dim_y, w) complex."""
    dim_y, dim_x = space.shape[-2], space.shape[-1]
    if _mdft_axes(space.dtype, dim_y, dim_x, direct_any=(dim_x,)):
        cols = tuple(range(x0, x0 + w))
        yr, yi = dft.prdft_last(space,
                                dft.sub_cols_r2c_mats(dim_x, cols))
        return _cdft_mid(yr + 1j * yi, dft.c2c_mats(dim_y, dft.FORWARD))
    grid = jnp.fft.rfft(_mat(space), axis=-1)
    return jnp.fft.fft(_mat(grid[..., x0:x0 + w]), axis=-2)


def xy_backward_r2c(grid, dim_x: int):
    """R2C backward xy-stage: inverse y DFT then real inverse x DFT.

    ``grid`` is (planes, dim_y, dim_x//2+1) complex; returns real
    (planes, dim_y, dim_x). Mirrors reference backward_xy with the c2r
    x-transform (execution_host.cpp:344-351, transform_real_1d_host.hpp).
    The matmul path needs no XLA C2R op at all (the hermitian doubling
    lives in the c2r matrices — ops.dft), sidestepping the TPU backend's
    rank-3 irfft corruption by construction.
    """
    dim_y = grid.shape[-2]
    if _mdft_axes(grid.dtype, dim_y, dim_x, direct_any=(dim_x,)):
        grid = _cdft_mid(grid, dft.c2c_mats(dim_y, dft.BACKWARD))
        return dft.pirdft_last(jnp.real(grid), jnp.imag(grid),
                               dft.c2r_mats(dim_x))
    rdtype = grid.real.dtype
    grid = jnp.fft.ifft(_mat(grid), axis=-2) * rdtype.type(dim_y)
    return _irfft_last(_mat(grid), dim_x) * rdtype.type(dim_x)


def xy_forward_r2c(space):
    """R2C forward xy-stage: real forward x DFT then y DFT.

    ``space`` is real (planes, dim_y, dim_x); returns
    (planes, dim_y, dim_x//2+1) complex.
    """
    dim_y, dim_x = space.shape[-2], space.shape[-1]
    if _mdft_axes(space.dtype, dim_y, dim_x, direct_any=(dim_x,)):
        yr, yi = dft.prdft_last(space, dft.r2c_mats(dim_x))
        return _cdft_mid(yr + 1j * yi, dft.c2c_mats(dim_y, dft.FORWARD))
    grid = jnp.fft.rfft(_mat(space), axis=-1)
    return jnp.fft.fft(_mat(grid), axis=-2)


# ---------------------------------------------------------------------------
# Profiler phase attribution: wrap every stage in a jax.named_scope so XLA
# traces show the pipeline phases by name — the device-side counterpart of
# the reference's HOST_TIMING labels ("z transform", "pack", "unpack", ...,
# execution_host.cpp:251-295).
# ---------------------------------------------------------------------------

import functools as _functools


def _named(fn, label: str):
    @_functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with jax.named_scope(f"spfft.{label}"):
            return fn(*args, **kwargs)
    return wrapper


decompress = _named(decompress, "decompress")
compress = _named(compress, "compress")
z_backward = _named(z_backward, "z_backward")
z_forward = _named(z_forward, "z_forward")
sticks_to_grid = _named(sticks_to_grid, "unpack")
sticks_to_grid_padded = _named(sticks_to_grid_padded, "unpack")
grid_to_sticks = _named(grid_to_sticks, "pack")
complete_stick_hermitian = _named(complete_stick_hermitian, "stick_symmetry")
complete_plane_hermitian = _named(complete_plane_hermitian, "plane_symmetry")
complete_plane_hermitian_t = _named(complete_plane_hermitian_t,
                                    "plane_symmetry")
xy_backward_c2c = _named(xy_backward_c2c, "xy_backward")
xy_forward_c2c = _named(xy_forward_c2c, "xy_forward")
xy_backward_r2c = _named(xy_backward_r2c, "xy_backward")
xy_forward_r2c = _named(xy_forward_r2c, "xy_forward")
xy_backward_c2c_split = _named(xy_backward_c2c_split, "xy_backward_split")
xy_forward_c2c_split = _named(xy_forward_c2c_split, "xy_forward_split")
xy_backward_r2c_split = _named(xy_backward_r2c_split, "xy_backward_split")
xy_forward_r2c_split = _named(xy_forward_r2c_split, "xy_forward_split")

"""Device-side computation stages (jit-traceable) for sparse 3D FFTs."""

from . import stages  # noqa: F401

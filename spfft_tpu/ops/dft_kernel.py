"""Fused Pallas matmul-DFT stage kernels (TPU hot path).

The XLA form of a planar DFT stage (:func:`spfft_tpu.ops.dft.pdft_last`)
is three ``dot_general`` ops plus an elementwise Karatsuba combine. XLA
cannot carry one fused elementwise chain across three matmuls, so at
grid scale every stage materialises p1/p2/p3 and the (xr+xi) operand sum
as HBM intermediates around the dots. These kernels do the dots and the
combine per row tile entirely in VMEM — one HBM read of the operands,
one write of the results:

* :func:`pdft_last` — one stage, minor-axis contraction. Measured
  0.796 ms vs 1.087 ms for the XLA form at the 256^3 stage shape
  (M=65536, N=256), identical accuracy (rel 8.2e-8 vs numpy f64 —
  scripts/probe_r5_fused_stage.py).
* :func:`pdft2` (+ ``prdft2``/``pdft2_cr`` R2C twins) — TWO stages with
  the inter-stage transpose done in VMEM: stage-1 dot over the minor
  axis, swap of the two minor axes, stage-2 dot over the new minor
  axis. This removes the materialised grid-sized ``swapaxes`` pass
  between the xy stages. Measured 1.62 ms vs 2.07 ms for the XLA
  three-pass form at 256^3 (scripts/probe_r5_fused2d.py); the fused
  form is MXU-bound (~1.57 ms of 6-pass f32 matmul at this shape), so
  it sits at the precision ladder's floor.

Precision: Mosaic honours ``Precision.HIGHEST`` for f32 dots (measured
rel 8.1e-8 on a 256-point pass, identical to XLA HIGHEST —
scripts/probe_r5_pallas_dot.py), which is what keeps the library's
1e-6 contract available; ``Precision.HIGH`` is *rejected* by Mosaic and
DEFAULT fails the contract, so the kernels are HIGHEST-only.

Eligibility (:func:`eligible_mats`): TPU backend, f32 operands, plain
matrix tuples (the two-stage Cooley-Tukey path keeps its XLA form), and
axis lengths that fit the VMEM tiling budget. Everything else falls
back to the XLA path — same math, same layouts. Disable with
``SPFFT_TPU_FUSED_STAGE=0`` (the A/B knob used by the probes).

Reference parity: these kernels fuse what the reference runs as separate
batched FFTW/cuFFT executes plus explicit pack/unpack transposes
(reference: src/fft/transform_1d_host.hpp:76-118, the local transpose in
src/transpose/transpose_host.hpp:94-154); on TPU the transpose lives in
VMEM inside the same kernel instead of being a strided plan.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_HI = jax.lax.Precision.HIGHEST
_DN = (((1,), (0,)), ((), ()))

#: Longest axis the fused kernels accept. Matches dft.MATMUL_DFT_MAX —
#: above it the pipeline uses the two-stage Cooley-Tukey XLA form anyway.
MAX_DIM = 512

#: Per-kernel VMEM budget (bytes) the tile chooser aims under. v5e has
#: ~16 MB/core; staying near half leaves room for Mosaic's own
#: double-buffering of the streamed operand tiles.
_VMEM_BUDGET = 9 * 1024 * 1024


def enabled() -> bool:
    """Fused stages are on by default on TPU; ``SPFFT_TPU_FUSED_STAGE=0``
    disables (read per trace so tests can flip it)."""
    return os.environ.get("SPFFT_TPU_FUSED_STAGE", "1").strip() != "0" \
        and jax.default_backend() == "tpu"


def _plain_mats(mats) -> bool:
    """True for a tuple of plain 2-D arrays (rejects TwoStageMats and
    anything else the XLA path special-cases)."""
    return (isinstance(mats, tuple) and len(mats) in (2, 3)
            and all(isinstance(m, (np.ndarray, jnp.ndarray)) and m.ndim == 2
                    for m in mats))


def eligible_mats(*mats_list) -> bool:
    """All matrix tuples are plain and within the kernel's axis cap."""
    for mats in mats_list:
        if not _plain_mats(mats):
            return False
        if any(d > MAX_DIM for m in mats for d in m.shape):
            return False
    return True


def _f32(*arrs) -> bool:
    return all(a.dtype == jnp.float32 for a in arrs)


# -- single fused stage ------------------------------------------------------

def _stage_kernel(xr_ref, xi_ref, cr_ref, ci_ref, cs_ref, yr_ref, yi_ref):
    a = xr_ref[...]
    b = xi_ref[...]
    p1 = jax.lax.dot_general(a, cr_ref[...], _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    p2 = jax.lax.dot_general(b, ci_ref[...], _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    p3 = jax.lax.dot_general(a + b, cs_ref[...], _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    yr_ref[...] = p1 - p2
    yi_ref[...] = p3 - p1 - p2


def _stage_tm(k: int, mo: int) -> int:
    """Row-tile size: large tiles amortise the resident matrices; shrink
    until 2 in + 2 out tiles + 3 matrices fit the VMEM budget."""
    for tm in (1024, 512, 256, 128):
        if (2 * tm * k + 2 * tm * mo + 3 * k * mo) * 4 <= _VMEM_BUDGET:
            return tm
    return 128


def pdft_last(xr, xi, mats, interpret: bool = False):
    """Fused planar complex DFT along the minor axis — drop-in for the
    eligible subset of :func:`spfft_tpu.ops.dft.pdft_last`."""
    cr, ci, cs = (jnp.asarray(m) for m in mats)
    k, mo = cr.shape
    lead = xr.shape[:-1]
    m = int(np.prod(lead)) if lead else 1
    tm = _stage_tm(k, mo)
    yr, yi = pl.pallas_call(
        _stage_kernel,
        grid=(pl.cdiv(m, tm),),
        in_specs=[
            pl.BlockSpec((tm, k), lambda i: (i, 0)),
            pl.BlockSpec((tm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tm, mo), lambda i: (i, 0)),
            pl.BlockSpec((tm, mo), lambda i: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((m, mo), jnp.float32)] * 2,
        interpret=interpret,
    )(xr.reshape(m, k), xi.reshape(m, k), cr, ci, cs)
    return yr.reshape(lead + (mo,)), yi.reshape(lead + (mo,))


# -- fused two-stage (stage1 · in-VMEM transpose · stage2) -------------------

def _kara(ar, ai, cr, ci, cs):
    p1 = jax.lax.dot_general(ar, cr, _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    p2 = jax.lax.dot_general(ai, ci, _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    p3 = jax.lax.dot_general(ar + ai, cs, _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    return p1 - p2, p3 - p1 - p2


def _swap2(g, tp, b_out, a_in):
    """(tp*a_in, b_out) -> (tp*b_out, a_in) via the 3-D minor swap."""
    return jnp.swapaxes(g.reshape(tp, a_in, b_out), -1, -2) \
        .reshape(tp * b_out, a_in)


def _kernel2_cc(xr_ref, xi_ref, c1r_ref, c1i_ref, c1s_ref,
                c2r_ref, c2i_ref, c2s_ref, or_ref, oi_ref):
    tp, a_in, b_in = xr_ref.shape
    b_out = c1r_ref.shape[1]
    gr, gi = _kara(xr_ref[...].reshape(tp * a_in, b_in),
                   xi_ref[...].reshape(tp * a_in, b_in),
                   c1r_ref[...], c1i_ref[...], c1s_ref[...])
    gr = _swap2(gr, tp, b_out, a_in)
    gi = _swap2(gi, tp, b_out, a_in)
    hr, hi = _kara(gr, gi, c2r_ref[...], c2i_ref[...], c2s_ref[...])
    a_out = hr.shape[1]
    or_ref[...] = hr.reshape(tp, b_out, a_out)
    oi_ref[...] = hi.reshape(tp, b_out, a_out)


def _kernel2_rc(x_ref, c1a_ref, c1b_ref, c2r_ref, c2i_ref, c2s_ref,
                or_ref, oi_ref):
    tp, a_in, b_in = x_ref.shape
    b_out = c1a_ref.shape[1]
    x = x_ref[...].reshape(tp * a_in, b_in)
    gr = jax.lax.dot_general(x, c1a_ref[...], _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    gi = jax.lax.dot_general(x, c1b_ref[...], _DN, precision=_HI,
                             preferred_element_type=jnp.float32)
    gr = _swap2(gr, tp, b_out, a_in)
    gi = _swap2(gi, tp, b_out, a_in)
    hr, hi = _kara(gr, gi, c2r_ref[...], c2i_ref[...], c2s_ref[...])
    a_out = hr.shape[1]
    or_ref[...] = hr.reshape(tp, b_out, a_out)
    oi_ref[...] = hi.reshape(tp, b_out, a_out)


def _kernel2_cr(xr_ref, xi_ref, c1r_ref, c1i_ref, c1s_ref,
                c2a_ref, c2b_ref, o_ref):
    tp, a_in, b_in = xr_ref.shape
    b_out = c1r_ref.shape[1]
    gr, gi = _kara(xr_ref[...].reshape(tp * a_in, b_in),
                   xi_ref[...].reshape(tp * a_in, b_in),
                   c1r_ref[...], c1i_ref[...], c1s_ref[...])
    gr = _swap2(gr, tp, b_out, a_in)
    gi = _swap2(gi, tp, b_out, a_in)
    h = jax.lax.dot_general(gr, c2a_ref[...], _DN, precision=_HI,
                            preferred_element_type=jnp.float32) \
        + jax.lax.dot_general(gi, c2b_ref[...], _DN, precision=_HI,
                              preferred_element_type=jnp.float32)
    o_ref[...] = h.reshape(tp, b_out, h.shape[1])


#: Tighter budget for the two-stage kernels: their in-VMEM transpose and
#: two live dot accumulators cost Mosaic more than the footprint formula
#: sees (a tp=4 256-class kernel, ~7.8 MB by the formula, fails to
#: compile on v5e — probe_r5_fused2d.py), so aim well under half VMEM.
_VMEM_BUDGET2 = 5 * 1024 * 1024


def plane_tp(a_in, b_in, b_out, a_out, n_chan_in, n_chan_out,
             mats_elems):
    """Planes per grid step for the two-stage kernels, sized to VMEM
    (input + intermediate + output tiles per plane plus the resident
    matrices). ``None`` when even one plane per step does not fit —
    callers must fall back to the single-stage form."""
    per_plane = (n_chan_in * a_in * b_in + 2 * a_in * b_out
                 + n_chan_out * b_out * a_out) * 4
    mats = mats_elems * 4
    for tp in (4, 2, 1):
        if tp * per_plane + mats <= _VMEM_BUDGET2:
            return tp
    return None


#: (input channels, output channels, stage-1 matrices, stage-2 matrices)
#: per two-stage kernel mode — the single source for the VMEM sizing
#: used by both the eligibility gate and the kernels themselves.
_MODE_CHANNELS = {"cc": (2, 2, 3, 3), "rc": (1, 2, 2, 3),
                  "cr": (2, 1, 3, 2)}


def _tp2(mode: str, a_in: int, b_in: int, b_out: int, a_out: int):
    ci, co, m1, m2 = _MODE_CHANNELS[mode]
    return plane_tp(a_in, b_in, b_out, a_out, ci, co,
                    m1 * b_in * b_out + m2 * a_in * a_out)


def fits2(mode: str, a_in: int, b_in: int, b_out: int, a_out: int) -> bool:
    """Whether the two-stage kernel of ``mode`` ('cc'/'rc'/'cr') fits
    the VMEM budget at these axis lengths."""
    return _tp2(mode, a_in, b_in, b_out, a_out) is not None


def _pallas2(kernel, ins, in_specs, out_shapes, out_specs, grid,
             interpret):
    return pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shapes, interpret=interpret)(*ins)


def pdft2(xr, xi, mats1, mats2, interpret: bool = False):
    """Fused [stage-1 minor dot, transpose, stage-2 minor dot] on planar
    complex operands: ``(P, A, B) -> (P, B', A')`` — replaces
    ``pdft_last(mats1) ; swapaxes(-1, -2) ; pdft_last(mats2)``."""
    c1 = tuple(jnp.asarray(m) for m in mats1)
    c2 = tuple(jnp.asarray(m) for m in mats2)
    p, a_in, b_in = xr.shape
    b_out = c1[0].shape[1]
    a_out = c2[0].shape[1]
    tp = _tp2("cc", a_in, b_in, b_out, a_out)
    assert tp is not None, "caller must gate on fits2"
    mspecs = [pl.BlockSpec((b_in, b_out), lambda i: (0, 0))] * 3 \
        + [pl.BlockSpec((a_in, a_out), lambda i: (0, 0))] * 3
    yr, yi = _pallas2(
        _kernel2_cc, (xr, xi) + c1 + c2,
        [pl.BlockSpec((tp, a_in, b_in), lambda i: (i, 0, 0))] * 2 + mspecs,
        [jax.ShapeDtypeStruct((p, b_out, a_out), jnp.float32)] * 2,
        [pl.BlockSpec((tp, b_out, a_out), lambda i: (i, 0, 0))] * 2,
        (pl.cdiv(p, tp),), interpret)
    return yr, yi


def prdft2(x, mats1, mats2, interpret: bool = False):
    """R2C head twin of :func:`pdft2`: real input, stage 1 is the
    half-spectrum real DFT (two dots), stage 2 complex."""
    c1 = tuple(jnp.asarray(m) for m in mats1)
    c2 = tuple(jnp.asarray(m) for m in mats2)
    p, a_in, b_in = x.shape
    b_out = c1[0].shape[1]
    a_out = c2[0].shape[1]
    tp = _tp2("rc", a_in, b_in, b_out, a_out)
    assert tp is not None, "caller must gate on fits2"
    mspecs = [pl.BlockSpec((b_in, b_out), lambda i: (0, 0))] * 2 \
        + [pl.BlockSpec((a_in, a_out), lambda i: (0, 0))] * 3
    yr, yi = _pallas2(
        _kernel2_rc, (x,) + c1 + c2,
        [pl.BlockSpec((tp, a_in, b_in), lambda i: (i, 0, 0))] + mspecs,
        [jax.ShapeDtypeStruct((p, b_out, a_out), jnp.float32)] * 2,
        [pl.BlockSpec((tp, b_out, a_out), lambda i: (i, 0, 0))] * 2,
        (pl.cdiv(p, tp),), interpret)
    return yr, yi


def pdft2_cr(xr, xi, mats1, mats2, interpret: bool = False):
    """C2R tail twin of :func:`pdft2`: stage 1 complex, stage 2 the real
    inverse DFT (two dots into one real output)."""
    c1 = tuple(jnp.asarray(m) for m in mats1)
    c2 = tuple(jnp.asarray(m) for m in mats2)
    p, a_in, b_in = xr.shape
    b_out = c1[0].shape[1]
    a_out = c2[0].shape[1]
    tp = _tp2("cr", a_in, b_in, b_out, a_out)
    assert tp is not None, "caller must gate on fits2"
    mspecs = [pl.BlockSpec((b_in, b_out), lambda i: (0, 0))] * 3 \
        + [pl.BlockSpec((a_in, a_out), lambda i: (0, 0))] * 2
    out = _pallas2(
        _kernel2_cr, (xr, xi) + c1 + c2,
        [pl.BlockSpec((tp, a_in, b_in), lambda i: (i, 0, 0))] * 2 + mspecs,
        [jax.ShapeDtypeStruct((p, b_out, a_out), jnp.float32)],
        [pl.BlockSpec((tp, b_out, a_out), lambda i: (i, 0, 0))],
        (pl.cdiv(p, tp),), interpret)
    return out[0]

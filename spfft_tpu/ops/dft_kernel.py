"""Fused Pallas matmul-DFT stage kernels (TPU hot path).

The XLA form of a planar DFT stage (:func:`spfft_tpu.ops.dft.pdft_last`)
is three ``dot_general`` ops plus an elementwise Karatsuba combine. XLA
cannot carry one fused elementwise chain across three matmuls, so at
grid scale every stage materialises p1/p2/p3 and the (xr+xi) operand sum
as HBM intermediates around the dots. These kernels do the dots and the
combine per row tile entirely in VMEM — one HBM read of the operands,
one write of the results:

* :func:`pdft_last` — one stage, minor-axis contraction. Measured
  0.796 ms vs 1.087 ms for the XLA form at the 256^3 stage shape
  (M=65536, N=256), identical accuracy (rel 8.2e-8 vs numpy f64 —
  scripts/probe_r5_fused_stage.py).
* :func:`pdft2` (+ ``prdft2``/``pdft2_cr`` R2C twins) — TWO stages with
  the inter-stage transpose done in VMEM: stage-1 dot over the minor
  axis, swap of the two minor axes, stage-2 dot over the new minor
  axis. This removes the materialised grid-sized ``swapaxes`` pass
  between the xy stages. Measured 1.62 ms vs 2.07 ms for the XLA
  three-pass form at 256^3 (scripts/probe_r5_fused2d.py); the fused
  form is MXU-bound (~1.57 ms of 6-pass f32 matmul at this shape), so
  it sits at the precision ladder's floor.

Precision: Mosaic honours ``Precision.HIGHEST`` for f32 dots (measured
rel 8.1e-8 on a 256-point pass, identical to XLA HIGHEST —
scripts/probe_r5_pallas_dot.py), which is what keeps the library's
1e-6 contract available; ``Precision.HIGH`` is *rejected* by Mosaic and
DEFAULT fails the contract, so the kernels are HIGHEST-only.

Eligibility (:func:`eligible_mats` + :func:`fits2`): TPU backend, f32
operands, plain matrix tuples (the two-stage Cooley-Tukey path keeps
its XLA form), and axis lengths that fit the VMEM tiling budget.
Everything else falls back to the XLA path — same math, same layouts.
Disable with ``SPFFT_TPU_FUSED_STAGE=0`` (the A/B knob used by the
probes).

Reference parity: these kernels fuse what the reference runs as separate
batched FFTW/cuFFT executes plus explicit pack/unpack transposes
(reference: src/fft/transform_1d_host.hpp:76-118, the local transpose in
src/transpose/transpose_host.hpp:94-154); on TPU the transpose lives in
VMEM inside the same kernel instead of being a strided plan.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_HI = jax.lax.Precision.HIGHEST
_DN = (((1,), (0,)), ((), ()))

#: Empirical ceiling of the fused kernels, independent of the matmul-DFT
#: cap: above 320 the two-stage xy kernel no longer fits VMEM, and the
#: single-stage kernel alone measures a net LOSS against the XLA stages
#: (same-session interleaved A/B: 384^3 pair 56.5 vs 54.2 ms, 512^3
#: 161.2 vs 148.2 — the shrunken row tiles forced by the compile
#: ceiling spend more on matrix streaming than the combine fusion
#: saves), while <= 320 wins (256^3 12.3 -> 10.5, 320^3 36.6 -> 33.1).
_EMPIRICAL_MAX = 320


def max_dim() -> int:
    """Longest axis the fused kernels accept: the empirical VMEM/perf
    ceiling clamped to the CURRENT matmul-DFT cap. Reads
    ``dft.MATMUL_DFT_MAX`` per call (module-attribute access, never
    bound at import) so monkeypatched/retuned caps propagate to kernel
    eligibility immediately (round-5 advisor finding)."""
    from . import dft
    return min(_EMPIRICAL_MAX, dft.MATMUL_DFT_MAX)

#: Per-kernel VMEM budget (bytes) the single-stage tile chooser aims
#: under. The EMPIRICAL compile ceiling on v5e is ~5.5 MB by the
#: footprint formula (tm sweep at 384/512: 5.2 MB compiles, 7.3 MB
#: crashes the compile helper — Mosaic's double-buffering of streamed
#: tiles and dot accumulators roughly doubles the formula), so both
#: budgets sit just under it. 256-class stages keep tm=1024 (5.0 MB);
#: 384 -> tm=512, 512 -> tm=256.
_VMEM_BUDGET = int(5.5 * 1024 * 1024)


def enabled() -> bool:
    """Fused stages are on by default on TPU; ``SPFFT_TPU_FUSED_STAGE=0``
    disables (read per trace so tests can flip it)."""
    return os.environ.get("SPFFT_TPU_FUSED_STAGE", "1").strip() != "0" \
        and jax.default_backend() == "tpu"


def _plain_mats(mats) -> bool:
    """True for a tuple of plain 2-D arrays (rejects TwoStageMats and
    anything else the XLA path special-cases)."""
    return (isinstance(mats, tuple) and len(mats) in (2, 3)
            and all(isinstance(m, (np.ndarray, jnp.ndarray)) and m.ndim == 2
                    for m in mats))


def eligible_mats(*mats_list, cap=None) -> bool:
    """All matrix tuples are plain and within the axis cap (default
    :func:`max_dim`; the z-stage dispatch passes the full matmul cap —
    see dft.pdft_last_opt)."""
    limit = max_dim() if cap is None else cap
    for mats in mats_list:
        if not _plain_mats(mats):
            return False
        if any(d > limit for m in mats for d in m.shape):
            return False
    return True


def _dot(a, c):
    return jax.lax.dot_general(a, c, _DN, precision=_HI,
                               preferred_element_type=jnp.float32)


def _kara(ar, ai, cr, ci, cs):
    """Karatsuba 3-mult complex DFT on 2-D planar operands."""
    p1 = _dot(ar, cr)
    p2 = _dot(ai, ci)
    p3 = _dot(ar + ai, cs)
    return p1 - p2, p3 - p1 - p2


# -- single fused stage ------------------------------------------------------

def _stage_kernel(xr_ref, xi_ref, cr_ref, ci_ref, cs_ref, yr_ref, yi_ref):
    yr, yi = _kara(xr_ref[...], xi_ref[...],
                   cr_ref[...], ci_ref[...], cs_ref[...])
    yr_ref[...] = yr
    yi_ref[...] = yi


def _stage_tm(k: int, mo: int):
    """Row-tile size: large tiles amortise the resident matrices; shrink
    until 2 in + 2 out tiles + 3 matrices fit the VMEM budget. Returns
    ``None`` when even tm=128 exceeds the budget (the matrices alone
    overflow it at retuned caps) — dispatchers must treat that as
    ineligible and keep the XLA form, mirroring the fits2/plane_tp
    pattern, instead of risking a Mosaic compile crash (round-5 advisor
    finding)."""
    for tm in (1024, 512, 256, 128):
        if (2 * tm * k + 2 * tm * mo + 3 * k * mo) * 4 <= _VMEM_BUDGET:
            return tm
    return None


def fits1(k: int, mo: int) -> bool:
    """Whether the single-stage kernel fits the VMEM budget at this
    matrix shape — the fits2 twin for :func:`pdft_last` dispatch."""
    return _stage_tm(k, mo) is not None


def pdft_last(xr, xi, mats, interpret: bool = False):
    """Fused planar complex DFT along the minor axis — drop-in for the
    eligible subset of :func:`spfft_tpu.ops.dft.pdft_last`."""
    cr, ci, cs = (jnp.asarray(m) for m in mats)
    k, mo = cr.shape
    lead = xr.shape[:-1]
    m = int(np.prod(lead)) if lead else 1
    tm = _stage_tm(k, mo)
    assert tm is not None, "caller must gate on fits1"
    yr, yi = pl.pallas_call(
        _stage_kernel,
        grid=(pl.cdiv(m, tm),),
        in_specs=[
            pl.BlockSpec((tm, k), lambda i: (i, 0)),
            pl.BlockSpec((tm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
            pl.BlockSpec((k, mo), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tm, mo), lambda i: (i, 0)),
            pl.BlockSpec((tm, mo), lambda i: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((m, mo), jnp.float32)] * 2,
        interpret=interpret,
    )(xr.reshape(m, k), xi.reshape(m, k), cr, ci, cs)
    return yr.reshape(lead + (mo,)), yi.reshape(lead + (mo,))


# -- fused two-stage (stage1 · in-VMEM transpose · stage2) -------------------

#: (input channels, output channels, stage-1 matrices, stage-2 matrices)
#: per two-stage kernel mode — the single source for operand counts and
#: VMEM sizing, shared by the eligibility gate, the driver and the
#: kernel body. 'cc' = complex->complex both stages; 'rc' = real-input
#: rdft stage 1 (R2C forward head); 'cr' = real-output irdft stage 2
#: (R2C backward tail).
_MODE_CHANNELS = {"cc": (2, 2, 3, 3), "rc": (1, 2, 2, 3),
                  "cr": (2, 1, 3, 2)}

#: Tighter budget for the two-stage kernels: their in-VMEM transpose and
#: two live dot accumulators cost Mosaic more than the footprint formula
#: sees (a tp=4 256-class kernel, ~7.8 MB by the formula, fails to
#: compile on v5e — probe_r5_fused2d.py), so aim well under half VMEM.
_VMEM_BUDGET2 = 5 * 1024 * 1024


def plane_tp(a_in, b_in, b_out, a_out, n_chan_in, n_chan_out,
             mats_elems):
    """Planes per grid step for the two-stage kernels, sized to VMEM
    (input + intermediate + output tiles per plane plus the resident
    matrices). ``None`` when even one plane per step does not fit —
    callers must fall back to the single-stage form."""
    per_plane = (n_chan_in * a_in * b_in + 2 * a_in * b_out
                 + n_chan_out * b_out * a_out) * 4
    mats = mats_elems * 4
    for tp in (4, 2, 1):
        if tp * per_plane + mats <= _VMEM_BUDGET2:
            return tp
    return None


def _tp2(mode: str, a_in: int, b_in: int, b_out: int, a_out: int):
    ci, co, m1, m2 = _MODE_CHANNELS[mode]
    return plane_tp(a_in, b_in, b_out, a_out, ci, co,
                    m1 * b_in * b_out + m2 * a_in * a_out)


def fits2(mode: str, a_in: int, b_in: int, b_out: int, a_out: int) -> bool:
    """Whether the two-stage kernel of ``mode`` ('cc'/'rc'/'cr') fits
    the VMEM budget at these axis lengths."""
    return _tp2(mode, a_in, b_in, b_out, a_out) is not None


def _kernel2(mode, swap_out, *refs):
    """Shared two-stage kernel body: stage-1 dot over the minor axis,
    in-VMEM swap of the two minor axes, stage-2 dot over the new minor
    axis. Operand refs are laid out [inputs, stage-1 mats, stage-2 mats,
    outputs] per ``_MODE_CHANNELS[mode]``. ``swap_out`` stores the
    result transposed back to ``(tp, a_out, b_out)`` — the layout the
    distributed xy wrappers end in — with one more in-VMEM swap instead
    of a materialised HBM pass."""
    n_in, n_out, m1, m2 = _MODE_CHANNELS[mode]
    ins = refs[:n_in]
    c1 = [r[...] for r in refs[n_in:n_in + m1]]
    c2 = [r[...] for r in refs[n_in + m1:n_in + m1 + m2]]
    outs = refs[n_in + m1 + m2:]
    tp, a_in, b_in = ins[0].shape
    b_out = c1[0].shape[1]
    flat = [r[...].reshape(tp * a_in, b_in) for r in ins]
    if mode == "rc":
        gr, gi = _dot(flat[0], c1[0]), _dot(flat[0], c1[1])
    else:
        gr, gi = _kara(flat[0], flat[1], *c1)
    gr = jnp.swapaxes(gr.reshape(tp, a_in, b_out), -1, -2) \
        .reshape(tp * b_out, a_in)
    gi = jnp.swapaxes(gi.reshape(tp, a_in, b_out), -1, -2) \
        .reshape(tp * b_out, a_in)

    def store(ref, h):
        h = h.reshape(tp, b_out, h.shape[1])
        ref[...] = jnp.swapaxes(h, -1, -2) if swap_out else h

    if mode == "cr":
        store(outs[0], _dot(gr, c2[0]) + _dot(gi, c2[1]))
    else:
        hr, hi = _kara(gr, gi, *c2)
        store(outs[0], hr)
        store(outs[1], hi)


def _run2(mode, ins, mats1, mats2, interpret, swap_out=False):
    c1 = tuple(jnp.asarray(m) for m in mats1)
    c2 = tuple(jnp.asarray(m) for m in mats2)
    n_in, n_out, m1, m2 = _MODE_CHANNELS[mode]
    p, a_in, b_in = ins[0].shape
    b_out = c1[0].shape[1]
    a_out = c2[0].shape[1]
    tp = _tp2(mode, a_in, b_in, b_out, a_out)
    assert tp is not None, "caller must gate on fits2"
    oshape = (a_out, b_out) if swap_out else (b_out, a_out)
    return pl.pallas_call(
        functools.partial(_kernel2, mode, swap_out),
        grid=(pl.cdiv(p, tp),),
        in_specs=[pl.BlockSpec((tp, a_in, b_in), lambda i: (i, 0, 0))] * n_in
        + [pl.BlockSpec((b_in, b_out), lambda i: (0, 0))] * m1
        + [pl.BlockSpec((a_in, a_out), lambda i: (0, 0))] * m2,
        out_specs=[pl.BlockSpec((tp,) + oshape,
                                lambda i: (i, 0, 0))] * n_out,
        out_shape=[jax.ShapeDtypeStruct((p,) + oshape,
                                        jnp.float32)] * n_out,
        interpret=interpret,
    )(*ins, *c1, *c2)


def pdft2(xr, xi, mats1, mats2, interpret: bool = False):
    """Fused [stage-1 minor dot, transpose, stage-2 minor dot] on planar
    complex operands: ``(P, A, B) -> (P, B', A')`` — replaces
    ``pdft_last(mats1) ; swapaxes(-1, -2) ; pdft_last(mats2)``."""
    yr, yi = _run2("cc", (xr, xi), mats1, mats2, interpret)
    return yr, yi


def prdft2(x, mats1, mats2, interpret: bool = False):
    """R2C head twin of :func:`pdft2`: real input, stage 1 is the
    half-spectrum real DFT (two dots), stage 2 complex."""
    yr, yi = _run2("rc", (x,), mats1, mats2, interpret)
    return yr, yi


def pdft2_cr(xr, xi, mats1, mats2, interpret: bool = False):
    """C2R tail twin of :func:`pdft2`: stage 1 complex, stage 2 the real
    inverse DFT (two dots into one real output)."""
    return _run2("cr", (xr, xi), mats1, mats2, interpret)[0]


def pdft2_swapped(xr, xi, mats1, mats2, interpret: bool = False):
    """:func:`pdft2` with the result stored back in ``(P, A', B')``
    order (one more in-VMEM swap) — the layout the distributed xy stage
    wrappers produce, replacing their two materialised ``swapaxes``
    passes (ops.stages._cdft_mid)."""
    yr, yi = _run2("cc", (xr, xi), mats1, mats2, interpret, swap_out=True)
    return yr, yi

"""On-device double precision: double-single channels + exact-sliced dots.

The chip has no f64 ALU, so ``precision="double"`` historically ran on
the CPU backend. This module provides the ON-DEVICE double path
(round-4 verdict item 5): every value is a DOUBLE-SINGLE pair (hi, lo)
of f32 — ~48 significant bits — and every DFT contraction runs as an
Ozaki-style EXACT-SLICED matmul:

  * operands are sliced into beta-bit limbs on a power-of-two grid,
    with beta chosen so each partial dot is EXACT in the f32 MXU
    accumulator ((beta+1) + (beta+1) + log2(n) <= 24 bits);
  * partial dots are combined hi-to-lo with Knuth TwoSum chains, every
    rounding error captured into the lo channel.

Measured on the chip (scripts/probe_r5_ds.py, 4096x256 @ 256x256):
plain f32 HIGHEST dot 7.1e-8 relative; the verdict's compensated 3-dot
sketch 6.5e-8 (the f32 accumulator rounds regardless — recorded
negative result); exact-sliced 36-dot 5.5e-13. Two hazards both
materialised and are guarded here: the algebraic simplifier folds
``(a + C) - C`` and TwoSum identities unless the intermediate is
``optimization_barrier``-ed (the documented dot-merge-simplifier
class), and slices one bit over the exactness budget silently plateau
the error at ~2^-25 (measured with beta=8 at n=256).

Reference bar: FFTW double plans / cuFFT Z2Z as the default precision
(reference: src/fft/fftw_plan_1d.hpp:74-94,
src/gpu_util/gpu_fft_api.hpp:90-148).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

_HIGHEST = jax.lax.Precision.HIGHEST

#: Round-to-integer constant for the f32 round trick: (t + C) - C rounds
#: t to the nearest integer (round-to-nearest-even) for |t| < 2^22.
_C_ROUND = np.float32(1.5 * 2 ** 23)

#: Slice-ladder depth for double-single values (~beta*VALUE_SLICES
#: significant bits below each array's max exponent) and for the f64
#: matrices. Partial dots beyond ORDER_MAX are dropped — the floor is
#: ~2^(-beta*(ORDER_MAX+1)) ≈ 2e-13 per stage at beta=6, measured
#: 2-4e-14 through the whole backward at 64^3/128^3 on-chip with the
#: deeper (8, 9, 8) ladder; (7, 7, 6) keeps a >100x margin to the
#: 2e-11 contract envelope at 28 instead of 45 partial dots per real
#: contraction. Slices past ORDER_MAX can never pair and are not built.
VALUE_SLICES = 7
MAT_SLICES = 7
ORDER_MAX = 6


def slice_beta(n: int) -> int:
    """Largest slice width keeping partial dots exact in the f32
    accumulator: (beta+1)+(beta+1)+ceil(log2 n) <= 24."""
    logn = max(int(np.ceil(np.log2(max(n, 2)))), 1)
    return max((22 - logn) // 2, 2)


def _two_sum(a, b):
    """Knuth TwoSum: exact a + b = t + e for any f32 pair. The sum is
    barriered so the algebraic simplifier cannot rewrite (a+b)-b -> a
    and erase the error term (measured to plateau the whole scheme at
    ~2.5e-8 when it fires)."""
    t = jax.lax.optimization_barrier(a + b)
    bv = t - a
    av = t - bv
    return t, (a - av) + (b - bv)


def ds_add(ah, al, bh, bl):
    """Double-single addition with renormalisation."""
    sh, se = _two_sum(ah, bh)
    lo = se + (al + bl)
    return _two_sum(sh, lo)


def ds_neg(h, l):
    return -h, -l


def _round_to_grid(x, inv_sc, sc):
    """Round x to the nearest multiple of the power-of-two sc — exactly
    representable when x/sc fits ~22 bits. The add is barriered: the
    simplifier would fold (t + C) - C to t."""
    t = x * inv_sc
    r = jax.lax.optimization_barrier(t + _C_ROUND) - _C_ROUND
    return r * sc


def ds_slices(hi, lo, beta: int, s: int = VALUE_SLICES):
    """Slice a double-single array into ``s`` beta-bit limbs on
    PER-ROW power-of-two ladders (anchored at each contraction row's
    max exponent). Each limb is exactly representable and partial-dot
    exactness only needs slice widths, not a shared anchor, so per-row
    anchors are free — and essential: a forward xy-DFT concentrates the
    grid's energy into few modes, and a GLOBAL anchor drops every
    element more than ~beta*s bits below the array max off the ladder
    (measured: the composed forward pipeline plateaued at 2.5e-8 with a
    global anchor while every isolated stage sat at 1e-14). Residual
    exposure is the WITHIN-row dynamic range only
    (docs/precision.md)."""
    mx = jnp.max(jnp.abs(hi), axis=-1, keepdims=True)
    # power-of-two anchor in [2*mx, 4*mx) by EXPONENT BIT extraction —
    # exp2/log2 are approximate vector transcendentals on the TPU VPU,
    # and an anchor that is not exactly a power of two makes every
    # slice inexact (measured: a data-dependent 7e-9 floor, invariant
    # under ladder depth, on cancellation-heavy forward grids)
    bits = jax.lax.bitcast_convert_type(
        jnp.maximum(mx, np.float32(1e-30)).astype(jnp.float32), jnp.int32)
    # Lower exponent clamp: an all-zero row (the r2c sin matrix
    # guarantees one; so do pad rows) would anchor at ~2^-98, whose
    # deepest inverse scale 2^(98+beta*s) OVERFLOWS f32 and turns the
    # row into 0*inf = NaN. The bound must track the LADDER DEPTH:
    # log2(inv_deepest) = 125 - expo + beta*s <= 126, i.e.
    # expo >= beta*s - 1 — a fixed 64 was sized for beta=6 and
    # overflowed again at the beta=10 short axes the randomized sweep
    # found (NaN only on all-zero rows). Real rows anchored above the
    # clamp are unaffected; tinier ones still slice ~70 bits down.
    expo_min = max(64, beta * s - 1)
    expo = jnp.clip((bits >> 23) & 0xFF, expo_min, 250)
    e0 = jax.lax.bitcast_convert_type((expo + 2) << 23, jnp.float32)
    e0 = jax.lax.optimization_barrier(e0)
    inv0 = 1.0 / e0  # exact: e0 is a power of two
    out = []
    rh, rl = hi, lo
    for i in range(s):
        sc = e0 * np.float32(2.0 ** (-beta * (i + 1)))
        inv = inv0 * np.float32(2.0 ** (beta * (i + 1)))
        q = _round_to_grid(rh, inv, sc)
        rh = rh - q          # exact: q carries rh's top bits
        rh, rl = _two_sum(rh, rl)
        out.append(q)
    return out


def mat_slices_host(m64: np.ndarray, beta: int,
                    s: int = MAT_SLICES) -> tuple:
    """Slice an f64 matrix into beta-bit f32 limbs at plan time (host
    f64 arithmetic — exact)."""
    out = []
    r = np.asarray(m64, np.float64).copy()
    mx = float(np.max(np.abs(r)))
    e0 = 2.0 ** (np.floor(np.log2(mx)) + 1) if mx > 0 else 1.0
    for i in range(s):
        sc = e0 * 2.0 ** (-beta * (i + 1))
        q = np.round(r / sc) * sc
        out.append(np.ascontiguousarray(q.astype(np.float32)))
        r -= q
    return tuple(out)


def _dot(a, c):
    return jax.lax.dot_general(a, jnp.asarray(c),
                               (((a.ndim - 1,), (0,)), ((), ())),
                               precision=_HIGHEST)


def ozaki_dot_last(vslices, mslices, order_max: int = ORDER_MAX):
    """(..., K) x (K, M) contraction over exact slice pairs: partial
    dots of combined order i+j <= order_max, combined descending with
    TwoSum so every bit lands in (hi, lo)."""
    sh = sl = None
    for o in range(order_max + 1):
        for i in range(min(o + 1, len(vslices))):
            j = o - i
            if j >= len(mslices):
                continue
            p = _dot(vslices[i], mslices[j])
            if sh is None:
                sh, sl = p, jnp.zeros_like(p)
            else:
                sh, e = _two_sum(sh, p)
                sl = sl + e
    return sh, sl


@dataclasses.dataclass(frozen=True)
class DSMats:
    """Plan-time sliced complex DFT matrix (f64 source)."""

    n: int
    beta: int
    cr: tuple  # f32 slices of the real part
    ci: tuple  # f32 slices of the imaginary part


@functools.lru_cache(maxsize=32)
def ds_c2c_mats(n: int, sign: int, scale: float = 1.0) -> DSMats:
    """Sliced matrices for a complex length-``n`` DFT in f64, ``scale``
    folded in before slicing (sign convention as ops.dft.c2c_mats:
    BACKWARD = unnormalised inverse)."""
    from .dft import BACKWARD
    s = +1 if sign == BACKWARD else -1
    k = np.arange(n)
    ang = s * 2 * np.pi * np.outer(k, k) / n
    beta = slice_beta(n)
    return DSMats(n, beta,
                  mat_slices_host(np.cos(ang) * scale, beta),
                  mat_slices_host(np.sin(ang) * scale, beta))


@functools.lru_cache(maxsize=32)
def ds_r2c_mats(n: int, scale: float = 1.0) -> DSMats:
    """Sliced forward real-to-halfspectrum matrices in f64 (the DS twin
    of ops.dft._rdft_mats): Yr = X @ cr, Yi = X @ ci with the reference
    rfft layout (dim_x_freq = n//2+1)."""
    xf = n // 2 + 1
    ang = 2 * np.pi * np.outer(np.arange(n), np.arange(xf)) / n
    beta = slice_beta(n)
    return DSMats(n, beta, mat_slices_host(np.cos(ang) * scale, beta),
                  mat_slices_host(-np.sin(ang) * scale, beta))


@functools.lru_cache(maxsize=32)
def ds_c2r_mats(n: int, scale: float = 1.0) -> DSMats:
    """Sliced halfspectrum-to-real matrices in f64 (DS twin of
    ops.dft._irdft_mats): x = Yr @ cr + Yi @ ci, hermitian doubling
    folded into the matrices (w = 1 on self-conjugate bins, 2
    otherwise) — no complex op and no XLA C2R involved."""
    xf = n // 2 + 1
    k = np.arange(xf)
    w = np.full(xf, 2.0)
    w[0] = 1.0
    if n % 2 == 0:
        w[-1] = 1.0
    ang = 2 * np.pi * np.outer(k, np.arange(n)) / n
    beta = slice_beta(n)
    return DSMats(n, beta,
                  mat_slices_host(w[:, None] * np.cos(ang) * scale, beta),
                  mat_slices_host(w[:, None] * -np.sin(ang) * scale, beta))


def ds_rdft_last(xh, xl, m: DSMats):
    """Real forward DFT along the minor axis on a double-single channel
    -> planar half-spectrum ds channels (two exact-sliced contractions —
    half the dots of the complex form)."""
    vs = ds_slices(xh, xl, m.beta)
    yr = ozaki_dot_last(vs, m.cr)
    yi = ozaki_dot_last(vs, m.ci)
    return (*yr, *yi)


def ds_irdft_last(rh, rl, ih, il, m: DSMats):
    """Planar half-spectrum ds channels -> real inverse along the minor
    axis: x = Yr @ cr + Yi @ ci with a double-single combine."""
    vr = ds_slices(rh, rl, m.beta)
    vi = ds_slices(ih, il, m.beta)
    return ds_add(*ozaki_dot_last(vr, m.cr), *ozaki_dot_last(vi, m.ci))


def ds_cdft_last(rh, rl, ih, il, m: DSMats):
    """Complex DFT along the minor axis on double-single planar
    channels: four exact-sliced real contractions plus double-single
    complex combines. Returns (yrh, yrl, yih, yil)."""
    vsr = ds_slices(rh, rl, m.beta)
    vsi = ds_slices(ih, il, m.beta)
    p_rr = ozaki_dot_last(vsr, m.cr)
    p_ii = ozaki_dot_last(vsi, m.ci)
    p_ri = ozaki_dot_last(vsr, m.ci)
    p_ir = ozaki_dot_last(vsi, m.cr)
    yrh, yrl = ds_add(*p_rr, *ds_neg(*p_ii))
    yih, yil = ds_add(*p_ri, *p_ir)
    return yrh, yrl, yih, yil


def split_host_f64(x64: np.ndarray):
    """Host f64 -> (hi, lo) f32 pair (exact: lo = x - f32(x))."""
    hi = x64.astype(np.float32)
    lo = (x64 - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def combine_host_f64(hi, lo) -> np.ndarray:
    return np.asarray(hi, np.float64) + np.asarray(lo, np.float64)

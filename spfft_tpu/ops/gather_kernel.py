"""Pallas TPU kernel for monotone gathers — the sparse compression hot path.

The decompress/compress stages move millions of sparse values between the
user's value array and the packed stick array (reference:
src/compression/compression_host.hpp, compression_gpu kernels). XLA lowers
arbitrary-index gathers on TPU to near-serial element loads (~80 ms for 13M
elements on v5e — measured), two orders of magnitude off HBM bandwidth.

When the user's value order is stick-major and z-ascending — the layout the
reference itself recommends for performance (docs/source/details.rst "Data
Distribution") and the natural output of index generators — both directions
become *monotone* gathers: ``out[j] = src[idx[j]] * mask[j]`` with ``idx``
non-decreasing. Monotonicity bounds the source span of any 1024-slot output
tile, so a tile's sources fit in VMEM and the gather decomposes into

  1. a contiguous DMA of the span rows (double-buffered across grid steps),
  2. K in-register row gathers via Mosaic's ``dynamic_gather``
     (``take_along_axis`` along lanes, indices < 128),
  3. a select-accumulate over the K candidate rows.

Tables (span start row, lane/row selectors, validity mask) are precomputed on
host at plan time. Non-monotone value orders fall back to the XLA gather path
(plan.py decides).

Data is planar (separate real/imag (rows, 128) arrays): the TPU lane
dimension must be the innermost 128 and complex dtypes cannot cross the
pallas boundary.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_SUB = 8
TILE_LANE = 128
TILE = TILE_SUB * TILE_LANE  # output slots per grid step

#: Fall back to the XLA gather when a tile's source span exceeds this many
#: 128-element rows (pathologically gappy index sets; VMEM scratch is
#: 2 buffers x 2 channels x K x 128 x 4B).
MAX_SPAN_ROWS = 64


@dataclasses.dataclass(frozen=True)
class MonotoneGatherTables:
    """Plan-time tables for one monotone gather direction."""

    row0: np.ndarray      # (G,) int32 — first source row of each tile's span
    lane_sel: np.ndarray  # (G, 8, 128) int32 in [0, 128)
    row_sel: np.ndarray   # (G, 8, 128) int32 in [0, K)
    mask: np.ndarray      # (G, 8, 128) float32 — 0 for invalid slots
    num_out: int          # valid output slots (<= G * TILE)
    src_rows: int         # M: padded source array rows
    span_rows: int        # K


def build_monotone_gather_tables(idx: np.ndarray, valid: np.ndarray,
                                 num_src: int):
    """Build tables for ``out[j] = src[idx[j]] * valid[j]``.

    Args:
      idx: (L,) non-decreasing source indices (any value where invalid).
      valid: (L,) bool.
      num_src: size of the source array.
    Returns:
      MonotoneGatherTables, or None if the monotone-span precondition fails
      (span of some tile exceeds MAX_SPAN_ROWS).
    """
    L = int(idx.shape[0])
    if L == 0:
        return None
    idx = np.asarray(idx, np.int64)
    if (np.diff(idx) < 0).any():
        return None
    G = -(-L // TILE)
    pad = G * TILE - L
    idx_p = np.concatenate([idx, np.full(pad, idx[-1], np.int64)])
    valid_p = np.concatenate([np.asarray(valid, bool),
                              np.zeros(pad, bool)])
    tiles = idx_p.reshape(G, TILE)
    row0 = (tiles[:, 0] // TILE_LANE).astype(np.int32)
    rel = tiles - row0[:, None].astype(np.int64) * TILE_LANE
    span = int(rel.max()) // TILE_LANE + 1
    if span > MAX_SPAN_ROWS:
        return None
    lane_sel = (rel % TILE_LANE).astype(np.int32)
    row_sel = (rel // TILE_LANE).astype(np.int32)
    # Cover the whole source array, not just the last referenced span: the
    # planar source is built by zero-PADDING the (num_src,) array to
    # src_rows * 128, which requires src_rows * 128 >= num_src even when the
    # trailing source region is never referenced.
    src_rows = max(int(row0.max()) + span, -(-int(num_src) // TILE_LANE))
    return MonotoneGatherTables(
        row0=row0,
        lane_sel=lane_sel.reshape(G, TILE_SUB, TILE_LANE),
        row_sel=row_sel.reshape(G, TILE_SUB, TILE_LANE),
        mask=valid_p.astype(np.float32).reshape(G, TILE_SUB, TILE_LANE),
        num_out=L, src_rows=src_rows, span_rows=span)


def _kernel(K: int, row0_ref, lane_ref, rowsel_ref, mask_ref,
            re_hbm, im_hbm, out_re_ref, out_im_ref, sc, sem):
    g = pl.program_id(0)
    n_g = pl.num_programs(0)

    def dma(gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(gg):
        slot = jax.lax.rem(jnp.asarray(gg, jnp.int32), jnp.int32(2))
        dma(gg, slot, 0, re_hbm).start()
        dma(gg, slot, 1, im_hbm).start()

    @pl.when(g == 0)
    def _():
        start(0)

    @pl.when(g + 1 < n_g)
    def _():
        start(g + 1)

    slot = jax.lax.rem(jnp.asarray(g, jnp.int32), jnp.int32(2))
    dma(g, slot, 0, re_hbm).wait()
    dma(g, slot, 1, im_hbm).wait()

    lane = lane_ref[0]
    row = rowsel_ref[0]
    acc_re = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
    acc_im = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
    for k in range(K):
        sel = row == k
        src_re = jnp.broadcast_to(sc[slot, 0, k][None, :],
                                  (TILE_SUB, TILE_LANE))
        src_im = jnp.broadcast_to(sc[slot, 1, k][None, :],
                                  (TILE_SUB, TILE_LANE))
        acc_re += jnp.where(sel, jnp.take_along_axis(src_re, lane, axis=1), 0)
        acc_im += jnp.where(sel, jnp.take_along_axis(src_im, lane, axis=1), 0)
    m = mask_ref[0]
    out_re_ref[0] = acc_re * m
    out_im_ref[0] = acc_im * m


@functools.partial(jax.jit, static_argnames=("span_rows", "src_rows",
                                             "interpret"))
def monotone_gather(re, im, row0, lane_sel, row_sel, mask, *,
                    span_rows: int, src_rows: int, interpret: bool = False):
    """Run the monotone gather.

    Args:
      re, im: (src_rows, 128) float32 planar source.
      row0/lane_sel/row_sel/mask: device tables (see
        build_monotone_gather_tables).
    Returns:
      (out_re, out_im): each (G, 8, 128) float32.
    """
    G = row0.shape[0]
    K = span_rows
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(G,),
        in_specs=[
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda g, r: (g, 0, 0)),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda g, r: (g, 0, 0)),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda g, r: (g, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda g, r: (g, 0, 0)),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE), lambda g, r: (g, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 2, K, TILE_LANE), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out_shape = (jax.ShapeDtypeStruct((G, TILE_SUB, TILE_LANE), jnp.float32),
                 jax.ShapeDtypeStruct((G, TILE_SUB, TILE_LANE), jnp.float32))
    return pl.pallas_call(
        functools.partial(_kernel, K), out_shape=out_shape,
        grid_spec=grid_spec, interpret=interpret,
    )(row0, lane_sel, row_sel, mask, re, im)


def planar_from_interleaved(values_il, src_rows: int):
    """(N, 2) interleaved -> two zero-padded (src_rows, 128) planar arrays."""
    n = values_il.shape[0]
    pad = src_rows * TILE_LANE - n
    re = jnp.pad(values_il[:, 0], (0, pad)).reshape(src_rows, TILE_LANE)
    im = jnp.pad(values_il[:, 1], (0, pad)).reshape(src_rows, TILE_LANE)
    return re, im


def interleaved_from_planar(out_re, out_im, num_out: int):
    """Kernel outputs -> (num_out, 2) interleaved."""
    re = out_re.reshape(-1)[:num_out]
    im = out_im.reshape(-1)[:num_out]
    return jnp.stack([re, im], axis=-1)

"""Pallas TPU kernel for windowed gathers — the sparse compression hot path.

The decompress/compress stages move millions of sparse values between the
user's value array and the packed stick array (reference:
src/compression/compression_host.hpp, compression_gpu kernels). XLA lowers
arbitrary-index gathers on TPU to near-serial element loads (~80 ms for 13M
elements on v5e — measured), two orders of magnitude off HBM bandwidth.

The kernel computes ``out[j] = src[idx[j]] * mask[j]`` for an *arbitrary*
plan-time-constant index list by decomposing it into

  1. contiguous DMAs of K-row source windows (double-buffered across grid
     steps),
  2. K in-register row gathers via Mosaic's ``dynamic_gather``
     (``take_along_axis`` along lanes, indices < 128),
  3. a select-accumulate over the K candidate rows.

Each 1024-slot output tile owns one *chunk* per distinct K-row source
window its indices touch; a tile's chunks are consecutive grid steps that
accumulate into the same output block (the standard Pallas revisiting-
reduction pattern). When the value order is stick-major and z-ascending —
the layout the reference itself recommends for performance
(docs/source/details.rst "Data Distribution") — indices are monotone, every
tile touches the minimal number of windows, and the decomposition is
optimal. Locally-coherent but unsorted orders (shuffled sticks, z-sorted
within each) just emit more chunks and stay on the fast path; a truly
random order would blow the chunk count up, so the builder falls back
(returns None) when the modelled DMA traffic exceeds the measured XLA
gather cost. K is chosen per plan from the window-count distribution.

Per-chunk selector tables are precomputed on host at plan time and packed
into one int32 word per output slot: lane (bits 0-6), window row (bits 7-19),
validity (bit 20). Data is planar (separate real/imag (rows, 128) arrays):
the TPU lane dimension must be the innermost 128 and complex dtypes cannot
cross the pallas boundary.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_SUB = 8
TILE_LANE = 128
TILE = TILE_SUB * TILE_LANE  # output slots per tile

#: Candidate source-window heights (rows) for the chunk decomposition; the
#: builder picks the one minimising modelled DMA + compute cost.
K_CANDIDATES = (8, 16, 32, 64, 128)

_LANE_BITS = 7
_ROW_SHIFT = _LANE_BITS
_VALID_SHIFT = 20
_ROW_MASK = (1 << (_VALID_SHIFT - _ROW_SHIFT)) - 1

# Wide-kernel packed-word layout (int16): lane 0-6, row-in-subwindow 7-11,
# valid 12. kp_rows <= 32 so the row field needs only 5 bits.
_W_VALID_SHIFT = 12
_W_ROW_MASK = (1 << (_W_VALID_SHIFT - _ROW_SHIFT)) - 1


#: Max chunks per kernel launch: the three scalar-prefetch tables live in
#: SMEM (1 MB on v5e); 3 arrays x 4 B x 70k = 840 KB leaves headroom for
#: spills. Larger chunk counts are split into tile-aligned segments.
SEG_CHUNK_LIMIT = 70_000


@dataclasses.dataclass(frozen=True)
class MonotoneGatherTables:
    """Plan-time tables for one windowed gather direction."""

    row0: np.ndarray      # (C,) int32 — first source row of each chunk's DMA
    out_tile: np.ndarray  # (C,) int32 — output tile the chunk accumulates into
    first: np.ndarray     # (C,) int32 — 1 on a tile's first chunk
    packed: np.ndarray    # (C, 8, 128) int32 — lane | row << 7 | valid << 20
    num_out: int          # valid output slots (<= num_tiles * TILE)
    num_tiles: int        # G: output tiles
    src_rows: int         # M: padded source array rows
    span_rows: int        # K: DMA window height
    segs: tuple = ()      # ((c0, c1, t0, t1), ...) — tile-aligned launch
                          # segments keeping scalar-prefetch SMEM in budget;
                          # empty = single launch


def _tile_aligned_segments(first: np.ndarray, out_tile: np.ndarray,
                           num_tiles: int, limit: int) -> tuple:
    """Split chunk range [0, C) into segments of <= ``limit`` chunks whose
    boundaries land on a tile's FIRST chunk (so no output tile spans two
    launches and the revisiting accumulation stays within one call)."""
    C = int(first.shape[0])
    if C <= limit:
        return ()
    starts = np.flatnonzero(first == 1)
    segs = []
    c0 = 0
    while c0 < C:
        if C - c0 <= limit:
            c1 = C
        else:
            cand = starts[(starts > c0) & (starts <= c0 + limit)]
            if len(cand) == 0:  # one tile alone exceeds the limit: give up
                return None
            c1 = int(cand[-1])
        t0 = int(out_tile[c0])
        t1 = int(out_tile[c1 - 1]) + 1  # c1 > c0 always: cand > c0 or C
        segs.append((c0, c1, t0, t1))
        c0 = c1
    assert segs[-1][3] == num_tiles
    return tuple(segs)


#: Fallback ceiling: the kernel's cost scales with the chunk count C while
#: the XLA gather's scales with the output size (~G tiles); the measured
#: kernel advantage at C ≈ G is ~6x (scripts/sweep.py, 256^3 on v5e), so
#: past C ≈ 6G the decomposition stops paying for itself.
_CHUNK_BLOWUP_LIMIT = 6


def build_monotone_gather_tables(idx: np.ndarray, valid: np.ndarray,
                                 num_src: int, k_rows: int = 0,
                                 allow_segments: bool = True):
    """Build tables for ``out[j] = src[idx[j]] * valid[j]``.

    Args:
      idx: (L,) source indices, in any order (any in-range value where
        invalid). Monotone (non-decreasing) indices give the minimal chunk
        count; arbitrary order works as long as each 1024-slot output tile
        touches a bounded set of K-row source windows.
      valid: (L,) bool.
      num_src: size of the source array.
      k_rows: force the DMA window height (0 = choose from the window-count
        distribution).
      allow_segments: past SEG_CHUNK_LIMIT chunks the gather runs as
        several tile-aligned launches (scalar-prefetch SMEM budget);
        ``False`` declines instead — the stacked-uniform-table layout of
        distributed plans cannot segment per shard.
    Returns:
      MonotoneGatherTables, or None if ``idx`` is empty or so disordered
      that the chunk decomposition would be slower than the XLA gather
      (caller falls back).
    """
    L = int(idx.shape[0])
    if L == 0:
        return None
    idx = np.asarray(idx, np.int64)
    G = -(-L // TILE)
    pad = G * TILE - L
    idx_p = np.concatenate([idx, np.full(pad, idx[-1], np.int64)])
    valid_p = np.concatenate([np.asarray(valid, bool), np.zeros(pad, bool)])
    tiles = idx_p.reshape(G, TILE)
    rows = tiles // TILE_LANE                      # (G, TILE)
    rows_sorted = np.sort(rows, axis=1)            # per-tile, for windowing

    def chunks_per_tile(k):
        win = rows_sorted // k
        return 1 + (np.diff(win, axis=1) != 0).sum(axis=1)

    if k_rows:
        K = int(k_rows)
    else:
        # cost ~ chunks * (K DMA rows + fixed per-step overhead). The
        # overhead term is large: each grid step costs ~400-500 ns of
        # scalar bookkeeping + DMA issue regardless of K (measured at
        # 256^3: K=8 pair 30.7 ms vs K=32 23.9 ms) — weight it like ~64
        # DMA rows so the chooser trades window waste against step count.
        K = min(K_CANDIDATES,
                key=lambda k: int(chunks_per_tile(k).sum()) * (k + 64))
    win_sorted = rows_sorted // K
    # one chunk per (tile, distinct window); windows ascend within a tile so
    # a tile's chunks are consecutive grid steps (the revisiting pattern)
    new_win = np.concatenate([np.ones((G, 1), bool),
                              np.diff(win_sorted, axis=1) != 0], axis=1)
    chunks_t = new_win.sum(axis=1).astype(np.int64)
    C = int(chunks_t.sum())
    if C > _CHUNK_BLOWUP_LIMIT * G + 64:
        return None  # too disordered: XLA gather is the better program
    tile_of = np.repeat(np.arange(G, dtype=np.int64), chunks_t)
    win_ids = win_sorted[new_win].astype(np.int64)  # (C,) window per chunk
    win32 = (rows // K).astype(np.int32)  # int32 up front: the (C, TILE)
    rows32 = rows.astype(np.int32)  # temporaries are the peak allocation
    wc = win_ids[:, None].astype(np.int32)
    in_win = win32[tile_of] == wc                        # (C, TILE)
    row_in = np.clip(rows32[tile_of] - wc * K, 0, K - 1)
    m = in_win & valid_p.reshape(G, TILE)[tile_of]
    lanes = (tiles % TILE_LANE).astype(np.int32)  # (G, TILE), not (C, TILE)
    packed = (lanes[tile_of]
              | (row_in << _ROW_SHIFT)
              | (m.astype(np.int32) << _VALID_SHIFT))
    row0 = (win_ids * K).astype(np.int32)
    # first chunk of each tile initialises its output block
    first = np.zeros(C, np.int32)
    first[np.cumsum(chunks_t) - chunks_t] = 1
    # Cover the whole source array, not just the last referenced span: the
    # planar source is built by zero-PADDING the (num_src,) array to
    # src_rows * 128, which requires src_rows * 128 >= num_src even when the
    # trailing source region is never referenced.
    src_rows = max(int(row0.max()) + K, -(-int(num_src) // TILE_LANE))
    out_tile32 = tile_of.astype(np.int32)
    segs = _tile_aligned_segments(first, out_tile32, G, SEG_CHUNK_LIMIT)
    if segs is None or (segs and not allow_segments):
        return None
    return MonotoneGatherTables(
        row0=row0,
        out_tile=out_tile32,
        first=first,
        packed=packed.reshape(C, TILE_SUB, TILE_LANE),
        num_out=L, num_tiles=G, src_rows=src_rows, span_rows=K,
        segs=segs)


#: Wide-kernel geometry: output tiles processed per grid step. The narrow
#: kernel's cost is per-grid-step overhead (~450-500 ns/step measured at
#: 256^3 — BENCHMARKS.md roofline); amortising it over P tiles with ONE
#: K-row DMA window and per-tile sub-windows cuts the per-slot cost ~10-20x
#: (scripts/probe_wide_kernel.py: 196 ns/step for 8 tiles vs ~470 ns for 1).
WIDE_P = 8

#: Sub-window height candidates (rows selected per tile). Chosen from the
#: per-tile row-spread distribution: monotone decompress spans <= 9 rows
#: per 1024-slot tile (indices advance by <= 1 per slot), sparse compress
#: spans ~ 8/fill_fraction.
WIDE_KP_CANDIDATES = (8, 12, 16, 24, 32)

#: Max chunks per wide launch. NOT an SMEM budget: the TPU compile helper
#: deterministically crashes (subprocess exit 1) compiling this kernel with
#: a grid of ~2000+ steps — C=1961 compiles, C=2000 does not, across every
#: (P, kp, K) probed (scripts/probe_wide_sweep.py bisect, 2026-07-30) —
#: while the narrow kernel compiles at C=17k+. 1536 leaves margin in case
#: the threshold shifts with kernel-body size; larger tables run as
#: multiple tile-aligned launches (one extra launch per ~12.6M output
#: slots — negligible next to the per-step win).
WIDE_SEG_CHUNK_LIMIT = 1536

#: Wide fallback ceiling, in chunks per super-tile: per-slot unit cost of
#: the wide step is C*(P*kp + 96)/(G_s*P*TILE); the XLA gather breaks even
#: with the narrow kernel at ~6 chunks/tile * (K+64) units (see
#: _CHUNK_BLOWUP_LIMIT), which translates to ~16 wide chunks per super-tile
#: at P=8, kp=16. Past that the decomposition loses to the XLA gather.
_WIDE_BLOWUP_LIMIT = 16


@dataclasses.dataclass(frozen=True)
class WideGatherTables:
    """Plan-time tables for one wide windowed-gather direction.

    Each chunk is one grid step covering a SUPER-TILE of ``p_tiles``
    1024-slot output tiles: one K-row DMA window shared by the step, and
    per-tile kp-row sub-windows at byte-packed offsets. Chunks of one
    super-tile are consecutive grid steps (revisiting accumulation)."""

    row0: np.ndarray      # (C,) int32 — DMA window start row
    sub: np.ndarray       # (C, P//4) int32 — per-tile sub-window offsets,
                          # byte-packed little-endian, relative to row0
    out_tile: np.ndarray  # (C,) int32 — output super-tile index
    first: np.ndarray     # (C,) int32 — 1 on a super-tile's first chunk
    packed: np.ndarray    # (C, P*8, 128) int16 — lane | row-in-sub << 7
                          #  | valid << 12 (kp <= 32 keeps row in 5 bits;
                          # int16 halves table upload + streaming traffic,
                          # and P*8 sublanes align to the 16-row int16 tile)
    num_out: int          # valid output slots
    num_super: int        # G_s: super-tiles
    src_rows: int         # padded source rows
    span_rows: int        # K: DMA window height
    kp_rows: int          # kp: per-tile sub-window height
    p_tiles: int          # P: tiles per super-tile
    segs: tuple = ()      # ((c0, c1, t0, t1), ...) in super-tile units


def build_wide_gather_tables(idx: np.ndarray, valid: np.ndarray,
                             num_src: int, *, p_tiles: int = WIDE_P,
                             kp_rows: int = 0, k_rows: int = 0,
                             allow_segments: bool = True):
    """Build wide-kernel tables for ``out[j] = src[idx[j]] * valid[j]``.

    Same contract as :func:`build_monotone_gather_tables` (any order works;
    monotone is optimal; returns None on empty input or when the cover
    would be slower than the XLA gather), but covers ``p_tiles`` output
    tiles per chunk. ``kp_rows``/``k_rows`` force the sub-window/DMA-window
    heights (0 = choose from the data) — the distributed builder forces
    common values across shards so the SPMD program is uniform.
    """
    L = int(idx.shape[0])
    if L == 0:
        return None
    P = int(p_tiles)
    if P % 4 != 0:
        raise ValueError("p_tiles must be a multiple of 4 (byte packing)")
    if kp_rows and not 0 < int(kp_rows) <= _W_ROW_MASK + 1:
        raise ValueError(
            f"kp_rows must be in [1, {_W_ROW_MASK + 1}] — the packed "
            f"word's row field is {_W_VALID_SHIFT - _ROW_SHIFT} bits")
    native = _native_wide_tables(idx, valid, num_src, P, int(kp_rows),
                                 int(k_rows), allow_segments)
    if native is not None:
        return None if native == "blowup" else native
    SUPER = P * TILE
    idx = np.asarray(idx, np.int64)
    G_s = -(-L // SUPER)
    pad = G_s * SUPER - L
    idx_p = np.concatenate([idx, np.full(pad, idx[-1], np.int64)])
    valid_p = np.concatenate([np.asarray(valid, bool), np.zeros(pad, bool)])
    rows = (idx_p // TILE_LANE).astype(np.int32).reshape(G_s, P, TILE)
    lanes = (idx_p % TILE_LANE).astype(np.int32).reshape(G_s, P, TILE)
    vmask = valid_p.reshape(G_s, P, TILE)

    BIG = np.int32(2 ** 30)
    rmin = np.where(vmask, rows, BIG).min(axis=2)         # (G_s, P)
    rmax = np.where(vmask, rows, -1).max(axis=2)
    has = vmask.any(axis=2)
    spread = np.where(has, rmax - np.where(has, rmin, 0) + 1, 1)

    if kp_rows:
        kp = int(kp_rows)
    else:
        # Cost model: chunks(kp) ~ sum over super-tiles of the max per-tile
        # round count ceil(spread/kp); per-step cost ~ P*kp select rows plus
        # ~64 rows-equivalent of fixed overhead (DMA issue + scalar work —
        # calibrated against scripts/probe_wide_vs_narrow.py, where the
        # coverage-percentile chooser picked kp=32 for the 256^3 compress
        # direction and lost 2x per step to a barely-smaller chunk count).
        def cost(kp_c):
            rounds = -(-spread // kp_c)           # (G_s, P) ceil
            c_est = int(rounds.max(axis=1).sum()) if G_s else 1
            return c_est * (P * kp_c + 64)
        kp = min(WIDE_KP_CANDIDATES, key=cost)
    if k_rows:
        K = int(k_rows)
    else:
        base = np.where(has, rmin, BIG)
        b0 = base.min(axis=1)
        bspan = np.where(has, base - b0[:, None], 0).max(axis=1)
        q = int(np.quantile(bspan, 0.99)) if bspan.size else 0
        K = max(kp + 8, min(512, kp + 248,
                            int(np.ceil((q + kp) / 8.0) * 8)))
    if K - kp > 255:
        K = kp + 248  # sub-window offsets are byte-packed
    # Clamp window starts so every DMA window lies inside the EXACT source
    # extent ceil(num_src/128): src_rows then equals the exact extent and
    # the runtime's source zero-padding pass (a 53 MB copy per direction at
    # 256^3 — probe_r4_hlo) disappears. A clamped round covers fewer tiles
    # and simply takes another round; tiny sources (r_exact < K) keep the
    # padded form.
    r_exact = -(-int(num_src) // TILE_LANE)
    r_clamp = np.int32(r_exact - K) if (num_src > 0 and r_exact >= K) \
        else None

    # Multi-round cover: each round emits one chunk per still-active
    # super-tile. The minimum-base tile is always inside the window, so
    # every round covers at least kp rows of it — guaranteed progress.
    uncovered = vmask.copy()
    r0s, subs, packs, sts, rds = [], [], [], [], []
    rounds = 0
    total_chunks = 0
    while True:
        active = uncovered.any(axis=(1, 2))
        if rounds == 0:
            # every super-tile needs >= 1 chunk so its output block is
            # initialised even when it has no valid slots at all
            active = np.ones(G_s, bool)
        if not active.any():
            break
        a = np.flatnonzero(active)
        ar, av, al = rows[a], uncovered[a], lanes[a]
        base = np.where(av, ar, BIG).min(axis=2)          # (n_a, P)
        hasu = av.any(axis=2)
        r0 = np.where(hasu, base, BIG).min(axis=1)
        r0 = np.where(r0 == BIG, 0, r0).astype(np.int32)
        if r_clamp is not None:
            r0 = np.minimum(r0, r_clamp)
        # A tile participates if any of its rows fall inside the DMA
        # window; its kp-row sub-window saturates at the window top so
        # tail rows stay coverable when r0 is clamped (see r_clamp).
        inwin = hasu & (base <= r0[:, None] + (K - 1))
        basec = np.where(inwin, np.minimum(base, r0[:, None] + (K - kp)),
                         r0[:, None])
        cover = av & inwin[:, :, None] \
            & (ar >= basec[:, :, None]) & (ar < basec[:, :, None] + kp)
        sub_rel = np.clip(basec - r0[:, None], 0, K - kp).astype(np.int32)
        rin = np.clip(ar - basec[:, :, None], 0, kp - 1)
        packed = (al | (rin << _ROW_SHIFT)
                  | (cover.astype(np.int32) << _W_VALID_SHIFT))
        r0s.append(r0)
        subs.append(sub_rel)
        packs.append(packed.astype(np.int16))
        sts.append(a.astype(np.int32))
        rds.append(np.full(len(a), rounds, np.int32))
        uncovered[a] = av & ~cover
        rounds += 1
        total_chunks += len(a)
        if total_chunks > _WIDE_BLOWUP_LIMIT * G_s + 64:
            return None  # too disordered: the cover loses to XLA

    st_all = np.concatenate(sts)
    order = np.lexsort((np.concatenate(rds), st_all))
    st_o = st_all[order]
    row0 = np.concatenate(r0s)[order]
    sub_o = np.concatenate(subs)[order]                   # (C, P)
    packed_o = np.concatenate(packs)[order]               # (C, P, TILE)
    C = int(st_o.shape[0])
    first = np.zeros(C, np.int32)
    first[0] = 1
    first[1:] = (st_o[1:] != st_o[:-1]).astype(np.int32)

    words = np.zeros((C, P // 4), np.int32)
    for j in range(P):
        words[:, j // 4] |= sub_o[:, j].astype(np.int32) << (8 * (j % 4))

    src_rows = max(int(row0.max()) + K, -(-int(num_src) // TILE_LANE))
    segs = _tile_aligned_segments(first, st_o, G_s, WIDE_SEG_CHUNK_LIMIT)
    if segs is None or (segs and not allow_segments):
        return None
    return WideGatherTables(
        row0=row0, sub=words, out_tile=st_o, first=first,
        packed=packed_o.reshape(C, P * TILE_SUB, TILE_LANE),
        num_out=L, num_super=G_s, src_rows=src_rows, span_rows=K,
        kp_rows=kp, p_tiles=P, segs=segs)


def _native_wide_tables(idx, valid, num_src, P, kp_rows, k_rows,
                        allow_segments):
    """Run the C++ cover (native/planner.cpp) when available: identical
    tables ~40x faster than the NumPy multi-round cover (which remains the
    executable specification, the fallback, and the parity oracle —
    tests/test_native_planner.py compares both table sets element-wise).
    Returns a WideGatherTables, the string "blowup" (caller falls back to
    the narrow kernel / XLA exactly as the NumPy builder's None), or None
    when the native library is unavailable."""
    from .. import native

    try:
        out = native.wide_gather_tables(
            np.asarray(idx, np.int64),
            np.asarray(valid, bool), num_src=int(num_src), p_tiles=P,
            kp_rows=kp_rows, k_rows=k_rows)
    except native.WideCoverBlowup:
        return "blowup"
    if out is None:
        return None
    row0, sub, out_tile, first, packed, kp, K, max_row0 = out
    L = int(np.asarray(idx).shape[0])
    G_s = -(-L // (P * TILE))
    src_rows = max(int(max_row0) + K, -(-int(num_src) // TILE_LANE))
    segs = _tile_aligned_segments(first, out_tile, G_s,
                                  WIDE_SEG_CHUNK_LIMIT)
    if segs is None or (segs and not allow_segments):
        return "blowup"
    return WideGatherTables(
        row0=row0, sub=sub, out_tile=out_tile, first=first, packed=packed,
        num_out=L, num_super=G_s, src_rows=src_rows, span_rows=K,
        kp_rows=kp, p_tiles=P, segs=segs)


def build_best_gather_tables(idx, valid, num_src, allow_segments=True,
                             wide: Optional[bool] = None):
    """The preferred decomposition: wide kernel tables, falling back to the
    narrow single-tile kernel (whose per-tile windows tolerate somewhat
    different disorder patterns), then None (caller uses the XLA gather).
    ``wide=False`` forces narrow (testing)."""
    if wide is not False:
        t = build_wide_gather_tables(idx, valid, num_src,
                                     allow_segments=allow_segments)
        if t is not None:
            return t
    if wide is True:
        return None
    return build_monotone_gather_tables(idx, valid, num_src,
                                        allow_segments=allow_segments)


def compression_gather_inputs(value_indices, num_slots: int,
                              pad_values_to=None):
    """The (idx, valid) pairs for both compression directions.

    Decompress gathers slot <- value (idx = each occupied slot's position
    in the user's value array, forward-filled over unoccupied slots so a
    locally-coherent value order keeps the windows local); compress gathers
    value <- slot (idx = the flat value indices, optionally padded with
    repeats of the last index and valid=False — the padded-value layout of
    distributed shards). Works for ANY value order (duplicates resolve to
    the last occurrence, matching stages.decompress); single source of
    truth for local plan._init_pallas and the distributed per-shard tables.
    """
    from .. import native

    vi = np.asarray(value_indices, np.int64)
    n = len(vi)
    if n and (vi.min() < 0 or vi.max() >= num_slots):
        # the native path rejects these; the NumPy fancy-indexing fallback
        # would silently wrap negatives — fail identically on both
        raise IndexError(f"value index out of range [0, {num_slots})")
    nat = native.compression_inputs(vi, num_slots) if n else None
    if nat is not None:
        dec_idx, occupied = nat
    else:
        occupied = np.zeros(num_slots, bool)
        occupied[vi] = True
        pos = np.zeros(num_slots, np.int64)
        pos[vi] = np.arange(n, dtype=np.int64)  # last occurrence wins
        # forward-fill each unoccupied slot with the nearest occupied slot
        # at or below it (leading gap: the first occupied slot), so idx
        # stays local when the value order is; for sorted vi this reduces
        # to the running occupied count.
        if n:
            filled = np.maximum.accumulate(
                np.where(occupied, np.arange(num_slots, dtype=np.int64),
                         -1))
            filled = np.where(filled < 0, int(np.flatnonzero(occupied)[0]),
                              filled)
            dec_idx = pos[filled]
        else:
            dec_idx = np.zeros(num_slots, np.int64)
    out_n = n if pad_values_to is None else pad_values_to
    cmp_idx = np.zeros(out_n, np.int64)
    if n:
        cmp_idx[:n] = vi
        cmp_idx[n:] = vi[-1]
    cmp_valid = np.arange(out_n) < n
    return (dec_idx, occupied), (cmp_idx, cmp_valid)


def pad_tables_to(t: "MonotoneGatherTables", c_max: int):
    """Pad a table set to ``c_max`` chunks so shape-heterogeneous per-shard
    tables can be stacked into one SPMD-sharded array.

    Padding chunks are no-ops targeting a DUMMY output tile (index
    ``t.num_tiles``): all-zero packed words (valid=0, lane=0, row=0) and
    row0=0 (src_rows >= K always holds, so the DMA window is in range).
    The first padding chunk has first=1 so the dummy tile is initialised,
    never read-modify-written uninitialised. Callers must pass
    ``num_tiles + 1`` to ``monotone_gather`` and slice off the dummy tile
    (the flat real-output prefix is unchanged because the dummy is last).

    Returns (row0, out_tile, first, packed) padded to c_max rows.
    """
    pad = c_max - t.row0.shape[0]
    if pad < 0:
        raise ValueError("c_max smaller than existing chunk count")
    if pad == 0:
        return t.row0, t.out_tile, t.first, t.packed
    row0 = np.concatenate([t.row0, np.zeros(pad, np.int32)])
    out_tile = np.concatenate(
        [t.out_tile, np.full(pad, t.num_tiles, np.int32)])
    first = np.concatenate(
        [t.first, np.ones(1, np.int32), np.zeros(pad - 1, np.int32)])
    packed = np.concatenate(
        [t.packed, np.zeros((pad, TILE_SUB, TILE_LANE), np.int32)])
    return row0, out_tile, first, packed


def _tile_compute_win(K: int, t, win_re, win_im):
    """Per-tile compute on explicit (K, 128) window ARRAYS: decode the
    packed selector words ``t`` (8, 128), gather K candidate rows from
    the window, select-accumulate. Shared with the fused
    compression+DFT kernels (ops.fused_kernel), whose windows are
    computed in VMEM rather than DMA'd."""
    lane = t & (TILE_LANE - 1)
    row = (t >> _ROW_SHIFT) & _ROW_MASK
    m = (t >> _VALID_SHIFT).astype(jnp.float32)
    acc_re = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
    acc_im = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
    for k in range(K):
        sel = row == k
        src_re = jnp.broadcast_to(win_re[k][None, :],
                                  (TILE_SUB, TILE_LANE))
        src_im = jnp.broadcast_to(win_im[k][None, :],
                                  (TILE_SUB, TILE_LANE))
        acc_re += jnp.where(sel, jnp.take_along_axis(src_re, lane, axis=1), 0)
        acc_im += jnp.where(sel, jnp.take_along_axis(src_im, lane, axis=1), 0)
    return acc_re * m, acc_im * m


def _tile_compute(K: int, packed_ref, sc, slot):
    """Shared per-tile compute: decode the packed selector words, gather K
    candidate rows from the VMEM window, select-accumulate."""
    return _tile_compute_win(K, packed_ref[0], sc[slot, 0], sc[slot, 1])


def _kernel(K: int, row0_ref, out_tile_ref, first_ref, packed_ref,
            re_hbm, im_hbm, out_re_ref, out_im_ref, sc, sem):
    g = pl.program_id(0)
    n_g = pl.num_programs(0)

    def dma(gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(gg):
        slot = jax.lax.rem(jnp.asarray(gg, jnp.int32), jnp.int32(2))
        dma(gg, slot, 0, re_hbm).start()
        dma(gg, slot, 1, im_hbm).start()

    @pl.when(g == 0)
    def _():
        start(0)

    @pl.when(g + 1 < n_g)
    def _():
        start(g + 1)

    slot = jax.lax.rem(jnp.asarray(g, jnp.int32), jnp.int32(2))
    dma(g, slot, 0, re_hbm).wait()
    dma(g, slot, 1, im_hbm).wait()

    acc_re, acc_im = _tile_compute(K, packed_ref, sc, slot)

    # Chunks of one output tile are consecutive grid steps mapping to the
    # same out block (revisiting): initialise on the first, accumulate after.
    @pl.when(first_ref[g] == 1)
    def _():
        out_re_ref[0] = acc_re
        out_im_ref[0] = acc_im

    @pl.when(first_ref[g] == 0)
    def _():
        out_re_ref[0] = out_re_ref[0] + acc_re
        out_im_ref[0] = out_im_ref[0] + acc_im


def _kernel_batched(K: int, row0_ref, out_tile_ref, first_ref, packed_ref,
                    re_hbm, im_hbm, out_re_ref, out_im_ref, sc, sem):
    """Batched variant: grid (B, C); batch b gathers from source slab b into
    output slab b through the SAME (batch-invariant) tables. The
    double-buffered DMA pipeline runs across the flattened (b, g) step
    sequence, prefetching across the batch boundary."""
    b = pl.program_id(0)
    g = pl.program_id(1)
    n_b = pl.num_programs(0)
    n_g = pl.num_programs(1)
    step = b * n_g + g

    def dma(bb, gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[bb, pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(bb, gg, slot):
        dma(bb, gg, slot, 0, re_hbm).start()
        dma(bb, gg, slot, 1, im_hbm).start()

    @pl.when(step == 0)
    def _():
        start(0, 0, 0)

    @pl.when(step + 1 < n_b * n_g)
    def _():
        nxt_b = jnp.where(g + 1 < n_g, b, b + 1)
        nxt_g = jnp.where(g + 1 < n_g, g + 1, 0)
        start(nxt_b, nxt_g, jax.lax.rem(step + 1, jnp.int32(2)))

    slot = jax.lax.rem(step, jnp.int32(2))
    dma(b, g, slot, 0, re_hbm).wait()
    dma(b, g, slot, 1, im_hbm).wait()

    acc_re, acc_im = _tile_compute(K, packed_ref, sc, slot)

    @pl.when(first_ref[g] == 1)
    def _():
        out_re_ref[0, 0] = acc_re
        out_im_ref[0, 0] = acc_im

    @pl.when(first_ref[g] == 0)
    def _():
        out_re_ref[0, 0] = out_re_ref[0, 0] + acc_re
        out_im_ref[0, 0] = out_im_ref[0, 0] + acc_im


@functools.partial(jax.jit, static_argnames=("span_rows", "src_rows",
                                             "num_tiles", "interpret",
                                             "segs"))
def monotone_gather(re, im, row0, out_tile, first, packed, *,
                    span_rows: int, src_rows: int, num_tiles: int,
                    interpret: bool = False, segs: tuple = ()):
    """Run the windowed gather.

    Args:
      re, im: (src_rows, 128) float32 planar source — or (B, src_rows, 128)
        for a batch sharing the tables (each batch slab gathered into its
        own output slab).
      row0/out_tile/first/packed: device tables (see
        build_monotone_gather_tables).
      segs: tile-aligned launch segments from the table builder (static);
        each runs as its own pallas_call over its chunk slice and the
        per-segment outputs concatenate along the tile axis.
    Returns:
      (out_re, out_im): each (num_tiles, 8, 128) float32, with a leading B
      when the source was batched.
    """
    if segs:
        outs_re, outs_im = [], []
        for (c0, c1, t0, t1) in segs:
            o_re, o_im = _monotone_gather_call(
                re, im, row0[c0:c1], out_tile[c0:c1] - t0, first[c0:c1],
                packed[c0:c1], span_rows=span_rows, num_tiles=t1 - t0,
                interpret=interpret)
            outs_re.append(o_re)
            outs_im.append(o_im)
        axis = 1 if re.ndim == 3 else 0
        return (jnp.concatenate(outs_re, axis=axis),
                jnp.concatenate(outs_im, axis=axis))
    return _monotone_gather_call(re, im, row0, out_tile, first, packed,
                                 span_rows=span_rows, num_tiles=num_tiles,
                                 interpret=interpret)


def _monotone_gather_call(re, im, row0, out_tile, first, packed, *,
                          span_rows: int, num_tiles: int, interpret: bool,
                          carry=None):
    """One pallas_call over one chunk range (the whole table when
    unsegmented). ``carry`` as in :func:`_wide_gather_call`."""
    C = row0.shape[0]
    K = span_rows
    if carry is not None:
        return _monotone_gather_call_aliased(
            re, im, row0, out_tile, first, packed, span_rows=K,
            num_tiles=num_tiles, interpret=interpret, carry=carry)
    if re.ndim == 3:
        B = re.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # row0, out_tile, first
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, ot, fs: (g, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec((1, 1, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, ot, fs: (b, ot[g], 0, 0)),
                pl.BlockSpec((1, 1, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, ot, fs: (b, ot[g], 0, 0)),
            ),
            scratch_shapes=[
                pltpu.VMEM((2, 2, K, TILE_LANE), jnp.float32),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32))
        return pl.pallas_call(
            functools.partial(_kernel_batched, K), out_shape=out_shape,
            grid_spec=grid_spec, interpret=interpret,
        )(row0, out_tile, first, packed, re, im)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # row0, out_tile, first
        grid=(C,),
        in_specs=[
            pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                         lambda g, r0, ot, fs: (g, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                         lambda g, r0, ot, fs: (ot[g], 0, 0)),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                         lambda g, r0, ot, fs: (ot[g], 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 2, K, TILE_LANE), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out_shape = (
        jax.ShapeDtypeStruct((num_tiles, TILE_SUB, TILE_LANE), jnp.float32),
        jax.ShapeDtypeStruct((num_tiles, TILE_SUB, TILE_LANE), jnp.float32))
    return pl.pallas_call(
        functools.partial(_kernel, K), out_shape=out_shape,
        grid_spec=grid_spec, interpret=interpret,
    )(row0, out_tile, first, packed, re, im)


def _monotone_gather_call_aliased(re, im, row0, out_tile, first, packed, *,
                                  span_rows: int, num_tiles: int,
                                  interpret: bool, carry):
    """Narrow-kernel launch writing into an ALIASED full-size output pair
    (see _wide_gather_call's carry)."""
    C = row0.shape[0]
    K = span_rows
    base = functools.partial(_kernel_batched if re.ndim == 3 else _kernel, K)
    kern = lambda *r: base(*r[:6], *r[8:])  # drop the 2 unused carry refs
    scratch = [
        pltpu.VMEM((2, 2, K, TILE_LANE), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    carry_specs = [pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)]
    aliases = {6: 0, 7: 1}
    if re.ndim == 3:
        B = re.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, ot, fs: (g, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ] + carry_specs,
            out_specs=(
                pl.BlockSpec((1, 1, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, ot, fs: (b, ot[g], 0, 0)),
                pl.BlockSpec((1, 1, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, ot, fs: (b, ot[g], 0, 0)),
            ),
            scratch_shapes=scratch,
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32))
        return pl.pallas_call(
            kern, out_shape=out_shape, grid_spec=grid_spec,
            interpret=interpret, input_output_aliases=aliases,
        )(row0, out_tile, first, packed, re, im, *carry)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(C,),
        in_specs=[
            pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                         lambda g, r0, ot, fs: (g, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ] + carry_specs,
        out_specs=(
            pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                         lambda g, r0, ot, fs: (ot[g], 0, 0)),
            pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                         lambda g, r0, ot, fs: (ot[g], 0, 0)),
        ),
        scratch_shapes=scratch,
    )
    out_shape = (
        jax.ShapeDtypeStruct((num_tiles, TILE_SUB, TILE_LANE), jnp.float32),
        jax.ShapeDtypeStruct((num_tiles, TILE_SUB, TILE_LANE), jnp.float32))
    return pl.pallas_call(
        kern, out_shape=out_shape, grid_spec=grid_spec,
        interpret=interpret, input_output_aliases=aliases,
    )(row0, out_tile, first, packed, re, im, *carry)


def pad_wide_tables_to(t: WideGatherTables, c_max: int):
    """Wide analogue of :func:`pad_tables_to`: pad to ``c_max`` chunks with
    no-op chunks targeting a DUMMY super-tile (index ``t.num_super``) —
    all-invalid packed words, row0=0 (src_rows >= K always), sub=0. The
    first padding chunk has first=1 so the dummy block is initialised.
    Callers pass ``num_super + 1`` to :func:`wide_gather` and rely on the
    flat real-output prefix being unchanged (the dummy block is last).

    Returns (row0, sub, out_tile, first, packed) padded to c_max rows."""
    pad = c_max - t.row0.shape[0]
    if pad < 0:
        raise ValueError("c_max smaller than existing chunk count")
    if pad == 0:
        return t.row0, t.sub, t.out_tile, t.first, t.packed
    P = t.p_tiles
    row0 = np.concatenate([t.row0, np.zeros(pad, np.int32)])
    sub = np.concatenate([t.sub, np.zeros((pad, P // 4), np.int32)])
    out_tile = np.concatenate(
        [t.out_tile, np.full(pad, t.num_super, np.int32)])
    first = np.concatenate(
        [t.first, np.ones(1, np.int32), np.zeros(pad - 1, np.int32)])
    packed = np.concatenate(
        [t.packed,
         np.zeros((pad, P * TILE_SUB, TILE_LANE), np.int16)])
    return row0, sub, out_tile, first, packed


def _wide_tile_compute(kp: int, t, win_re, win_im):
    """Per-tile compute of the wide kernel: decode one tile's packed block
    (already widened to int32), gather kp candidate rows from its
    (kp, 128) sub-window, select-accumulate."""
    lane = t & (TILE_LANE - 1)
    row = (t >> _ROW_SHIFT) & _W_ROW_MASK
    m = (t >> _W_VALID_SHIFT).astype(jnp.float32)
    acc_re = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
    acc_im = jnp.zeros((TILE_SUB, TILE_LANE), jnp.float32)
    for k in range(kp):
        sel = row == k
        sre = jnp.broadcast_to(win_re[k][None, :], (TILE_SUB, TILE_LANE))
        sim = jnp.broadcast_to(win_im[k][None, :], (TILE_SUB, TILE_LANE))
        acc_re += jnp.where(sel, jnp.take_along_axis(sre, lane, axis=1), 0)
        acc_im += jnp.where(sel, jnp.take_along_axis(sim, lane, axis=1), 0)
    return acc_re * m, acc_im * m


def _wide_step(kp: int, P: int, sub_ref, g, packed_blk, sc, slot, write):
    """Shared per-step body of the wide kernels: decode each tile's byte-
    packed sub-window offset, slice its (kp, 128) sub-window out of the
    DMA'd window, compute, and hand (p, acc_re, acc_im) to ``write`` for
    the output store. The int16 packed block is loaded and widened ONCE
    per step; per-tile rows are register slices."""
    t_all = packed_blk[...].astype(jnp.int32)        # (P*8, 128)
    for p in range(P):
        word = sub_ref[g, p // 4]
        sub = (word >> (8 * (p % 4))) & 0xFF
        win_re = sc[slot, 0, pl.ds(sub, kp), :]
        win_im = sc[slot, 1, pl.ds(sub, kp), :]
        t = t_all[p * TILE_SUB:(p + 1) * TILE_SUB]
        acc_re, acc_im = _wide_tile_compute(kp, t, win_re, win_im)
        write(p, acc_re, acc_im)


def _kernel_wide(K: int, kp: int, P: int, row0_ref, sub_ref, out_tile_ref,
                 first_ref, packed_ref, re_hbm, im_hbm, out_re_ref,
                 out_im_ref, sc, sem):
    g = pl.program_id(0)
    n_g = pl.num_programs(0)

    def dma(gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(gg):
        slot = jax.lax.rem(jnp.asarray(gg, jnp.int32), jnp.int32(2))
        dma(gg, slot, 0, re_hbm).start()
        dma(gg, slot, 1, im_hbm).start()

    @pl.when(g == 0)
    def _():
        start(0)

    @pl.when(g + 1 < n_g)
    def _():
        start(g + 1)

    slot = jax.lax.rem(jnp.asarray(g, jnp.int32), jnp.int32(2))
    dma(g, slot, 0, re_hbm).wait()
    dma(g, slot, 1, im_hbm).wait()

    frst = first_ref[g]

    def write(p, acc_re, acc_im):
        @pl.when(frst == 1)
        def _():
            out_re_ref[p] = acc_re
            out_im_ref[p] = acc_im

        @pl.when(frst == 0)
        def _():
            out_re_ref[p] = out_re_ref[p] + acc_re
            out_im_ref[p] = out_im_ref[p] + acc_im

    _wide_step(kp, P, sub_ref, g, packed_ref[0], sc, slot, write)


def _kernel_wide_batched(K: int, kp: int, P: int, row0_ref, sub_ref,
                         out_tile_ref, first_ref, packed_ref, re_hbm,
                         im_hbm, out_re_ref, out_im_ref, sc, sem):
    """Batched wide variant: grid (B, C), batch-invariant tables, DMA
    pipeline prefetching across the batch boundary (see _kernel_batched)."""
    b = pl.program_id(0)
    g = pl.program_id(1)
    n_b = pl.num_programs(0)
    n_g = pl.num_programs(1)
    step = b * n_g + g

    def dma(bb, gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[bb, pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(bb, gg, slot):
        dma(bb, gg, slot, 0, re_hbm).start()
        dma(bb, gg, slot, 1, im_hbm).start()

    @pl.when(step == 0)
    def _():
        start(0, 0, 0)

    @pl.when(step + 1 < n_b * n_g)
    def _():
        nxt_b = jnp.where(g + 1 < n_g, b, b + 1)
        nxt_g = jnp.where(g + 1 < n_g, g + 1, 0)
        start(nxt_b, nxt_g, jax.lax.rem(step + 1, jnp.int32(2)))

    slot = jax.lax.rem(step, jnp.int32(2))
    dma(b, g, slot, 0, re_hbm).wait()
    dma(b, g, slot, 1, im_hbm).wait()

    frst = first_ref[g]

    def write(p, acc_re, acc_im):
        @pl.when(frst == 1)
        def _():
            out_re_ref[0, p] = acc_re
            out_im_ref[0, p] = acc_im

        @pl.when(frst == 0)
        def _():
            out_re_ref[0, p] = out_re_ref[0, p] + acc_re
            out_im_ref[0, p] = out_im_ref[0, p] + acc_im

    _wide_step(kp, P, sub_ref, g, packed_ref[0], sc, slot, write)


@functools.partial(jax.jit, static_argnames=("span_rows", "kp_rows",
                                             "p_tiles", "src_rows",
                                             "num_super", "interpret",
                                             "segs"))
def wide_gather(re, im, row0, sub, out_tile, first, packed, *,
                span_rows: int, kp_rows: int, p_tiles: int, src_rows: int,
                num_super: int, interpret: bool = False, segs: tuple = ()):
    """Run the wide windowed gather.

    Args:
      re, im: (src_rows, 128) float32 planar source — or (B, src_rows, 128)
        batched.
      row0/sub/out_tile/first/packed: device tables (see
        build_wide_gather_tables).
    Returns:
      (out_re, out_im): each (num_super * p_tiles, 8, 128) float32, with a
      leading B when batched. Flat prefix = the num_out output slots.
    """
    if segs:
        outs_re, outs_im = [], []
        for (c0, c1, t0, t1) in segs:
            o_re, o_im = wide_gather(
                re, im, row0[c0:c1], sub[c0:c1], out_tile[c0:c1] - t0,
                first[c0:c1], packed[c0:c1], span_rows=span_rows,
                kp_rows=kp_rows, p_tiles=p_tiles, src_rows=src_rows,
                num_super=t1 - t0, interpret=interpret)
            outs_re.append(o_re)
            outs_im.append(o_im)
        axis = 1 if re.ndim == 3 else 0
        return (jnp.concatenate(outs_re, axis=axis),
                jnp.concatenate(outs_im, axis=axis))
    if re.ndim == 3 and re.shape[0] * row0.shape[0] > WIDE_SEG_CHUNK_LIMIT:
        # The compile-crash threshold (see WIDE_SEG_CHUNK_LIMIT) is on the
        # TOTAL grid step count; a batched launch compiles B * C steps, so
        # big batches run as per-slab launches instead (loses cross-batch
        # DMA prefetch only).
        outs = [_wide_gather_call(re[b], im[b], row0, sub, out_tile, first,
                                  packed, span_rows=span_rows,
                                  kp_rows=kp_rows, p_tiles=p_tiles,
                                  num_super=num_super, interpret=interpret)
                for b in range(re.shape[0])]
        return (jnp.stack([o[0] for o in outs]),
                jnp.stack([o[1] for o in outs]))
    return _wide_gather_call(re, im, row0, sub, out_tile, first, packed,
                             span_rows=span_rows, kp_rows=kp_rows,
                             p_tiles=p_tiles, num_super=num_super,
                             interpret=interpret)


def _wide_gather_call(re, im, row0, sub, out_tile, first, packed, *,
                      span_rows: int, kp_rows: int, p_tiles: int,
                      num_super: int, interpret: bool, carry=None):
    """One wide launch. ``carry`` (segmented tables only): the previous
    segment's full-size output pair, ALIASED into this launch's output —
    blocks this segment's ``out_tile`` never names keep the carried
    content, so multi-launch tables accumulate with zero copy traffic."""
    C = row0.shape[0]
    K, kp, P = span_rows, kp_rows, p_tiles
    kern = functools.partial(_kernel_wide_batched if re.ndim == 3
                             else _kernel_wide, K, kp, P)
    scratch = [
        pltpu.VMEM((2, 2, K, TILE_LANE), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    aliases = {7: 0, 8: 1} if carry is not None else {}
    carry_in = () if carry is None else tuple(carry)
    carry_specs = [] if carry is None else [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    if carry is not None:
        base = kern
        kern = lambda *r: base(*r[:7], *r[9:])  # drop unused carry refs
    if re.ndim == 3:
        B = re.shape[0]
        if B * C > WIDE_SEG_CHUNK_LIMIT:
            # The compile-crash threshold (WIDE_SEG_CHUNK_LIMIT) is on
            # the TOTAL grid step count; big batches run per slab
            # (loses cross-batch DMA prefetch only).
            outs = [_wide_gather_call(
                re[b], im[b], row0, sub, out_tile, first, packed,
                span_rows=K, kp_rows=kp, p_tiles=P, num_super=num_super,
                interpret=interpret,
                carry=None if carry is None else (carry[0][b], carry[1][b]))
                for b in range(B)]
            return (jnp.stack([o[0] for o in outs]),
                    jnp.stack([o[1] for o in outs]))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,  # row0, sub, out_tile, first
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, P * TILE_SUB, TILE_LANE),
                             lambda b, g, r0, sb, ot, fs: (g, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ] + carry_specs,
            out_specs=(
                pl.BlockSpec((1, P, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, sb, ot, fs: (b, ot[g], 0, 0)),
                pl.BlockSpec((1, P, TILE_SUB, TILE_LANE),
                             lambda b, g, r0, sb, ot, fs: (b, ot[g], 0, 0)),
            ),
            scratch_shapes=scratch,
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, num_super * P, TILE_SUB, TILE_LANE),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, num_super * P, TILE_SUB, TILE_LANE),
                                 jnp.float32))
        return pl.pallas_call(
            kern, out_shape=out_shape, grid_spec=grid_spec,
            interpret=interpret, input_output_aliases=aliases,
        )(row0, sub, out_tile, first, packed, re, im, *carry_in)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # row0, sub, out_tile, first
        grid=(C,),
        in_specs=[
            pl.BlockSpec((1, P * TILE_SUB, TILE_LANE),
                         lambda g, r0, sb, ot, fs: (g, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ] + carry_specs,
        out_specs=(
            pl.BlockSpec((P, TILE_SUB, TILE_LANE),
                         lambda g, r0, sb, ot, fs: (ot[g], 0, 0)),
            pl.BlockSpec((P, TILE_SUB, TILE_LANE),
                         lambda g, r0, sb, ot, fs: (ot[g], 0, 0)),
        ),
        scratch_shapes=scratch,
    )
    out_shape = (
        jax.ShapeDtypeStruct((num_super * P, TILE_SUB, TILE_LANE),
                             jnp.float32),
        jax.ShapeDtypeStruct((num_super * P, TILE_SUB, TILE_LANE),
                             jnp.float32))
    return pl.pallas_call(
        kern, out_shape=out_shape, grid_spec=grid_spec,
        interpret=interpret, input_output_aliases=aliases,
    )(row0, sub, out_tile, first, packed, re, im, *carry_in)


# -- uniform dispatch over the two table kinds -------------------------------

def gather_device_tables(t) -> tuple:
    """Device-committed tables for either kind: a tuple of per-SEGMENT
    table tuples (one entry for unsegmented tables). Slicing happens here
    at plan time — slicing shared tables inside the jitted hot path costs
    a 25 MB copy per execution (probe_r4_hlo)."""
    wide = isinstance(t, WideGatherTables)
    segs = t.segs if t.segs else ((0, t.row0.shape[0], 0,
                                   t.num_super if wide else t.num_tiles),)
    out = []
    for (c0, c1, t0, t1) in segs:
        if wide:
            out.append((jnp.asarray(t.row0[c0:c1]),
                        jnp.asarray(t.sub[c0:c1]),
                        jnp.asarray(t.out_tile[c0:c1]),
                        jnp.asarray(t.first[c0:c1]),
                        jnp.asarray(t.packed[c0:c1])))
        else:
            out.append((jnp.asarray(t.row0[c0:c1]),
                        jnp.asarray(t.out_tile[c0:c1]),
                        jnp.asarray(t.first[c0:c1]),
                        jnp.asarray(t.packed[c0:c1])))
    return tuple(out)


def run_gather(re, im, dev_tables: tuple, t, interpret: bool = False):
    """Run whichever kernel matches ``t`` (WideGatherTables or
    MonotoneGatherTables) on planar sources; returns (out_re, out_im)
    whose flat prefix holds the ``t.num_out`` output slots.

    Segmented tables run as one launch per segment. On real hardware the
    segments ACCUMULATE into one output buffer via pallas input/output
    aliasing (out_tile indices are absolute; blocks a segment never
    visits retain the previous launch's content) — zero concatenation
    traffic. Interpret mode keeps the concat path (the interpreter does
    not preserve unwritten blocks of aliased outputs).
    """
    wide = isinstance(t, WideGatherTables)
    segs = t.segs
    if not segs:
        if wide:
            return _wide_gather_call(
                re, im, *dev_tables[0], span_rows=t.span_rows,
                kp_rows=t.kp_rows, p_tiles=t.p_tiles,
                num_super=t.num_super, interpret=interpret)
        return _monotone_gather_call(
            re, im, *dev_tables[0], span_rows=t.span_rows,
            num_tiles=t.num_tiles, interpret=interpret)
    total = t.num_super if wide else t.num_tiles
    if interpret:
        outs = []
        for (c0, c1, t0, t1), tabs in zip(segs, dev_tables):
            if wide:
                row0, sub, ot, first, packed = tabs
                outs.append(_wide_gather_call(
                    re, im, row0, sub, ot - t0, first, packed,
                    span_rows=t.span_rows, kp_rows=t.kp_rows,
                    p_tiles=t.p_tiles, num_super=t1 - t0,
                    interpret=True))
            else:
                row0, ot, first, packed = tabs
                outs.append(_monotone_gather_call(
                    re, im, row0, ot - t0, first, packed,
                    span_rows=t.span_rows, num_tiles=t1 - t0,
                    interpret=True))
        axis = 1 if re.ndim == 3 else 0
        return (jnp.concatenate([o[0] for o in outs], axis=axis),
                jnp.concatenate([o[1] for o in outs], axis=axis))
    carry = None
    for tabs in dev_tables:
        if wide:
            carry = _wide_gather_call(
                re, im, *tabs, span_rows=t.span_rows, kp_rows=t.kp_rows,
                p_tiles=t.p_tiles, num_super=total, interpret=False,
                carry=carry)
        else:
            carry = _monotone_gather_call(
                re, im, *tabs, span_rows=t.span_rows, num_tiles=total,
                interpret=False, carry=carry)
    return carry


def run_gather_values(values_il, tables, device_tables=None,
                      interpret: bool = False):
    """Convenience wrapper for either table kind: interleaved (N, 2) source
    -> (num_out, 2) output.

    ``device_tables`` may supply the pre-committed jax arrays of
    :func:`gather_device_tables` to keep table upload off the hot path.
    """
    re, im = planar_from_interleaved(values_il, tables.src_rows)
    if device_tables is None:
        device_tables = gather_device_tables(tables)
    out_re, out_im = run_gather(re, im, device_tables, tables,
                                interpret=interpret)
    return interleaved_from_planar(out_re, out_im, tables.num_out)


def run_monotone_gather(values_il, tables: MonotoneGatherTables,
                        device_tables=None, interpret: bool = False):
    """Narrow-kernel alias of :func:`run_gather_values` (kept for callers
    that build MonotoneGatherTables explicitly)."""
    return run_gather_values(values_il, tables, device_tables, interpret)


def planar_from_interleaved(values_il, src_rows: int, pair: bool = False):
    """Value array -> two zero-padded (src_rows, 128) planar arrays.

    Default layout is interleaved rows (N, 2) (batched: (B, N, 2));
    ``pair=True`` reads the planar-pair layout (2, N) (batched: (B, 2, N))
    — row 0 real, row 1 imaginary. The pair form exists because a large
    (N, 2) array at the jit boundary can be assigned TPU's T(8,128) tiled
    layout, padding the minor dim 2 -> 128 (64x memory — 36 GB at 512^3),
    while strided flat interleaves lower ~70x too slow; (2, N) row slices
    are both compact (4x sublane pad at most) and fast.
    """
    if pair:
        n = values_il.shape[-1]
        re_flat = values_il[..., 0, :]
        im_flat = values_il[..., 1, :]
    else:
        n = values_il.shape[-2]
        re_flat = values_il[..., 0]
        im_flat = values_il[..., 1]
    pad = src_rows * TILE_LANE - n
    batch = [(0, 0)] * (re_flat.ndim - 1)
    shape = re_flat.shape[:-1] + (src_rows, TILE_LANE)
    re = jnp.pad(re_flat, batch + [(0, pad)]).reshape(shape)
    im = jnp.pad(im_flat, batch + [(0, pad)]).reshape(shape)
    return re, im


def planar_from_complex(x, src_rows: int):
    """Complex (S, Z) sticks — or batched (B, S, Z) — -> two zero-padded
    (src_rows, 128) planar arrays (leading B preserved). Goes straight
    from the complex values to planar so no big interleaved (N, 2)
    intermediate can be assigned the 64x-padded tiled layout (see
    planar_from_interleaved)."""
    batch = x.shape[:1] if x.ndim == 3 else ()
    re_flat = jnp.real(x).reshape(batch + (-1,))
    im_flat = jnp.imag(x).reshape(batch + (-1,))
    pad = [(0, 0)] * len(batch) + [(0, src_rows * TILE_LANE
                                    - re_flat.shape[-1])]
    shape = batch + (src_rows, TILE_LANE)
    return (jnp.pad(re_flat, pad).reshape(shape),
            jnp.pad(im_flat, pad).reshape(shape))


def interleaved_from_planar(out_re, out_im, num_out: int,
                            pair: bool = False):
    """Kernel outputs -> (num_out, 2) interleaved ((B, num_out, 2) when
    batched); ``pair=True`` returns the planar-pair layout (2, num_out) /
    (B, 2, num_out) instead, never materialising a big (N, 2) shape (see
    planar_from_interleaved on why)."""
    if out_re.ndim == 4:
        B = out_re.shape[0]
        re = out_re.reshape(B, -1)[:, :num_out]
        im = out_im.reshape(B, -1)[:, :num_out]
    else:
        re = out_re.reshape(-1)[:num_out]
        im = out_im.reshape(-1)[:num_out]
    return jnp.stack([re, im], axis=-2 if pair else -1)

"""Matmul-DFT: FFT stages as MXU dot_generals against plan-time matrices.

XLA:TPU lowers ``jnp.fft`` to DFT *convolutions* (O(N^2) matmuls at
operand_precision=highest) plus 67 MB-class internal layout copies per 2D
transform (measured at 256^3 — scripts/probe_r4_hlo.py). Expressing the
same DFT as explicit minor-axis dot_generals against f32 matrix constants
is strictly better on this hardware:

  * same MXU cost, none of the internal layout copies (measured 1.5 ms
    faster on the 256^3 fused pair, scripts/probe_r4_dft2.py);
  * Karatsuba 3-mult complex multiply (3 dots instead of 4);
  * normalisation constants fold into the matrices (zero extra passes);
  * works for ANY length, primes included, and supports half-spectrum
    real transforms directly — no XLA C2R op, which sidesteps the TPU
    backend's rank-3 irfft corruption (see stages._irfft_last);
  * stages can stay PLANAR (separate re/im f32 arrays), avoiding the
    X64SplitLow/High machinery XLA wraps around complex dtypes.

Accuracy: HIGHEST-precision dots measure ~1e-7 relative error per pass
vs numpy's FFT (256-point, scripts/probe_r4_dft.py); lower precisions
fail the library's 1e-6 contract and are not offered.

The O(N^2) flop count is intentional: at the stick/plane lengths this
library sees (<= ~512) the MXU eats the DFT matmul at a higher effective
rate than any O(N log N) decomposition we measured — a four-step radix-2
split halves MXU flops but loses the gain to butterfly HBM passes
(scripts/probe_r4_dft2.py). ``MATMUL_DFT_MAX`` caps the direct form;
longer axes fall back to ``jnp.fft`` in ops.stages.

Reference parity: these replace the reference's FFTW/cuFFT plan objects
(reference: src/fft/fftw_plan_1d.hpp:74-94, src/fft/transform_1d_gpu.hpp)
— the "plan" here is the matrix constant pair embedded in the executable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

#: Longest axis the direct matmul-DFT handles; beyond this ops.stages
#: falls back to jnp.fft (the O(N^2) flops would dominate, and no
#: workload in the reference's envelope exceeds it).
MATMUL_DFT_MAX = 512

_HIGHEST = jax.lax.Precision.HIGHEST

BACKWARD = +1   # unnormalised inverse DFT (e^{+2 pi i k n / N})
FORWARD = -1    # plain DFT


@functools.lru_cache(maxsize=None)
def _dft_mats(n: int, sign: int, scale: float):
    """(Cr, Ci, Cs) f32 numpy constants for the length-``n`` DFT with
    ``scale`` folded in; Cs = Cr + Ci pre-summed for the Karatsuba form."""
    k = np.arange(n)
    m = np.exp(sign * 2j * np.pi * np.outer(k, k) / n) * scale
    cr = np.ascontiguousarray(m.real.astype(np.float32))
    ci = np.ascontiguousarray(m.imag.astype(np.float32))
    return cr, ci, np.ascontiguousarray(cr + ci)


@functools.lru_cache(maxsize=None)
def _rdft_mats(n: int, scale: float):
    """Forward real-to-halfspectrum matrices (n, n//2+1): Yr = X @ Cr,
    Yi = X @ Ci (reference rfft layout, dim_x_freq = n//2+1 —
    reference: src/parameters/parameters.cpp:49)."""
    xf = n // 2 + 1
    k = np.arange(xf)
    m = np.exp(-2j * np.pi * np.outer(np.arange(n), k) / n) * scale
    return (np.ascontiguousarray(m.real.astype(np.float32)),
            np.ascontiguousarray(m.imag.astype(np.float32)))


@functools.lru_cache(maxsize=None)
def _irdft_mats(n: int, scale: float):
    """Halfspectrum-to-real matrices (n//2+1, n): x = Yr @ A + Yi @ B.

    From hermitian symmetry: x[m] = sum_k w[k] (Yr[k] cos(2 pi k m / n)
    - Yi[k] sin(2 pi k m / n)) with w = 1 for the self-conjugate bins
    (k=0 and, for even n, k=n/2) and 2 otherwise. The doubling absorbs
    the missing negative-frequency half; no complex op and no XLA C2R
    involved (the TPU backend's rank-3 irfft silently corrupts large
    batches — see stages._irfft_last).
    """
    xf = n // 2 + 1
    k = np.arange(xf)
    w = np.full(xf, 2.0)
    w[0] = 1.0
    if n % 2 == 0:
        w[-1] = 1.0
    ang = 2 * np.pi * np.outer(k, np.arange(n)) / n
    # x[m] = sum_k w Re(Y[k] e^{+i ang}) = sum_k w (Yr cos - Yi sin)
    a = (w[:, None] * np.cos(ang)) * scale
    b = (w[:, None] * -np.sin(ang)) * scale
    return (np.ascontiguousarray(a.astype(np.float32)),
            np.ascontiguousarray(b.astype(np.float32)))


def _sub_rows(mats, rows):
    """Row-select a matrix pair/triple: the split-x path applies the DFT
    from only the occupied input positions ``rows`` (wrapped windows are
    just non-contiguous row selections — no roll/pad stage needed)."""
    rows = np.asarray(rows)
    return tuple(np.ascontiguousarray(m[rows]) for m in mats)


def _sub_cols(mats, cols):
    """Column-select a matrix pair/triple: produce only the occupied
    output positions ``cols``."""
    cols = np.asarray(cols)
    return tuple(np.ascontiguousarray(m[:, cols]) for m in mats)


def _dot(a, c):
    """(..., K) @ (K, M) -> (..., M) at HIGHEST precision."""
    return jax.lax.dot_general(a, jnp.asarray(c),
                               (((a.ndim - 1,), (0,)), ((), ())),
                               precision=_HIGHEST)


# -- planar complex DFT ------------------------------------------------------

def pdft_last(xr, xi, mats):
    """Complex DFT along the minor axis on planar operands.

    Karatsuba 3-mult: P1 = Xr Cr, P2 = Xi Ci, P3 = (Xr+Xi)(Cr+Ci);
    Yr = P1 - P2, Yi = P3 - P1 - P2 (the (Cr+Ci) sum is a plan-time
    constant, so the extra operand add is on the small matrix, not the
    data).
    """
    cr, ci, cs = mats
    p1 = _dot(xr, cr)
    p2 = _dot(xi, ci)
    p3 = _dot(xr + xi, cs)
    return p1 - p2, p3 - p1 - p2


def cdft_last(x, mats):
    """Complex-dtype wrapper of :func:`pdft_last` (drop-in inside jit:
    XLA splits/joins the complex pair for free)."""
    yr, yi = pdft_last(jnp.real(x), jnp.imag(x), mats)
    return yr + 1j * yi


# -- real transforms ---------------------------------------------------------

def prdft_last(x, mats):
    """Real forward DFT along the minor axis -> planar half spectrum
    (..., n//2+1): two dots, half the flops of the complex form."""
    a, b = mats
    return _dot(x, a), _dot(x, b)


def pirdft_last(yr, yi, mats):
    """Planar half spectrum -> real inverse along the minor axis
    (..., n): two dots; hermitian doubling folded into the matrices."""
    a, b = mats
    return _dot(yr, a) + _dot(yi, b)


# -- stage-level helpers (mats builders with scale folding) ------------------

def c2c_mats(n: int, sign: int, scale: float = 1.0):
    """Matrices for a complex length-``n`` DFT; ``scale`` is folded in.
    ``sign=BACKWARD`` with ``scale=1`` gives the library's unnormalised
    inverse (ifft * n — docs/source/details.rst 'Transform Definition'
    semantics, matching stages.z_backward)."""
    if sign == BACKWARD:
        # unnormalised inverse: e^{+...} with no 1/n — fold the caller's
        # extra scale directly
        return _dft_mats(n, +1, float(scale))
    return _dft_mats(n, -1, float(scale))


def r2c_mats(n: int, scale: float = 1.0):
    return _rdft_mats(n, float(scale))


def c2r_mats(n: int, scale: float = 1.0):
    """Unnormalised inverse real transform: irfft * n equivalents."""
    return _irdft_mats(n, float(scale))


@functools.lru_cache(maxsize=None)
def sub_rows_mats(n: int, sign: int, rows: tuple, scale: float = 1.0):
    """Row-selected complex DFT matrices (cached per window): the
    split-x contraction from the occupied positions only."""
    return _sub_rows(c2c_mats(n, sign, scale), np.asarray(rows))


@functools.lru_cache(maxsize=None)
def sub_cols_mats(n: int, sign: int, cols: tuple, scale: float = 1.0):
    """Column-selected complex DFT matrices (cached per window)."""
    return _sub_cols(c2c_mats(n, sign, scale), np.asarray(cols))


@functools.lru_cache(maxsize=None)
def sub_rows_c2r_mats(n: int, rows: tuple, scale: float = 1.0):
    """Row-selected inverse-real matrices: half-spectrum window -> dense
    real axis (hermitian weights ride along with their rows)."""
    return _sub_rows(c2r_mats(n, scale), np.asarray(rows))


@functools.lru_cache(maxsize=None)
def sub_cols_r2c_mats(n: int, cols: tuple, scale: float = 1.0):
    """Column-selected forward-real matrices: real axis -> half-spectrum
    window."""
    return _sub_cols(r2c_mats(n, scale), np.asarray(cols))


def use_matmul_dft(n: int, dtype) -> bool:
    """Route a length-``n`` axis through the matmul DFT? TPU backend,
    single precision, within the direct-form cap. CPU keeps pocketfft
    (a real O(N log N) FFT); double precision keeps jnp.fft (f64 dots
    are emulated and slow on TPU, and the double path is CPU-bound
    anyway — docs/precision.md)."""
    import os
    single = jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                  jnp.dtype(jnp.complex64))
    if os.environ.get("SPFFT_TPU_FORCE_MATMUL_DFT") == "1":
        return single and n <= MATMUL_DFT_MAX  # force past the backend gate
    if os.environ.get("SPFFT_TPU_NO_MATMUL_DFT") == "1":
        return False
    return (jax.default_backend() == "tpu" and n <= MATMUL_DFT_MAX
            and single)

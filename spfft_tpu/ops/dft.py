"""Matmul-DFT: FFT stages as MXU dot_generals against plan-time matrices.

XLA:TPU lowers ``jnp.fft`` to DFT *convolutions* (O(N^2) matmuls at
operand_precision=highest) plus 67 MB-class internal layout copies per 2D
transform (measured at 256^3 — scripts/probe_r4_hlo.py). Expressing the
same DFT as explicit minor-axis dot_generals against f32 matrix constants
is strictly better on this hardware:

  * same MXU cost, none of the internal layout copies (measured 1.5 ms
    faster on the 256^3 fused pair, scripts/probe_r4_dft2.py);
  * Karatsuba 3-mult complex multiply (3 dots instead of 4);
  * normalisation constants fold into the matrices (zero extra passes);
  * works for ANY length, primes included, and supports half-spectrum
    real transforms directly — no XLA C2R op, which sidesteps the TPU
    backend's rank-3 irfft corruption (see stages._irfft_last);
  * stages can stay PLANAR (separate re/im f32 arrays), avoiding the
    X64SplitLow/High machinery XLA wraps around complex dtypes.

Accuracy: HIGHEST-precision dots measure ~1e-7 relative error per pass
vs numpy's FFT (256-point, scripts/probe_r4_dft.py); lower precisions
fail the library's 1e-6 contract and are not offered.

The O(N^2) flop count is intentional: at the stick/plane lengths this
library sees (<= ~512) the MXU eats the DFT matmul at a higher effective
rate than any O(N log N) decomposition we measured — a four-step radix-2
split halves MXU flops but loses the gain to butterfly HBM passes
(scripts/probe_r4_dft2.py). ``MATMUL_DFT_MAX`` caps the direct form;
composite axes above it run a TWO-STAGE Cooley-Tukey factorization
N = N1*N2 (both <= the cap): reshape (…, N1, N2), stage-1 dot over N1,
one planar twiddle multiply (fused elementwise), stage-2 dot over N2,
and a final minor-axes swap — keeping 768/1024-class axes off the
conv-lowered ``jnp.fft`` TPU path entirely (round-4 verdict item; the
reference gets arbitrary N from FFTW plans, fftw_plan_1d.hpp:74-94).
Axes above the cap with no such factorization run the DIRECT form up
to ``MATMUL_DFT_DIRECT_FALLBACK_MAX`` (primes have no cheaper matmul
route); only lengths beyond that fall back to ``jnp.fft`` in
ops.stages.

Reference parity: these replace the reference's FFTW/cuFFT plan objects
(reference: src/fft/fftw_plan_1d.hpp:74-94, src/fft/transform_1d_gpu.hpp)
— the "plan" here is the matrix constant pair embedded in the executable.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

#: Longest axis the direct matmul-DFT PREFERS; composite lengths above
#: it run the two-stage Cooley-Tukey split (fewer MXU flops).
MATMUL_DFT_MAX = 512

#: Unfactorable lengths (primes, and composites whose smallest balanced
#: split exceeds the cap) still run the DIRECT matmul form up to this
#: length. Bluestein at the padded length (2048 for N=1021) can cost
#: FEWER MACs (~0.59M/row via two-stage 2048 passes vs 1.04M direct)
#: but spends THREE grid-scale passes plus chirp elementwise traffic
#: where the direct form spends one — the same movement-vs-flops trade
#: the measured radix-split experiment lost (module docstring;
#: probe_r4_dft2.py) — and the jnp.fft fallback is the conv-lowered
#: O(N^2) TPU path with the compile-explosion hazard the matmul layer
#: exists to avoid (scripts/probe_fftcompile.py). Beyond this cap the
#: N^2 flops genuinely dominate and jnp.fft remains (the reference
#: gets any N from FFTW, fftw_plan_1d.hpp:74-94).
MATMUL_DFT_DIRECT_FALLBACK_MAX = 1024


def _mdft_covered_len(n: int) -> bool:
    """A length the matmul layer can execute: direct (incl. the direct
    fallback for unfactorable lengths) or two-stage."""
    return (n <= MATMUL_DFT_DIRECT_FALLBACK_MAX
            or two_stage_factor(n) is not None)


def _direct_form_len(n: int) -> bool:
    """Lengths whose matrix builders yield PLAIN matrix tuples — the
    split-window row/column selections and the hermitian x-stage need
    them (TwoStageMats does not row/column-select). Composite lengths
    above the cap return TwoStageMats from c2c_mats and so do NOT
    qualify; unfactorable ones up to the direct fallback cap do."""
    return n <= MATMUL_DFT_MAX or (
        two_stage_factor(n) is None
        and n <= MATMUL_DFT_DIRECT_FALLBACK_MAX)

_HIGHEST = jax.lax.Precision.HIGHEST

BACKWARD = +1   # unnormalised inverse DFT (e^{+2 pi i k n / N})
FORWARD = -1    # plain DFT


# Matrix caches are bounded (round-4 advisor finding): scale is folded
# into the keys, and per-plan scales (1/global_size) plus split-x window
# tuples make entries effectively per-plan — an unbounded cache leaks
# O(n^2) f32 matrices for the process lifetime in plan-churning servers.
# 32 entries cover every axis of a handful of live plans; evicted
# matrices rebuild in milliseconds at the next plan construction.
#
# _dft_mats additionally caps resident BYTES (round-5 advisor finding):
# an entry-count bound alone lets 32 prime-fallback triples at n>512
# (a 1021 axis costs ~12.5 MB per triple) pin ~400 MB for the process
# lifetime of a long-lived server; the byte-aware LRU below evicts
# oldest-first once the total exceeds the budget, so worst-case
# residency stays bounded regardless of axis mix.
DFT_MATS_CACHE_BYTES = 96 * 1024 * 1024


class _ByteLRU:
    """A thread-safe LRU keyed like ``functools.lru_cache`` but bounded
    by BOTH entry count and the summed ``nbytes`` of the cached arrays
    (serve-registry plans build concurrently from worker threads).
    Provides ``cache_clear()`` for drop-in compatibility."""

    def __init__(self, builder, max_entries: int, max_bytes: int):
        self._builder = builder
        self._max_entries = max_entries
        self._max_bytes = max_bytes
        self._store = collections.OrderedDict()  #: guarded by _lock
        self._bytes = 0                          #: guarded by _lock
        self._lock = threading.Lock()

    def __call__(self, *key):
        with self._lock:
            hit = self._store.get(key)
            if hit is not None:
                self._store.move_to_end(key)
                return hit
        val = self._builder(*key)  # build outside the lock (ms-scale)
        nbytes = sum(int(m.nbytes) for m in val)
        with self._lock:
            if key not in self._store:
                self._store[key] = val
                self._bytes += nbytes
            while len(self._store) > 1 \
                    and (self._bytes > self._max_bytes
                         or len(self._store) > self._max_entries):
                _, old = self._store.popitem(last=False)
                self._bytes -= sum(int(m.nbytes) for m in old)
        return val

    def cache_clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._bytes = 0

    @property
    def cache_bytes(self) -> int:
        with self._lock:
            return self._bytes


def _build_dft_mats(n: int, sign: int, scale: float):
    """(Cr, Ci, Cs) f32 numpy constants for the length-``n`` DFT with
    ``scale`` folded in; Cs = Cr + Ci pre-summed for the Karatsuba form."""
    k = np.arange(n)
    m = np.exp(sign * 2j * np.pi * np.outer(k, k) / n) * scale
    cr = np.ascontiguousarray(m.real.astype(np.float32))
    ci = np.ascontiguousarray(m.imag.astype(np.float32))
    return cr, ci, np.ascontiguousarray(cr + ci)


_dft_mats = _ByteLRU(_build_dft_mats, max_entries=32,
                     max_bytes=DFT_MATS_CACHE_BYTES)


@functools.lru_cache(maxsize=32)
def _rdft_mats(n: int, scale: float):
    """Forward real-to-halfspectrum matrices (n, n//2+1): Yr = X @ Cr,
    Yi = X @ Ci (reference rfft layout, dim_x_freq = n//2+1 —
    reference: src/parameters/parameters.cpp:49)."""
    xf = n // 2 + 1
    k = np.arange(xf)
    m = np.exp(-2j * np.pi * np.outer(np.arange(n), k) / n) * scale
    return (np.ascontiguousarray(m.real.astype(np.float32)),
            np.ascontiguousarray(m.imag.astype(np.float32)))


@functools.lru_cache(maxsize=32)
def _irdft_mats(n: int, scale: float):
    """Halfspectrum-to-real matrices (n//2+1, n): x = Yr @ A + Yi @ B.

    From hermitian symmetry: x[m] = sum_k w[k] (Yr[k] cos(2 pi k m / n)
    - Yi[k] sin(2 pi k m / n)) with w = 1 for the self-conjugate bins
    (k=0 and, for even n, k=n/2) and 2 otherwise. The doubling absorbs
    the missing negative-frequency half; no complex op and no XLA C2R
    involved (the TPU backend's rank-3 irfft silently corrupts large
    batches — see stages._irfft_last).
    """
    xf = n // 2 + 1
    k = np.arange(xf)
    w = np.full(xf, 2.0)
    w[0] = 1.0
    if n % 2 == 0:
        w[-1] = 1.0
    ang = 2 * np.pi * np.outer(k, np.arange(n)) / n
    # x[m] = sum_k w Re(Y[k] e^{+i ang}) = sum_k w (Yr cos - Yi sin)
    a = (w[:, None] * np.cos(ang)) * scale
    b = (w[:, None] * -np.sin(ang)) * scale
    return (np.ascontiguousarray(a.astype(np.float32)),
            np.ascontiguousarray(b.astype(np.float32)))


def _sub_rows(mats, rows):
    """Row-select a matrix pair/triple: the split-x path applies the DFT
    from only the occupied input positions ``rows`` (wrapped windows are
    just non-contiguous row selections — no roll/pad stage needed)."""
    rows = np.asarray(rows)
    return tuple(np.ascontiguousarray(m[rows]) for m in mats)


def _sub_cols(mats, cols):
    """Column-select a matrix pair/triple: produce only the occupied
    output positions ``cols``."""
    cols = np.asarray(cols)
    return tuple(np.ascontiguousarray(m[:, cols]) for m in mats)


def _dot(a, c):
    """(..., K) @ (K, M) -> (..., M) at HIGHEST precision."""
    return jax.lax.dot_general(a, jnp.asarray(c),
                               (((a.ndim - 1,), (0,)), ((), ())),
                               precision=_HIGHEST)


# -- planar complex DFT ------------------------------------------------------

def pdft_last(xr, xi, mats):
    """Complex DFT along the minor axis on planar operands.

    Karatsuba 3-mult: P1 = Xr Cr, P2 = Xi Ci, P3 = (Xr+Xi)(Cr+Ci);
    Yr = P1 - P2, Yi = P3 - P1 - P2 (the (Cr+Ci) sum is a plan-time
    constant, so the extra operand add is on the small matrix, not the
    data). Dispatches to the two-stage Cooley-Tukey form when ``mats``
    is a :class:`TwoStageMats` (axis length above ``MATMUL_DFT_MAX``).
    """
    if isinstance(mats, TwoStageMats):
        return _pdft_two_stage(xr, xi, mats)
    cr, ci, cs = mats
    p1 = _dot(xr, cr)
    p2 = _dot(xi, ci)
    p3 = _dot(xr + xi, cs)
    return p1 - p2, p3 - p1 - p2


def _dot_mid(a, c):
    """(..., K, M) @ (K, J) -> (..., M, J) at HIGHEST precision:
    contracts the SECOND-minor axis (dot_general appends the rhs free
    dim after the lhs free dims, so the result needs no transpose)."""
    return jax.lax.dot_general(a, jnp.asarray(c),
                               (((a.ndim - 2,), (0,)), ((), ())),
                               precision=_HIGHEST)


def _pdft_mid(xr, xi, mats):
    """Karatsuba complex DFT contracting the second-minor axis."""
    cr, ci, cs = mats
    p1 = _dot_mid(xr, cr)
    p2 = _dot_mid(xi, ci)
    p3 = _dot_mid(xr + xi, cs)
    return p1 - p2, p3 - p1 - p2


def _pdft_two_stage(xr, xi, m: "TwoStageMats"):
    """Two-stage Cooley-Tukey DFT of length n1*n2 on planar minor-axis
    operands. With n = i1*n2 + i2 and k = k2*n1 + k1:

      X[k] = sum_{i2} W_{n2}^{i2 k2} * T[i2, k1]
             * sum_{i1} x[i1*n2 + i2] W_{n1}^{i1 k1}

    stage 1 contracts i1 (second-minor after the reshape) producing
    (..., i2, k1); the twiddle T[i2, k1] = W_n^{i2 k1} is a fused
    elementwise complex multiply; stage 2 contracts i2 producing
    (..., k1, k2); the final swap orders flat k = k2*n1 + k1. Total
    flops ~ n*(n1+n2) vs n^2 direct — 16x fewer at n=1024."""
    lead = xr.shape[:-1]
    n = m.n1 * m.n2
    xr = xr.reshape(lead + (m.n1, m.n2))
    xi = xi.reshape(lead + (m.n1, m.n2))
    ar, ai = _pdft_mid(xr, xi, m.mats1)          # (..., n2, k1)
    tr, ti = jnp.asarray(m.tr), jnp.asarray(m.ti)
    br = ar * tr - ai * ti
    bi = ar * ti + ai * tr
    yr, yi = _pdft_mid(br, bi, m.mats2)          # (..., k1, k2)
    yr = jnp.swapaxes(yr, -1, -2).reshape(lead + (n,))
    yi = jnp.swapaxes(yi, -1, -2).reshape(lead + (n,))
    return yr, yi


def cdft_last(x, mats):
    """Complex-dtype wrapper of :func:`pdft_last` (drop-in inside jit:
    XLA splits/joins the complex pair for free). Routed through the
    fused-kernel dispatch so the distributed stage wrappers
    (ops.stages.z_backward etc., executing inside shard_map on a real
    TPU mesh) get the fused stage too."""
    yr, yi = pdft_last_opt(jnp.real(x), jnp.imag(x), mats)
    return yr + 1j * yi


# -- real transforms ---------------------------------------------------------

def prdft_last(x, mats):
    """Real forward DFT along the minor axis -> planar half spectrum
    (..., n//2+1): two dots, half the flops of the complex form."""
    a, b = mats
    return _dot(x, a), _dot(x, b)


def pirdft_last(yr, yi, mats):
    """Planar half spectrum -> real inverse along the minor axis
    (..., n): two dots; hermitian doubling folded into the matrices."""
    a, b = mats
    return _dot(yr, a) + _dot(yi, b)


# -- fused-kernel dispatch ---------------------------------------------------
#
# The plan pipelines call these instead of the raw stage functions: on a
# TPU backend with f32 operands and plain (non-Cooley-Tukey) matrices the
# stage executes as a fused Pallas kernel (ops.dft_kernel — one HBM read,
# one write, Karatsuba combine in VMEM); everything else takes the XLA
# form above, same math and layouts. SPFFT_TPU_FUSED_STAGE=0 forces the
# XLA form everywhere (the probes' A/B knob).

def _fused_ok(xr, *mats_list, cap=None) -> bool:
    from . import dft_kernel as dk
    return (dk.enabled() and xr.dtype == jnp.float32
            and dk.eligible_mats(*mats_list, cap=cap))


def _fits2_ok(mode, xr, mats1, mats2) -> bool:
    """Shared two-stage dispatch gate: 3-D f32 operand, eligible plain
    matrices (eligible_mats rejects TwoStageMats and over-cap axes),
    kernel enabled, and the mode's VMEM fit."""
    if xr.ndim != 3 or not _fused_ok(xr, mats1, mats2):
        return False
    from . import dft_kernel as dk
    return dk.fits2(mode, xr.shape[1], xr.shape[2],
                    mats1[0].shape[1], mats2[0].shape[1])


def pdft_last_opt(xr, xi, mats):
    """:func:`pdft_last` through the fused stage kernel when eligible.
    Complex 3-matrix tuples only — a 2-matrix rdft tuple would pass the
    shared eligibility check (it is valid for the two-stage kernels) but
    crash the single-stage kernel's unpack.

    2-D operands (the z-stages, and the vmapped batched z-stages) take
    the kernel up to the full matmul cap: standalone the kernel beats
    the XLA stage at 384/512 too (4.09 vs 4.82 / 12.63 vs 13.58 ms —
    probe_r5_colblock.py); the >320 pair-level LOSS that set
    dft_kernel.max_dim() comes from the materialised swapaxes between
    kernel xy stages (XLA dots absorb those transposes via layout
    freedom, Pallas boundaries cannot), which a z-stage does not have."""
    if (not isinstance(mats, TwoStageMats) and len(mats) == 3
            and _fused_ok(xr, mats, cap=(MATMUL_DFT_MAX if xr.ndim == 2
                                         else None))):
        from . import dft_kernel as dk
        if dk.fits1(*np.shape(mats[0])):
            return dk.pdft_last(xr, xi, mats)
    return pdft_last(xr, xi, mats)


def _swap_pair(gr, gi):
    return jnp.swapaxes(gr, -1, -2), jnp.swapaxes(gi, -1, -2)


def pdft2_minor(xr, xi, mats1, mats2):
    """[minor DFT (mats1), transpose, minor DFT (mats2)] on planar
    complex ``(P, A, B)`` operands -> ``(P, B', A')``: one fused kernel
    when eligible, else the three-pass XLA form with per-stage fusion."""
    if _fits2_ok("cc", xr, mats1, mats2):
        from . import dft_kernel as dk
        return dk.pdft2(xr, xi, mats1, mats2)
    gr, gi = pdft_last_opt(xr, xi, mats1)
    gr, gi = _swap_pair(gr, gi)
    return pdft_last_opt(gr, gi, mats2)


def prdft2_minor(x, mats1, mats2):
    """R2C head twin of :func:`pdft2_minor`: real in, rdft stage 1."""
    if _fits2_ok("rc", x, mats1, mats2):
        from . import dft_kernel as dk
        return dk.prdft2(x, mats1, mats2)
    gr, gi = prdft_last(x, mats1)
    gr, gi = _swap_pair(gr, gi)
    return pdft_last_opt(gr, gi, mats2)


def cdft2_xy(x, mats_minor, mats_mid):
    """[minor-axis DFT (mats_minor), mid-axis DFT (mats_mid)] on a
    complex ``(..., mid, minor)`` operand -> ``(..., k_mid, k_minor)``
    — the distributed xy-stage shape (ops.stages.xy_*_c2c). One fused
    kernel with both transposes in VMEM when eligible; otherwise the
    XLA pair of stages around materialised swaps."""
    xr, xi = jnp.real(x), jnp.imag(x)
    if _fits2_ok("cc", xr, mats_minor, mats_mid):
        from . import dft_kernel as dk
        yr, yi = dk.pdft2_swapped(xr, xi, mats_minor, mats_mid)
        return yr + 1j * yi
    y = cdft_last(x, mats_minor)
    y = cdft_last(jnp.swapaxes(y, -1, -2), mats_mid)
    return jnp.swapaxes(y, -1, -2)


def pdft2_minor_cr(xr, xi, mats1, mats2):
    """C2R tail twin of :func:`pdft2_minor`: irdft stage 2, real out."""
    if _fits2_ok("cr", xr, mats1, mats2):
        from . import dft_kernel as dk
        return dk.pdft2_cr(xr, xi, mats1, mats2)
    gr, gi = pdft_last_opt(xr, xi, mats1)
    gr, gi = _swap_pair(gr, gi)
    return pirdft_last(gr, gi, mats2)


# -- stage-level helpers (mats builders with scale folding) ------------------

@dataclasses.dataclass(frozen=True)
class TwoStageMats:
    """Plan-time constants of the two-stage Cooley-Tukey DFT (see
    :func:`_pdft_two_stage`): stage matrices for the two factors plus
    the planar (n2, n1) twiddle. The caller's scale is folded into the
    stage-2 matrices."""

    n1: int
    n2: int
    mats1: tuple
    mats2: tuple
    tr: np.ndarray
    ti: np.ndarray


@functools.lru_cache(maxsize=1024)
def two_stage_factor(n: int):
    """The balanced factorization ``(n1, n2)`` with ``n1 * n2 == n``,
    both factors <= ``MATMUL_DFT_MAX`` and ``n1 + n2`` minimal (fewest
    MXU flops) — or ``None`` when ``n`` fits the direct form or has no
    such factorization (primes above the cap)."""
    if n <= MATMUL_DFT_MAX:
        return None
    import math
    for n1 in range(math.isqrt(n), 1, -1):
        if n % n1 == 0:
            n2 = n // n1
            if n1 <= MATMUL_DFT_MAX and n2 <= MATMUL_DFT_MAX:
                return n1, n2
            return None  # n2 only grows as n1 shrinks
    return None


def matmul_dft_limit() -> int:
    """Largest axis length the matmul-DFT layer can ever cover (the
    two-stage form with both factors at the cap). Individual lengths
    still need a valid factorization — gate with
    :func:`use_matmul_dft`."""
    return MATMUL_DFT_MAX * MATMUL_DFT_MAX


@functools.lru_cache(maxsize=32)
def _two_stage_mats(n: int, s: int, scale: float) -> TwoStageMats:
    n1, n2 = two_stage_factor(n)
    ang = s * 2 * np.pi * np.outer(np.arange(n2), np.arange(n1)) / n
    return TwoStageMats(n1, n2, _dft_mats(n1, s, 1.0),
                        _dft_mats(n2, s, scale),
                        np.ascontiguousarray(np.cos(ang).astype(np.float32)),
                        np.ascontiguousarray(np.sin(ang).astype(np.float32)))


def c2c_mats(n: int, sign: int, scale: float = 1.0):
    """Matrices for a complex length-``n`` DFT; ``scale`` is folded in.
    ``sign=BACKWARD`` with ``scale=1`` gives the library's unnormalised
    inverse (ifft * n — docs/source/details.rst 'Transform Definition'
    semantics, matching stages.z_backward). Lengths above
    ``MATMUL_DFT_MAX`` return :class:`TwoStageMats` (pdft_last
    dispatches on the type)."""
    s = +1 if sign == BACKWARD else -1
    # BACKWARD is the unnormalised inverse: e^{+...} with no 1/n — the
    # caller's extra scale folds directly either way
    if n > MATMUL_DFT_MAX:
        if two_stage_factor(n) is not None:
            return _two_stage_mats(n, s, float(scale))
        if n <= MATMUL_DFT_DIRECT_FALLBACK_MAX:
            # unfactorable (prime-class) length: direct form (see
            # MATMUL_DFT_DIRECT_FALLBACK_MAX for the flop rationale)
            return _dft_mats(n, s, float(scale))
        raise ValueError(
            f"axis length {n} exceeds MATMUL_DFT_MAX={MATMUL_DFT_MAX} "
            f"with no two-factor split and exceeds the direct fallback "
            f"cap {MATMUL_DFT_DIRECT_FALLBACK_MAX} — gate with "
            f"use_matmul_dft()")
    return _dft_mats(n, s, float(scale))


def r2c_mats(n: int, scale: float = 1.0):
    return _rdft_mats(n, float(scale))


def c2r_mats(n: int, scale: float = 1.0):
    """Unnormalised inverse real transform: irfft * n equivalents."""
    return _irdft_mats(n, float(scale))


@functools.lru_cache(maxsize=32)
def sub_rows_mats(n: int, sign: int, rows: tuple, scale: float = 1.0):
    """Row-selected complex DFT matrices (cached per window): the
    split-x contraction from the occupied positions only."""
    return _sub_rows(c2c_mats(n, sign, scale), np.asarray(rows))


@functools.lru_cache(maxsize=32)
def sub_cols_mats(n: int, sign: int, cols: tuple, scale: float = 1.0):
    """Column-selected complex DFT matrices (cached per window)."""
    return _sub_cols(c2c_mats(n, sign, scale), np.asarray(cols))


@functools.lru_cache(maxsize=32)
def sub_rows_c2r_mats(n: int, rows: tuple, scale: float = 1.0):
    """Row-selected inverse-real matrices: half-spectrum window -> dense
    real axis (hermitian weights ride along with their rows)."""
    return _sub_rows(c2r_mats(n, scale), np.asarray(rows))


@functools.lru_cache(maxsize=32)
def sub_cols_r2c_mats(n: int, cols: tuple, scale: float = 1.0):
    """Column-selected forward-real matrices: real axis -> half-spectrum
    window."""
    return _sub_cols(r2c_mats(n, scale), np.asarray(cols))


def mdft_axes(dtype, *dims, direct=(), direct_any=()) -> bool:
    """THE shared matmul-DFT routing predicate (one home so the plan
    pipeline, the stage-level xy gates and the precision model cannot
    drift): every axis in ``dims`` must be coverable (direct or
    two-stage — per axis, not just the max: one unfactorable axis above
    the fallback cap must fail the whole gate). Axes in ``direct``
    additionally need PLAIN c2c matrices (split-window row/column
    selections of ``c2c_mats``; composite lengths above the cap return
    TwoStageMats and do not qualify). Axes in ``direct_any`` need only
    a real-transform builder (``r2c_mats``/``c2r_mats`` are plain
    direct matrices at ANY length up to the fallback cap — composite
    768-class R2C x-axes included)."""
    return (all(use_matmul_dft(d, dtype) for d in dims)
            and all(_direct_form_len(d) for d in direct)
            and all(d <= MATMUL_DFT_DIRECT_FALLBACK_MAX
                    for d in direct_any))


def mdft_coverable(dims, hermitian: bool = False) -> bool:
    """Backend-independent STRUCTURAL half of the routing predicate:
    could these axes run the matmul-DFT forms at all (direct or
    two-stage; hermitian x-axis = ``dims[0]`` direct-only)? Used by the
    precision model, which must not depend on the importing process's
    backend."""
    ok = all(_mdft_covered_len(d) for d in dims)
    return ok and (not hermitian
                   or dims[0] <= MATMUL_DFT_DIRECT_FALLBACK_MAX)


def use_matmul_dft(n: int, dtype) -> bool:
    """Route a length-``n`` axis through the matmul DFT? TPU backend,
    single precision, direct form or a valid two-stage factorization.
    CPU keeps pocketfft (a real O(N log N) FFT); double precision keeps
    jnp.fft (f64 dots are emulated and slow on TPU, and the double path
    is CPU-bound anyway — docs/precision.md)."""
    import os
    single = jnp.dtype(dtype) in (jnp.dtype(jnp.float32),
                                  jnp.dtype(jnp.complex64))
    covered = _mdft_covered_len(n)
    if os.environ.get("SPFFT_TPU_FORCE_MATMUL_DFT") == "1":
        return single and covered  # force past the backend gate
    if os.environ.get("SPFFT_TPU_NO_MATMUL_DFT") == "1":
        return False
    return jax.default_backend() == "tpu" and covered and single

"""Fused sparse-compression + z-stick-DFT Pallas TPU kernels.

The reference's single biggest GPU win is fusing the sparse compression
scatter/gather directly into the transform kernels (compression_gpu +
the z-stick FFT never round-trip through global memory). Our pipeline
reproduced both halves as *separate* Pallas kernels — the windowed
gather (:mod:`~spfft_tpu.ops.gather_kernel`) and the matmul-DFT z stage
(:mod:`~spfft_tpu.ops.dft_kernel`) — with the dense
``(num_sticks, dim_z)`` planar stick pair materialised in HBM between
them, in both directions. These kernels close that gap:

* :func:`run_decompress_zdft` (backward): each grid step is one chunk
  of the narrow windowed-gather decomposition — it DMAs its K-row
  source window, assembles its gathered 1024-slot tile and accumulates
  it into a VMEM scratch covering a SUPER-TILE of ``r_sticks`` whole
  sticks; on a super-tile's last chunk the scratch is reshaped to
  ``(r_sticks, dim_z)`` and contracted against the resident Karatsuba
  DFT matrices, and only the *transformed* planar block is written.
  The dense pre-FFT stick intermediate never touches HBM.
* :func:`run_zdft_compress` (forward twin): each grid step DMAs the
  RAW stick rows covering its chunk's source window, z-transforms them
  in VMEM (any FULL scaling folded into the matrices at plan time —
  compile-time scaling, zero extra passes), slices the transformed
  window out of the flat slot layout, and runs the windowed compress
  gather against it. The transformed stick array never touches HBM;
  the cost is a bounded DFT recompute where windows overlap, which the
  plan-time cost model gates (:func:`compress_recompute_rows`).

Geometry: tables reuse the NARROW gather decomposition (chunks of one
1024-slot tile; chunks of a tile are consecutive grid steps). A fused
super-tile groups ``p_tiles`` consecutive 1024-slot tiles so that
``r_sticks * dim_z == p_tiles * 1024`` exactly — whole sticks per
output block. ``dim_z % 128 == 0`` keeps every in-kernel reshape in
the lane-preserving / sublane-merge family that the existing two-stage
kernels (ops.dft_kernel._kernel2) already exercise on Mosaic, and
makes the forward window slice row-aligned.

Eligibility (:func:`eligible_dim` + the plan's gate): f32 only,
``dim_z % 128 == 0``, ``dim_z`` within the fused-kernel axis cap
(:func:`spfft_tpu.ops.dft_kernel.max_dim` — the VMEM/perf ceiling),
unsegmented narrow tables, and the forward recompute model under
:data:`RECOMPUTE_LIMIT`. Everything else falls back to the two-kernel
path — same math, same layouts — with the reason recorded through
``obs`` (``spfft_plan_pallas_fallback_total``).

``SPFFT_TPU_FUSED_COMPRESS=0`` disables the fused path;
``SPFFT_TPU_FUSED_INTERPRET=1`` forces interpret-mode execution (and
activation off-TPU) for the CPU A/B lane (``benchmark.py --fused``,
``make fused-smoke``).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .dft_kernel import _dot, _kara
from .gather_kernel import (TILE, TILE_LANE, TILE_SUB,
                            MonotoneGatherTables, _tile_compute_win)

#: Target stick rows per backward super-tile: large enough that the
#: per-super-tile (r, dim_z) x (dim_z, dim_z) Karatsuba dot keeps the
#: MXU busy (>= 64 rows), small enough that the accumulation scratch
#: stays a footnote in the VMEM budget. Default for :func:`target_r`;
#: override per-experiment with ``SPFFT_TPU_FUSED_TARGET_R``.
TARGET_R = 64

#: Hard cap on 1024-slot tiles per super-tile (scratch rows =
#: p_tiles * 8; 64 tiles = 512 KB of f32 scratch per channel pair).
MAX_P_TILES = 64

#: Forward recompute ceiling: the fused forward z-transforms every
#: stick its chunk windows touch, so overlapping windows re-transform
#: sticks. The fused path declines when the modelled transformed rows
#: exceed this multiple of the unfused single pass (num_sticks rows) —
#: past it the DFT recompute outweighs the saved HBM round trip of the
#: transformed stick array (2 * num_sticks * dim_z * 8 bytes).
#: Default for :func:`recompute_limit`; override per-experiment with
#: ``SPFFT_TPU_FUSED_RECOMPUTE_LIMIT``.
RECOMPUTE_LIMIT = 4.0


def target_r() -> int:
    """Effective backward super-tile row target: the
    ``SPFFT_TPU_FUSED_TARGET_R`` env override (clamped to [8, 512],
    read per plan build so chip-profile retuning needs no code change)
    or :data:`TARGET_R`."""
    raw = os.environ.get("SPFFT_TPU_FUSED_TARGET_R", "").strip()
    if raw:
        try:
            return max(8, min(int(raw), 512))
        except ValueError:
            pass
    return TARGET_R


def recompute_limit() -> float:
    """Effective forward recompute ceiling: the
    ``SPFFT_TPU_FUSED_RECOMPUTE_LIMIT`` env override (clamped to
    [1.0, 64.0], read per plan build) or :data:`RECOMPUTE_LIMIT`."""
    raw = os.environ.get("SPFFT_TPU_FUSED_RECOMPUTE_LIMIT", "").strip()
    if raw:
        try:
            return max(1.0, min(float(raw), 64.0))
        except ValueError:
            pass
    return RECOMPUTE_LIMIT

#: Per-kernel VMEM budget the geometry chooser stays under — matches
#: the single-stage DFT kernel's empirically-calibrated ceiling
#: (ops.dft_kernel._VMEM_BUDGET rationale).
_VMEM_BUDGET = int(5.5 * 1024 * 1024)


def enabled() -> bool:
    """Fused compression+DFT is on by default where eligible;
    ``SPFFT_TPU_FUSED_COMPRESS=0`` disables (read per decision so tests
    and the benchmark A/B flag can flip it)."""
    return os.environ.get("SPFFT_TPU_FUSED_COMPRESS", "1").strip() != "0"


def interpret_forced() -> bool:
    """``SPFFT_TPU_FUSED_INTERPRET=1`` runs the fused kernels in
    interpret mode and activates them off-TPU — the CPU A/B and smoke
    lane (numbers there are honest overhead-only, like the overlap
    round's CPU A/B)."""
    return os.environ.get("SPFFT_TPU_FUSED_INTERPRET", "").strip() == "1"


def eligible_dim(dim_z: int):
    """Gate on the z-axis length alone. Returns ``None`` when eligible,
    else the fallback-reason string."""
    from . import dft_kernel as dk
    if dim_z <= 0 or dim_z % TILE_LANE != 0:
        return "dimz_not_multiple_128"
    if dim_z > dk.max_dim():
        return "dimz_over_cap"
    if not dk.fits1(dim_z, dim_z):
        return "vmem"
    return None


def super_tile_geometry(dim_z: int):
    """``(r_sticks, p_tiles)`` with ``r_sticks * dim_z == p_tiles *
    TILE`` exactly: whole sticks per super-tile, whole 1024-slot gather
    tiles per super-tile."""
    g = math.gcd(dim_z, TILE)
    r_min = TILE // g          # sticks per minimal super-tile
    p_min = dim_z // g         # 1024-slot tiles per minimal super-tile
    k = max(1, -(-target_r() // r_min))
    k = min(k, max(1, MAX_P_TILES // p_min))
    return r_min * k, p_min * k


def _fits_backward(dim_z: int, p_tiles: int, span_rows: int,
                   complete: bool = False) -> bool:
    mats = 3 * dim_z * dim_z
    window = 2 * 2 * span_rows * TILE_LANE
    scratch = 2 * p_tiles * TILE_SUB * TILE_LANE
    out = 2 * 2 * p_tiles * TILE  # double-buffered output blocks
    # hermitian completion: in-kernel one-hot mirror matrix + iota
    # transients (generous — the compiler reuses most of them)
    mirror = 4 * dim_z * dim_z if complete else 0
    return (mats + window + scratch + out + mirror) * 4 <= _VMEM_BUDGET


def _fits_forward(dim_z: int, win_sticks: int, span_rows: int) -> bool:
    mats = 3 * dim_z * dim_z
    window = 2 * 2 * win_sticks * dim_z          # raw-stick DMA buffers
    work = 6 * win_sticks * dim_z                # transformed + flat views
    out = 2 * 2 * TILE
    return (mats + window + work + out) * 4 <= _VMEM_BUDGET


# -- plan-time tables --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FusedDecompressTables:
    """Backward fused tables: the narrow decompress gather tables plus
    per-chunk super-tile metadata. Chunk order is tile-major (the
    narrow builder's revisiting order), so a super-tile's chunks are
    consecutive grid steps."""

    row0: np.ndarray     # (C,) int32 — DMA window start row (as narrow)
    pos: np.ndarray      # (C,) int32 — chunk's 1024-tile index WITHIN
                         # its super-tile (scratch slot)
    sfirst: np.ndarray   # (C,) int32 — 1 on a super-tile's first chunk
    slast: np.ndarray    # (C,) int32 — 1 on a super-tile's last chunk
    sup: np.ndarray      # (C,) int32 — output super-tile index
    packed: np.ndarray   # (C, 8, 128) int32 — narrow selector words
    dim_z: int
    r_sticks: int        # sticks per super-tile (output block rows)
    p_tiles: int         # 1024-slot tiles per super-tile
    num_super: int       # output blocks: ceil(num_tiles / p_tiles)
    num_sticks: int      # valid stick rows (callers slice [:num_sticks])
    src_rows: int        # padded source rows (as narrow)
    span_rows: int       # K: DMA window height
    #: r2c hermitian (0,0)-stick completion info: (2,) int32
    #: ``[z_sup, z_row]`` — the zero stick's super-tile and its row
    #: within it — or None for plans that need no in-kernel completion
    #: (c2c, or r2c without the (0,0) stick). ``z_sup = -1`` is the
    #: "never matches" sentinel the distributed shape-uniform tables
    #: use for shards that don't own the zero stick.
    zinfo: Optional[np.ndarray] = None


@dataclasses.dataclass(frozen=True)
class FusedCompressTables:
    """Forward fused tables: the narrow compress gather tables with the
    source windows re-expressed as RAW STICK ranges (the kernel
    transforms them in VMEM before gathering)."""

    s0: np.ndarray       # (C,) int32 — first raw stick of the window DMA
    off: np.ndarray      # (C,) int32 — transformed-window start row,
                         # relative to s0, in the flat (rows, 128) layout
    out_tile: np.ndarray  # (C,) int32 — output value tile (as narrow)
    first: np.ndarray    # (C,) int32 — 1 on a tile's first chunk
    packed: np.ndarray   # (C, 8, 128) int32 — narrow selector words
    dim_z: int
    win_sticks: int      # S_w: raw sticks DMA'd per chunk
    num_tiles: int       # output value tiles
    num_out: int         # valid output slots
    src_sticks: int      # padded raw-stick rows the source must carry
    span_rows: int       # K: transformed-window height (as narrow)


def build_fused_decompress_tables(t: MonotoneGatherTables, dim_z: int,
                                  num_sticks: int,
                                  zero_stick_id: Optional[int] = None):
    """Extend narrow decompress tables with the super-tile metadata the
    fused kernel needs, or return a fallback-reason string.

    ``zero_stick_id`` (r2c plans that own the (0,0) stick) folds the
    hermitian stick completion into the kernel: the zero stick's
    super-tile / row position rides along as the ``zinfo`` scalar pair
    and the kernel mirror-fills its empty z half before the z-DFT."""
    reason = eligible_dim(dim_z)
    if reason:
        return reason
    if t.segs:
        return "segmented"
    complete = zero_stick_id is not None
    r_sticks, p_tiles = super_tile_geometry(dim_z)
    if not _fits_backward(dim_z, p_tiles, t.span_rows, complete):
        return "vmem"
    sup = t.out_tile // p_tiles
    pos = t.out_tile - sup * p_tiles
    C = int(t.row0.shape[0])
    sfirst = np.zeros(C, np.int32)
    slast = np.zeros(C, np.int32)
    sfirst[0] = 1
    slast[-1] = 1
    sfirst[1:] |= (sup[1:] != sup[:-1]).astype(np.int32)
    slast[:-1] |= (sup[1:] != sup[:-1]).astype(np.int32)
    num_super = -(-t.num_tiles // p_tiles)
    zinfo = None
    if complete:
        zid = int(zero_stick_id)
        zinfo = np.array([zid // r_sticks, zid % r_sticks], np.int32)
    return FusedDecompressTables(
        row0=t.row0, pos=pos.astype(np.int32), sfirst=sfirst,
        slast=slast, sup=sup.astype(np.int32), packed=t.packed,
        dim_z=int(dim_z), r_sticks=r_sticks, p_tiles=p_tiles,
        num_super=num_super, num_sticks=int(num_sticks),
        src_rows=t.src_rows, span_rows=t.span_rows, zinfo=zinfo)


def compress_recompute_rows(t: MonotoneGatherTables, dim_z: int) -> int:
    """Stick rows the fused forward would z-transform in total (each
    chunk transforms its whole window) — the cost model's numerator."""
    q = dim_z // TILE_LANE
    win_sticks = -(-t.span_rows // q) + 1
    return int(t.row0.shape[0]) * win_sticks


def build_fused_compress_tables(t: MonotoneGatherTables, dim_z: int,
                                num_sticks: int):
    """Re-express narrow compress tables as raw-stick windows, or
    return a fallback-reason string. The cost-model gate declines when
    the window-overlap DFT recompute exceeds :data:`RECOMPUTE_LIMIT`
    times the unfused single transform pass."""
    reason = eligible_dim(dim_z)
    if reason:
        return reason
    if t.segs:
        return "segmented"
    q = dim_z // TILE_LANE
    win_sticks = -(-t.span_rows // q) + 1
    if not _fits_forward(dim_z, win_sticks, t.span_rows):
        return "vmem"
    if compress_recompute_rows(t, dim_z) > recompute_limit() \
            * max(int(num_sticks), 1):
        return "recompute_blowup"
    # window rows [row0, row0+K) of the flat (rows, 128) transformed
    # layout live inside raw sticks [s0, s0 + win_sticks)
    s0 = (t.row0.astype(np.int64) * TILE_LANE) // dim_z
    off = t.row0.astype(np.int64) - s0 * q
    assert int((off + t.span_rows).max(initial=0)) <= win_sticks * q
    # the DMA always reads the STATIC win_sticks rows from s0, so the
    # source must be padded to the furthest row any window's DMA touches
    src_sticks = max(int((s0 + win_sticks).max(initial=0)),
                     int(num_sticks))
    return FusedCompressTables(
        s0=s0.astype(np.int32), off=off.astype(np.int32),
        out_tile=t.out_tile, first=t.first, packed=t.packed,
        dim_z=int(dim_z), win_sticks=win_sticks,
        num_tiles=t.num_tiles, num_out=t.num_out,
        src_sticks=src_sticks, span_rows=t.span_rows)


def decompress_device_tables(t: FusedDecompressTables) -> tuple:
    """Device-committed table tuple for :func:`run_decompress_zdft`
    (plus the ``zinfo`` completion pair when the plan carries one — the
    kernel signature is static on its presence, so c2c plans trace the
    exact program they always did)."""
    base = (jnp.asarray(t.row0), jnp.asarray(t.pos),
            jnp.asarray(t.sfirst), jnp.asarray(t.slast),
            jnp.asarray(t.sup), jnp.asarray(t.packed))
    if t.zinfo is None:
        return base
    return base + (jnp.asarray(t.zinfo),)


def compress_device_tables(t: FusedCompressTables) -> tuple:
    """Device-committed table tuple for :func:`run_zdft_compress`."""
    return (jnp.asarray(t.s0), jnp.asarray(t.off),
            jnp.asarray(t.out_tile), jnp.asarray(t.first),
            jnp.asarray(t.packed))


def commit_mats(mats) -> tuple:
    """Device-committed Karatsuba DFT matrix triple. Any FULL scaling
    is already folded into the matrix VALUES at plan time —
    compile-time scaling, the kernels never multiply by a runtime
    scalar."""
    return tuple(jnp.asarray(np.asarray(m, np.float32)) for m in mats)


# -- backward kernel: gather-decompress -> z-DFT -----------------------------

def _complete_zero_stick(R, dz, xr, xi, is_z, z_row):
    """In-kernel r2c hermitian completion of the (0,0) stick, on the
    RAW (pre-z-DFT) super-tile rows: fill each empty z slot from its
    conjugate mirror ``F(-z) = conj(F(z))`` — exactly the unfused
    ``where(nz, v, ±roll(v[::-1], 1))`` of the two-kernel path
    (plan._backward_rest_tp), expressed as a one-hot MXU contraction
    because Mosaic has no ``rev`` lowering. One-hot rows make the dot a
    single exact f32 product per element, so the fused and unfused
    paths stay bit-identical. ``is_z`` (this super-tile owns the zero
    stick) and ``z_row`` arrive as DATA, not trace constants, so one
    compiled program serves every shard of a distributed plan."""
    row_r = jax.lax.dynamic_slice_in_dim(xr, z_row, 1, 0)   # (1, dz)
    row_i = jax.lax.dynamic_slice_in_dim(xi, z_row, 1, 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (dz, dz), 0)
    kk = jax.lax.broadcasted_iota(jnp.int32, (dz, dz), 1)
    jk = jj + kk
    # M[j, k] = 1 iff (j + k) % dz == 0, so (row @ M)[k] = row[(dz-k)%dz]
    mir = jnp.where((jk == 0) | (jk == dz), 1.0, 0.0).astype(jnp.float32)
    mir_r = _dot(row_r, mir)
    mir_i = _dot(row_i, mir)
    nz = (row_r != 0.0) | (row_i != 0.0)
    new_r = jnp.where(nz, row_r, mir_r)
    new_i = jnp.where(nz, row_i, -mir_i)
    rowsel = (jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)
              == z_row) & is_z
    return jnp.where(rowsel, new_r, xr), jnp.where(rowsel, new_i, xi)


def _dec_zdft_body(K, P, R, dz, complete, g, pos_ref, sfirst_ref,
                   slast_ref, sup_ref, zinfo_ref, packed_ref,
                   cr_ref, ci_ref, cs_ref, write, acc, sc, slot):
    """Shared per-step body of the backward fused kernel. ``write``
    stores the transformed (R, dz) planar pair on the super-tile's last
    chunk; DMA wait has already happened. ``complete`` statically gates
    the r2c (0,0)-stick hermitian completion (``zinfo_ref`` is None —
    and never read — without it)."""
    acc_re, acc_im = _tile_compute_win(K, packed_ref[0],
                                       sc[slot, 0], sc[slot, 1])

    @pl.when(sfirst_ref[g] == 1)
    def _():
        acc[0] = jnp.zeros((P * TILE_SUB, TILE_LANE), jnp.float32)
        acc[1] = jnp.zeros((P * TILE_SUB, TILE_LANE), jnp.float32)

    p8 = pos_ref[g] * TILE_SUB
    acc[0, pl.ds(p8, TILE_SUB)] = acc[0, pl.ds(p8, TILE_SUB)] + acc_re
    acc[1, pl.ds(p8, TILE_SUB)] = acc[1, pl.ds(p8, TILE_SUB)] + acc_im

    @pl.when(slast_ref[g] == 1)
    def _():
        xr = acc[0].reshape(R, dz)
        xi = acc[1].reshape(R, dz)
        if complete:
            xr, xi = _complete_zero_stick(
                R, dz, xr, xi, zinfo_ref[0] == sup_ref[g], zinfo_ref[1])
        yr, yi = _kara(xr, xi, cr_ref[...], ci_ref[...], cs_ref[...])
        write(yr, yi)


def _kernel_dec_zdft(K, P, R, dz, complete, *refs):
    if complete:
        (row0_ref, pos_ref, sfirst_ref, slast_ref, sup_ref, zinfo_ref,
         packed_ref, cr_ref, ci_ref, cs_ref, re_hbm, im_hbm,
         out_r_ref, out_i_ref, acc, sc, sem) = refs
    else:
        (row0_ref, pos_ref, sfirst_ref, slast_ref, sup_ref,
         packed_ref, cr_ref, ci_ref, cs_ref, re_hbm, im_hbm,
         out_r_ref, out_i_ref, acc, sc, sem) = refs
        zinfo_ref = None
    g = pl.program_id(0)
    n_g = pl.num_programs(0)

    def dma(gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(gg):
        slot = jax.lax.rem(jnp.asarray(gg, jnp.int32), jnp.int32(2))
        dma(gg, slot, 0, re_hbm).start()
        dma(gg, slot, 1, im_hbm).start()

    @pl.when(g == 0)
    def _():
        start(0)

    @pl.when(g + 1 < n_g)
    def _():
        start(g + 1)

    slot = jax.lax.rem(jnp.asarray(g, jnp.int32), jnp.int32(2))
    dma(g, slot, 0, re_hbm).wait()
    dma(g, slot, 1, im_hbm).wait()

    def write(yr, yi):
        out_r_ref[...] = yr
        out_i_ref[...] = yi

    _dec_zdft_body(K, P, R, dz, complete, g, pos_ref, sfirst_ref,
                   slast_ref, sup_ref, zinfo_ref, packed_ref,
                   cr_ref, ci_ref, cs_ref, write, acc, sc, slot)


def _kernel_dec_zdft_batched(K, P, R, dz, complete, *refs):
    """Batched grid (B, C): batch b gathers+transforms slab b through
    the shared tables; DMA pipeline prefetches across the batch
    boundary (the gather kernels' pattern)."""
    if complete:
        (row0_ref, pos_ref, sfirst_ref, slast_ref, sup_ref, zinfo_ref,
         packed_ref, cr_ref, ci_ref, cs_ref, re_hbm, im_hbm,
         out_r_ref, out_i_ref, acc, sc, sem) = refs
    else:
        (row0_ref, pos_ref, sfirst_ref, slast_ref, sup_ref,
         packed_ref, cr_ref, ci_ref, cs_ref, re_hbm, im_hbm,
         out_r_ref, out_i_ref, acc, sc, sem) = refs
        zinfo_ref = None
    b = pl.program_id(0)
    g = pl.program_id(1)
    n_b = pl.num_programs(0)
    n_g = pl.num_programs(1)
    step = b * n_g + g

    def dma(bb, gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[bb, pl.ds(row0_ref[gg], K), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(bb, gg, slot):
        dma(bb, gg, slot, 0, re_hbm).start()
        dma(bb, gg, slot, 1, im_hbm).start()

    @pl.when(step == 0)
    def _():
        start(0, 0, 0)

    @pl.when(step + 1 < n_b * n_g)
    def _():
        nxt_b = jnp.where(g + 1 < n_g, b, b + 1)
        nxt_g = jnp.where(g + 1 < n_g, g + 1, 0)
        start(nxt_b, nxt_g, jax.lax.rem(step + 1, jnp.int32(2)))

    slot = jax.lax.rem(step, jnp.int32(2))
    dma(b, g, slot, 0, re_hbm).wait()
    dma(b, g, slot, 1, im_hbm).wait()

    def write(yr, yi):
        out_r_ref[0] = yr
        out_i_ref[0] = yi

    _dec_zdft_body(K, P, R, dz, complete, g, pos_ref, sfirst_ref,
                   slast_ref, sup_ref, zinfo_ref, packed_ref,
                   cr_ref, ci_ref, cs_ref, write, acc, sc, slot)


def run_decompress_zdft(re, im, dev_tables: tuple, mats: tuple,
                        t: FusedDecompressTables,
                        interpret: bool = False):
    """Gathered decompress + z-DFT in one ``pallas_call``.

    Args:
      re, im: (src_rows, 128) planar f32 value source — or
        (B, src_rows, 128) batched.
      dev_tables: :func:`decompress_device_tables` output.
      mats: :func:`commit_mats` backward z-DFT triple.
    Returns:
      (sr, si): transformed planar sticks, each
      ``(num_super * r_sticks, dim_z)`` f32 (leading B when batched);
      rows ``[:num_sticks]`` are the valid sticks.
    """
    from .. import faults as _faults
    _faults.check_site("kernel.launch")  # trace time: once per compile
    C = int(t.row0.shape[0])
    K, P, R, dz = t.span_rows, t.p_tiles, t.r_sticks, t.dim_z
    complete = t.zinfo is not None
    n_scalar = 6 if complete else 5  # (+ zinfo) row0, pos, sfirst,
    scratch = [                      # slast, sup
        pltpu.VMEM((2, P * TILE_SUB, TILE_LANE), jnp.float32),
        pltpu.VMEM((2, 2, K, TILE_LANE), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    mat_specs = [pl.BlockSpec((dz, dz), lambda *a: (0, 0))] * 3
    if re.ndim == 3:
        B = re.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=n_scalar,
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda b, g, *_: (g, 0, 0)),
            ] + mat_specs + [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec((1, R, dz),
                             lambda b, g, r0, ps, sf, sl, sp, *_:
                             (b, sp[g], 0)),
                pl.BlockSpec((1, R, dz),
                             lambda b, g, r0, ps, sf, sl, sp, *_:
                             (b, sp[g], 0)),
            ),
            scratch_shapes=scratch,
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, t.num_super * R, dz), jnp.float32),
            jax.ShapeDtypeStruct((B, t.num_super * R, dz), jnp.float32))
        kern = functools.partial(_kernel_dec_zdft_batched, K, P, R, dz,
                                 complete)
    else:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=n_scalar,
            grid=(C,),
            in_specs=[
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda g, *_: (g, 0, 0)),
            ] + mat_specs + [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec((R, dz),
                             lambda g, r0, ps, sf, sl, sp, *_:
                             (sp[g], 0)),
                pl.BlockSpec((R, dz),
                             lambda g, r0, ps, sf, sl, sp, *_:
                             (sp[g], 0)),
            ),
            scratch_shapes=scratch,
        )
        out_shape = (
            jax.ShapeDtypeStruct((t.num_super * R, dz), jnp.float32),
            jax.ShapeDtypeStruct((t.num_super * R, dz), jnp.float32))
        kern = functools.partial(_kernel_dec_zdft, K, P, R, dz, complete)
    assert len(dev_tables) == (7 if complete else 6)
    row0, pos, sfirst, slast, sup, packed = dev_tables[:6]
    zex = dev_tables[6:]
    cr, ci, cs = mats
    return pl.pallas_call(
        kern, out_shape=out_shape, grid_spec=grid_spec,
        interpret=interpret,
    )(row0, pos, sfirst, slast, sup, *zex, packed, cr, ci, cs, re, im)


# -- forward kernel: z-DFT -> windowed compress gather -----------------------

def _zdft_cmp_body(K, S_w, q, g, off_ref, first_ref, packed_ref,
                   cr_ref, ci_ref, cs_ref, sc, slot, store):
    """Shared per-step body of the forward fused kernel: transform the
    DMA'd raw sticks, slice the chunk's flat window out, gather."""
    xr = sc[slot, 0]
    xi = sc[slot, 1]
    yr, yi = _kara(xr, xi, cr_ref[...], ci_ref[...], cs_ref[...])
    # (S_w, q*128) -> (S_w*q, 128): lane-preserving leading-dim split
    fr = yr.reshape(S_w * q, TILE_LANE)
    fi = yi.reshape(S_w * q, TILE_LANE)
    win_re = jax.lax.dynamic_slice_in_dim(fr, off_ref[g], K, 0)
    win_im = jax.lax.dynamic_slice_in_dim(fi, off_ref[g], K, 0)
    acc_re, acc_im = _tile_compute_win(K, packed_ref[0], win_re, win_im)
    store(first_ref[g], acc_re, acc_im)


def _kernel_zdft_cmp(K, S_w, q, s0_ref, off_ref, out_tile_ref, first_ref,
                     packed_ref, cr_ref, ci_ref, cs_ref, re_hbm, im_hbm,
                     out_re_ref, out_im_ref, sc, sem):
    g = pl.program_id(0)
    n_g = pl.num_programs(0)

    def dma(gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[pl.ds(s0_ref[gg], S_w), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(gg):
        slot = jax.lax.rem(jnp.asarray(gg, jnp.int32), jnp.int32(2))
        dma(gg, slot, 0, re_hbm).start()
        dma(gg, slot, 1, im_hbm).start()

    @pl.when(g == 0)
    def _():
        start(0)

    @pl.when(g + 1 < n_g)
    def _():
        start(g + 1)

    slot = jax.lax.rem(jnp.asarray(g, jnp.int32), jnp.int32(2))
    dma(g, slot, 0, re_hbm).wait()
    dma(g, slot, 1, im_hbm).wait()

    def store(frst, acc_re, acc_im):
        @pl.when(frst == 1)
        def _():
            out_re_ref[0] = acc_re
            out_im_ref[0] = acc_im

        @pl.when(frst == 0)
        def _():
            out_re_ref[0] = out_re_ref[0] + acc_re
            out_im_ref[0] = out_im_ref[0] + acc_im

    _zdft_cmp_body(K, S_w, q, g, off_ref, first_ref, packed_ref,
                   cr_ref, ci_ref, cs_ref, sc, slot, store)


def _kernel_zdft_cmp_batched(K, S_w, q, s0_ref, off_ref, out_tile_ref,
                             first_ref, packed_ref, cr_ref, ci_ref, cs_ref,
                             re_hbm, im_hbm, out_re_ref, out_im_ref,
                             sc, sem):
    b = pl.program_id(0)
    g = pl.program_id(1)
    n_b = pl.num_programs(0)
    n_g = pl.num_programs(1)
    step = b * n_g + g

    def dma(bb, gg, slot, chan, hbm):
        return pltpu.make_async_copy(
            hbm.at[bb, pl.ds(s0_ref[gg], S_w), :], sc.at[slot, chan],
            sem.at[slot, chan])

    def start(bb, gg, slot):
        dma(bb, gg, slot, 0, re_hbm).start()
        dma(bb, gg, slot, 1, im_hbm).start()

    @pl.when(step == 0)
    def _():
        start(0, 0, 0)

    @pl.when(step + 1 < n_b * n_g)
    def _():
        nxt_b = jnp.where(g + 1 < n_g, b, b + 1)
        nxt_g = jnp.where(g + 1 < n_g, g + 1, 0)
        start(nxt_b, nxt_g, jax.lax.rem(step + 1, jnp.int32(2)))

    slot = jax.lax.rem(step, jnp.int32(2))
    dma(b, g, slot, 0, re_hbm).wait()
    dma(b, g, slot, 1, im_hbm).wait()

    def store(frst, acc_re, acc_im):
        @pl.when(frst == 1)
        def _():
            out_re_ref[0, 0] = acc_re
            out_im_ref[0, 0] = acc_im

        @pl.when(frst == 0)
        def _():
            out_re_ref[0, 0] = out_re_ref[0, 0] + acc_re
            out_im_ref[0, 0] = out_im_ref[0, 0] + acc_im

    _zdft_cmp_body(K, S_w, q, g, off_ref, first_ref, packed_ref,
                   cr_ref, ci_ref, cs_ref, sc, slot, store)


def run_zdft_compress(sr, si, dev_tables: tuple, mats: tuple,
                      t: FusedCompressTables,
                      interpret: bool = False):
    """z-DFT + windowed compress gather in one ``pallas_call``.

    Args:
      sr, si: (src_sticks, dim_z) planar f32 RAW (un-transformed)
        sticks — or (B, src_sticks, dim_z) batched. Rows past the
        plan's num_sticks must be zero.
      dev_tables: :func:`compress_device_tables` output.
      mats: :func:`commit_mats` forward z-DFT triple (scaling folded
        into the matrices).
    Returns:
      (out_re, out_im): each (num_tiles, 8, 128) f32 (leading B when
      batched); the flat prefix holds the ``num_out`` output values.
    """
    from .. import faults as _faults
    _faults.check_site("kernel.launch")  # trace time: once per compile
    C = int(t.s0.shape[0])
    K, S_w, dz = t.span_rows, t.win_sticks, t.dim_z
    q = dz // TILE_LANE
    scratch = [
        pltpu.VMEM((2, 2, S_w, dz), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    mat_specs = [pl.BlockSpec((dz, dz), lambda *a: (0, 0))] * 3
    if sr.ndim == 3:
        B = sr.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,  # s0, off, out_tile, first
            grid=(B, C),
            in_specs=[
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda b, g, s0, of, ot, fs: (g, 0, 0)),
            ] + mat_specs + [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec((1, 1, TILE_SUB, TILE_LANE),
                             lambda b, g, s0, of, ot, fs:
                             (b, ot[g], 0, 0)),
                pl.BlockSpec((1, 1, TILE_SUB, TILE_LANE),
                             lambda b, g, s0, of, ot, fs:
                             (b, ot[g], 0, 0)),
            ),
            scratch_shapes=scratch,
        )
        out_shape = (
            jax.ShapeDtypeStruct((B, t.num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32),
            jax.ShapeDtypeStruct((B, t.num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32))
        kern = functools.partial(_kernel_zdft_cmp_batched, K, S_w, q)
    else:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(C,),
            in_specs=[
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda g, s0, of, ot, fs: (g, 0, 0)),
            ] + mat_specs + [
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda g, s0, of, ot, fs: (ot[g], 0, 0)),
                pl.BlockSpec((1, TILE_SUB, TILE_LANE),
                             lambda g, s0, of, ot, fs: (ot[g], 0, 0)),
            ),
            scratch_shapes=scratch,
        )
        out_shape = (
            jax.ShapeDtypeStruct((t.num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32),
            jax.ShapeDtypeStruct((t.num_tiles, TILE_SUB, TILE_LANE),
                                 jnp.float32))
        kern = functools.partial(_kernel_zdft_cmp, K, S_w, q)
    s0, off, out_tile, first, packed = dev_tables
    cr, ci, cs = mats
    return pl.pallas_call(
        kern, out_shape=out_shape, grid_spec=grid_spec,
        interpret=interpret,
    )(s0, off, out_tile, first, packed, cr, ci, cs, sr, si)


def pad_sticks_planar(sr, si, src_sticks: int):
    """Zero-pad planar (num_sticks, dim_z) stick channels — or batched
    (B, num_sticks, dim_z) — to the ``src_sticks`` rows the forward
    kernel's window DMAs may touch (a handful of rows; XLA folds the
    pad into the producing op's output buffer)."""
    pad = src_sticks - sr.shape[-2]
    if pad <= 0:
        return sr, si
    widths = [(0, 0)] * (sr.ndim - 2) + [(0, pad), (0, 0)]
    return jnp.pad(sr, widths), jnp.pad(si, widths)

"""SLO watchdog: declared objectives evaluated against live metrics.

An operator declares service-level objectives — p99 request latency, an
error-rate budget, a quarantine ceiling — and the watchdog evaluates
them against :class:`~spfft_tpu.serve.metrics.ServeMetrics` snapshots:
each objective's BURN RATE (observed / objective) is exported as a
``spfft_slo_*`` Prometheus gauge, and when any burn rate exceeds the
declared budget the executor's ``health()`` flips to ``degraded`` (via
``ServeMetrics.record_slo`` — the raw lifecycle state is preserved;
SLO pressure only ever degrades an otherwise-healthy report, it cannot
mask a failed executor).

Declaration formats (docs/control_plane.md "SLO declaration"):

* programmatic — ``SLOSpec(latency_p99_s=0.050, error_rate=0.01,
  max_quarantines=0)`` (any subset; None = objective not declared);
* CLI string — ``"p99_ms=50,error_rate=0.01,max_quarantines=0"``
  (``serve.bench --slo``);
* JSON file — ``{"latency_p99_s": 0.05, "error_rate": 0.01,
  "max_quarantines": 0}`` (``--slo @objectives.json``).

Burn-rate semantics: for a positive objective, ``observed /
objective``; for a ZERO objective (e.g. ``max_quarantines=0`` — "never
quarantine"), any observation at all burns infinitely. A violation is
``burn > budget`` (budget default 1.0 — at the objective is still
within it). Evaluation is pure arithmetic over one consistent metrics
snapshot: deterministic given the snapshot, cheap enough to run every
controller step.

Multi-window alerting (round 18, the SRE-workbook shape): a single
evaluation's violation degrades ``health()`` immediately (cheap,
reversible), but PAGING on it would wake an operator for every blip.
The watchdog therefore also keeps two rolling burn windows per
objective — ``fast_window`` and ``slow_window`` evaluations
(evaluation counts, not seconds: determinism again) — and raises the
page condition only while BOTH window means exceed the budget: the
fast window proves the burn is current, the slow window proves it is
sustained. Exported as ``spfft_slo_window_burn_rate{slo,window}``,
``spfft_slo_window_alert`` and the rising-edge counter
``spfft_slo_window_alerts_total``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
from typing import Dict, List, Optional

from ..errors import InvalidParameterError


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Declared objectives; ``None`` leaves an objective undeclared."""

    latency_p99_s: Optional[float] = None
    error_rate: Optional[float] = None
    max_quarantines: Optional[float] = None

    def __post_init__(self):
        for name in ("latency_p99_s", "error_rate", "max_quarantines"):
            v = getattr(self, name)
            if v is not None and (not isinstance(v, (int, float))
                                  or v < 0 or math.isnan(float(v))):
                raise InvalidParameterError(
                    f"SLO objective {name} must be a number >= 0, "
                    f"got {v!r}")

    def declared(self) -> Dict[str, float]:
        return {name: float(v) for name, v in dataclasses.asdict(
            self).items() if v is not None}

    @classmethod
    def parse(cls, text: str) -> "SLOSpec":
        """``"p99_ms=50,error_rate=0.01,max_quarantines=0"`` or
        ``"@file.json"`` (a JSON object of objective fields)."""
        text = text.strip()
        if text.startswith("@"):
            try:
                with open(text[1:]) as f:
                    payload = json.load(f)
            except (OSError, ValueError) as exc:
                raise InvalidParameterError(
                    f"cannot read SLO file {text[1:]!r}: {exc}")
            if not isinstance(payload, dict):
                raise InvalidParameterError(
                    f"SLO file {text[1:]!r} must hold a JSON object")
            try:
                return cls(**payload)
            except TypeError as exc:
                raise InvalidParameterError(f"bad SLO file: {exc}")
        kwargs: Dict[str, float] = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise InvalidParameterError(
                    f"bad SLO entry {part!r} (want key=value)")
            key, _, value = part.partition("=")
            key = key.strip()
            try:
                v = float(value)
            except ValueError:
                raise InvalidParameterError(
                    f"bad SLO value in {part!r}")
            if key in ("p99_ms", "latency_p99_ms"):
                kwargs["latency_p99_s"] = v / 1e3
            elif key in ("p99_s", "latency_p99_s"):
                kwargs["latency_p99_s"] = v
            elif key == "error_rate":
                kwargs["error_rate"] = v
            elif key == "max_quarantines":
                kwargs["max_quarantines"] = v
            else:
                raise InvalidParameterError(
                    f"unknown SLO objective {key!r} (want p99_ms / "
                    f"p99_s / error_rate / max_quarantines)")
        return cls(**kwargs)


def _burn(observed: float, objective: float) -> float:
    if objective > 0:
        return observed / objective
    return math.inf if observed > 0 else 0.0


class SLOWatchdog:
    """Evaluates an :class:`SLOSpec` against ``metrics`` snapshots.

    :meth:`evaluate` returns ``{"violations": [...], "burn": {...},
    "observed": {...}, "objectives": {...}}`` and pushes the result
    into the Prometheus registry and the metrics sink's health state.
    """

    def __init__(self, metrics, spec: SLOSpec, budget: float = 1.0,
                 fast_window: int = 6, slow_window: int = 30):
        if budget <= 0:
            raise InvalidParameterError("SLO budget must be > 0")
        if fast_window < 1 or slow_window < fast_window:
            raise InvalidParameterError(
                "want 1 <= fast_window <= slow_window, got "
                f"{fast_window}/{slow_window}")
        self.metrics = metrics
        self.spec = spec
        self.budget = float(budget)
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        self.evaluations = 0
        #: per-objective rolling burn history (slow_window deep) and
        #: the set of objectives currently in the page condition (for
        #: rising-edge counting) — evaluate() is the only writer
        self._burn_hist: Dict[str, collections.deque] = {}
        self._alerting: set = set()

    def _window_burns(self, name: str) -> Dict[str, float]:
        hist = self._burn_hist[name]
        fast = list(hist)[-self.fast_window:]
        slow = list(hist)
        return {"fast": sum(fast) / len(fast),
                "slow": sum(slow) / len(slow)}

    def _observed(self, signals: Dict) -> Dict[str, float]:
        completed = signals.get("completed", 0)
        failed = signals.get("failed", 0)
        total = completed + failed
        return {
            "latency_p99_s": signals.get("latency_p99", 0.0),
            "error_rate": (failed / total) if total else 0.0,
            "max_quarantines": signals.get("quarantines", 0),
        }

    def evaluate(self, signals: Optional[Dict] = None) -> Dict:
        """One evaluation over ``signals`` (defaults to a fresh
        ``metrics.signals()`` snapshot)."""
        if signals is None:
            signals = self.metrics.signals()
        observed_all = self._observed(signals)
        objectives = self.spec.declared()
        burn: Dict[str, float] = {}
        observed: Dict[str, float] = {}
        violations = []
        for name, objective in objectives.items():
            obs_v = observed_all[name]
            b = _burn(obs_v, objective)
            burn[name] = b
            observed[name] = obs_v
            if b > self.budget:
                violations.append(name)
        self.evaluations += 1
        window_burn: Dict[str, Dict[str, float]] = {}
        window_alerts: List[str] = []
        for name in objectives:
            hist = self._burn_hist.setdefault(
                name, collections.deque(maxlen=self.slow_window))
            hist.append(burn[name])
            window_burn[name] = self._window_burns(name)
            # Page only on evidence a full fast window deep: both
            # windows burning above budget. Shorter history is at most
            # a health degradation (the single-eval violation above),
            # never a page.
            if (len(hist) >= self.fast_window
                    and window_burn[name]["fast"] > self.budget
                    and window_burn[name]["slow"] > self.budget):
                window_alerts.append(name)
        from .. import obs
        obs.GLOBAL_COUNTERS.inc("spfft_slo_evaluations_total", 1,
                                help="SLO watchdog evaluations.")
        for name, objective in objectives.items():
            labels = {"slo": name}
            obs.GLOBAL_COUNTERS.set(
                "spfft_slo_objective", objective,
                help="Declared SLO objective value.", **labels)
            obs.GLOBAL_COUNTERS.set(
                "spfft_slo_observed", observed[name],
                help="Observed value at last SLO evaluation.", **labels)
            obs.GLOBAL_COUNTERS.set(
                "spfft_slo_burn_rate",
                burn[name] if math.isfinite(burn[name]) else -1.0,
                help="observed/objective at last evaluation (-1 = "
                     "infinite: a zero objective was burned).",
                **labels)
            obs.GLOBAL_COUNTERS.set(
                "spfft_slo_violation",
                1 if name in violations else 0,
                help="1 while this SLO's burn rate exceeds its budget.",
                **labels)
            for window in ("fast", "slow"):
                wb = window_burn[name][window]
                obs.GLOBAL_COUNTERS.set(
                    "spfft_slo_window_burn_rate",
                    wb if math.isfinite(wb) else -1.0,
                    help="Mean burn rate over each alerting window "
                         "(labels: slo, window=fast|slow; -1 = "
                         "infinite).",
                    slo=name, window=window)
            obs.GLOBAL_COUNTERS.set(
                "spfft_slo_window_alert",
                1 if name in window_alerts else 0,
                help="1 while BOTH burn windows of this SLO exceed "
                     "the budget (multi-window page condition).",
                **labels)
        for name in window_alerts:
            if name not in self._alerting:
                obs.GLOBAL_COUNTERS.inc(
                    "spfft_slo_window_alerts_total", 1,
                    help="Multi-window page conditions entered.",
                    slo=name)
                obs.record_event("slo.alert", slo=name)
                # the rising edge is a flight-recorder auto trigger:
                # snapshot the black box the moment the page condition
                # is entered, not when an operator notices
                obs.maybe_auto_capture("slo_alert", name)
        self._alerting = set(window_alerts)
        if violations:
            obs.GLOBAL_COUNTERS.inc(
                "spfft_slo_violations_total", len(violations),
                help="SLO violations observed across evaluations.")
        if obs.active():
            obs.GLOBAL_TRACER.instant(
                "slo.evaluate", cat="control", track="control",
                args={"violations": ",".join(violations) or "none",
                      "budget": self.budget})
        if self.metrics is not None:
            self.metrics.record_slo(violations)
        return {"violations": violations, "burn": burn,
                "observed": observed, "objectives": objectives,
                "budget": self.budget, "window_burn": window_burn,
                "window_alerts": window_alerts}

"""Feedback controller: the obs→serve loop, closed.

Consumes the live telemetry the serving stack already produces
(:meth:`ServeMetrics.signals` — queue-wait and device-execute
reservoirs, padded-rows and batch-histogram counters, stage/dispatch
overhead accounting) and retunes the executor's :class:`ServeConfig`
online. Every rule is DETERMINISTIC — pure arithmetic over counter
deltas between steps, no wall-clock reads, no randomness — so a
scripted telemetry sequence always produces the same decision sequence
(the property the tier-1 scenario tests pin).

Signals → rules → knobs (the docs/control_plane.md table, in code):

* **batch_window** ← queue-wait p95 vs device-execute p50. Requests
  waiting much longer than a bucket takes to execute means the window
  is holding a backlog hostage → HALVE the window. Queue drained well
  below the execute time → decay back toward the default (the window
  only ever helps a trickle).
* **pin_after** ← padded-rows ratio. A pad-heavy delta (ladder pad rows
  per fused live row above ``pad_hi``) means the adaptive pinning
  observer is too slow for this trace → pin one bucket sooner. Pads
  gone → decay back toward the default.
* **max_batch** ← fused batch histogram + queue depth. Buckets
  repeatedly full AT the cap while a backlog persists → double the cap
  (more rows per dispatch). Largest fused bucket far below the cap →
  halve back toward the default.
* **pipeline_depth** ← stage-vs-dispatch overlap ratio. Host staging
  cost rivaling dispatch cost means the host is on the critical path →
  one more in-flight slot to overlap it. Staging negligible → decay to
  the backend-aware auto depth (0).
* **overlap_chunks** ← exchange-vs-compute span ratio. The
  distributed dispatch path records cumulative exchange and
  exchange-compute seconds (``ServeMetrics.record_exchange_overlap``,
  fed from the overlap pipeline's recorded spans); exchange time
  rivaling compute time on ``overlap_streak_steps`` CONSECUTIVE steps
  means the pipeline has compute left to hide the wire behind → DOUBLE
  K (within the declared 1..64 clamp). Exchange well hidden (ratio
  below ``overlap_lo``) → halve back toward the K=1 default, which is
  the bit-identical monolithic path. The streak is the hysteresis —
  one chunky step moves nothing.
* **wire_precision** ← the same exchange-vs-compute deltas, behind
  HARDER thresholds (``wire_hi`` > ``overlap_hi``, longer streak).
  Chunking hides wire time for free; compression spends accuracy
  budget — so the rung escalates one step only when the exchange still
  dominates after the chunking rule has had its chance, and decays one
  step back when the wire is well hidden. Plans built under the new
  value re-probe against their own declared ``wire_error_budget`` and
  may still refuse the rung (the budget gate belongs to the plan, not
  the controller); rung moves are counted
  (``spfft_wire_rung_changes_total{direction}``).
* **max_queue** ← ``rejected_queue_full`` burn. Rejects on
  ``reject_streak_steps`` CONSECUTIVE steps mean the queue bound is
  turning a transient burst into dropped traffic → DOUBLE the bound
  (still clamped to the declared KNOB_SPECS range; memory pressure is
  the hard bound, not the soft one). A single-step blip changes
  nothing — backpressure on a genuine overload is the knob working as
  designed. Idle periods decay the bound back toward the default by
  halving (retracing the growth path).
* **spmd_batch_window / spmd_max_batch** ← SPMD queue depth vs
  collective-launch p50 (``SPMDCoalescer.signals()``, merged in when a
  coalescer is attached). Distributed requests backing up (depth >= 2)
  while the coalescing window is shorter than one collective launch on
  consecutive distributed steps means arrivals during a launch miss
  the next window → DOUBLE the window (more requests per collective
  round); a window above default that coalesces nothing decays back by
  halving. Rounds repeatedly full AT the batch cap with a backlog →
  double ``spmd_max_batch``; rounds far below an elevated cap → halve
  it back (the fused ``max_batch`` rule, re-aimed at the distributed
  lane).

Stability machinery, also deterministic:

* **hysteresis** — every rule's shrink and grow thresholds are far
  apart (``shrink_ratio`` vs ``grow_ratio``, ``pad_hi`` vs ``pad_lo``),
  so a signal sitting between them changes nothing;
* **cooldown** — after a knob moves, that knob is frozen for
  ``cooldown_steps`` controller steps (steps, not seconds: determinism
  again), so one burst cannot see-saw a knob within its own settling
  time;
* **idle decay** — a step with zero completed work and an empty queue
  walks every managed knob one move back toward its declared default.

Bounds are the config's own clamp — a rule can *request* anything and
the knob still never leaves its declared range (the fuzz invariant).

:class:`ControlLoop` wraps a controller in a background thread for live
serving (``serve.bench --control``); tests call :meth:`Controller.step`
directly with scripted signals.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from .config import ServeConfig


@dataclasses.dataclass(frozen=True)
class Decision:
    """One accepted knob change (the controller's view; the config's
    history carries the same facts for exporters)."""

    step: int
    knob: str
    old: float
    new: float
    reason: str


#: Knobs the feedback rules manage (everything else in ServeConfig is
#: hot-swappable but only moved by operators/the tuner).
MANAGED_KNOBS = ("batch_window", "pin_after", "max_batch",
                 "pipeline_depth", "max_queue", "overlap_chunks",
                 "spmd_batch_window", "spmd_max_batch",
                 "lease_ttl_ms", "wire_precision")


class Controller:
    """Rule-based feedback controller over one executor's config.

    ``metrics`` supplies live signals (:meth:`ServeMetrics.signals`);
    tests may instead pass a ``signals`` dict straight to :meth:`step`.
    ``executor`` is optional and only consulted for the backend-aware
    auto pipeline depth (the depth rule is skipped without it).
    ``watchdog`` (an :class:`~spfft_tpu.control.slo.SLOWatchdog`) is
    evaluated once per step when given, so one loop drives both
    retuning and SLO accounting.
    """

    def __init__(self, config: ServeConfig, metrics=None, executor=None,
                 watchdog=None, spmd=None, cooldown_steps: int = 3,
                 shrink_ratio: float = 2.0, grow_ratio: float = 0.5,
                 pad_hi: float = 0.25, pad_lo: float = 0.02,
                 exec_floor_s: float = 1e-4,
                 reject_streak_steps: int = 2,
                 overlap_hi: float = 1.0, overlap_lo: float = 0.25,
                 overlap_streak_steps: int = 2,
                 spmd_streak_steps: int = 2,
                 rtt_hi: float = 0.2, rtt_streak_steps: int = 2,
                 wire_hi: float = 1.5, wire_lo: float = 0.25,
                 wire_streak_steps: int = 3):
        self.config = config
        self.metrics = metrics
        self.executor = executor
        self.watchdog = watchdog
        self.spmd = spmd
        self.cooldown_steps = max(0, int(cooldown_steps))
        self.shrink_ratio = float(shrink_ratio)
        self.grow_ratio = float(grow_ratio)
        self.pad_hi = float(pad_hi)
        self.pad_lo = float(pad_lo)
        self.exec_floor_s = float(exec_floor_s)
        self.reject_streak_steps = max(1, int(reject_streak_steps))
        self.overlap_hi = float(overlap_hi)
        self.overlap_lo = float(overlap_lo)
        self.overlap_streak_steps = max(1, int(overlap_streak_steps))
        self.spmd_streak_steps = max(1, int(spmd_streak_steps))
        self.rtt_hi = float(rtt_hi)
        self.rtt_streak_steps = max(1, int(rtt_streak_steps))
        self.wire_hi = float(wire_hi)
        self.wire_lo = float(wire_lo)
        self.wire_streak_steps = max(1, int(wire_streak_steps))
        self._wire_streak = 0
        self._overlap_streak = 0
        self._reject_streak = 0
        self._spmd_streak = 0
        self._rtt_streak = 0
        self._step = 0
        self._prev: Optional[Dict] = None
        self._last_change: Dict[str, int] = {}
        self._decisions: List[Decision] = []

    # -- bookkeeping -------------------------------------------------------
    @property
    def steps(self) -> int:
        return self._step

    def decisions(self) -> List[Decision]:
        return list(self._decisions)

    def _cool(self, knob: str) -> bool:
        last = self._last_change.get(knob)
        return (last is not None
                and self._step - last <= self.cooldown_steps)

    def _retune(self, out: List[Decision], knob: str, value,
                reason: str) -> bool:
        """Apply one rule's request; True when the knob actually moved
        (cooldown respected, clamped no-ops record nothing)."""
        if self._cool(knob):
            return False
        old = self.config.get(knob)
        new = self.config.set(knob, value, reason=reason,
                              source="controller")
        if new != old:
            self._last_change[knob] = self._step
            d = Decision(self._step, knob, old, new, reason)
            self._decisions.append(d)
            out.append(d)
            return True
        return False

    def _delta(self, signals: Dict, key: str) -> float:
        prev = (self._prev or {}).get(key, 0)
        return signals.get(key, 0) - prev

    # -- the rules ---------------------------------------------------------
    def step(self, signals: Optional[Dict] = None) -> List[Decision]:
        """One deterministic control step over ``signals`` (defaults to
        ``self.metrics.signals()``). Returns the decisions accepted this
        step (possibly empty)."""
        if signals is None:
            if self.metrics is None:
                raise ValueError("Controller needs metrics or explicit "
                                 "signals")
            signals = self.metrics.signals()
            if self.spmd is not None:
                signals.update(self.spmd.signals())
        self._step += 1
        out: List[Decision] = []
        first = self._prev is None
        completed_d = self._delta(signals, "completed")
        idle = (completed_d == 0 and signals.get("queue_depth", 0) == 0
                and self._delta(signals, "spmd_launches") == 0
                and signals.get("spmd_queue_depth", 0) == 0)
        if first:
            pass  # calibration step: record the baseline, act next
        elif idle:
            self._reject_streak = 0
            self._overlap_streak = 0
            self._spmd_streak = 0
            self._rtt_streak = 0
            self._wire_streak = 0
            self._decay_toward_defaults(out)
        else:
            self._rule_batch_window(out, signals)
            self._rule_pin_after(out, signals)
            self._rule_max_batch(out, signals)
            self._rule_pipeline_depth(out, signals)
            self._rule_max_queue(out, signals)
            self._rule_overlap_chunks(out, signals)
            self._rule_wire_precision(out, signals)
            self._rule_spmd_coalesce(out, signals)
            self._rule_lease_ttl(out, signals)
        self._prev = dict(signals)
        from .. import obs
        obs.GLOBAL_COUNTERS.inc(
            "spfft_control_steps_total", 1,
            help="Controller steps executed.")
        if self.watchdog is not None:
            self.watchdog.evaluate()
        return out

    def _decay_toward_defaults(self, out: List[Decision]) -> None:
        """Idle: walk each managed knob one move back toward its
        default — windows/halvings retrace their own path, integer knobs
        step by one."""
        for knob in MANAGED_KNOBS:
            cur = self.config.get(knob)
            default = ServeConfig.default(knob)
            if cur == default:
                continue
            if knob in ("batch_window", "spmd_batch_window"):
                # retrace the halving/doubling path, snapping onto the
                # default once one move reaches or crosses it
                if cur < default:
                    nxt = default if cur == 0 or cur * 2 >= default \
                        else cur * 2
                else:
                    nxt = max(default, cur / 2)
            elif knob in ("max_queue", "overlap_chunks",
                          "spmd_max_batch", "lease_ttl_ms"):
                # these grow rules double, so the decay halves — one
                # idle step per growth step back toward the default
                nxt = max(default, cur // 2) if cur > default \
                    else min(default, cur * 2)
            else:
                nxt = cur + 1 if cur < default else cur - 1
            moved = self._retune(out, knob, nxt,
                                 "idle: decay toward default")
            if moved and knob == "wire_precision":
                from .. import obs
                obs.GLOBAL_COUNTERS.inc(
                    "spfft_wire_rung_changes_total", 1,
                    direction="down")

    def _rule_batch_window(self, out, s) -> None:
        qw = s.get("queue_wait_p95", 0.0)
        dx = max(s.get("device_execute_p50", 0.0), self.exec_floor_s)
        w = self.config.get("batch_window")
        default = ServeConfig.default("batch_window")
        if qw > self.shrink_ratio * dx and w > 0.0:
            self._retune(out, "batch_window", w / 2.0,
                         f"queue buildup: queue_wait p95 {qw * 1e3:.2f}"
                         f" ms > {self.shrink_ratio:g} x device p50 "
                         f"{dx * 1e3:.2f} ms")
        elif qw < self.grow_ratio * dx and w < default:
            nxt = default if w == 0.0 else min(default, w * 2.0)
            self._retune(out, "batch_window", nxt,
                         f"queue drained: queue_wait p95 "
                         f"{qw * 1e3:.2f} ms < {self.grow_ratio:g} x "
                         f"device p50 {dx * 1e3:.2f} ms")

    def _rule_pin_after(self, out, s) -> None:
        rows_d = self._delta(s, "fused_rows")
        if rows_d <= 0:
            return
        pad_d = self._delta(s, "padded_rows")
        ratio = pad_d / rows_d
        pin = self.config.get("pin_after")
        default = ServeConfig.default("pin_after")
        if ratio > self.pad_hi and pin > 1:
            self._retune(out, "pin_after", pin - 1,
                         f"pad-heavy trace: {pad_d:g} pad rows / "
                         f"{rows_d:g} live rows = {ratio:.2f}")
        elif ratio < self.pad_lo and pin < default:
            self._retune(out, "pin_after", pin + 1,
                         f"pads gone ({ratio:.3f}): decay toward "
                         f"default")

    def _rule_max_batch(self, out, s) -> None:
        mb = self.config.get("max_batch")
        default = ServeConfig.default("max_batch")
        hist = s.get("fused_hist") or {}
        prev_hist = (self._prev or {}).get("fused_hist") or {}
        full_d = hist.get(mb, 0) - prev_hist.get(mb, 0)
        sizes_d = [b for b in hist
                   if hist.get(b, 0) - prev_hist.get(b, 0) > 0]
        if full_d >= 3 and s.get("max_queue_depth", 0) > mb:
            self._retune(out, "max_batch", mb * 2,
                         f"backlog of full buckets: {full_d:g} buckets "
                         f"at the cap {mb} with queue depth "
                         f"{s.get('max_queue_depth', 0):g}")
        elif mb > default and sizes_d \
                and max(sizes_d) <= max(1, mb // 4):
            self._retune(out, "max_batch", max(default, mb // 2),
                         f"buckets far below cap: largest fused "
                         f"{max(sizes_d)} <= {mb}//4")

    def _rule_max_queue(self, out, s) -> None:
        """Grow the queue bound on SUSTAINED ``rejected_queue_full``
        burn (ROADMAP control follow-on #3): rejects on
        ``reject_streak_steps`` consecutive non-idle steps double
        ``max_queue`` within its declared bounds; the idle decay walks
        it back by halving. One blip is backpressure doing its job and
        moves nothing (the streak is the hysteresis)."""
        rej_d = self._delta(s, "rejected_queue_full")
        if rej_d <= 0:
            self._reject_streak = 0
            return
        self._reject_streak += 1
        if self._reject_streak < self.reject_streak_steps:
            return
        mq = self.config.get("max_queue")
        new = self._retune(
            out, "max_queue", mq * 2,
            f"sustained queue-full burn: +{rej_d:g} rejects on step "
            f"{self._step} ({self._reject_streak} consecutive "
            f"reject steps)")
        if new:
            self._reject_streak = 0

    def _rule_overlap_chunks(self, out, s) -> None:
        """Retune the exchange-overlap chunk count K from recorded
        exchange-vs-compute span seconds (round-18 satellite of the pod
        frontend): exchange time above ``overlap_hi`` x compute time on
        ``overlap_streak_steps`` consecutive distributed steps doubles
        K within the declared clamp — more chunks, more compute to hide
        the wire behind; exchange below ``overlap_lo`` x compute halves
        K back toward the K=1 default (the bit-identical monolithic
        path, which round 9 measured as strictly cheaper when there is
        nothing to hide). Steps with no distributed work reset the
        streak and move nothing."""
        ex_d = self._delta(s, "exchange_s")
        cp_d = self._delta(s, "exchange_compute_s")
        if ex_d <= 0 and cp_d <= 0:
            self._overlap_streak = 0
            return
        k = self.config.get("overlap_chunks")
        default = ServeConfig.default("overlap_chunks")
        ratio = ex_d / max(cp_d, self.exec_floor_s)
        if ratio > self.overlap_hi:
            self._overlap_streak += 1
            if self._overlap_streak >= self.overlap_streak_steps \
                    and self._retune(
                        out, "overlap_chunks", k * 2,
                        f"exchange rivals compute: {ex_d * 1e3:.1f} ms "
                        f"exchange vs {cp_d * 1e3:.1f} ms compute over "
                        f"{self._overlap_streak} consecutive steps"):
                self._overlap_streak = 0
        else:
            self._overlap_streak = 0
            if ratio < self.overlap_lo and k > default:
                self._retune(out, "overlap_chunks",
                             max(default, k // 2),
                             f"exchange hidden ({ratio:.2f} x compute):"
                             f" decay toward default")

    def _rule_wire_precision(self, out, s) -> None:
        """Escalate the wire-compression rung under SUSTAINED exposed
        exchange (the compressed-wire tentpole's controller half): the
        same exchange-vs-compute span deltas that drive
        ``overlap_chunks``, behind harder thresholds (``wire_hi`` >
        ``overlap_hi`` and a longer streak) — chunking hides wire time
        for free, compression spends accuracy budget, so the rung moves
        only when the exchange still dominates after the chunking rule
        has had its chance. One rung per move, within the declared
        [0, 3] clamp; plans built under the new value re-probe against
        their own ``wire_error_budget`` and may still decline (the
        budget gate is the plan's, not the controller's). Exchange well
        hidden (below ``wire_lo``) decays one rung back; streak +
        cooldown are the anti-oscillation guard the scenario test
        pins. Rung moves are counted by direction."""
        ex_d = self._delta(s, "exchange_s")
        cp_d = self._delta(s, "exchange_compute_s")
        if ex_d <= 0 and cp_d <= 0:
            self._wire_streak = 0
            return
        rung = self.config.get("wire_precision")
        default = ServeConfig.default("wire_precision")
        ratio = ex_d / max(cp_d, self.exec_floor_s)
        if ratio > self.wire_hi:
            self._wire_streak += 1
            if self._wire_streak >= self.wire_streak_steps \
                    and self._retune(
                        out, "wire_precision", rung + 1,
                        f"exposed exchange: {ex_d * 1e3:.1f} ms "
                        f"exchange vs {cp_d * 1e3:.1f} ms compute over "
                        f"{self._wire_streak} consecutive steps"):
                self._wire_streak = 0
                from .. import obs
                obs.GLOBAL_COUNTERS.inc(
                    "spfft_wire_rung_changes_total", 1, direction="up")
        else:
            self._wire_streak = 0
            if ratio < self.wire_lo and rung > default:
                if self._retune(
                        out, "wire_precision", rung - 1,
                        f"exchange hidden ({ratio:.2f} x compute): "
                        f"decay toward default"):
                    from .. import obs
                    obs.GLOBAL_COUNTERS.inc(
                        "spfft_wire_rung_changes_total", 1,
                        direction="down")

    def _rule_lease_ttl(self, out, s) -> None:
        """Widen the membership lease under wire-RTT inflation (round
        21): a measured ``wire_rtt`` above ``rtt_hi`` x the lease TTL on
        ``rtt_streak_steps`` consecutive non-idle steps means heartbeat
        renewals are racing the expiry ladder — a slow-but-alive pod
        would start suspecting healthy hosts. Doubling ``lease_ttl_ms``
        within its declared bounds restores the renewal margin; the
        idle decay halves it back once the wire recovers. Steps with no
        RTT signal (loopback pods) reset the streak and move
        nothing."""
        rtt = s.get("wire_rtt", 0.0)
        if rtt <= 0.0:
            self._rtt_streak = 0
            return
        ttl_s = self.config.get("lease_ttl_ms") / 1e3
        if rtt <= self.rtt_hi * ttl_s:
            self._rtt_streak = 0
            return
        self._rtt_streak += 1
        if self._rtt_streak < self.rtt_streak_steps:
            return
        if self._retune(
                out, "lease_ttl_ms",
                self.config.get("lease_ttl_ms") * 2,
                f"wire RTT inflation: {rtt * 1e3:.1f} ms RTT vs "
                f"{ttl_s * 1e3:.0f} ms lease TTL over "
                f"{self._rtt_streak} consecutive steps"):
            self._rtt_streak = 0

    def _rule_spmd_coalesce(self, out, s) -> None:
        """Retune the pod SPMD lane's coalescing window and batch cap
        from the coalescer's live signals (``SPMDCoalescer.signals``):
        distributed requests backing up (queue depth >= 2) while the
        window is shorter than one collective launch on
        ``spmd_streak_steps`` consecutive distributed steps means
        arrivals during a launch keep missing the next window → DOUBLE
        ``spmd_batch_window`` (more requests per collective round); a
        window above default that coalesced nothing this step decays
        back by halving. Rounds repeatedly full AT ``spmd_max_batch``
        with a backlog double the cap; rounds far below an elevated cap
        halve it back — the fused ``max_batch`` rule, re-aimed at the
        distributed lane. Steps with no collective launches reset the
        streak and move nothing."""
        launches_d = self._delta(s, "spmd_launches")
        if launches_d <= 0:
            self._spmd_streak = 0
            return
        depth = s.get("spmd_queue_depth", 0)
        p50 = max(s.get("spmd_launch_p50", 0.0), self.exec_floor_s)
        w = self.config.get("spmd_batch_window")
        default = ServeConfig.default("spmd_batch_window")
        if depth >= 2 and w < p50:
            self._spmd_streak += 1
            if self._spmd_streak >= self.spmd_streak_steps:
                nxt = default if w == 0.0 else w * 2.0
                if self._retune(
                        out, "spmd_batch_window", nxt,
                        f"SPMD backlog: depth {depth:g} with window "
                        f"{w * 1e3:.2f} ms < launch p50 "
                        f"{p50 * 1e3:.2f} ms over {self._spmd_streak} "
                        f"consecutive distributed steps"):
                    self._spmd_streak = 0
        else:
            self._spmd_streak = 0
            if w > default and self._delta(s, "spmd_coalesced") == 0:
                self._retune(out, "spmd_batch_window",
                             max(default, w / 2.0),
                             "window coalesced nothing: decay toward "
                             "default")
        mb = self.config.get("spmd_max_batch")
        mb_default = ServeConfig.default("spmd_max_batch")
        hist = s.get("spmd_batch_hist") or {}
        prev_hist = (self._prev or {}).get("spmd_batch_hist") or {}
        full_d = hist.get(mb, 0) - prev_hist.get(mb, 0)
        sizes_d = [b for b in hist
                   if hist.get(b, 0) - prev_hist.get(b, 0) > 0]
        if full_d >= 2 and depth > 0:
            self._retune(out, "spmd_max_batch", mb * 2,
                         f"full collective rounds: {full_d:g} rounds "
                         f"at the cap {mb} with SPMD queue depth "
                         f"{depth:g}")
        elif mb > mb_default and sizes_d \
                and max(sizes_d) <= max(1, mb // 4):
            self._retune(out, "spmd_max_batch",
                         max(mb_default, mb // 2),
                         f"rounds far below cap: largest coalesced "
                         f"batch {max(sizes_d)} <= {mb}//4")

    def _rule_pipeline_depth(self, out, s) -> None:
        if self.executor is None:
            return
        stage_d = self._delta(s, "stage_s")
        disp_d = self._delta(s, "dispatch_s")
        if disp_d <= 0:
            return
        cur = self.config.get("pipeline_depth")
        try:
            auto = self.executor._pipeline_slots()
        except Exception:
            return
        if stage_d > 0.5 * disp_d:
            base = cur if cur > 0 else auto
            self._retune(out, "pipeline_depth", base + 1,
                         f"host staging on the critical path: stage "
                         f"{stage_d * 1e3:.1f} ms vs dispatch "
                         f"{disp_d * 1e3:.1f} ms")
        elif cur > 0 and stage_d < 0.1 * disp_d:
            nxt = cur - 1 if cur > auto else 0
            self._retune(out, "pipeline_depth", nxt,
                         "staging negligible: decay toward auto depth")


class ControlLoop:
    """Background thread stepping a :class:`Controller` every
    ``interval`` seconds against a live executor. The loop thread is
    the only caller of ``step`` (decisions stay ordered); stop() joins
    it. Use as a context manager around a serving window."""

    def __init__(self, controller: Controller, interval: float = 0.05):
        if interval <= 0:
            raise ValueError("interval must be > 0")
        self.controller = controller
        self.interval = float(interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ControlLoop":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="spfft-control-loop", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.controller.step()
            except Exception:
                # the control plane must never take down the data
                # plane; a broken rule skips a beat, counted below
                from .. import obs
                obs.GLOBAL_COUNTERS.inc(
                    "spfft_control_step_errors_total", 1,
                    help="Controller steps that raised (skipped).")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "ControlLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

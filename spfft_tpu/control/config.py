"""ServeConfig: THE typed home of every serving/execution knob.

Until this round the executor's knobs were module constants scattered
across three layers (``serve/executor.py`` batching/pinning/quarantine
constants, ``serve/registry.py`` LRU bounds, ``parallel/dist.py``'s
``overlap_chunks`` env default) — hand-retuned each round by reading
the ci-tpu log. The reference library tunes its execution strategy from
measured structure (buffer sizes, exchange mechanism, MPI-behind-compute
scheduling all derive from the plan's exact byte accounting — PAPER.md
execution layer); this module is the serving-era analogue's foundation:
one :class:`ServeConfig` object that

* declares every knob ONCE, with its default, hard bounds and the
  telemetry signal that drives it (:data:`KNOB_SPECS` — the executor's
  ``DEFAULT_*`` constants now alias these defaults, so there is exactly
  one place a number lives);
* is HOT-SWAPPABLE under a lock: the feedback controller
  (:mod:`~spfft_tpu.control.controller`) retunes a live executor by
  calling :meth:`ServeConfig.set` while the dispatcher reads the same
  object through lock-guarded attribute access — a retune applies from
  the next bucket, and the executor's correctness contract (vmap rows
  independent, batch shape can never perturb live rows) makes any
  mid-stream change bit-exact by construction;
* BOUNDS-CLAMPS every write and RECORDS every accepted change as a
  decision: a bounded in-memory history, a
  ``spfft_control_decisions_total{knob,source}`` Prometheus counter, a
  ``spfft_control_knob{knob}`` gauge, and (when tracing is on) a
  ``control.retune`` instant event on the ``control`` track — so
  Perfetto shows *why* a knob moved next to the request spans it moved
  in response to;
* round-trips a JSON artifact (:meth:`save` / :meth:`load`): the
  offline auto-tuner (``python -m spfft_tpu.control tune``) emits a
  recommended-config file and ``serve`` loads it at boot via the
  ``SPFFT_TPU_SERVE_CONFIG`` env var (:meth:`boot`).

See docs/control_plane.md for the signals → rules → knobs table.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..errors import InvalidParameterError

#: Boot artifact location: when set, :meth:`ServeConfig.boot` (the
#: executor's default config source) loads this JSON file — the
#: auto-tuner's output becomes the fleet's serving defaults without a
#: code change. A malformed artifact raises at boot (fail fast: a typo'd
#: config silently ignored is worse than a crashed boot).
CONFIG_ENV = "SPFFT_TPU_SERVE_CONFIG"

#: Artifact schema marker (bumped on incompatible format changes).
ARTIFACT_KEY = "spfft_tpu_serve_config"
ARTIFACT_VERSION = 1

#: Decisions kept in each config's in-memory history (ring).
HISTORY_LIMIT = 256


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One knob's declaration: default, hard clamp bounds, type and the
    telemetry signal the controller drives it from (documentation — the
    rules live in :mod:`~spfft_tpu.control.controller`)."""

    name: str
    default: float
    lo: float
    hi: float
    kind: type                  # int or float
    signal: str                 # what drives it (docs + CLI `show`)
    doc: str

    def clamp(self, value) -> float:
        v = self.kind(value)
        if v < self.lo:
            v = self.kind(self.lo)
        elif v > self.hi:
            v = self.kind(self.hi)
        return v


#: Every knob the control plane owns. Defaults carry their measured
#: provenance forward from the modules that used to own them:
#: batch_window 1 ms (round-7 arrival-latency retune), max_batch 8
#: (latency amplification bound vs FUSED_BATCH_MAX_GRID), pin_after 3 /
#: max_pinned_shapes 4 (round-7 adaptive pinning), quarantine 3 @ 0.25 s
#: (round-8 fault tolerance), registry 2 GiB / 32 plans (round-6 LRU),
#: overlap_chunks 1 (round-9: K=1 is the bit/HLO-identical monolithic
#: path; K>1 pays only where the backend overlaps collectives).
KNOB_SPECS: Dict[str, KnobSpec] = {spec.name: spec for spec in (
    KnobSpec("batch_window", 0.001, 0.0, 0.1, float,
             "queue-wait p95 vs device-execute p50",
             "Same-signature batching window (seconds) a trickle bucket "
             "waits for company."),
    KnobSpec("max_batch", 8, 1, 128, int,
             "fused batch histogram + queue depth",
             "Bucket cap: most live rows one fused dispatch carries."),
    KnobSpec("max_queue", 256, 1, 65536, int,
             "rejected_queue_full counter",
             "Bounded request queue capacity (overflow rejects, "
             "QueueFullError)."),
    KnobSpec("pin_after", 3, 0, 64, int,
             "padded-rows ratio",
             "Consecutive same-size fused buckets before that exact "
             "shape is pinned (0 disables pinning)."),
    KnobSpec("max_pinned_shapes", 4, 1, 64, int,
             "pinned-shape churn",
             "Pinned exact batch shapes kept per signature (LRU)."),
    KnobSpec("pipeline_depth", 0, 0, 32, int,
             "stage-vs-dispatch overlap ratio",
             "In-flight bucket window; 0 = backend-aware auto (pool+1 "
             "on accelerators, pool on CPU)."),
    KnobSpec("quarantine_after", 3, 0, 64, int,
             "device-attributed failure streaks",
             "Consecutive device-attributed failures before a pool "
             "device is quarantined (0 disables)."),
    KnobSpec("quarantine_backoff", 0.25, 0.001, 60.0, float,
             "probation outcomes",
             "Initial quarantine probation backoff (seconds, doubles "
             "per failed canary)."),
    KnobSpec("overlap_chunks", 1, 1, 64, int,
             "per-chunk wire bytes + async-split evidence",
             "Distributed exchange pipeline chunks K (1 = monolithic, "
             "bit-identical path)."),
    KnobSpec("registry_max_bytes", 2 * 1024 ** 3, 1024 ** 2,
             64 * 1024 ** 3, int,
             "registry bytes_in_use / evictions",
             "Plan registry LRU byte budget over estimated plan "
             "residency."),
    KnobSpec("registry_max_plans", 32, 1, 4096, int,
             "registry evictions",
             "Plan registry LRU entry cap."),
    KnobSpec("plan_store_max_bytes", 16 * 1024 ** 3, 0,
             1024 ** 4, int,
             "spfft_store_{spills,evictions}_total",
             "Persistent plan-artifact store byte cap (oldest-first "
             "GC on spill; 0 = unbounded)."),
    KnobSpec("fused_target_r", 64, 8, 512, int,
             "measured chip profiles (offline retune)",
             "Fused-kernel super-tile row target R: decompress+z-DFT "
             "gather window sizing (ops/fused_kernel.py cost model)."),
    KnobSpec("fused_recompute_limit", 4.0, 1.0, 64.0, float,
             "spfft_plan_pallas_fallback_total{reason=recompute_blowup}",
             "Fused compress recompute-blowup gate: decline when "
             "windowed gather rows exceed this multiple of the stick "
             "count."),
    KnobSpec("execute_timeout_ms", 0, 0, 600_000, int,
             "spfft_execute_timeouts_total",
             "Per-bucket device-execute watchdog (ms): a "
             "materialisation exceeding it is abandoned and failed as "
             "a typed transient ExecuteTimeoutError feeding the retry "
             "+ quarantine ladder (0 = off)."),
    KnobSpec("net_connect_timeout_ms", 2000, 1, 600_000, int,
             "spfft_cluster_rpc_failures_total",
             "TCP connect timeout (ms) for a host lane's wire RPCs: "
             "an unreachable agent fails over this fast."),
    KnobSpec("net_rpc_timeout_ms", 30_000, 1, 600_000, int,
             "spfft_net_rpc_rtt_seconds",
             "Per-RPC socket read timeout (ms) on the pod wire; a "
             "submit adds the request's own deadline on top."),
    KnobSpec("spmd_batch_window", 0.002, 0.0, 0.1, float,
             "SPMD queue depth vs collective-launch p50",
             "Coalescing window (seconds) the pod SPMD lane holds a "
             "distributed request open for same-signature company "
             "before launching the collective round."),
    KnobSpec("spmd_max_batch", 8, 1, 128, int,
             "SPMD batch-size histogram",
             "Most distributed requests one coalesced SPMD collective "
             "round carries."),
    KnobSpec("lease_ttl_ms", 1500, 50, 600_000, int,
             "spfft_net_rpc_rtt_seconds inflation vs the TTL",
             "Membership lease lifetime (ms): an agent whose heartbeat "
             "has not renewed its lease within this window starts down "
             "the suspected->probed->evicted ladder. The controller "
             "widens it when observed wire RTT inflates toward it."),
    KnobSpec("heartbeat_interval_ms", 500, 10, 600_000, int,
             "spfft_membership_heartbeats_total",
             "How often an agent renews its membership lease with the "
             "view coordinator (ms); keep well under lease_ttl_ms."),
    KnobSpec("lane_probe_backoff", 0.25, 0.001, 60.0, float,
             "spfft_cluster_probes_total",
             "Base backoff (seconds) before the pod frontend's first "
             "health probe of a dead lane; doubles per failed probe "
             "with jitter, capped at 64x."),
    KnobSpec("blob_store_max_bytes", 0, 0, 1024 ** 4, int,
             "spfft_blob_gc_total",
             "Byte cap for the remote blob tier's req/ request-journal "
             "namespace: the gc sweep evicts oldest-mtime keys past it "
             "(0 = unbounded, no sweep)."),
    KnobSpec("wire_precision", 0, 0, 3, int,
             "exposed-exchange ratio + spfft_wire_rung_declined_total",
             "Requested wire-compression rung for distributed exchanges "
             "(0=full, 1=f32, 2=bf16, 3=int8+per-stick scales); the "
             "plan's measured-error probe may decline down the ladder "
             "within wire_error_budget."),
    KnobSpec("wire_error_budget", 0.01, 1e-6, 1.0, float,
             "spfft_wire_rung_declined_total{reason=over_budget}",
             "Declared rel-l2 error budget for the compressed wire: a "
             "rung whose probe error exceeds it is REFUSED at plan "
             "build and the plan falls one rung down."),
)}

#: String-valued settings (paths) the numeric KnobSpec clamp cannot
#: carry. They live beside the knobs: hot-readable under the same
#: lock, round-tripped through the JSON artifact (under ``"paths"``),
#: but never exported as Prometheus gauges. ``plan_store_path`` ""
#: (the default) disables the disk plan tier unless the
#: ``SPFFT_TPU_PLAN_STORE`` env var names one; ``blob_store_url`` ""
#: disables the remote blob artifact tier unless
#: ``SPFFT_TPU_BLOB_STORE`` names one (http:// URL or a shared
#: directory — see ``net/blobstore.py``).
PATH_SETTINGS: Dict[str, str] = {"plan_store_path": "",
                                 "blob_store_url": ""}


def _counters():
    # late import: obs is cheap, but keeping it out of module import
    # keeps config importable from anywhere (dist.py, registry) without
    # ordering concerns
    from .. import obs
    return obs


class ServeConfig:
    """Typed, bounds-clamped, hot-swappable serving configuration.

    Reads (``config.batch_window`` or :meth:`get`) and writes
    (:meth:`set`) are lock-guarded, so a controller thread can retune a
    knob while the dispatcher reads it: the new value applies from the
    reader's next access. Every ACCEPTED change (value actually moved)
    is recorded as a decision — history entry, Prometheus counter/gauge
    and, when tracing is on, a ``control.retune`` instant on the
    ``control`` track.
    """

    def __init__(self, values: Optional[Dict] = None):
        self._lock = threading.Lock()
        #: guarded by _lock
        self._values: Dict[str, float] = {
            name: spec.default for name, spec in KNOB_SPECS.items()}
        self._paths: Dict[str, str] = dict(PATH_SETTINGS)  #: guarded by _lock
        #: guarded by _lock
        self._history: "collections.deque" = collections.deque(
            maxlen=HISTORY_LIMIT)
        self._seq = 0  #: guarded by _lock
        self._decisions_by_source: Dict[str, int] = {}  #: guarded by _lock
        if values:
            self.update(values, reason="initial values", source="init")

    # -- path settings -----------------------------------------------------
    @property
    def plan_store_path(self) -> str:
        with self._lock:
            return self._paths["plan_store_path"]

    @property
    def blob_store_url(self) -> str:
        with self._lock:
            return self._paths["blob_store_url"]

    def set_path(self, name: str, value: str) -> str:
        if name not in PATH_SETTINGS:
            raise InvalidParameterError(
                f"unknown path setting {name!r} "
                f"(settings: {sorted(PATH_SETTINGS)})")
        with self._lock:
            self._paths[name] = str(value or "")
            return self._paths[name]

    def paths(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._paths)

    # -- reading -----------------------------------------------------------
    def __getattr__(self, name: str):
        # only consulted when normal attribute lookup fails — i.e. for
        # knob names (internal attributes hit __dict__ first, so the
        # self._lock/self._values lookups below never recurse)
        if name.startswith("_") or name not in KNOB_SPECS:
            raise AttributeError(name)
        with self._lock:
            return self._values[name]

    def get(self, name: str):
        if name not in KNOB_SPECS:
            raise InvalidParameterError(f"unknown knob {name!r} "
                                        f"(knobs: {sorted(KNOB_SPECS)})")
        with self._lock:
            return self._values[name]

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time copy of every knob value."""
        with self._lock:
            return dict(self._values)

    @staticmethod
    def spec(name: str) -> KnobSpec:
        spec = KNOB_SPECS.get(name)
        if spec is None:
            raise InvalidParameterError(f"unknown knob {name!r} "
                                        f"(knobs: {sorted(KNOB_SPECS)})")
        return spec

    @staticmethod
    def default(name: str):
        return ServeConfig.spec(name).default

    @staticmethod
    def bounds(name: str) -> Tuple[float, float]:
        spec = ServeConfig.spec(name)
        return (spec.lo, spec.hi)

    def decisions(self) -> List[Dict]:
        """The bounded decision history, oldest first (each entry:
        seq/knob/old/new/requested/clamped/reason/source)."""
        with self._lock:
            return list(self._history)

    def decision_count(self, source: Optional[str] = None) -> int:
        """Lifetime accepted-decision count (per ``source`` when given)
        — survives the bounded history window."""
        with self._lock:
            if source is None:
                return sum(self._decisions_by_source.values())
            return self._decisions_by_source.get(source, 0)

    # -- writing -----------------------------------------------------------
    def set(self, name: str, value, reason: str = "",
            source: str = "manual"):
        """Clamp ``value`` into ``name``'s declared bounds and apply it.
        Returns the CLAMPED value actually in effect. A write that does
        not move the knob records nothing; an accepted change records a
        decision everywhere an operator might look for it (history,
        ``spfft_control_*`` series, trace annotation)."""
        spec = self.spec(name)
        clamped = spec.clamp(value)
        with self._lock:
            old = self._values[name]
            if clamped == old:
                return old
            self._values[name] = clamped
            self._seq += 1
            requested = spec.kind(value)
            entry = {
                "seq": self._seq, "knob": name, "old": old,
                "new": clamped, "requested": requested,
                "clamped": clamped != requested,
                "reason": reason, "source": source,
            }
            self._history.append(entry)
            self._decisions_by_source[source] = \
                self._decisions_by_source.get(source, 0) + 1
        obs = _counters()
        obs.GLOBAL_COUNTERS.inc(
            "spfft_control_decisions_total", 1,
            help="Accepted control-plane knob changes.",
            knob=name, source=source)
        obs.GLOBAL_COUNTERS.set(
            "spfft_control_knob", clamped,
            help="Current value of each control-plane knob.", knob=name)
        if entry["clamped"]:
            obs.GLOBAL_COUNTERS.inc(
                "spfft_control_clamped_total", 1,
                help="Knob writes clamped into their declared bounds.",
                knob=name)
        obs.record_event("control.knob", knob=name, old=old,
                         new=clamped, reason=reason, source=source)
        if obs.active():
            obs.GLOBAL_TRACER.instant(
                "control.retune", cat="control", track="control",
                args={"knob": name, "old": old, "new": clamped,
                      "clamped": entry["clamped"], "reason": reason,
                      "source": source})
        return clamped

    def update(self, values: Dict, reason: str = "",
               source: str = "manual") -> Dict[str, float]:
        """Apply several knobs; unknown names raise before anything is
        written. Returns {name: clamped value in effect}."""
        for name in values:
            self.spec(name)  # validate all names first
        return {name: self.set(name, v, reason=reason, source=source)
                for name, v in values.items()}

    # -- persistence -------------------------------------------------------
    def to_artifact(self, provenance: Optional[Dict] = None) -> Dict:
        """The recommended-config artifact format the tuner emits and
        :meth:`load` consumes."""
        return {ARTIFACT_KEY: ARTIFACT_VERSION,
                "values": self.snapshot(),
                "paths": self.paths(),
                "provenance": provenance or {}}

    def save(self, path: str, provenance: Optional[Dict] = None) -> None:
        with open(path, "w") as f:
            json.dump(self.to_artifact(provenance), f, indent=2)

    @classmethod
    def load(cls, path: str) -> "ServeConfig":
        """Load a recommended-config artifact. Unknown knobs in the
        file raise (a misspelt knob silently ignored is a tuning run
        thrown away); out-of-bounds values clamp, like every write."""
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as exc:
            raise InvalidParameterError(
                f"cannot read serve-config artifact {path!r}: {exc}")
        if not isinstance(payload, dict) \
                or payload.get(ARTIFACT_KEY) != ARTIFACT_VERSION:
            raise InvalidParameterError(
                f"{path!r} is not a spfft_tpu serve-config artifact "
                f"(want {ARTIFACT_KEY}={ARTIFACT_VERSION})")
        values = payload.get("values")
        if not isinstance(values, dict):
            raise InvalidParameterError(
                f"{path!r} carries no 'values' mapping")
        cfg = cls()
        cfg.update(values, reason=f"loaded from {path}", source="boot")
        paths = payload.get("paths")
        if paths is not None:
            if not isinstance(paths, dict):
                raise InvalidParameterError(
                    f"{path!r} 'paths' must be a mapping")
            for name, value in paths.items():
                cfg.set_path(name, value)
        return cfg

    @classmethod
    def boot(cls) -> "ServeConfig":
        """The executor's default config source: a fresh config, seeded
        from the ``SPFFT_TPU_SERVE_CONFIG`` artifact when that env var
        is set (the auto-tuner's output applied at boot). Each executor
        gets its OWN config object — a controller owns one executor's
        knobs, not the process's."""
        path = os.environ.get(CONFIG_ENV)
        if path:
            return cls.load(path)
        return cls()


#: Process-global config: the default the NON-serving layers
#: (``parallel/dist.py`` overlap_chunks, ``PlanRegistry`` bounds)
#: resolve through when no explicit value or executor-owned config is
#: in play. Lazily boots from the env artifact.
_GLOBAL: Optional[ServeConfig] = None  #: guarded by _GLOBAL_LOCK
_GLOBAL_LOCK = threading.Lock()


def global_config() -> ServeConfig:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ServeConfig.boot()
        return _GLOBAL


def set_global_config(cfg: Optional[ServeConfig]) -> None:
    """Replace (or with None: reset, re-booting lazily) the process
    default — tests and embedding applications."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = cfg

"""CLI: ``python -m spfft_tpu.control <tune|show|check>``.

* ``tune`` — run the offline auto-tuner (serve.bench knob grid, plus
  ``--overlap-ab`` for the round-9 exchange A/B) and write the
  recommended-config artifact ``serve`` loads at boot.
* ``show`` — print every knob with its current boot value, bounds,
  default and driving signal (the docs table, live).
* ``check FILE`` — validate a recommended-config artifact (schema +
  knob names + bounds) and print what it would apply.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..errors import InvalidParameterError
from .config import CONFIG_ENV, KNOB_SPECS, ServeConfig


def _cmd_show(args) -> int:
    cfg = ServeConfig.boot()
    values = cfg.snapshot()
    import os
    src = os.environ.get(CONFIG_ENV)
    print(f"boot config source: "
          f"{src if src else f'defaults ({CONFIG_ENV} unset)'}")
    width = max(len(n) for n in KNOB_SPECS)
    for name, spec in KNOB_SPECS.items():
        mark = "" if values[name] == spec.default \
            else f"  (default {spec.default:g})"
        print(f"  {name:<{width}}  = {values[name]:<12g} "
              f"bounds [{spec.lo:g}, {spec.hi:g}]{mark}")
        print(f"  {'':<{width}}    signal: {spec.signal}")
    if args.json:
        print(json.dumps({"values": values,
                          "bounds": {n: [s.lo, s.hi]
                                     for n, s in KNOB_SPECS.items()},
                          "defaults": {n: s.default
                                       for n, s in KNOB_SPECS.items()}}))
    return 0


def _cmd_check(args) -> int:
    try:
        cfg = ServeConfig.load(args.file)
    except InvalidParameterError as exc:
        print(f"INVALID: {exc}", file=sys.stderr)
        return 1
    values = cfg.snapshot()
    changed = {n: v for n, v in values.items()
               if v != KNOB_SPECS[n].default}
    clamped = [d for d in cfg.decisions() if d["clamped"]]
    print(f"{args.file}: valid serve-config artifact")
    print(f"  knobs off default: {changed if changed else 'none'}")
    for d in clamped:
        print(f"  NOTE: {d['knob']} requested {d['requested']:g} was "
              f"clamped to {d['new']:g}")
    print(json.dumps({"ok": True, "values": values,
                      "off_default": changed,
                      "clamped": [d['knob'] for d in clamped]}))
    return 0


def _cmd_tune(args) -> int:
    if args.cpu:
        from ..utils.platform import force_virtual_cpu_devices
        force_virtual_cpu_devices(max(args.devices, 1))
    from .tuner import tune
    artifact = tune(args)
    print(json.dumps({"metric": "control.tune grid "
                               f"dim={args.dim} requests={args.requests}",
                      "value": 1, "unit": "ok",
                      "values": artifact["values"],
                      "best": artifact["provenance"].get("best")}))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m spfft_tpu.control")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("tune", help="offline auto-tune; writes the "
                                    "recommended-config artifact")
    t.add_argument("--dim", type=int, default=24)
    t.add_argument("--requests", type=int, default=96)
    t.add_argument("--signatures", type=int, default=3)
    t.add_argument("--threads", type=int, default=4)
    t.add_argument("--seed", type=int, default=42)
    t.add_argument("--quick", action="store_true",
                   help="2x1 grid instead of 4x3 (CI-speed)")
    t.add_argument("--windows-ms", type=float, nargs="+", default=None)
    t.add_argument("--max-batches", type=int, nargs="+", default=None)
    t.add_argument("--p99-slack", type=float, default=0.05,
                   help="throughput slack within which lower p99 wins")
    t.add_argument("--overlap-ab", action="store_true",
                   help="also run scripts/bench_overlap_ab.py to pick "
                        "overlap_chunks (recommends K=1 unless the "
                        "backend shows async overlap evidence)")
    t.add_argument("--overlap-dim", type=int, default=48)
    t.add_argument("--cpu", action="store_true")
    t.add_argument("--devices", type=int, default=0)
    t.add_argument("-o", "--output", default=None,
                   metavar="CONFIG.json")
    t.set_defaults(func=_cmd_tune)

    s = sub.add_parser("show", help="print knobs, bounds, signals")
    s.add_argument("--json", action="store_true")
    s.set_defaults(func=_cmd_show)

    c = sub.add_parser("check", help="validate a config artifact")
    c.add_argument("file")
    c.set_defaults(func=_cmd_check)

    args = p.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())

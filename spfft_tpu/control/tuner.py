"""Offline auto-tuner: measured knob recommendations as an artifact.

``python -m spfft_tpu.control tune`` replaces three standing "retune
from the ci-tpu log by hand" chores with a mechanism: it RUNS the
existing measurement protocols — the ``serve.bench`` trace replay over
a small grid of (batch_window, max_batch) settings, and (on a >= 2
device mesh) the round-9 ``scripts/bench_overlap_ab.py`` interleaved
A/B over overlap chunk counts — scores the results, and emits a
recommended-config artifact (:meth:`ServeConfig.to_artifact` JSON,
grid provenance embedded) that ``serve`` loads at boot via
``SPFFT_TPU_SERVE_CONFIG`` (or ``serve.bench --config``).

Scoring: throughput first, p99 latency as the tiebreak within
``p99_slack`` (default 5%) of the best throughput — a knob that buys
1% throughput for a fat tail is not a win for a serving system. The
overlap recommendation only moves off K=1 when the backend showed
async start/done evidence (``overlap_meaningful``): on XLA:CPU the
round-9 A/B measures chunking overhead, not overlap, and recommending
K>1 from it would be tuning on noise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from .config import ServeConfig

#: Default serve.bench grid (kept small: each cell is a full replay).
DEFAULT_WINDOWS_MS = (0.0, 0.5, 1.0, 2.0)
DEFAULT_MAX_BATCHES = (4, 8, 16)
QUICK_WINDOWS_MS = (0.0, 1.0)
QUICK_MAX_BATCHES = (8,)


def _run_serve_bench(dim: int, requests: int, signatures: int,
                     threads: int, window_s: float, max_batch: int,
                     seed: int) -> Optional[Dict]:
    """One grid cell: the serve.bench replay with these knobs, JSON
    payload returned (None when the run failed — a broken cell is
    skipped, not fatal)."""
    from ..serve.bench import main as bench_main
    fd, path = tempfile.mkstemp(suffix=".json", prefix="spfft_tune_")
    os.close(fd)
    try:
        rc = bench_main(["--dim", str(dim), "--requests", str(requests),
                         "--signatures", str(signatures),
                         "--threads", str(threads),
                         "--window", repr(window_s),
                         "--max-batch", str(max_batch),
                         "--seed", str(seed), "-o", path])
        if rc != 0:
            return None
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def _score_grid(cells: List[Dict], p99_slack: float) -> Optional[Dict]:
    """Best cell: max throughput, then min p99 among cells within
    ``p99_slack`` of that throughput."""
    ok = [c for c in cells if c.get("result")]
    if not ok:
        return None
    best_tp = max(c["result"]["throughput_rps"] for c in ok)
    close = [c for c in ok
             if c["result"]["throughput_rps"]
             >= best_tp * (1.0 - p99_slack)]
    return min(close, key=lambda c: (
        c["result"]["serve_metrics"]["latency_seconds"]["p99"],
        -c["result"]["throughput_rps"]))


def _tune_overlap(args) -> Dict:
    """The round-9 overlap A/B (interleaved, same-session) as a tuner
    stage. Recommends K=1 unless the backend demonstrated async
    start/done overlap — honest by construction on CPU."""
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "scripts",
        "bench_overlap_ab.py")
    if not os.path.exists(script):
        return {"skipped": "scripts/bench_overlap_ab.py not found"}
    fd, path = tempfile.mkstemp(suffix=".json", prefix="spfft_tune_ab_")
    os.close(fd)
    try:
        cmd = [sys.executable, script, "--dim", str(args.overlap_dim),
               "--reps", "5", "--rounds", "3", "-o", path]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=900)
        if proc.returncode != 0:
            return {"skipped": f"bench_overlap_ab failed rc="
                               f"{proc.returncode}",
                    "stderr": proc.stderr[-500:]}
        with open(path) as f:
            payload = json.load(f)
    except Exception as exc:
        return {"skipped": f"bench_overlap_ab unavailable: {exc!r}"}
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    rows = payload.get("rows") or []
    best = {"k": 1}
    if payload.get("overlap_meaningful") and rows:
        best = max(rows, key=lambda r: r.get("vs_k1", 0.0))
    return {"recommended_k": int(best.get("k", 1)),
            "overlap_meaningful": bool(payload.get(
                "overlap_meaningful")),
            "backend": payload.get("backend"),
            "rows": rows}


def tune(args) -> Dict:
    """Run the grid, pick the winner, return (and optionally write) the
    recommended-config artifact."""
    windows = (QUICK_WINDOWS_MS if args.quick
               else DEFAULT_WINDOWS_MS) if args.windows_ms is None \
        else tuple(args.windows_ms)
    batches = (QUICK_MAX_BATCHES if args.quick
               else DEFAULT_MAX_BATCHES) if args.max_batches is None \
        else tuple(args.max_batches)
    t0 = time.time()
    cells: List[Dict] = []
    for w_ms in windows:
        for mb in batches:
            result = _run_serve_bench(args.dim, args.requests,
                                      args.signatures, args.threads,
                                      w_ms / 1e3, int(mb), args.seed)
            cell = {"batch_window_ms": w_ms, "max_batch": int(mb),
                    "result": result and {
                        "throughput_rps": result["throughput_rps"],
                        "speedup_vs_serial":
                            result["speedup_vs_serial"],
                        "serve_metrics": {"latency_seconds":
                                          result["serve_metrics"]
                                          ["latency_seconds"]}}}
            cells.append(cell)
            print(f"tune: window={w_ms}ms max_batch={mb} -> "
                  f"{'FAILED' if result is None else str(result['throughput_rps']) + ' req/s'}",
                  file=sys.stderr)
    best = _score_grid(cells, args.p99_slack)
    values: Dict[str, float] = {}
    if best is not None:
        values["batch_window"] = best["batch_window_ms"] / 1e3
        values["max_batch"] = best["max_batch"]
    overlap = None
    if args.overlap_ab:
        overlap = _tune_overlap(args)
        if "recommended_k" in overlap:
            values["overlap_chunks"] = overlap["recommended_k"]
    cfg = ServeConfig()
    if values:
        cfg.update(values, reason="offline auto-tune", source="tuner")
    provenance = {
        "protocol": "serve.bench grid"
                    + (" + bench_overlap_ab" if args.overlap_ab else ""),
        "grid": cells,
        "best": best and {"batch_window_ms": best["batch_window_ms"],
                          "max_batch": best["max_batch"]},
        "overlap_ab": overlap,
        "args": {"dim": args.dim, "requests": args.requests,
                 "signatures": args.signatures, "threads": args.threads,
                 "seed": args.seed, "p99_slack": args.p99_slack},
        "elapsed_s": round(time.time() - t0, 2),
    }
    try:
        from ..utils.platform import platform_summary
        provenance["platform"] = platform_summary()
    except Exception:
        pass
    artifact = cfg.to_artifact(provenance)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(artifact, f, indent=2)
        print(f"wrote {args.output}")
    return artifact

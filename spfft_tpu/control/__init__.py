"""spfft_tpu.control — the telemetry-driven control plane.

Closes the obs→serve loop the ROADMAP names: round 10 made every
tuning signal machine-readable (queue-wait spans, padded-rows
counters, per-chunk wire bytes, compile durations); this package makes
observability ACT on them instead of just exporting them.

* :mod:`~spfft_tpu.control.config` — :class:`ServeConfig`, the one
  typed home of every serving/execution knob: hot-swappable under
  lock, bounds-clamped, every change recorded (history +
  ``spfft_control_*`` Prometheus series + ``control.retune`` trace
  annotation). ``SPFFT_TPU_SERVE_CONFIG`` loads a recommended-config
  artifact at boot.
* :mod:`~spfft_tpu.control.controller` — :class:`Controller` /
  :class:`ControlLoop`, the deterministic rule-based feedback loop
  (hysteresis + step-counted cooldown) retuning batch window, pin
  policy, bucket cap and pipeline depth from live
  ``ServeMetrics.signals()``.
* :mod:`~spfft_tpu.control.slo` — :class:`SLOSpec` /
  :class:`SLOWatchdog`: declared objectives (p99 latency, error rate,
  quarantine ceiling) evaluated against metrics snapshots; burn rates
  exported as ``spfft_slo_*`` gauges, violations degrade ``health()``.
* ``python -m spfft_tpu.control`` — ``tune`` (offline auto-tuner over
  the serve.bench / bench_overlap_ab protocols, emits the boot
  artifact), ``show`` (knobs, bounds, signals), ``check`` (validate an
  artifact).

See docs/control_plane.md.
"""

from .config import (CONFIG_ENV, KNOB_SPECS, KnobSpec, ServeConfig,
                     global_config, set_global_config)
from .controller import MANAGED_KNOBS, ControlLoop, Controller, Decision
from .slo import SLOSpec, SLOWatchdog

__all__ = [
    "ServeConfig", "KnobSpec", "KNOB_SPECS", "CONFIG_ENV",
    "global_config", "set_global_config",
    "Controller", "ControlLoop", "Decision", "MANAGED_KNOBS",
    "SLOSpec", "SLOWatchdog",
]

"""Span-closure checker: every obs span opened must have a closure
story on all paths.

The round-10 contract — zero unclosed spans on every failure path — is
runtime-tested by the fault suite; this checker makes its *shape*
static. A span-open site is any ``<recv>.begin(...)`` call (the
package's only span-opening spelling outside the ``with
tracer.span(...)`` context manager, which closes itself). Each open
site must satisfy one of:

1. **Handler closure** — the enclosing function contains a close call
   (``finish`` / ``close`` / ``end`` / ``end_all``) on the same
   receiver chain inside an ``except`` handler or ``finally`` block
   (the begin-then-try idiom: ``Tracer.span`` itself, the executor's
   bucket paths), meaning an exception cannot escape with the span
   open.
2. **Sweep closure** — the function contains a sweeping close
   (``close`` / ``end_all``) on the same receiver after the open (the
   resolve-then-settle idiom).
3. **Declared cross-function closure** — ``# span: closed-by(<target>)``
   on the open line; the checker verifies the target function exists in
   the index and itself contains a close call. This is how the
   executor's submit-thread-opens / dispatcher-closes handoff is
   declared (``serve.queue_wait``).
4. **Waiver** — ``# span: waived(reason)``, listed in the report.

Close calls on a DIFFERENT receiver chain never satisfy a site: closing
``other.trace`` cannot settle ``req.trace``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import Finding, PackageIndex, dotted

CHECKER = "span-closure"

OPEN_METHODS = {"begin"}
CLOSE_METHODS = {"finish", "close", "end", "end_all"}
SWEEP_METHODS = {"close", "end_all"}


def _recv_chain(call: ast.Call) -> Optional[str]:
    """Receiver chain of a method call: ``req.trace.finish(...)`` ->
    ``req.trace``; plain-name calls return None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    return dotted(call.func.value)


def _method(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


class _SpanVisitor(ast.NodeVisitor):
    """Collects open sites and close sites (with handler/finally
    context) in one function body."""

    def __init__(self):
        self.opens: List[Tuple[ast.Call, str]] = []
        #: (line, receiver chain, method, in_handler)
        self.closes: List[Tuple[int, str, str, bool]] = []
        self._handler_depth = 0

    def visit_Try(self, node: ast.Try):
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        self._handler_depth += 1
        for handler in node.handlers:
            for stmt in handler.body:
                self.visit(stmt)
        for stmt in node.finalbody:
            self.visit(stmt)
        self._handler_depth -= 1

    def visit_Call(self, node: ast.Call):
        meth = _method(node)
        recv = _recv_chain(node)
        if meth in OPEN_METHODS and recv is not None:
            self.opens.append((node, recv))
        elif meth in CLOSE_METHODS and recv is not None:
            self.closes.append((node.lineno, recv, meth,
                                self._handler_depth > 0))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        return  # nested defs analysed separately

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


def _iter_functions(index: PackageIndex):
    for mod in index.modules.values():
        for fi in mod.functions.values():
            yield mod, fi
        for ci in mod.classes.values():
            for fi in ci.methods.values():
                yield mod, fi


def _target_exists_and_closes(index: PackageIndex,
                              target: str) -> bool:
    """closed-by(<target>): the named function exists and contains a
    close call. Accepts ``Class.method``, ``function`` or a full
    ``module::Class.method`` spelling."""
    for mod, fi in _iter_functions(index):
        qual = fi.qualname
        short = qual.split("::", 1)[-1]
        if target not in (qual, short, fi.name):
            continue
        v = _SpanVisitor()
        for stmt in fi.node.body:
            v.visit(stmt)
        if v.closes:
            return True
    return False


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    open_count = 0
    for mod, fi in _iter_functions(index):
        v = _SpanVisitor()
        for stmt in fi.node.body:
            v.visit(stmt)
        if not v.opens:
            continue
        for node, recv in v.opens:
            open_count += 1
            # Tracer.begin's own definition is the primitive, not a
            # call site; a method NAMED begin whose body this is never
            # appears here because we only look at calls.
            handler_close = any(r == recv and in_handler
                                for (_, r, _, in_handler) in v.closes)
            sweep_close = any(r == recv and m in SWEEP_METHODS
                              and line >= node.lineno
                              for (line, r, m, _) in v.closes)
            if handler_close or sweep_close:
                continue
            target = mod.closed_by_for(node)
            if target is not None:
                if _target_exists_and_closes(index, target):
                    continue
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, node.lineno,
                    f"span opened on {recv!r} declares closed-by"
                    f"({target}) but no such function with a close "
                    f"call exists in the package"))
                continue
            reason = mod.waiver_for(node, "span")
            findings.append(Finding(
                CHECKER, "error", mod.relpath, node.lineno,
                f"span opened on {recv!r} in {fi.qualname} has no "
                f"closure on all paths: no handler/finally close, no "
                f"sweeping close after it, and no "
                f"`# span: closed-by(...)` declaration",
                waived=reason is not None, reason=reason or ""))
    return findings, {"span_open_sites": open_count}

"""Event-kind registry checker.

Every structured journal event the package emits — a
``record_event("<kind>", **attrs)`` call — must name a kind declared
EXACTLY ONCE in ``obs/recorder.py``'s ``EVENT_SPECS``, with every
keyword attr inside the kind's declared key set; and every declared
kind must be emitted somewhere. The runtime journal drops undeclared
kinds/attrs silently (counted — it must never take down serving), so
this checker is where a typo'd kind or attr becomes a build failure
instead of a silently-empty flight recorder.

What counts as an emission: any call whose callee is literally named
``record_event`` (``obs.record_event``, ``_obs.record_event``, a bare
``record_event``, or a module-local wrapper's inner call — e.g.
``faults._journal``). Wrappers that forward a VARIABLE kind get a
warning, not an error (the registry can't see through them), waivable
like everything else with ``# events: waived(reason)``.

Checks:

1. emitted kind literal not declared -> error (waivable);
2. declared kind never emitted anywhere -> error;
3. emission keyword not in the kind's declared attr key set -> error;
4. duplicate kind keys / malformed specs (not a
   ``(category, help, (attr, ...))`` literal, kind not dotted
   ``category.name`` lowercase) -> error;
5. extra positional args on ``record_event`` (its signature is
   kind-only positional: attrs must be keywords) -> error.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, ModuleInfo, PackageIndex

CHECKER = "event-registry"

KIND_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")
SPECS_NAME = "EVENT_SPECS"
EMIT_NAME = "record_event"


def _find_specs(index: PackageIndex):
    """The EVENT_SPECS dict literal: (module, ast.Dict) or None."""
    for mod in index.modules.values():
        for stmt in mod.tree.body:
            targets = []
            value = None
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                targets = [stmt.target]
                value = stmt.value
            if any(t.id == SPECS_NAME for t in targets) \
                    and isinstance(value, ast.Dict):
                return mod, value
    return None


def _parse_specs(mod: ModuleInfo, node: ast.Dict,
                 findings: List[Finding]
                 ) -> Dict[str, Tuple[Set[str], int]]:
    """kind -> (declared attr keys, lineno); malformed specs are
    reported and still registered (one finding, not a cascade)."""
    declared: Dict[str, Tuple[Set[str], int]] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant)
                and isinstance(k.value, str)):
            findings.append(Finding(
                CHECKER, "error", mod.relpath,
                getattr(k, "lineno", node.lineno),
                f"non-literal key in {SPECS_NAME} (kinds must be "
                f"string literals the checker can read)"))
            continue
        kind = k.value
        if kind in declared:
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"event kind {kind!r} declared more than once in "
                f"{SPECS_NAME}"))
            continue
        if not KIND_RE.match(kind):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"event kind {kind!r} is not dotted lowercase "
                f"'category.name'"))
        attrs: Set[str] = set()
        ok = (isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) == 3
              and isinstance(v.elts[0], ast.Constant)
              and isinstance(v.elts[0].value, str)
              and isinstance(v.elts[1], ast.Constant)
              and isinstance(v.elts[1].value, str)
              and isinstance(v.elts[2], (ast.Tuple, ast.List)))
        if ok:
            for a in v.elts[2].elts:
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, str):
                    attrs.add(a.value)
                else:
                    ok = False
        if not ok:
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"event kind {kind!r} spec is not a literal "
                f"(category, help, (attr, ...)) tuple"))
        elif not re.match(r"^[a-z][a-z0-9_]*$", v.elts[0].value):
            # the category is the owning SUBSYSTEM (control, serve,
            # cluster, ...), deliberately not the dotted prefix — one
            # subsystem owns several event nouns
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"event kind {kind!r} category "
                f"{v.elts[0].value!r} is not a lowercase identifier"))
        declared[kind] = (attrs, k.lineno)
    return declared


def _is_emit_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == EMIT_NAME
    if isinstance(func, ast.Attribute):
        return func.attr == EMIT_NAME
    return False


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    specs = _find_specs(index)
    if specs is None:
        findings.append(Finding(
            CHECKER, "error", "obs/recorder.py", 1,
            f"no {SPECS_NAME} declaration found — every journal event "
            f"kind must be declared once in obs/recorder.py"))
        return findings, {}
    specs_mod, specs_node = specs
    declared = _parse_specs(specs_mod, specs_node, findings)

    emitted: Set[str] = set()
    emissions = 0
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and _is_emit_call(node)):
                continue
            # skip the definition module's own journal plumbing is NOT
            # needed: recorder.py's internal emissions (incident
            # capture outcomes) are real events like any other
            emissions += 1
            reason = mod.waiver_for(node, "events")
            if len(node.args) > 1:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, node.lineno,
                    f"{EMIT_NAME} takes one positional arg (the "
                    f"kind); attrs must be keywords",
                    waived=reason is not None, reason=reason or ""))
            first = node.args[0] if node.args else None
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                findings.append(Finding(
                    CHECKER, "warning", mod.relpath, node.lineno,
                    f"{EMIT_NAME} called with a non-literal kind — "
                    f"the registry cannot verify it statically",
                    waived=reason is not None, reason=reason or ""))
                continue
            kind = first.value
            emitted.add(kind)
            info = declared.get(kind)
            if info is None:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, node.lineno,
                    f"event kind {kind!r} emitted here but not "
                    f"declared in obs/recorder.py {SPECS_NAME}",
                    waived=reason is not None, reason=reason or ""))
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue  # **splat: runtime filtering covers it
                if kw.arg not in info[0]:
                    findings.append(Finding(
                        CHECKER, "error", mod.relpath, node.lineno,
                        f"event {kind!r} emitted with undeclared attr "
                        f"{kw.arg!r} (declared: "
                        f"{sorted(info[0])})",
                        waived=reason is not None, reason=reason or ""))

    for kind, (_, lineno) in sorted(declared.items()):
        if kind in emitted:
            continue
        stub = ast.Constant(value=kind)
        stub.lineno = lineno
        stub.end_lineno = lineno
        reason = specs_mod.waiver_for(stub, "events")
        findings.append(Finding(
            CHECKER, "error", specs_mod.relpath, lineno,
            f"event kind {kind!r} declared in {SPECS_NAME} but "
            f"never emitted by any {EMIT_NAME} call",
            waived=reason is not None, reason=reason or ""))

    extras = {"declared_event_kinds": len(declared),
              "event_emission_sites": emissions}
    return findings, extras

"""spfft_tpu.analysis — the project lint engine.

An AST-based static-analysis pass that enforces the contracts the code
already claims (see docs/static_analysis.md for the checker catalogue
and annotation syntax):

* ``lock-discipline`` / ``lock-order`` — ``#: guarded by _lock``
  fields only touched under their lock; acquisition-order graph with
  deadlock-shape (cycle) detection (:mod:`.locks`);
* ``span-closure`` — every obs span open site has a closure story on
  all paths (:mod:`.spans`);
* ``counter-registry`` — every ``spfft_*`` series declared exactly
  once in ``obs/counters.py`` and surfaced by ``prometheus_text``
  (:mod:`.counters_check`);
* ``error-taxonomy`` — every exception class carries a code, is
  raised somewhere and is documented (:mod:`.errors_check`);
* ``knob-registry`` — ``KNOB_SPECS`` sanity, env spellings, docs rows
  (:mod:`.knobs`);
* ``fault-sites`` — every fault-injection check names a site declared
  exactly once in ``faults.SITES``, and every declared site is checked
  somewhere (:mod:`.faults_check`);
* ``event-registry`` — every ``record_event`` kind declared exactly
  once in ``obs/recorder.py``'s ``EVENT_SPECS``, every declared kind
  emitted, attrs inside the declared key set (:mod:`.events_check`);
* ``trace-context`` — every ``# trace: boundary(param)``-annotated
  cluster RPC boundary forwards its propagated trace context, opens
  no context-less span, and is never called without the context bound
  (:mod:`.trace_check`);
* ``baseline-lint`` — unused imports + undefined names, the
  dependency-free twin of the ruff config (:mod:`.baseline`).

Run with ``python -m spfft_tpu.analysis`` or ``make analyze``; the
package is parsed ONCE (:func:`core.index_package`) and every checker
consumes the shared index.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from . import (baseline, counters_check, errors_check, events_check,
               faults_check, knobs, locks, spans, trace_check)
from .core import (Finding, PackageIndex, Report, index_package,
                   index_sources)

__all__ = ["Finding", "PackageIndex", "Report", "index_package",
           "index_sources", "run_analysis", "CHECKERS"]

#: Checker registry: name -> callable(index) -> (findings, extras).
#: errors/knobs take repo-dependent doc arguments; run_analysis wires
#: them.
CHECKERS = ("lock-discipline", "span-closure", "counter-registry",
            "error-taxonomy", "knob-registry", "fault-sites",
            "event-registry", "trace-context", "baseline-lint")


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def run_analysis(root: Optional[str] = None,
                 checkers: Optional[List[str]] = None,
                 docs_root: Optional[str] = None) -> Report:
    """Run the selected ``checkers`` (default: all) over the package at
    ``root`` (default: the installed spfft_tpu package) and return the
    combined :class:`Report`."""
    root = root or package_root()
    docs_root = docs_root if docs_root is not None else \
        os.path.dirname(os.path.abspath(root))
    selected = list(checkers) if checkers else list(CHECKERS)
    unknown = set(selected) - set(CHECKERS)
    if unknown:
        raise ValueError(f"unknown checkers: {sorted(unknown)} "
                         f"(available: {list(CHECKERS)})")
    index = index_package(root)
    report = Report()
    if "lock-discipline" in selected:
        findings, extras = locks.check(index)
        report.extend("lock-discipline", findings)
        report.extras.update(extras)
    if "span-closure" in selected:
        findings, extras = spans.check(index)
        report.extend("span-closure", findings)
        report.extras.update(extras)
    if "counter-registry" in selected:
        findings, extras = counters_check.check(index)
        report.extend("counter-registry", findings)
        report.extras.update(extras)
    if "error-taxonomy" in selected:
        docs = errors_check.default_docs_paths(docs_root)
        findings, extras = errors_check.check(
            index, docs_paths=docs or None)
        report.extend("error-taxonomy", findings)
        report.extras.update(extras)
    if "knob-registry" in selected:
        doc = os.path.join(docs_root, "docs", "control_plane.md")
        findings, extras = knobs.check(
            index, doc_path=doc if os.path.exists(doc) else None)
        report.extend("knob-registry", findings)
        report.extras.update(extras)
    if "fault-sites" in selected:
        findings, extras = faults_check.check(index)
        report.extend("fault-sites", findings)
        report.extras.update(extras)
    if "event-registry" in selected:
        findings, extras = events_check.check(index)
        report.extend("event-registry", findings)
        report.extras.update(extras)
    if "trace-context" in selected:
        findings, extras = trace_check.check(index)
        report.extend("trace-context", findings)
        report.extras.update(extras)
    if "baseline-lint" in selected:
        findings, extras = baseline.check(index)
        report.extend("baseline-lint", findings)
        report.extras.update(extras)
    return report

"""Shared module-indexing core of the project lint engine.

The reference library leans on compiler-enforced invariants (typed
views, ``disjoint`` aliasing checks — the L0 memory layer); this Python
rewrite has none of that, so the contracts the code claims in comments
and docstrings — "mutated only under the executor's pool lock", "every
span closed on all failure paths", "counter names declared once" — were
enforced by review discipline alone. This package turns them into
machine-checked annotations: every checker (:mod:`locks`, :mod:`spans`,
:mod:`counters_check`, :mod:`errors_check`, :mod:`knobs`,
:mod:`baseline`) runs over the ONE index built here, so the package is
parsed exactly once per analysis run.

Annotation grammar (comments, parsed with :mod:`tokenize` so they carry
exact line numbers):

``#: guarded by <lock>``
    On (or on the line above) the first ``self.<field> = ...``
    assignment: every read/write of ``<field>`` in that class must sit
    inside ``with self.<lock>``. On a module-level assignment the lock
    is a module-level lock object.
``# lock: waived(<reason>)``
    Trailing on an access line (or standalone on the line above the
    statement): suppresses the lock-discipline finding; the report
    lists every waiver with its reason.
``# lock: holds(<lock>)``
    On a ``def`` line: the body is assumed to hold ``<lock>`` (the
    "_locked-suffix helper" idiom); the checker instead verifies every
    resolvable CALL of the method is made while holding it.
``# span: closed-by(<Qualname>)``
    On a span-open line: closure happens cross-function in
    ``<Qualname>`` (``Class.method`` or a function name), which must
    exist and contain a close call.
``# trace: boundary(<param>)``
    On a ``def`` line: the function is a cluster RPC boundary whose
    ``<param>`` carries the propagated trace context (see
    :mod:`.trace_check` for the three rules this enables).
``# span: waived(<reason>)`` / ``# counters: waived(...)`` /
``# errors: waived(...)`` / ``# knobs: waived(...)`` /
``# trace: waived(...)``
    Per-checker escape hatches, all listed in the report.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: Annotation comment patterns.
GUARD_RE = re.compile(r"#:\s*guarded by\s+([A-Za-z_][A-Za-z0-9_]*)")
WAIVE_RE = re.compile(
    r"#\s*(lock|span|counters|errors|knobs|lint|faults|trace|events)"
    r"\s*:\s*waived\(([^)]*)\)")
HOLDS_RE = re.compile(
    r"#\s*lock\s*:\s*holds\(([A-Za-z_][A-Za-z0-9_]*)\)")
CLOSED_BY_RE = re.compile(r"#\s*span\s*:\s*closed-by\(([^)]+)\)")

#: Constructors whose result is a lock-like object (``with`` works and
#: mutual exclusion is the point).
LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


class Finding:
    """One checker result. ``severity`` is ``error`` (nonzero exit) or
    ``warning``; a waived finding is demoted to the report's waiver
    list instead."""

    __slots__ = ("checker", "severity", "path", "line", "message",
                 "waived", "reason")

    def __init__(self, checker: str, severity: str, path: str,
                 line: int, message: str, waived: bool = False,
                 reason: str = ""):
        self.checker = checker
        self.severity = severity
        self.path = path
        self.line = line
        self.message = message
        self.waived = waived
        self.reason = reason

    def to_dict(self) -> dict:
        d = {"checker": self.checker, "severity": self.severity,
             "path": self.path, "line": self.line,
             "message": self.message}
        if self.waived:
            d["waived"] = True
            d["reason"] = self.reason
        return d

    def __repr__(self):  # pragma: no cover - debugging aid
        w = " [waived]" if self.waived else ""
        return (f"{self.path}:{self.line}: [{self.checker}] "
                f"{self.message}{w}")


class FunctionInfo:
    """One function/method: AST node, qualname, def-line annotations."""

    __slots__ = ("name", "qualname", "node", "holds", "class_name")

    def __init__(self, name: str, qualname: str, node,
                 holds: Optional[str], class_name: Optional[str]):
        self.name = name
        self.qualname = qualname
        self.node = node
        self.holds = holds
        self.class_name = class_name


class ClassInfo:
    """One class: methods, lock fields, guarded-field declarations and
    inferred field types."""

    __slots__ = ("name", "key", "node", "methods", "lock_fields",
                 "guarded", "field_types", "bases")

    def __init__(self, name: str, key: str, node):
        self.name = name
        self.key = key            # "<relpath>::<ClassName>"
        self.node = node
        self.methods: Dict[str, FunctionInfo] = {}
        self.lock_fields: Set[str] = set()
        self.guarded: Dict[str, str] = {}       # field -> lock attr
        self.field_types: Dict[str, str] = {}   # field -> class key
        self.bases: List[str] = []


class ModuleInfo:
    """One parsed module plus its comment map and annotations."""

    __slots__ = ("path", "relpath", "source", "tree", "comments",
                 "classes", "functions", "module_locks",
                 "guarded_globals", "instance_types", "import_alias",
                 "imported_names", "waivers_by_line", "closed_by_line",
                 "class_aliases", "standalone_comment_lines")

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source)
        self.comments: Dict[int, List[str]] = {}
        self.standalone_comment_lines: set = set()
        self._collect_comments()
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.module_locks: Set[str] = set()
        self.guarded_globals: Dict[str, str] = {}
        #: module-level ``NAME = ClassName(...)`` instances -> class key
        self.instance_types: Dict[str, str] = {}
        #: ``import x.y as z`` / ``from . import obs as _obs``
        self.import_alias: Dict[str, str] = {}
        #: ``from .m import NAME [as A]`` -> (module, original name)
        self.imported_names: Dict[str, Tuple[str, str]] = {}
        #: ``from .m import ClassName`` resolved to class keys later
        self.class_aliases: Dict[str, str] = {}
        self.waivers_by_line: Dict[int, Tuple[str, str]] = {}
        self.closed_by_line: Dict[int, str] = {}
        self._collect_annotation_lines()

    def _collect_comments(self) -> None:
        lines = self.source.splitlines()
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    line = tok.start[0]
                    self.comments.setdefault(line, []).append(
                        tok.string)
                    text = (lines[line - 1] if line <= len(lines)
                            else "")
                    if text.lstrip().startswith("#"):
                        self.standalone_comment_lines.add(line)
        except tokenize.TokenError:  # pragma: no cover
            pass

    def _collect_annotation_lines(self) -> None:
        for line, texts in self.comments.items():
            for text in texts:
                m = WAIVE_RE.search(text)
                if m:
                    self.waivers_by_line[line] = (m.group(1),
                                                  m.group(2).strip())
                m = CLOSED_BY_RE.search(text)
                if m:
                    self.closed_by_line[line] = m.group(1).strip()

    # -- comment lookups ----------------------------------------------------
    def comment_match(self, regex, line: int) -> Optional[re.Match]:
        for text in self.comments.get(line, ()):
            m = regex.search(text)
            if m:
                return m
        return None

    def statement_annotation(self, node, table: Dict[int, Tuple],
                             kind: Optional[str] = None):
        """Annotation covering ``node``'s statement: a trailing comment
        on any line the statement spans, or a STANDALONE comment on the
        line directly above it (a trailing comment on the previous
        statement never leaks onto this one)."""
        end = getattr(node, "end_lineno", node.lineno)
        for line in range(node.lineno, end + 1):
            hit = table.get(line)
            if hit is not None and (kind is None or hit[0] == kind):
                return hit
        if node.lineno - 1 in self.standalone_comment_lines:
            hit = table.get(node.lineno - 1)
            if hit is not None and (kind is None or hit[0] == kind):
                return hit
        return None

    def waiver_for(self, node, checker: str) -> Optional[str]:
        hit = self.statement_annotation(node, self.waivers_by_line,
                                        checker)
        return hit[1] if hit is not None else None

    def closed_by_for(self, node) -> Optional[str]:
        end = getattr(node, "end_lineno", node.lineno)
        for line in range(node.lineno, end + 1):
            if line in self.closed_by_line:
                return self.closed_by_line[line]
        if node.lineno - 1 in self.standalone_comment_lines:
            return self.closed_by_line.get(node.lineno - 1)
        return None

    def guard_decl_for(self, node) -> Optional[str]:
        end = getattr(node, "end_lineno", node.lineno)
        lines = list(range(node.lineno, end + 1))
        if node.lineno - 1 in self.standalone_comment_lines:
            lines.append(node.lineno - 1)
        for line in lines:
            m = self.comment_match(GUARD_RE, line)
            if m:
                return m.group(1)
        return None


def dotted(node) -> Optional[str]:
    """``a.b.c`` attribute/name chain as a string, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node) -> Optional[str]:
    """Dotted name of a call's callee, else None."""
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return None


def _is_lock_ctor(node) -> bool:
    name = call_name(node)
    if name is None:
        return False
    last = name.split(".")[-1]
    return last in LOCK_FACTORIES


class PackageIndex:
    """The parsed package: every module, class, function, lock and
    annotation — built once, consumed by every checker."""

    def __init__(self, modules: Dict[str, ModuleInfo]):
        self.modules = modules
        #: class key -> ClassInfo
        self.classes: Dict[str, ClassInfo] = {}
        #: bare class name -> [class keys] (ambiguity-aware resolution)
        self.class_names: Dict[str, List[str]] = {}
        #: bare method name -> [(class key, FunctionInfo)]
        self.methods_by_name: Dict[str, List[Tuple[str, FunctionInfo]]] \
            = {}
        for mod in modules.values():
            self._index_module(mod)
        for mod in modules.values():
            self._resolve_imports(mod)
            self._infer_field_types(mod)

    # -- construction -------------------------------------------------------
    def _index_module(self, mod: ModuleInfo) -> None:
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._index_class(mod, stmt)
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                fi = self._make_function(mod, stmt, None)
                mod.functions[stmt.name] = fi
            elif isinstance(stmt, ast.Assign):
                self._index_module_assign(mod, stmt)
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                name = stmt.target.id
                if stmt.value is not None \
                        and _is_lock_ctor(stmt.value):
                    mod.module_locks.add(name)
                lock = mod.guard_decl_for(stmt)
                if lock is not None:
                    mod.guarded_globals[name] = lock
            elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._index_import(mod, stmt)

    def _index_import(self, mod: ModuleInfo, stmt) -> None:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                mod.import_alias[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            return
        base = "." * stmt.level + (stmt.module or "")
        for alias in stmt.names:
            name = alias.asname or alias.name
            mod.imported_names[name] = (base, alias.name)

    def _index_module_assign(self, mod: ModuleInfo, stmt: ast.Assign):
        for tgt in stmt.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if _is_lock_ctor(stmt.value):
                mod.module_locks.add(tgt.id)
            cname = call_name(stmt.value)
            if cname is not None:
                mod.instance_types.setdefault(tgt.id, cname)
            lock = mod.guard_decl_for(stmt)
            if lock is not None:
                mod.guarded_globals[tgt.id] = lock
        # AnnAssign module globals handled via ast.AnnAssign walk below

    def _make_function(self, mod: ModuleInfo, node,
                       class_name: Optional[str]) -> FunctionInfo:
        qual = (f"{class_name}.{node.name}" if class_name
                else node.name)
        holds = None
        end = getattr(node, "end_lineno", node.lineno)
        # a holds() annotation on the def line, the line above, or any
        # line of the (possibly multi-line) signature
        sig_end = node.body[0].lineno - 1 if node.body else end
        lines = list(range(node.lineno, sig_end + 1))
        if node.lineno - 1 in mod.standalone_comment_lines:
            lines.insert(0, node.lineno - 1)
        for line in lines:
            m = mod.comment_match(HOLDS_RE, line)
            if m:
                holds = m.group(1)
                break
        fi = FunctionInfo(node.name, f"{mod.relpath}::{qual}", node,
                          holds, class_name)
        key = (f"{mod.relpath}::{class_name}" if class_name else None)
        self.methods_by_name.setdefault(node.name, []).append(
            (key or mod.relpath, fi))
        return fi

    def _index_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        key = f"{mod.relpath}::{node.name}"
        ci = ClassInfo(node.name, key, node)
        ci.bases = [dotted(b) for b in node.bases
                    if dotted(b) is not None]
        mod.classes[node.name] = ci
        self.classes[key] = ci
        self.class_names.setdefault(node.name, []).append(key)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[stmt.name] = self._make_function(
                    mod, stmt, node.name)
        # guarded/lock fields: scan every self.<f> = ... in every method
        for fi in ci.methods.values():
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                    value = sub.value
                elif isinstance(sub, ast.AnnAssign) \
                        and sub.value is not None:
                    targets = [sub.target]
                    value = sub.value
                else:
                    continue
                for tgt in targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    if _is_lock_ctor(value):
                        ci.lock_fields.add(tgt.attr)
                    lock = mod.guard_decl_for(sub)
                    if lock is not None:
                        ci.guarded.setdefault(tgt.attr, lock)

    # -- import/name resolution --------------------------------------------
    def _module_by_suffix(self, name: str) -> Optional[ModuleInfo]:
        """Resolve a dotted/relative module reference to an indexed
        module by path-suffix matching (the index is rooted at one
        package, so suffixes are unambiguous in practice)."""
        name = name.lstrip(".")
        if not name:
            return None
        tail = name.replace(".", "/")
        for rel, mod in self.modules.items():
            stem = rel[:-3] if rel.endswith(".py") else rel
            if stem.endswith("/__init__"):
                stem = stem[:-len("/__init__")]
            if stem == tail or stem.endswith("/" + tail):
                return mod
        return None

    def _resolve_imports(self, mod: ModuleInfo) -> None:
        """Resolve ``from x import Name`` to class keys / instance
        types, following re-exports up to a few hops."""
        for name, (src, orig) in mod.imported_names.items():
            target = self._module_by_suffix(src)
            seen = 0
            while target is not None and seen < 4:
                if orig in target.classes:
                    mod.class_aliases[name] = target.classes[orig].key
                    break
                if orig in target.instance_types:
                    mod.instance_types.setdefault(
                        name, target.instance_types[orig])
                    # class name may need that module's context; store
                    # origin module alongside via a synthetic alias
                    mod.class_aliases.setdefault(
                        "~origin~" + name, target.relpath)
                    break
                if orig in target.imported_names:
                    src2, orig = target.imported_names[orig]
                    target = self._module_by_suffix(src2)
                    seen += 1
                    continue
                break

    def resolve_class(self, mod: ModuleInfo,
                      name: Optional[str]) -> Optional[str]:
        """Class key for a (possibly dotted) class reference as seen
        from ``mod``; None when unknown/ambiguous."""
        if not name:
            return None
        last = name.split(".")[-1]
        if last in mod.classes:
            return mod.classes[last].key
        if last in mod.class_aliases:
            return mod.class_aliases[last]
        keys = self.class_names.get(last)
        if keys and len(keys) == 1:
            return keys[0]
        return None

    def _infer_field_types(self, mod: ModuleInfo) -> None:
        """``self.f = ClassName(...)`` / annotated parameters /
        ``ClassName.classmethod()`` results -> field class keys."""
        for ci in mod.classes.values():
            for fi in ci.methods.values():
                params: Dict[str, Optional[str]] = {}
                args = fi.node.args
                for a in list(args.posonlyargs) + list(args.args) \
                        + list(args.kwonlyargs):
                    params[a.arg] = self._annotation_class(
                        mod, a.annotation)
                for sub in ast.walk(fi.node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    for tgt in sub.targets:
                        if not (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            continue
                        key = self._value_class(mod, sub.value, params)
                        if key is not None:
                            ci.field_types.setdefault(tgt.attr, key)
        # module-level instances: resolve the recorded ctor names
        resolved = {}
        for name, ctor in mod.instance_types.items():
            origin = mod.class_aliases.get("~origin~" + name)
            key = None
            if origin is not None:
                key = self.resolve_class(self.modules[origin], ctor)
            if key is None:
                key = self.resolve_class(mod, ctor)
            if key is not None:
                resolved[name] = key
        mod.instance_types = resolved

    def _annotation_class(self, mod: ModuleInfo,
                          ann) -> Optional[str]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return self.resolve_class(mod, ann.value.split("[")[0])
        if isinstance(ann, ast.Subscript):
            # Optional[X] / "Optional[X]"
            return self._annotation_class(mod, ann.slice)
        name = dotted(ann)
        return self.resolve_class(mod, name)

    def _value_class(self, mod: ModuleInfo, value,
                     params: Dict[str, Optional[str]],
                     cls_key: Optional[str] = None) -> Optional[str]:
        if isinstance(value, ast.IfExp):
            return (self._value_class(mod, value.body, params, cls_key)
                    or self._value_class(mod, value.orelse, params,
                                         cls_key))
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                key = self._value_class(mod, v, params, cls_key)
                if key is not None:
                    return key
        if isinstance(value, ast.Name):
            return params.get(value.id)
        cname = call_name(value)
        if cname is None:
            return None
        if cname == "cls" and cls_key is not None:
            return cls_key
        key = self.resolve_class(mod, cname)
        if key is not None:
            return key
        # ClassName.classmethod() -> ClassName
        parts = cname.split(".")
        if len(parts) >= 2:
            owner = self.resolve_class(mod, ".".join(parts[:-1]))
            if owner is not None and parts[-1] in \
                    self.classes[owner].methods:
                return owner
        return None

    # -- generic receiver typing (used by locks/spans) ----------------------
    def receiver_class(self, mod: ModuleInfo, ci: Optional[ClassInfo],
                       fi: FunctionInfo, recv: str,
                       local_types: Dict[str, str]) -> Optional[str]:
        """Class key of a dotted receiver expression, best-effort."""
        parts = recv.split(".")
        if parts[0] in ("self", "cls") and ci is not None:
            if len(parts) == 1:
                return ci.key
            if len(parts) == 2:
                return ci.field_types.get(parts[1])
            return None
        if len(parts) == 1:
            if parts[0] in local_types:
                return local_types[parts[0]]
            if parts[0] in mod.instance_types:
                return mod.instance_types[parts[0]]
            return None
        # module alias / imported module attribute: "mod.NAME"
        head, rest = parts[0], parts[1:]
        target = None
        if head in mod.import_alias:
            target = self._module_by_suffix(mod.import_alias[head])
        elif head in mod.imported_names:
            src, orig = mod.imported_names[head]
            target = self._module_by_suffix(
                src + "." + orig if src.endswith(".") else
                (src + "." + orig if src else orig))
            if target is None:
                target = self._module_by_suffix(src)
        if target is not None and len(rest) == 1:
            return target.instance_types.get(rest[0])
        return None

    def local_types(self, mod: ModuleInfo,
                    fi: FunctionInfo) -> Dict[str, str]:
        """Simple intra-function inference: ``x = ClassName(...)`` and
        ``x = self.field`` local variable types."""
        out: Dict[str, str] = {}
        ci = (mod.classes.get(fi.class_name)
              if fi.class_name else None)
        params: Dict[str, Optional[str]] = {}
        args = fi.node.args
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            key = self._annotation_class(mod, a.annotation)
            if key is not None:
                params[a.arg] = key
        out.update({k: v for k, v in params.items() if v})
        cls_key = ci.key if ci is not None else None
        for sub in ast.walk(fi.node):
            if not isinstance(sub, ast.Assign):
                continue
            for tgt in sub.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                key = self._value_class(mod, sub.value, params,
                                        cls_key)
                if key is None and ci is not None:
                    val = dotted(sub.value)
                    if val and val.startswith("self.") \
                            and val.count(".") == 1:
                        key = ci.field_types.get(val.split(".")[1])
                if key is not None:
                    out.setdefault(tgt.id, key)
        return out


# -- package loading --------------------------------------------------------

DEFAULT_EXCLUDES = ("analysis/fixtures",)


def iter_py_files(root: str) -> Iterable[Tuple[str, str]]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__",)]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                if any(rel.startswith(e) for e in DEFAULT_EXCLUDES):
                    continue
                yield path, rel


def index_package(root: str) -> PackageIndex:
    """Parse every ``.py`` under ``root`` (the spfft_tpu package
    directory) into one :class:`PackageIndex`."""
    modules: Dict[str, ModuleInfo] = {}
    for path, rel in iter_py_files(root):
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        modules[rel] = ModuleInfo(path, rel, source)
    return PackageIndex(modules)


def index_sources(sources: Dict[str, str]) -> PackageIndex:
    """Index in-memory sources ``{relpath: source}`` — the fixture-test
    entry point."""
    return PackageIndex({rel: ModuleInfo(rel, rel, src)
                         for rel, src in sources.items()})


# -- report -----------------------------------------------------------------

class Report:
    """All findings + waivers of one analysis run."""

    def __init__(self):
        self.findings: List[Finding] = []
        self.checkers_run: List[str] = []
        self.extras: Dict[str, object] = {}

    def extend(self, checker: str, findings: Iterable[Finding]) -> None:
        self.checkers_run.append(checker)
        self.findings.extend(findings)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings
                if not f.waived and f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings
                if not f.waived and f.severity == "warning"]

    @property
    def waivers(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    def ok(self) -> bool:
        return not self.errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok(),
            "checkers": self.checkers_run,
            "summary": {"errors": len(self.errors),
                        "warnings": len(self.warnings),
                        "waivers": len(self.waivers)},
            "findings": [f.to_dict() for f in self.findings
                         if not f.waived],
            "waivers": [f.to_dict() for f in self.waivers],
            "extras": self.extras,
        }

    def to_json(self, indent=2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=False)

    def text(self) -> str:
        lines = []
        for f in sorted(self.findings,
                        key=lambda f: (f.waived, f.path, f.line)):
            if f.waived:
                continue
            lines.append(f"{f.path}:{f.line}: {f.severity}: "
                         f"[{f.checker}] {f.message}")
        if self.waivers:
            lines.append("")
            lines.append(f"waivers ({len(self.waivers)}):")
            for f in sorted(self.waivers,
                            key=lambda f: (f.path, f.line)):
                lines.append(f"  {f.path}:{f.line}: [{f.checker}] "
                             f"{f.message} — waived: {f.reason}")
        lines.append("")
        lines.append(f"{len(self.errors)} error(s), "
                     f"{len(self.warnings)} warning(s), "
                     f"{len(self.waivers)} waiver(s) "
                     f"[{', '.join(self.checkers_run)}]")
        return "\n".join(lines)

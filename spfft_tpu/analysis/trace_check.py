"""Cross-host trace-context propagation checker.

The pod frontend's federated-telemetry contract (docs/cluster.md) is
that one trace id survives the host boundary: the frontend opens the
``cluster.request`` root span, captures its ``obs.TraceContext`` and
every RPC that executes traced work on a host lane must CARRY that
context through — so the lane's spans nest under the frontend's and a
Perfetto view of a pod request reads as one tree, not N orphans. Like
the lock-order graph, that contract spans functions and files, which
is exactly where review discipline leaks; this checker makes it a
machine-checked annotation.

Annotation grammar::

    # trace: boundary(<param>)

on (or directly above) a ``def`` line marks that function as an RPC
boundary whose ``<param>`` is the propagated trace context. Three
rules then hold:

1. **carry** — the boundary body must forward ``<param>`` into at
   least one call (an ``executor.submit(..., trace_ctx=ctx)``, a
   ``begin(parent=ctx)``, a wire encoding ``ctx.to_wire()`` — anything
   that references it as a call input). A boundary that never touches
   its context silently orphans every downstream span.
2. **restore** — every ``.begin(`` span-open inside the boundary must
   reference ``<param>`` among its arguments: a span opened at an
   annotated RPC boundary without the propagated context starts a NEW
   trace id on the far side of the wire, which is precisely the bug
   class this checker exists for.
3. **bind** — every resolvable call of a boundary function (matched by
   callee name across the package) must bind ``<param>``, positionally
   or by keyword (``**kwargs`` forwarding counts). A caller that
   drops the context breaks the chain one hop earlier.

Violations are errors, waivable with ``# trace: waived(<reason>)`` on
the offending line (all waivers are listed in the report). Non-literal
/ dynamic dispatch is out of scope by design — the cluster RPC surface
is deliberately direct (``lane.rpc_submit(...)``) so rule 3 can
resolve its call sites statically.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from .core import Finding, FunctionInfo, ModuleInfo, PackageIndex

CHECKER = "trace-context"

BOUNDARY_RE = re.compile(
    r"#\s*trace\s*:\s*boundary\(([A-Za-z_][A-Za-z0-9_]*)\)")


def _boundary_param(mod: ModuleInfo, fi: FunctionInfo):
    """The ``# trace: boundary(param)`` annotation covering ``fi``'s
    signature (any signature line, or a standalone comment directly
    above the def), or None."""
    node = fi.node
    sig_end = node.body[0].lineno - 1 if node.body else node.lineno
    lines = list(range(node.lineno, sig_end + 1))
    if node.lineno - 1 in mod.standalone_comment_lines:
        lines.insert(0, node.lineno - 1)
    for line in lines:
        m = mod.comment_match(BOUNDARY_RE, line)
        if m:
            return m.group(1)
    return None


def _params(node) -> List[str]:
    args = node.args
    return [a.arg for a in
            list(args.posonlyargs) + list(args.args)]


def _call_references(call: ast.Call, param: str) -> bool:
    """Does ``param`` appear anywhere among the call's inputs?"""
    for sub in list(call.args) + [kw.value for kw in call.keywords]:
        for n in ast.walk(sub):
            if isinstance(n, ast.Name) and n.id == param:
                return True
    return False


def _callee_tail(call: ast.Call) -> str:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return ""


def _waived(mod: ModuleInfo, node, findings: List[Finding],
            message: str) -> None:
    reason = mod.waiver_for(node, "trace")
    findings.append(Finding(
        CHECKER, "error", mod.relpath, node.lineno, message,
        waived=reason is not None, reason=reason or ""))


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []

    # -- collect annotated boundaries ---------------------------------------
    #: bare function name -> [(mod, fi, param)]
    boundaries: Dict[str, List[Tuple[ModuleInfo, FunctionInfo, str]]] \
        = {}
    for mod in index.modules.values():
        if mod.relpath.startswith("analysis/"):
            continue
        funcs = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            param = _boundary_param(mod, fi)
            if param is None:
                continue
            if param not in _params(fi.node) and param not in \
                    [a.arg for a in fi.node.args.kwonlyargs]:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, fi.node.lineno,
                    f"boundary annotation names {param!r}, which is "
                    f"not a parameter of {fi.qualname}"))
                continue
            boundaries.setdefault(fi.name, []).append((mod, fi, param))

    # -- rules 1+2: inside each boundary ------------------------------------
    for entries in boundaries.values():
        for mod, fi, param in entries:
            forwarded = False
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                if _call_references(node, param):
                    forwarded = True
                if _callee_tail(node) == "begin" \
                        and not _call_references(node, param):
                    _waived(mod, node, findings,
                            f"span opened inside trace boundary "
                            f"{fi.qualname} without its context "
                            f"{param!r} — this starts a new trace id "
                            f"across the host boundary")
            if not forwarded:
                _waived(mod, fi.node, findings,
                        f"trace boundary {fi.qualname} never forwards "
                        f"its context {param!r} into any call — "
                        f"downstream spans are orphaned")

    # -- rule 3: every resolvable call binds the context --------------------
    calls_checked = 0
    for mod in index.modules.values():
        if mod.relpath.startswith("analysis/"):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_tail(node)
            entries = boundaries.get(name)
            if not entries:
                continue
            bmod, bfi, param = entries[0]
            calls_checked += 1
            params = _params(bfi.node)
            if param in params:
                pos = params.index(param)
                if params and params[0] in ("self", "cls") \
                        and isinstance(node.func, ast.Attribute):
                    pos -= 1
                bound_pos = len(node.args) > pos >= 0
            else:
                bound_pos = False  # keyword-only context parameter
            bound_kw = any(kw.arg == param or kw.arg is None
                           for kw in node.keywords)
            if not (bound_pos or bound_kw):
                _waived(mod, node, findings,
                        f"call of trace boundary {bfi.qualname} does "
                        f"not bind its context parameter {param!r} — "
                        f"the trace chain breaks here")

    extras = {"trace_boundaries":
              sum(len(v) for v in boundaries.values()),
              "boundary_calls_checked": calls_checked}
    return findings, extras

"""Error-taxonomy checker.

``errors.py`` mirrors the reference's exception hierarchy + C error
enum; this checker keeps that taxonomy real instead of decorative:

1. every exception class resolves a ``code`` (its own ``code =
   ErrorCode.X`` or an ancestor's, within the module), and every
   referenced ``ErrorCode`` member exists in the enum;
2. every exception class is USED — subclassed in-module, raised, or
   constructed somewhere in the package (``raise X(...)``,
   ``future.set_exception(X(...))``, ...). API-parity classes kept for
   mechanical migration from the reference enum carry an explicit
   ``# errors: waived(reason)`` on their ``class`` line, which the
   report lists;
3. every exception class has a row/mention in the docs (the taxonomy
   tables in docs/) — an undocumented error type is a support ticket
   with no manual page.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo, PackageIndex, dotted

CHECKER = "error-taxonomy"

ENUM_BASES = {"IntEnum", "Enum", "enum.IntEnum", "enum.Enum"}


def _find_errors_module(index: PackageIndex) -> Optional[ModuleInfo]:
    for rel, mod in index.modules.items():
        if rel == "errors.py" or rel.endswith("/errors.py"):
            return mod
    return None


def _enum_members(mod: ModuleInfo) -> Dict[str, Set[str]]:
    """{enum class name: {member names}} for enum classes in the
    module."""
    out: Dict[str, Set[str]] = {}
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.ClassDef):
            continue
        bases = {dotted(b) for b in stmt.bases}
        if not (bases & ENUM_BASES):
            continue
        members: Set[str] = set()
        for sub in stmt.body:
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        members.add(tgt.id)
        out[stmt.name] = members
    return out


def _exception_classes(mod: ModuleInfo, enums: Dict[str, Set[str]]):
    """Exception classes of the module in definition order:
    [(node, bases-in-module)]."""
    names = set()
    out = []
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.ClassDef) or stmt.name in enums:
            continue
        bases = [dotted(b) for b in stmt.bases]
        in_module = [b for b in bases if b in names]
        is_exc = any(b in names or b in ("Exception", "BaseException")
                     for b in bases)
        if is_exc:
            names.add(stmt.name)
            out.append((stmt, in_module))
    return out


def _own_code(node: ast.ClassDef):
    """(ErrorCode member name, lineno) of a ``code = ErrorCode.X``
    class attribute, else None."""
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "code":
                    name = dotted(stmt.value)
                    if name and "." in name:
                        return name.split(".", 1)[1], stmt.lineno
                    return (name or "?"), stmt.lineno
    return None


def _usage_sites(index: PackageIndex,
                 errors_mod: ModuleInfo) -> Set[str]:
    """Class names raised or constructed anywhere in the package
    outside the errors module itself (import statements don't count)."""
    used: Set[str] = set()
    for mod in index.modules.values():
        if mod is errors_mod:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Raise) and node.exc is not None:
                target = node.exc
                if isinstance(target, ast.Call):
                    target = target.func
                name = dotted(target)
                if name:
                    used.add(name.split(".")[-1])
            elif isinstance(node, ast.Call):
                name = dotted(node.func)
                if name:
                    used.add(name.split(".")[-1])
    return used


def _docs_text(docs_paths: List[str]) -> str:
    chunks = []
    for path in docs_paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                chunks.append(f.read())
        except OSError:
            continue
    return "\n".join(chunks)


def default_docs_paths(repo_root: str) -> List[str]:
    out = []
    readme = os.path.join(repo_root, "README.md")
    if os.path.exists(readme):
        out.append(readme)
    docs = os.path.join(repo_root, "docs")
    if os.path.isdir(docs):
        for fn in sorted(os.listdir(docs)):
            if fn.endswith(".md"):
                out.append(os.path.join(docs, fn))
    return out


def check(index: PackageIndex,
          docs_paths: Optional[List[str]] = None
          ) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    mod = _find_errors_module(index)
    if mod is None:
        findings.append(Finding(CHECKER, "error", "errors.py", 1,
                                "no errors.py module found"))
        return findings, {}
    enums = _enum_members(mod)
    classes = _exception_classes(mod, enums)
    if not classes:
        findings.append(Finding(CHECKER, "error", mod.relpath, 1,
                                "errors.py defines no exception "
                                "classes"))
        return findings, {}

    # 1 — code resolution through the in-module hierarchy
    codes: Dict[str, Optional[Tuple[str, int]]] = {}
    parent: Dict[str, List[str]] = {}
    for node, in_module_bases in classes:
        codes[node.name] = _own_code(node)
        parent[node.name] = in_module_bases

    def resolved_code(name: str, depth=0):
        if depth > 10:
            return None
        own = codes.get(name)
        if own is not None:
            return own
        for base in parent.get(name, ()):
            r = resolved_code(base, depth + 1)
            if r is not None:
                return r
        return None

    all_members = set()
    for members in enums.values():
        all_members |= members
    for node, _bases in classes:
        code = resolved_code(node.name)
        if code is None:
            findings.append(Finding(
                CHECKER, "error", mod.relpath, node.lineno,
                f"exception class {node.name} resolves no error code "
                f"(no `code = ErrorCode.X` on it or any ancestor)"))
        else:
            member, lineno = code
            if enums and member not in all_members:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, lineno,
                    f"{node.name}.code references unknown ErrorCode "
                    f"member {member!r}"))

    # 2 — every class is used (raised/constructed/subclassed)
    used = _usage_sites(index, mod)
    subclassed = {b for _node, bases in classes for b in bases}
    for node, _bases in classes:
        if node.name in used or node.name in subclassed:
            continue
        reason = mod.waiver_for(node, "errors")
        findings.append(Finding(
            CHECKER, "error", mod.relpath, node.lineno,
            f"exception class {node.name} is never raised, "
            f"constructed or subclassed in the package",
            waived=reason is not None, reason=reason or ""))

    # 3 — documented in the taxonomy docs
    if docs_paths is not None:
        text = _docs_text(docs_paths)
        for node, _bases in classes:
            if re.search(r"\b%s\b" % re.escape(node.name), text):
                continue
            reason = mod.waiver_for(node, "errors")
            findings.append(Finding(
                CHECKER, "error", mod.relpath, node.lineno,
                f"exception class {node.name} has no row/mention in "
                f"the docs taxonomy",
                waived=reason is not None, reason=reason or ""))

    return findings, {"error_classes": len(classes)}

"""Knob-registry checker.

``control/config.py``'s ``KNOB_SPECS`` (+ ``PATH_SETTINGS``) is THE
declared home of every serving knob; this checker keeps the
declaration, the env-var spellings and the operator docs in sync:

1. **Spec sanity** — every ``KnobSpec(name, default, lo, hi, kind,
   ...)``: ``lo <= default <= hi``, ``kind`` is ``int`` or ``float``,
   an int knob's bounds/default are integral.
2. **Docs row** — every knob and path setting has a row in the "Knob
   reference" table of ``docs/control_plane.md`` whose default and
   ``[lo, hi]`` bounds match the code; a table row naming an unknown
   knob (stale docs after a rename) is an error.
3. **Env spelling** — the table's env column must name an
   ``SPFFT_TPU_*`` literal that actually appears in the package
   source, and any source env literal whose suffix is a near-miss of a
   knob's canonical ``SPFFT_TPU_<KNOB>`` spelling (edit distance 1-2,
   not exact) is flagged — the typo'd-env-that-silently-does-nothing
   failure mode.
4. **Controller coverage** — the controller's ``MANAGED_KNOBS``
   declaration and its feedback rules must agree: a managed knob no
   ``_retune(...)`` call ever moves (the idle decay walks it but
   nothing drives it away from default — dead management), or a rule
   moving a knob missing from ``MANAGED_KNOBS`` (it never decays back
   on idle), is a finding; so is a managed name that is not a declared
   knob at all.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo, PackageIndex

CHECKER = "knob-registry"

ENV_RE = re.compile(r"SPFFT_TPU_[A-Z0-9_]+")
ROW_RE = re.compile(r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`\s*\|(.*)$")


def _fold(node) -> Optional[float]:
    """Constant-fold the numeric expressions KNOB_SPECS uses
    (``2 * 1024 ** 3`` etc.)."""
    if isinstance(node, ast.Constant) \
            and isinstance(node.value, (int, float)):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _fold(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        left, right = _fold(node.left), _fold(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Pow):
            return left ** right
        if isinstance(node.op, ast.Div):
            return left / right
    return None


class KnobDecl:
    __slots__ = ("name", "default", "lo", "hi", "kind", "lineno")

    def __init__(self, name, default, lo, hi, kind, lineno):
        self.name = name
        self.default = default
        self.lo = lo
        self.hi = hi
        self.kind = kind
        self.lineno = lineno


def _find_config(index: PackageIndex):
    for mod in index.modules.values():
        for stmt in mod.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                if any(isinstance(t, ast.Name)
                       and t.id == "KNOB_SPECS" for t in targets):
                    return mod, stmt.value
    return None


def _parse_knobs(mod: ModuleInfo, value,
                 findings: List[Finding]) -> List[KnobDecl]:
    """KnobSpec(...) calls inside the KNOB_SPECS dict-comprehension
    (or a plain dict of calls)."""
    decls: List[KnobDecl] = []
    for node in ast.walk(value):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "KnobSpec"):
            continue
        args = node.args
        if len(args) < 5 or not (isinstance(args[0], ast.Constant)
                                 and isinstance(args[0].value, str)):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, node.lineno,
                "KnobSpec entry not statically parseable (want "
                "positional name, default, lo, hi, kind)"))
            continue
        name = args[0].value
        default, lo, hi = (_fold(args[1]), _fold(args[2]),
                           _fold(args[3]))
        kind = args[4].id if isinstance(args[4], ast.Name) else None
        decls.append(KnobDecl(name, default, lo, hi, kind,
                              node.lineno))
    return decls


def _path_settings(mod: ModuleInfo) -> List[Tuple[str, int]]:
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if any(isinstance(t, ast.Name)
                   and t.id == "PATH_SETTINGS" for t in targets) \
                    and isinstance(stmt.value, ast.Dict):
                return [(k.value, k.lineno) for k in stmt.value.keys
                        if isinstance(k, ast.Constant)]
    return []


def _env_literals(index: PackageIndex) -> Set[str]:
    out: Set[str] = set()
    for mod in index.modules.values():
        for m in ENV_RE.finditer(mod.source):
            out.add(m.group(0))
    return out


def _edit_distance(a: str, b: str, cap: int = 3) -> int:
    if abs(len(a) - len(b)) >= cap:
        return cap
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        if min(cur) >= cap:
            return cap
        prev = cur
    return min(prev[-1], cap)


def _doc_rows(doc_text: str) -> Dict[str, Tuple[str, int]]:
    """{name: (rest-of-row, line number)} for ``| `name` | ...`` table
    rows in the knob reference doc."""
    rows: Dict[str, Tuple[str, int]] = {}
    for lineno, line in enumerate(doc_text.splitlines(), 1):
        m = ROW_RE.match(line.strip())
        if m and m.group(1) not in rows:
            rows[m.group(1)] = (m.group(2), lineno)
    return rows


def _find_managed(index: PackageIndex):
    """The controller's ``MANAGED_KNOBS`` declaration: the module, the
    declared (name, lineno) entries, and the knob-name literals passed
    to ``self._retune(out, "<knob>", ...)`` anywhere in that module.
    Returns None when no module declares MANAGED_KNOBS (fixture indexes
    without a controller stay out of section 4)."""
    for mod in index.modules.values():
        for stmt in mod.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if not any(isinstance(t, ast.Name)
                       and t.id == "MANAGED_KNOBS" for t in targets):
                continue
            entries: List[Tuple[str, int]] = []
            if isinstance(stmt.value, (ast.Tuple, ast.List)):
                for el in stmt.value.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        entries.append((el.value, el.lineno))
            retuned: Dict[str, int] = {}
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "_retune"
                        and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)
                        and isinstance(node.args[1].value, str)):
                    retuned.setdefault(node.args[1].value, node.lineno)
            return mod, entries, retuned
    return None


def _num(cell: str) -> Optional[float]:
    cell = cell.strip().strip("`")
    try:
        return float(cell)
    except ValueError:
        return None


def check(index: PackageIndex,
          doc_path: Optional[str] = None,
          doc_text: Optional[str] = None
          ) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    found = _find_config(index)
    if found is None:
        findings.append(Finding(
            CHECKER, "error", "control/config.py", 1,
            "no KNOB_SPECS declaration found"))
        return findings, {}
    mod, value = found
    decls = _parse_knobs(mod, value, findings)
    paths = _path_settings(mod)

    # 1 — spec sanity
    for d in decls:
        if None in (d.default, d.lo, d.hi):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, d.lineno,
                f"knob {d.name!r}: default/lo/hi not constant-foldable"))
            continue
        if not (d.lo <= d.default <= d.hi):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, d.lineno,
                f"knob {d.name!r}: default {d.default} outside "
                f"declared bounds [{d.lo}, {d.hi}]"))
        if d.kind not in ("int", "float"):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, d.lineno,
                f"knob {d.name!r}: kind must be int or float, got "
                f"{d.kind!r}"))
        elif d.kind == "int":
            for label, v in (("default", d.default), ("lo", d.lo),
                             ("hi", d.hi)):
                if v is not None and float(v) != int(v):
                    findings.append(Finding(
                        CHECKER, "error", mod.relpath, d.lineno,
                        f"int knob {d.name!r}: {label} {v} is not "
                        f"integral"))

    # 3a — env near-miss scan (code side)
    envs = _env_literals(index)
    known = {f"SPFFT_TPU_{d.name.upper()}" for d in decls}
    known |= {f"SPFFT_TPU_{name.upper()}" for name, _ in paths}
    for env in sorted(envs):
        if env in known:
            continue
        for want in sorted(known):
            dist = _edit_distance(env, want)
            if 0 < dist <= 2:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, 1,
                    f"env var {env!r} found in source is a near-miss "
                    f"of the canonical knob env {want!r} — typo'd "
                    f"knob envs silently do nothing"))
                break

    # 2/3b — docs table cross-check
    if doc_text is None and doc_path is not None \
            and os.path.exists(doc_path):
        with open(doc_path, "r", encoding="utf-8") as f:
            doc_text = f.read()
    if doc_text is not None:
        rows = _doc_rows(doc_text)
        doc_rel = doc_path or "docs/control_plane.md"
        declared_names = {d.name for d in decls} \
            | {name for name, _ in paths}
        for d in decls:
            row = rows.get(d.name)
            if row is None:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, d.lineno,
                    f"knob {d.name!r} has no row in the knob "
                    f"reference table of {doc_rel}"))
                continue
            rest, rowline = row
            cells = [c.strip() for c in rest.strip("|").split("|")]
            # cells: default | bounds | env | signal...
            if len(cells) >= 2:
                doc_default = _num(cells[0])
                if doc_default is not None and d.default is not None \
                        and doc_default != float(d.default):
                    findings.append(Finding(
                        CHECKER, "error", doc_rel, rowline,
                        f"knob {d.name!r}: documented default "
                        f"{cells[0]} != declared {d.default}"))
                bm = re.match(r"^\[([^,\]]+),\s*([^\]]+)\]$",
                              cells[1])
                if bm and d.lo is not None and d.hi is not None:
                    doc_lo, doc_hi = _num(bm.group(1)), \
                        _num(bm.group(2))
                    if (doc_lo, doc_hi) != (float(d.lo), float(d.hi)):
                        findings.append(Finding(
                            CHECKER, "error", doc_rel, rowline,
                            f"knob {d.name!r}: documented bounds "
                            f"{cells[1]} != declared [{d.lo}, "
                            f"{d.hi}]"))
            if len(cells) >= 3:
                env_cell = cells[2].strip("`")
                if env_cell and env_cell not in ("—", "-", ""):
                    if env_cell not in envs:
                        findings.append(Finding(
                            CHECKER, "error", doc_rel, rowline,
                            f"knob {d.name!r}: documented env "
                            f"{env_cell!r} does not appear in the "
                            f"package source"))
        for name, lineno in paths:
            if name not in rows:
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, lineno,
                    f"path setting {name!r} has no row in the knob "
                    f"reference table of {doc_rel}"))
        for name, (_rest, rowline) in rows.items():
            if name not in declared_names:
                findings.append(Finding(
                    CHECKER, "error", doc_rel, rowline,
                    f"knob reference table row {name!r} matches no "
                    f"declared knob or path setting (stale docs?)"))

    # 4 — controller coverage: MANAGED_KNOBS vs the _retune rules
    managed_count = 0
    managed = _find_managed(index)
    if managed is not None:
        cmod, entries, retuned = managed
        managed_count = len(entries)
        declared = {d.name for d in decls}
        managed_names = {name for name, _ in entries}
        for name, lineno in entries:
            if name not in declared:
                findings.append(Finding(
                    CHECKER, "error", cmod.relpath, lineno,
                    f"MANAGED_KNOBS entry {name!r} is not a declared "
                    f"knob in KNOB_SPECS"))
            elif name not in retuned:
                findings.append(Finding(
                    CHECKER, "error", cmod.relpath, lineno,
                    f"managed knob {name!r} has no controller rule — "
                    f"no _retune(...) call ever moves it, so the idle "
                    f"decay manages a knob nothing drives"))
        for name, lineno in sorted(retuned.items()):
            if name not in managed_names:
                findings.append(Finding(
                    CHECKER, "error", cmod.relpath, lineno,
                    f"controller rule moves knob {name!r} which is "
                    f"not in MANAGED_KNOBS — it will never decay back "
                    f"to default on idle"))

    return findings, {"knobs": len(decls), "path_settings": len(paths),
                      "managed_knobs": managed_count}

"""CLI: ``python -m spfft_tpu.analysis`` — run the project lint engine.

Exit status: 0 when every checker passes (waived findings are listed
but do not fail), 1 on any unwaived error, 2 on usage errors.

Examples::

    python -m spfft_tpu.analysis                       # all checkers
    python -m spfft_tpu.analysis --json report.json    # machine output
    python -m spfft_tpu.analysis --checker lock-discipline \
                                 --checker span-closure
    python -m spfft_tpu.analysis --baseline-only       # the lint half
"""

from __future__ import annotations

import argparse
import json
import sys

from . import CHECKERS, run_analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spfft_tpu.analysis",
        description="spfft_tpu project lint engine "
                    "(docs/static_analysis.md)")
    ap.add_argument("--root", default=None,
                    help="package directory to analyze (default: the "
                         "installed spfft_tpu package)")
    ap.add_argument("--docs-root", default=None,
                    help="repo root holding docs/ and README.md "
                         "(default: the package's parent)")
    ap.add_argument("--checker", action="append", default=None,
                    choices=list(CHECKERS), dest="checkers",
                    help="run only the named checker (repeatable)")
    ap.add_argument("--baseline-only", action="store_true",
                    help="run only the baseline lint (the make lint "
                         "fallback when ruff is unavailable)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="also write the machine-readable report here")
    ap.add_argument("--list", action="store_true",
                    help="list available checkers and exit")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the human-readable report on "
                         "success")
    args = ap.parse_args(argv)

    if args.list:
        for name in CHECKERS:
            print(name)
        return 0
    checkers = args.checkers
    if args.baseline_only:
        checkers = ["baseline-lint"]
    try:
        report = run_analysis(root=args.root, checkers=checkers,
                              docs_root=args.docs_root)
    except (OSError, SyntaxError, ValueError) as exc:
        print(f"analysis failed: {exc}", file=sys.stderr)
        return 2
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report.to_dict(), f, indent=2)
    if report.ok() and args.quiet:
        return 0
    print(report.text())
    return 0 if report.ok() else 1


if __name__ == "__main__":
    sys.exit(main())

"""Counter/series registry checker.

Every ``spfft_*`` Prometheus series the package emits must be declared
EXACTLY ONCE in ``obs/counters.py``'s ``METRIC_SPECS`` and be
surfaceable by ``obs.prometheus_text`` — a typo'd counter name becomes
a lint error here instead of a silently-new series on the scrape
endpoint.

What counts as a reference:

* a string-literal first argument of any ``.inc(`` / ``.set(`` /
  ``.get(`` call (the ``Counters`` recording surface);
* any other non-docstring string literal that *looks like* a metric
  name (``spfft_<...>`` — the ``record_store`` event->name dict is the
  motivating case). Package identifiers starting ``spfft_tpu`` are
  excluded.

Checks:

1. referenced name not declared -> error (waivable
   ``# counters: waived(reason)``);
2. ``inc`` on a gauge / ``set`` on a counter -> error;
3. a ``_total``-suffixed name declared as a gauge -> error; a counter
   without the ``_total`` suffix -> warning (exposition convention);
4. declared name never referenced AND not rendered by an exporter
   ``add(...)`` literal or f-string family pattern -> error ("declared
   but never recorded/surfaced");
5. duplicate literal keys inside ``METRIC_SPECS`` -> error.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, ModuleInfo, PackageIndex

CHECKER = "counter-registry"

NAME_RE = re.compile(r"^spfft_[a-z][a-z0-9_]*$")
RECORD_METHODS = {"inc": "counter", "set": "gauge", "get": None}
SPECS_NAME = "METRIC_SPECS"


def _is_metric_literal(value: str) -> bool:
    # a trailing underscore marks a prefix/piece (tempfile prefixes,
    # f-string fragments), never a whole series name
    return (NAME_RE.match(value) is not None
            and not value.endswith("_")
            and not value.startswith("spfft_tpu"))


def _docstring_ids(tree) -> Set[int]:
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def _find_specs(index: PackageIndex):
    """The METRIC_SPECS dict literal: (module, ast.Dict) or None."""
    for mod in index.modules.values():
        for stmt in mod.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            if any(t.id == SPECS_NAME for t in targets) \
                    and isinstance(value, ast.Dict):
                return mod, value
    return None


def _parse_specs(mod: ModuleInfo, node: ast.Dict,
                 findings: List[Finding]) -> Dict[str, Tuple[str, int]]:
    declared: Dict[str, Tuple[str, int]] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant)
                and isinstance(k.value, str)):
            continue
        name = k.value
        mtype = ""
        if isinstance(v, (ast.Tuple, ast.List)) and v.elts \
                and isinstance(v.elts[0], ast.Constant):
            mtype = v.elts[0].value
        elif isinstance(v, ast.Call):
            for arg in v.args[:1]:
                if isinstance(arg, ast.Constant):
                    mtype = arg.value
            for kw in v.keywords:
                if kw.arg == "mtype" \
                        and isinstance(kw.value, ast.Constant):
                    mtype = kw.value.value
        if name in declared:
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"metric {name!r} declared more than once in "
                f"{SPECS_NAME}"))
            continue
        declared[name] = (str(mtype), k.lineno)
        if mtype not in ("counter", "gauge"):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"metric {name!r} has unknown type {mtype!r} "
                f"(want 'counter' or 'gauge')"))
        elif name.endswith("_total") and mtype != "counter":
            findings.append(Finding(
                CHECKER, "error", mod.relpath, k.lineno,
                f"metric {name!r} ends in _total but is declared a "
                f"{mtype} (exposition convention: _total == counter)"))
        elif mtype == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                CHECKER, "warning", mod.relpath, k.lineno,
                f"counter {name!r} does not end in _total "
                f"(exposition convention)"))
    return declared


def _exporter_surfaces(index: PackageIndex):
    """Literal names and f-string family patterns passed to a
    ``.add(name, ...)`` exporter call anywhere in the package."""
    literals: Set[str] = set()
    patterns: List[re.Pattern] = []
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add" and node.args):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, str):
                if _is_metric_literal(first.value):
                    literals.add(first.value)
            elif isinstance(first, ast.JoinedStr):
                parts = []
                for piece in first.values:
                    if isinstance(piece, ast.Constant):
                        parts.append(re.escape(str(piece.value)))
                    else:
                        parts.append(r"[a-z0-9_]+")
                pat = "^" + "".join(parts) + "$"
                if pat.startswith("^spfft_"):
                    patterns.append(re.compile(pat))
    return literals, patterns


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    specs = _find_specs(index)
    if specs is None:
        findings.append(Finding(
            CHECKER, "error", "obs/counters.py", 1,
            f"no {SPECS_NAME} declaration found — every spfft_* "
            f"series must be declared once in obs/counters.py"))
        return findings, {}
    specs_mod, specs_node = specs
    declared = _parse_specs(specs_mod, specs_node, findings)

    # -- collect references --------------------------------------------------
    referenced: Dict[str, List[Tuple[str, int]]] = {}
    recorded: Set[str] = set()
    for mod in index.modules.values():
        if mod is specs_mod:
            continue
        doc_ids = _docstring_ids(mod.tree)
        # f-string constituents are fragments, not names
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.JoinedStr):
                for piece in node.values:
                    doc_ids.add(id(piece))
        call_arg_ids: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in RECORD_METHODS \
                    and node.args:
                first = node.args[0]
                call_arg_ids.add(id(first))
                if isinstance(first, ast.Constant) \
                        and isinstance(first.value, str) \
                        and _is_metric_literal(first.value):
                    name = first.value
                    referenced.setdefault(name, []).append(
                        (mod.relpath, node.lineno))
                    want = RECORD_METHODS[node.func.attr]
                    recorded.add(name)
                    info = declared.get(name)
                    if info is not None and want is not None \
                            and info[0] in ("counter", "gauge") \
                            and info[0] != want:
                        reason = mod.waiver_for(node, "counters")
                        findings.append(Finding(
                            CHECKER, "error", mod.relpath, node.lineno,
                            f".{node.func.attr}() on {name!r} but it "
                            f"is declared a {info[0]}",
                            waived=reason is not None,
                            reason=reason or ""))
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and id(node) not in doc_ids \
                    and id(node) not in call_arg_ids \
                    and _is_metric_literal(node.value):
                referenced.setdefault(node.value, []).append(
                    (mod.relpath, node.lineno))
                recorded.add(node.value)

    # -- referenced but undeclared -------------------------------------------
    for name, sites in sorted(referenced.items()):
        if name in declared:
            continue
        for relpath, lineno in sites:
            mod = index.modules[relpath]
            node_stub = ast.Constant(value=name)
            node_stub.lineno = lineno
            node_stub.end_lineno = lineno
            reason = mod.waiver_for(node_stub, "counters")
            findings.append(Finding(
                CHECKER, "error", relpath, lineno,
                f"series {name!r} recorded here but not declared in "
                f"obs/counters.py {SPECS_NAME}",
                waived=reason is not None, reason=reason or ""))

    # -- declared but never recorded/surfaced --------------------------------
    literals, patterns = _exporter_surfaces(index)
    for name, (mtype, lineno) in sorted(declared.items()):
        if name in recorded or name in literals:
            continue
        if any(p.match(name) for p in patterns):
            continue
        stub = ast.Constant(value=name)
        stub.lineno = lineno
        stub.end_lineno = lineno
        reason = specs_mod.waiver_for(stub, "counters")
        findings.append(Finding(
            CHECKER, "error", specs_mod.relpath, lineno,
            f"metric {name!r} declared in {SPECS_NAME} but never "
            f"recorded or rendered by an exporter",
            waived=reason is not None, reason=reason or ""))

    extras = {"declared_metrics": len(declared),
              "referenced_metrics": len(referenced)}
    return findings, extras

"""Baseline lint: unused imports + undefined names (pyflakes-lite).

``make lint`` prefers a real ``ruff`` binary when the environment has
one (config in pyproject.toml, pyflakes-family rules only); this
module is the dependency-free fallback so the lint gate never degrades
to a no-op on a machine without ruff — the two implement the same two
rule families:

* **unused-import** (F401): a name bound by ``import``/``from ...
  import`` and never referenced in the module — by a ``Name`` load, a
  string annotation, or an ``__all__`` entry. Imports in
  ``__init__.py`` files are treated as intentional re-exports (the
  ruff config mirrors this with a per-file ignore).
* **undefined-name** (F821): a ``Name`` load that resolves in no
  enclosing scope, the module scope (order-blind, deliberately more
  conservative than pyflakes) or builtins.

``# noqa`` on the offending line suppresses either, and findings
honour the shared ``# lint: waived(reason)`` annotation.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo, PackageIndex

CHECKER = "baseline-lint"

_BUILTINS = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__builtins__", "__debug__", "__loader__", "__path__",
    "__annotations__", "__dict__", "__class__", "WindowsError",
}


def _has_noqa(mod: ModuleInfo, line: int) -> bool:
    return any("noqa" in c for c in mod.comments.get(line, ()))


class _Scope:
    __slots__ = ("kind", "names", "globals_", "parent")

    def __init__(self, kind: str, parent: Optional["_Scope"]):
        self.kind = kind          # module | function | class | comp
        self.names: Set[str] = set()
        self.globals_: Set[str] = set()
        self.parent = parent


def _string_annotation_names(value: str) -> Set[str]:
    try:
        tree = ast.parse(value, mode="eval")
    except SyntaxError:
        return set()
    return {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}


class _ModuleLint(ast.NodeVisitor):
    """One pass collecting imports, bindings per scope and name loads;
    findings computed at the end."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.module_scope = _Scope("module", None)
        self.scope = self.module_scope
        #: import bindings: name -> (line, display) in MODULE scope
        self.imports: Dict[str, Tuple[int, str]] = {}
        #: every referenced name, module-wide (for unused-import)
        self.referenced: Set[str] = set()
        #: (name, line) loads to resolve against scopes
        self.loads: List[Tuple[str, int, _Scope]] = []
        self.findings: List[Finding] = []

    # -- bindings ------------------------------------------------------------
    def _bind(self, name: str) -> None:
        if name in self.scope.globals_:
            self.module_scope.names.add(name)
        else:
            self.scope.names.add(name)

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self._bind(name)
            if self.scope is self.module_scope:
                self.imports.setdefault(
                    name, (node.lineno, alias.name))

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                # star import: give up on both rules for this module
                self.imports.clear()
                self.module_scope.names.add("*")
                continue
            name = alias.asname or alias.name
            self._bind(name)
            if self.scope is self.module_scope:
                self.imports.setdefault(
                    name, (node.lineno, alias.name))

    def visit_Global(self, node: ast.Global):
        self.scope.globals_.update(node.names)
        self.module_scope.names.update(node.names)

    def visit_Nonlocal(self, node: ast.Nonlocal):
        # treat as binding in current scope (resolution is lexical
        # anyway and we keep the checker conservative)
        self.scope.names.update(node.names)

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load):
            self.referenced.add(node.id)
            self.loads.append((node.id, node.lineno, self.scope))
        else:
            self._bind(node.id)

    def visit_Attribute(self, node: ast.Attribute):
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if node.name:
            self._bind(node.name)
        self.generic_visit(node)

    def _visit_annotation(self, ann) -> None:
        if ann is None:
            return
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            names = _string_annotation_names(ann.value)
            self.referenced.update(names)
            for n in names:
                self.loads.append((n, ann.lineno, self.scope))
            return
        self.visit(ann)

    # -- scopes --------------------------------------------------------------
    def _function(self, node):
        self._bind(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        args = node.args
        for default in list(args.defaults) + \
                [d for d in args.kw_defaults if d is not None]:
            self.visit(default)
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            self._visit_annotation(a.annotation)
        self._visit_annotation(node.returns)
        outer = self.scope
        inner = _Scope("function", self._lexical_parent(outer))
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            inner.names.add(a.arg)
        self.scope = inner
        for stmt in node.body:
            self.visit(stmt)
        self.scope = outer

    def _lexical_parent(self, scope: _Scope) -> _Scope:
        """Class scopes are skipped by nested functions (Python scoping
        rule) — kept conservative: we keep the class scope in the chain
        to avoid false positives on idiomatic class-constant reads, but
        mark it so resolution order stays sane."""
        return scope

    visit_FunctionDef = _function
    visit_AsyncFunctionDef = _function

    def visit_Lambda(self, node: ast.Lambda):
        args = node.args
        for default in list(args.defaults) + \
                [d for d in args.kw_defaults if d is not None]:
            self.visit(default)
        outer = self.scope
        inner = _Scope("function", outer)
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            inner.names.add(a.arg)
        self.scope = inner
        self.visit(node.body)
        self.scope = outer

    def visit_ClassDef(self, node: ast.ClassDef):
        self._bind(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        for base in node.bases:
            self.visit(base)
        for kw in node.keywords:
            self.visit(kw.value)
        outer = self.scope
        self.scope = _Scope("class", outer)
        for stmt in node.body:
            self.visit(stmt)
        self.scope = outer

    def _comprehension(self, node):
        outer = self.scope
        self.scope = _Scope("comp", outer)
        for gen in node.generators:
            self.visit(gen.iter)
            self.visit(gen.target)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self.scope = outer

    visit_ListComp = _comprehension
    visit_SetComp = _comprehension
    visit_GeneratorExp = _comprehension
    visit_DictComp = _comprehension

    def visit_AnnAssign(self, node: ast.AnnAssign):
        self._visit_annotation(node.annotation)
        if node.value is not None:
            self.visit(node.value)
        self.visit(node.target)

    # -- results -------------------------------------------------------------
    def run(self) -> List[Finding]:
        tree = self.mod.tree
        for stmt in tree.body:
            self.visit(stmt)
        if "*" in self.module_scope.names:
            return self.findings  # star import: resolution is hopeless
        # __all__ entries count as references (re-export)
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == "__all__"
                            for t in stmt.targets):
                for el in ast.walk(stmt.value):
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        self.referenced.add(el.value)
        is_init = self.mod.relpath.endswith("__init__.py")
        if not is_init:
            for name, (lineno, display) in sorted(
                    self.imports.items(), key=lambda kv: kv[1][0]):
                if name in self.referenced:
                    continue
                if _has_noqa(self.mod, lineno):
                    continue
                stub = ast.Constant(value=name)
                stub.lineno = lineno
                stub.end_lineno = lineno
                reason = self.mod.waiver_for(stub, "lint")
                self.findings.append(Finding(
                    CHECKER, "error", self.mod.relpath, lineno,
                    f"unused import: {display!r} (bound as {name!r})",
                    waived=reason is not None, reason=reason or ""))
        for name, lineno, scope in self.loads:
            if name in _BUILTINS:
                continue
            s: Optional[_Scope] = scope
            found = False
            while s is not None:
                if name in s.names:
                    found = True
                    break
                s = s.parent
            if not found and name in self.module_scope.names:
                found = True
            if found or _has_noqa(self.mod, lineno):
                continue
            stub = ast.Constant(value=name)
            stub.lineno = lineno
            stub.end_lineno = lineno
            reason = self.mod.waiver_for(stub, "lint")
            self.findings.append(Finding(
                CHECKER, "error", self.mod.relpath, lineno,
                f"undefined name: {name!r}",
                waived=reason is not None, reason=reason or ""))
        return self.findings


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    for mod in index.modules.values():
        findings.extend(_ModuleLint(mod).run())
    # de-duplicate repeated undefined-name hits per (file, name)
    seen: Set[Tuple[str, str]] = set()
    out: List[Finding] = []
    for f in findings:
        key = (f.path, f.message)
        if f.message.startswith("undefined name") and key in seen:
            continue
        seen.add(key)
        out.append(f)
    return out, {}

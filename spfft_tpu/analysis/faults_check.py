"""Fault-site registry checker.

The fault seam (:mod:`spfft_tpu.faults`) is only as trustworthy as its
site names: a chaos script targeting ``store.lod`` silently injects
nothing, and a check site added without a ``SITES`` entry is invisible
to the harness's coverage accounting. This checker closes the loop —
every site name used at a check call must be declared exactly once in
``faults.SITES``, and every declared site must be checked somewhere.

What counts as a reference:

* a string-literal first argument of any ``check_site(`` /
  ``_check_fault(`` call (the unambiguous fault-seam entry points);
* a string-literal first argument of a ``.check(`` / ``._check(`` call
  when the literal is DOTTED (``store.spill``) or already a declared
  site — plain ``.check("x")`` calls on unrelated objects are ignored.

Checks:

1. site referenced at a check call but not declared in ``SITES`` ->
   error (waivable ``# faults: waived(reason)``);
2. site declared in ``SITES`` but never checked anywhere -> error at
   the declaration line (waivable);
3. duplicate declaration inside ``SITES`` -> error.

Variable (non-literal) site arguments — the seam plumbing itself, e.g.
``FaultPlan.check``'s forwarding — are skipped: the contract is on the
leaf call sites that name a subsystem.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from .core import Finding, ModuleInfo, PackageIndex

CHECKER = "fault-sites"

SPECS_NAME = "SITES"
SPECS_MODULE = "faults.py"
SITE_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)*$")
#: Call names that ALWAYS take a fault-site first argument.
STRICT_FUNCS = {"check_site", "_check_fault"}
#: Call names that take one only when the literal is dotted/declared.
LOOSE_FUNCS = {"check", "_check"}


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def _find_sites(index: PackageIndex):
    """The ``SITES`` tuple in faults.py: (module, ast node) or None."""
    for mod in index.modules.values():
        if mod.relpath != SPECS_MODULE:
            continue
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == SPECS_NAME
                            for t in stmt.targets) \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)):
                return mod, stmt.value
    return None


def _parse_sites(mod: ModuleInfo, node,
                 findings: List[Finding]) -> Dict[str, int]:
    declared: Dict[str, int] = {}
    for elt in node.elts:
        if not (isinstance(elt, ast.Constant)
                and isinstance(elt.value, str)):
            findings.append(Finding(
                CHECKER, "error", mod.relpath, elt.lineno,
                f"non-literal entry in {SPECS_NAME} — site names must "
                f"be plain strings"))
            continue
        name = elt.value
        if name in declared:
            findings.append(Finding(
                CHECKER, "error", mod.relpath, elt.lineno,
                f"site {name!r} declared more than once in "
                f"{SPECS_NAME}"))
            continue
        declared[name] = elt.lineno
        if SITE_RE.match(name) is None:
            findings.append(Finding(
                CHECKER, "error", mod.relpath, elt.lineno,
                f"site {name!r} does not match the site grammar "
                f"(lowercase dotted words)"))
    return declared


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    sites = _find_sites(index)
    if sites is None:
        findings.append(Finding(
            CHECKER, "error", SPECS_MODULE, 1,
            f"no {SPECS_NAME} declaration found — every fault site "
            f"must be declared once in faults.py"))
        return findings, {}
    sites_mod, sites_node = sites
    declared = _parse_sites(sites_mod, sites_node, findings)

    # -- collect references --------------------------------------------------
    referenced: Dict[str, List[Tuple[str, int]]] = {}
    for mod in index.modules.values():
        if mod is sites_mod or mod.relpath.startswith("analysis/"):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fname = _call_name(node)
            if fname not in STRICT_FUNCS and fname not in LOOSE_FUNCS:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue  # seam plumbing forwards a variable; skip
            name = first.value
            if fname in LOOSE_FUNCS and "." not in name \
                    and name not in declared:
                continue  # unrelated .check("...") call
            referenced.setdefault(name, []).append(
                (mod.relpath, node.lineno))

    # -- referenced but undeclared -------------------------------------------
    for name, where in sorted(referenced.items()):
        if name in declared:
            continue
        for relpath, lineno in where:
            mod = index.modules[relpath]
            stub = ast.Constant(value=name)
            stub.lineno = lineno
            stub.end_lineno = lineno
            reason = mod.waiver_for(stub, "faults")
            findings.append(Finding(
                CHECKER, "error", relpath, lineno,
                f"fault site {name!r} checked here but not declared "
                f"in faults.py {SPECS_NAME}",
                waived=reason is not None, reason=reason or ""))

    # -- declared but never checked ------------------------------------------
    for name, lineno in sorted(declared.items()):
        if name in referenced:
            continue
        stub = ast.Constant(value=name)
        stub.lineno = lineno
        stub.end_lineno = lineno
        reason = sites_mod.waiver_for(stub, "faults")
        findings.append(Finding(
            CHECKER, "error", sites_mod.relpath, lineno,
            f"fault site {name!r} declared in {SPECS_NAME} but no "
            f"check call ever targets it — dead coverage claim",
            waived=reason is not None, reason=reason or ""))

    extras = {"declared_sites": len(declared),
              "checked_sites": len(referenced)}
    return findings, extras

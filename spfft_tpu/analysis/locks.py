"""Lock-discipline + lock-acquisition-order checkers.

Two checks over the index:

1. **Guarded-field discipline** — a field declared ``#: guarded by
   <lock>`` may only be read or written inside ``with self.<lock>``
   (or a module-level ``with <lock>`` for guarded globals) in its own
   class/module. ``__init__``/``__new__`` are exempt (the object is not
   shared yet); a method annotated ``# lock: holds(<lock>)`` is assumed
   to run under the lock and every resolvable CALL of it is verified to
   actually hold it; ``# lock: waived(reason)`` suppresses one access
   and lands in the report's waiver list.

2. **Acquisition-order graph** — for every function the checker
   computes which known locks are held at each call site (lexically
   nested ``with`` blocks plus ``holds`` annotations), resolves calls
   through the index's receiver typing, propagates transitive
   acquisitions to a fixpoint, and records every "held A while
   acquiring B" edge. A cycle in that digraph is the deadlock shape a
   threaded dispatcher can actually hit, reported with one witness
   path per cycle. The edge list itself lands in the report's extras
   (``lock_order_edges``) — reviewable documentation of the real
   locking hierarchy.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (ClassInfo, Finding, FunctionInfo, ModuleInfo,
                   PackageIndex, dotted)

CHECKER = "lock-discipline"
ORDER_CHECKER = "lock-order"

#: Ambiguous-name call resolution unions candidates only up to this
#: many; beyond it the call is skipped (a generic name like ``get``).
AMBIGUOUS_CAP = 3

#: Method names that collide with builtin-collection / stdlib-object
#: methods: calling one on an UNRESOLVED receiver is almost always a
#: dict/list/deque/thread/file operation, so the by-name fallback must
#: never union it onto a same-named class method (that is how a
#: ``self._store.get(...)`` dict read was once mis-read as
#: ``PlanRegistry.get`` and produced a phantom deadlock cycle). Typed
#: receivers resolve these names normally.
GENERIC_METHOD_NAMES = frozenset({
    "get", "set", "pop", "popitem", "append", "appendleft", "popleft",
    "extend", "extendleft", "update", "clear", "copy", "keys",
    "values", "items", "setdefault", "remove", "discard", "add",
    "insert", "sort", "reverse", "index", "count", "move_to_end",
    "join", "start", "run", "wait", "notify", "notify_all", "acquire",
    "release", "put", "read", "write", "flush", "close", "open",
    "send", "recv", "match", "search", "split", "strip", "load",
    "dump", "loads", "dumps", "encode", "decode", "format", "replace",
})

EXEMPT_METHODS = {"__init__", "__new__", "__del__"}


def _is_property(node) -> bool:
    for dec in node.decorator_list:
        name = dotted(dec)
        if name in ("property", "cached_property",
                    "functools.cached_property"):
            return True
    return False


def _with_locks(node, ci: Optional[ClassInfo],
                mod: ModuleInfo) -> Set[str]:
    """Lock names acquired by one ``with`` statement: ``self.<attr>``
    for known class lock fields, bare names for module locks."""
    out: Set[str] = set()
    for item in node.items:
        name = dotted(item.context_expr)
        if name is None:
            continue
        if name.startswith("self.") and ci is not None:
            attr = name.split(".", 1)[1]
            if "." not in attr:
                out.add(attr)
        elif name in mod.module_locks:
            out.add(name)
    return out


class _AccessVisitor(ast.NodeVisitor):
    """Walks one function body tracking the set of held lock names and
    recording guarded-field accesses made without the right lock."""

    def __init__(self, checker, mod: ModuleInfo,
                 ci: Optional[ClassInfo], fi: FunctionInfo):
        self.checker = checker
        self.mod = mod
        self.ci = ci
        self.fi = fi
        self.held: Set[str] = set()
        if fi.holds:
            self.held.add(fi.holds)
        #: (access node, enclosing statement, field, lock)
        self.violations: List[Tuple[ast.AST, ast.AST, str, str]] = []
        self._stmt_stack: List[ast.AST] = []
        #: (call node, frozenset(held lock ids)) for the order graph
        self.calls: List[Tuple[ast.Call, frozenset]] = []
        #: property reads of same-class @property methods:
        #: (method name, line, frozenset(held lock ids))
        self.property_reads: List[Tuple[str, int, frozenset]] = []
        #: every dotted attribute read: (receiver chain, attr, line,
        #: held) — the order graph maps reads on a __getattr__-bearing
        #: class (ServeConfig's knob reads) onto that method
        self.attr_reads: List[Tuple[str, str, int, frozenset]] = []
        #: with-acquisitions: (lock id, frozenset(held before))
        self.acquisitions: List[Tuple[str, frozenset]] = []

    # lock ids are package-unique strings: "ClassName._lock" scoped by
    # module, or "<module>:<name>" for module-level locks
    def _lock_id(self, name: str) -> str:
        if self.ci is not None and name in self.ci.lock_fields:
            return f"{self.ci.key}.{name}"
        if name in self.mod.module_locks:
            return f"{self.mod.relpath}::{name}"
        if self.ci is not None:
            return f"{self.ci.key}.{name}"
        return f"{self.mod.relpath}::{name}"

    def _held_ids(self) -> frozenset:
        return frozenset(self._lock_id(n) for n in self.held)

    def visit(self, node):
        if isinstance(node, ast.stmt):
            self._stmt_stack.append(node)
            try:
                super().visit(node)
            finally:
                self._stmt_stack.pop()
        else:
            super().visit(node)

    def _stmt(self) -> Optional[ast.AST]:
        return self._stmt_stack[-1] if self._stmt_stack else None

    def visit_With(self, node: ast.With):
        acquired = _with_locks(node, self.ci, self.mod)
        for name in acquired - self.held:
            self.acquisitions.append((self._lock_id(name),
                                      self._held_ids()))
        for item in node.items:
            self.visit(item.context_expr)
        before = set(self.held)
        self.held |= acquired
        for stmt in node.body:
            self.visit(stmt)
        self.held = before

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call):
        self.calls.append((node, self._held_ids()))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and self.ci is not None:
            lock = self.ci.guarded.get(node.attr)
            if lock is not None and lock not in self.held:
                self.violations.append(
                    (node, self._stmt(), node.attr, f"self.{lock}"))
            fi = self.ci.methods.get(node.attr)
            if fi is not None and _is_property(fi.node):
                # a @property read runs the getter: the order graph
                # must see locks the getter takes (config-backed knob
                # properties read ServeConfig._lock)
                self.property_reads.append(
                    (node.attr, node.lineno, self._held_ids()))
        recv = dotted(node.value)
        if recv is not None:
            self.attr_reads.append((recv, node.attr, node.lineno,
                                    self._held_ids()))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        lock = self.mod.guarded_globals.get(node.id)
        if lock is not None and lock not in self.held \
                and not isinstance(node.ctx, ast.Del):
            self.violations.append((node, self._stmt(), node.id, lock))

    # don't descend into nested defs/classes; they are visited as their
    # own functions (a nested function does NOT inherit the held set —
    # it usually runs on another thread)
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return


def _iter_functions(index: PackageIndex):
    for mod in index.modules.values():
        for fi in mod.functions.values():
            yield mod, None, fi
        for ci in mod.classes.values():
            for fi in ci.methods.values():
                yield mod, ci, fi
        # nested defs inside functions (closures, thread targets) are
        # analysed as independent functions with no inherited locks
        seen = {id(fi.node) for fi in mod.functions.values()}
        for ci in mod.classes.values():
            seen |= {id(fi.node) for fi in ci.methods.values()}
        for owner_mod, owner_ci, owner_fi in list(
                _top_level(mod)):
            for sub in ast.walk(owner_fi.node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and id(sub) not in seen:
                    seen.add(id(sub))
                    nested = FunctionInfo(
                        sub.name,
                        f"{owner_fi.qualname}.<{sub.name}>", sub,
                        None, owner_fi.class_name)
                    yield mod, owner_ci, nested


def _top_level(mod: ModuleInfo):
    for fi in mod.functions.values():
        yield mod, None, fi
    for ci in mod.classes.values():
        for fi in ci.methods.values():
            yield mod, ci, fi


def _resolve_call(index: PackageIndex, mod: ModuleInfo,
                  ci: Optional[ClassInfo], fi: FunctionInfo,
                  node: ast.Call,
                  local_types: Dict[str, str]) -> List[FunctionInfo]:
    name = dotted(node.func)
    if name is None:
        return []
    parts = name.split(".")
    # plain function call: same module, or imported function
    if len(parts) == 1:
        if parts[0] in mod.functions:
            return [mod.functions[parts[0]]]
        if parts[0] in mod.imported_names:
            src, orig = mod.imported_names[parts[0]]
            target = index._module_by_suffix(src)
            if target is not None and orig in target.functions:
                return [target.functions[orig]]
        return []
    recv, meth = ".".join(parts[:-1]), parts[-1]
    # module-function call through an alias: "_obs.record_compile"
    if len(parts) == 2 and parts[0] in mod.import_alias:
        target = index._module_by_suffix(mod.import_alias[parts[0]])
        if target is not None and meth in target.functions:
            return [target.functions[meth]]
    key = index.receiver_class(mod, ci, fi, recv, local_types)
    if key is not None:
        target_ci = index.classes.get(key)
        if target_ci is not None and meth in target_ci.methods:
            return [target_ci.methods[meth]]
        return []
    # unresolved receiver: fall back to by-name union when the method
    # name is rare enough to be meaningful
    if meth in GENERIC_METHOD_NAMES:
        return []
    candidates = index.methods_by_name.get(meth, [])
    candidates = [fi2 for _, fi2 in candidates
                  if fi2.class_name is not None]
    if 0 < len(candidates) <= AMBIGUOUS_CAP:
        return candidates
    return []


def check(index: PackageIndex) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    visitors: Dict[str, _AccessVisitor] = {}
    contexts: Dict[str, Tuple[ModuleInfo, Optional[ClassInfo],
                              FunctionInfo]] = {}
    for mod, ci, fi in _iter_functions(index):
        v = _AccessVisitor(CHECKER, mod, ci, fi)
        exempt = fi.name in EXEMPT_METHODS and ci is not None
        for stmt in fi.node.body:
            v.visit(stmt)
        visitors[fi.qualname] = v
        contexts[fi.qualname] = (mod, ci, fi)
        if fi.holds:
            continue  # body assumed under lock: discipline satisfied
        if exempt:
            continue
        for node, stmt, field, lock in v.violations:
            reason = mod.waiver_for(node, "lock")
            if reason is None and stmt is not None:
                # a standalone waiver on the line above the enclosing
                # STATEMENT covers accesses inside multi-line
                # conditions where a trailing comment cannot sit
                hit = mod.waivers_by_line.get(stmt.lineno - 1)
                if hit is not None and hit[0] == "lock":
                    reason = hit[1]
            findings.append(Finding(
                CHECKER, "error", mod.relpath, node.lineno,
                f"guarded field {field!r} accessed outside "
                f"`with {lock}` in {fi.qualname}",
                waived=reason is not None, reason=reason or ""))

    # holds() call-site verification: every resolvable call of a
    # holds-annotated method must be made while holding that lock
    holds_targets = {fi.qualname: (ci, fi)
                     for mod, ci, fi in _iter_functions(index)
                     if fi.holds and ci is not None}
    for qual, v in visitors.items():
        mod, ci, fi = contexts[qual]
        local = index.local_types(mod, fi)
        for node, held in v.calls:
            for target in _resolve_call(index, mod, ci, fi, node, local):
                if target.qualname not in holds_targets:
                    continue
                tci, tfi = holds_targets[target.qualname]
                need = f"{tci.key}.{tfi.holds}"
                if need in held:
                    continue
                reason = mod.waiver_for(node, "lock")
                findings.append(Finding(
                    CHECKER, "error", mod.relpath, node.lineno,
                    f"{fi.qualname} calls {tfi.qualname} (annotated "
                    f"`lock: holds({tfi.holds})`) without holding "
                    f"{tfi.holds}",
                    waived=reason is not None, reason=reason or ""))

    order_findings, extras = _order_graph(index, visitors, contexts)
    findings.extend(order_findings)
    return findings, extras


# -- lock-acquisition order -------------------------------------------------

def _order_graph(index, visitors, contexts):
    """Edges "held A while acquiring B" (direct + call-transitive),
    then cycle detection."""
    # transitive acquisition sets per function (fixpoint)
    acquires: Dict[str, Set[str]] = {q: set() for q in visitors}
    callees: Dict[str, Set[str]] = {q: set() for q in visitors}
    call_edges: Dict[str, List[Tuple[str, frozenset, int]]] = \
        {q: [] for q in visitors}
    for qual, v in visitors.items():
        mod, ci, fi = contexts[qual]
        local = index.local_types(mod, fi)
        for lock, held in v.acquisitions:
            acquires[qual].add(lock)
        for node, held in v.calls:
            for target in _resolve_call(index, mod, ci, fi, node,
                                        local):
                if target.qualname in visitors:
                    callees[qual].add(target.qualname)
                    call_edges[qual].append(
                        (target.qualname, held, node.lineno))
        if ci is not None:
            for attr, line, held in v.property_reads:
                target = ci.methods.get(attr)
                if target is not None \
                        and target.qualname in visitors:
                    callees[qual].add(target.qualname)
                    call_edges[qual].append(
                        (target.qualname, held, line))
        for recv, attr, line, held in v.attr_reads:
            key = index.receiver_class(mod, ci, fi, recv, local)
            if key is None:
                continue
            target_ci = index.classes.get(key)
            if target_ci is None:
                continue
            target = target_ci.methods.get(attr)
            if target is not None and not _is_property(target.node):
                continue  # plain method reference, runs nothing
            if target is None and not attr.startswith("_"):
                # instance fields are underscore-named by project
                # convention (and ServeConfig.__getattr__ rejects
                # underscore names), so only public misses route to a
                # dynamic getter
                target = target_ci.methods.get("__getattr__")
            if target is not None and target.qualname in visitors:
                callees[qual].add(target.qualname)
                call_edges[qual].append((target.qualname, held, line))
    changed = True
    while changed:
        changed = False
        for qual in visitors:
            for callee in callees[qual]:
                extra = acquires[callee] - acquires[qual]
                if extra:
                    acquires[qual] |= extra
                    changed = True

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for qual, v in visitors.items():
        mod, ci, fi = contexts[qual]
        for lock, held in v.acquisitions:
            for h in held:
                if h != lock:
                    edges.setdefault((h, lock), (mod.relpath,
                                                 fi.node.lineno))
        for callee, held, line in call_edges[qual]:
            for acquired in acquires[callee]:
                for h in held:
                    if h != acquired:
                        edges.setdefault((h, acquired),
                                         (mod.relpath, line))

    # cycle detection (DFS over the lock digraph)
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    findings: List[Finding] = []
    seen_cycles: Set[frozenset] = set()
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(node):
        color[node] = 1
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if color.get(nxt, 0) == 0:
                dfs(nxt)
            elif color.get(nxt) == 1:
                i = stack.index(nxt)
                cycle = stack[i:] + [nxt]
                key = frozenset(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    path, line = edges.get(
                        (cycle[0], cycle[1]), ("", 0))
                    findings.append(Finding(
                        ORDER_CHECKER, "error", path, line,
                        "lock acquisition-order cycle (deadlock "
                        "shape): " + " -> ".join(
                            _short(lk) for lk in cycle)))
        stack.pop()
        color[node] = 2

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)

    extras = {"lock_order_edges": sorted(
        f"{_short(a)} -> {_short(b)} (at {p}:{ln})"
        for (a, b), (p, ln) in edges.items())}
    return findings, extras


def _short(lock_id: str) -> str:
    """Human-readable lock id: ClassName._lock / module.py::_lock."""
    if "::" in lock_id:
        mod, rest = lock_id.split("::", 1)
        if "." in rest and rest.split(".")[0][:1].isupper():
            return rest
        return f"{mod.rsplit('/', 1)[-1]}::{rest}"
    return lock_id

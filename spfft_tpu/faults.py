"""Deterministic fault injection for the whole package.

Round 8 proved the pattern on the serving executor: the
failure-handling machinery (bucket-failure isolation, bounded retries,
device quarantine, the crash-proof dispatch supervisor) is only
trustworthy if every path is TESTABLE without real hardware faults.
This module is that seam, promoted from ``serve/faults.py`` to package
level so every subsystem built since — plan table builds, the artifact
store, the registry's singleflight build, fused Pallas launches, the
distributed exchange — shares one oracle. A :class:`FaultPlan` is
consulted at named check sites:

===================== ====================================================
site                  where it fires
===================== ====================================================
``stage``             host-side payload staging of a fused bucket
``dispatch``          the executable dispatch call (fused or serial;
                      carries the pool-device index when a pool is in use)
``materialise``       ``block_until_ready`` on a bucket's results
``loop``              top of each dispatch-loop iteration (crashing here
                      exercises the supervisor, not per-bucket handling)
``plan.build``        compression-table build (foreground join AND the
                      background builder thread — fires inside the thread,
                      surfacing through the sticky ``TableBuildError``)
``registry.build``    the singleflight owner's build in
                      ``PlanRegistry.get_or_build``
``store.load``        artifact read from disk
``store.spill``       top of a plan spill (serialize + write)
``store.replace``     the atomic ``os.replace`` publish step
``store.fsync``       the pre-publish ``fsync`` of a temp file
``store.aot``         AOT executable deserialize while loading
``kernel.launch``     a fused Pallas kernel launch (fires at trace time
                      under jit — once per compile, not per step)
``exchange.pack``     distributed pre-exchange pack (trace time)
``exchange.collective`` the all-to-all / collective itself (trace time)
``exchange.unpack``   distributed post-exchange unpack (trace time)
``exchange.chunk``    each chunk of an overlapped exchange (trace time)
``exchange.quantize`` the int8 wire rung's scale computation (the
                      plan-build probe; a firing check declines the
                      rung, falling back one rung, counted)
``cluster.route``     the pod frontend's host-pick for a single-device
                      request (before the lane RPC)
``cluster.rpc``       each host-lane RPC through the pod transport
                      (submit / signals / metrics / health)
``cluster.reconcile`` the per-host digest-validation collective during
                      pod reconciliation
``cluster.spmd_window`` each coalesced SPMD window round, before the
                      collective launch (all member futures fail typed)
``net.frame``         encode/decode of one wire frame (either socket
                      end of the pod's TCP transport)
``net.send``          the socket send of a framed request/response
``net.recv``          each socket read while receiving a frame (a
                      firing check is a dropped/truncated frame)
``net.accept``        the host agent's accept of an inbound connection
                      (a firing check drops the connection)
``blob.get``          a remote blob-tier read (artifact or alias)
``blob.put``          a remote blob-tier write
``net.heartbeat``     a membership lease-renewal heartbeat (sender's
                      wire call AND the coordinator's renewal handling)
``cluster.view``      serving/adopting a signed membership view (the
                      coordinator's snapshot and the frontend's fetch)
``cluster.readmit``   the re-reconcile step of a probed dead lane
                      before it is readmitted to routing
===================== ====================================================

A firing check raises :class:`InjectedFault` (or an
:class:`InjectedDiskFull` ``OSError`` for the ``enospc`` kind), which
flows through the SAME except-paths a real XLA/runtime/disk failure
would — nothing special-cases injected errors beyond their
transient/permanent tag. Faults fire two ways, both deterministic:

* **scripted** — ``"dispatch@3"`` fails the 3rd dispatch check,
  ``"store.spill@1:enospc"`` makes the first spill hit a full disk,
  ``"device1@*:permanent"`` fails every check on pool device 1. Site
  call counters are per-site (and per-device), so a script replays
  identically on an identical sequence of checks.
* **probabilistic** — ``rate`` per-check probability from a seeded RNG
  (``random.Random(seed)``), optionally restricted to one ``scope``
  site or ``"device:N"``. Same seed + same check sequence = same fault
  sequence, which is what lets ``serve.bench --fault-rate`` and
  ``--chaos`` measure degradation instead of just asserting it.

Script kinds beyond the round-8 trio:

* ``enospc`` — raises :class:`InjectedDiskFull`, an ``OSError`` with
  ``errno.ENOSPC``, so store code paths that branch on ``OSError`` /
  errno exercise their real handling (the memory-only degradation
  ladder).
* ``hang`` — sleeps ``hang_seconds`` before raising a transient fault,
  simulating a wedged device execute; pairs with the executor's
  ``execute_timeout_ms`` watchdog knob.

Subsystems outside the executor reach the seam through the ambient
hook: ``faults.arm(plan)`` installs a process-global plan that
:func:`check_site` consults (a no-op when nothing is armed, so the hot
path costs one global read). The executor keeps its per-instance
``inject_faults`` API.

Transient-vs-permanent classification (:func:`is_transient`) drives
retry policy: injected faults carry an explicit ``transient`` flag;
real exceptions classify by an explicit ``transient`` attribute when
present, then by type (``TimeoutError``), then by the gRPC-style
status markers XLA runtime errors embed (``RESOURCE_EXHAUSTED``,
``UNAVAILABLE``, ...). Everything else is permanent — retrying a shape
error or a poisoned payload would just burn device time twice.
tests/data/runtime_error_corpus.json pins both classifiers against
real XLA/PJRT/Mosaic error text.
"""

from __future__ import annotations

import errno
import random
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from .errors import (DuplicateIndicesError, InvalidIndicesError,
                     InvalidParameterError, ServeError)

#: The package's named fault-check sites. Dotted names group by
#: subsystem; the analyzer's fault-site checker enforces that every
#: ``check``/``check_site`` call uses a name declared here exactly
#: once, and that every declared site is checked somewhere.
SITES = (
    # serving executor (round 8)
    "stage", "dispatch", "materialise", "loop",
    # plan lifecycle
    "plan.build",
    # registry
    "registry.build",
    # artifact store
    "store.load", "store.spill", "store.replace", "store.fsync",
    "store.aot",
    # fused Pallas kernels
    "kernel.launch",
    # distributed exchange
    "exchange.pack", "exchange.collective", "exchange.unpack",
    "exchange.chunk", "exchange.quantize",
    # pod cluster (round 18; spmd_window joined with the coalescer)
    "cluster.route", "cluster.rpc", "cluster.reconcile",
    "cluster.spmd_window",
    # wire transport + remote artifact tier (net/)
    "net.frame", "net.send", "net.recv", "net.accept",
    "blob.get", "blob.put",
    # lease-based membership + lane resurrection (round 21)
    "net.heartbeat", "cluster.view", "cluster.readmit",
    # flight recorder: a failing incident-bundle write is typed and
    # non-fatal (recording must never take down serving)
    "obs.capture",
)

#: Substrings of runtime error text treated as transient — the
#: retryable subset of the gRPC status codes XLA/PJRT embed in
#: RuntimeError messages (device OOM under fragmentation, a briefly
#: unreachable device, a preempted collective).
TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE",
                     "DEADLINE_EXCEEDED", "ABORTED")

#: Script kinds a :class:`FaultPlan` entry may carry.
KINDS = ("transient", "permanent", "poison", "enospc", "hang")


class InjectedFault(ServeError):
    """A failure raised by a :class:`FaultPlan` check. Carries the
    ``transient`` classification retry policies read and the
    ``device_attributed`` classification quarantine accounting reads
    (True by default — injection simulates infrastructure faults; the
    ``poison`` script kind injects request-attributed ones); otherwise
    handled exactly like any runtime failure."""

    def __init__(self, message: str, transient: bool = True,
                 device_attributed: bool = True):
        super().__init__(message)
        self.transient = transient
        self.device_attributed = device_attributed


class InjectedDiskFull(InjectedFault, OSError):
    """The ``enospc`` script kind: an injected disk-full failure. It IS
    an ``OSError`` with ``errno.ENOSPC`` so store code that branches on
    ``OSError``/errno (atomic writes, the memory-only degradation
    ladder) exercises its real handling, and it IS an
    :class:`InjectedFault` so harnesses can tell injected storms from
    genuine disk trouble. Permanent and not device-attributed — a full
    volume is neither retryable in place nor the accelerator's fault."""

    def __init__(self, message: str):
        InjectedFault.__init__(self, message, transient=False,
                               device_attributed=False)
        self.errno = errno.ENOSPC
        self.strerror = "No space left on device"


#: ``OSError`` errnos that mark a PERSISTENT disk problem — retrying
#: the same write cannot help; the store's degradation ladder flips to
#: memory-only instead. Everything else OSError-shaped (EINTR, EAGAIN,
#: a transient NFS hiccup) gets the bounded-retry rung first.
PERSISTENT_DISK_ERRNOS = (errno.ENOSPC, errno.EROFS, errno.EDQUOT,
                          errno.EIO)


def is_persistent_disk_error(exc: BaseException) -> bool:
    """Whether ``exc`` is an ``OSError`` whose errno marks the disk
    itself as unusable (:data:`PERSISTENT_DISK_ERRNOS`) — the trigger
    for the store's memory-only degradation, as opposed to a transient
    I/O error worth a bounded retry."""
    return (isinstance(exc, OSError)
            and getattr(exc, "errno", None) in PERSISTENT_DISK_ERRNOS)


def is_transient(exc: BaseException) -> bool:
    """Whether ``exc`` warrants the one bounded retry. An explicit
    ``transient`` attribute wins (injected faults, or any runtime that
    tags its errors); ``TimeoutError`` and XLA runtime errors carrying a
    retryable gRPC status marker are transient; everything else —
    shape/type errors, poisoned payloads, logic bugs — is permanent."""
    tagged = getattr(exc, "transient", None)
    if tagged is not None:
        return bool(tagged)
    if isinstance(exc, TimeoutError):
        return True
    text = str(exc)
    return any(marker in text for marker in TRANSIENT_MARKERS)


#: Exception types that indict the REQUEST, not the device it ran on:
#: shape/type/index errors (a poisoned payload fails identically on
#: every healthy device) and the library's own validation errors.
REQUEST_ERROR_TYPES = (TypeError, ValueError, IndexError, KeyError,
                       InvalidParameterError, InvalidIndicesError,
                       DuplicateIndicesError)


def attributes_device(exc: BaseException) -> bool:
    """Whether a failure should count against the DEVICE it ran on
    (quarantine accounting) rather than the request that triggered it.
    An explicit ``device_attributed`` attribute wins (injected faults,
    or a runtime that tags its errors); request-shaped errors
    (:data:`REQUEST_ERROR_TYPES` — a poisoned payload raises the same
    error on every healthy device) indict the request; everything else
    — XLA runtime errors, timeouts, unknown failures — charges the
    device, which preserves the round-8 quarantine behaviour for real
    hardware faults. This is the classifier that stops a pure
    poisoned-request flood from spuriously quarantining a healthy
    device (ROADMAP round-11 follow-on)."""
    tagged = getattr(exc, "device_attributed", None)
    if tagged is not None:
        return bool(tagged)
    if isinstance(exc, REQUEST_ERROR_TYPES):
        return False
    return True


_ENTRY_RE = re.compile(
    r"^(?P<site>[a-z][a-z0-9_.]*|device\d+)"
    r"@(?P<nth>\d+|\*)(?::(?P<kind>\w+))?$")


def _parse_entry(spec: str) -> Tuple[str, Optional[int], str]:
    """One script entry ``SITE@N[:KIND]`` -> (counter key, nth-or-None
    for always, kind). SITE is a check site or ``deviceK``; ``N`` is
    the 1-based call index of that counter, ``*`` fires on every call;
    KIND is ``transient`` (default), ``permanent`` (both
    device-attributed), ``poison`` (permanent AND request-attributed —
    simulates a bad payload, exercising the quarantine-attribution
    seam), ``enospc`` (an ``OSError`` disk-full, exercising the store's
    degradation ladder) or ``hang`` (sleeps ``hang_seconds`` before a
    transient fault, exercising the execute watchdog)."""
    m = _ENTRY_RE.match(spec.strip())
    if not m:
        raise InvalidParameterError(
            f"bad fault-script entry {spec!r} (want SITE@N[:KIND], e.g. "
            f"'dispatch@3', 'store.spill@1:enospc', "
            f"'device1@*:permanent')")
    site = m.group("site")
    if site not in SITES and not site.startswith("device"):
        raise InvalidParameterError(
            f"unknown fault site {site!r} (sites: {SITES} or deviceK)")
    nth = None if m.group("nth") == "*" else int(m.group("nth"))
    if nth is not None and nth < 1:
        raise InvalidParameterError("fault-script call index is 1-based")
    kind = m.group("kind") or "transient"
    if kind not in KINDS:
        raise InvalidParameterError(
            f"fault kind must be one of {'|'.join(KINDS)}, got {kind!r}")
    return site, nth, kind


def _record(metric: str, **labels) -> None:
    """Best-effort counter recording; import is lazy because obs is a
    heavier import than this leaf module and faults must stay
    importable everywhere (including from obs-free unit tests)."""
    try:
        from .obs import GLOBAL_COUNTERS
    except Exception:  # pragma: no cover - circular/partial import
        return
    GLOBAL_COUNTERS.inc(metric, **labels)


def _journal(site: str, fire: str) -> None:
    """Best-effort flight-recorder journal entry for a fired fault
    (same lazy-import discipline as :func:`_record`)."""
    try:
        from .obs import record_event
    except Exception:  # pragma: no cover - circular/partial import
        return
    record_event("fault.fired", site=site, kind=fire)


class FaultPlan:
    """Deterministic fault-injection oracle, shared package-wide.

    ``script`` is an iterable of ``SITE@N[:KIND]`` entries (or one
    comma-separated string); ``rate`` adds seeded per-check transient
    faults, optionally restricted to ``scope`` (a site name or
    ``"device:N"``); ``hang_seconds`` is how long a ``hang`` entry
    wedges its caller before failing. Thread-safe: checks run on
    dispatcher/builder/spill threads, stats reads come from anywhere.
    """

    def __init__(self, rate: float = 0.0, seed: int = 0,
                 scope: Optional[str] = None, script=None,
                 hang_seconds: float = 30.0):
        if not 0.0 <= rate <= 1.0:
            raise InvalidParameterError("fault rate must be in [0, 1]")
        if scope is not None:
            key = scope.replace("device:", "device")
            if key not in SITES and not (key.startswith("device")
                                         and key[6:].isdigit()):
                raise InvalidParameterError(
                    f"bad fault scope {scope!r} (sites: {SITES} or "
                    f"'device:N')")
            scope = key
        if isinstance(script, str):
            script = [s for s in script.split(",") if s.strip()]
        if hang_seconds < 0:
            raise InvalidParameterError("hang_seconds must be >= 0")
        self._rate = float(rate)
        self._rng = random.Random(seed)  #: guarded by _lock
        self._scope = scope
        self._script: List[Tuple[str, Optional[int], str]] = \
            [_parse_entry(s) for s in (script or [])]
        self._hang_seconds = float(hang_seconds)
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}  #: guarded by _lock
        #: guarded by _lock
        self._fired: Dict[str, int] = {kind: 0 for kind in KINDS}
        self._fired_by_site: Dict[str, int] = {}  #: guarded by _lock

    def _in_scope(self, site: str, dev_key: Optional[str]) -> bool:
        if self._scope is None:
            return site != "loop"  # rate faults never crash the loop
        return self._scope == site or self._scope == dev_key

    def check(self, site: str, device: Optional[int] = None) -> None:
        """One pipeline checkpoint: increments the ``site`` counter (and
        the ``deviceN`` counter when a pool device index is given) and
        raises :class:`InjectedFault` (or :class:`InjectedDiskFull`)
        when a script entry or the seeded rate says this call fails.
        No-op otherwise."""
        with self._lock:
            n = self._calls[site] = self._calls.get(site, 0) + 1
            dev_key = dn = None
            if device is not None:
                dev_key = f"device{device}"
                dn = self._calls[dev_key] = self._calls.get(dev_key,
                                                           0) + 1
            fire = None
            for key, nth, kind in self._script:
                hit = (key == site and (nth is None or nth == n)) or \
                      (key == dev_key and (nth is None or nth == dn))
                if hit:
                    fire = kind
                    break
            if fire is None and self._rate > 0.0 \
                    and self._in_scope(site, dev_key):
                if self._rng.random() < self._rate:
                    fire = "transient"
            if fire is None:
                return
            self._fired[fire] += 1
            self._fired_by_site[site] = \
                self._fired_by_site.get(site, 0) + 1
            hang = self._hang_seconds if fire == "hang" else 0.0
        _record("spfft_faults_injected_total", site=site, kind=fire)
        _journal(site, fire)
        where = site if device is None else f"{site} (device {device})"
        if fire == "enospc":
            raise InjectedDiskFull(f"injected disk-full at {where}")
        if hang:
            time.sleep(hang)  # outside the lock: only the caller wedges
        raise InjectedFault(f"injected {fire} fault at {where}",
                            transient=fire in ("transient", "hang"),
                            device_attributed=fire != "poison")

    def stats(self) -> Dict:
        """Counter snapshot: checks seen and faults fired, per site."""
        with self._lock:
            return {
                "rate": self._rate,
                "scope": self._scope,
                "script_entries": len(self._script),
                "checks": dict(self._calls),
                "fired_transient": self._fired["transient"],
                "fired_permanent": self._fired["permanent"],
                "fired_poison": self._fired["poison"],
                "fired_enospc": self._fired["enospc"],
                "fired_hang": self._fired["hang"],
                "fired_by_site": dict(self._fired_by_site),
            }


#: The process-global ambient plan :func:`check_site` consults. Plain
#: attribute read on the hot path; writes go through :func:`arm` /
#: :func:`disarm` (tests and the chaos harness are the only writers).
_AMBIENT: Optional[FaultPlan] = None
_AMBIENT_LOCK = threading.Lock()


def arm(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` as the process-global ambient fault plan that
    :func:`check_site` consults (``None`` disarms). Subsystems without
    an injection API of their own — plan builds, the store, the
    registry, fused kernels, the exchange — fire through this hook."""
    global _AMBIENT
    with _AMBIENT_LOCK:
        _AMBIENT = plan
    try:
        from .obs import GLOBAL_COUNTERS
    except Exception:  # pragma: no cover - circular/partial import
        return
    GLOBAL_COUNTERS.set("spfft_faults_armed",
                        0.0 if plan is None else 1.0)


def disarm() -> None:
    """Remove the ambient fault plan (idempotent)."""
    arm(None)


def armed() -> Optional[FaultPlan]:
    """The currently armed ambient plan, if any."""
    return _AMBIENT


def check_site(site: str, device: Optional[int] = None) -> None:
    """Package-wide fault checkpoint: consult the ambient
    :class:`FaultPlan` if one is armed, else no-op. This is the ONE
    line a subsystem adds per seam; cost when disarmed is a global
    read and an ``is not None``."""
    plan = _AMBIENT
    if plan is not None:
        plan.check(site, device)

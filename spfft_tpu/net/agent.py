"""The server side of the pod's wire: :class:`HostAgent`.

``python -m spfft_tpu.net.agent --host h0`` turns one process into one
pod host: a local ``ServeExecutor`` (own registry, own artifact store,
optionally the fleet's remote blob tier) fronted by a framed-TCP
accept loop speaking the :mod:`~spfft_tpu.net.frame` protocol. The
dispatch table is the ``HostLane`` seam verbatim — submit / signals /
signatures / plan / metrics / health — plus the membership and
introspection verbs the elastic pod needs (prewarm, drain, shutdown,
stats, spans).

Three contracts the agent keeps:

* **One trace id end-to-end** — a submit frame carries the frontend's
  ``TraceContext``; the agent restores it, so the local
  ``serve.request`` (or ``cluster.spmd_execute``) span is a child of
  the remote ``cluster.request`` root across the process boundary.
* **Typed errors only** — a handler that raises answers with an
  ``error`` record; :func:`~spfft_tpu.net.frame.error_from_wire` maps
  it back onto the taxonomy client-side (a remote ``QueueFullError``
  stays backpressure, never lane death).
* **Plans never cross the wire** — ``plan`` answers a descriptor
  (held / distributed / fingerprint); execution happens here, next to
  the devices that compiled the plan.

``net.accept`` is the agent's fault site: a firing check drops the
inbound connection on the floor — the client sees exactly a crashed
host.

The agent is also one membership node (:mod:`~spfft_tpu.net.membership`):
it holds a lease it renews over the ``heartbeat`` verb, serves the
signed pod view over ``view``, promotes itself to view coordinator
when it is the lowest alive host id, and fences stale-epoch submits
with the typed transient ``StaleEpochError`` (counted
``spfft_net_agent_rejected_total{reason="stale_epoch"}``). Frames
that fail wire authentication reject permanent ``NetAuthError`` at
the door, counted ``{reason="auth"}``.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Dict, Optional, Tuple

from .. import faults as _faults
from .. import obs as _obs
from ..control.config import global_config
from ..errors import (DeadlineExpiredError, InvalidParameterError,
                      NetAuthError, NetProtocolError, QueueFullError,
                      StaleEpochError)
from ..faults import InjectedFault
from ..obs.exporters import prometheus_text
from ..parallel.multihost import plan_fingerprint
from ..plan import TransformPlan
from ..serve.executor import ServeExecutor
from ..types import Scaling
from .frame import (error_to_wire, pack_values, recv_frame, send_frame,
                    signature_from_wire, signature_to_wire,
                    unpack_values)
from .membership import HeartbeatLoop, MembershipNode


def _jsonify(obj):
    """Make a telemetry snapshot JSON-clean: stringify non-str dict
    keys (the fused-batch histogram is int-keyed) and coerce numpy
    scalars through their Python item()."""
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if hasattr(obj, "item") and not isinstance(obj, (str, bytes)):
        try:
            return obj.item()
        except Exception:
            return str(obj)
    return obj


class HostAgent:
    """One pod host: a TCP accept loop dispatching framed requests
    onto a local :class:`ServeExecutor`. ``port=0`` binds an ephemeral
    port (read it back from :attr:`port` — how the smoke wires a pod
    of subprocesses together)."""

    def __init__(self, host: str, executor: ServeExecutor,
                 bind: str = "127.0.0.1", port: int = 0,
                 peers: Optional[Dict[str, str]] = None,
                 advertise: Optional[str] = None):
        self.host = host
        self.executor = executor
        self.closing = threading.Event()
        self._lock = threading.Lock()
        self._inflight = 0  #: guarded by _lock
        self._conns: set = set()  #: guarded by _lock
        # this host's half of the pod SPMD lane: the same coalescing
        # scheduler the in-process frontend runs, so same-signature
        # distributed requests arriving over the wire share collective
        # rounds too (serve.cluster has no net imports — no cycle)
        from ..serve.cluster import SPMDCoalescer
        self._spmd = SPMDCoalescer(span_args={"host": host})
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind, port))
        self._sock.listen(64)
        # short accept timeout: the loop notices `closing` promptly
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        # this host's membership half: lease + heartbeat + (when this
        # is the lowest alive host id) the view-coordinator role
        self.membership = MembershipNode(
            host, address=advertise or f"{bind}:{self.port}",
            peers=peers)
        self._heartbeats = HeartbeatLoop(self.membership)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HostAgent":
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"spfft-agent-{self.host}")
        self._thread.start()
        self._heartbeats.start()
        return self

    def close(self) -> None:
        self.closing.set()
        self._heartbeats.stop()
        try:
            self._sock.close()
        except OSError:
            pass
        # sever live keep-alive connections too: a closed host must
        # look DOWN to pooled clients (EOF on their idle sockets), not
        # keep answering frames from still-parked handler threads
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._spmd.close()

    # -- the accept loop ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self.closing.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                if self.closing.is_set():
                    return
                continue
            try:
                _faults.check_site("net.accept")
            except InjectedFault:
                # a dropped inbound connection: the client observes a
                # crashed host (EOF), which is the point of the site
                conn.close()
                continue
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True,
                name=f"spfft-agent-{self.host}-conn").start()

    def _handle_conn(self, conn) -> None:
        cfg = global_config()
        conn.settimeout(cfg.net_rpc_timeout_ms / 1000.0)
        with self._lock:
            self._conns.add(conn)
        try:
            while not self.closing.is_set():
                try:
                    frame = recv_frame(conn, eof_ok=True)
                except NetAuthError as exc:
                    # the authentication door: a frame that does not
                    # verify rejects typed + permanent, counted, and
                    # the stream is dropped (never dispatched)
                    _obs.GLOBAL_COUNTERS.inc(
                        "spfft_net_agent_rejected_total", reason="auth")
                    try:
                        send_frame(conn, error_to_wire(exc))
                    except (OSError, NetProtocolError, NetAuthError,
                            InjectedFault):
                        pass
                    return
                except (NetProtocolError, InjectedFault) as exc:
                    # best effort: tell the client what went wrong,
                    # then give up on this (possibly desynced) stream
                    try:
                        send_frame(conn, error_to_wire(exc))
                    except (OSError, NetProtocolError, InjectedFault):
                        pass
                    return
                except OSError:
                    return
                if frame is None:
                    return
                header, payload = frame
                op = str(header.get("type", "?"))
                _obs.GLOBAL_COUNTERS.inc(
                    "spfft_net_agent_requests_total", op=op)
                try:
                    reply, rpayload = self._dispatch(op, header, payload)
                except Exception as exc:
                    reply, rpayload = error_to_wire(exc), b""
                try:
                    send_frame(conn, reply, rpayload)
                except (OSError, NetProtocolError, InjectedFault):
                    return
        finally:
            with self._lock:
                self._conns.discard(conn)
            conn.close()

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, op: str, header: dict,
                  payload: bytes) -> Tuple[dict, bytes]:
        if op == "submit":
            ctx = _obs.TraceContext.from_wire(header.get("ctx"))
            return self._handle_submit(header, payload, ctx)
        if op == "signals":
            return ({"type": "signals_ok",
                     "signals": _jsonify(
                         self.executor.metrics.signals())}, b"")
        if op == "signatures":
            return ({"type": "signatures_ok",
                     "signatures": [
                         signature_to_wire(s) for s in
                         self.executor.registry.signatures()]}, b"")
        if op == "plan":
            sig = signature_from_wire(header.get("signature") or {})
            plan = self.executor.registry.get(sig)
            if plan is None:
                return {"type": "plan_ok", "held": False}, b""
            distributed = not isinstance(plan, TransformPlan)
            return ({"type": "plan_ok", "held": True,
                     "distributed": distributed,
                     "fingerprint":
                         plan_fingerprint(plan.dist_plan).hex()
                         if distributed else None}, b"")
        if op == "metrics":
            return ({"type": "metrics_ok",
                     "text": prometheus_text(
                         metrics=self.executor.metrics,
                         registry=self.executor.registry)}, b"")
        if op == "health":
            return ({"type": "health_ok",
                     "health": _jsonify(self.executor.health())}, b"")
        if op == "prewarm":
            sigs = [signature_from_wire(d)
                    for d in header.get("signatures", [])]
            warmed = self.executor.registry.prewarm_signatures(
                sigs, strict=bool(header.get("strict", True)))
            return ({"type": "prewarm_ok", "warmed": warmed}, b"")
        if op == "stats":
            return ({"type": "stats_ok",
                     "registry": _jsonify(
                         self.executor.registry.stats())}, b"")
        if op == "spans":
            return self._handle_spans()
        if op == "incident":
            from ..obs.recorder import build_incident_bundle
            return ({"type": "incident_ok",
                     "bundle": _jsonify(build_incident_bundle(
                         str(header.get("reason", "remote")),
                         host=self.host))}, b"")
        if op == "drain":
            self.executor.close(drain=True)
            return {"type": "drain_ok"}, b""
        if op == "shutdown":
            self.closing.set()
            return {"type": "shutdown_ok"}, b""
        if op == "ping":
            return {"type": "pong", "host": self.host}, b""
        if op == "heartbeat":
            ack = self.membership.on_heartbeat(
                str(header.get("host", "?")), header.get("address"))
            return ({"type": "heartbeat_ok", **ack}, b"")
        if op == "view":
            return ({"type": "view_ok",
                     "view": self.membership.on_view()}, b"")
        raise InvalidParameterError(f"unknown wire op {op!r}")

    def _admit(self, timeout) -> None:
        """The agent's own admission seam (mirroring the SPMD lane's):
        a submit whose deadline is already spent rejects typed without
        touching a device, and the count of submits in flight across
        ALL connections is bounded by the ``max_queue`` knob — a
        storming client cannot queue this host to death behind its
        accept loop. Raising here answers the frame with the same
        typed error record any handler failure does."""
        if timeout is not None and float(timeout) <= 0:
            _obs.GLOBAL_COUNTERS.inc("spfft_net_agent_rejected_total",
                                     reason="expired")
            raise DeadlineExpiredError(
                f"request deadline already expired at host "
                f"{self.host!r} admission")
        cap = int(global_config().max_queue)
        with self._lock:
            if self._inflight >= cap:
                _obs.GLOBAL_COUNTERS.inc(
                    "spfft_net_agent_rejected_total",
                    reason="queue_full")
                raise QueueFullError(
                    f"host {self.host!r} agent is at capacity ({cap} "
                    f"submits in flight)")
            self._inflight += 1

    # trace: boundary(ctx)
    def _handle_submit(self, header: dict, payload: bytes,
                       ctx) -> Tuple[dict, bytes]:
        """Execute one submit frame to completion (the reply IS the
        result — the asynchrony lives client-side in the lane's thread
        pool), restoring the propagated trace context so this host's
        spans join the frontend's trace."""
        try:
            self.membership.check_epoch(header.get("epoch"))
        except StaleEpochError:
            _obs.GLOBAL_COUNTERS.inc("spfft_net_agent_rejected_total",
                                     reason="stale_epoch")
            raise
        sig = signature_from_wire(header.get("signature") or {})
        values = unpack_values(header, payload)
        kind = str(header.get("kind", "backward"))
        scaling = Scaling(header.get("scaling", Scaling.NONE.value))
        timeout = header.get("timeout")
        priority = str(header.get("priority", "normal"))
        plan = self.executor.registry.get(sig)
        if plan is None:
            raise InvalidParameterError(
                f"signature not held by host {self.host!r} "
                f"(warm up first)")
        self._admit(timeout)
        try:
            if isinstance(plan, TransformPlan):
                fut = self.executor.submit(
                    sig, values, kind, scaling=scaling, timeout=timeout,
                    priority=priority, trace_ctx=ctx)
            else:
                # the coalescer batches same-signature arrivals from
                # every connection into one collective round
                fut = self._spmd.submit(sig, plan, values, kind,
                                        scaling, ctx, timeout=timeout,
                                        priority=priority)
            result = fut.result()
        finally:
            with self._lock:
                self._inflight -= 1
        meta, rpayload = pack_values(result)
        return {"type": "result", **meta}, rpayload

    def _handle_spans(self) -> Tuple[dict, bytes]:
        tracer = _obs.GLOBAL_TRACER
        spans = [{"name": s.name, "trace_id": s.trace_id,
                  "span_id": s.span_id, "parent_id": s.parent_id,
                  "member_trace_ids":
                      (s.args or {}).get("member_trace_ids")}
                 for s in tracer.events() if isinstance(s, _obs.Span)]
        return ({"type": "spans_ok", "spans": spans,
                 "open": tracer.open_count()}, b"")


# ---------------------------------------------------------------------------
# CLI: one process = one pod host
# ---------------------------------------------------------------------------

def _demo_warm(registry, spec: str) -> None:
    """Warm the demo plan set the smokes serve: ``N,CUTOFF,SHARDS`` +
    an optional mode — ``full`` (default) builds the single-device C2C
    plan AND the matching distributed plan; ``dist`` builds ONLY the
    distributed plan (the joining-host case: singles come warm from
    the artifact tiers, and the distributed plan — which is never
    serialized — is derived deterministically from the same triplet
    set, so its fingerprint reconciles against the incumbents)."""
    from ..benchmark import cutoff_stick_triplets
    from ..parallel import make_distributed_plan, make_mesh
    from ..types import TransformType
    from ..utils.workloads import (even_plane_split,
                                   round_robin_stick_partition)
    from ..serve.registry import signature_for

    parts = spec.split(",")
    if len(parts) not in (3, 4):
        raise InvalidParameterError(
            f"--demo-warm wants N,CUTOFF,SHARDS[,MODE], got {spec!r}")
    n, cutoff, shards = int(parts[0]), float(parts[1]), int(parts[2])
    mode = parts[3] if len(parts) == 4 else "full"
    if mode not in ("full", "dist"):
        raise InvalidParameterError(
            f"--demo-warm mode must be full|dist, got {mode!r}")
    dims = (n, n, n)
    trip = cutoff_stick_triplets(n, n, n, cutoff, hermitian=False)
    if mode == "full":
        registry.get_or_build(TransformType.C2C, *dims, trip,
                              precision="double")
    if shards > 1:
        sparts = round_robin_stick_partition(trip, dims, shards)
        planes = even_plane_split(dims[2], shards)
        dplan = make_distributed_plan(TransformType.C2C, *dims, sparts,
                                      planes, mesh=make_mesh(shards),
                                      precision="double")
        dsig = signature_for(TransformType.C2C, *dims, trip,
                             precision="double", device_count=shards)
        registry.put(dsig, dplan)
    if registry.store is not None:
        # flush async spills (incl. remote blob puts) before the port
        # announcement: a joiner that boots next must find them
        registry.store.drain()


def main(argv=None) -> int:
    import argparse

    from ..serve.registry import PlanRegistry

    ap = argparse.ArgumentParser(
        prog="python -m spfft_tpu.net.agent",
        description="Run one pod host: a ServeExecutor behind a "
                    "framed-TCP HostAgent.")
    ap.add_argument("--host", required=True,
                    help="this lane's host name in the pod")
    ap.add_argument("--bind", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (announced on stdout)")
    ap.add_argument("--store", default="",
                    help="plan-artifact store root (disk tier)")
    ap.add_argument("--blob", default="",
                    help="remote blob tier: http:// URL or shared "
                         "directory")
    ap.add_argument("--manifest", default="",
                    help="warmup manifest to boot from")
    ap.add_argument("--demo-warm", default="",
                    help="N,CUTOFF,SHARDS[,MODE] demo plan set "
                         "(MODE=full|dist)")
    ap.add_argument("--trace", action="store_true",
                    help="enable tracing at sample rate 1.0")
    ap.add_argument("--peers", default="",
                    help="pod roster for lease-based membership: "
                         "name=host:port,... (empty = standalone)")
    ap.add_argument("--advertise", default="",
                    help="address peers should heartbeat this agent "
                         "at (default: bind:port)")
    args = ap.parse_args(argv)

    if args.blob:
        global_config().set_path("blob_store_url", args.blob)
    if args.trace:
        _obs.enable()
        _obs.GLOBAL_TRACER.set_sample_rate(1.0)

    registry = PlanRegistry(store=(args.store or False))
    if args.manifest:
        registry.warmup_manifest(args.manifest, compile=True)
    if args.demo_warm:
        _demo_warm(registry, args.demo_warm)
    peers = {}
    for entry in filter(None, args.peers.split(",")):
        name, _, addr = entry.partition("=")
        if not name or ":" not in addr:
            ap.error(f"--peers entry {entry!r} is not name=host:port")
        peers[name.strip()] = addr.strip()
    executor = ServeExecutor(registry)
    agent = HostAgent(args.host, executor, bind=args.bind,
                      port=args.port, peers=peers or None,
                      advertise=(args.advertise or None)).start()
    print(json.dumps({"agent": args.host, "port": agent.port}),
          flush=True)
    try:
        agent.closing.wait()
    except KeyboardInterrupt:
        pass
    finally:
        agent.close()
        try:
            executor.close(drain=False)
        except Exception:
            pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

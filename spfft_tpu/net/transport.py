"""The client side of the pod's wire: :class:`TcpTransport` +
:class:`TcpHostLane`.

``serve.cluster.HostLane`` is the five-RPC host boundary against an
in-process executor; :class:`TcpHostLane` is the same surface with the
executor on the far side of a socket — the frontend cannot tell them
apart (``PodFrontend`` routes, reconciles, federates and fails over
identically), which is the whole point of the seam.

RPCs ride POOLED keep-alive connections: a completed round trip
returns its socket to a :class:`_SocketPool` and the next RPC reuses
it (the agent's connection loop already serves many frames per
connection), with an idle-timeout reaper closing sockets the traffic
no longer needs — ``pool=False`` restores the round-19
one-connect-per-RPC wire the ``pod_wire`` bench row measures.
Connection/read failures, protocol violations and injected
``cluster.rpc``/``net.*`` faults all translate into the typed,
transient ``HostLaneError`` the frontend's route-around handling keys
on (a stale pooled socket is NOT a failure: checkout probes liveness
and a send that trips over a just-closed keep-alive falls back to a
fresh connect, so a dead host still surfaces synchronously at
``start_call`` where the frontend fails over); a typed ``error``
record in the response re-raises as its original taxonomy class (a
remote ``QueueFullError`` stays backpressure, not lane death).

The transport measures each successful round trip into an EWMA
(:attr:`TcpTransport.rtt`, exported as
``spfft_net_rpc_rtt_seconds{host}``) and :meth:`TcpHostLane.rpc_signals`
merges it into the host's signal snapshot as ``wire_rtt`` — the third
term of ``serve.cluster.load_score``, so a far-away host really does
score busier than a near one at equal queue depth.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import List, Optional, Tuple

from .. import obs as _obs
from ..control.config import global_config
from ..errors import HostLaneError, NetProtocolError
from ..faults import InjectedFault
from ..serve.cluster import HostLane, LoopbackTransport
from ..serve.registry import PlanSignature
from ..types import Scaling
from .frame import (error_from_wire, pack_values, recv_frame,
                    send_frame, signature_from_wire, signature_to_wire,
                    unpack_values)

#: EWMA weight of the newest round-trip sample.
_RTT_ALPHA = 0.2


def _ctx_to_wire(ctx) -> Optional[dict]:
    """Trace context → frame-header form (None stays None)."""
    return None if ctx is None else ctx.to_wire()


class _SocketPool:
    """Idle keep-alive sockets for one transport's (host, address).

    ``checkout`` hands back a pooled socket after a liveness probe
    (non-blocking ``MSG_PEEK``: a server-closed keep-alive reads EOF
    and is discarded; unexpected buffered bytes mean a desynced stream
    and are discarded too) or ``None`` on a miss; ``checkin`` returns
    a socket whose RPC completed cleanly. A lazy daemon reaper closes
    sockets idle past ``idle_timeout`` seconds, so a traffic lull does
    not pin file descriptors on either side of the wire. The client
    idle timeout sits well under the agent's per-connection read
    timeout (``net_rpc_timeout_ms``, 30 s default), so the client
    side, not the server, retires idle connections."""

    def __init__(self, idle_timeout: float = 5.0, max_idle: int = 8):
        self.idle_timeout = float(idle_timeout)
        self.max_idle = int(max_idle)
        self._lock = threading.Lock()
        self._idle: List[Tuple[socket.socket, float]] = []  #: guarded by _lock
        self._closed = False  #: guarded by _lock
        self._reaper: Optional[threading.Thread] = None  #: guarded by _lock
        self.hits = 0  #: guarded by _lock
        self.misses = 0  #: guarded by _lock
        self.reaped = 0  #: guarded by _lock

    @staticmethod
    def _alive(sock) -> bool:
        try:
            sock.setblocking(False)
            try:
                chunk = sock.recv(1, socket.MSG_PEEK)
            finally:
                sock.setblocking(True)
        except (BlockingIOError, InterruptedError):
            return True  # nothing buffered: healthy idle keep-alive
        except OSError:
            return False
        # EOF (b"") = server closed; actual bytes = desynced stream —
        # either way the socket is not reusable
        del chunk
        return False

    def checkout(self):
        with self._lock:
            while self._idle:
                sock, _ = self._idle.pop()
                if self._alive(sock):
                    self.hits += 1
                    return sock
                try:
                    sock.close()
                except OSError:
                    pass
            self.misses += 1
            return None

    def checkin(self, sock) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self.max_idle:
                self._idle.append((sock, time.monotonic()))
                self._ensure_reaper_locked()
                return
        try:
            sock.close()
        except OSError:
            pass

    # lock: holds(_lock)
    def _ensure_reaper_locked(self) -> None:
        if self._reaper is None or not self._reaper.is_alive():
            self._reaper = threading.Thread(
                target=self._reap_loop, daemon=True,
                name="spfft-net-pool-reaper")
            self._reaper.start()

    def _reap_loop(self) -> None:
        while True:
            time.sleep(max(self.idle_timeout / 4.0, 0.05))
            now = time.monotonic()
            stale: List[socket.socket] = []
            with self._lock:
                keep = []
                for sock, stamp in self._idle:
                    if now - stamp > self.idle_timeout:
                        stale.append(sock)
                    else:
                        keep.append((sock, stamp))
                self._idle = keep
                self.reaped += len(stale)
                done = self._closed or not self._idle
                if done:
                    self._reaper = None
            for sock in stale:
                try:
                    sock.close()
                except OSError:
                    pass
            if done:
                return

    def stats(self) -> dict:
        with self._lock:
            return {"idle": len(self._idle), "hits": self.hits,
                    "misses": self.misses, "reaped": self.reaped}

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
            self._closed = True
        for sock, _ in idle:
            try:
                sock.close()
            except OSError:
                pass


class TcpTransport(LoopbackTransport):
    """The wire twin of ``LoopbackTransport``: same ``check`` seam
    (liveness + the ``cluster.rpc`` fault site), plus :meth:`call` —
    one framed request/response round trip with its latency folded
    into :attr:`rtt`. Timeouts resolve through the control plane's
    ``net_connect_timeout_ms`` / ``net_rpc_timeout_ms`` knobs unless
    given explicitly (seconds)."""

    def __init__(self, host: str, address: Tuple[str, int],
                 connect_timeout: Optional[float] = None,
                 rpc_timeout: Optional[float] = None,
                 pool: bool = True,
                 pool_idle_timeout: float = 5.0):
        super().__init__(host)
        self.address = (str(address[0]), int(address[1]))
        cfg = global_config()
        self._connect_timeout = (
            float(connect_timeout) if connect_timeout is not None
            else cfg.net_connect_timeout_ms / 1000.0)
        self._rpc_timeout = (
            float(rpc_timeout) if rpc_timeout is not None
            else cfg.net_rpc_timeout_ms / 1000.0)
        self._rtt_lock = threading.Lock()
        self._rtt = 0.0  #: guarded by _rtt_lock
        self._pool = _SocketPool(pool_idle_timeout) if pool else None

    @property
    def rtt(self) -> float:
        """EWMA of successful RPC round trips (seconds); 0.0 until the
        first completes."""
        with self._rtt_lock:
            return self._rtt

    def _fail(self, op: str, exc: BaseException) -> HostLaneError:
        _obs.GLOBAL_COUNTERS.inc("spfft_cluster_rpc_failures_total",
                                 host=self.host, op=op)
        return HostLaneError(
            f"host lane {self.host!r} wire RPC {op!r} to "
            f"{self.address} failed: {exc}", host=self.host)

    def start_call(self, header: dict, payload: bytes = b"",
                   timeout: Optional[float] = None):
        """The SYNCHRONOUS half of an RPC: connect and send the request
        frame, returning ``(sock, op, t0)`` for :meth:`finish_call`.
        Kept separate so a submit surfaces a dead host HERE — at
        routing time, where the frontend can fail over — not later in
        a background future. Connect/send failures raise the transient
        :class:`HostLaneError`."""
        op = str(header.get("type", "?"))
        t0 = time.monotonic()
        read_timeout = timeout if timeout is not None \
            else self._rpc_timeout
        if self._pool is not None:
            sock = self._pool.checkout()
            if sock is not None:
                try:
                    sock.settimeout(read_timeout)
                    send_frame(sock, header, payload)
                    return sock, op, t0
                except OSError:
                    # the keep-alive went stale between checkout and
                    # send (server FIN in flight): fall back to a
                    # fresh connect — a genuinely dead host fails THAT
                    sock.close()
                except (NetProtocolError, InjectedFault) as exc:
                    sock.close()
                    raise self._fail(op, exc) from exc
        try:
            sock = self._connect_with_retry(op)
        except (OSError, InjectedFault) as exc:
            raise self._fail(op, exc) from exc
        try:
            sock.settimeout(read_timeout)
            send_frame(sock, header, payload)
        except (OSError, NetProtocolError, InjectedFault) as exc:
            sock.close()
            raise self._fail(op, exc) from exc
        return sock, op, t0

    #: Fresh-connect attempts before the lane is declared dead, and
    #: the base backoff between them (exponential + jitter). One
    #: refused connect from an agent mid-restart must not kill the
    #: lane; a truly dead-but-reachable host still exhausts the budget
    #: in well under a second on ECONNREFUSED. Only refused/reset-class
    #: errors retry — a connect TIMEOUT (unreachable host, blackholed
    #: route) fails fast so failover starts after ONE connect timeout,
    #: not three.
    CONNECT_ATTEMPTS = 3
    CONNECT_BACKOFF_S = 0.05
    _RETRYABLE_CONNECT_ERRORS = (ConnectionRefusedError,
                                 ConnectionResetError,
                                 ConnectionAbortedError)

    def _connect_with_retry(self, op: str):
        last = None
        for attempt in range(self.CONNECT_ATTEMPTS):
            if attempt:
                delay = self.CONNECT_BACKOFF_S * (2 ** (attempt - 1))
                time.sleep(delay * (1.0 + random.random() * 0.25))
                _obs.GLOBAL_COUNTERS.inc(
                    "spfft_net_rpc_retries_total", verb=op)
            try:
                return socket.create_connection(
                    self.address, timeout=self._connect_timeout)
            except self._RETRYABLE_CONNECT_ERRORS as exc:
                last = exc
        raise last

    def finish_call(self, sock, op: str,
                    t0: float) -> Tuple[dict, bytes]:
        """The (possibly deferred) second half: read the response
        frame, fold the measured round trip into :attr:`rtt`, and
        re-raise a typed ``error`` record as its original taxonomy
        class. A cleanly completed round trip returns its socket to
        the keep-alive pool (the stream stays framed even after a
        typed error reply — the agent's connection loop keeps
        serving); any read failure closes it."""
        try:
            reply, rpayload = recv_frame(sock)
        except (OSError, NetProtocolError, InjectedFault) as exc:
            sock.close()
            raise self._fail(op, exc) from exc
        if self._pool is not None:
            self._pool.checkin(sock)
        else:
            sock.close()
        dt = time.monotonic() - t0
        with self._rtt_lock:
            self._rtt = dt if self._rtt <= 0.0 \
                else (1.0 - _RTT_ALPHA) * self._rtt + _RTT_ALPHA * dt
            rtt = self._rtt
        _obs.GLOBAL_COUNTERS.set("spfft_net_rpc_rtt_seconds", rtt,
                                 host=self.host)
        if reply.get("type") == "error":
            raise error_from_wire(reply)
        return reply, rpayload

    def call(self, header: dict, payload: bytes = b"",
             timeout: Optional[float] = None) -> Tuple[dict, bytes]:
        """One full request/response round trip (both halves,
        blocking)."""
        sock, op, t0 = self.start_call(header, payload, timeout)
        return self.finish_call(sock, op, t0)

    def pool_stats(self) -> Optional[dict]:
        """Keep-alive pool counters (idle/hits/misses/reaped); None on
        an unpooled transport."""
        return None if self._pool is None else self._pool.stats()

    def close(self) -> None:
        """Close any idle keep-alive sockets (in-flight RPCs keep
        theirs until finish_call)."""
        if self._pool is not None:
            self._pool.close()


class TcpHostLane(HostLane):
    """A ``HostLane`` whose executor lives in another process behind a
    :class:`HostAgent`. ``executor`` is None — every ``rpc_*`` crosses
    the wire; a small thread pool makes :meth:`rpc_submit` return a
    ``Future`` immediately (the frontend's submit path stays
    non-blocking) while the round trip completes in the background."""

    def __init__(self, host: str, address: Tuple[str, int],
                 connect_timeout: Optional[float] = None,
                 rpc_timeout: Optional[float] = None,
                 max_inflight: int = 8, pool: bool = True):
        self.host = host
        self.executor = None
        self.draining = False
        self.transport = TcpTransport(host, address,
                                      connect_timeout=connect_timeout,
                                      rpc_timeout=rpc_timeout,
                                      pool=pool)
        self._pool = ThreadPoolExecutor(
            max_workers=max_inflight,
            thread_name_prefix=f"spfft-net-{host}")

    # trace: boundary(ctx)
    def rpc_submit(self, signature: PlanSignature, values,
                   kind: str = "backward",
                   scaling: Scaling = Scaling.NONE,
                   timeout: Optional[float] = None,
                   priority: str = "normal", ctx=None,
                   epoch: Optional[int] = None) -> Future:
        """Submit one request over the wire. The propagated trace
        context rides the frame header, so the agent's ``serve.request``
        root carries the frontend's trace id — one id end-to-end across
        the process boundary. ``epoch`` stamps the frontend's view
        epoch for membership fencing (the agent rejects stale stamps
        typed as ``StaleEpochError``). Connect + send run synchronously
        (a ``kill -9``'d host raises ``HostLaneError`` HERE, where the
        frontend fails over); only the response read is deferred to the
        lane's pool."""
        self.transport.check("submit")
        meta, payload = pack_values(values)
        header = {"type": "submit",
                  "signature": signature_to_wire(signature),
                  "kind": kind, "scaling": Scaling(scaling).value,
                  "timeout": timeout, "priority": priority,
                  "ctx": _ctx_to_wire(ctx), "epoch": epoch,
                  **meta}
        wire_timeout = None if timeout is None \
            else timeout + self.transport._rpc_timeout
        sock, op, t0 = self.transport.start_call(header, payload,
                                                 timeout=wire_timeout)
        return self._pool.submit(self._wire_finish, sock, op, t0)

    def _wire_finish(self, sock, op, t0):
        reply, rpayload = self.transport.finish_call(sock, op, t0)
        return unpack_values(reply, rpayload)

    def rpc_signals(self) -> dict:
        self.transport.check("signals")
        reply, _ = self.transport.call({"type": "signals"})
        signals = dict(reply.get("signals") or {})
        # the wire's contribution to load_score: a far host at equal
        # queue depth really is the slower choice
        signals["wire_rtt"] = self.transport.rtt
        return signals

    def rpc_signatures(self) -> List[PlanSignature]:
        self.transport.check("signatures")
        reply, _ = self.transport.call({"type": "signatures"})
        return [signature_from_wire(d)
                for d in reply.get("signatures", [])]

    def rpc_plan(self, signature: PlanSignature):
        """A remote PLAN DESCRIPTOR (the plan object itself never
        crosses the wire): ``{"remote": True, "distributed": bool,
        "fingerprint": hex|None}``, or None when unheld. The frontend
        routes and reconciles from the descriptor."""
        self.transport.check("plan")
        reply, _ = self.transport.call(
            {"type": "plan", "signature": signature_to_wire(signature)})
        if not reply.get("held"):
            return None
        return {"remote": True,
                "distributed": bool(reply.get("distributed")),
                "fingerprint": reply.get("fingerprint")}

    def rpc_metrics_text(self) -> str:
        self.transport.check("metrics")
        reply, _ = self.transport.call({"type": "metrics"})
        return str(reply.get("text", ""))

    def rpc_health(self) -> dict:
        self.transport.check("health")
        reply, _ = self.transport.call({"type": "health"})
        return dict(reply.get("health") or {})

    def rpc_prewarm(self, signatures, strict: bool = True) -> int:
        self.transport.check("prewarm")
        reply, _ = self.transport.call(
            {"type": "prewarm",
             "signatures": [signature_to_wire(s) for s in signatures],
             "strict": bool(strict)})
        return int(reply.get("warmed", 0))

    def rpc_drain(self) -> None:
        self.transport.check("drain")
        self.transport.call({"type": "drain"})

    def rpc_shutdown(self) -> None:
        self.transport.check("shutdown")
        self.transport.call({"type": "shutdown"})

    def rpc_stats(self) -> dict:
        """The remote registry's ``stats()`` — the warm-boot observable
        (``builds == 0`` after a remote-tier prewarm)."""
        self.transport.check("stats")
        reply, _ = self.transport.call({"type": "stats"})
        return dict(reply.get("registry") or {})

    def rpc_spans(self) -> dict:
        """The agent's completed-span summaries + open count — how a
        smoke asserts one trace id crossed the process boundary and
        nothing leaked."""
        self.transport.check("spans")
        reply, _ = self.transport.call({"type": "spans"})
        return {"spans": list(reply.get("spans", [])),
                "open": int(reply.get("open", 0))}

    def rpc_incident(self, reason: str) -> dict:
        """The agent process's in-memory incident bundle — the remote
        half of a pod-wide flight-recorder capture."""
        self.transport.check("incident")
        reply, _ = self.transport.call(
            {"type": "incident", "reason": str(reason)})
        return dict(reply.get("bundle") or {})

    def rpc_heartbeat(self, host: str,
                      address: Optional[str] = None) -> dict:
        """Renew ``host``'s membership lease with this lane's agent
        (redirect acks name the real coordinator)."""
        self.transport.check("heartbeat")
        reply, _ = self.transport.call(
            {"type": "heartbeat", "host": host, "address": address})
        return {k: v for k, v in reply.items() if k != "type"}

    # trace: boundary(ctx)
    def rpc_view(self, ctx=None) -> dict:
        """Fetch the agent's signed membership view (wire form). The
        propagated trace context rides the header so a view refetch
        inside a stale-epoch retry stays on the request's trace."""
        self.transport.check("view")
        reply, _ = self.transport.call(
            {"type": "view", "ctx": _ctx_to_wire(ctx)})
        return dict(reply.get("view") or {})

    def close(self) -> None:
        """Release the lane's client thread pool and any idle
        keep-alive sockets (the remote agent is NOT shut down — lanes
        don't own hosts)."""
        self._pool.shutdown(wait=True)
        self.transport.close()


def wire_overhead_probe(repeats: int = 24, n: int = 8) -> dict:
    """Measure what the wire costs: median ``rpc_submit`` round trip of
    a tiny C2C backward through a loopback lane vs through an
    in-process TCP agent fronting the SAME executor — once over the
    round-19 connect-per-RPC wire (the ``pod_wire`` bench sub-row,
    semantics unchanged) and once over the pooled keep-alive wire (the
    ``pod_wire_pooled`` sub-row). Returns microsecond medians plus the
    deltas. All paths are warmed (JIT + connection machinery) before
    timing so the medians compare steady-state transports, not compile
    time."""
    import statistics

    import numpy as np

    from ..benchmark import cutoff_stick_triplets
    from ..serve.executor import ServeExecutor
    from ..serve.registry import PlanRegistry
    from ..types import TransformType
    from .agent import HostAgent

    trip = cutoff_stick_triplets(n, n, n, 0.9, hermitian=False)
    reg = PlanRegistry()
    sig, _plan = reg.get_or_build(TransformType.C2C, n, n, n, trip,
                                  precision="double")
    executor = ServeExecutor(reg)
    rng = np.random.default_rng(7)
    v = rng.standard_normal(len(trip)) \
        + 1j * rng.standard_normal(len(trip))

    def timed(lane) -> float:
        for _ in range(3):  # warm the JIT + transport path
            lane.rpc_submit(sig, v, ctx=None).result(timeout=120)
        samples = []
        for _ in range(repeats):
            t0 = time.monotonic()
            lane.rpc_submit(sig, v, ctx=None).result(timeout=120)
            samples.append(time.monotonic() - t0)
        return statistics.median(samples)

    agent = None
    tcp_lane = None
    pooled_lane = None
    try:
        loop_lane = HostLane("probe-loop", executor)
        loop_s = timed(loop_lane)
        agent = HostAgent("probe-tcp", executor)
        agent.start()
        tcp_lane = TcpHostLane("probe-tcp",
                               ("127.0.0.1", agent.port), pool=False)
        tcp_s = timed(tcp_lane)
        pooled_lane = TcpHostLane("probe-tcp-pooled",
                                  ("127.0.0.1", agent.port), pool=True)
        pooled_s = timed(pooled_lane)
        pool_stats = pooled_lane.transport.pool_stats() or {}
    finally:
        if tcp_lane is not None:
            tcp_lane.close()
        if pooled_lane is not None:
            pooled_lane.close()
        if agent is not None:
            agent.close()
        executor.close(drain=False)
    return {
        "repeats": int(repeats),
        "loopback_us": loop_s * 1e6,
        "tcp_us": tcp_s * 1e6,
        "tcp_pooled_us": pooled_s * 1e6,
        "overhead_us": max(0.0, (tcp_s - loop_s) * 1e6),
        "overhead_pooled_us": max(0.0, (pooled_s - loop_s) * 1e6),
        "pool_hits": int(pool_stats.get("hits", 0)),
        "pool_misses": int(pool_stats.get("misses", 0)),
    }

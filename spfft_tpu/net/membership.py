"""Lease-based, epoch-fenced pod membership.

The pod's liveness problem through round 20: membership was
frontend-local (two frontends could hold contradictory views of the
same pod) and a lane marked dead stayed dead forever. This module is
the convergence point both gaps close through:

* **Leases** — every agent holds a time-bounded lease it renews with
  a lightweight ``heartbeat`` RPC; the ack carries the coordinator's
  full signed view, which the follower adopts each beat (elections
  run over real per-host states, never a states-less roster). A lease
  that stops renewing walks the expiry ladder ``alive -> suspected ->
  probed -> evicted`` at multiples of ``lease_ttl_ms`` past its last
  renewal; no state is removed on a single missed beat. Members
  registered statically via :meth:`ViewCoordinator.ensure` (loopback
  lanes nothing heartbeats) hold no lease and never expire.
* **Epochs** — a single :class:`ViewCoordinator` (the lowest alive
  host id; deterministic, no Raft — leases + fencing suffice at pod
  scale) bumps a monotonic view epoch on EVERY membership change and
  serves the signed view over the ``view`` RPC. Frontends stamp the
  epoch on routed work; agents reject anything older than their view
  with the typed transient
  :class:`~spfft_tpu.errors.StaleEpochError` — the sender refetches
  the view and retries, so a partitioned frontend can never
  split-brain the pod.
* **Election** — :func:`elect_coordinator` is a pure function of the
  view (lowest alive host id), so every node that holds the same view
  names the same coordinator; a dead coordinator is detected by its
  heartbeat targets (failure streak), locally suspected, and the
  next-lowest alive host promotes itself with an epoch bump.

:class:`MembershipNode` is one agent's half: a roster + cached view,
a heartbeat sender (:meth:`MembershipNode.tick`), and an embedded
coordinator that activates when this host is elected.
:class:`ViewCoordinator` is also used standalone by ``PodFrontend``
for loopback pods (the frontend is trivially the coordinator of an
in-process pod) and shared between frontends in tests.

Views are signed: HMAC-SHA256 over the canonical JSON encoding when
``SPFFT_TPU_NET_SECRET`` is set, a plain SHA-256 integrity digest
otherwise; a view whose signature does not verify is rejected with
the permanent :class:`~spfft_tpu.errors.NetAuthError` and counted
``spfft_membership_views_total{outcome="bad_sig"}``.

Fault sites: ``net.heartbeat`` fires on each renewal (sender wire
call and coordinator handling), ``cluster.view`` on serving/adopting
a view. Counters: ``spfft_membership_epoch{node}``,
``spfft_membership_transitions_total{host,to}``,
``spfft_membership_heartbeats_total{outcome}``,
``spfft_membership_views_total{outcome}``,
``spfft_cluster_stale_epoch_total{node}``.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .. import faults as _faults
from .. import obs as _obs
from ..errors import (InvalidParameterError, NetAuthError,
                      NetProtocolError, StaleEpochError)

#: Ladder states, rung order. ``evicted`` members stay in the view
#: (tombstoned) so late frontends learn the eviction instead of
#: mistaking the host for never-seen.
ALIVE = "alive"
SUSPECTED = "suspected"
PROBED = "probed"
EVICTED = "evicted"
LADDER = (ALIVE, SUSPECTED, PROBED, EVICTED)
_RANK = {s: i for i, s in enumerate(LADDER)}

#: Ladder timing as multiples of the lease TTL past the last renewal:
#: suspect after one full TTL, escalate to probed at 1.5x, evict at
#: 2.5x — an agent that restarts inside ~2.5 TTLs rejoins without
#: ever having been evicted.
SUSPECT_AFTER = 1.0
PROBE_AFTER = 1.5
EVICT_AFTER = 2.5

#: Consecutive heartbeat failures before a node locally suspects its
#: coordinator and re-elects.
COORD_FAIL_STREAK = 3

_UNSET = object()


def _lease_ttl_s() -> float:
    from ..control.config import global_config
    return global_config().lease_ttl_ms / 1e3


def _secret() -> Optional[bytes]:
    from .frame import net_secret
    return net_secret()


def _count_hb(outcome: str) -> None:
    _obs.GLOBAL_COUNTERS.inc("spfft_membership_heartbeats_total",
                             outcome=outcome)


def _count_view(outcome: str) -> None:
    _obs.GLOBAL_COUNTERS.inc("spfft_membership_views_total",
                             outcome=outcome)


def _gauge_epoch(node: str, epoch: int) -> None:
    _obs.GLOBAL_COUNTERS.set("spfft_membership_epoch", epoch,
                             node=node)


def elect_coordinator(members: Dict[str, str]) -> Optional[str]:
    """The deterministic coordinator of a view: the LOWEST alive host
    id (string sort — host ids are operator-chosen names like ``h0``).
    Every node holding the same view elects the same coordinator; no
    ballots."""
    alive = sorted(h for h, state in members.items()
                   if state == ALIVE)
    return alive[0] if alive else None


class MembershipView:
    """One immutable, signed snapshot of the pod: ``epoch``,
    ``coordinator``, and per-host ``{"state", "address"}`` rows."""

    __slots__ = ("epoch", "coordinator", "members", "signature")

    def __init__(self, epoch: int, coordinator: Optional[str],
                 members: Dict[str, Dict], signature: str = ""):
        self.epoch = int(epoch)
        self.coordinator = coordinator
        self.members = {str(h): {"state": str(m["state"]),
                                 "address": m.get("address")}
                        for h, m in members.items()}
        self.signature = signature

    def states(self) -> Dict[str, str]:
        return {h: m["state"] for h, m in self.members.items()}

    def _canonical(self) -> bytes:
        return json.dumps(
            {"epoch": self.epoch, "coordinator": self.coordinator,
             "members": self.members},
            sort_keys=True).encode("utf-8")

    def signed(self, secret: Optional[bytes] = None
               ) -> "MembershipView":
        """A copy carrying the view signature: HMAC-SHA256 under the
        pod secret, else a SHA-256 integrity digest."""
        body = self._canonical()
        if secret:
            sig = _hmac.new(secret, body, hashlib.sha256).hexdigest()
        else:
            sig = hashlib.sha256(body).hexdigest()
        return MembershipView(self.epoch, self.coordinator,
                              self.members, signature=sig)

    def verify(self, secret: Optional[bytes] = None) -> bool:
        return _hmac.compare_digest(
            self.signed(secret).signature, self.signature or "")

    def to_wire(self) -> dict:
        return {"epoch": self.epoch, "coordinator": self.coordinator,
                "members": self.members,
                "signature": self.signature}

    @classmethod
    def from_wire(cls, wire: dict) -> "MembershipView":
        try:
            return cls(int(wire["epoch"]), wire.get("coordinator"),
                       dict(wire["members"]),
                       signature=str(wire.get("signature", "")))
        except (KeyError, TypeError, ValueError) as exc:
            raise NetProtocolError(
                f"malformed membership view: {exc!r}") from exc


class _Member:
    """``renewed is None`` means the member holds NO lease (it was
    statically registered via :meth:`ViewCoordinator.ensure` — a
    loopback/frontend-embedded lane nothing heartbeats) and is exempt
    from lease expiry; the first heartbeat converts it to a leased
    member."""

    __slots__ = ("state", "address", "renewed")

    def __init__(self, state: str, address: Optional[str],
                 renewed: Optional[float]):
        self.state = state
        self.address = address
        self.renewed = renewed


class ViewCoordinator:
    """The pod's single membership authority: a lease table plus the
    monotonic view epoch. Thread-safe; a frontend embeds one for
    loopback pods, an agent's :class:`MembershipNode` activates one
    when elected."""

    def __init__(self, host: str, clock: Callable[[], float] = None,
                 lease_ttl_s: Optional[float] = None,
                 secret=_UNSET):
        self.host = str(host)
        self._clock = clock or time.monotonic
        self._ttl = lease_ttl_s
        self._secret = _secret() if secret is _UNSET else secret
        self._lock = threading.Lock()
        self._epoch = 1  #: guarded by _lock
        self._members: Dict[str, _Member] = {}  #: guarded by _lock
        self._members[self.host] = _Member(ALIVE, None, self._clock())

    # lock: holds(_lock)
    def _bump(self, host: str, to: str) -> None:
        self._epoch += 1
        _obs.GLOBAL_COUNTERS.inc(
            "spfft_membership_transitions_total", host=host, to=to)
        _obs.record_event("membership.transition", host=host, to=to,
                          epoch=self._epoch)
        _gauge_epoch(self.host, self._epoch)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def ttl(self) -> float:
        return self._ttl if self._ttl is not None else _lease_ttl_s()

    def ensure(self, host: str, address: Optional[str] = None) -> None:
        """Register ``host`` alive if it is not already a member (the
        frontend's initial roster; idempotent, so two frontends over
        the same lanes converge instead of double-bumping). A member
        registered this way holds NO lease — nothing heartbeats a
        loopback lane, so lease expiry must not walk it down the
        ladder; explicit :meth:`evict`/:meth:`readmit` remain its only
        transitions until a first heartbeat leases it."""
        with self._lock:
            m = self._members.get(host)
            if m is None:
                self._members[host] = _Member(ALIVE, address, None)
                self._bump(host, ALIVE)
            elif address is not None and m.address is None:
                m.address = address

    def heartbeat(self, host: str, address: Optional[str] = None,
                  now: Optional[float] = None) -> dict:
        """Renew ``host``'s lease (creating or resurrecting it — a
        heartbeat from an evicted or unknown host readmits it alive
        with an epoch bump). Returns the renewal ack every agent
        converges on: epoch, coordinator, TTL, the address roster AND
        the full signed view — followers adopt it each beat, so a
        coordinator death is re-elected over real per-host states, not
        a states-less roster (exactly one successor promotes)."""
        _faults.check_site("net.heartbeat")
        if now is None:
            now = self._clock()
        with self._lock:
            m = self._members.get(host)
            if m is None:
                m = self._members[host] = _Member(ALIVE, address, now)
                self._bump(host, ALIVE)
            else:
                if address is not None:
                    m.address = address
                m.renewed = now
                if m.state != ALIVE:
                    m.state = ALIVE
                    self._bump(host, ALIVE)
            _count_hb("ok")
            roster = {h: mm.address for h, mm in self._members.items()
                      if mm.address and mm.state != EVICTED}
            snapshot = self._view_locked()
            ack = {"epoch": self._epoch, "coordinator": self.host,
                   "lease_ttl_ms": int(self.ttl() * 1e3),
                   "roster": roster}
        # sign outside the lock (hashing is the expensive part)
        ack["view"] = snapshot.signed(self._secret).to_wire()
        return ack

    def expire(self, now: Optional[float] = None
               ) -> List[Tuple[str, str, str]]:
        """Walk every lease down the suspected->probed->evicted ladder
        by age past its last renewal; each transition bumps the epoch.
        Returns ``(host, old_state, new_state)`` transitions."""
        if now is None:
            now = self._clock()
        ttl = self.ttl()
        out = []
        with self._lock:
            for host, m in self._members.items():
                if host == self.host or m.state == EVICTED \
                        or m.renewed is None:
                    continue  # self, tombstones and leaseless members
                age = now - m.renewed
                if age > EVICT_AFTER * ttl:
                    target = EVICTED
                elif age > PROBE_AFTER * ttl:
                    target = PROBED
                elif age > SUSPECT_AFTER * ttl:
                    target = SUSPECTED
                else:
                    target = ALIVE
                if _RANK[target] > _RANK[m.state]:
                    out.append((host, m.state, target))
                    m.state = target
                    self._bump(host, target)
        return out

    def evict(self, host: str) -> None:
        """Explicit eviction (the frontend observed the death itself
        — ``kill_host`` / exhausted failover)."""
        with self._lock:
            m = self._members.get(host)
            if m is not None and m.state != EVICTED:
                m.state = EVICTED
                self._bump(host, EVICTED)

    def readmit(self, host: str, address: Optional[str] = None
                ) -> None:
        """Explicit readmission after the resurrection ladder
        re-reconciled the host. A leaseless (statically ensured)
        member stays leaseless — readmission must not start a lease
        nothing will renew."""
        now = self._clock()
        with self._lock:
            m = self._members.get(host)
            if m is None:
                self._members[host] = _Member(ALIVE, address, None)
                self._bump(host, ALIVE)
            elif m.state != ALIVE:
                m.state = ALIVE
                if m.renewed is not None:
                    m.renewed = now
                if address is not None:
                    m.address = address
                self._bump(host, ALIVE)

    def leave(self, host: str) -> None:
        """Remove a drained host entirely (a polite leave is not a
        tombstone)."""
        with self._lock:
            if self._members.pop(host, None) is not None:
                self._bump(host, "left")

    def promote(self, seed: Optional[MembershipView],
                dead: Optional[str] = None) -> None:
        """Become the authority after winning an election: adopt the
        last known view's members (the dead coordinator suspected,
        leases restarted now) and bump past its epoch."""
        now = self._clock()
        with self._lock:
            if seed is not None:
                for host, row in seed.members.items():
                    if host == self.host:
                        continue
                    state = row["state"]
                    if host == dead and state == ALIVE:
                        state = SUSPECTED
                    self._members.setdefault(
                        host, _Member(state, row.get("address"), now))
                self._epoch = max(self._epoch, seed.epoch)
            self._epoch += 1
            _obs.record_event("membership.elect", host=self.host,
                              epoch=self._epoch)
            _gauge_epoch(self.host, self._epoch)

    # lock: holds(_lock)
    def _view_locked(self) -> MembershipView:
        """The unsigned snapshot of the current members + epoch."""
        members = {h: {"state": m.state, "address": m.address}
                   for h, m in self._members.items()}
        return MembershipView(self._epoch, self.host, members)

    def view(self, now: Optional[float] = None) -> MembershipView:
        """The signed current view. Serving implies current ladder
        state, so expiry runs first."""
        _faults.check_site("cluster.view")
        self.expire(now)
        with self._lock:
            snapshot = self._view_locked()
        _count_view("served")
        return snapshot.signed(self._secret)

    def check_epoch(self, epoch: Optional[int],
                    node: Optional[str] = None) -> None:
        """Epoch fencing: reject work stamped with an epoch older than
        the current view (typed transient — refetch and retry)."""
        if epoch is None:
            return
        with self._lock:
            current = self._epoch
        if int(epoch) < current:
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_stale_epoch_total",
                                     node=node or self.host)
            raise StaleEpochError(
                f"operation stamped with stale view epoch {epoch} "
                f"(current {current}) — refetch the view and retry",
                stale=int(epoch), current=current)


def send_heartbeat(address: str, header: dict,
                   timeout: Optional[float] = None) -> dict:
    """One heartbeat/view RPC over its own short-lived socket (the
    membership plane deliberately does not share the request-plane
    connection pool: a wedged data socket must not stop renewals)."""
    from . import frame as _frame
    _faults.check_site("net.heartbeat")
    host, _, port = address.rpartition(":")
    if not host or not port.isdigit():
        raise InvalidParameterError(
            f"bad membership address {address!r} (want host:port)")
    if timeout is None:
        from ..control.config import global_config
        timeout = global_config().net_connect_timeout_ms / 1e3
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        sock.settimeout(timeout)
        _frame.send_frame(sock, header)
        reply, _ = _frame.recv_frame(sock)
        if reply.get("type") == "error":
            raise _frame.error_from_wire(reply)
        return reply
    finally:
        try:
            sock.close()
        except OSError:
            pass


class MembershipNode:
    """One agent's membership half: roster + cached view + heartbeat
    sender, with an embedded :class:`ViewCoordinator` that activates
    when this host is the elected coordinator (lowest alive id)."""

    def __init__(self, host: str, address: Optional[str] = None,
                 peers: Optional[Dict[str, str]] = None,
                 clock: Callable[[], float] = None,
                 secret=_UNSET):
        self.host = str(host)
        self.address = address
        self._clock = clock or time.monotonic
        self._secret = _secret() if secret is _UNSET else secret
        self._lock = threading.Lock()
        self._roster: Dict[str, str] = dict(peers or {})  #: guarded by _lock
        self._view: Optional[MembershipView] = None  #: guarded by _lock
        #: hosts THIS node locally believes dead (heartbeat failure
        #: streaks) — kept OUTSIDE the adopted view, which is signed
        #: and must never be mutated; cleared on the next successful
        #: renewal. guarded by _lock
        self._suspected: set = set()
        self._fail_streak = 0  #: guarded by _lock
        self._coord = ViewCoordinator(host, clock=self._clock,
                                      secret=self._secret)
        active = not self._roster or self.host <= min(self._roster)
        self._active = active  #: guarded by _lock

    # -- role ----------------------------------------------------------------
    @property
    def is_coordinator(self) -> bool:
        with self._lock:
            return self._active

    def coordinator(self) -> Tuple[str, Optional[str]]:
        """``(host, address)`` of the coordinator this node believes
        in: itself when active, else the election over its freshest
        view (with locally suspected hosts overlaid — the adopted view
        itself stays untouched so its signature keeps verifying), else
        the lowest peer id."""
        with self._lock:
            if self._active:
                return self.host, self.address
            if self._view is not None:
                states = self._view.states()
                for suspect in self._suspected:
                    if suspect in states:
                        states[suspect] = SUSPECTED
                host = elect_coordinator(states)
                if host is not None and host != self.host:
                    row = self._view.members.get(host) or {}
                    addr = row.get("address") \
                        or self._roster.get(host)
                    return host, addr
            host = min(self._roster) if self._roster else self.host
            return host, self._roster.get(host)

    @property
    def epoch(self) -> int:
        with self._lock:
            if not self._active and self._view is not None:
                return self._view.epoch
        return self._coord.epoch

    # -- server side (agent dispatch) ----------------------------------------
    def on_heartbeat(self, host: str, address: Optional[str] = None
                     ) -> dict:
        """Handle an inbound renewal: renew when coordinator, redirect
        otherwise (the sender retargets without waiting a beat)."""
        if self.is_coordinator:
            ack = self._coord.heartbeat(host, address)
            if address:
                with self._lock:
                    self._roster[host] = address
            return ack
        _count_hb("redirect")
        coord, addr = self.coordinator()
        return {"redirect": coord, "address": addr,
                "epoch": self.epoch}

    def on_view(self) -> dict:
        """Serve the signed view: authoritative when coordinator, the
        freshest adopted view otherwise."""
        if self.is_coordinator:
            return self._coord.view().to_wire()
        with self._lock:
            cached = self._view
        if cached is not None:
            _count_view("served")
            return cached.to_wire()
        return self._coord.view().to_wire()

    def check_epoch(self, epoch: Optional[int]) -> None:
        """Epoch fencing at the agent's door."""
        if epoch is None:
            return
        current = self.epoch
        if int(epoch) < current:
            _obs.GLOBAL_COUNTERS.inc("spfft_cluster_stale_epoch_total",
                                     node=self.host)
            raise StaleEpochError(
                f"operation stamped with stale view epoch {epoch} "
                f"(current {current}) — refetch the view and retry",
                stale=int(epoch), current=current)

    def adopt(self, wire: dict) -> bool:
        """Verify and adopt a remote view; False when it is older than
        what this node already holds. A signature that does not verify
        is the permanent :class:`NetAuthError`."""
        _faults.check_site("cluster.view")
        view = MembershipView.from_wire(wire)
        if not view.verify(self._secret):
            _count_view("bad_sig")
            raise NetAuthError(
                "membership view signature does not verify")
        with self._lock:
            if self._view is not None \
                    and view.epoch < self._view.epoch:
                _count_view("stale")
                return False
            self._view = view
            for h, row in view.members.items():
                if row.get("address") and row["state"] != EVICTED:
                    self._roster[h] = row["address"]
        _count_view("adopted")
        _gauge_epoch(self.host, view.epoch)
        return True

    # -- sender side (the agent's heartbeat loop) ----------------------------
    def tick(self, send: Callable[[str, dict], dict] = None,
             now: Optional[float] = None) -> str:
        """One heartbeat-loop step. Coordinator: run lease expiry.
        Follower: renew with the coordinator via ``send(address,
        header) -> ack`` (default: the wire RPC), follow redirects,
        adopt the ack; ``COORD_FAIL_STREAK`` consecutive failures
        locally suspects the coordinator, re-elects, and promotes this
        node if it wins."""
        if send is None:
            send = lambda addr, hdr: send_heartbeat(addr, hdr)  # noqa: E731
        if self.is_coordinator:
            self._coord.expire(now)
            return "coordinator"
        coord, addr = self.coordinator()
        header = {"type": "heartbeat", "host": self.host,
                  "address": self.address}
        try:
            if addr is None:
                raise NetProtocolError(
                    f"no address for coordinator {coord!r}")
            ack = send(addr, header)
            if ack.get("redirect") and ack["redirect"] != coord \
                    and ack.get("address"):
                ack = send(ack["address"], header)
            if ack.get("redirect"):
                raise NetProtocolError(
                    f"coordinator redirect loop via {coord!r}")
        except Exception:
            _count_hb("failed")
            return self._on_heartbeat_failure(coord)
        with self._lock:
            self._fail_streak = 0
            self._suspected.clear()  # the coordinator answered
            roster = ack.get("roster") or {}
            for h, a in roster.items():
                if a:
                    self._roster[h] = a
        # adopt the coordinator's signed view riding the ack: THIS is
        # what a later election runs over — without it a coordinator
        # death would leave every follower stateless and self-electing
        view_wire = ack.get("view")
        if view_wire:
            try:
                self.adopt(view_wire)
            except _faults.InjectedFault:
                pass  # the renewal itself succeeded; next beat retries
        _gauge_epoch(self.host, int(ack.get("epoch", 0)))
        return "ok"

    def _on_heartbeat_failure(self, coord: str) -> str:
        with self._lock:
            self._fail_streak += 1
            if self._fail_streak < COORD_FAIL_STREAK:
                return "failed"
            # the coordinator is gone as far as this node can tell:
            # suspect it LOCALLY (never by mutating the adopted view —
            # it is signed and must keep verifying when re-served) and
            # re-run the election over the freshest real states; with
            # no view yet (bootstrap), peers are presumed alive so a
            # high-id node defers instead of self-promoting
            self._fail_streak = 0
            self._suspected.add(coord)
            seed = self._view
            if seed is not None:
                states = dict(seed.states())
            else:
                states = {h: ALIVE for h in self._roster}
            states.setdefault(self.host, ALIVE)
            for suspect in self._suspected:
                states[suspect] = SUSPECTED
            winner = elect_coordinator(states) or self.host
            if winner != self.host:
                # someone else should win; drop the dead coordinator
                # from the roster so the next tick targets the winner
                self._roster.pop(coord, None)
                return "re-elected"
            self._active = True
        self._coord.promote(seed, dead=coord)
        return "promoted"


class HeartbeatLoop:
    """Daemon thread driving :meth:`MembershipNode.tick` every
    ``heartbeat_interval_ms`` (read live — retunes apply on the next
    beat)."""

    def __init__(self, node: MembershipNode,
                 send: Callable[[str, dict], dict] = None):
        self._node = node
        self._send = send
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _interval(self) -> float:
        from ..control.config import global_config
        return global_config().heartbeat_interval_ms / 1e3

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._node.tick(self._send)
            except Exception:
                _count_hb("failed")
            self._stop.wait(self._interval())

    def start(self) -> "HeartbeatLoop":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"spfft-heartbeat-{self._node.host}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

"""``make pod-smoke``: a REAL two-process pod over localhost TCP.

``serve.cluster``'s smoke proves the pod contracts against loopback
lanes in one process; this one proves the wire. It spawns agent
processes (``python -m spfft_tpu.net.agent``), fronts them with
:class:`~spfft_tpu.net.transport.TcpHostLane`, and checks end to end:

* a mixed single-device + distributed trace is bit-exact against a
  serial oracle built in THIS process — same plans, different process,
  every payload crossing the frame protocol twice;
* two CONCURRENT same-signature distributed requests provably
  coalesce agent-side: signature affinity co-locates them, the
  agents' ``spmd_batch_window`` (booted off a
  ``SPFFT_TPU_SERVE_CONFIG`` knob artifact) drains both into one
  collective round (``spfft_cluster_spmd_coalesced_total`` moves, one
  ``cluster.spmd_execute`` span carries both member trace ids) and
  both stay bit-exact;
* one trace id end-to-end: the agents' ``serve.request`` /
  ``cluster.spmd_execute`` spans (fetched over the ``spans`` RPC)
  carry the frontend's ``cluster.request`` trace ids, and neither side
  leaks an open span;
* a host JOINING mid-stream boots warm off the shared blob tier
  (remote registry ``builds == 0`` after the manifest prewarm +
  re-reconciliation) and then serves traffic;
* ``kill -9`` of an agent fails over TYPED — survivors stay bit-exact,
  the pod degrades, nothing hangs and nothing leaks;
* the pod SELF-HEALS with zero operator intervention: the killed
  agent's lease expires on the coordinator (agents heartbeat each
  other over the wire), the eviction bumps the view epoch and TWO
  concurrent frontends converge on the same epoch/view, the agent
  restarts on the same port with a fresh store dir (warm boot off the
  shared blob tier, ``builds == 0``), heartbeats itself back into the
  view, and the routing-piggybacked probe ladder re-reconciles and
  readmits it — after which it serves bit-exact again;
* a drain-leave walks the membership ladder
  (``leave_started → drained → left``).

Prints ``POD SMOKE GREEN`` and exits 0 on success.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List

import numpy as np

from .. import obs as _obs
from .transport import TcpHostLane

#: what every agent subprocess needs to shard on a CPU-only box
_AGENT_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def _spawn_agent(host: str, store: str, blob: str, warm: str,
                 timeout: float = 240.0, extra_env=None,
                 port: int = 0, peers: str = ""):
    """Start one agent process and wait for its port announcement.
    Returns ``(proc, port)``; raises if the agent dies before
    announcing. ``extra_env`` merges over the sharding defaults (the
    smoke uses it to boot agents off a ``SPFFT_TPU_SERVE_CONFIG``
    knob artifact); ``port`` pins the listen port (the restart half of
    the self-healing phase rebinds the dead agent's address) and
    ``peers`` seeds the agent's membership roster."""
    cmd = [sys.executable, "-m", "spfft_tpu.net.agent",
           "--host", host, "--port", str(port), "--trace",
           "--store", store, "--blob", blob, "--demo-warm", warm]
    if peers:
        cmd += ["--peers", peers]
    env = dict(os.environ)
    env.update(_AGENT_ENV)
    env.update(extra_env or {})
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True,
                            env=env)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break  # EOF — the agent died during warmup
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("agent") == host and "port" in rec:
            return proc, int(rec["port"])
    proc.kill()
    raise RuntimeError(
        f"agent {host!r} never announced its port "
        f"(exit={proc.poll()})")


def _counter_sum(name: str, **labels) -> float:
    """Sum this process's samples of ``name`` matching ``labels``."""
    fam = _obs.GLOBAL_COUNTERS.snapshot().get(name)
    if not fam:
        return 0.0
    total = 0.0
    for key, value in fam["samples"].items():
        kd = dict(key)
        if all(kd.get(k) == v for k, v in labels.items()):
            total += value
    return total


def _run_pod_smoke(seed: int = 0) -> int:
    from ..benchmark import cutoff_stick_triplets
    from ..parallel import make_distributed_plan, make_mesh
    from ..serve.cluster import PodFrontend
    from ..serve.registry import PlanRegistry, signature_for
    from ..types import TransformType
    from ..utils.workloads import (even_plane_split,
                                   round_robin_stick_partition)

    failures: List[str] = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    n = 10
    dims = (n, n, n)
    shards = 2
    trip = cutoff_stick_triplets(n, n, n, 0.9, hermitian=False)
    rng = np.random.default_rng(seed)

    # the serial oracle: the same deterministic plan builds, local
    reg = PlanRegistry()
    sig, plan = reg.get_or_build(TransformType.C2C, *dims, trip,
                                 precision="double")
    parts = round_robin_stick_partition(trip, dims, shards)
    planes = even_plane_split(dims[2], shards)
    dplan = make_distributed_plan(TransformType.C2C, *dims, parts,
                                  planes, mesh=make_mesh(shards),
                                  precision="double")
    dsig = signature_for(TransformType.C2C, *dims, trip,
                         precision="double", device_count=shards)

    _obs.enable()
    tracer = _obs.GLOBAL_TRACER
    tracer.reset()
    tracer.set_sample_rate(1.0)

    tmp = tempfile.TemporaryDirectory(prefix="spfft-pod-smoke-")
    blob = os.path.join(tmp.name, "blob")
    os.makedirs(blob)
    # knob artifact the agents boot from: a generous coalescing window
    # so the coalesce phase's concurrent pair provably shares a round
    from ..control.config import CONFIG_ENV, ServeConfig
    knob_cfg = ServeConfig()
    knob_cfg.set("spmd_batch_window", 0.25, source="smoke",
                 reason="pod-smoke coalesce phase window")
    # tight leases so the self-healing phase's kill -> lease-expiry ->
    # evict ladder resolves in well under a second of wall clock
    knob_cfg.set("lease_ttl_ms", 300, source="smoke",
                 reason="pod-smoke fast lease expiry")
    knob_cfg.set("heartbeat_interval_ms", 100, source="smoke",
                 reason="pod-smoke fast lease renewal")
    knob_path = os.path.join(tmp.name, "serve_config.json")
    knob_cfg.save(knob_path)
    agent_env = {CONFIG_ENV: knob_path}
    # frontend-side: keep the resurrection ladder's exponential
    # backoff short so routing-piggybacked probes readmit quickly
    from ..control.config import global_config
    global_config().set("lane_probe_backoff", 0.05, source="smoke",
                        reason="pod-smoke fast readmission probes")
    procs: Dict[str, subprocess.Popen] = {}
    lanes: Dict[str, TcpHostLane] = {}
    ports: Dict[str, int] = {}
    pod = pod2 = None
    try:
        procs["h0"], ports["h0"] = _spawn_agent(
            "h0", os.path.join(tmp.name, "store-h0"), blob,
            "10,0.9,2,full", extra_env=agent_env)
        peers = f"h0=127.0.0.1:{ports['h0']}"
        procs["h1"], ports["h1"] = _spawn_agent(
            "h1", os.path.join(tmp.name, "store-h1"), blob,
            "10,0.9,2,full", extra_env=agent_env, peers=peers)
        for host in ("h0", "h1"):
            lanes[host] = TcpHostLane(host, ("127.0.0.1", ports[host]))
        pod = PodFrontend([lanes["h0"], lanes["h1"]], policy="rr",
                          seed=seed)

        # -- mixed traffic, bit-exact across two real processes --------
        singles = []
        for _ in range(24):
            v = (rng.standard_normal(len(trip))
                 + 1j * rng.standard_normal(len(trip)))
            singles.append((v, pod.submit_backward(sig, v)))
        dvalues = [
            (rng.standard_normal(p.num_values)
             + 1j * rng.standard_normal(p.num_values))
            for p in dplan.dist_plan.shard_plans]
        dfut = pod.submit(dsig, dvalues)
        for v, fut in singles:
            got = np.asarray(fut.result(timeout=120))
            check(np.array_equal(got, np.asarray(plan.backward(v))),
                  "single result not bit-exact vs serial oracle")
        dgot = np.asarray(dfut.result(timeout=120))
        check(np.array_equal(dgot, np.asarray(dplan.backward(dvalues))),
              "distributed result not bit-exact vs serial oracle")

        # -- cross-request SPMD coalescing over the real wire ----------
        # two concurrent same-signature distributed submits: signature
        # affinity co-locates them on one agent, whose 0.25 s window
        # (the knob artifact above) drains both into ONE collective
        # round — both bit-exact, provably coalesced below
        dpair = []
        for _ in range(2):
            dpair.append([
                (rng.standard_normal(p.num_values)
                 + 1j * rng.standard_normal(p.num_values))
                for p in dplan.dist_plan.shard_plans])
        pair_futs = [pod.submit(dsig, dv) for dv in dpair]
        for dv, fut in zip(dpair, pair_futs):
            got = np.asarray(fut.result(timeout=120))
            check(np.array_equal(got, np.asarray(dplan.backward(dv))),
                  "coalesced distributed result not bit-exact vs "
                  "serial oracle")
        coalesced = 0.0
        for host, lane in lanes.items():
            text = lane.rpc_metrics_text()
            for line in text.splitlines():
                if line.startswith("spfft_cluster_spmd_coalesced_total"):
                    coalesced += float(line.rsplit(None, 1)[-1])
        check(coalesced >= 2,
              f"agent-side spfft_cluster_spmd_coalesced_total is "
              f"{coalesced}, the concurrent pair never shared a round")

        # -- one trace id across the process boundary ------------------
        check(tracer.open_count() == 0,
              f"{tracer.open_count()} unclosed client spans")
        roots = [s for s in tracer.events()
                 if isinstance(s, _obs.Span)
                 and s.name == "cluster.request"]
        check(len(roots) == 27,
              f"expected 27 cluster.request roots, got {len(roots)}")
        root_ids = {s.trace_id for s in roots}
        crossed = 0
        shared_rounds = []
        for host, lane in lanes.items():
            remote = lane.rpc_spans()
            check(remote["open"] == 0,
                  f"{host}: {remote['open']} unclosed agent spans")
            served = [s for s in remote["spans"]
                      if s["name"] in ("serve.request",
                                       "cluster.spmd_execute")]
            foreign = [s for s in served
                       if s["trace_id"] not in root_ids]
            check(not foreign,
                  f"{host}: {len(foreign)} agent spans carry trace ids "
                  f"no client root issued")
            crossed += len(served)
            shared_rounds += [
                s for s in remote["spans"]
                if s["name"] == "cluster.spmd_execute"
                and len(s.get("member_trace_ids") or []) >= 2]
        # 24 singles + the solo distributed request + ONE coalesced
        # round serving the concurrent pair
        check(crossed >= 26,
              f"only {crossed} spans crossed the process boundary")
        check(len(shared_rounds) == 1
              and set(shared_rounds[0]["member_trace_ids"]) <= root_ids,
              f"expected ONE cluster.spmd_execute span serving both "
              f"paired requests, got {len(shared_rounds)}")

        # -- elastic join: boots warm off the blob tier ----------------
        procs["h2"], ports["h2"] = _spawn_agent(
            "h2", os.path.join(tmp.name, "store-h2"), blob,
            "10,0.9,2,dist", extra_env=agent_env, peers=peers)
        lanes["h2"] = TcpHostLane("h2", ("127.0.0.1", ports["h2"]))
        pod.join(lanes["h2"])
        stats2 = lanes["h2"].rpc_stats()
        check(stats2.get("builds", -1) == 0,
              f"joiner compiled plans instead of booting warm: "
              f"{stats2}")
        for _ in range(6):
            v = (rng.standard_normal(len(trip))
                 + 1j * rng.standard_normal(len(trip)))
            got = np.asarray(pod.submit_backward(sig, v)
                             .result(timeout=120))
            check(np.array_equal(got, np.asarray(plan.backward(v))),
                  "post-join result not bit-exact")
        check(_counter_sum("spfft_cluster_routed_total",
                           host="h2") >= 1,
              "joined host h2 served no traffic")
        check(_counter_sum("spfft_cluster_membership_total",
                           event="joined") >= 1,
              "membership ladder missing the 'joined' event")

        # -- kill -9 one agent: typed failover, bit-exact survivors ----
        epoch_pre = pod.view()["epoch"]
        procs["h1"].kill()
        procs["h1"].wait(timeout=30)
        for _ in range(6):
            v = (rng.standard_normal(len(trip))
                 + 1j * rng.standard_normal(len(trip)))
            got = np.asarray(pod.submit_backward(sig, v)
                             .result(timeout=120))
            check(np.array_equal(got, np.asarray(plan.backward(v))),
                  "survivor result not bit-exact after kill -9")
        check(not lanes["h1"].alive,
              "killed lane h1 still marked alive")
        check(_counter_sum("spfft_cluster_rpc_failures_total",
                           host="h1") >= 1,
              "kill -9 produced no typed RPC failure")
        health = pod.health()
        check(health["state"] == "degraded",
              f"pod not degraded after kill -9: {health['state']}")
        check(tracer.open_count() == 0,
              "unclosed client spans after failover phase")

        # -- self-healing: lease expiry -> evict -> restart -> readmit -
        # The round-21 loop over the real wire, zero operator
        # intervention: the killed agent's lease expires on h0's
        # coordinator (agents heartbeat each other — 300 ms leases off
        # the knob artifact), the eviction bumps the view epoch, a
        # SECOND concurrent frontend observes the SAME epoch/view, the
        # agent restarts on the SAME port, heartbeats itself back into
        # the view, and each frontend's routing-piggybacked probe
        # ladder re-reconciles and readmits it warm (builds == 0 off
        # the blob tier).
        pod2 = PodFrontend(
            [TcpHostLane(h, ("127.0.0.1", ports[h]))
             for h in ("h0", "h2")], seed=seed + 1)
        evicted_view = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            va = pod.view()
            if (va["members"].get("h1", {}).get("state") == "evicted"
                    and va["epoch"] > epoch_pre):
                evicted_view = va
                break
            time.sleep(0.1)
        check(evicted_view is not None,
              "h1's lease never expired into an eviction on the "
              "coordinator (no epoch bump seen by frontend A)")
        vb = pod2.view()
        check(evicted_view is not None
              and vb["epoch"] == evicted_view["epoch"]
              and vb["members"].get("h1", {}).get("state") == "evicted",
              f"frontend B did not converge on the eviction view: "
              f"{vb} vs {evicted_view}")
        # restart the killed agent on the SAME port (fresh store dir:
        # its warm boot must come from the shared blob tier)
        procs["h1"], _ = _spawn_agent(
            "h1", os.path.join(tmp.name, "store-h1-r"), blob,
            "10,0.9,2,full", extra_env=agent_env,
            port=ports["h1"], peers=peers)
        probe_lane = TcpHostLane("h1", ("127.0.0.1", ports["h1"]))
        try:
            check(probe_lane.rpc_stats().get("builds", -1) == 0,
                  "restarted h1 compiled plans instead of booting "
                  "warm off the blob tier")
        finally:
            probe_lane.close()
        # zero operator intervention: routed traffic drives frontend
        # A's probe ladder (it observed the death) until the lane is
        # re-reconciled and readmitted; frontend B keeps serving
        # through it directly
        readmit_deadline = time.monotonic() + 60.0
        while time.monotonic() < readmit_deadline:
            for front in (pod, pod2):
                v = (rng.standard_normal(len(trip))
                     + 1j * rng.standard_normal(len(trip)))
                got = np.asarray(front.submit_backward(sig, v)
                                 .result(timeout=120))
                check(np.array_equal(got,
                                     np.asarray(plan.backward(v))),
                      "request diverged during the readmission window")
            if (_counter_sum("spfft_cluster_readmits_total",
                             host="h1", outcome="readmitted") >= 1):
                break
            time.sleep(0.2)
        check(_counter_sum("spfft_cluster_readmits_total",
                           host="h1", outcome="readmitted") >= 1,
              "the probe ladder never readmitted restarted h1")
        check(lanes["h1"].alive,
              "restarted h1's lane still marked dead after readmission")
        alive_view = pod.view()
        check(alive_view["members"].get("h1", {}).get("state")
              == "alive" and alive_view["epoch"] > evicted_view["epoch"],
              f"readmission did not re-alive h1 with an epoch bump: "
              f"{alive_view}")
        check(pod2.view()["epoch"] == alive_view["epoch"],
              "frontends did not converge after readmission")
        # the resurrected lane must actually serve again, bit-exact
        served_by_h1 = _counter_sum("spfft_cluster_routed_total",
                                    host="h1")
        for _ in range(8):
            v = (rng.standard_normal(len(trip))
                 + 1j * rng.standard_normal(len(trip)))
            got = np.asarray(pod.submit_backward(sig, v)
                             .result(timeout=120))
            check(np.array_equal(got, np.asarray(plan.backward(v))),
                  "post-readmission result not bit-exact")
        check(_counter_sum("spfft_cluster_routed_total",
                           host="h1") > served_by_h1,
              "readmitted h1 received no routes")
        check(tracer.open_count() == 0,
              "unclosed client spans after the self-healing phase")

        # -- drain-leave: the other half of elasticity -----------------
        left = pod.leave("h2")
        check(left["drained"],
              f"leave did not drain h2: {left}")
        for event in ("leave_started", "drained", "left"):
            check(_counter_sum("spfft_cluster_membership_total",
                               event=event) >= 1,
                  f"membership ladder missing the {event!r} event")

        # polite shutdown for the survivors that still listen
        for host in ("h0", "h1", "h2"):
            try:
                lanes[host].rpc_shutdown()
            except Exception:
                pass
    finally:
        if pod2 is not None:
            pod2.close()
        if pod is not None:
            pod.close()
        for lane in lanes.values():
            try:
                lane.close()
            except Exception:
                pass
        for proc in procs.values():
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:
                pass
        _obs.disable()
        tmp.cleanup()

    for msg in failures:
        print(f"pod-smoke FAIL: {msg}")
    if failures:
        return 1
    print(f"pod-smoke: bit-exact across a real TCP pod "
          f"(2 processes + 1 mid-stream join, builds=0 on the joiner, "
          f"a concurrent distributed pair COALESCED into one "
          f"collective round agent-side, kill -9 failover typed, "
          f"then SELF-HEALED: lease expired -> evicted with an epoch "
          f"bump seen by two frontends -> restarted warm off the blob "
          f"tier -> probe ladder readmitted, {crossed} spans crossed "
          f"the process boundary on one trace id each)")
    print("POD SMOKE GREEN")
    return 0


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spfft_tpu.net.smoke",
        description="Two-process pod smoke over localhost TCP.")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    return _run_pod_smoke(args.seed)


if __name__ == "__main__":
    raise SystemExit(main())

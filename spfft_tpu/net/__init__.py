"""Real wire transport for the pod: framed TCP RPC, host agents and
the remote blob artifact tier.

``serve.cluster`` defined the host boundary (the five-RPC
``HostLane`` seam) against an in-process ``LoopbackTransport``; this
package is the same seam crossed by a real socket:

* :mod:`~spfft_tpu.net.frame` — the framed protocol (length-prefixed,
  versioned header, typed JSON records, npz array payloads) plus the
  wire forms of ``PlanSignature``, ``obs.TraceContext`` and the typed
  error taxonomy.
* :mod:`~spfft_tpu.net.transport` — :class:`TcpTransport` (the client
  stub behind the ``cluster.rpc`` fault seam, measuring round-trip
  latency into ``load_score``) and :class:`TcpHostLane`, the drop-in
  remote twin of ``serve.cluster.HostLane``.
* :mod:`~spfft_tpu.net.agent` — :class:`HostAgent`, the server side
  (``python -m spfft_tpu.net.agent``) fronting a local
  ``ServeExecutor``.
* :mod:`~spfft_tpu.net.blobstore` — the object-store-shaped byte
  transport below the disk tier of ``PlanArtifactStore``.
* :mod:`~spfft_tpu.net.smoke` — the two-process localhost pod behind
  ``make pod-smoke``.
"""

from .blobstore import (BlobStore, FileBlobStore, HttpBlobStore,
                        open_blobstore)
from .frame import (FRAME_VERSION, error_from_wire, error_to_wire,
                    pack_values, recv_frame, send_frame,
                    signature_from_wire, signature_to_wire,
                    unpack_values)
from .transport import TcpHostLane, TcpTransport

__all__ = [
    "BlobStore", "FileBlobStore", "HttpBlobStore", "open_blobstore",
    "FRAME_VERSION", "error_from_wire", "error_to_wire",
    "pack_values", "recv_frame", "send_frame", "signature_from_wire",
    "signature_to_wire", "unpack_values",
    "TcpHostLane", "TcpTransport",
]

"""The pod's framed wire protocol.

One frame carries one typed record each way:

.. code-block:: text

    +-------+---------+------------+-------------+--------+---------+
    | MAGIC | VERSION | HEADER_LEN | PAYLOAD_LEN | HEADER | PAYLOAD |
    |  4 B  |   1 B   |  4 B (BE)  |  8 B (BE)   |  JSON  |  bytes  |
    +-------+---------+------------+-------------+--------+---------+

The header is a JSON object whose ``"type"`` field names the record
(``submit``/``signals``/``plan``/... requests, ``result``/``*_ok``/
``error`` responses); the payload is opaque bytes — for transform
values an ``np.savez`` archive (:func:`pack_values` /
:func:`unpack_values`), empty otherwise. Anything malformed — bad
magic, version skew, truncated read, non-JSON header — raises the
typed, transient :class:`~spfft_tpu.errors.NetProtocolError`; the
transport translates it into the ``HostLaneError`` the frontend's
route-around handling keys on.

Cross-host identity rides the header: ``PlanSignature`` as its
``dataclasses.asdict`` form (all plain str/int fields — JSON
round-trips it exactly), ``obs.TraceContext`` as its ``to_wire`` dict
(one trace id end-to-end), and failures as ``{"type": "error",
"error_type": <class name>, "message": ...}`` records that
:func:`error_from_wire` maps back onto the typed taxonomy — a remote
``QueueFullError`` re-raises as ``QueueFullError``, never as a string.

Fault sites: ``net.frame`` fires on each encode/decode, ``net.send``
on the socket send, ``net.recv`` on every socket read (a firing check
is a dropped or truncated frame mid-flight).

**Authentication.** The version byte is the negotiation seam: when
``SPFFT_TPU_NET_SECRET`` is set, frames go out as version 2 with a
32-byte HMAC-SHA256 over header+payload keyed by the shared secret,
inserted between the preamble and the header. A receiver rejects any
mismatch — an authenticated frame it cannot verify, an authenticated
frame when it holds no secret, or a plaintext frame when it requires
auth — with the typed PERMANENT
:class:`~spfft_tpu.errors.NetAuthError` at the door (retrying with
the same secret can never succeed). Unknown versions stay
:class:`NetProtocolError` (protocol skew, transient).
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac as _hmac
import io
import json
import os
import struct
from typing import List, Optional, Tuple, Union

import numpy as np

from .. import faults as _faults
from .. import obs as _obs
from ..errors import GenericError, NetAuthError, NetProtocolError
from ..serve.registry import PlanSignature

MAGIC = b"SPFN"
FRAME_VERSION = 1
#: The authenticated protocol: preamble carries version 2 and a
#: 32-byte HMAC-SHA256(secret, header+payload) precedes the header.
FRAME_VERSION_AUTH = 2

#: Env var holding the pod's shared wire secret; empty/unset = the
#: plaintext version-1 protocol.
NET_SECRET_ENV = "SPFFT_TPU_NET_SECRET"

_MAC_BYTES = 32
_UNSET = object()

#: Preamble layout: magic, version, header length, payload length.
_PREAMBLE = struct.Struct(">4sBIQ")

#: Sanity caps a hostile/corrupt preamble cannot exceed (a truncated
#: length field must reject, not allocate gigabytes).
MAX_HEADER_BYTES = 1 << 22
MAX_PAYLOAD_BYTES = 1 << 33

_RECV_CHUNK = 1 << 16


def net_secret() -> Optional[bytes]:
    """The process's shared wire secret (``SPFFT_TPU_NET_SECRET``),
    or None for the plaintext protocol."""
    raw = os.environ.get(NET_SECRET_ENV, "")
    return raw.encode("utf-8") if raw else None


def _frame_mac(secret: bytes, hbytes: bytes, payload: bytes) -> bytes:
    mac = _hmac.new(secret, hbytes, hashlib.sha256)
    mac.update(payload)
    return mac.digest()


def send_frame(sock, header: dict, payload: bytes = b"",
               secret=_UNSET) -> None:
    """Encode and send one frame. Socket errors propagate as
    ``OSError`` (the transport classifies them); a header that cannot
    serialize is a :class:`NetProtocolError`. With a shared secret
    (``secret=`` override, else ``SPFFT_TPU_NET_SECRET``) the frame
    goes out authenticated as version 2."""
    _faults.check_site("net.frame")
    if secret is _UNSET:
        secret = net_secret()
    try:
        hbytes = json.dumps(header).encode("utf-8")
    except (TypeError, ValueError) as exc:
        raise NetProtocolError(
            f"frame header is not JSON-serializable: {exc}") from exc
    if secret:
        data = b"".join([
            _PREAMBLE.pack(MAGIC, FRAME_VERSION_AUTH, len(hbytes),
                           len(payload)),
            _frame_mac(secret, hbytes, payload), hbytes, payload])
    else:
        data = b"".join([
            _PREAMBLE.pack(MAGIC, FRAME_VERSION, len(hbytes),
                           len(payload)),
            hbytes, payload])
    _faults.check_site("net.send")
    sock.sendall(data)
    _obs.GLOBAL_COUNTERS.inc("spfft_net_frames_total", dir="send")
    _obs.GLOBAL_COUNTERS.inc("spfft_net_bytes_total", len(data),
                             dir="send")


def _recv_exact(sock, n: int, what: str) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        _faults.check_site("net.recv")
        chunk = sock.recv(min(_RECV_CHUNK, n - len(buf)))
        if not chunk:
            raise NetProtocolError(
                f"connection closed mid-frame reading {what} "
                f"({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock, eof_ok: bool = False, secret=_UNSET
               ) -> Optional[Tuple[dict, bytes]]:
    """Receive one frame: ``(header, payload)``. A clean EOF before the
    first byte returns None when ``eof_ok`` (the agent's
    end-of-connection); everything else malformed raises
    :class:`NetProtocolError`. Authentication mismatches — see the
    module docstring — raise the permanent :class:`NetAuthError`."""
    _faults.check_site("net.recv")
    first = sock.recv(1)
    if not first:
        if eof_ok:
            return None
        raise NetProtocolError("connection closed before a frame")
    pre = first + _recv_exact(sock, _PREAMBLE.size - 1,
                              "frame preamble")
    magic, version, hlen, plen = _PREAMBLE.unpack(pre)
    if magic != MAGIC:
        raise NetProtocolError(f"bad frame magic {magic!r}")
    if version not in (FRAME_VERSION, FRAME_VERSION_AUTH):
        raise NetProtocolError(
            f"frame version {version} != {FRAME_VERSION} (protocol "
            f"skew across the pod)")
    if hlen > MAX_HEADER_BYTES or plen > MAX_PAYLOAD_BYTES:
        raise NetProtocolError(
            f"frame lengths implausible (header {hlen}, payload "
            f"{plen})")
    if secret is _UNSET:
        secret = net_secret()
    mac = None
    if version == FRAME_VERSION_AUTH:
        mac = _recv_exact(sock, _MAC_BYTES, "frame mac")
    hbytes = _recv_exact(sock, hlen, "frame header")
    payload = _recv_exact(sock, plen, "frame payload") if plen else b""
    if version == FRAME_VERSION_AUTH:
        if not secret:
            raise NetAuthError(
                "peer sent an authenticated frame but this endpoint "
                "holds no SPFFT_TPU_NET_SECRET")
        if not _hmac.compare_digest(
                mac, _frame_mac(secret, hbytes, payload)):
            raise NetAuthError(
                "frame HMAC does not verify — shared-secret mismatch "
                "across the pod")
    elif secret:
        raise NetAuthError(
            "peer sent a plaintext frame but this endpoint requires "
            "authentication (SPFFT_TPU_NET_SECRET is set)")
    _faults.check_site("net.frame")
    try:
        header = json.loads(hbytes)
    except ValueError as exc:
        raise NetProtocolError(
            f"frame header is not JSON: {exc}") from exc
    if not isinstance(header, dict) or "type" not in header:
        raise NetProtocolError("frame header lacks a 'type' field")
    _obs.GLOBAL_COUNTERS.inc("spfft_net_frames_total", dir="recv")
    _obs.GLOBAL_COUNTERS.inc("spfft_net_bytes_total",
                             _PREAMBLE.size + hlen + plen, dir="recv")
    return header, payload


# -- array payloads ----------------------------------------------------------
def pack_values(values: Union[None, np.ndarray, List]
                ) -> Tuple[dict, bytes]:
    """``(meta, payload)`` for a transform's values: a single array or
    a list of per-shard arrays (distributed requests/results), packed
    as an ``np.savez`` archive. Merge ``meta`` into the frame header;
    :func:`unpack_values` reverses it."""
    if values is None:
        return {"values": "none"}, b""
    buf = io.BytesIO()
    if isinstance(values, (list, tuple)):
        arrays = [np.asarray(v) for v in values]
        np.savez(buf, **{f"a{i}": a for i, a in enumerate(arrays)})
        return {"values": "list", "n": len(arrays)}, buf.getvalue()
    np.savez(buf, a0=np.asarray(values))
    return {"values": "single", "n": 1}, buf.getvalue()


def unpack_values(meta: dict, payload: bytes):
    """The values packed by :func:`pack_values`, or raise the typed
    :class:`NetProtocolError` when the archive does not decode."""
    kind = meta.get("values", "none")
    if kind == "none":
        return None
    if kind not in ("single", "list"):
        raise NetProtocolError(f"unknown values kind {kind!r}")
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            arrays = [np.asarray(z[f"a{i}"])
                      for i in range(int(meta.get("n", 1)))]
    except Exception as exc:
        raise NetProtocolError(
            f"array payload failed to decode: {exc!r}") from exc
    return arrays if kind == "list" else arrays[0]


# -- signatures --------------------------------------------------------------
def signature_to_wire(sig: PlanSignature) -> dict:
    """``PlanSignature`` -> plain dict (all fields str/int, so JSON
    round-trips it losslessly)."""
    return dataclasses.asdict(sig)


def signature_from_wire(payload: dict) -> PlanSignature:
    try:
        return PlanSignature(**payload)
    except TypeError as exc:
        raise NetProtocolError(
            f"malformed wire signature: {exc}") from exc


# -- typed errors over the wire ----------------------------------------------
#: Non-package types :func:`error_from_wire` restores exactly — the
#: request-shaped builtins ``faults.REQUEST_ERROR_TYPES`` classifies.
_WIRE_BUILTINS = {t.__name__: t for t in
                  (TypeError, ValueError, IndexError, KeyError,
                   TimeoutError)}


def error_to_wire(exc: BaseException) -> dict:
    """The error-record header for one failure (the agent's reply when
    a handler raises)."""
    return {"type": "error", "error_type": type(exc).__name__,
            "message": str(exc)}


def error_from_wire(header: dict) -> BaseException:
    """An exception INSTANCE for an error record, mapped back onto the
    typed taxonomy: an ``errors.py`` class by name, a request-shaped
    builtin, or ``GenericError`` for anything unknown (still typed —
    a remote failure never surfaces as a bare string or a raw
    foreign type)."""
    from .. import errors as _errors
    name = str(header.get("error_type", ""))
    message = str(header.get("message", ""))
    cls = getattr(_errors, name, None)
    if cls is None:
        cls = getattr(_faults, name, None)
    if isinstance(cls, type) and issubclass(cls, GenericError):
        try:
            return cls(message)
        except Exception:  # an exotic constructor signature
            return GenericError(f"{name}: {message}")
    if name in _WIRE_BUILTINS:
        return _WIRE_BUILTINS[name](message)
    return GenericError(f"remote {name or 'failure'}: {message}")

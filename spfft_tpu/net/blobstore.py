"""Object-store-shaped byte transport: the remote artifact tier.

``PlanArtifactStore`` keeps two local tiers (in-memory LRU via the
registry, on-disk artifacts); this module is the tier below disk —
an abstract ``get/put/list`` byte surface an autoscaled worker boots
warm from, because the fleet's shared artifact set outlives any one
host's volume. Two backends behind :class:`BlobStore`:

* :class:`FileBlobStore` — a shared directory (NFS-mount-shaped);
  atomic writes, missing key -> ``None``.
* :class:`HttpBlobStore` — a minimal HTTP object store (GET/PUT, 404
  = miss) over ``http.client``; :func:`serve_blobstore` /
  ``python -m spfft_tpu.net.blobstore --serve`` runs the matching
  local server over a :class:`FileBlobStore` root.

The store consumes these VERBATIM bytes through the same
``parse_artifact`` digest/version gauntlet as a disk read — a corrupt
or stale remote artifact rejects with the same typed taxonomy, never
loads. Failures raise the typed
:class:`~spfft_tpu.errors.BlobStoreError` (the artifact store treats
it as a remote miss); ``blob.get``/``blob.put`` are the package fault
sites. Every operation lands in
``spfft_blob_ops_total{op,outcome}``.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import urllib.parse
from typing import List, Optional

from .. import faults as _faults
from .. import obs as _obs
from ..errors import BlobStoreError, InvalidParameterError
from ..faults import InjectedFault

#: Default per-operation HTTP timeout (seconds). Deliberately short:
#: the remote tier is an optimisation — a wedged object store must
#: degrade to a miss quickly, not stall a plan load.
HTTP_TIMEOUT_S = 10.0


def _count(op: str, outcome: str) -> None:
    _obs.GLOBAL_COUNTERS.inc("spfft_blob_ops_total", op=op,
                             outcome=outcome)


class BlobStore:
    """The abstract byte surface: ``get(key) -> bytes | None`` (None =
    miss), ``put(key, data)``, ``list() -> [key]``, plus the GC half —
    ``stat(key) -> {"size", "mtime"} | None`` and
    ``delete(key) -> bool`` (False = already gone). Keys are relative
    slash-separated paths (the store uses ``art/<key>`` and
    ``req/<rkey>`` namespaces)."""

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def list(self) -> List[str]:
        raise NotImplementedError

    def stat(self, key: str) -> Optional[dict]:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError


def _validate_key(key: str) -> str:
    if not key or key.startswith(("/", ".")) or ".." in key \
            or "\\" in key:
        raise InvalidParameterError(f"bad blob key {key!r}")
    return key


class FileBlobStore(BlobStore):
    """A directory as an object store — the shared-volume backend (and
    what the HTTP server fronts)."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, *(_validate_key(key).split("/")))

    def get(self, key: str) -> Optional[bytes]:
        path = self._path(key)
        try:
            _faults.check_site("blob.get")
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            _count("get", "miss")
            return None
        except (OSError, InjectedFault) as exc:
            _count("get", "error")
            raise BlobStoreError(
                f"blob get {key!r} failed: {exc}") from exc
        _count("get", "hit")
        return data

    def put(self, key: str, data: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            _faults.check_site("blob.put")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except (OSError, InjectedFault) as exc:
            _count("put", "error")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise BlobStoreError(
                f"blob put {key!r} failed: {exc}") from exc
        _count("put", "ok")

    def list(self) -> List[str]:
        out = []
        for dirpath, _, names in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            for name in names:
                if ".tmp-" in name:
                    continue
                key = name if rel == "." else f"{rel}/{name}"
                out.append(key.replace(os.sep, "/"))
        return sorted(out)

    def stat(self, key: str) -> Optional[dict]:
        try:
            st = os.stat(self._path(key))
        except FileNotFoundError:
            return None
        except OSError as exc:
            raise BlobStoreError(
                f"blob stat {key!r} failed: {exc}") from exc
        return {"size": st.st_size, "mtime": st.st_mtime}

    def delete(self, key: str) -> bool:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            return False
        except OSError as exc:
            raise BlobStoreError(
                f"blob delete {key!r} failed: {exc}") from exc
        return True


class HttpBlobStore(BlobStore):
    """A minimal HTTP object store client: ``GET /<key>`` (404 = miss),
    ``PUT /<key>``, ``GET /?list=1`` -> JSON key array. One connection
    per operation — robust against a restarted server, and the remote
    tier is far off any hot path."""

    def __init__(self, url: str, timeout: float = HTTP_TIMEOUT_S):
        parsed = urllib.parse.urlsplit(url)
        if parsed.scheme != "http" or not parsed.netloc:
            raise InvalidParameterError(
                f"HttpBlobStore needs an http:// URL, got {url!r}")
        self.url = url
        self._host = parsed.hostname
        self._port = parsed.port or 80
        self._base = parsed.path.rstrip("/")
        self._timeout = float(timeout)

    def _request(self, method: str, key: str,
                 body: Optional[bytes] = None, query: str = ""):
        path = f"{self._base}/{urllib.parse.quote(key)}" if key \
            else f"{self._base}/?list=1"
        if key and query:
            path = f"{path}?{query}"
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=self._timeout)
        try:
            conn.request(method, path, body=body)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def get(self, key: str) -> Optional[bytes]:
        _validate_key(key)
        try:
            _faults.check_site("blob.get")
            status, data = self._request("GET", key)
        except (OSError, InjectedFault) as exc:
            _count("get", "error")
            raise BlobStoreError(
                f"blob get {key!r} failed: {exc}") from exc
        if status == 404:
            _count("get", "miss")
            return None
        if status != 200:
            _count("get", "error")
            raise BlobStoreError(
                f"blob get {key!r} answered HTTP {status}")
        _count("get", "hit")
        return data

    def put(self, key: str, data: bytes) -> None:
        _validate_key(key)
        try:
            _faults.check_site("blob.put")
            status, _ = self._request("PUT", key, body=data)
        except (OSError, InjectedFault) as exc:
            _count("put", "error")
            raise BlobStoreError(
                f"blob put {key!r} failed: {exc}") from exc
        if status not in (200, 201, 204):
            _count("put", "error")
            raise BlobStoreError(
                f"blob put {key!r} answered HTTP {status}")
        _count("put", "ok")

    def list(self) -> List[str]:
        try:
            status, data = self._request("GET", "")
        except OSError as exc:
            raise BlobStoreError(f"blob list failed: {exc}") from exc
        if status != 200:
            raise BlobStoreError(f"blob list answered HTTP {status}")
        try:
            keys = json.loads(data)
        except ValueError as exc:
            raise BlobStoreError(
                f"blob list is not JSON: {exc}") from exc
        return [str(k) for k in keys]

    def stat(self, key: str) -> Optional[dict]:
        _validate_key(key)
        try:
            status, data = self._request("GET", key, query="stat=1")
        except OSError as exc:
            raise BlobStoreError(
                f"blob stat {key!r} failed: {exc}") from exc
        if status == 404:
            return None
        if status != 200:
            raise BlobStoreError(
                f"blob stat {key!r} answered HTTP {status}")
        try:
            row = json.loads(data)
            return {"size": int(row["size"]),
                    "mtime": float(row["mtime"])}
        except (ValueError, KeyError, TypeError) as exc:
            raise BlobStoreError(
                f"blob stat {key!r} is malformed: {exc}") from exc

    def delete(self, key: str) -> bool:
        _validate_key(key)
        try:
            status, _ = self._request("DELETE", key)
        except OSError as exc:
            raise BlobStoreError(
                f"blob delete {key!r} failed: {exc}") from exc
        if status == 404:
            return False
        if status not in (200, 204):
            raise BlobStoreError(
                f"blob delete {key!r} answered HTTP {status}")
        return True


def open_blobstore(spec: Optional[str]) -> Optional[BlobStore]:
    """Resolve a blob-store spec: empty/None -> no remote tier,
    ``http://...`` -> :class:`HttpBlobStore`, anything else -> a
    :class:`FileBlobStore` directory."""
    if not spec:
        return None
    if spec.startswith("http://"):
        return HttpBlobStore(spec)
    return FileBlobStore(spec)


def gc_blobstore(store: BlobStore, max_bytes: int,
                 prefix: str = "req/") -> dict:
    """Bound the remote tier's ``prefix`` namespace (default: the
    ``req/`` request journal, which grows per served signature and has
    no local-tier GC) to ``max_bytes`` by an oldest-mtime-first sweep
    — the same eviction order as the disk tier's ``store gc``.
    ``max_bytes <= 0`` means unbounded: nothing is swept (counted
    ``skipped``). Per-key failures are typed and NON-FATAL: a
    concurrently-deleted or unreachable key counts
    ``spfft_blob_gc_total{outcome="error"}`` and the sweep continues —
    GC is an optimisation, never an availability risk. Returns
    ``{"removed": [keys], "bytes_in_use": n, "errors": n}``."""
    if max_bytes is None or int(max_bytes) <= 0:
        _obs.GLOBAL_COUNTERS.inc("spfft_blob_gc_total",
                                 outcome="skipped")
        return {"removed": [], "bytes_in_use": None, "errors": 0}
    rows = []
    errors = 0
    for key in store.list():
        if not key.startswith(prefix):
            continue
        try:
            st = store.stat(key)
        except BlobStoreError:
            errors += 1
            _obs.GLOBAL_COUNTERS.inc("spfft_blob_gc_total",
                                     outcome="error")
            continue
        if st is not None:
            rows.append((float(st["mtime"]), key, int(st["size"])))
    rows.sort()  # oldest first
    in_use = sum(size for _, _, size in rows)
    removed: List[str] = []
    for mtime, key, size in rows:
        if in_use <= int(max_bytes):
            break
        try:
            if store.delete(key):
                removed.append(key)
                _obs.GLOBAL_COUNTERS.inc("spfft_blob_gc_total",
                                         outcome="removed")
            in_use -= size
        except BlobStoreError:
            errors += 1
            _obs.GLOBAL_COUNTERS.inc("spfft_blob_gc_total",
                                     outcome="error")
    return {"removed": removed, "bytes_in_use": in_use,
            "errors": errors}


# -- the matching local HTTP server ------------------------------------------
def serve_blobstore(root: str, bind: str = "127.0.0.1",
                    port: int = 0):
    """Run an HTTP object store over ``root`` on a daemon thread:
    ``(server, thread)``; the bound port is ``server.server_port``."""
    import http.server

    store = FileBlobStore(root)

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet: tests/smokes drive this
            pass

        def _key(self) -> str:
            return urllib.parse.unquote(
                urllib.parse.urlsplit(self.path).path.lstrip("/"))

        def do_GET(self):
            parsed = urllib.parse.urlsplit(self.path)
            if not parsed.path.strip("/"):
                body = json.dumps(store.list()).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if ("stat", "1") in urllib.parse.parse_qsl(parsed.query):
                try:
                    row = store.stat(self._key())
                except (BlobStoreError, InvalidParameterError):
                    self.send_response(500)
                    self.end_headers()
                    return
                if row is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(row).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                data = store.get(self._key())
            except (BlobStoreError, InvalidParameterError):
                self.send_response(500)
                self.end_headers()
                return
            if data is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_DELETE(self):
            try:
                removed = store.delete(self._key())
            except (BlobStoreError, InvalidParameterError):
                self.send_response(500)
                self.end_headers()
                return
            self.send_response(204 if removed else 404)
            self.end_headers()

        def do_PUT(self):
            length = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(length)
            try:
                store.put(self._key(), data)
            except (BlobStoreError, InvalidParameterError):
                self.send_response(500)
                self.end_headers()
                return
            self.send_response(204)
            self.end_headers()

    server = http.server.ThreadingHTTPServer((bind, port), Handler)
    thread = threading.Thread(target=server.serve_forever,
                              daemon=True, name="spfft-blob-server")
    thread.start()
    return server, thread


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m spfft_tpu.net.blobstore",
        description="Serve a directory as the pod's remote artifact "
                    "tier over HTTP.")
    ap.add_argument("--serve", metavar="ROOT", required=True,
                    help="FileBlobStore root directory to serve")
    ap.add_argument("--bind", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    server, thread = serve_blobstore(args.serve, args.bind, args.port)
    print(json.dumps({"blobstore": args.serve,
                      "port": server.server_port}), flush=True)
    try:
        thread.join()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

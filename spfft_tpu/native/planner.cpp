// Native index planner: sparse frequency triplets -> z-stick tables.
//
// C++ implementation of the semantics of the reference index conversion
// (reference: src/compression/indices.hpp:120-186 convert_index_triplets,
// :49-55 to_storage_index) — the plan-time hot loop of the framework. The
// NumPy path in spfft_tpu/indexing.py is the fallback and the executable
// specification; this library exists because planning a 256^3 spherical
// cutoff (8.8M triplets) takes seconds through generic sort-based
// np.unique, while the dense bitmap-rank algorithm here is O(n + dimX*dimY)
// and runs in tens of milliseconds.
//
// Exposed via a plain C ABI loaded with ctypes (no pybind11 in this image).

#include <algorithm>
#include <climits>
#include <cstdint>
#include <cstring>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// Error codes mirrored in spfft_tpu/native/__init__.py.
constexpr int64_t kErrInvalidBounds = -1;
constexpr int64_t kErrTooManyValues = -2;
// Allocation failure / grid too large for the dense-bitmap algorithm — the
// caller falls back to the NumPy path (no C++ exception may cross the C ABI).
constexpr int64_t kErrNoNativePath = -3;

}  // namespace

extern "C" {

// Convert (n, 3) int64 row-major triplets into per-value flat indices and
// the ascending unique stick-key list.
//
// Outputs:
//   value_indices[n]  int32 : stick_id * dim_z + z_storage  per value
//   stick_keys[n]     int32 : first num_sticks entries hold the ascending
//                             unique keys x_storage * dim_y + y_storage
//   centered_out      int32 : 1 if any index was negative
// Returns num_sticks (>= 0) or a negative error code.
int64_t spfft_tpu_plan_indices(int32_t hermitian, int64_t dim_x,
                               int64_t dim_y, int64_t dim_z,
                               const int64_t* xyz, int64_t n,
                               int32_t* value_indices, int32_t* stick_keys,
                               int32_t* centered_out) {
  if (n > dim_x * dim_y * dim_z) return kErrTooManyValues;

  // Pass 1: centered detection (any negative index, indices.hpp:129-135).
  bool centered = false;
#pragma omp parallel for reduction(|| : centered) schedule(static)
  for (int64_t i = 0; i < 3 * n; ++i) centered = centered || (xyz[i] < 0);
  *centered_out = centered ? 1 : 0;

  // Bounds, exactly as reference indices.hpp:137-149.
  const int64_t max_x = (hermitian || centered ? dim_x / 2 + 1 : dim_x) - 1;
  const int64_t max_y = (centered ? dim_y / 2 + 1 : dim_y) - 1;
  const int64_t max_z = (centered ? dim_z / 2 + 1 : dim_z) - 1;
  const int64_t min_x = hermitian ? 0 : max_x - dim_x + 1;
  const int64_t min_y = max_y - dim_y + 1;
  const int64_t min_z = max_z - dim_z + 1;

  const int64_t plane = dim_x * dim_y;
  std::vector<uint8_t> present;
  std::vector<int32_t> rank;
  try {
    present.assign(static_cast<size_t>(plane), 0);
    rank.resize(static_cast<size_t>(plane));
  } catch (...) {
    return kErrNoNativePath;
  }

  // Pass 2: bounds check + mark present stick keys. Benign write races on
  // the bitmap (all writers store 1).
  bool oob = false;
#pragma omp parallel for reduction(|| : oob) schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int64_t x = xyz[3 * i], y = xyz[3 * i + 1], z = xyz[3 * i + 2];
    if (x < min_x || x > max_x || y < min_y || y > max_y || z < min_z ||
        z > max_z) {
      oob = true;
      continue;
    }
    const int64_t xs = x < 0 ? x + dim_x : x;
    const int64_t ys = y < 0 ? y + dim_y : y;
    // Relaxed atomic store: many threads may mark the same key; all store 1.
    __atomic_store_n(&present[static_cast<size_t>(xs * dim_y + ys)],
                     static_cast<uint8_t>(1), __ATOMIC_RELAXED);
  }
  if (oob) return kErrInvalidBounds;

  // Pass 3: rank present keys in ascending order (the ordered-map semantics
  // of indices.hpp:152-165, without the map).
  int32_t num_sticks = 0;
  for (int64_t k = 0; k < plane; ++k) {
    if (present[static_cast<size_t>(k)]) {
      rank[static_cast<size_t>(k)] = num_sticks;
      stick_keys[num_sticks++] = static_cast<int32_t>(k);
    }
  }

  // Pass 4: per-value flat index stick_id * dim_z + z (indices.hpp:168-176).
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int64_t x = xyz[3 * i], y = xyz[3 * i + 1], z = xyz[3 * i + 2];
    const int64_t xs = x < 0 ? x + dim_x : x;
    const int64_t ys = y < 0 ? y + dim_y : y;
    const int64_t zs = z < 0 ? z + dim_z : z;
    value_indices[i] = static_cast<int32_t>(
        static_cast<int64_t>(rank[static_cast<size_t>(xs * dim_y + ys)]) *
            dim_z +
        zs);
  }
  return num_sticks;
}

// Inverse maps (indexing.inverse_slot_map / inverse_col_map): scatter of
// iota, included so the whole plan build can run natively. The scatter loop
// is serial so that duplicate indices resolve to the *last* occurrence,
// matching the NumPy fallback's fancy-assignment semantics. Returns 0, or
// -1 if any index is out of [0, num_slots).
int32_t spfft_tpu_inverse_map(const int32_t* indices, int64_t n,
                              int32_t* out, int64_t num_slots,
                              int32_t sentinel) {
  bool oob = false;
#pragma omp parallel for reduction(|| : oob) schedule(static)
  for (int64_t i = 0; i < n; ++i)
    oob = oob || indices[i] < 0 || indices[i] >= num_slots;
  if (oob) return -1;
#pragma omp parallel for schedule(static)
  for (int64_t s = 0; s < num_slots; ++s) out[s] = sentinel;
  for (int64_t i = 0; i < n; ++i) out[indices[i]] = static_cast<int32_t>(i);
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Wide-gather table builder (ops/gather_kernel.build_wide_gather_tables).
//
// The NumPy builder is the executable specification; this native version
// exists because the vectorised multi-round cover makes ~20 full passes
// over (G_s, P, 1024) arrays (122 s at 512^3 — 105M slots). Here every
// super-tile is covered independently (sequential rounds over its 8192
// slots), parallel over super-tiles: one pass, cache-resident.
//
// Geometry and cover semantics replicate the Python builder EXACTLY (the
// parity test builds both and compares every table): padding with the last
// index / valid=false, the kp cost model over the candidate list, the
// linear-interpolation 0.99 quantile for K, byte-packed sub offsets,
// lane | row << 7 | valid << 12 int16 packed words, first=1 on a
// super-tile's round-0 chunk, and the 16 * G_s + 64 chunk blowup limit.

namespace {

constexpr int kTile = 1024;
constexpr int kLane = 128;
constexpr int32_t kBig = INT32_C(1) << 30;

struct WideGeom {
  int P;
  int kp;
  int K;
  int64_t G_s;
  int32_t r_clamp;  // max allowed row0 (kBig = unclamped): keeps every
                    // DMA window inside ceil(num_src/128) source rows so
                    // the runtime needs no source zero-padding pass
};

// Per-super-tile cover: returns the chunk count; when fill outputs are
// non-null, also writes row0 / sub words / packed for each chunk emitted
// (chunks for this super-tile start at chunk offset base_c).
int64_t cover_super_tile(const int64_t* idx_p, const uint8_t* valid_p,
                         int64_t st, const WideGeom& g, int64_t limit,
                         int32_t* row0_out, int32_t* sub_out,
                         int16_t* packed_out, int64_t base_c) {
  const int P = g.P, kp = g.kp, K = g.K;
  const int64_t s0 = st * P * kTile;
  // Uncovered = valid (invalid slots never need covering).
  bool uncovered[8][kTile];  // P <= 8 enforced at the ABI
  bool any_unc = false;
  for (int p = 0; p < P; ++p)
    for (int t = 0; t < kTile; ++t) {
      bool u = valid_p[s0 + p * kTile + t] != 0;
      uncovered[p][t] = u;
      any_unc = any_unc || u;
    }
  int64_t c = 0;
  for (int round = 0;; ++round) {
    if (round > 0 && !any_unc) break;
    if (c >= limit) return limit + 1;  // caller treats as blowup
    // base[p] = min uncovered row
    int32_t base[8];
    bool hasu[8];
    int32_t r0 = kBig;
    for (int p = 0; p < P; ++p) {
      int32_t b = kBig;
      for (int t = 0; t < kTile; ++t)
        if (uncovered[p][t]) {
          int32_t r = static_cast<int32_t>(idx_p[s0 + p * kTile + t] / kLane);
          if (r < b) b = r;
        }
      base[p] = b;
      hasu[p] = b != kBig;
      if (b < r0) r0 = b;
    }
    if (r0 == kBig) r0 = 0;
    if (r0 > g.r_clamp) r0 = g.r_clamp;
    bool inwin[8];
    int32_t basec[8];
    for (int p = 0; p < P; ++p) {
      // sub-window saturates at the window top so tail rows stay
      // coverable when r0 is clamped (see r_clamp)
      inwin[p] = hasu[p] && base[p] <= r0 + (K - 1);
      basec[p] = inwin[p] ? std::min(base[p], r0 + (K - kp)) : r0;
    }
    if (row0_out != nullptr) {
      const int64_t cc = base_c + c;
      row0_out[cc] = r0;
      for (int w = 0; w < P / 4; ++w) {
        int32_t word = 0;
        for (int j = 0; j < 4; ++j) {
          int p = 4 * w + j;
          int32_t rel = basec[p] - r0;
          if (rel < 0) rel = 0;
          if (rel > K - kp) rel = K - kp;
          word |= rel << (8 * j);
        }
        sub_out[cc * (P / 4) + w] = word;
      }
      int16_t* pk = packed_out + cc * int64_t(P) * kTile;
      for (int p = 0; p < P; ++p)
        for (int t = 0; t < kTile; ++t) {
          const int64_t v = idx_p[s0 + p * kTile + t];
          const int32_t lane = static_cast<int32_t>(v % kLane);
          int32_t rin = static_cast<int32_t>(v / kLane) - basec[p];
          if (rin < 0) rin = 0;
          if (rin > kp - 1) rin = kp - 1;
          const bool cov =
              uncovered[p][t] && inwin[p] &&
              static_cast<int32_t>(v / kLane) >= basec[p] &&
              static_cast<int32_t>(v / kLane) < basec[p] + kp;
          pk[p * kTile + t] = static_cast<int16_t>(
              lane | (rin << 7) | ((cov ? 1 : 0) << 12));
        }
    }
    // Un-cover
    any_unc = false;
    for (int p = 0; p < P; ++p) {
      if (!inwin[p]) {
        for (int t = 0; t < kTile; ++t)
          any_unc = any_unc || uncovered[p][t];
        continue;
      }
      for (int t = 0; t < kTile; ++t)
        if (uncovered[p][t]) {
          const int32_t r =
              static_cast<int32_t>(idx_p[s0 + p * kTile + t] / kLane);
          if (r >= basec[p] && r < basec[p] + kp)
            uncovered[p][t] = false;
          else
            any_unc = true;
        }
    }
    ++c;
  }
  return c;
}

}  // namespace

extern "C" {

// Phase 1: choose geometry + count chunks.
//
// idx[L] int64 (any order), valid[L] uint8; P must be 8 and a multiple of
// 4. kp_in / k_in force the sub-window / DMA-window heights (0 = choose
// from the data, replicating the Python cost model / quantile). On
// success writes kp/K/C and returns 0; returns -1 when the cover exceeds
// the blowup limit (caller falls back), -2 on invalid arguments.
int32_t spfft_tpu_wide_tables_plan(const int64_t* idx, const uint8_t* valid,
                                   int64_t L, int64_t num_src, int32_t P,
                                   int32_t kp_in, int32_t k_in,
                                   int32_t* kp_out, int32_t* k_out,
                                   int64_t* c_out) {
  if (L <= 0 || P != 8) return -2;
  const int64_t SUPER = int64_t(P) * kTile;
  const int64_t G_s = (L + SUPER - 1) / SUPER;
  const int64_t Lp = G_s * SUPER;

  // Padded copies (pad index = last index, pad valid = 0).
  std::vector<int64_t> idx_p(Lp);
  std::vector<uint8_t> valid_p(Lp);
  std::memcpy(idx_p.data(), idx, sizeof(int64_t) * L);
  std::memcpy(valid_p.data(), valid, L);
  for (int64_t i = L; i < Lp; ++i) {
    idx_p[i] = idx[L - 1];
    valid_p[i] = 0;
  }

  // Per-tile spread / base stats (valid slots only).
  std::vector<int32_t> spread(G_s * P), rmin(G_s * P);
  std::vector<uint8_t> has(G_s * P);
#pragma omp parallel for schedule(static)
  for (int64_t tp = 0; tp < G_s * P; ++tp) {
    int32_t lo = kBig, hi = -1;
    const int64_t s0 = tp * kTile;
    for (int t = 0; t < kTile; ++t)
      if (valid_p[s0 + t]) {
        const int32_t r = static_cast<int32_t>(idx_p[s0 + t] / kLane);
        if (r < lo) lo = r;
        if (r > hi) hi = r;
      }
    has[tp] = hi >= 0;
    rmin[tp] = lo;
    spread[tp] = hi >= 0 ? hi - lo + 1 : 1;
  }

  int kp = kp_in;
  if (kp == 0) {
    // cost(kp) = C_est * (P*kp + 64), C_est = sum of per-super-tile max
    // round counts (gather_kernel.WIDE_KP_CANDIDATES).
    const int cands[5] = {8, 12, 16, 24, 32};
    int64_t best_cost = INT64_MAX;
    for (int cand : cands) {
      int64_t c_est = 0;
#pragma omp parallel for reduction(+ : c_est) schedule(static)
      for (int64_t st = 0; st < G_s; ++st) {
        int32_t mx = 1;
        for (int p = 0; p < P; ++p) {
          const int32_t r = (spread[st * P + p] + cand - 1) / cand;
          if (r > mx) mx = r;
        }
        c_est += mx;
      }
      const int64_t cost = c_est * (int64_t(P) * cand + 64);
      if (cost < best_cost) {
        best_cost = cost;
        kp = cand;
      }
    }
  }
  if (kp < 1 || kp > 32) return -2;

  int K = k_in;
  if (K == 0) {
    // bspan quantile 0.99 with linear interpolation (np.quantile).
    std::vector<int32_t> bspan(G_s);
#pragma omp parallel for schedule(static)
    for (int64_t st = 0; st < G_s; ++st) {
      int32_t b0 = kBig, mx = 0;
      for (int p = 0; p < P; ++p)
        if (has[st * P + p] && rmin[st * P + p] < b0)
          b0 = rmin[st * P + p];
      for (int p = 0; p < P; ++p)
        if (has[st * P + p] && rmin[st * P + p] - b0 > mx)
          mx = rmin[st * P + p] - b0;
      bspan[st] = mx;
    }
    std::sort(bspan.begin(), bspan.end());
    double q;
    if (G_s == 1) {
      q = bspan[0];
    } else {
      const double pos = 0.99 * double(G_s - 1);
      const int64_t i0 = static_cast<int64_t>(pos);
      const double frac = pos - double(i0);
      q = bspan[i0] +
          frac * (bspan[std::min(i0 + 1, G_s - 1)] - bspan[i0]);
    }
    const int64_t qi = static_cast<int64_t>(q);  // int(np.quantile(...))
    int64_t k64 = (qi + kp + 7) / 8 * 8;
    if (k64 > 512) k64 = 512;
    if (k64 > kp + 248) k64 = kp + 248;
    if (k64 < kp + 8) k64 = kp + 8;
    K = static_cast<int32_t>(k64);
  }
  if (K - kp > 255) K = kp + 248;

  int32_t r_clamp = kBig;
  const int64_t r_exact = (num_src + kLane - 1) / kLane;
  if (num_src > 0 && r_exact >= K)
    r_clamp = static_cast<int32_t>(r_exact - K);
  const WideGeom geom{P, kp, K, G_s, r_clamp};
  const int64_t limit = 16 * G_s + 64;
  std::vector<int64_t> counts(G_s);
  bool blowup = false;
#pragma omp parallel for reduction(|| : blowup) schedule(dynamic, 16)
  for (int64_t st = 0; st < G_s; ++st) {
    counts[st] = cover_super_tile(idx_p.data(), valid_p.data(), st, geom,
                                  limit, nullptr, nullptr, nullptr, 0);
    blowup = blowup || counts[st] > limit;
  }
  int64_t total = 0;
  for (int64_t st = 0; st < G_s; ++st) total += counts[st];
  if (blowup || total > limit) return -1;
  *kp_out = kp;
  *k_out = K;
  *c_out = total;
  return 0;
}

// Phase 2: fill the tables (geometry and C from phase 1). Outputs:
//   row0[C] i32, sub[C * P/4] i32, out_tile[C] i32, first[C] i32,
//   packed[C * P * 1024] i16, max_row0_out (for src_rows).
// Returns 0, or -2 if the recomputed chunk count disagrees with C.
int32_t spfft_tpu_wide_tables_fill(const int64_t* idx, const uint8_t* valid,
                                   int64_t L, int64_t num_src, int32_t P,
                                   int32_t kp, int32_t K, int64_t C,
                                   int32_t* row0,
                                   int32_t* sub, int32_t* out_tile,
                                   int32_t* first, int16_t* packed,
                                   int32_t* max_row0_out) {
  if (L <= 0 || P != 8) return -2;
  const int64_t SUPER = int64_t(P) * kTile;
  const int64_t G_s = (L + SUPER - 1) / SUPER;
  const int64_t Lp = G_s * SUPER;
  std::vector<int64_t> idx_p(Lp);
  std::vector<uint8_t> valid_p(Lp);
  std::memcpy(idx_p.data(), idx, sizeof(int64_t) * L);
  std::memcpy(valid_p.data(), valid, L);
  for (int64_t i = L; i < Lp; ++i) {
    idx_p[i] = idx[L - 1];
    valid_p[i] = 0;
  }
  int32_t r_clamp = kBig;
  const int64_t r_exact = (num_src + kLane - 1) / kLane;
  if (num_src > 0 && r_exact >= K)
    r_clamp = static_cast<int32_t>(r_exact - K);
  const WideGeom geom{P, kp, K, G_s, r_clamp};
  const int64_t limit = 16 * G_s + 64;

  std::vector<int64_t> counts(G_s);
#pragma omp parallel for schedule(dynamic, 16)
  for (int64_t st = 0; st < G_s; ++st)
    counts[st] = cover_super_tile(idx_p.data(), valid_p.data(), st, geom,
                                  limit, nullptr, nullptr, nullptr, 0);
  std::vector<int64_t> offs(G_s + 1, 0);
  for (int64_t st = 0; st < G_s; ++st) offs[st + 1] = offs[st] + counts[st];
  if (offs[G_s] != C) return -2;

#pragma omp parallel for schedule(dynamic, 16)
  for (int64_t st = 0; st < G_s; ++st) {
    cover_super_tile(idx_p.data(), valid_p.data(), st, geom, limit, row0,
                     sub, packed, offs[st]);
    for (int64_t c = offs[st]; c < offs[st + 1]; ++c) {
      out_tile[c] = static_cast<int32_t>(st);
      first[c] = c == offs[st] ? 1 : 0;
    }
  }
  int32_t mx = 0;
#pragma omp parallel for reduction(max : mx) schedule(static)
  for (int64_t c = 0; c < C; ++c)
    if (row0[c] > mx) mx = row0[c];
  *max_row0_out = mx;
  return 0;
}


// Compression gather inputs (ops/gather_kernel.compression_gather_inputs,
// decompress direction): occupied mask + forward-filled position map.
// dec_idx[s] = position in the value array of the nearest occupied slot at
// or below s (leading gap: the first occupied slot); duplicates resolve to
// the LAST occurrence — both exactly as the NumPy path. Returns 0, or -1
// if any index is out of [0, num_slots).
int32_t spfft_tpu_compression_inputs(const int64_t* vi, int64_t n,
                                     int64_t num_slots, int64_t* dec_idx,
                                     uint8_t* occupied) {
  bool oob = false;
#pragma omp parallel for reduction(|| : oob) schedule(static)
  for (int64_t i = 0; i < n; ++i)
    oob = oob || vi[i] < 0 || vi[i] >= num_slots;
  if (oob) return -1;
#pragma omp parallel for schedule(static)
  for (int64_t s = 0; s < num_slots; ++s) {
    occupied[s] = 0;
    dec_idx[s] = -1;
  }
  // last occurrence wins (serial, like the NumPy fancy assignment)
  for (int64_t i = 0; i < n; ++i) {
    occupied[vi[i]] = 1;
    dec_idx[vi[i]] = i;
  }
  if (n > 0) {
    // forward fill; leading gap takes the first occupied slot's position
    int64_t first = -1;
    for (int64_t s = 0; s < num_slots; ++s)
      if (occupied[s]) {
        first = dec_idx[s];
        break;
      }
    int64_t cur = first;
    for (int64_t s = 0; s < num_slots; ++s) {
      if (occupied[s])
        cur = dec_idx[s];
      dec_idx[s] = cur;
    }
  } else {
#pragma omp parallel for schedule(static)
    for (int64_t s = 0; s < num_slots; ++s) dec_idx[s] = 0;
  }
  return 0;
}

}  // extern "C"

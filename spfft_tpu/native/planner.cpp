// Native index planner: sparse frequency triplets -> z-stick tables.
//
// C++ implementation of the semantics of the reference index conversion
// (reference: src/compression/indices.hpp:120-186 convert_index_triplets,
// :49-55 to_storage_index) — the plan-time hot loop of the framework. The
// NumPy path in spfft_tpu/indexing.py is the fallback and the executable
// specification; this library exists because planning a 256^3 spherical
// cutoff (8.8M triplets) takes seconds through generic sort-based
// np.unique, while the dense bitmap-rank algorithm here is O(n + dimX*dimY)
// and runs in tens of milliseconds.
//
// Exposed via a plain C ABI loaded with ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// Error codes mirrored in spfft_tpu/native/__init__.py.
constexpr int64_t kErrInvalidBounds = -1;
constexpr int64_t kErrTooManyValues = -2;
// Allocation failure / grid too large for the dense-bitmap algorithm — the
// caller falls back to the NumPy path (no C++ exception may cross the C ABI).
constexpr int64_t kErrNoNativePath = -3;

}  // namespace

extern "C" {

// Convert (n, 3) int64 row-major triplets into per-value flat indices and
// the ascending unique stick-key list.
//
// Outputs:
//   value_indices[n]  int32 : stick_id * dim_z + z_storage  per value
//   stick_keys[n]     int32 : first num_sticks entries hold the ascending
//                             unique keys x_storage * dim_y + y_storage
//   centered_out      int32 : 1 if any index was negative
// Returns num_sticks (>= 0) or a negative error code.
int64_t spfft_tpu_plan_indices(int32_t hermitian, int64_t dim_x,
                               int64_t dim_y, int64_t dim_z,
                               const int64_t* xyz, int64_t n,
                               int32_t* value_indices, int32_t* stick_keys,
                               int32_t* centered_out) {
  if (n > dim_x * dim_y * dim_z) return kErrTooManyValues;

  // Pass 1: centered detection (any negative index, indices.hpp:129-135).
  bool centered = false;
#pragma omp parallel for reduction(|| : centered) schedule(static)
  for (int64_t i = 0; i < 3 * n; ++i) centered = centered || (xyz[i] < 0);
  *centered_out = centered ? 1 : 0;

  // Bounds, exactly as reference indices.hpp:137-149.
  const int64_t max_x = (hermitian || centered ? dim_x / 2 + 1 : dim_x) - 1;
  const int64_t max_y = (centered ? dim_y / 2 + 1 : dim_y) - 1;
  const int64_t max_z = (centered ? dim_z / 2 + 1 : dim_z) - 1;
  const int64_t min_x = hermitian ? 0 : max_x - dim_x + 1;
  const int64_t min_y = max_y - dim_y + 1;
  const int64_t min_z = max_z - dim_z + 1;

  const int64_t plane = dim_x * dim_y;
  std::vector<uint8_t> present;
  std::vector<int32_t> rank;
  try {
    present.assign(static_cast<size_t>(plane), 0);
    rank.resize(static_cast<size_t>(plane));
  } catch (...) {
    return kErrNoNativePath;
  }

  // Pass 2: bounds check + mark present stick keys. Benign write races on
  // the bitmap (all writers store 1).
  bool oob = false;
#pragma omp parallel for reduction(|| : oob) schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int64_t x = xyz[3 * i], y = xyz[3 * i + 1], z = xyz[3 * i + 2];
    if (x < min_x || x > max_x || y < min_y || y > max_y || z < min_z ||
        z > max_z) {
      oob = true;
      continue;
    }
    const int64_t xs = x < 0 ? x + dim_x : x;
    const int64_t ys = y < 0 ? y + dim_y : y;
    // Relaxed atomic store: many threads may mark the same key; all store 1.
    __atomic_store_n(&present[static_cast<size_t>(xs * dim_y + ys)],
                     static_cast<uint8_t>(1), __ATOMIC_RELAXED);
  }
  if (oob) return kErrInvalidBounds;

  // Pass 3: rank present keys in ascending order (the ordered-map semantics
  // of indices.hpp:152-165, without the map).
  int32_t num_sticks = 0;
  for (int64_t k = 0; k < plane; ++k) {
    if (present[static_cast<size_t>(k)]) {
      rank[static_cast<size_t>(k)] = num_sticks;
      stick_keys[num_sticks++] = static_cast<int32_t>(k);
    }
  }

  // Pass 4: per-value flat index stick_id * dim_z + z (indices.hpp:168-176).
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int64_t x = xyz[3 * i], y = xyz[3 * i + 1], z = xyz[3 * i + 2];
    const int64_t xs = x < 0 ? x + dim_x : x;
    const int64_t ys = y < 0 ? y + dim_y : y;
    const int64_t zs = z < 0 ? z + dim_z : z;
    value_indices[i] = static_cast<int32_t>(
        static_cast<int64_t>(rank[static_cast<size_t>(xs * dim_y + ys)]) *
            dim_z +
        zs);
  }
  return num_sticks;
}

// Inverse maps (indexing.inverse_slot_map / inverse_col_map): scatter of
// iota, included so the whole plan build can run natively. The scatter loop
// is serial so that duplicate indices resolve to the *last* occurrence,
// matching the NumPy fallback's fancy-assignment semantics. Returns 0, or
// -1 if any index is out of [0, num_slots).
int32_t spfft_tpu_inverse_map(const int32_t* indices, int64_t n,
                              int32_t* out, int64_t num_slots,
                              int32_t sentinel) {
  bool oob = false;
#pragma omp parallel for reduction(|| : oob) schedule(static)
  for (int64_t i = 0; i < n; ++i)
    oob = oob || indices[i] < 0 || indices[i] >= num_slots;
  if (oob) return -1;
#pragma omp parallel for schedule(static)
  for (int64_t s = 0; s < num_slots; ++s) out[s] = sentinel;
  for (int64_t i = 0; i < n; ++i) out[indices[i]] = static_cast<int32_t>(i);
  return 0;
}

}  // extern "C"

"""Loader for the native (C++) plan-time kernels.

The C++ sources in this directory are compiled on demand into a shared
library next to the sources (``g++ -O3 -fopenmp -shared -fPIC``) and loaded
with ctypes — this image has no pybind11, and a plain C ABI keeps the
boundary trivial. Everything here has a NumPy fallback in
:mod:`spfft_tpu.indexing`; the native path only accelerates plan
construction (the reference's plan-time index conversion,
src/compression/indices.hpp:120-186), never the jitted transform itself.

Set ``SPFFT_TPU_NO_NATIVE=1`` to force the NumPy fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "planner.cpp")
_LIB = os.path.join(_DIR, f"_planner_{sys.implementation.cache_tag}.so")

_lock = threading.Lock()
#: guarded by _lock
_lib: Optional[ctypes.CDLL] = None
#: guarded by _lock
_load_failed = False


class WideCoverBlowup(Exception):
    """The wide-gather cover exceeded its chunk limit — the caller falls
    back exactly where the NumPy builder returns None. A dedicated type so
    unrelated ValueErrors are never misread as the fallback signal."""


def _compile() -> None:
    """Compile to a temp file and rename atomically: concurrent processes
    (multi-host plan construction, pytest-xdist) may race on first use, and
    a partially written .so must never be dlopen'd."""
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.spfft_tpu_plan_indices.restype = ctypes.c_int64
    lib.spfft_tpu_plan_indices.argtypes = [
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.spfft_tpu_inverse_map.restype = ctypes.c_int32
    lib.spfft_tpu_inverse_map.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int32]
    lib.spfft_tpu_wide_tables_plan.restype = ctypes.c_int32
    lib.spfft_tpu_wide_tables_plan.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.spfft_tpu_compression_inputs.restype = ctypes.c_int32
    lib.spfft_tpu_compression_inputs.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_void_p]
    lib.spfft_tpu_wide_tables_fill.restype = ctypes.c_int32
    lib.spfft_tpu_wide_tables_fill.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p]
    return lib


def _load() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the native library; None if unavailable."""
    global _lib, _load_failed
    # lock: waived(double-checked fast path - _lib is write-once under _lock and a stale None just falls through to the locked slow path)
    if _lib is not None:
        return _lib  # lock: waived(same benign race - the handle is immutable once published)
    # lock: waived(racy pre-check - the locked block re-reads _load_failed before deciding)
    if _load_failed or os.environ.get("SPFFT_TPU_NO_NATIVE") == "1":
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _compile()
            try:
                _lib = _bind(ctypes.CDLL(_LIB))
            except (OSError, AttributeError):
                # A stale/foreign binary (restored with a fresh mtime by a
                # checkout, or built from an older source revision missing a
                # symbol) — rebuild once before giving up.
                _compile()
                _lib = _bind(ctypes.CDLL(_LIB))
        except (OSError, AttributeError, subprocess.CalledProcessError):
            _load_failed = True
    return _lib  # lock: waived(post-with read - either the handle this call published or another loader's, both final)


def available() -> bool:
    return _load() is not None


def plan_indices(hermitian: bool, dim_x: int, dim_y: int, dim_z: int,
                 triplets: np.ndarray):
    """Native ``convert_index_triplets`` core. Returns
    ``(value_indices, stick_keys, centered)`` or None if the native library
    is unavailable. Raises the same exception types as the NumPy path for
    invalid input (mapped from the C error codes)."""
    lib = _load()
    if lib is None:
        return None
    from ..errors import InvalidIndicesError, InvalidParameterError

    xyz = np.ascontiguousarray(triplets, dtype=np.int64)
    if xyz.ndim != 2 or xyz.shape[1] != 3:
        raise InvalidParameterError(
            f"expected (n, 3) index triplets, got shape {xyz.shape}")
    n = xyz.shape[0]
    value_indices = np.empty(n, np.int32)
    stick_keys = np.empty(max(n, 1), np.int32)
    centered = ctypes.c_int32(0)
    num_sticks = lib.spfft_tpu_plan_indices(
        ctypes.c_int32(1 if hermitian else 0), dim_x, dim_y, dim_z,
        xyz.ctypes.data, n, value_indices.ctypes.data,
        stick_keys.ctypes.data, ctypes.byref(centered))
    if num_sticks == -1:
        raise InvalidIndicesError(
            f"index triplet out of bounds for dims ({dim_x},{dim_y},{dim_z}),"
            f" hermitian={hermitian}")
    if num_sticks == -2:
        raise InvalidParameterError(
            "more frequency values than grid elements (indices.hpp:126-128)")
    if num_sticks == -3:
        # Grid too large for the dense-bitmap algorithm (allocation failed)
        # — let the NumPy path handle it.
        return None
    return value_indices, stick_keys[:num_sticks].copy(), bool(centered.value)


def compression_inputs(value_indices: np.ndarray, num_slots: int):
    """Native decompress-direction gather inputs (occupied mask +
    forward-filled position map; see
    ops/gather_kernel.compression_gather_inputs — the NumPy version is the
    specification). Returns (dec_idx int64[num_slots], occupied bool) or
    None if the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    vi = np.ascontiguousarray(value_indices, np.int64)
    dec_idx = np.empty(num_slots, np.int64)
    occupied = np.empty(num_slots, np.uint8)
    st = lib.spfft_tpu_compression_inputs(
        vi.ctypes.data, vi.shape[0], num_slots, dec_idx.ctypes.data,
        occupied.ctypes.data)
    if st != 0:
        raise IndexError(f"value index out of range [0, {num_slots})")
    return dec_idx, occupied.astype(bool)


def wide_gather_tables(idx: np.ndarray, valid: np.ndarray, *,
                       num_src: int, p_tiles: int, kp_rows: int,
                       k_rows: int):
    """Native wide-gather table build (the cover loop of
    ops/gather_kernel.build_wide_gather_tables — its NumPy version is the
    executable specification and the fallback).

    Returns ``(row0, sub, out_tile, first, packed, kp, K, max_row0)`` or
    None if the native library is unavailable / P != 8; raises
    :class:`WideCoverBlowup` on a chunk-count blowup exactly where the
    NumPy builder returns None — the caller maps that to its fallback."""
    lib = _load()
    if lib is None or p_tiles != 8:
        return None
    idx64 = np.ascontiguousarray(idx, np.int64)
    val8 = np.ascontiguousarray(valid, np.uint8)
    L = idx64.shape[0]
    kp_o = ctypes.c_int32(0)
    k_o = ctypes.c_int32(0)
    c_o = ctypes.c_int64(0)
    st = lib.spfft_tpu_wide_tables_plan(
        idx64.ctypes.data, val8.ctypes.data, L, int(num_src), p_tiles,
        kp_rows, k_rows, ctypes.byref(kp_o), ctypes.byref(k_o),
        ctypes.byref(c_o))
    if st == -1:
        raise WideCoverBlowup()  # caller falls back
    if st != 0:
        return None
    C, kp, K = c_o.value, kp_o.value, k_o.value
    row0 = np.empty(C, np.int32)
    sub = np.empty((C, p_tiles // 4), np.int32)
    out_tile = np.empty(C, np.int32)
    first = np.empty(C, np.int32)
    packed = np.empty((C, p_tiles * 8, 128), np.int16)
    mx = ctypes.c_int32(0)
    st = lib.spfft_tpu_wide_tables_fill(
        idx64.ctypes.data, val8.ctypes.data, L, int(num_src), p_tiles, kp,
        K, C, row0.ctypes.data, sub.ctypes.data, out_tile.ctypes.data,
        first.ctypes.data, packed.ctypes.data, ctypes.byref(mx))
    if st != 0:  # pragma: no cover - phase disagreement would be a bug
        return None
    return row0, sub, out_tile, first, packed, kp, K, mx.value


def inverse_map(indices: np.ndarray, num_slots: int,
                sentinel: int) -> Optional[np.ndarray]:
    """Native inverse map (scatter of iota with last-wins duplicates), or
    None if the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    idx = np.ascontiguousarray(indices, dtype=np.int32).reshape(-1)
    out = np.empty(num_slots, np.int32)
    status = lib.spfft_tpu_inverse_map(idx.ctypes.data, idx.shape[0],
                                       out.ctypes.data, num_slots,
                                       ctypes.c_int32(sentinel))
    if status != 0:
        raise IndexError(
            f"inverse map index out of range [0, {num_slots})")
    return out

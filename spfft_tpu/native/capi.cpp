// Native C API host: embeds CPython and dispatches to spfft_tpu.capi_bridge.
//
// Role-equivalent of the reference C API implementation (reference:
// src/spfft/grid.cpp:88-103, transform.cpp — C entry points wrapping C++ in
// try/catch and returning SpfftError codes). Here the "C++ core" is the
// JAX/XLA pipeline of the Python package; this translation unit owns only
// the runtime embedding: interpreter lifecycle, the GIL, and marshalling
// plain integers across the ABI. All argument validation, numpy buffer
// wrapping, and error-code mapping happens in spfft_tpu/capi_bridge.py,
// which returns (code, payload) tuples and never raises across the
// boundary.
//
// Build (see Makefile target `capi`):
//   g++ -O3 -std=c++17 -shared -fPIC capi.cpp -o libspfft_tpu.so
//       $(python3-config --includes) $(python3-config --ldflags --embed)

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <mutex>

#include "spfft_tpu.h"  // keep definitions checked against the public ABI

namespace {

constexpr int kSuccess = 0;
constexpr int kInvalidHandle = 2;        // SPFFT_TPU_INVALID_HANDLE_ERROR
constexpr int kInvalidParameter = 5;     // SPFFT_TPU_INVALID_PARAMETER_ERROR
constexpr int kUnknown = 1;              // SPFFT_TPU_UNKNOWN_ERROR
constexpr int kRuntimeInit = 100;        // SPFFT_TPU_RUNTIME_INIT_ERROR

std::mutex g_init_mutex;
PyObject* g_bridge = nullptr;  // spfft_tpu.capi_bridge module (owned)
bool g_we_initialized = false;

// Plan handles are the bridge's integer plan ids, stored directly in the
// opaque pointer (id 0 is never issued).
inline void* id_to_handle(long long id) {
  return reinterpret_cast<void*>(static_cast<intptr_t>(id));
}
inline long long handle_to_id(void* h) {
  return static_cast<long long>(reinterpret_cast<intptr_t>(h));
}

// Ensure the interpreter is running and the bridge module is imported.
// Returns 0 or an error code. On success the caller still must take the
// GIL via PyGILState_Ensure for its own calls.
int ensure_runtime(const char* package_path) {
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (g_bridge != nullptr) return kSuccess;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(/*install_sigint_handler=*/0);
    if (!Py_IsInitialized()) return kRuntimeInit;
    g_we_initialized = true;
  }
  PyGILState_STATE st = PyGILState_Ensure();
  int code = kSuccess;
  if (package_path != nullptr) {
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* p = PyUnicode_FromString(package_path);
    if (sys_path == nullptr || p == nullptr ||
        PyList_Insert(sys_path, 0, p) != 0) {
      code = kRuntimeInit;
    }
    Py_XDECREF(p);
  }
  if (code == kSuccess) {
    g_bridge = PyImport_ImportModule("spfft_tpu.capi_bridge");
    if (g_bridge == nullptr) {
      PyErr_Print();
      code = kRuntimeInit;
    }
  }
  PyGILState_Release(st);
  // If we started the interpreter, detach this thread's state so any
  // thread (including this one) can re-acquire via PyGILState_Ensure —
  // unconditionally, or a failed import would leave the GIL held forever
  // and deadlock every later call instead of returning an error code.
  static bool detached = false;
  if (g_we_initialized && !detached) {
    PyEval_SaveThread();
    detached = true;
  }
  return code;
}

// Call bridge.<fn>(args...) where every argument is a long long; the bridge
// returns (code, payload). Writes payload to *payload_out if non-null.
int call_bridge(const char* fn, std::initializer_list<long long> args,
                long long* payload_out) {
  int code = ensure_runtime(nullptr);
  if (code != kSuccess) return code;
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* tuple = PyTuple_New(static_cast<Py_ssize_t>(args.size()));
  if (tuple == nullptr) {
    PyErr_Clear();
    PyGILState_Release(st);
    return kUnknown;
  }
  Py_ssize_t i = 0;
  for (long long a : args) {
    PyObject* v = PyLong_FromLongLong(a);
    if (v == nullptr) {
      PyErr_Clear();
      Py_DECREF(tuple);
      PyGILState_Release(st);
      return kUnknown;
    }
    PyTuple_SET_ITEM(tuple, i++, v);
  }
  PyObject* callable = PyObject_GetAttrString(g_bridge, fn);
  PyObject* result =
      callable != nullptr ? PyObject_CallObject(callable, tuple) : nullptr;
  Py_XDECREF(callable);
  Py_DECREF(tuple);
  if (result == nullptr) {
    PyErr_Print();
    PyGILState_Release(st);
    return kUnknown;
  }
  long long payload = 0;
  if (PyTuple_Check(result) && PyTuple_GET_SIZE(result) == 2) {
    code = static_cast<int>(PyLong_AsLongLong(PyTuple_GET_ITEM(result, 0)));
    payload = PyLong_AsLongLong(PyTuple_GET_ITEM(result, 1));
  } else {
    code = kUnknown;
  }
  Py_DECREF(result);
  if (PyErr_Occurred()) {
    PyErr_Print();
    code = kUnknown;
  }
  if (payload_out != nullptr) *payload_out = payload;
  PyGILState_Release(st);
  return code;
}

}  // namespace

extern "C" {

int spfft_tpu_abi_version(void) {
  return 2;  // keep equal to SPFFT_TPU_ABI_VERSION in include/spfft_tpu.h
}

int spfft_tpu_init(const char* package_path) {
  return ensure_runtime(package_path);
}

int spfft_tpu_plan_create(void** plan, int transform_type, int dim_x,
                          int dim_y, int dim_z, long long num_values,
                          const int* index_triplets, int precision,
                          int use_pallas) {
  if (plan == nullptr || (index_triplets == nullptr && num_values > 0)) {
    return kInvalidParameter;
  }
  long long pid = 0;
  int code = call_bridge(
      "plan_create",
      {transform_type, dim_x, dim_y, dim_z, num_values,
       static_cast<long long>(reinterpret_cast<intptr_t>(index_triplets)),
       precision, use_pallas},
      &pid);
  if (code == kSuccess) *plan = id_to_handle(pid);
  return code;
}

int spfft_tpu_plan_create_distributed(void** plan, int transform_type,
                                      int dim_x, int dim_y, int dim_z,
                                      int num_shards,
                                      const long long* values_per_shard,
                                      const int* index_triplets,
                                      const int* planes_per_shard,
                                      int precision, int exchange_type,
                                      int use_pallas) {
  if (plan == nullptr || values_per_shard == nullptr ||
      planes_per_shard == nullptr || num_shards < 1) {
    return kInvalidParameter;
  }
  long long total = 0;
  for (int r = 0; r < num_shards; ++r) total += values_per_shard[r];
  if (index_triplets == nullptr && total > 0) return kInvalidParameter;
  long long pid = 0;
  int code = call_bridge(
      "plan_create_distributed",
      {transform_type, dim_x, dim_y, dim_z, num_shards,
       static_cast<long long>(reinterpret_cast<intptr_t>(values_per_shard)),
       static_cast<long long>(reinterpret_cast<intptr_t>(index_triplets)),
       static_cast<long long>(reinterpret_cast<intptr_t>(planes_per_shard)),
       precision, exchange_type, use_pallas},
      &pid);
  if (code == kSuccess) *plan = id_to_handle(pid);
  return code;
}

int spfft_tpu_multi_backward(int num_transforms, void* const* plans,
                             const void* const* values,
                             void* const* spaces) {
  if (num_transforms < 1 || plans == nullptr || values == nullptr ||
      spaces == nullptr) {
    return kInvalidParameter;
  }
  for (int i = 0; i < num_transforms; ++i) {
    if (values[i] == nullptr || spaces[i] == nullptr) {
      return kInvalidParameter;
    }
  }
  return call_bridge(
      "multi_backward",
      {num_transforms,
       static_cast<long long>(reinterpret_cast<intptr_t>(plans)),
       static_cast<long long>(reinterpret_cast<intptr_t>(values)),
       static_cast<long long>(reinterpret_cast<intptr_t>(spaces))},
      nullptr);
}

int spfft_tpu_multi_forward(int num_transforms, void* const* plans,
                            const void* const* spaces, int scaling,
                            void* const* values) {
  if (num_transforms < 1 || plans == nullptr || values == nullptr ||
      spaces == nullptr) {
    return kInvalidParameter;
  }
  for (int i = 0; i < num_transforms; ++i) {
    if (values[i] == nullptr || spaces[i] == nullptr) {
      return kInvalidParameter;
    }
  }
  return call_bridge(
      "multi_forward",
      {num_transforms,
       static_cast<long long>(reinterpret_cast<intptr_t>(plans)),
       static_cast<long long>(reinterpret_cast<intptr_t>(spaces)), scaling,
       static_cast<long long>(reinterpret_cast<intptr_t>(values))},
      nullptr);
}

int spfft_tpu_plan_destroy(void* plan) {
  return call_bridge("plan_destroy", {handle_to_id(plan)}, nullptr);
}

int spfft_tpu_backward(void* plan, const void* values, void* space) {
  if (values == nullptr || space == nullptr) return kInvalidParameter;
  return call_bridge(
      "backward",
      {handle_to_id(plan),
       static_cast<long long>(reinterpret_cast<intptr_t>(values)),
       static_cast<long long>(reinterpret_cast<intptr_t>(space))},
      nullptr);
}

int spfft_tpu_forward(void* plan, const void* space, int scaling,
                      void* values) {
  if (values == nullptr || space == nullptr) return kInvalidParameter;
  return call_bridge(
      "forward",
      {handle_to_id(plan),
       static_cast<long long>(reinterpret_cast<intptr_t>(space)), scaling,
       static_cast<long long>(reinterpret_cast<intptr_t>(values))},
      nullptr);
}

int spfft_tpu_execute_pair(void* plan, const void* values_in, int scaling,
                           void* values_out) {
  if (values_in == nullptr || values_out == nullptr) return kInvalidParameter;
  return call_bridge(
      "execute_pair",
      {handle_to_id(plan),
       static_cast<long long>(reinterpret_cast<intptr_t>(values_in)), scaling,
       static_cast<long long>(reinterpret_cast<intptr_t>(values_out))},
      nullptr);
}

static int plan_info(void* plan, int what, long long* out,
                     long long shard = 0) {
  if (out == nullptr) return kInvalidParameter;
  return call_bridge("plan_info", {handle_to_id(plan), what, shard}, out);
}

static int plan_info_int(void* plan, int what, int* out,
                         long long shard = 0) {
  if (out == nullptr) return kInvalidParameter;
  long long v = 0;
  int code = plan_info(plan, what, &v, shard);
  if (code == kSuccess) *out = static_cast<int>(v);
  return code;
}

int spfft_tpu_plan_dim_x(void* plan, int* out) {
  if (out == nullptr) return kInvalidParameter;
  long long v = 0;
  int code = plan_info(plan, 0, &v);
  if (code == kSuccess) *out = static_cast<int>(v);
  return code;
}

int spfft_tpu_plan_dim_y(void* plan, int* out) {
  if (out == nullptr) return kInvalidParameter;
  long long v = 0;
  int code = plan_info(plan, 1, &v);
  if (code == kSuccess) *out = static_cast<int>(v);
  return code;
}

int spfft_tpu_plan_dim_z(void* plan, int* out) {
  if (out == nullptr) return kInvalidParameter;
  long long v = 0;
  int code = plan_info(plan, 2, &v);
  if (code == kSuccess) *out = static_cast<int>(v);
  return code;
}

int spfft_tpu_plan_num_values(void* plan, long long* out) {
  return plan_info(plan, 3, out);
}

int spfft_tpu_plan_transform_type(void* plan, int* out) {
  if (out == nullptr) return kInvalidParameter;
  long long v = 0;
  int code = plan_info(plan, 4, &v);
  if (code == kSuccess) *out = static_cast<int>(v);
  return code;
}

int spfft_tpu_plan_num_shards(void* plan, int* out) {
  if (out == nullptr) return kInvalidParameter;
  long long v = 0;
  int code = plan_info(plan, 5, &v);
  if (code == kSuccess) *out = static_cast<int>(v);
  return code;
}

int spfft_tpu_plan_global_size(void* plan, long long* out) {
  return plan_info(plan, 6, out);
}

int spfft_tpu_plan_num_global_elements(void* plan, long long* out) {
  return plan_info(plan, 7, out);
}

int spfft_tpu_plan_local_z_offset(void* plan, int shard, int* out) {
  return plan_info_int(plan, 8, out, shard);
}

int spfft_tpu_plan_local_z_length(void* plan, int shard, int* out) {
  return plan_info_int(plan, 9, out, shard);
}

int spfft_tpu_plan_local_slice_size(void* plan, int shard, long long* out) {
  return plan_info(plan, 10, out, shard);
}

int spfft_tpu_plan_num_local_elements(void* plan, int shard,
                                      long long* out) {
  return plan_info(plan, 11, out, shard);
}

int spfft_tpu_plan_exchange_type(void* plan, int* out) {
  return plan_info_int(plan, 12, out);
}

int spfft_tpu_plan_pallas_active(void* plan, int* out) {
  return plan_info_int(plan, 13, out);
}

const char* spfft_tpu_error_string(int code) {
  switch (code) {
    case 0: return "success";
    case 1: return "unknown error";
    case 2: return "invalid plan handle";
    case 3: return "size overflow";
    case 4: return "allocation failure";
    case 5: return "invalid parameter";
    case 6: return "duplicate z-stick indices";
    case 7: return "frequency index out of bounds";
    case 8: return "distributed support missing";
    case 9: return "distributed/collective failure";
    case 10: return "plan parameters mismatch across shards";
    case 11: return "host execution failure";
    case 12: return "FFT backend failure";
    case 13: return "device (TPU/XLA) failure";
    case 15: return "device support missing";
    case 16: return "device allocation failure";
    case 22: return "device FFT failure";
    case 100: return "embedded Python runtime initialisation failed";
    default: return "unrecognised error code";
  }
}

}  // extern "C"

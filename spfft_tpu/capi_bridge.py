"""Python side of the C API (see include/spfft_tpu.h, native/capi.cpp).

Every function here is called from the embedded interpreter inside
``libspfft_tpu.so`` with plain integers (addresses, sizes, enum values) and
returns ``(error_code, payload)`` — exceptions never cross the C boundary.
The error-code mapping reproduces the reference C API's try/catch->code
pattern (reference: src/spfft/grid.cpp:88-103 wraps every C entry point and
returns SpfftError).

Caller-owned memory is viewed (never copied on input, one copy on output)
through ``ctypes`` pointers; layout contracts are documented in the header.
"""

from __future__ import annotations

import ctypes
import itertools
import traceback
from typing import Dict, Tuple

import numpy as np

from .errors import ErrorCode, GenericError, InvalidParameterError
from .plan import TransformPlan, make_local_plan
from .types import ExchangeType, Scaling, TransformType

_plans: Dict[int, object] = {}
_next_id = itertools.count(1)

_INVALID_HANDLE = 2  # SPFFT_TPU_INVALID_HANDLE_ERROR


def _code_for(exc: BaseException) -> int:
    if isinstance(exc, GenericError):
        return int(exc.error_code())
    return int(ErrorCode.UNKNOWN)


def _guarded(fn):
    def wrapper(*args) -> Tuple[int, int]:
        try:
            payload = fn(*args)
            return (int(ErrorCode.SUCCESS), 0 if payload is None
                    else int(payload))
        except BaseException as exc:  # noqa: BLE001 — C boundary
            traceback.print_exc()
            return (_code_for(exc), 0)
    wrapper.__name__ = fn.__name__
    return wrapper


def _real_ctype(precision: str):
    return ctypes.c_float if precision == "single" else ctypes.c_double


def _view(addr: int, n: int, precision: str) -> np.ndarray:
    """View n reals of caller memory at addr (no copy)."""
    ptr = ctypes.cast(addr, ctypes.POINTER(_real_ctype(precision)))
    return np.ctypeslib.as_array(ptr, shape=(n,))


def _get_plan(pid: int) -> TransformPlan:
    plan = _plans.get(pid)
    if plan is None:
        raise _InvalidHandle()
    return plan


def _values_rows(plan, out) -> np.ndarray:
    """A plan's value result as interleaved rows for the C ABI buffer —
    large local plans return the planar-pair (2, N) layout
    (plan.pair_values_io), which must be transposed host-side."""
    arr = np.asarray(out)
    if getattr(plan, "pair_values_io", False) and arr.shape[0] == 2:
        return np.ascontiguousarray(arr.T)
    return arr


class _InvalidHandle(GenericError):
    code = ErrorCode.INVALID_HANDLE


#: C ABI <-> ExchangeType, in the reference's enum order (types.h:33-62).
_EXCHANGE_BY_INT = {
    0: ExchangeType.DEFAULT,
    1: ExchangeType.BUFFERED,
    2: ExchangeType.BUFFERED_FLOAT,
    3: ExchangeType.COMPACT_BUFFERED,
    4: ExchangeType.COMPACT_BUFFERED_FLOAT,
    5: ExchangeType.UNBUFFERED,
}
_INT_BY_EXCHANGE = {v: k for k, v in _EXCHANGE_BY_INT.items()}


def _pallas_mode(use_pallas: int):
    """SpfftTpuPallasMode -> the Python use_pallas tri-state."""
    if use_pallas not in (-1, 0, 1):
        raise InvalidParameterError(f"bad pallas mode {use_pallas}")
    return None if use_pallas == -1 else bool(use_pallas)


def _check_create_enums(transform_type: int, precision: int) -> None:
    if transform_type not in (0, 1):
        raise InvalidParameterError(f"bad transform type {transform_type}")
    if precision not in (0, 1):
        raise InvalidParameterError(f"bad precision {precision}")


@_guarded
def plan_create(transform_type: int, dim_x: int, dim_y: int, dim_z: int,
                num_values: int, triplets_addr: int, precision: int,
                use_pallas: int) -> int:
    _check_create_enums(transform_type, precision)
    if num_values < 0:
        raise InvalidParameterError(f"negative num_values {num_values}")
    if num_values == 0:
        trip = np.empty((0, 3), np.int32)
    else:
        ptr = ctypes.cast(triplets_addr, ctypes.POINTER(ctypes.c_int32))
        trip = np.array(np.ctypeslib.as_array(ptr, shape=(num_values, 3)),
                        np.int32, copy=True)
    plan = make_local_plan(
        TransformType.C2C if transform_type == 0 else TransformType.R2C,
        dim_x, dim_y, dim_z, trip,
        precision="single" if precision == 0 else "double",
        use_pallas=_pallas_mode(use_pallas))
    pid = next(_next_id)
    _plans[pid] = plan
    return pid


@_guarded
def plan_create_distributed(transform_type: int, dim_x: int, dim_y: int,
                            dim_z: int, num_shards: int, vps_addr: int,
                            triplets_addr: int, pps_addr: int,
                            precision: int, exchange_type: int,
                            use_pallas: int) -> int:
    """Distributed plan over num_shards local devices (reference:
    spfft_grid_create_distributed, grid.h — communicator -> device mesh;
    exchange_type is the reference's distributed-grid exchangeType)."""
    from .parallel import make_distributed_plan, make_mesh

    _check_create_enums(transform_type, precision)
    if exchange_type not in _EXCHANGE_BY_INT:
        raise InvalidParameterError(f"bad exchange type {exchange_type}")
    vps = np.array(np.ctypeslib.as_array(
        ctypes.cast(vps_addr, ctypes.POINTER(ctypes.c_longlong)),
        shape=(num_shards,)), np.int64, copy=True)
    pps = np.array(np.ctypeslib.as_array(
        ctypes.cast(pps_addr, ctypes.POINTER(ctypes.c_int32)),
        shape=(num_shards,)), np.int64, copy=True)
    if (vps < 0).any():
        raise InvalidParameterError("negative per-shard value count")
    total = int(vps.sum())
    if total == 0:
        trip = np.empty((0, 3), np.int32)
    else:
        ptr = ctypes.cast(triplets_addr, ctypes.POINTER(ctypes.c_int32))
        trip = np.array(np.ctypeslib.as_array(ptr, shape=(total, 3)),
                        np.int32, copy=True)
    offsets = np.concatenate([[0], np.cumsum(vps)]).astype(int)
    per_shard = [trip[offsets[r]:offsets[r + 1]] for r in range(num_shards)]
    plan = make_distributed_plan(
        TransformType.C2C if transform_type == 0 else TransformType.R2C,
        dim_x, dim_y, dim_z, per_shard, [int(p) for p in pps],
        mesh=make_mesh(num_shards),
        precision="single" if precision == 0 else "double",
        exchange=_EXCHANGE_BY_INT[exchange_type],
        use_pallas=_pallas_mode(use_pallas))
    pid = next(_next_id)
    _plans[pid] = plan
    return pid


@_guarded
def plan_destroy(pid: int) -> None:
    if _plans.pop(pid, None) is None:
        raise _InvalidHandle()


def _is_distributed(plan) -> bool:
    from .parallel.dist import DistributedTransformPlan
    return isinstance(plan, DistributedTransformPlan)


def _split_values_view(plan, values_addr: int) -> list:
    """View the C caller's concatenated per-shard value array as one numpy
    list per shard (shard order, no copy). Shared by every distributed
    entry so the shard-order convention lives in one place."""
    dp = plan.dist_plan
    total = dp.num_global_elements
    flat = _view(values_addr, 2 * total, plan.precision).reshape(total, 2)
    per, off = [], 0
    for sp in dp.shard_plans:
        per.append(flat[off:off + sp.num_values])
        off += sp.num_values
    return per


def _concat_padded_values(plan, padded: np.ndarray) -> np.ndarray:
    """Padded sharded (S, max_values, 2) device result -> concatenated
    true per-shard values (the C API wire layout)."""
    dp = plan.dist_plan
    return np.concatenate([padded[r, :dp.shard_plans[r].num_values]
                           for r in range(dp.num_shards)], axis=0)


def _dist_backward(plan, values_addr: int, space_addr: int) -> None:
    """Concatenated per-shard values -> full cube in global z order."""
    dp = plan.dist_plan
    per = _split_values_view(plan, values_addr)
    # The padded device result is already interleaved (C2C) / real (R2C):
    # slice each shard's true slab out directly, no complex round trip.
    padded = np.asarray(plan.backward(per))
    cube = np.concatenate([padded[r, :dp.num_planes[r]]
                           for r in range(dp.num_shards)], axis=0)
    width = 1 if dp.hermitian else 2
    n_space = dp.dim_z * dp.dim_y * dp.dim_x * width
    _view(space_addr, n_space, plan.precision)[:] = cube.reshape(-1)


def _dist_forward(plan, space_addr: int, scaling: int,
                  values_addr: int) -> None:
    """Full cube in global z order -> concatenated per-shard values."""
    dp = plan.dist_plan
    width = 1 if dp.hermitian else 2
    n_space = dp.dim_z * dp.dim_y * dp.dim_x * width
    shape = (dp.dim_z, dp.dim_y, dp.dim_x) + \
        (() if dp.hermitian else (2,))
    cube = _view(space_addr, n_space, plan.precision).reshape(shape)
    slabs, off = [], 0
    for n in dp.num_planes:
        slabs.append(cube[off:off + n])
        off += n
    if scaling not in (0, 1):
        raise InvalidParameterError(f"bad scaling {scaling}")
    padded = np.asarray(plan.forward(
        slabs, Scaling.FULL if scaling == 1 else Scaling.NONE))
    out = _concat_padded_values(plan, padded)
    total = dp.num_global_elements
    _view(values_addr, 2 * total, plan.precision)[:] = out.reshape(-1)


@_guarded
def backward(pid: int, values_addr: int, space_addr: int) -> None:
    plan = _get_plan(pid)
    if _is_distributed(plan):
        return _dist_backward(plan, values_addr, space_addr)
    p = plan.index_plan
    values = _view(values_addr, 2 * p.num_values,
                   plan.precision).reshape(p.num_values, 2)
    space = np.asarray(plan.backward(values.copy()))
    n_space = p.dim_z * p.dim_y * p.dim_x * (1 if p.hermitian else 2)
    _view(space_addr, n_space, plan.precision)[:] = space.reshape(-1)


@_guarded
def forward(pid: int, space_addr: int, scaling: int,
            values_addr: int) -> None:
    plan = _get_plan(pid)
    if _is_distributed(plan):
        return _dist_forward(plan, space_addr, scaling, values_addr)
    p = plan.index_plan
    n_space = p.dim_z * p.dim_y * p.dim_x * (1 if p.hermitian else 2)
    space = _view(space_addr, n_space, plan.precision)
    shape = (p.dim_z, p.dim_y, p.dim_x) + (() if p.hermitian else (2,))
    if scaling not in (0, 1):
        raise InvalidParameterError(f"bad scaling {scaling}")
    values = _values_rows(plan, plan.forward(
        space.copy().reshape(shape),
        Scaling.FULL if scaling == 1 else Scaling.NONE))
    _view(values_addr, 2 * p.num_values,
          plan.precision)[:] = values.reshape(-1)


@_guarded
def execute_pair(pid: int, values_in_addr: int, scaling: int,
                 values_out_addr: int) -> None:
    """Fused backward+forward round trip (ONE device program via
    plan.apply_pointwise) — the C API's SCF-inner-loop entry. In-place
    (out == in) allowed: the input is copied into device memory before the
    output view is written."""
    plan = _get_plan(pid)
    if scaling not in (0, 1):
        raise InvalidParameterError(f"bad scaling {scaling}")
    sc = Scaling.FULL if scaling == 1 else Scaling.NONE
    if _is_distributed(plan):
        total = plan.dist_plan.num_global_elements
        per = [p.copy() for p in _split_values_view(plan, values_in_addr)]
        padded = np.asarray(plan.apply_pointwise(per, scaling=sc))
        out = _concat_padded_values(plan, padded)
        _view(values_out_addr, 2 * total,
              plan.precision)[:] = out.reshape(-1)
        return
    p = plan.index_plan
    values = _view(values_in_addr, 2 * p.num_values,
                   plan.precision).reshape(p.num_values, 2)
    out = _values_rows(plan, plan.apply_pointwise(values.copy(),
                                                  scaling=sc))
    _view(values_out_addr, 2 * p.num_values,
          plan.precision)[:] = out.reshape(-1)


def _read_addr_array(addr: int, n: int) -> list:
    """n pointer-sized entries of a caller array (plan handles or buffer
    addresses)."""
    ptr = ctypes.cast(addr, ctypes.POINTER(ctypes.c_void_p))
    return [int(ptr[i] or 0) for i in range(n)]


def _multi_io(pid_handles: list):
    """Resolve plan handles; error early on nulls/unknowns."""
    return [_get_plan(h) for h in pid_handles]


def _fuse_gate(plan, batch: int) -> bool:
    """The SAME B-aware fusion gate as multi._shared_plan: shared-handle
    batches through the C API must not fuse where the measured gates say
    per-transform dispatch wins (large batches fuse at 0.47-0.64x the
    speed — BENCHMARKS.md 'Fused shared-plan batches')."""
    from .multi import FUSED_BATCH_MAX_DIST_TOTAL, FUSED_BATCH_MAX_GRID
    if batch < 2:
        return False
    if _is_distributed(plan):
        dp = plan.dist_plan
        slab = dp.dim_x * dp.dim_y * dp.max_planes
        return batch * slab <= FUSED_BATCH_MAX_DIST_TOTAL
    return batch * plan.global_size <= FUSED_BATCH_MAX_GRID


@_guarded
def multi_backward(n: int, plans_addr: int, values_addr: int,
                   spaces_addr: int) -> None:
    """Batched backward over n transforms (reference:
    spfft_multi_transform_backward, multi_transform.h:37-54). All same
    handle -> ONE fused device program via backward_batched; mixed handles
    dispatch every transform before the first host synchronisation (the
    reference's overlap schedule, realised by XLA async dispatch)."""
    handles = _read_addr_array(plans_addr, n)
    vaddrs = _read_addr_array(values_addr, n)
    saddrs = _read_addr_array(spaces_addr, n)
    plans = _multi_io(handles)
    if len(set(handles)) == 1 and _fuse_gate(plans[0], n) \
            and _is_distributed(plans[0]):
        plan, dp = plans[0], plans[0].dist_plan
        per_b = [[v.copy() for v in _split_values_view(plan, a)]
                 for a in vaddrs]
        batch = np.asarray(plan.backward_batched(per_b))  # (S, B, ...)
        width = 1 if dp.hermitian else 2
        n_space = dp.dim_z * dp.dim_y * dp.dim_x * width
        for b, a in enumerate(saddrs):
            cube = np.concatenate(
                [batch[r, b, :dp.num_planes[r]]
                 for r in range(dp.num_shards)], axis=0)
            _view(a, n_space, plan.precision)[:] = cube.reshape(-1)
        return
    if len(set(handles)) == 1 and _fuse_gate(plans[0], n):
        plan, p = plans[0], plans[0].index_plan
        vals = [_view(a, 2 * p.num_values, plan.precision)
                .reshape(p.num_values, 2).copy() for a in vaddrs]
        batch = np.asarray(plan.backward_batched(vals))
        width = 1 if p.hermitian else 2
        n_space = p.dim_z * p.dim_y * p.dim_x * width
        for i, a in enumerate(saddrs):
            _view(a, n_space, plan.precision)[:] = batch[i].reshape(-1)
        return
    outs = []
    for plan, va in zip(plans, vaddrs):
        if _is_distributed(plan):
            outs.append(None)  # handled below; dist path syncs internally
        else:
            p = plan.index_plan
            v = _view(va, 2 * p.num_values,
                      plan.precision).reshape(p.num_values, 2)
            outs.append(plan.backward(v.copy()))  # async dispatch
    for plan, va, sa, out in zip(plans, vaddrs, saddrs, outs):
        if _is_distributed(plan):
            _dist_backward(plan, va, sa)
        else:
            p = plan.index_plan
            width = 1 if p.hermitian else 2
            n_space = p.dim_z * p.dim_y * p.dim_x * width
            _view(sa, n_space,
                  plan.precision)[:] = np.asarray(out).reshape(-1)


@_guarded
def multi_forward(n: int, plans_addr: int, spaces_addr: int, scaling: int,
                  values_addr: int) -> None:
    """Batched forward over n transforms (reference:
    spfft_multi_transform_forward, multi_transform.h:56-72)."""
    if scaling not in (0, 1):
        raise InvalidParameterError(f"bad scaling {scaling}")
    sc = Scaling.FULL if scaling == 1 else Scaling.NONE
    handles = _read_addr_array(plans_addr, n)
    saddrs = _read_addr_array(spaces_addr, n)
    vaddrs = _read_addr_array(values_addr, n)
    plans = _multi_io(handles)
    if len(set(handles)) == 1 and _fuse_gate(plans[0], n) \
            and _is_distributed(plans[0]):
        plan, dp = plans[0], plans[0].dist_plan
        width = 1 if dp.hermitian else 2
        n_space = dp.dim_z * dp.dim_y * dp.dim_x * width
        shape = (dp.dim_z, dp.dim_y, dp.dim_x) + \
            (() if dp.hermitian else (2,))
        per_b = []
        for a in saddrs:
            cube = _view(a, n_space, plan.precision).copy().reshape(shape)
            slabs, off = [], 0
            for np_ in dp.num_planes:
                slabs.append(cube[off:off + np_])
                off += np_
            per_b.append(slabs)
        batch = np.asarray(plan.forward_batched(per_b, sc))  # (S, B, mv, 2)
        total = dp.num_global_elements
        for b, a in enumerate(vaddrs):
            out = _concat_padded_values(plan, batch[:, b])
            _view(a, 2 * total, plan.precision)[:] = out.reshape(-1)
        return
    if len(set(handles)) == 1 and _fuse_gate(plans[0], n):
        plan, p = plans[0], plans[0].index_plan
        width = 1 if p.hermitian else 2
        n_space = p.dim_z * p.dim_y * p.dim_x * width
        shape = (p.dim_z, p.dim_y, p.dim_x) + (() if p.hermitian else (2,))
        slabs = [_view(a, n_space, plan.precision).copy().reshape(shape)
                 for a in saddrs]
        batch = np.asarray(plan.forward_batched(slabs, sc))
        for i, a in enumerate(vaddrs):
            rows = _values_rows(plan, batch[i])
            _view(a, 2 * p.num_values,
                  plan.precision)[:] = np.ascontiguousarray(
                      rows).reshape(-1)
        return
    outs = []
    for plan, sa in zip(plans, saddrs):
        if _is_distributed(plan):
            outs.append(None)
        else:
            p = plan.index_plan
            width = 1 if p.hermitian else 2
            n_space = p.dim_z * p.dim_y * p.dim_x * width
            shape = (p.dim_z, p.dim_y, p.dim_x) + \
                (() if p.hermitian else (2,))
            slab = _view(sa, n_space, plan.precision).copy().reshape(shape)
            outs.append(plan.forward(slab, sc))  # async dispatch
    for plan, sa, va, out in zip(plans, saddrs, vaddrs, outs):
        if _is_distributed(plan):
            _dist_forward(plan, sa, scaling, va)
        else:
            p = plan.index_plan
            rows = _values_rows(plan, out)
            _view(va, 2 * p.num_values,
                  plan.precision)[:] = rows.reshape(-1)


@_guarded
def plan_info(pid: int, what: int, shard: int = 0) -> int:
    plan = _get_plan(pid)
    if _is_distributed(plan):
        dp = plan.dist_plan
        num_shards = dp.num_shards
        base = {0: dp.dim_x, 1: dp.dim_y, 2: dp.dim_z,
                3: dp.num_global_elements,
                4: 0 if dp.transform_type == TransformType.C2C else 1,
                5: num_shards,
                6: dp.dim_x * dp.dim_y * dp.dim_z,
                7: dp.num_global_elements,
                12: _INT_BY_EXCHANGE[plan.exchange],
                13: int(plan._pallas_dist is not None)}
        if what in base:
            return base[what]
        if not 0 <= shard < num_shards:
            raise InvalidParameterError(
                f"shard {shard} out of range [0, {num_shards})")
        return {8: int(dp.plane_offsets[shard]),
                9: int(dp.num_planes[shard]),
                10: dp.dim_x * dp.dim_y * int(dp.num_planes[shard]),
                11: dp.shard_plans[shard].num_values}[what]
    p = plan.index_plan
    base = {0: p.dim_x, 1: p.dim_y, 2: p.dim_z, 3: p.num_values,
            4: 0 if p.transform_type == TransformType.C2C else 1,
            5: 1, 6: p.dim_x * p.dim_y * p.dim_z, 7: p.num_values,
            12: _INT_BY_EXCHANGE[ExchangeType.DEFAULT],
            13: int(plan.pallas_active)}
    if what in base:
        return base[what]
    if shard != 0:
        raise InvalidParameterError(
            f"shard {shard} out of range [0, 1) for a local plan")
    return {8: 0, 9: p.dim_z, 10: p.dim_x * p.dim_y * p.dim_z,
            11: p.num_values}[what]

"""Python side of the C API (see include/spfft_tpu.h, native/capi.cpp).

Every function here is called from the embedded interpreter inside
``libspfft_tpu.so`` with plain integers (addresses, sizes, enum values) and
returns ``(error_code, payload)`` — exceptions never cross the C boundary.
The error-code mapping reproduces the reference C API's try/catch->code
pattern (reference: src/spfft/grid.cpp:88-103 wraps every C entry point and
returns SpfftError).

Caller-owned memory is viewed (never copied on input, one copy on output)
through ``ctypes`` pointers; layout contracts are documented in the header.
"""

from __future__ import annotations

import ctypes
import itertools
import traceback
from typing import Dict, Tuple

import numpy as np

from .errors import ErrorCode, GenericError, InvalidParameterError
from .plan import TransformPlan, make_local_plan
from .types import Scaling, TransformType

_plans: Dict[int, TransformPlan] = {}
_next_id = itertools.count(1)

_INVALID_HANDLE = 2  # SPFFT_TPU_INVALID_HANDLE_ERROR


def _code_for(exc: BaseException) -> int:
    if isinstance(exc, GenericError):
        return int(exc.error_code())
    return int(ErrorCode.UNKNOWN)


def _guarded(fn):
    def wrapper(*args) -> Tuple[int, int]:
        try:
            payload = fn(*args)
            return (int(ErrorCode.SUCCESS), 0 if payload is None
                    else int(payload))
        except BaseException as exc:  # noqa: BLE001 — C boundary
            traceback.print_exc()
            return (_code_for(exc), 0)
    wrapper.__name__ = fn.__name__
    return wrapper


def _real_ctype(precision: str):
    return ctypes.c_float if precision == "single" else ctypes.c_double


def _view(addr: int, n: int, precision: str) -> np.ndarray:
    """View n reals of caller memory at addr (no copy)."""
    ptr = ctypes.cast(addr, ctypes.POINTER(_real_ctype(precision)))
    return np.ctypeslib.as_array(ptr, shape=(n,))


def _get_plan(pid: int) -> TransformPlan:
    plan = _plans.get(pid)
    if plan is None:
        raise _InvalidHandle()
    return plan


class _InvalidHandle(GenericError):
    code = ErrorCode.INVALID_HANDLE


@_guarded
def plan_create(transform_type: int, dim_x: int, dim_y: int, dim_z: int,
                num_values: int, triplets_addr: int, precision: int) -> int:
    if transform_type not in (0, 1):
        raise InvalidParameterError(f"bad transform type {transform_type}")
    if precision not in (0, 1):
        raise InvalidParameterError(f"bad precision {precision}")
    if num_values < 0:
        raise InvalidParameterError(f"negative num_values {num_values}")
    if num_values == 0:
        trip = np.empty((0, 3), np.int32)
    else:
        ptr = ctypes.cast(triplets_addr, ctypes.POINTER(ctypes.c_int32))
        trip = np.array(np.ctypeslib.as_array(ptr, shape=(num_values, 3)),
                        np.int32, copy=True)
    plan = make_local_plan(
        TransformType.C2C if transform_type == 0 else TransformType.R2C,
        dim_x, dim_y, dim_z, trip,
        precision="single" if precision == 0 else "double")
    pid = next(_next_id)
    _plans[pid] = plan
    return pid


@_guarded
def plan_destroy(pid: int) -> None:
    if _plans.pop(pid, None) is None:
        raise _InvalidHandle()


@_guarded
def backward(pid: int, values_addr: int, space_addr: int) -> None:
    plan = _get_plan(pid)
    p = plan.index_plan
    values = _view(values_addr, 2 * p.num_values,
                   plan.precision).reshape(p.num_values, 2)
    space = np.asarray(plan.backward(values.copy()))
    n_space = p.dim_z * p.dim_y * p.dim_x * (1 if p.hermitian else 2)
    _view(space_addr, n_space, plan.precision)[:] = space.reshape(-1)


@_guarded
def forward(pid: int, space_addr: int, scaling: int,
            values_addr: int) -> None:
    plan = _get_plan(pid)
    p = plan.index_plan
    n_space = p.dim_z * p.dim_y * p.dim_x * (1 if p.hermitian else 2)
    space = _view(space_addr, n_space, plan.precision)
    shape = (p.dim_z, p.dim_y, p.dim_x) + (() if p.hermitian else (2,))
    if scaling not in (0, 1):
        raise InvalidParameterError(f"bad scaling {scaling}")
    values = np.asarray(plan.forward(
        space.copy().reshape(shape),
        Scaling.FULL if scaling == 1 else Scaling.NONE))
    _view(values_addr, 2 * p.num_values,
          plan.precision)[:] = values.reshape(-1)


@_guarded
def plan_info(pid: int, what: int) -> int:
    plan = _get_plan(pid)
    p = plan.index_plan
    return {0: p.dim_x, 1: p.dim_y, 2: p.dim_z, 3: p.num_values,
            4: 0 if p.transform_type == TransformType.C2C else 1}[what]

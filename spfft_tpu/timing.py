"""Hierarchical scope timing — the rt_graph equivalent.

Reimplements the semantics of the reference's self-contained scope timer
(reference: src/timing/rt_graph.hpp:106-177 ``Timer``, :83-102
``TimingResult``; hooked in via HOST_TIMING_* macros, src/timing/timing.hpp:44-62):
nested named scopes accumulate start/stop timestamps, ``process()``
reconstructs the call tree, and the result prints a Count/Total/%/Parent%/
Median/Min/Max table or exports JSON — the same stats the reference benchmark
dumps (tests/programs/benchmark.cpp:276-308).

TPU caveat, stated honestly: jitted work is dispatched asynchronously, so a
host-side scope around a jitted call measures dispatch unless the scope blocks.
``timed_transform(label)`` yields a box; assigning the produced arrays to
``box.value`` inside the scope makes the measurement ``block_until_ready`` on
them, so enabled timing measures real wall-clock. Device-side phase
attribution comes from ``jax.profiler`` traces instead — the pipeline stages
are wrapped in ``jax.named_scope`` so XLA profiles show z/exchange/xy phases
by name.

Timing is off by default (the reference compiles the macros out unless
SPFFT_TIMING, CMakeLists.txt:181-184); enable with ``enable()`` or the
SPFFT_TPU_TIMING=1 env var.
"""

from __future__ import annotations

import contextlib
import json as _json
import os
import statistics
import threading
import time
from typing import Any, Dict, List, Optional

import jax


class _Node:
    __slots__ = ("label", "times", "children")

    def __init__(self, label: str):
        self.label = label
        self.times: List[float] = []
        self.children: "Dict[str, _Node]" = {}


class TimingResult:
    """Processed call tree with per-scope statistics
    (reference: rt_graph.hpp:83-102)."""

    def __init__(self, root: _Node):
        self._root = root

    def _rows(self):
        rows = []
        total_all = sum(sum(c.times) for c in self._root.children.values())

        def visit(node: _Node, depth: int, parent_total: float):
            total = sum(node.times)
            rows.append({
                "label": node.label, "depth": depth,
                "count": len(node.times), "total": total,
                "pct": 100.0 * total / total_all if total_all else 0.0,
                "parent_pct": (100.0 * total / parent_total
                               if parent_total else 100.0),
                "median": statistics.median(node.times) if node.times else 0.0,
                "min": min(node.times) if node.times else 0.0,
                "max": max(node.times) if node.times else 0.0,
            })
            for child in node.children.values():
                visit(child, depth + 1, total)

        for child in self._root.children.values():
            visit(child, 0, total_all)
        return rows

    def print(self) -> None:
        """Print the stats table (reference: TimingResult::print)."""
        rows = self._rows()
        if not rows:
            print("(no timings recorded)")
            return
        hdr = (f"{'scope':<40}{'count':>7}{'total[s]':>12}{'%':>8}"
               f"{'parent%':>9}{'median[s]':>12}{'min[s]':>12}{'max[s]':>12}")
        print(hdr)
        print("-" * len(hdr))
        for r in rows:
            label = "  " * r["depth"] + r["label"]
            print(f"{label:<40}{r['count']:>7}{r['total']:>12.6f}"
                  f"{r['pct']:>8.2f}{r['parent_pct']:>9.2f}"
                  f"{r['median']:>12.6f}{r['min']:>12.6f}{r['max']:>12.6f}")

    def json(self) -> str:
        """JSON export (reference: TimingResult::json)."""

        def dump(node: _Node) -> Dict[str, Any]:
            return {
                "label": node.label,
                "count": len(node.times),
                "total": sum(node.times),
                "times": node.times,
                "sub": [dump(c) for c in node.children.values()],
            }

        return _json.dumps(
            {"timings": [dump(c) for c in self._root.children.values()]})


class Timer:
    """Nested scope timer (reference: rt_graph.hpp:106-155).

    THREAD-SAFE since the obs round: the scope stack is THREAD-LOCAL
    (each thread nests its own scopes from the shared root — the
    serving executor's dispatcher, prewarm and submitter threads can
    all enter ``timed_transform`` scopes concurrently without
    corrupting each other's call paths), while the tree itself (child
    creation, sample appends, ``record``) mutates under one lock. A
    ``reset`` mid-scope on another thread orphans that thread's
    in-flight scope (its sample lands in the discarded tree) — callers
    quiesce before resetting, same contract as ``ServeMetrics.reset``.
    """

    def __init__(self):
        self._record_lock = threading.Lock()
        self._root = _Node("<root>")      #: guarded by _record_lock
        self._tls = threading.local()     #: guarded by _record_lock

    def reset(self) -> None:
        with self._record_lock:
            self._root = _Node("<root>")
            self._tls = threading.local()

    def _stack(self) -> List[_Node]:
        """This thread's scope stack, rooted at the CURRENT root (a
        stale stack from before a reset is discarded)."""
        # lock: waived(lock-free fast path by design - thread-local handle read)
        tls = self._tls
        stack = getattr(tls, "stack", None)
        # lock: waived(identity check against the current root - a racing reset just rebuilds this stack)
        if stack is None or stack[0] is not self._root:
            stack = tls.stack = [self._root]  # lock: waived(rebuild against whichever root the race left current)
        return stack

    def record(self, label: str, seconds: float) -> None:
        """Append one pre-measured duration under a ROOT-LEVEL scope
        named ``label``. Cross-thread safe (the serving layer records
        request latencies from its dispatcher thread); never touches
        any scope stack."""
        with self._record_lock:
            node = self._root.children.get(label)
            if node is None:
                node = self._root.children[label] = _Node(label)
            node.times.append(seconds)

    @contextlib.contextmanager
    def scoped(self, label: str, block: Any = None):
        """Time a scope; if ``block`` is given, ``block_until_ready`` it
        before closing the measurement (for async device work).
        Nesting is per-thread (thread-local stack); tree mutation is
        locked."""
        stack = self._stack()
        parent = stack[-1]
        with self._record_lock:
            node = parent.children.get(label)
            if node is None:
                node = parent.children[label] = _Node(label)
        stack.append(node)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block is not None:
                jax.block_until_ready(block)
            dt = time.perf_counter() - t0
            with self._record_lock:
                node.times.append(dt)
            stack.pop()

    def process(self) -> TimingResult:
        # lock: waived(read-side snapshot by design - wraps the live tree)
        return TimingResult(self._root)


#: Global timer, mirroring the reference's GlobalTimer singleton
#: (reference: src/timing/timing.cpp:36).
GlobalTimer = Timer()

_enabled = os.environ.get("SPFFT_TPU_TIMING") == "1"


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def suppressed():
    """Temporarily disable timing inside a scope. Used by the batched
    multi-transform API so per-transform timing does not serialise the batch
    (blocking between dispatches would destroy the compute/comm overlap the
    batching exists for)."""
    global _enabled
    prev = _enabled
    _enabled = False
    try:
        yield
    finally:
        _enabled = prev


class _ResultBox:
    """Mutable late-binding holder so a scope can block on a result produced
    inside it."""

    def __init__(self):
        self.value: Optional[Any] = None


@contextlib.contextmanager
def timed_transform(label: str):
    """Scope for one transform execution: ``box.value = <result>`` inside the
    scope makes the timing block on it."""
    if not _enabled:
        yield _ResultBox()
        return
    box = _ResultBox()
    parent = GlobalTimer
    with parent.scoped(label):
        try:
            yield box
        finally:
            if box.value is not None:
                jax.block_until_ready(box.value)

"""Local (single-device) sparse 3D FFT plans.

The reference's ``Grid`` + ``Transform`` pair pre-allocates buffers and builds
FFTW/cuFFT plans at construction (reference: src/spfft/grid_internal.cpp:75-98,
src/spfft/transform_internal.cpp:86-136). The TPU-native equivalent of a
"plan" is a pair of jitted executables closed over static index tables: XLA
owns buffer allocation and intra-computation reuse (making the reference's
manual two-array aliasing unnecessary), and the compiled executable *is* the
plan cache.

Pipeline (reference: src/execution/execution_host.cpp:249-352):

  backward:  decompress -> [stick symmetry] -> z-IFFT -> scatter to planes
             -> [plane symmetry] -> xy-IFFT
  forward:   xy-FFT -> gather sticks -> z-FFT -> compress [scaled]

Complex I/O crosses the host<->device boundary as interleaved real arrays with
a trailing axis of 2 (see utils.dtypes), matching the reference's interleaved
complex format.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .errors import InvalidParameterError
from .indexing import IndexPlan, build_index_plan
from .ops import stages
from .timing import timed_transform
from .types import Scaling, TransformType
from .utils.dtypes import (as_interleaved, complex_dtype,
                           complex_to_interleaved, interleaved_to_complex,
                           real_dtype)


class TransformPlan:
    """A compiled sparse 3D FFT on a single device.

    Equivalent to a local reference ``Transform`` (reference:
    include/spfft/transform.hpp:56-227) — C2C or R2C, double or single
    precision, arbitrary sparse frequency triplets.
    """

    def __init__(self, index_plan: IndexPlan, precision: str = "single"):
        self.index_plan = index_plan
        self.precision = precision
        self._rdt = real_dtype(precision)
        self._cdt = complex_dtype(precision)
        # Static tables, device-committed once (plan time, never at execute
        # time — mirroring SURVEY.md §3.1's plan/execute split).
        self._value_indices = jnp.asarray(index_plan.value_indices)
        self._scatter_cols = jnp.asarray(index_plan.scatter_cols)
        self._backward_jit = jax.jit(self._backward_impl)
        self._forward_jit = {
            Scaling.NONE: jax.jit(functools.partial(self._forward_impl,
                                                    scaled=False)),
            Scaling.FULL: jax.jit(functools.partial(self._forward_impl,
                                                    scaled=True)),
        }

    # -- reference Transform getters (transform.hpp:91-151) -----------------
    @property
    def transform_type(self) -> TransformType:
        return self.index_plan.transform_type

    @property
    def dim_x(self) -> int:
        return self.index_plan.dim_x

    @property
    def dim_y(self) -> int:
        return self.index_plan.dim_y

    @property
    def dim_z(self) -> int:
        return self.index_plan.dim_z

    @property
    def local_z_length(self) -> int:
        return self.index_plan.dim_z

    @property
    def local_z_offset(self) -> int:
        return 0

    @property
    def local_slice_size(self) -> int:
        """dim_x * dim_y * local_z_length (reference: transform.cpp:99)."""
        return self.dim_x * self.dim_y * self.local_z_length

    @property
    def num_local_elements(self) -> int:
        return self.index_plan.num_values

    @property
    def num_global_elements(self) -> int:
        return self.index_plan.num_values

    @property
    def global_size(self) -> int:
        return self.dim_x * self.dim_y * self.dim_z

    # -- jitted pipelines ----------------------------------------------------
    @property
    def _is_r2c(self) -> bool:
        return self.index_plan.hermitian

    def _backward_impl(self, values_il):
        p = self.index_plan
        values = interleaved_to_complex(values_il).astype(self._cdt)
        sticks = stages.decompress(values, self._value_indices,
                                   p.num_sticks, p.dim_z)
        if self._is_r2c and p.zero_stick_id is not None:
            zid = p.zero_stick_id
            sticks = sticks.at[zid].set(
                stages.complete_stick_hermitian(sticks[zid]))
        sticks = stages.z_backward(sticks)
        grid = stages.sticks_to_grid(sticks, self._scatter_cols, p.dim_z,
                                     p.dim_y, p.dim_x_freq)
        if self._is_r2c:
            grid = stages.complete_plane_hermitian(grid)
            return stages.xy_backward_r2c(grid, p.dim_x)
        return complex_to_interleaved(stages.xy_backward_c2c(grid))

    def _forward_impl(self, space, *, scaled: bool):
        p = self.index_plan
        if self._is_r2c:
            grid = stages.xy_forward_r2c(space.astype(self._rdt))
        else:
            grid = stages.xy_forward_c2c(
                interleaved_to_complex(space).astype(self._cdt))
        sticks = stages.grid_to_sticks(grid, self._scatter_cols)
        sticks = stages.z_forward(sticks)
        scale = 1.0 / self.global_size if scaled else None
        values = stages.compress(sticks, self._value_indices, scale)
        return complex_to_interleaved(values)

    # -- public execution (reference: transform.hpp:198-211) -----------------
    def backward(self, values):
        """Frequency -> space. ``values`` is (num_values,) complex (or
        interleaved (num_values, 2) real). Returns the space-domain slab:
        (dim_z, dim_y, dim_x, 2) interleaved for C2C, real (dim_z, dim_y,
        dim_x) for R2C. Unnormalised inverse DFT (details.rst
        "Transform Definition")."""
        values_il = self._coerce_values(values)
        with timed_transform("backward") as box:
            box.value = self._backward_jit(values_il)
        return box.value

    def forward(self, space, scaling: Scaling = Scaling.NONE):
        """Space -> frequency. Returns (num_values, 2) interleaved sparse
        values; ``scaling=Scaling.FULL`` multiplies by 1/(Nx·Ny·Nz)
        (details.rst "Normalization")."""
        scaling = Scaling(scaling)
        space = self._coerce_space(space)
        with timed_transform("forward") as box:
            box.value = self._forward_jit[scaling](space)
        return box.value

    # -- input coercion ------------------------------------------------------
    def _coerce_values(self, values):
        if isinstance(values, jax.Array) and values.ndim == 2 \
                and values.shape == (self.index_plan.num_values, 2):
            return values
        arr = as_interleaved(values, self.precision)
        if arr.shape != (self.index_plan.num_values, 2):
            raise InvalidParameterError(
                f"expected {self.index_plan.num_values} frequency values, "
                f"got shape {arr.shape[:-1]}")
        return arr

    def _coerce_space(self, space):
        p = self.index_plan
        shape3 = (self.local_z_length, p.dim_y, p.dim_x)
        if self._is_r2c:
            arr = space if isinstance(space, jax.Array) \
                else np.asarray(space, self._rdt)
            if arr.shape != shape3:
                raise InvalidParameterError(
                    f"expected real space-domain slab {shape3}, "
                    f"got {arr.shape}")
            return arr
        if isinstance(space, jax.Array) and space.shape == shape3 + (2,):
            return space
        arr = as_interleaved(space, self.precision)
        if arr.shape != shape3 + (2,):
            raise InvalidParameterError(
                f"expected space-domain slab {shape3} complex, "
                f"got {arr.shape[:-1]}")
        return arr


def make_local_plan(transform_type: TransformType, dim_x: int, dim_y: int,
                    dim_z: int, triplets, precision: str = "single",
                    ) -> TransformPlan:
    """Build a local plan from raw index triplets — the moral equivalent of
    ``Grid::create_transform`` without a communicator (reference:
    grid.hpp:138-141)."""
    plan = build_index_plan(TransformType(transform_type), dim_x, dim_y,
                            dim_z, np.asarray(triplets))
    return TransformPlan(plan, precision=precision)

"""Local (single-device) sparse 3D FFT plans.

The reference's ``Grid`` + ``Transform`` pair pre-allocates buffers and builds
FFTW/cuFFT plans at construction (reference: src/spfft/grid_internal.cpp:75-98,
src/spfft/transform_internal.cpp:86-136). The TPU-native equivalent of a
"plan" is a pair of jitted executables closed over static index tables: XLA
owns buffer allocation and intra-computation reuse (making the reference's
manual two-array aliasing unnecessary), and the compiled executable *is* the
plan cache.

Pipeline (reference: src/execution/execution_host.cpp:249-352):

  backward:  decompress -> [stick symmetry] -> z-IFFT -> scatter to planes
             -> [plane symmetry] -> xy-IFFT
  forward:   xy-FFT -> gather sticks -> z-FFT -> compress [scaled]

Complex I/O crosses the host<->device boundary as interleaved real arrays with
a trailing axis of 2 (see utils.dtypes), matching the reference's interleaved
complex format.
"""

from __future__ import annotations

import functools
import logging
import time as _time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("spfft_tpu")

#: Above this many sparse values, the plan's device boundary for value
#: arrays is the planar PAIR layout (2, N) — row 0 real, row 1 imag —
#: instead of interleaved rows (N, 2): XLA can assign a large (N, 2)
#: boundary array TPU's T(8,128) tiled layout, padding the minor dim
#: 2 -> 128 — 64x memory, 36 GB at 512^3 (measured), while flat (2N,)
#: strided interleaves lower ~70x too slow; (2, N) is compact AND fast
#: (see ops/gather_kernel.planar_from_interleaved). 16M keeps the
#: battle-tested (N, 2) layout for every grid up to 256^3 and switches
#: 320^3+.
PAIR_IO_THRESHOLD = 16_000_000

from .errors import InvalidParameterError
from .indexing import IndexPlan, build_index_plan
from .ops import stages
from .timing import timed_transform
from .types import Scaling, TransformType
from .utils.dtypes import (as_interleaved, complex_dtype,
                           complex_to_interleaved, interleaved_to_complex,
                           real_dtype)

import dataclasses as _dataclasses


@_dataclasses.dataclass(frozen=True)
class PlanTables:
    """Host-side snapshot of everything a plan's background table build
    produces — the restore payload of the persistent plan-artifact
    store (:mod:`spfft_tpu.serve.store`).

    A plan restored from one of these skips BOTH expensive halves of a
    cold start: index-table construction (the ``IndexPlan`` arrives
    fully materialised from the artifact) and the background
    compression-table build thread (the gather/fused tables arrive
    prebuilt; only the cheap device commit runs). ``pallas_box`` /
    ``fused_box`` hold the same host table dataclasses the build thread
    would have produced (``gather_kernel.MonotoneGatherTables`` /
    ``WideGatherTables``, ``fused_kernel.Fused*Tables``); activation is
    re-decided at restore time from the RESTORING process's backend —
    tables exported on a TPU restore inactive-but-committed on CPU and
    vice versa, exactly like a fresh build would have decided."""

    s_pad: int
    pallas_box: Optional[dict]      # {"dec": tables|None, "cmp": ...}
    fused_box: dict                 # {"dec": tables|None, "cmp": ...}
    fused_reasons: dict             # per-direction gate decline reasons


def predicted_rel_error(precision: str, max_dim: int,
                        mdft_covered: Optional[bool] = None,
                        device_double: bool = False) -> float:
    """Conservative predicted relative l2 error of a backward transform vs
    a dense f64 oracle, for uniform-magnitude (O(1) dynamic range) value
    sets.

    Calibrated against the measured on-TPU precision matrix with the
    round-4 matmul-DFT stages (docs/precision.md;
    scripts/precision_matrix.py): single-precision backward l2 vs the
    dense f64 oracle measures 1.4e-7 (32^3) / 1.5e-7 (64^3) / 1.7e-7
    (128^3) / 1.8e-7 (256^3) / 1.9e-7 (512^3) — fit err ~ 1.5e-7 *
    (n/64)^0.13; the model uses a ~1.8x envelope so every measured point
    (including the adversarial rows, worst 1.92e-7) sits well below it.
    Double precision follows the same shape from the f64 epsilon. The
    model covers the reference-contract workload class (values of
    bounded dynamic range, test_check_values.hpp:46-50); measured 1e±6
    dynamic range stayed at 1.9e-7 relative l2
    (docs/precision.md 'Adversarial rows').

    Calibrated domain: the matmul-DFT forms (direct — incl. the
    prime-fallback lengths, measured 1.44e-7 at a 521 axis and 1.42e-7
    at 1021 on-chip — or two-stage, single precision) and the CPU f64
    path. Plans the matmul pipeline cannot cover (an unfactorable axis
    above the direct-fallback cap; an R2C x-axis above it) execute
    through XLA's ``jnp.fft`` lowering, where the envelope is
    extrapolation —
    an extra 4x safety factor applies so the contract fails loudly
    rather than promising uncalibrated accuracy (round-4 advisor
    finding). ``mdft_covered`` is the
    STRUCTURAL routing answer (ops.dft.mdft_coverable) from the caller;
    ``None`` infers it from ``max_dim`` alone (single-axis query).
    """
    from .ops.dft import mdft_coverable
    if mdft_covered is None:
        mdft_covered = mdft_coverable((max_dim,))
    shape = (max(max_dim, 1) / 64.0) ** 0.13
    if precision == "single":
        base = 2.8e-7 * shape
        if not mdft_covered:
            base *= 4.0  # uncalibrated jnp.fft path
        return base
    if device_double:
        # on-device double-single (ops/dsdft.py): exact-sliced dots with
        # an ORDER_MAX drop floor ~2^-54 and double-single carries;
        # measured pipeline ~1e-12-class (docs/precision.md round-5 rows)
        return 2.0e-11 * shape
    return 5.0e-15 * shape  # f64 eps * same shape, ~10x headroom


class TransformPlan:
    """A compiled sparse 3D FFT on a single device.

    Equivalent to a local reference ``Transform`` (reference:
    include/spfft/transform.hpp:56-227) — C2C or R2C, double or single
    precision, arbitrary sparse frequency triplets.
    """

    def __init__(self, index_plan: IndexPlan, precision: str = "single",
                 use_pallas: Optional[bool] = None,
                 donate_inputs: bool = False,
                 max_rel_error: Optional[float] = None,
                 device_double: Optional[bool] = None,
                 _restore: Optional[PlanTables] = None):
        from .utils.platform import enable_persistent_compilation_cache
        enable_persistent_compilation_cache()
        _t0_build = _time.perf_counter()
        #: When True, the fused round-trip executables (apply_pointwise /
        #: iterate_pointwise) DONATE their values argument: the output has
        #: the same shape, so XLA aliases the input buffer into it, cutting
        #: peak HBM by one values array (measured: 417 -> 347 MB at 256^3,
        #: 1803 -> 1566 MB at 384^3 — scripts/probe_donation.py) — the TPU
        #: form of the reference's two-array in-place buffer economy
        #: (reference: src/spfft/grid_internal.cpp:75-98). backward/forward
        #: do NOT donate: their input and output shapes differ, so XLA
        #: could never alias them and the donation would only produce
        #: unusable-donation warnings. The caller's input device array is
        #: CONSUMED by the donating calls (invalid afterwards); numpy
        #: inputs are unaffected (their device copy is transient anyway).
        self.donate_inputs = bool(donate_inputs)
        self.index_plan = index_plan
        self.precision = precision
        self._rdt = real_dtype(precision)
        self._cdt = complex_dtype(precision)
        self._pair_io = index_plan.num_values >= PAIR_IO_THRESHOLD
        from .ops import dft as _dft
        # On-device double: double-single (hi, lo) f32 channels through
        # exact-sliced Ozaki dots (ops/dsdft.py) — ~1e-12 relative on the
        # chip, where f64 arrays cannot even exist. C2C and R2C,
        # direct-form axes. SPFFT_TPU_DEVICE_DOUBLE=0 restores the old
        # behavior
        # (CPU-backend f64; on a TPU session that silently truncated to
        # f32 — the bug this mode replaces); =force enables off-TPU for
        # tests.
        import os as _os
        _ds_env = _os.environ.get("SPFFT_TPU_DEVICE_DOUBLE", "")
        self._ds = (precision == "double" and _ds_env != "0"
                    and device_double is not False
                    and max(index_plan.dim_x, index_plan.dim_y,
                            index_plan.dim_z) <= _dft.MATMUL_DFT_MAX
                    and (_ds_env == "force"
                         or jax.default_backend() == "tpu"))
        if precision == "double" and not self._ds \
                and device_double is not False \
                and (jax.default_backend() == "tpu"
                     or not jax.config.jax_enable_x64):
            # device_double=False callers (the distributed comm-size-1
            # delegate) warn at their own layer with their own wording.
            # The CPU-without-x64 case is the same silent trap: JAX
            # truncates every f64 array to f32 with only a UserWarning.
            if jax.default_backend() != "tpu":
                why = "jax x64 is not enabled on this CPU backend"
            elif _ds_env == "0":
                why = "SPFFT_TPU_DEVICE_DOUBLE=0 disabled it"
            else:
                why = (f"an axis above {_dft.MATMUL_DFT_MAX} is outside "
                       f"the mode")
            logger.warning(
                "spfft_tpu: precision='double' without the on-device "
                "double mode (%s) runs at FLOAT32 device precision — "
                "use the CPU backend with jax x64 enabled "
                "(JAX_ENABLE_X64=1) for true f64 (docs/precision.md)",
                why)
        # the double-single pipeline has its own (N, 4) host-f64
        # boundary; the planar pair layout never applies to it
        if self._ds:
            self._pair_io = False
        if max_rel_error is not None:
            from .ops.dft import mdft_coverable
            predicted = predicted_rel_error(
                precision, max(index_plan.dim_x, index_plan.dim_y,
                               index_plan.dim_z),
                mdft_coverable((index_plan.dim_x, index_plan.dim_y,
                                index_plan.dim_z), index_plan.hermitian),
                device_double=self._ds)
            if predicted > max_rel_error:
                from .errors import PrecisionContractError
                hint = ("the CPU backend (JAX_PLATFORMS=cpu, jax x64) "
                        "reaches f64 epsilon"
                        if precision == "double" else
                        "precision='double' (on-device double-single "
                        "for axes <= 512, CPU backend otherwise)")
                raise PrecisionContractError(
                    f"precision='{precision}' predicts relative error "
                    f"~{predicted:.1e} at dims ({index_plan.dim_x},"
                    f"{index_plan.dim_y},{index_plan.dim_z}), above the "
                    f"requested max_rel_error={max_rel_error:.1e} — "
                    f"{hint} (docs/precision.md)")
        #: Matmul-DFT (T-layout) pipeline: every DFT contracts the minor
        #: axis against plan-time matrices, the plane grid stays
        #: transposed (planes, x, y) through the y-stage, and the round
        #: trip pays ONE transpose pair instead of XLA fft2's four
        #: internal layout copies (ops/dft.py; scripts/probe_r4_dft2.py).
        # The shared routing predicate (ops.dft.mdft_axes): every axis
        # direct or two-stage; the R2C x-axis needs the direct form
        # (half-spectrum matrices don't factor through the split).
        self._use_mdft = _dft.mdft_axes(
            self._cdt, index_plan.dim_x, index_plan.dim_y,
            index_plan.dim_z,
            direct_any=(index_plan.dim_x,) if index_plan.hermitian else ())
        if self._pair_io:
            # Layout flip is observable by callers (forward/apply_pointwise
            # return (2, N) instead of (N, 2)); say so once at plan build.
            logger.info(
                "spfft_tpu: plan has %d values (>= %d) — device value "
                "arrays use the planar pair layout (2, N); see "
                "TransformPlan.pair_values_io",
                index_plan.num_values, PAIR_IO_THRESHOLD)
        # Static tables, device-committed once (plan time, never at execute
        # time — mirroring SURVEY.md §3.1's plan/execute split). They are
        # passed to the jitted pipelines as arguments, not closure constants.
        # Only the tables the ACTIVE path touches live in the hot dict (an
        # unused pytree leaf would still ship to the device on every call);
        # the fallback-path tables (slot_src, value_indices — 87 MB at
        # 256^3) commit lazily via _commit_fallback / the _tables property.
        self._pallas_box = None
        self._pallas_active_flag = False
        #: Fused compression+z-DFT state (ops/fused_kernel.py): per-
        #: direction tables ("dec"/"cmp"), per-direction fallback
        #: reasons, and the activation flag. Built on the same
        #: background thread as the gather tables.
        self._fused_box = {"dec": None, "cmp": None}
        self._fused_reasons = {}
        self._fused_active_flag = False
        self._build_thread = None
        self._build_exc = None
        self._tables_full = None
        #: AOT executables installed by the plan-artifact store after a
        #: restore: ``{"backward": Exported, "forward_none": ...,
        #: "forward_full": ...}`` — ``jax.export`` deserialisations that
        #: skip the trace/lower half of the first execution. Only the
        #: default-placement public entries consult them (a device-pool
        #: pinned execution keeps the per-device jit path).
        self._aot = None
        self._restore_tables = _restore
        will_build = self._decide_pallas(use_pallas)  # also sets _s_pad
        p = index_plan
        extra = self._s_pad - p.num_sticks
        pads = np.zeros(extra, np.int32)
        self._tables_hot = {}
        if self._use_mdft or self._ds:
            self._tables_hot["col_inv_t"] = jnp.asarray(p.col_inv_t)
            self._tables_hot["scatter_cols_t"] = jnp.asarray(
                np.concatenate([p.scatter_cols_t, pads]) if extra
                else p.scatter_cols_t)
        if not self._use_mdft and not self._ds:
            # (_ds reads only the T tables + the compression fallbacks;
            # an unused pytree leaf would ship every call — see above)
            self._tables_hot["col_inv"] = jnp.asarray(p.col_inv)
            self._tables_hot["scatter_cols"] = jnp.asarray(
                np.concatenate([p.scatter_cols, pads]) if extra
                else p.scatter_cols)
        if _restore is not None:
            self._commit_restored(_restore)
        elif not will_build:
            self._commit_fallback("dec")
            self._commit_fallback("cmp")
        self._init_split_x()
        # Hermitian x < 0 folding (indexing.canonicalize_hermitian_triplets):
        # folded values are stored conjugated, so the boundary applies a
        # ±1 sign to the imaginary lane — backward input and forward
        # output — in each value layout. ±1 multiplies are exact in f32,
        # so the fold costs no precision and works identically under the
        # XLA gather, the Pallas gather kernel, and the fused z-DFT kernel.
        vc = index_plan.value_conj
        if vc is not None and bool(np.asarray(vc).any()):
            s = np.where(np.asarray(vc), -1.0, 1.0)
            o = np.ones_like(s)
            self._conj_mults = {
                "il": np.stack([o, s], axis=-1),        # (N, 2)
                "pair": np.stack([o, s], axis=0),       # (2, N)
                "ds": np.stack([o, o, s, s], axis=-1),  # (N, 4) [rh,rl,ih,il]
            }
        else:
            self._conj_mults = None
        if self._ds:
            from .ops import dsdft as _dsdft
            gs = 1.0 / float(self.global_size)
            herm = index_plan.hermitian
            self._ds_mats = {
                "z_b": _dsdft.ds_c2c_mats(p.dim_z, _dft.BACKWARD),
                "y_b": _dsdft.ds_c2c_mats(p.dim_y, _dft.BACKWARD),
                # hermitian x-stages are the REAL half-spectrum forms
                # (hermitian doubling folded into the c2r matrices)
                "x_b": (_dsdft.ds_c2r_mats(p.dim_x) if herm
                        else _dsdft.ds_c2c_mats(p.dim_x, _dft.BACKWARD)),
                "x_f": (_dsdft.ds_r2c_mats(p.dim_x) if herm
                        else _dsdft.ds_c2c_mats(p.dim_x, _dft.FORWARD)),
                "y_f": _dsdft.ds_c2c_mats(p.dim_y, _dft.FORWARD),
                "z_f": _dsdft.ds_c2c_mats(p.dim_z, _dft.FORWARD),
                "z_fs": _dsdft.ds_c2c_mats(p.dim_z, _dft.FORWARD, gs),
            }
        self._batched = None
        self._device_tables = {}
        self._pair_jits = {}
        # runtime fused-kernel demotion ladder (docs/kernels.md): per
        # direction {"reason", "unfused_ok", "probes", "probing",
        # "permanent"}. Written only by the thread driving executions
        # (the serving dispatcher, or the single caller thread).
        self._fused_demotions = {}
        self._backward_jit = jax.jit(self._backward_impl)
        self._forward_jit = {
            Scaling.NONE: jax.jit(functools.partial(self._forward_impl,
                                                    scaled=False)),
            Scaling.FULL: jax.jit(functools.partial(self._forward_impl,
                                                    scaled=True)),
        }
        # the FOREGROUND half of the plan.build fault seam: constructing
        # the plan (the background builder thread carries the other half)
        from . import faults as _faults
        _faults.check_site("plan.build")
        if will_build:
            # The compression-table build (native cover + device commit,
            # ~2-3 s at 256^3) runs CONCURRENTLY with whatever the caller
            # does next — typically the first execution's trace + XLA
            # compile / cache load, which takes longer. Public execution
            # methods join via _finalize(); plan construction itself
            # returns in well under a second (the reference's sub-second
            # plan construction, parameters.cpp + FFTW_ESTIMATE).
            import threading
            self._build_thread = threading.Thread(
                target=self._build_compression_tables, daemon=True)
            self._build_thread.start()
        # plan-build observability (spfft_tpu.obs): counters always, a
        # compile-track span when tracing is on. The background
        # compression-table build is NOT included — it overlaps the
        # caller's next work by design and reports via its own scope.
        from . import obs as _obs
        _obs.record_plan_build(self, _time.perf_counter() - _t0_build,
                               _t0_build)

    def _decide_pallas(self, use_pallas: Optional[bool]) -> bool:
        """Decide (cheaply, at construction) whether the Pallas
        windowed-gather compression tables will be built, and fix
        ``_s_pad`` accordingly. The heavy build itself runs in
        :meth:`_build_compression_tables` on a background thread.

        ``use_pallas=True`` on a non-TPU backend builds the tables (useful
        for table-level testing) but execution stays on the XLA path — note
        the asymmetry with ``DistributedTransformPlan``, whose
        ``use_pallas=True`` runs the kernel in *interpret mode* on non-TPU
        (its SPMD body must execute the same program on every backend); the
        kernel is float32-only, so forcing it on a double-precision plan is
        an error rather than a silent downcast."""
        p = self.index_plan
        #: Stick rows of the packed stick array. Plans that attempt
        #: compression tables pad to the next multiple of 32 past
        #: num_sticks: the pad sticks are zeros, so (a) the unpack gather
        #: needs NO sentinel concatenation (a 53 MB copy at 256^3 —
        #: probe_r4_hlo), and (b) dim_z % 4 == 0 grids make num_slots a
        #: whole number of kernel tiles, turning the kernel-output
        #: reshape into a bitcast.
        self._s_pad = p.num_sticks
        self._backend_ok = jax.default_backend() == "tpu"
        self._use_pallas_req = use_pallas
        if use_pallas is True and self.precision != "single":
            raise InvalidParameterError(
                "the Pallas compression kernel is single-precision only")
        if self._restore_tables is not None:
            # Artifact restore: the tables (and the padding they were
            # built against) come prebuilt from the store — never start
            # the background build thread, whatever the auto rule says.
            self._s_pad = int(self._restore_tables.s_pad)
            if self._s_pad < p.num_sticks:
                raise InvalidParameterError(
                    f"restored plan tables pad {self._s_pad} stick rows "
                    f"but the index plan has {p.num_sticks}")
            return False
        # Auto threshold, re-measured round 3 with sync-cancelled timing
        # (scripts/sweep.py; the round-2 numbers carried ~5 ms of tunnel
        # readback per measurement, which hid the XLA path's small-size
        # advantage): 64^3/137k values XLA 0.45 vs kernel 0.74 ms;
        # 96^3/463k values kernel 1.0 vs XLA 5.2 ms; 128^3 kernel 0.4 vs
        # 14.7; 256^3 kernel 12.4 vs 129.8. Crossover between 137k and
        # 463k values -> 200k.
        auto = self._backend_ok and self.precision == "single" \
            and p.num_values >= 200_000
        if use_pallas is False or (use_pallas is None and not auto):
            return False
        if p.num_values == 0 or p.num_sticks == 0:
            return False
        self._s_pad = -(-(p.num_sticks + 1) // 32) * 32
        return True

    def _build_compression_tables(self) -> None:
        """The heavy half of the Pallas setup: gather inputs, the wide/
        narrow cover builds (native C++), and the device commit of the
        packed tables. Runs on the plan's background build thread;
        :meth:`_finalize` joins and re-raises any failure. The value
        order handling is unchanged: any order works, stick-major/
        z-ascending (the layout the reference recommends,
        details.rst 'Data Distribution') is optimal, and a too-scattered
        order falls back to the XLA gather with a logged notice."""
        from .ops import gather_kernel as gk
        _t0_tables = _time.perf_counter()
        try:
            from . import faults as _faults
            _faults.check_site("plan.build")
            p = self.index_plan
            use_pallas = self._use_pallas_req
            vi = p.value_indices.astype(np.int64)
            num_slots = self._s_pad * p.dim_z
            (dec_idx, occupied), (cmp_idx, cmp_valid) = \
                gk.compression_gather_inputs(vi, num_slots)
            dec = gk.build_best_gather_tables(dec_idx, occupied,
                                              p.num_values)
            # commit the first table set while the second builds on host
            if dec is not None:
                self._tables_hot["dec_tabs"] = gk.gather_device_tables(dec)
            cmp_ = gk.build_best_gather_tables(cmp_idx, cmp_valid,
                                               num_slots)
            if cmp_ is not None:
                self._tables_hot["cmp_tabs"] = gk.gather_device_tables(cmp_)
            if dec is None or cmp_ is None:
                from . import obs as _obs
                fell_back = [n for n, t in (("decompress", dec),
                                            ("compress", cmp_))
                             if t is None]
                for stage in fell_back:
                    _obs.record_plan_fallback(stage, "value_order")
                # WARNING only when the caller explicitly asked for the
                # kernel; auto mode logs at INFO.
                log = logger.warning if use_pallas is True else logger.info
                log(
                    "spfft_tpu: value order too scattered for the Pallas "
                    "compression kernel (%s) — using the slower XLA gather "
                    "path there (sort triplets with utils.workloads."
                    "sort_triplets_stick_major for the fast path)",
                    " and ".join(fell_back))
            self._build_fused_tables(dec_idx, occupied, cmp_idx, cmp_valid,
                                     num_slots, dec, cmp_)
            if dec is None and cmp_ is None:
                self._pallas_box = None
                return
            self._pallas_box = {"dec": dec, "cmp": cmp_}
            self._pallas_active_flag = self._backend_ok
        except BaseException as exc:  # re-raised by _finalize
            self._build_exc = exc
        finally:
            from . import obs as _obs
            _obs.record_compile(
                "compression_tables",
                _time.perf_counter() - _t0_tables, _t0_tables,
                num_values=int(self.index_plan.num_values),
                failed=self._build_exc is not None)

    def _build_fused_tables(self, dec_idx, occupied, cmp_idx, cmp_valid,
                            num_slots, dec_best, cmp_best) -> None:
        """Build the fused compression+z-DFT tables (ops/fused_kernel)
        for whichever directions pass the gate; record every decline as
        a ``spfft_plan_pallas_fallback_total`` reason. Runs on the
        background build thread, after the gather tables.

        The fused kernels consume the NARROW chunk decomposition
        (chunks of one 1024-slot tile, tile-major — the revisiting
        order the super-tile accumulation needs); when the preferred
        gather tables came out wide, a narrow set is built here just
        for the fused path."""
        from . import obs as _obs
        from .ops import dft as _dft
        from .ops import fused_kernel as fkm
        from .ops import gather_kernel as gk

        p = self.index_plan
        if not self._use_mdft or not fkm.enabled() \
                or not (self._backend_ok or fkm.interpret_forced()):
            return  # the fused path was never in play — nothing to record

        def narrow(best, idx, valid, n_src):
            if isinstance(best, gk.MonotoneGatherTables):
                return best
            if best is None:  # best-effort build already blew up
                return None
            return gk.build_monotone_gather_tables(idx, valid, n_src)

        reasons = {}
        box = {"dec": None, "cmp": None}
        # backward: gather-decompress + z-DFT. The r2c (0,0)-stick
        # hermitian completion runs BETWEEN decompress and the z stage
        # and rides INSIDE the kernel (ops/fused_kernel
        # ._complete_zero_stick), so r2c plans take the fused path too.
        zid = p.zero_stick_id if self._is_r2c else None
        nt = narrow(dec_best, dec_idx, occupied, p.num_values)
        if nt is None:
            reasons["dec"] = "value_order"
        else:
            out = fkm.build_fused_decompress_tables(
                nt, p.dim_z, self._s_pad, zero_stick_id=zid)
            if isinstance(out, str):
                reasons["dec"] = out
            else:
                box["dec"] = out
                self._tables_hot["fzd_tabs"] = \
                    fkm.decompress_device_tables(out)
                self._tables_hot["fzd_mats"] = fkm.commit_mats(
                    _dft.c2c_mats(p.dim_z, _dft.BACKWARD))
        # forward twin: z-DFT + compress gather, FULL scaling folded
        # into a second matrix triple at plan time
        ct = narrow(cmp_best, cmp_idx, cmp_valid, num_slots)
        if ct is None:
            reasons["cmp"] = "value_order"
        else:
            out = fkm.build_fused_compress_tables(ct, p.dim_z, self._s_pad)
            if isinstance(out, str):
                reasons["cmp"] = out
            else:
                box["cmp"] = out
                self._tables_hot["fzc_tabs"] = \
                    fkm.compress_device_tables(out)
                self._tables_hot["fzc_mats"] = fkm.commit_mats(
                    _dft.c2c_mats(p.dim_z, _dft.FORWARD))
                self._tables_hot["fzc_mats_s"] = fkm.commit_mats(
                    _dft.c2c_mats(p.dim_z, _dft.FORWARD,
                                  scale=1.0 / float(self.global_size)))
        stage_name = {"dec": "fused_decompress_zdft",
                      "cmp": "fused_zdft_compress"}
        for which, why in reasons.items():
            _obs.record_plan_fallback(stage_name[which], why)
            logger.info(
                "spfft_tpu: fused compression+DFT kernel unavailable for "
                "%s (%s) — keeping the two-kernel path there",
                stage_name[which], why)
        self._fused_reasons = reasons
        self._fused_box = box
        self._fused_active_flag = box["dec"] is not None \
            or box["cmp"] is not None

    def _commit_fallback(self, which: str) -> None:
        """Commit the XLA-gather fallback table for one compression
        direction (slot_src / value_indices — the big inverse maps that
        the Pallas path never reads)."""
        p = self.index_plan
        extra = self._s_pad - p.num_sticks
        if which == "dec" and "slot_src" not in self._tables_hot:
            ss = p.slot_src
            if extra:
                ss = np.concatenate(
                    [ss, np.full(extra * p.dim_z, p.num_values, np.int32)])
            self._tables_hot["slot_src"] = jnp.asarray(ss)
        if which == "cmp" and "value_indices" not in self._tables_hot:
            self._tables_hot["value_indices"] = jnp.asarray(
                p.value_indices)

    def _commit_restored(self, r: PlanTables) -> None:
        """Commit prebuilt tables from a plan artifact (the store's
        restore path): device-put the gather/fused tables the artifact
        carries, re-decide activation for THIS process's backend, and
        commit whatever fallback tables the outcome requires — the
        exact end state :meth:`_join_build` would have produced, with
        zero table construction."""
        from .ops import fused_kernel as fkm
        from .ops import gather_kernel as gk
        if self._use_pallas_req is False or self.precision != "single":
            # the caller (or the precision) rules the kernel path out —
            # mirror a fresh build's "never in play" end state
            self._commit_fallback("dec")
            self._commit_fallback("cmp")
            return
        box = r.pallas_box
        if box is not None and (box.get("dec") is not None
                                or box.get("cmp") is not None):
            self._pallas_box = {"dec": box.get("dec"),
                                "cmp": box.get("cmp")}
            if box.get("dec") is not None:
                self._tables_hot["dec_tabs"] = \
                    gk.gather_device_tables(box["dec"])
            if box.get("cmp") is not None:
                self._tables_hot["cmp_tabs"] = \
                    gk.gather_device_tables(box["cmp"])
            self._pallas_active_flag = self._backend_ok
        active = self._pallas_active_flag
        pb = self._pallas_box
        if pb is None or pb.get("dec") is None or not active:
            self._commit_fallback("dec")
        if pb is None or pb.get("cmp") is None or not active:
            self._commit_fallback("cmp")
        self._fused_reasons = dict(r.fused_reasons or {})
        fb = r.fused_box or {}
        if not self._use_mdft or not fkm.enabled() \
                or not (self._backend_ok or fkm.interpret_forced()):
            return
        from .ops import dft as _dft
        p = self.index_plan
        fbox = {"dec": None, "cmp": None}
        if fb.get("dec") is not None:
            fbox["dec"] = fb["dec"]
            self._tables_hot["fzd_tabs"] = \
                fkm.decompress_device_tables(fb["dec"])
            self._tables_hot["fzd_mats"] = fkm.commit_mats(
                _dft.c2c_mats(p.dim_z, _dft.BACKWARD))
        if fb.get("cmp") is not None:
            fbox["cmp"] = fb["cmp"]
            self._tables_hot["fzc_tabs"] = \
                fkm.compress_device_tables(fb["cmp"])
            self._tables_hot["fzc_mats"] = fkm.commit_mats(
                _dft.c2c_mats(p.dim_z, _dft.FORWARD))
            self._tables_hot["fzc_mats_s"] = fkm.commit_mats(
                _dft.c2c_mats(p.dim_z, _dft.FORWARD,
                              scale=1.0 / float(self.global_size)))
        self._fused_box = fbox
        self._fused_active_flag = fbox["dec"] is not None \
            or fbox["cmp"] is not None

    def export_tables(self) -> PlanTables:
        """Snapshot the plan's host-side built-table state for the
        persistent artifact store (joins the background build first).
        The snapshot is pure host data — numpy table dataclasses plus
        the stick padding they assume — and, together with the
        ``IndexPlan``, is everything a fresh process needs to
        reconstruct this plan without rebuilding anything."""
        self._finalize()
        box = None
        if self._pallas_box is not None:
            box = {"dec": self._pallas_box.get("dec"),
                   "cmp": self._pallas_box.get("cmp")}
        return PlanTables(
            s_pad=self._s_pad, pallas_box=box,
            fused_box={"dec": self._fused_box.get("dec"),
                       "cmp": self._fused_box.get("cmp")},
            fused_reasons=dict(self._fused_reasons))

    def install_aot(self, executables: dict) -> None:
        """Install ``jax.export``-deserialised executables for the
        default-placement public entries — the store's AOT prewarm.
        Single-request keys are ``"backward"`` / ``"forward_none"`` /
        ``"forward_full"``; batched keys (symbolic leading batch dim)
        are ``"batched_backward"`` / ``"batched_forward_none"`` /
        ``"batched_forward_full"``; identity fused-pair keys
        (``apply_pointwise`` with ``fn=None``) are ``"pair_none"`` /
        ``"pair_full"``. The first call then skips straight to
        execution instead of trace + lower (+ compile, when the
        backend's compilation cache misses)."""
        self._aot = dict(executables) if executables else None

    def _join_build(self) -> None:
        """Join the background table build (no-op afterwards) and commit
        whatever fallback tables the outcome requires. Never raises —
        :meth:`close`/``__del__`` use it for a silent teardown join."""
        th = self._build_thread
        if th is not None:
            th.join()
            self._build_thread = None
            if self._build_exc is None:
                box = self._pallas_box
                # an INACTIVE kernel (tables built off-TPU for testing)
                # still executes through the XLA gather, which needs the
                # fallback tables committed — use_pallas=True plans on
                # CPU used to KeyError on their first execution here
                active = self._pallas_active_flag
                if box is None or box["dec"] is None or not active:
                    self._commit_fallback("dec")
                if box is None or box["cmp"] is None or not active:
                    self._commit_fallback("cmp")

    def _finalize(self) -> None:
        """Join the background table build and surface any off-thread
        build failure as a typed :class:`~spfft_tpu.errors.TableBuildError`
        on first use. The failure is STICKY: every subsequent execution
        call re-raises it (a one-shot raise would leave later calls with
        neither pallas nor fallback tables committed and fail with a
        confusing KeyError inside the jitted pipeline — round-4 advisor
        finding)."""
        self._join_build()
        if self._build_exc is not None:
            from .errors import TableBuildError
            raise TableBuildError(
                f"the plan's background compression-table build failed: "
                f"{self._build_exc!r}", cause=self._build_exc)

    def check_build(self, wait: bool = False) -> None:
        """Surface background-builder DEATH without waiting for a
        request. ``wait=False`` (registration time: registry
        ``get_or_build`` resolution, executor registration) raises the
        sticky :class:`~spfft_tpu.errors.TableBuildError` only when the
        builder thread has ALREADY finished and failed — a live build
        is never blocked on. ``wait=True`` (warmup/prewarm, where
        blocking is the point) joins the build first, so a doomed plan
        fails before it is declared warm instead of on the first
        request (the round-14 error-latency fix)."""
        th = self._build_thread
        if not wait and th is not None and th.is_alive():
            return
        self._finalize()

    def close(self) -> None:
        """Join the plan's background compression-table build thread.
        Plans are otherwise passive (XLA owns the executables), but an
        abandoned plan must not leak a running builder: ``close`` (or
        garbage collection via ``__del__``) blocks until the thread is
        done. Never raises — a failed build surfaces as
        :class:`~spfft_tpu.errors.TableBuildError` on the next
        execution call, not at teardown. Idempotent."""
        self._join_build()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown: modules may already be gone

    @property
    def _pallas(self):
        self._finalize()
        return self._pallas_box

    @property
    def _pallas_active(self) -> bool:
        self._finalize()
        return self._pallas_active_flag

    @_pallas_active.setter
    def _pallas_active(self, value: bool) -> None:
        # tests force the kernel path in interpret mode on CPU
        self._finalize()
        self._pallas_active_flag = bool(value)

    @property
    def _fused(self):
        self._finalize()
        return self._fused_box

    def _fused_on(self, which: str, pallas: bool = True) -> bool:
        """Trace-time dispatch gate for one fused direction (``"dec"``
        backward / ``"cmp"`` forward). Callers reach this inside the
        jitted pipelines, after the public entry already finalized. A
        direction demoted at RUNTIME (:meth:`_fused_demote`) gates off
        here too, except while its bounded re-probe is running."""
        rec = self._fused_demotions.get(which)
        if rec is not None and not rec["probing"]:
            return False
        return (pallas and self._fused_active_flag
                and self._fused_box.get(which) is not None)

    #: Unfused successes a demoted direction banks before one fused
    #: re-probe, and how many failed probes make the demotion permanent.
    FUSED_REPROBE_AFTER = 32
    FUSED_REPROBE_MAX = 3

    def _invalidate_fused_jits(self, which: str) -> None:
        """Drop every cached executable that baked the ``which``
        direction's fused gate into its traced program, so the next
        dispatch re-traces under the CURRENT gate. Runtime demotion
        needs this: a real execution-time kernel failure lives inside
        an already-compiled executable, which would otherwise re-run
        the same broken launch forever."""
        if which == "dec":
            self._backward_jit = jax.jit(self._backward_impl)
            if self._aot is not None:
                self._aot.pop("backward", None)
                self._aot.pop("batched_backward", None)
        else:
            self._forward_jit = {
                Scaling.NONE: jax.jit(functools.partial(
                    self._forward_impl, scaled=False)),
                Scaling.FULL: jax.jit(functools.partial(
                    self._forward_impl, scaled=True)),
            }
            if self._aot is not None:
                self._aot.pop("forward_none", None)
                self._aot.pop("forward_full", None)
                self._aot.pop("batched_forward_none", None)
                self._aot.pop("batched_forward_full", None)
        if self._aot is not None:
            # the fused pair bakes BOTH directions into one program
            self._aot.pop("pair_none", None)
            self._aot.pop("pair_full", None)
        self._batched = None
        self._pair_jits = {}

    def _fused_demote(self, which: str, exc: BaseException,
                      probing: bool) -> None:
        """Stickily demote one direction to the unfused composition
        after a device-attributed launch/execution failure: record the
        reason (``fused_fallback_reasons`` + counter), gate the
        direction off and invalidate its executables. A failure during
        a re-probe re-demotes with the probe budget decremented; out of
        budget, the demotion is permanent (no further probes)."""
        rec = self._fused_demotions.get(which)
        if rec is None:
            rec = self._fused_demotions[which] = {
                "reason": "", "unfused_ok": 0, "probes": 0,
                "probing": False, "permanent": False}
        rec["reason"] = f"runtime: {type(exc).__name__}: {exc}"
        rec["unfused_ok"] = 0
        rec["probing"] = False
        if probing:
            rec["probes"] += 1
            rec["permanent"] = rec["probes"] >= self.FUSED_REPROBE_MAX
        self._fused_reasons[which] = rec["reason"]
        from . import obs as _obs
        _obs.GLOBAL_COUNTERS.inc("spfft_fused_demotions_total",
                                 which=which)
        if probing:
            _obs.GLOBAL_COUNTERS.inc("spfft_fused_reprobes_total",
                                     which=which, outcome="failed")
        _obs.record_event("fused.demote", which=which,
                          reason=rec["reason"],
                          permanent=rec["permanent"])
        logger.warning(
            "spfft_tpu: fused %s kernel failed at runtime (%r) — "
            "demoted to the unfused composition%s", which, exc,
            " permanently" if rec["permanent"] else
            f" (re-probe after {self.FUSED_REPROBE_AFTER} requests)")
        self._invalidate_fused_jits(which)

    def _fused_readmit(self, which: str) -> None:
        """A re-probe succeeded: lift the demotion (the fused trace that
        just ran stays cached) and count the readmission."""
        rec = self._fused_demotions.pop(which, None)
        self._fused_reasons.pop(which, None)
        from . import obs as _obs
        _obs.GLOBAL_COUNTERS.inc("spfft_fused_reprobes_total",
                                 which=which, outcome="readmitted")
        _obs.record_event("fused.readmit", which=which,
                          probes=rec["probes"] if rec else 0)
        logger.info(
            "spfft_tpu: fused %s kernel re-probe succeeded after %d "
            "failed probe(s) — readmitted", which,
            rec["probes"] if rec else 0)

    def fused_demotions(self) -> dict:
        """Snapshot of the runtime demotion ladder, per direction:
        ``{"dec"/"cmp": {"reason", "unfused_ok", "probes", "probing",
        "permanent"}}`` — empty when nothing is demoted."""
        return {k: dict(v) for k, v in self._fused_demotions.items()}

    def _guarded(self, which: str, call):
        """Dispatch one public execution whose traced program may run
        the ``which`` fused kernel, under the runtime demotion ladder:
        a device-attributed failure (an injected ``kernel.launch``
        fault, a Mosaic lowering error, a runtime launch failure)
        demotes the direction and RETRIES the same dispatch unfused —
        the request succeeds on the fallback composition instead of
        failing, and so does every subsequent request. Request-shaped
        errors (bad payloads) propagate untouched. ``call`` must read
        the jit caches at call time (a closure over ``self``), so the
        post-demotion retry picks up the re-traced executables."""
        rec = self._fused_demotions.get(which)
        probing = rec is not None and rec["probing"]
        fused = (self._fused_active_flag
                 and self._fused_box.get(which) is not None
                 and (rec is None or probing))
        if not fused:
            out = call()
            if rec is not None and not probing and not rec["permanent"]:
                rec["unfused_ok"] += 1
                if rec["unfused_ok"] >= self.FUSED_REPROBE_AFTER:
                    rec["probing"] = True
                    self._invalidate_fused_jits(which)
            return out
        from . import faults as _faults
        try:
            _faults.check_site("kernel.launch")
            out = call()
        except Exception as exc:
            if not _faults.attributes_device(exc):
                raise
            self._fused_demote(which, exc, probing)
            return call()
        if probing:
            self._fused_readmit(which)
        return out

    @property
    def _tables(self):
        """The FULL committed table set (hot-path tables plus every
        fallback/debug table) — for tests, probes and explicit
        ``pallas=False`` comparisons. Hot execution passes
        ``_tables_hot``, which carries only what the active path reads."""
        self._finalize()
        if self._tables_full is None:
            p = self.index_plan
            full = dict(self._tables_hot)
            if "slot_src" not in full:
                extra = self._s_pad - p.num_sticks
                ss = p.slot_src
                if extra:
                    ss = np.concatenate(
                        [ss, np.full(extra * p.dim_z, p.num_values,
                                     np.int32)])
                full["slot_src"] = jnp.asarray(ss)
            if "value_indices" not in full:
                full["value_indices"] = jnp.asarray(p.value_indices)
            if "scatter_cols" not in full:
                extra = self._s_pad - p.num_sticks
                sc = p.scatter_cols
                if extra:
                    sc = np.concatenate([sc, np.zeros(extra, np.int32)])
                full["scatter_cols"] = jnp.asarray(sc)
            if "col_inv" not in full:
                full["col_inv"] = jnp.asarray(p.col_inv)
            self._tables_full = full
        return self._tables_full

    def _init_split_x(self) -> None:
        """Enable the sparse-x xy-stage when the occupied x columns span
        under 70% of the x extent (the reference's "y transform over
        non-empty x-rows only", execution_host.cpp:139-145): the y-FFT then
        runs only on the occupied x window instead of the full plane. For
        C2C the window is *cyclic* — centered sets (negative x stored high)
        occupy a wrapped window, the flagship plane-wave sphere on a
        2x-cutoff grid included. For R2C it is a linear window of the half
        spectrum; plane symmetry applies to the x=0 sub-column when the
        window starts at 0 (when it doesn't, no x=0 stick exists and there
        is nothing to complete)."""
        from .indexing import (inverse_col_map, occupied_x_window,
                               window_sub_cols)

        p = self.index_plan
        self._split_x = None
        if p.num_sticks == 0:
            return
        if self._ds:
            return  # the double-single pipeline runs the dense path
        from .ops.dft import (MATMUL_DFT_DIRECT_FALLBACK_MAX,
                              _direct_form_len)
        x_direct = (p.dim_x <= MATMUL_DFT_DIRECT_FALLBACK_MAX
                    if self._is_r2c else _direct_form_len(p.dim_x))
        if self._use_mdft and not x_direct:
            # the split-x contraction needs PLAIN row/column-selected
            # matrices: the C2C builders return TwoStageMats for
            # composite axes above the cap (those run dense), while the
            # r2c/c2r builders are direct at any length up to the
            # fallback cap — prime-fallback and R2C axes keep the split
            return
        xf = p.dim_x_freq
        xs = p.scatter_cols % xf
        x0, w = occupied_x_window(xs, xf, allow_wrap=not self._is_r2c)
        if w > 0.7 * xf:
            return
        self._split_x = (x0, w)
        pads = np.zeros(self._s_pad - p.num_sticks, np.int32)
        if self._use_mdft:
            # T layout: window-x-major columns x_w * dim_y + y
            x_w = (p.stick_x.astype(np.int64) - x0) % xf
            cols_sub_t = (x_w * p.dim_y
                          + p.stick_y.astype(np.int64)).astype(np.int32)
            self._tables_hot["col_inv_sub_t"] = jnp.asarray(
                inverse_col_map(cols_sub_t, w * p.dim_y, p.num_sticks))
            self._tables_hot["scatter_cols_sub_t"] = jnp.asarray(
                np.concatenate([cols_sub_t, pads]))
        else:
            cols_sub = window_sub_cols(p.scatter_cols, xf, x0, w)
            col_inv_sub = inverse_col_map(cols_sub, p.dim_y * w,
                                          p.num_sticks)
            self._tables_hot["col_inv_sub"] = jnp.asarray(col_inv_sub)
            self._tables_hot["scatter_cols_sub"] = jnp.asarray(
                np.concatenate([cols_sub, pads]))

    def _tables_on(self, device):
        """The hot table set replicated onto ``device`` (cached per
        device; ``None`` = the default placement). Serving executors
        schedule independent requests across a device pool — jit
        dispatches on argument placement, so pinning an execution to a
        device means its tables must live there too. Call after
        ``_finalize`` (the hot dict can still gain fallback entries
        before the background build resolves)."""
        if device is None:
            return self._tables_hot
        cached = self._device_tables.get(device)
        if cached is None:
            cached = jax.device_put(self._tables_hot, device)
            self._device_tables[device] = cached
        return cached

    def estimated_device_bytes(self) -> int:
        """Approximate resident bytes this plan pins for its lifetime:
        the committed device tables (hot dict, whatever paths have
        committed so far) plus the host-side index arrays and the
        double-single matrix set when present. Used by the serving
        plan registry (spfft_tpu.serve.registry) for its byte-aware
        LRU budget; an ESTIMATE — XLA executable buffers are excluded
        (they are owned by the compilation cache, not the plan) and a
        still-running background table build is counted at its current
        state rather than joined."""
        pieces = [self._tables_hot]
        if getattr(self, "_ds_mats", None):
            pieces.append(tuple(self._ds_mats.values()))
        p = self.index_plan
        pieces.append((p.value_indices, p.stick_keys))
        leaves = jax.tree_util.tree_leaves(pieces)
        return sum(int(getattr(leaf, "nbytes", 0)) for leaf in leaves)

    @property
    def pallas_active(self) -> bool:
        """True when the compression stages run the Pallas windowed-gather
        kernel (TPU backend, single precision, value order coherent enough
        for the chunk decomposition). False means the XLA gather path."""
        return self._pallas_active

    @property
    def fused_active(self) -> bool:
        """True when at least one direction runs the FUSED
        compression+z-DFT kernel (ops/fused_kernel.py): the dense
        ``(num_sticks, dim_z)`` planar stick intermediate between the
        compression gather and the z stage never touches HBM there.
        Per-direction detail in :attr:`fused_fallback_reasons`."""
        self._finalize()
        return self._fused_active_flag

    @property
    def fused_fallback_reasons(self) -> dict:
        """Per-direction fallback reasons of the fused
        compression+z-DFT gate: ``{"dec": reason, "cmp": reason}`` with
        entries only for directions that DECLINED (empty dict = both
        fused, or the fused path was never in play — non-mdft pipeline,
        disabled, or no Pallas build). Reasons mirror the
        ``spfft_plan_pallas_fallback_total{stage,reason}`` counter."""
        self._finalize()
        return dict(self._fused_reasons)

    @property
    def pair_values_io(self) -> bool:
        """True when this plan's device-side value arrays use the planar
        PAIR layout (2, num_values) — row 0 real, row 1 imaginary —
        instead of interleaved rows (num_values, 2); large plans only
        (see PAIR_IO_THRESHOLD). ``backward`` accepts both (and complex/
        numpy inputs as always); ``forward``/``apply_pointwise`` then
        RETURN the pair layout; ``np.asarray(out).T`` gives rows."""
        return self._pair_io

    # -- reference Transform getters (transform.hpp:91-151) -----------------
    @property
    def transform_type(self) -> TransformType:
        return self.index_plan.transform_type

    @property
    def dim_x(self) -> int:
        return self.index_plan.dim_x

    @property
    def dim_y(self) -> int:
        return self.index_plan.dim_y

    @property
    def dim_z(self) -> int:
        return self.index_plan.dim_z

    @property
    def local_z_length(self) -> int:
        return self.index_plan.dim_z

    @property
    def local_z_offset(self) -> int:
        return 0

    @property
    def local_slice_size(self) -> int:
        """dim_x * dim_y * local_z_length (reference: transform.cpp:99)."""
        return self.dim_x * self.dim_y * self.local_z_length

    @property
    def num_local_elements(self) -> int:
        return self.index_plan.num_values

    @property
    def num_global_elements(self) -> int:
        return self.index_plan.num_values

    @property
    def global_size(self) -> int:
        return self.dim_x * self.dim_y * self.dim_z

    # -- jitted pipelines ----------------------------------------------------
    @property
    def _is_r2c(self) -> bool:
        return self.index_plan.hermitian

    def _decompress(self, values_il, tables, pallas=True):
        p = self.index_plan
        if not pallas or not self._pallas_active \
                or self._pallas["dec"] is None:
            if self._pair_io and values_il.shape[0] == 2:
                values_il = values_il.T  # pair boundary -> rows, XLA path
            return stages.decompress(values_il.astype(self._rdt),
                                     tables["slot_src"], self._s_pad,
                                     p.dim_z)
        from .ops import gather_kernel as gk
        t = self._pallas["dec"]
        re, im = gk.planar_from_interleaved(values_il.astype(np.float32),
                                            t.src_rows, pair=self._pair_io)
        out_re, out_im = gk.run_gather(re, im, tables["dec_tabs"], t)
        flat = (out_re.reshape(-1)[:t.num_out]
                + 1j * out_im.reshape(-1)[:t.num_out])
        return flat.reshape(self._s_pad, p.dim_z)

    def _compress(self, sticks, tables, scale, pallas=True):
        p = self.index_plan
        if not pallas or not self._pallas_active \
                or self._pallas["cmp"] is None:
            values = stages.compress(sticks, tables["value_indices"], scale)
            return values.T if self._pair_io else values
        from .ops import gather_kernel as gk
        t = self._pallas["cmp"]
        re, im = gk.planar_from_complex(sticks, t.src_rows)
        out_re, out_im = gk.run_gather(re, im, tables["cmp_tabs"], t)
        values = gk.interleaved_from_planar(out_re, out_im, t.num_out,
                                            pair=self._pair_io)
        if scale is not None:
            values = values * jnp.asarray(scale, values.dtype)
        return values

    def _decompress_planar(self, values_il, tables, pallas=True):
        """Values -> PLANAR stick channels (sr, si), each (s_pad, dim_z)
        f32 — the mdft pipeline's native form (no complex interleave)."""
        p = self.index_plan
        if not pallas or not self._pallas_active \
                or self._pallas["dec"] is None:
            if self._pair_io and values_il.shape[0] == 2:
                values_il = values_il.T
            flat = stages.gather_rows_with_sentinel(
                values_il.astype(self._rdt), tables["slot_src"])
            return (flat[:, 0].reshape(self._s_pad, p.dim_z),
                    flat[:, 1].reshape(self._s_pad, p.dim_z))
        from .ops import gather_kernel as gk
        t = self._pallas["dec"]
        re, im = gk.planar_from_interleaved(values_il.astype(np.float32),
                                            t.src_rows, pair=self._pair_io)
        out_re, out_im = gk.run_gather(re, im, tables["dec_tabs"], t)
        return (out_re.reshape(-1)[:t.num_out].reshape(self._s_pad,
                                                       p.dim_z),
                out_im.reshape(-1)[:t.num_out].reshape(self._s_pad,
                                                       p.dim_z))

    def _compress_planar(self, sr, si, tables, pallas=True):
        """PLANAR stick channels -> the plan's value output layout
        (scaling already folded into the z-DFT matrix upstream)."""
        p = self.index_plan
        if not pallas or not self._pallas_active \
                or self._pallas["cmp"] is None:
            flat = jnp.stack([sr.reshape(-1), si.reshape(-1)], axis=-1)
            values = flat[tables["value_indices"]]
            return values.T if self._pair_io else values
        from .ops import gather_kernel as gk
        t = self._pallas["cmp"]
        pad = t.src_rows * 128 - sr.size
        re = jnp.pad(sr.reshape(-1), (0, pad)).reshape(t.src_rows, 128)
        im = jnp.pad(si.reshape(-1), (0, pad)).reshape(t.src_rows, 128)
        out_re, out_im = gk.run_gather(re, im, tables["cmp_tabs"], t)
        return gk.interleaved_from_planar(out_re, out_im, t.num_out,
                                          pair=self._pair_io)

    def _decompress_zdft(self, values_il, tables):
        """Values -> z-TRANSFORMED planar stick channels (sr, si) in ONE
        fused Pallas kernel (ops/fused_kernel.run_decompress_zdft): the
        dense pre-FFT stick intermediate never touches HBM. Accepts the
        batched (B, ...) boundary too (batched kernel grid)."""
        from .ops import fused_kernel as fkm
        from .ops import gather_kernel as gk
        t = self._fused_box["dec"]
        re, im = gk.planar_from_interleaved(values_il.astype(np.float32),
                                            t.src_rows, pair=self._pair_io)
        sr, si = fkm.run_decompress_zdft(
            re, im, tables["fzd_tabs"], tables["fzd_mats"], t,
            interpret=fkm.interpret_forced())
        s = self._s_pad
        if sr.ndim == 3:
            return sr[:, :s], si[:, :s]
        return sr[:s], si[:s]

    def _zdft_compress(self, sr, si, tables, scaled: bool):
        """RAW planar stick channels -> the plan's value output layout
        in ONE fused Pallas kernel (ops/fused_kernel.run_zdft_compress);
        FULL scaling is folded into the plan-time matrix triple
        (compile-time scaling). Accepts batched (B, ...) sticks."""
        from .ops import fused_kernel as fkm
        from .ops import gather_kernel as gk
        t = self._fused_box["cmp"]
        sr, si = fkm.pad_sticks_planar(sr, si, t.src_sticks)
        out_re, out_im = fkm.run_zdft_compress(
            sr, si, tables["fzc_tabs"],
            tables["fzc_mats_s" if scaled else "fzc_mats"], t,
            interpret=fkm.interpret_forced())
        return gk.interleaved_from_planar(out_re, out_im, t.num_out,
                                          pair=self._pair_io)

    def _bwd_space_tp(self, values_il, tables, pallas=True):
        """The mdft backward pipeline with the fused-kernel dispatch:
        values -> planar/real space. Fused when the gate admitted the
        decompress direction, else decompress + z + tail unfused."""
        if self._fused_on("dec", pallas):
            sr, si = self._decompress_zdft(values_il, tables)
            return self._backward_after_z(sr, si, tables)
        sr, si = self._decompress_planar(values_il, tables, pallas)
        return self._backward_rest_tp(sr, si, tables)

    def _fwd_values_tp(self, space_p, tables, scaled: bool, pallas=True):
        """The mdft forward pipeline with the fused-kernel dispatch:
        planar/real space -> values in the plan's output layout."""
        if self._fused_on("cmp", pallas):
            sr, si = self._forward_pre_z(space_p, tables)
            return self._zdft_compress(sr, si, tables, scaled)
        scale = 1.0 / self.global_size if scaled else None
        sr, si = self._forward_head_tp(space_p, tables, scale)
        return self._compress_planar(sr, si, tables, pallas)

    def _backward_rest_tp(self, sr, si, tables):
        """Matmul-DFT T-layout tail of backward, fully PLANAR (separate
        re/im f32 arrays — XLA stores c64 interleaved T(2,128), so every
        complex materialisation between stages is an interleave copy the
        planar form never pays): z-DFT on sticks, unpack into the
        TRANSPOSED plane grid (planes, x, y), y-DFT on the minor axis,
        one swap, then the x-stage. Returns (xr, xi) planar space for
        C2C, the real space slab for R2C."""
        from .ops import dft
        p = self.index_plan
        if self._is_r2c and p.zero_stick_id is not None:
            # complete the (0,0) stick: conj = im sign flip in planar form
            zid = p.zero_stick_id
            rr, ri = sr[zid], si[zid]
            nz = (rr != 0) | (ri != 0)
            sr = sr.at[zid].set(jnp.where(nz, rr, jnp.roll(rr[::-1], 1)))
            si = si.at[zid].set(jnp.where(nz, ri, -jnp.roll(ri[::-1], 1)))
        sr, si = dft.pdft_last_opt(sr, si,
                                   dft.c2c_mats(p.dim_z, dft.BACKWARD))
        return self._backward_after_z(sr, si, tables)

    def _backward_after_z(self, sr, si, tables):
        """Everything of the T-layout backward tail AFTER the z-stage:
        unpack into the transposed plane grid, y-DFT, swap, x-stage.
        Split out so the fused decompress+z-DFT kernel (which emits
        already-transformed sticks) can join the pipeline here."""
        from .ops import dft
        p = self.index_plan
        xf = p.dim_x_freq
        unpack = stages.sticks_to_grid_padded \
            if self._s_pad > p.num_sticks else stages.sticks_to_grid
        if self._split_x is not None:
            x0, w = self._split_x
            col_tab = tables["col_inv_sub_t"]
            rows = tuple(int(r) for r in (x0 + np.arange(w)) % xf)
        else:
            x0, w = 0, xf
            col_tab = tables["col_inv_t"]
            rows = None
        gr = unpack(sr, col_tab, w, p.dim_y)
        gi = unpack(si, col_tab, w, p.dim_y)
        if self._is_r2c and x0 == 0:
            # complete the x=0 sub-plane along y (contiguous in T layout)
            cr, ci = gr[:, 0, :], gi[:, 0, :]
            nz = (cr != 0) | (ci != 0)
            gr = gr.at[:, 0, :].set(
                jnp.where(nz, cr, jnp.roll(cr[:, ::-1], 1, axis=-1)))
            gi = gi.at[:, 0, :].set(
                jnp.where(nz, ci, -jnp.roll(ci[:, ::-1], 1, axis=-1)))
        y_mats = dft.c2c_mats(p.dim_y, dft.BACKWARD)
        if self._is_r2c:
            mats = dft.c2r_mats(p.dim_x) if rows is None \
                else dft.sub_rows_c2r_mats(p.dim_x, rows)
            return dft.pdft2_minor_cr(gr, gi, y_mats, mats)
        mats = dft.c2c_mats(p.dim_x, dft.BACKWARD) if rows is None \
            else dft.sub_rows_mats(p.dim_x, dft.BACKWARD, rows)
        return dft.pdft2_minor(gr, gi, y_mats, mats)

    def _backward_rest_t(self, sticks, tables):
        """Complex-dtype wrapper of :meth:`_backward_rest_tp` (the batched
        path feeds complex sticks); returns the public interleaved (C2C)
        or real (R2C) space layout."""
        out = self._backward_rest_tp(jnp.real(sticks), jnp.imag(sticks),
                                     tables)
        if self._is_r2c:
            return out
        return jnp.stack([out[0], out[1]], axis=-1)

    def _forward_head_tp(self, space_p, tables, scale):
        """Planar T-layout head of forward: x-stage on the minor axis,
        one swap into the transposed grid, y-DFT minor, pack, then the
        z-DFT with any FULL scaling folded into its matrix. ``space_p``
        is (xr, xi) planar for C2C, the real slab for R2C. Returns
        (sr, si) planar sticks."""
        from .ops import dft
        p = self.index_plan
        sr, si = self._forward_pre_z(space_p, tables)
        return dft.pdft_last_opt(
            sr, si, dft.c2c_mats(p.dim_z, dft.FORWARD,
                                 scale=scale if scale else 1.0))

    def _forward_pre_z(self, space_p, tables):
        """The forward head UP TO the z-stage (xy stages + pack into
        raw sticks) — the seam the fused z-DFT+compress kernel joins
        at. Returns un-transformed (sr, si) planar sticks."""
        from .ops import dft
        p = self.index_plan
        xf = p.dim_x_freq
        y_mats = dft.c2c_mats(p.dim_y, dft.FORWARD)
        if self._split_x is not None:
            x0, w = self._split_x
            cols = tuple(int(c) for c in (x0 + np.arange(w)) % xf)
            cols_tab = tables["scatter_cols_sub_t"]
            if self._is_r2c:
                gr, gi = dft.prdft2_minor(
                    space_p.astype(self._rdt),
                    dft.sub_cols_r2c_mats(p.dim_x, cols), y_mats)
            else:
                gr, gi = dft.pdft2_minor(
                    space_p[0].astype(self._rdt),
                    space_p[1].astype(self._rdt),
                    dft.sub_cols_mats(p.dim_x, dft.FORWARD, cols), y_mats)
        else:
            cols_tab = tables["scatter_cols_t"]
            if self._is_r2c:
                gr, gi = dft.prdft2_minor(space_p.astype(self._rdt),
                                          dft.r2c_mats(p.dim_x), y_mats)
            else:
                gr, gi = dft.pdft2_minor(space_p[0].astype(self._rdt),
                                         space_p[1].astype(self._rdt),
                                         dft.c2c_mats(p.dim_x, dft.FORWARD),
                                         y_mats)
        sr = stages.grid_to_sticks(gr, cols_tab)
        si = stages.grid_to_sticks(gi, cols_tab)
        return sr, si

    def _forward_head_t(self, space, tables, scale):
        """Complex-dtype wrapper of :meth:`_forward_head_tp` (batched
        path): interleaved/real space in, complex sticks out."""
        sp = space if self._is_r2c else (space[..., 0], space[..., 1])
        sr, si = self._forward_head_tp(sp, tables, scale)
        return sr + 1j * si

    def _backward_rest(self, sticks, tables):
        """Everything after decompress: symmetry, z-IFFT, unpack, xy-IFFT."""
        if self._use_mdft:
            return self._backward_rest_t(sticks, tables)
        p = self.index_plan
        if self._is_r2c and p.zero_stick_id is not None:
            zid = p.zero_stick_id
            sticks = sticks.at[zid].set(
                stages.complete_stick_hermitian(sticks[zid]))
        sticks = stages.z_backward(sticks)
        unpack = stages.sticks_to_grid_padded \
            if self._s_pad > p.num_sticks else stages.sticks_to_grid
        if self._split_x is not None:
            x0, w = self._split_x
            sub = unpack(sticks, tables["col_inv_sub"], p.dim_y, w)
            if self._is_r2c:
                if x0 == 0:
                    sub = stages.complete_plane_hermitian(sub)
                return stages.xy_backward_r2c_split(sub, x0, p.dim_x,
                                                    p.dim_x_freq)
            return complex_to_interleaved(
                stages.xy_backward_c2c_split(sub, x0, p.dim_x))
        grid = unpack(sticks, tables["col_inv"], p.dim_y, p.dim_x_freq)
        if self._is_r2c:
            grid = stages.complete_plane_hermitian(grid)
            return stages.xy_backward_r2c(grid, p.dim_x)
        return complex_to_interleaved(stages.xy_backward_c2c(grid))

    # -- on-device double (double-single channels, ops/dsdft.py) ------------
    @staticmethod
    def _ds_complete(ch, idx):
        """Hermitian completion of ``ch[..., idx, :]`` along the minor
        axis on double-single channels [rh, rl, ih, il]: where an
        element was not supplied (all four channels zero), fill the
        conj reflection — sign-flipped on the imaginary channels; hi
        and lo transform identically (the DS twin of
        stages.complete_stick_hermitian / the x=0 plane completion)."""
        rows = tuple(c[..., idx, :] for c in ch)
        nz = (rows[0] != 0) | (rows[1] != 0) \
            | (rows[2] != 0) | (rows[3] != 0)

        def refl(v):
            return jnp.roll(v[..., ::-1], 1, axis=-1)

        return tuple(
            c.at[..., idx, :].set(jnp.where(
                nz, r, refl(r) if k < 2 else -refl(r)))
            for k, (c, r) in enumerate(zip(ch, rows)))

    def _ds_backward_impl(self, values_il, tables):
        """Backward on (N, 4) double-single channels [rh, rl, ih, il]:
        gathers are dtype-agnostic row moves, every DFT stage is the
        exact-sliced complex contraction, T-layout with one swap per
        direction (same dataflow as the mdft pipeline). Returns the
        (dim_z, dim_y, dim_x, 4) channel slab."""
        from .ops import dsdft
        p = self.index_plan
        flat = stages.gather_rows_with_sentinel(values_il,
                                                tables["slot_src"])
        ch = tuple(flat[..., k].reshape(flat.shape[:-2]
                                        + (p.num_sticks, p.dim_z))
                   for k in range(4))
        if self._is_r2c and p.zero_stick_id is not None:
            # complete the (0,0) stick (conj reflection = sign flip on
            # the imaginary channels) — hi and lo transform identically
            ch = self._ds_complete(ch, p.zero_stick_id)
        ch = dsdft.ds_cdft_last(*ch, self._ds_mats["z_b"])
        ch = tuple(stages.sticks_to_grid(c, tables["col_inv_t"],
                                         p.dim_x_freq, p.dim_y)
                   for c in ch)
        if self._is_r2c:
            # complete the x=0 sub-plane along y (minor axis in T layout)
            ch = self._ds_complete(ch, 0)
        ch = dsdft.ds_cdft_last(*ch, self._ds_mats["y_b"])
        ch = tuple(jnp.swapaxes(c, -1, -2) for c in ch)
        if self._is_r2c:
            oh, ol = dsdft.ds_irdft_last(*ch, self._ds_mats["x_b"])
            return jnp.stack([oh, ol], axis=-1)
        ch = dsdft.ds_cdft_last(*ch, self._ds_mats["x_b"])
        return jnp.stack(ch, axis=-1)

    def _ds_forward_impl(self, space4, tables, scaled: bool):
        """Forward mirror: (dim_z, dim_y, dim_x, 4) -> (N, 4), FULL
        scaling folded into the f64 z matrix before slicing."""
        from .ops import dsdft
        if self._is_r2c:
            ch = dsdft.ds_rdft_last(space4[..., 0], space4[..., 1],
                                    self._ds_mats["x_f"])
        else:
            ch = dsdft.ds_cdft_last(*(space4[..., k] for k in range(4)),
                                    self._ds_mats["x_f"])
        ch = tuple(jnp.swapaxes(c, -1, -2) for c in ch)
        ch = dsdft.ds_cdft_last(*ch, self._ds_mats["y_f"])
        ch = tuple(stages.grid_to_sticks(c, tables["scatter_cols_t"])
                   for c in ch)
        ch = dsdft.ds_cdft_last(*ch,
                                self._ds_mats["z_fs" if scaled else "z_f"])
        flat = jnp.stack([c.reshape(-1) for c in ch], axis=-1)
        return flat[tables["value_indices"]]

    def _ds_space_to_host(self, out) -> np.ndarray:
        """Channel slab -> host f64: (…, 4) -> interleaved (…, 2), or
        the R2C real slab (…, 2) [hi, lo] -> real (…,)."""
        from .ops import dsdft
        a = np.asarray(out)
        if a.shape[-1] == 2:  # real (hi, lo)
            return dsdft.combine_host_f64(a[..., 0], a[..., 1])
        return np.stack([dsdft.combine_host_f64(a[..., 0], a[..., 1]),
                         dsdft.combine_host_f64(a[..., 2], a[..., 3])],
                        axis=-1)

    _ds_values_to_host = _ds_space_to_host  # same channel layout

    def _apply_value_conj(self, values, *, batched=False):
        """Sign-flip the imaginary lane of the values folded from the
        redundant hermitian half (:attr:`IndexPlan.value_conj`): the
        backward input and the forward output are conjugated at the
        boundary in whatever layout the values take — interleaved
        (..., N, 2), the planar pair (..., 2, N), or double-single
        channels (..., N, 4). No-op (no graph nodes) when nothing was
        folded."""
        if self._conj_mults is None:
            return values
        if self._ds:
            m = self._conj_mults["ds"]
        elif self._pair_io and values.shape[1 if batched else 0] == 2:
            m = self._conj_mults["pair"]
        else:
            m = self._conj_mults["il"]
        return values * jnp.asarray(m, values.dtype)

    def _backward_impl(self, values_il, tables, *, pallas=True):
        values_il = self._apply_value_conj(values_il)
        if self._ds:
            return self._ds_backward_impl(values_il, tables)
        if self._use_mdft:
            out = self._bwd_space_tp(values_il, tables, pallas)
            if self._is_r2c:
                return out
            return jnp.stack([out[0], out[1]], axis=-1)
        return self._backward_rest(
            self._decompress(values_il, tables, pallas), tables)

    def _forward_head(self, space, tables, scale=None):
        """Everything before compress: xy-FFT, pack, z-FFT -> sticks.
        ``scale`` (mdft path only) folds FULL scaling into the z matrix."""
        if self._use_mdft:
            return self._forward_head_t(space, tables, scale)
        if self._is_r2c:
            if self._split_x is not None:
                x0, w = self._split_x
                grid = stages.xy_forward_r2c_split(
                    space.astype(self._rdt), x0, w)
                sticks = stages.grid_to_sticks(grid,
                                               tables["scatter_cols_sub"])
            else:
                grid = stages.xy_forward_r2c(space.astype(self._rdt))
                sticks = stages.grid_to_sticks(grid, tables["scatter_cols"])
        elif self._split_x is not None:
            x0, w = self._split_x
            grid = stages.xy_forward_c2c_split(
                interleaved_to_complex(space).astype(self._cdt), x0, w)
            sticks = stages.grid_to_sticks(grid,
                                           tables["scatter_cols_sub"])
        else:
            grid = stages.xy_forward_c2c(
                interleaved_to_complex(space).astype(self._cdt))
            sticks = stages.grid_to_sticks(grid, tables["scatter_cols"])
        return stages.z_forward(sticks)

    def _forward_impl(self, space, tables, *, scaled: bool, pallas=True):
        if self._ds:
            return self._apply_value_conj(
                self._ds_forward_impl(space, tables, scaled))
        if self._use_mdft:  # planar pipeline, scale folded into z matrix
            sp = space if self._is_r2c else (space[..., 0], space[..., 1])
            return self._apply_value_conj(
                self._fwd_values_tp(sp, tables, scaled, pallas))
        scale = 1.0 / self.global_size if scaled else None
        sticks = self._forward_head(space, tables)
        return self._apply_value_conj(
            self._compress(sticks, tables, scale, pallas))

    # -- batched execution ---------------------------------------------------
    def _decompress_batched(self, values_b, tables):
        """(B, num_values, 2) -> (B, num_sticks, dim_z) — one batched-grid
        kernel launch when the Pallas path is active, vmapped XLA gather
        otherwise."""
        p = self.index_plan
        if not self._pallas_active or self._pallas["dec"] is None:
            if self._pair_io and values_b.shape[1] == 2:
                values_b = jnp.swapaxes(values_b, 1, 2)  # pair -> rows
            return jax.vmap(
                lambda v: stages.decompress(v.astype(self._rdt),
                                            tables["slot_src"],
                                            self._s_pad, p.dim_z))(values_b)
        from .ops import gather_kernel as gk
        t = self._pallas["dec"]
        re, im = gk.planar_from_interleaved(values_b.astype(np.float32),
                                            t.src_rows,
                                            pair=self._pair_io)
        out_re, out_im = gk.run_gather(re, im, tables["dec_tabs"], t)
        B = values_b.shape[0]
        flat = (out_re.reshape(B, -1)[:, :t.num_out]
                + 1j * out_im.reshape(B, -1)[:, :t.num_out])
        return flat.reshape(B, self._s_pad, p.dim_z)

    def _compress_batched(self, sticks_b, tables, scale):
        """(B, num_sticks, dim_z) -> (B, num_values, 2) — or the planar
        pair (B, 2, num_values) for large plans (see pair_values_io)."""
        p = self.index_plan
        if not self._pallas_active or self._pallas["cmp"] is None:
            values = jax.vmap(
                lambda s: stages.compress(s, tables["value_indices"],
                                          scale))(sticks_b)
            return jnp.swapaxes(values, 1, 2) if self._pair_io else values
        from .ops import gather_kernel as gk
        t = self._pallas["cmp"]
        re, im = gk.planar_from_complex(sticks_b, t.src_rows)
        out_re, out_im = gk.run_gather(re, im, tables["cmp_tabs"], t)
        values = gk.interleaved_from_planar(out_re, out_im, t.num_out,
                                            pair=self._pair_io)
        if scale is not None:
            values = values * jnp.asarray(scale, values.dtype)
        return values

    def _backward_after_z_il(self, sr, si, tables):
        """:meth:`_backward_after_z` in the public space layout
        (interleaved for C2C, real for R2C) — the batched fused path's
        vmap body."""
        out = self._backward_after_z(sr, si, tables)
        if self._is_r2c:
            return out
        return jnp.stack([out[0], out[1]], axis=-1)

    def _backward_impl_batched(self, values_b, tables):
        values_b = self._apply_value_conj(values_b, batched=True)
        if self._ds:
            return jax.vmap(
                lambda v: self._ds_backward_impl(v, tables))(values_b)
        if self._use_mdft and self._fused_on("dec"):
            # one batched-grid fused kernel launch, then the xy tail
            # per slab (the z-transformed sticks never touch HBM dense)
            sr_b, si_b = self._decompress_zdft(values_b, tables)
            return jax.vmap(self._backward_after_z_il,
                            in_axes=(0, 0, None))(sr_b, si_b, tables)
        sticks_b = self._decompress_batched(values_b, tables)
        return jax.vmap(self._backward_rest,
                        in_axes=(0, None))(sticks_b, tables)

    def _forward_impl_batched(self, space_b, tables, *, scaled: bool):
        if self._ds:
            return self._apply_value_conj(jax.vmap(
                lambda sp: self._ds_forward_impl(sp, tables, scaled))(
                    space_b), batched=True)
        scale = 1.0 / self.global_size if scaled else None
        if self._use_mdft and self._fused_on("cmp"):
            sp_b = space_b if self._is_r2c \
                else (space_b[..., 0], space_b[..., 1])
            sr_b, si_b = jax.vmap(self._forward_pre_z,
                                  in_axes=(0, None))(sp_b, tables)
            return self._apply_value_conj(
                self._zdft_compress(sr_b, si_b, tables, scaled),
                batched=True)
        if self._use_mdft:
            sticks_b = jax.vmap(
                lambda s, t: self._forward_head(s, t, scale),
                in_axes=(0, None))(space_b, tables)
            return self._apply_value_conj(
                self._compress_batched(sticks_b, tables, None),
                batched=True)
        sticks_b = jax.vmap(self._forward_head,
                            in_axes=(0, None))(space_b, tables)
        return self._apply_value_conj(
            self._compress_batched(sticks_b, tables, scale), batched=True)

    def _batched_jits(self):
        """Lazily-built batched executables over a leading batch axis.

        The reference's multi-transform hand-interleaves the phases of N
        transforms for comm/compute overlap (reference:
        multi_transform_internal.hpp:47-145). For N transforms sharing one
        plan, the TPU-native form is a single executable with a batch
        dimension: XLA sees N× larger FFT batches and one gather per stage
        instead of N dispatches. The compression stages run the Pallas
        kernel with a batched grid (same tables, one launch) when active."""
        if self._batched is None:
            self._batched = {
                "backward": jax.jit(self._backward_impl_batched),
                Scaling.NONE: jax.jit(functools.partial(
                    self._forward_impl_batched, scaled=False)),
                Scaling.FULL: jax.jit(functools.partial(
                    self._forward_impl_batched, scaled=True)),
            }
        return self._batched

    def _stack_coerced(self, items, coerce):
        """Stack per-request arrays into the batch boundary layout. When
        every element coerces to a HOST array (the serving executor's
        common case: numpy request payloads), stack on host and pay ONE
        device transfer — ``jnp.stack`` over B separately-committed
        device arrays costs a device concat kernel plus B puts, which
        measurably erases the batching win for ms-scale transforms
        (spfft_tpu.serve; measured on the CPU backend)."""
        coerced = [coerce(v) for v in items]
        if all(isinstance(c, np.ndarray) for c in coerced):
            return jnp.asarray(np.stack(coerced))
        return jnp.stack(coerced)

    def batch_row_template(self, kind: str):
        """``(shape, dtype)`` of one COERCED host row of a batched
        execution — ``kind`` is ``"values"`` (backward input) or
        ``"space"`` (forward input) — or ``None`` when rows coerce to
        device arrays (double-single plans split on device put).

        This is the contract the serving executor's preallocated staging
        buffers rely on: a host buffer of ``(B,) + shape`` and exactly
        this dtype, filled row-by-row with ``_coerce_values`` /
        ``_coerce_space`` outputs, is accepted by
        :meth:`backward_batched` / :meth:`forward_batched` without any
        per-row re-coercion or host re-stack."""
        if self._ds:
            return None
        p = self.index_plan
        if kind == "values":
            if self._pair_io:
                return (2, p.num_values), self._rdt
            return (p.num_values, 2), self._rdt
        if kind != "space":
            raise InvalidParameterError(
                f"kind must be 'values' or 'space', got {kind!r}")
        shape3 = (self.local_z_length, p.dim_y, p.dim_x)
        if self._is_r2c:
            return shape3, self._rdt
        return shape3 + (2,), self._rdt

    def _prestaged(self, batch, per) -> bool:
        """True when ``batch`` is a host array already in the coerced
        batched layout ``(B,) + per`` at the plan's exact real dtype —
        the serving executor's reusable staging buffers. The dtype check
        is part of the bit-exactness contract: a wider dtype slipping
        through would retrace the jit at that dtype and compute in a
        different precision than the serial path."""
        return (isinstance(batch, np.ndarray)
                and batch.shape[1:] == per
                and batch.dtype == self._rdt)

    def backward_batched(self, values_batch, device=None):
        """Backward-execute a batch: ``values_batch`` is (B, num_values)
        complex or (B, num_values, 2) interleaved ((B, 2, num_values) for
        pair_values_io plans). Returns the (B, ...) stacked space-domain
        result in one fused execution. ``device`` pins the batch to one
        device of a pool (see :meth:`backward`)."""
        per = ((self.index_plan.num_values, 4) if self._ds
               else (2, self.index_plan.num_values) if self._pair_io
               else (self.index_plan.num_values, 2))
        if isinstance(values_batch, jax.Array) \
                and values_batch.shape[1:] == per:
            batch = values_batch
        elif self._prestaged(values_batch, per):
            # pre-staged host buffer (serving executor): one transfer,
            # no per-row coercion
            batch = jnp.asarray(values_batch)
        else:
            batch = self._stack_coerced(values_batch, self._coerce_values)
        self._finalize()
        with timed_transform("backward_batched") as box:
            if device is not None:
                batch = jax.device_put(batch, device)
            box.value = self._guarded(
                "dec", lambda: self._call_aot_or_jit(
                    "batched_backward", self._batched_jits()["backward"],
                    batch, device))
            if self._ds:
                box.value = self._ds_space_to_host(box.value)
        return box.value

    def forward_batched(self, space_batch, scaling: Scaling = Scaling.NONE,
                        device=None):
        """Forward-execute a batch of space-domain slabs in one fused
        execution. Returns (B, num_values, 2) interleaved values —
        (B, 2, num_values) for pair_values_io plans. ``device`` as in
        :meth:`backward`."""
        scaling = Scaling(scaling)
        if self._ds:
            # coerced DS slabs always carry a trailing channel axis:
            # (B, z, y, x, 2) hi/lo for R2C, (B, z, y, x, 4) for C2C —
            # a raw real R2C batch is also ndim 4, so the channel count
            # must be checked, not just the rank
            nch = 2 if self._is_r2c else 4
            coerced = (isinstance(space_batch, jax.Array)
                       and space_batch.ndim == 5
                       and space_batch.shape[-1] == nch)
        else:
            coerced = (isinstance(space_batch, jax.Array)
                       and space_batch.ndim
                       == (4 if self._is_r2c else 5))
        if coerced:
            batch = space_batch
        elif not self._ds and self._prestaged(
                space_batch, self.batch_row_template("space")[0]):
            batch = jnp.asarray(space_batch)
        else:
            batch = self._stack_coerced(space_batch, self._coerce_space)
        self._finalize()
        with timed_transform("forward_batched") as box:
            if device is not None:
                batch = jax.device_put(batch, device)
            box.value = self._guarded(
                "cmp", lambda: self._call_aot_or_jit(
                    "batched_forward_full" if scaling is Scaling.FULL
                    else "batched_forward_none",
                    self._batched_jits()[scaling], batch, device))
            if self._ds:
                box.value = self._ds_values_to_host(box.value)
        return box.value

    # -- fused round trip ----------------------------------------------------
    def _pair_impl(self, values_il, tables, *fn_args, scaled, fn):
        # the ds/mdft branches bypass _backward_impl/_forward_impl, so
        # the hermitian-fold conjugation applies here; the final branch
        # delegates to those impls, which conjugate themselves
        if self._ds:
            # fn is rejected up front (apply_pointwise): a pointwise fn
            # would run at f32 and silently break the double contract
            space4 = self._ds_backward_impl(
                self._apply_value_conj(values_il), tables)
            return self._apply_value_conj(
                self._ds_forward_impl(space4, tables, scaled))
        if self._use_mdft:
            # fully planar round trip; the space domain is materialised
            # in the public interleaved layout ONLY when a pointwise fn
            # needs to see it
            space = self._bwd_space_tp(
                self._apply_value_conj(values_il), tables)
            if fn is not None:
                if self._is_r2c:
                    space = fn(space, *fn_args)
                else:
                    s = fn(jnp.stack([space[0], space[1]], axis=-1),
                           *fn_args)
                    space = (s[..., 0], s[..., 1])
            return self._apply_value_conj(
                self._fwd_values_tp(space, tables, scaled))
        space = self._backward_impl(values_il, tables)
        if fn is not None:
            space = fn(space, *fn_args)
        return self._forward_impl(space, tables, scaled=scaled)

    def apply_pointwise(self, values, fn=None, *fn_args,
                        scaling: Scaling = Scaling.NONE):
        """backward → ``fn(space, *fn_args)`` → forward as ONE fused
        executable.

        The plane-wave-code inner loop (apply a local operator in the space
        domain): ``fn`` receives the space-domain array in its device layout
        — ``(dim_z, dim_y, dim_x, 2)`` interleaved for C2C, real
        ``(dim_z, dim_y, dim_x)`` for R2C — and must return the same shape.
        ``fn=None`` is the identity round trip (the reference benchmark's
        backward+forward pair, benchmark.cpp:84-96). Fusing saves a
        dispatch round trip and lets XLA schedule across the stage
        boundary: 18.6 vs 25.6 ms for the 256^3 identity pair on TPU v5e.

        The compiled executable is cached per ``(fn, scaling)`` by object
        identity, so pass a *stable* callable (module-level function or one
        created once) — a fresh lambda per call recompiles every call and
        grows the cache without bound. Data that changes between calls
        (e.g. the potential field of an SCF iteration) must flow through
        ``fn_args``, which are traced arguments, not compile-time
        constants.

        Returns the (num_values, 2) interleaved frequency values —
        (2, num_values) for pair_values_io plans."""
        scaling = Scaling(scaling)
        if self._ds and fn is not None:
            raise InvalidParameterError(
                "on-device double plans fuse only the identity round "
                "trip (fn=None): a pointwise fn would execute at f32 "
                "and silently break the double contract — compose "
                "backward / fn on the host f64 slab / forward instead "
                "(docs/precision.md)")
        values_il = self._coerce_values(values)
        key = (fn, scaling)
        jitted = self._pair_jits.get(key)
        if jitted is None:
            jitted = jax.jit(
                functools.partial(self._pair_impl,
                                  scaled=scaling is Scaling.FULL, fn=fn),
                donate_argnums=(0,) if self.donate_inputs else ())
            self._pair_jits[key] = jitted
        self._finalize()
        with timed_transform("apply_pointwise") as box:
            if fn is None and not fn_args:
                # the identity pair is a store-exported AOT entry; fn
                # captures are compile-time constants, so only the
                # fn-free round trip can reuse a serialized executable
                box.value = self._call_aot_or_jit(
                    "pair_full" if scaling is Scaling.FULL
                    else "pair_none", jitted, values_il, None)
            else:
                box.value = jitted(values_il, self._tables_hot, *fn_args)
            if self._ds:
                box.value = self._ds_values_to_host(box.value)
        return box.value

    def iterate_pointwise(self, values, fn, *fn_args, steps: int,
                          scaling: Scaling = Scaling.FULL):
        """Run ``steps`` fused round trips values → backward → fn(space) →
        forward → values as ONE executable (``lax.scan`` over the pair), so
        an N-step iterative solver costs a single dispatch.

        ``fn(space, *fn_args)`` as in :meth:`apply_pointwise`; ``fn_args``
        are loop-invariant traced arguments. ``scaling`` defaults to FULL
        so the iteration is a fixed-point map (NONE would multiply by the
        grid size every step). Returns the final (num_values, 2) values.
        Cached per ``(fn, scaling, steps)``; pass a stable callable."""
        scaling = Scaling(scaling)
        if self._ds:
            raise InvalidParameterError(
                "on-device double plans do not fuse iterate_pointwise "
                "(the pointwise fn would execute at f32) — loop "
                "apply_pointwise / backward+forward instead "
                "(docs/precision.md)")
        # the scan carry dtype must match the step output (_rdt); coerce
        # up-front rather than per step
        values_il = self._coerce_values(values).astype(self._rdt)
        key = (fn, scaling, int(steps), "scan")
        jitted = self._pair_jits.get(key)
        if jitted is None:
            scaled = scaling is Scaling.FULL

            def run(values_il, tables, *fn_args):
                def step(v, _):
                    return self._pair_impl(v, tables, *fn_args,
                                           scaled=scaled, fn=fn), None
                out, _ = jax.lax.scan(step, values_il, None,
                                      length=int(steps))
                return out

            jitted = jax.jit(
                run, donate_argnums=(0,) if self.donate_inputs else ())
            self._pair_jits[key] = jitted
        self._finalize()
        with timed_transform("iterate_pointwise") as box:
            box.value = jitted(values_il, self._tables_hot, *fn_args)
        return box.value

    # -- public execution (reference: transform.hpp:198-211) -----------------
    def backward(self, values, device=None):
        """Frequency -> space. ``values`` is (num_values,) complex (or
        interleaved (num_values, 2) real). Returns the space-domain slab:
        (dim_z, dim_y, dim_x, 2) interleaved for C2C, real (dim_z, dim_y,
        dim_x) for R2C. Unnormalised inverse DFT (details.rst
        "Transform Definition").

        ``device`` pins the execution (input + replicated tables) to one
        device of a pool — the serving executor's cross-device
        round-robin; ``None`` keeps the default placement."""
        values_il = self._coerce_values(values)
        self._finalize()
        with timed_transform("backward") as box:
            if device is not None:
                values_il = jax.device_put(values_il, device)
            box.value = self._guarded(
                "dec", lambda: self._call_aot_or_jit(
                    "backward", self._backward_jit, values_il, device))
            if self._ds:
                box.value = self._ds_space_to_host(box.value)
        return box.value

    def _call_aot_or_jit(self, key: str, jitted, arg, device):
        """Dispatch one public execution through the installed AOT
        executable when there is one for this ``key`` and the default
        placement, falling back PERMANENTLY to the jit path on any AOT
        failure (an executable exported under different plan-time env
        decisions can disagree with this process's table pytree — a
        cold-start optimisation must never fail a request)."""
        aot = self._aot.get(key) if self._aot is not None \
            and device is None else None
        tables = self._tables_on(device)
        if aot is not None:
            try:
                return aot.call(arg, tables)
            except Exception as exc:
                self._aot.pop(key, None)
                from . import obs as _obs
                _obs.record_store_aot_skip("call_failed")
                logger.warning(
                    "spfft_tpu: AOT executable %s failed (%r) — "
                    "falling back to the jit path permanently", key,
                    exc)
        return jitted(arg, tables)

    def forward(self, space, scaling: Scaling = Scaling.NONE,
                device=None):
        """Space -> frequency. Returns (num_values, 2) interleaved sparse
        values — (2, num_values) for pair_values_io plans;
        ``scaling=Scaling.FULL`` multiplies by 1/(Nx·Ny·Nz)
        (details.rst "Normalization"). ``device`` as in
        :meth:`backward`."""
        scaling = Scaling(scaling)
        space = self._coerce_space(space)
        self._finalize()
        with timed_transform("forward") as box:
            if device is not None:
                space = jax.device_put(space, device)
            key = "forward_full" if scaling is Scaling.FULL \
                else "forward_none"
            box.value = self._guarded(
                "cmp", lambda: self._call_aot_or_jit(
                    key, self._forward_jit[scaling], space, device))
            if self._ds:
                box.value = self._ds_values_to_host(box.value)
        return box.value

    # -- input coercion ------------------------------------------------------
    def _coerce_values(self, values):
        N = self.index_plan.num_values
        if self._ds:
            from .ops.dsdft import split_host_f64
            if isinstance(values, jax.Array) and values.ndim == 2 \
                    and values.shape == (N, 4):
                return values
            arr = np.asarray(values)
            if arr.shape == (N, 4) and not np.iscomplexobj(arr):
                return jnp.asarray(
                    np.ascontiguousarray(arr.astype(np.float32)))
            if np.iscomplexobj(arr) and arr.shape == (N,):
                re = arr.real.astype(np.float64)
                im = arr.imag.astype(np.float64)
            elif arr.shape == (N, 2):
                re = arr[:, 0].astype(np.float64)
                im = arr[:, 1].astype(np.float64)
            else:
                raise InvalidParameterError(
                    f"expected {N} frequency values, got shape "
                    f"{arr.shape}")
            rh, rl = split_host_f64(re)
            ih, il = split_host_f64(im)
            return jnp.asarray(np.ascontiguousarray(
                np.stack([rh, rl, ih, il], axis=-1)))
        if self._pair_io:
            # planar pair (2, N) device boundary (see pair_values_io)
            if isinstance(values, jax.Array):
                if values.shape == (2, N):
                    return values
                if values.shape == (N, 2):
                    # relayout via host: an on-device transpose materialises
                    # the tiled (N, 2) copy this layout exists to avoid
                    values = np.asarray(values)
            else:
                arr = np.asarray(values)
                if arr.shape == (2, N) and not np.iscomplexobj(arr):
                    # the plan's own output layout, round-tripped via host
                    return jnp.asarray(np.ascontiguousarray(
                        arr.astype(self._rdt)))
            arr = np.asarray(as_interleaved(values, self.precision))
            if arr.shape != (N, 2):
                raise InvalidParameterError(
                    f"expected {N} frequency values, "
                    f"got shape {arr.shape[:-1]}")
            return jnp.asarray(np.ascontiguousarray(arr.T))
        if isinstance(values, jax.Array) and values.ndim == 2 \
                and values.shape == (N, 2):
            return values
        arr = as_interleaved(values, self.precision)
        if arr.shape != (N, 2):
            raise InvalidParameterError(
                f"expected {N} frequency values, "
                f"got shape {arr.shape[:-1]}")
        return arr

    def _coerce_space(self, space):
        p = self.index_plan
        shape3 = (self.local_z_length, p.dim_y, p.dim_x)
        if self._ds:
            from .ops.dsdft import split_host_f64
            nch = 2 if self._is_r2c else 4
            if isinstance(space, jax.Array) \
                    and space.shape == shape3 + (nch,):
                return space
            arr = np.asarray(space)
            if arr.shape == shape3 + (nch,) and not np.iscomplexobj(arr):
                return jnp.asarray(
                    np.ascontiguousarray(arr.astype(np.float32)))
            if self._is_r2c:
                if arr.shape != shape3 or np.iscomplexobj(arr):
                    raise InvalidParameterError(
                        f"expected real space-domain slab {shape3}, "
                        f"got {arr.shape}")
                rh, rl = split_host_f64(arr.astype(np.float64))
                return jnp.asarray(np.ascontiguousarray(
                    np.stack([rh, rl], axis=-1)))
            if np.iscomplexobj(arr) and arr.shape == shape3:
                re = arr.real.astype(np.float64)
                im = arr.imag.astype(np.float64)
            elif arr.shape == shape3 + (2,):
                re = arr[..., 0].astype(np.float64)
                im = arr[..., 1].astype(np.float64)
            else:
                raise InvalidParameterError(
                    f"expected space-domain slab {shape3} complex, "
                    f"got {arr.shape}")
            rh, rl = split_host_f64(re)
            ih, il = split_host_f64(im)
            return jnp.asarray(np.ascontiguousarray(
                np.stack([rh, rl, ih, il], axis=-1)))
        if self._is_r2c:
            arr = space if isinstance(space, jax.Array) \
                else np.asarray(space, self._rdt)
            if arr.shape != shape3:
                raise InvalidParameterError(
                    f"expected real space-domain slab {shape3}, "
                    f"got {arr.shape}")
            return arr
        if isinstance(space, jax.Array) and space.shape == shape3 + (2,):
            return space
        arr = as_interleaved(space, self.precision)
        if arr.shape != shape3 + (2,):
            raise InvalidParameterError(
                f"expected space-domain slab {shape3} complex, "
                f"got {arr.shape[:-1]}")
        return arr


def restore_plan(index_plan: IndexPlan, tables: PlanTables,
                 precision: str = "single", **plan_kwargs) -> TransformPlan:
    """Reconstruct a :class:`TransformPlan` from persisted artifact
    state (:mod:`spfft_tpu.serve.store`): the index plan arrives fully
    materialised and ``tables`` carries the prebuilt gather/fused
    tables, so neither index-table construction nor the background
    compression-table build runs — the restored plan's construction
    cost is the device commit of the tables it ships with.
    ``plan_kwargs`` as in :class:`TransformPlan` (use_pallas,
    donate_inputs, max_rel_error, device_double)."""
    return TransformPlan(index_plan, precision=precision,
                         _restore=tables, **plan_kwargs)


def make_local_plan(transform_type: TransformType, dim_x: int, dim_y: int,
                    dim_z: int, triplets, precision: str = "single",
                    use_pallas: Optional[bool] = None,
                    donate_inputs: bool = False,
                    max_rel_error: Optional[float] = None) -> TransformPlan:
    """Build a local plan from raw index triplets — the moral equivalent of
    ``Grid::create_transform`` without a communicator (reference:
    grid.hpp:138-141). ``donate_inputs=True`` lets XLA reuse the caller's
    input device buffers for outputs (see TransformPlan.donate_inputs).
    ``max_rel_error`` demands an accuracy contract at construction: when
    the calibrated error model (:func:`predicted_rel_error`) says the
    chosen precision cannot meet it, a typed
    :class:`~spfft_tpu.errors.PrecisionContractError` is raised instead
    of returning silently-degraded results."""
    plan = build_index_plan(TransformType(transform_type), dim_x, dim_y,
                            dim_z, np.asarray(triplets))
    return TransformPlan(plan, precision=precision, use_pallas=use_pallas,
                         donate_inputs=donate_inputs,
                         max_rel_error=max_rel_error)

"""The reference-shaped user API: ``Grid`` and ``Transform``.

Mirrors the reference public surface (reference: include/spfft/grid.hpp:49-203,
include/spfft/transform.hpp:56-227) so code written against SpFFT maps
mechanically, while the semantics are TPU-native:

* The reference ``Grid`` pre-allocates two host/device scratch arrays sized to
  caller-declared maxima and every transform carves views out of them
  (reference: grid_internal.cpp:75-98, 207-227). Under XLA the compiler owns
  scratch allocation inside each compiled executable, so ``Grid`` here keeps
  the *limit-validation* role (transforms must fit the declared maxima —
  reference transform_internal.cpp:52-83) and carries the mesh for
  distributed transforms (the communicator analogue, grid.hpp:92-135).
* ``Transform::space_domain_data`` in the reference exposes the internal
  space-domain buffer for the user to read (after backward) or fill (before
  forward) (reference: transform.hpp:184, docs example). Here the transform
  holds the latest space-domain array; ``backward`` returns it and stores it,
  ``forward`` uses the stored array unless one is passed explicitly.
* The float twins (``GridFloat``/``TransformFloat``, reference
  grid_float.hpp) collapse into the ``precision`` argument.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np
from jax.sharding import Mesh

from .errors import InvalidParameterError
from .indexing import build_index_plan
from .parallel.dist import (DistributedTransformPlan, build_distributed_plan)
from .plan import TransformPlan
from .types import (ExchangeType, IndexFormat, ProcessingUnit, Scaling,
                    TransformType)


class Grid:
    """Transform factory with caller-declared size limits.

    Local: ``Grid(max_dim_x, max_dim_y, max_dim_z, max_num_local_z_sticks)``
    (reference: grid.hpp:64-80).
    Distributed: pass ``mesh=`` (+ ``max_local_z_length``) — the communicator
    analogue (reference: grid.hpp:92-135).
    """

    def __init__(self, max_dim_x: int, max_dim_y: int, max_dim_z: int,
                 max_num_local_z_sticks: int,
                 processing_unit: ProcessingUnit = ProcessingUnit.DEVICE,
                 num_threads: int = -1,
                 mesh: Optional[Mesh] = None,
                 max_local_z_length: Optional[int] = None,
                 exchange: ExchangeType = ExchangeType.DEFAULT,
                 precision: str = "single"):
        for name, v in (("max_dim_x", max_dim_x), ("max_dim_y", max_dim_y),
                        ("max_dim_z", max_dim_z)):
            if v < 1:
                raise InvalidParameterError(f"{name} must be >= 1, got {v}")
        if max_num_local_z_sticks < 0:
            raise InvalidParameterError("max_num_local_z_sticks must be >= 0")
        self._max_dim_x = max_dim_x
        self._max_dim_y = max_dim_y
        self._max_dim_z = max_dim_z
        self._max_num_local_z_sticks = max_num_local_z_sticks
        self._max_local_z_length = (max_local_z_length
                                    if max_local_z_length is not None
                                    else max_dim_z)
        self._processing_unit = ProcessingUnit(processing_unit)
        self._num_threads = num_threads
        self._mesh = mesh
        self._exchange = ExchangeType(exchange)
        self._precision = precision

    def copy(self) -> "Grid":
        """Deep-copy constructor parity (reference grid.hpp:82-90 /
        grid_internal.cpp:232-262, where the copy re-allocates fresh
        buffers so the twin grids never share scratch space). Plans here
        own no mutable buffers — XLA allocates per executable — so an
        independent ``Grid`` carrying the same limits IS the deep copy;
        transforms created from either are fully isolated."""
        return Grid(self._max_dim_x, self._max_dim_y, self._max_dim_z,
                    self._max_num_local_z_sticks, self._processing_unit,
                    self._num_threads, self._mesh,
                    self._max_local_z_length, self._exchange,
                    self._precision)

    __copy__ = copy
    __deepcopy__ = lambda self, memo: self.copy()  # noqa: E731

    # -- getters (reference grid.hpp:144-203) --------------------------------
    @property
    def max_dim_x(self) -> int:
        return self._max_dim_x

    @property
    def max_dim_y(self) -> int:
        return self._max_dim_y

    @property
    def max_dim_z(self) -> int:
        return self._max_dim_z

    @property
    def max_num_local_z_columns(self) -> int:
        return self._max_num_local_z_sticks

    @property
    def max_local_z_length(self) -> int:
        return self._max_local_z_length

    @property
    def processing_unit(self) -> ProcessingUnit:
        return self._processing_unit

    @property
    def num_threads(self) -> int:
        """Kept for API parity; threading is XLA's concern here
        (reference: grid.hpp:188, OpenMP thread count)."""
        return self._num_threads

    @property
    def mesh(self) -> Optional[Mesh]:
        """The device mesh (communicator analogue, reference grid.hpp:199)."""
        return self._mesh

    @property
    def distributed(self) -> bool:
        return self._mesh is not None

    # -- factory (reference grid.hpp:113-141) --------------------------------
    def create_transform(self, processing_unit: ProcessingUnit,
                         transform_type: TransformType,
                         dim_x: int, dim_y: int, dim_z: int,
                         local_z_length: Optional[int] = None,
                         num_local_elements: Optional[int] = None,
                         index_format: IndexFormat = IndexFormat.TRIPLETS,
                         indices=None,
                         planes_per_shard: Optional[Sequence[int]] = None,
                         triplets_per_shard: Optional[Sequence] = None,
                         ) -> "Transform":
        """Create a transform within this grid's limits.

        Local: pass ``indices`` as an (n, 3) triplet array (or flat
        interleaved x,y,z like the reference C API).
        Distributed (grid has a mesh): pass ``triplets_per_shard`` and
        ``planes_per_shard``.

        Validation mirrors reference transform_internal.cpp:52-83.
        """
        IndexFormat(index_format)  # only TRIPLETS exists (types.h:78-83)
        transform_type = TransformType(transform_type)
        ProcessingUnit(processing_unit)
        if dim_x > self._max_dim_x or dim_y > self._max_dim_y \
                or dim_z > self._max_dim_z:
            raise InvalidParameterError(
                f"transform dims ({dim_x},{dim_y},{dim_z}) exceed grid maxima "
                f"({self._max_dim_x},{self._max_dim_y},{self._max_dim_z})")

        if self.distributed:
            if triplets_per_shard is None or planes_per_shard is None:
                raise InvalidParameterError(
                    "distributed grid: triplets_per_shard and "
                    "planes_per_shard are required")
            if num_local_elements is not None or local_z_length is not None:
                raise InvalidParameterError(
                    "distributed grid: per-shard sizes come from "
                    "triplets_per_shard/planes_per_shard; num_local_elements "
                    "and local_z_length are not accepted")
            if max(planes_per_shard) > self._max_local_z_length:
                raise InvalidParameterError(
                    "local z length exceeds grid max_local_z_length")
            dist = build_distributed_plan(
                transform_type, dim_x, dim_y, dim_z,
                [np.asarray(t).reshape(-1, 3) for t in triplets_per_shard],
                planes_per_shard)
            if dist.max_sticks > self._max_num_local_z_sticks:
                raise InvalidParameterError(
                    f"{dist.max_sticks} local z sticks exceed grid limit "
                    f"{self._max_num_local_z_sticks}")
            plan = DistributedTransformPlan(
                dist, mesh=self._mesh, precision=self._precision,
                exchange=self._exchange)
            return Transform(plan)

        if indices is None:
            raise InvalidParameterError("indices are required")
        triplets = np.asarray(indices)
        if triplets.ndim == 1:
            # reference C API passes flat interleaved x1,y1,z1,x2,...
            if triplets.size % 3 != 0:
                raise InvalidParameterError(
                    f"flat index array length ({triplets.size}) is not a "
                    "multiple of 3 (expected interleaved x,y,z triplets)")
            triplets = triplets.reshape(-1, 3)
        if num_local_elements is not None \
                and triplets.shape[0] != num_local_elements:
            raise InvalidParameterError(
                f"num_local_elements ({num_local_elements}) != number of "
                f"triplets ({triplets.shape[0]})")
        if local_z_length is not None and local_z_length != dim_z:
            raise InvalidParameterError(
                "local transform requires local_z_length == dim_z")
        index_plan = build_index_plan(transform_type, dim_x, dim_y, dim_z,
                                      triplets)
        if index_plan.num_sticks > self._max_num_local_z_sticks:
            raise InvalidParameterError(
                f"{index_plan.num_sticks} z sticks exceed grid limit "
                f"{self._max_num_local_z_sticks}")
        return Transform(TransformPlan(index_plan,
                                       precision=self._precision))


class Transform:
    """Handle to one compiled sparse FFT, with the reference's execution
    surface (reference: transform.hpp:85-211)."""

    def __init__(self, plan: Union[TransformPlan, DistributedTransformPlan]):
        self._plan = plan
        self._space = None

    # -- getters (reference transform.hpp:91-171) ---------------------------
    @property
    def plan(self):
        return self._plan

    @property
    def type(self) -> TransformType:
        return self._plan.transform_type

    @property
    def dim_x(self) -> int:
        return self._plan.dim_x

    @property
    def dim_y(self) -> int:
        return self._plan.dim_y

    @property
    def dim_z(self) -> int:
        return self._plan.dim_z

    @property
    def distributed(self) -> bool:
        return isinstance(self._plan, DistributedTransformPlan)

    @property
    def processing_unit(self) -> ProcessingUnit:
        """DEVICE semantics always: results stay in HBM; numpy in/out is
        accepted everywhere (reference transform.hpp:151 returns the unit
        the transform was created with — here there is only one compute
        path, the accelerator)."""
        return ProcessingUnit.DEVICE

    @property
    def precision(self) -> str:
        return self._plan.precision

    @property
    def exchange_type(self) -> ExchangeType:
        """The exchange mechanism of a distributed plan; local transforms
        report DEFAULT (no exchange exists — reference grid.hpp only
        defines the exchange on distributed grids)."""
        return getattr(self._plan, "exchange", ExchangeType.DEFAULT)

    @property
    def num_shards(self) -> int:
        return self._plan.dist_plan.num_shards if self.distributed else 1

    @property
    def device_id(self) -> int:
        """For distributed plans, the ordinal of the mesh's first device;
        for local plans, the default device (a local executable follows
        its input's placement, so this is where it runs unless the caller
        device_put its data elsewhere). Reference transform.hpp:157
        returns the GPU device id."""
        if self.distributed:
            return int(self._plan.mesh.devices.flat[0].id)
        import jax
        default = jax.config.jax_default_device
        if default is None:
            return int(jax.devices()[0].id)
        if isinstance(default, str):  # platform name, e.g. "cpu"
            return int(jax.devices(default)[0].id)
        return int(default.id)

    @property
    def num_threads(self) -> int:
        """Intra-op parallelism is XLA's; reported as the device count the
        plan spans (reference transform.hpp:164 returns the OpenMP thread
        count — the per-rank compute-lane analogue)."""
        return self.num_shards

    @property
    def global_size(self) -> int:
        return self._plan.global_size

    @property
    def num_global_elements(self) -> int:
        return self._plan.num_global_elements

    def local_z_length(self, shard: int = 0) -> int:
        if self.distributed:
            return self._plan.local_z_length(shard)
        return self._plan.local_z_length

    def local_z_offset(self, shard: int = 0) -> int:
        if self.distributed:
            return self._plan.local_z_offset(shard)
        return 0

    def local_slice_size(self, shard: int = 0) -> int:
        return self.dim_x * self.dim_y * self.local_z_length(shard)

    def num_local_elements(self, shard: int = 0) -> int:
        if self.distributed:
            return self._plan.num_local_elements(shard)
        return self._plan.num_local_elements

    def clone(self) -> "Transform":
        """A new independent handle over the same compiled plan (reference
        transform.hpp:85; the deep grid copy is unnecessary — jitted
        executables are pure and thread-safe)."""
        return Transform(self._plan)

    # -- space-domain access (reference transform.hpp:184) -------------------
    def space_domain_data(self, location: Optional[ProcessingUnit] = None):
        """The current space-domain data: set by ``backward``, consumed by
        ``forward``. None until one of them ran or the setter was used.

        ``location`` mirrors the reference's processing-unit argument
        (transform.hpp:184): ``ProcessingUnit.HOST`` returns a numpy array,
        ``DEVICE`` (or None) returns the data where it lives.

        Unlike the reference — whose pointer is a writable buffer users
        fill before ``forward`` (transform.hpp:184) — the HOST result is a
        SNAPSHOT: the returned numpy array is marked READ-ONLY so ported
        reference code that writes into it fails loudly (a silent no-op
        would corrupt results). To feed modified space-domain data into
        ``forward``, pass a writable copy explicitly or call
        :meth:`set_space_domain_data`."""
        if self._space is None or location is None:
            return self._space
        if ProcessingUnit(location) == ProcessingUnit.HOST:
            snap = np.asarray(self._space)
            if snap is self._space or (isinstance(self._space, np.ndarray)
                                       and snap.base is self._space):
                # numpy-stored data: np.asarray aliases it — a true
                # snapshot needs a copy or the caller's own reference
                # could still mutate what we promised was frozen
                snap = snap.copy()
            else:
                snap = snap.view()
            snap.flags.writeable = False
            return snap
        return self._space

    def set_space_domain_data(self, space) -> None:
        self._space = space

    # -- execution (reference transform.hpp:198-211) -------------------------
    def backward(self, values):
        """Frequency -> space; stores and returns the space-domain data."""
        self._space = self._plan.backward(values)
        return self._space

    def forward(self, space=None, scaling: Scaling = Scaling.NONE):
        """Space -> frequency, from ``space`` or the stored space-domain
        data."""
        src = space if space is not None else self._space
        if src is None:
            raise InvalidParameterError(
                "no space-domain data: run backward() or "
                "set_space_domain_data() first")
        result = self._plan.forward(src, scaling)
        if space is not None:  # store only after validation succeeded
            self._space = space
        return result

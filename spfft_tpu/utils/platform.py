"""Host-platform helpers for test/dry-run environments.

This container's sitecustomize pre-configures the JAX TPU plugin and may
clobber JAX_PLATFORMS/XLA_FLAGS, so forcing a virtual multi-device CPU
platform must go through the live config — and must happen before the backend
initialises. Shared by the driver entry point, examples and the test
conftest so the workaround lives in one place.
"""

from __future__ import annotations

import os

import jax


def env_provides_devices() -> bool:
    """True if the environment already configures a multi-device platform
    (the driver sets JAX_PLATFORMS=cpu plus
    --xla_force_host_platform_device_count)."""
    return (os.environ.get("JAX_PLATFORMS") == "cpu"
            or "xla_force_host_platform_device_count"
            in os.environ.get("XLA_FLAGS", ""))


def force_virtual_cpu_devices(n: int, trust_env: bool = True) -> None:
    """Force an ``n``-device virtual CPU platform through the live config,
    unless the environment already provides one (and ``trust_env``). A no-op
    if the backend is already initialised (config updates then raise and are
    swallowed — callers check ``len(jax.devices())`` afterwards)."""
    if trust_env and env_provides_devices():
        return
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", max(n, 1))
    except Exception:
        pass

"""Host-platform helpers for test/dry-run environments.

This container's sitecustomize pre-configures the JAX TPU plugin, which
ignores JAX_PLATFORMS/XLA_FLAGS env vars — forcing a virtual multi-device CPU
platform must go through the live config, before the backend initialises.
Shared by the driver entry point, examples, benchmark CLI and the test
conftest so the workaround lives in one place.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger("spfft_tpu")

_cache_configured = False


def enable_persistent_compilation_cache() -> None:
    """Point XLA's persistent compilation cache at a durable directory.

    TPU FFT compiles are the dominant plan-time cost for large grids (16 s
    at 256^3, ~60 s at 512^3, measured — BENCHMARKS.md "envelope"); the
    reference plans in sub-second time because FFTW_ESTIMATE does no
    measurement (reference: src/parameters/parameters.cpp:43-140 plus plan
    construction). A persistent cache makes every plan after the first
    process-lifetime-independent: SCF codes that rebuild plans per geometry
    step pay the compile once per (shape, pipeline) ever, not once per run.

    Knob: ``SPFFT_TPU_CACHE_DIR`` — unset = ``~/.cache/spfft_tpu/xla``;
    ``0``/``off``/empty = disabled. A user-set
    ``jax_compilation_cache_dir`` (config or JAX_COMPILATION_CACHE_DIR env)
    is respected and never overridden. Called lazily at the first plan
    build (NOT at package import — merely importing the package must not
    mutate global JAX config or touch the filesystem); safe to call
    again."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    knob = os.environ.get("SPFFT_TPU_CACHE_DIR")
    if knob is not None and knob.strip().lower() in ("", "0", "off"):
        return
    try:
        if jax.config.jax_compilation_cache_dir:
            return  # user already configured a cache; leave it alone
        if jax.default_backend() != "tpu":
            # CPU compiles are fast, and XLA:CPU AOT cache entries embed
            # host-feature strings that mismatch noisily across loads;
            # the cache pays off on the TPU backend only.
            return
        path = knob or os.path.join(
            os.path.expanduser("~"), ".cache", "spfft_tpu", "xla")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache every compile that takes noticeable time: the default
        # 1 s floor would skip the many small stage executables whose
        # compiles still add up on remote-attached devices. Respect a
        # user-configured floor (env var or non-default config value) —
        # only lower it when it is still at jax's default.
        if ("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ
                and float(jax.config.jax_persistent_cache_min_compile_time_secs)
                == 1.0):
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.2)
    except Exception as e:  # pragma: no cover - config may be frozen
        logger.info("spfft_tpu: persistent compilation cache not enabled "
                    "(%s)", e)


def platform_summary() -> dict:
    """Backend provenance for serving metrics and benchmark JSON: which
    backend the process resolved, how many devices it sees and their
    kind. Initialises the backend on first call (same cost the first
    transform would pay anyway); serving exports embed this so recorded
    throughput numbers carry the platform they were measured on."""
    devices = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_count": len(devices),
        "device_kind": devices[0].device_kind if devices else "none",
    }


def force_virtual_cpu_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU platform through the live config.

    Env vars alone (JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count)
    are NOT sufficient in this container: the pre-registered TPU plugin ignores
    them, so the platform is always forced through the live config. The
    device COUNT still comes from XLA_FLAGS (this jax version has no
    ``jax_num_cpu_devices`` config), which XLA reads at backend
    initialisation — so it is appended here too, effective whenever the
    backend is not yet up. A no-op if the backend is already initialised
    (config updates then raise and are swallowed — callers check
    ``len(jax.devices())`` afterwards)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={max(n, 1)}"
        ).strip()
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", max(n, 1))
    except Exception:
        pass

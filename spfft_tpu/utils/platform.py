"""Host-platform helpers for test/dry-run environments.

This container's sitecustomize pre-configures the JAX TPU plugin, which
ignores JAX_PLATFORMS/XLA_FLAGS env vars — forcing a virtual multi-device CPU
platform must go through the live config, before the backend initialises.
Shared by the driver entry point, examples, benchmark CLI and the test
conftest so the workaround lives in one place.
"""

from __future__ import annotations

import jax


def force_virtual_cpu_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU platform through the live config.

    Env vars alone (JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count)
    are NOT sufficient in this container: the pre-registered TPU plugin ignores
    them, so the platform is always forced through the live config. A no-op if
    the backend is already initialised (config updates then raise and are
    swallowed — callers check ``len(jax.devices())`` afterwards)."""
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", max(n, 1))
    except Exception:
        pass

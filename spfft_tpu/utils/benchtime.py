"""The sync-cancelling wall-clock estimator shared by every benchmark.

The hard-sync readback through a remote-attached device costs 80-120 ms
regardless of queue depth (measured on the axon tunnel — bench.py), so any
"time N pipelined calls then sync once" number includes sync_cost/N of
pure transport latency, and its variance is what moved the round-1/2
headline numbers 10% between sessions. The difference of two group sizes
cancels the constant exactly:

    per_call = (T(g2) - T(g1)) / (g2 - g1)

with each T(g) = g pipelined calls ending in ONE hard sync. Used by
bench.py, scripts/sweep.py and scripts/measure_batch.py so every number
recorded in BENCHMARKS.md comes from the same estimator.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable


@dataclasses.dataclass(frozen=True)
class DiffEstimate:
    """Result of :func:`diff_estimate_seconds`. ``label`` describes the
    methodology that ACTUALLY produced ``seconds`` (so benchmark logs
    cannot silently diverge from the estimator). ``seconds`` is the min
    over trials (downward-biased best case — fine for "best sustained
    rate" headlines); ``median`` is the robust companion statistic for
    threshold tuning, where the min's optimism would shift crossovers
    (round-3 advisor finding)."""

    seconds: float
    spread: float
    fallback: bool
    label: str
    median: float = math.nan

    def __iter__(self):  # (seconds, spread, fallback) unpacking
        return iter((self.seconds, self.spread, self.fallback))


def diff_estimate_seconds(run_group: Callable[[int], float],
                          reps: int = 30,
                          trials: int = 4) -> DiffEstimate:
    """Estimate seconds per call from pipelined groups.

    Args:
      run_group: ``run_group(g)`` runs g pipelined calls, ends with ONE
        hard sync, and returns the wall seconds for the whole group.
      reps: sizing knob — group sizes are ``g1 = max(1, reps // 6)`` and
        ``g2 = max(g1 + 1, reps - g1)``.
      trials: difference trials; the minimum positive difference is
        reported (the best sustained rate the hardware delivered).

    Returns:
      A :class:`DiffEstimate` (iterates as ``(seconds, spread,
      fallback)``). When every difference is non-positive (the per-call
      time is below the sync-cost noise — tiny workloads), falls back to
      the plain pipelined mean of one g2 group, which re-includes
      sync_cost/g2; ``fallback`` is True and ``label`` says so.
    """
    g1 = max(1, reps // 6)
    g2 = max(g1 + 1, reps - g1)
    diffs = [(run_group(g2) - run_group(g1)) / (g2 - g1)
             for _ in range(trials)]
    positive = [d for d in diffs if d > 0]
    if positive:
        best = min(positive)
        spread = (max(positive) - best) / best
        med = sorted(positive)[len(positive) // 2]
        return DiffEstimate(
            best, spread, False,
            f"min of sync-cancelling trials ((T({g2})-T({g1}))/{g2 - g1}, "
            f"trial spread +{spread * 100:.1f}%, median "
            f"{med * 1e3:.3g} ms)", med)
    t = run_group(g2) / g2
    return DiffEstimate(t, math.nan, True,
                        f"pipelined mean of {g2} "
                        f"(diff estimator below noise)", t)

"""The sync-cancelling wall-clock estimator shared by every benchmark.

The hard-sync readback through a remote-attached device costs ~85-130 ms
regardless of queue depth (measured on the axon tunnel — bench.py,
scripts/probe_r5_mode.py), so any "time N pipelined calls then sync once"
number includes sync_cost/N of pure transport latency. The difference of
two group sizes cancels the constant:

    per_call = (T(g2) - T(g1)) / (g2 - g1)

with each T(g) = g pipelined calls ending in ONE hard sync.

ROBUSTNESS (round 5): the sync cost is itself BIMODAL (~88 vs ~128 ms,
constant per group regardless of group size — probe_r5_mode.py measured
13.3 ms/pair of apparent contrast at g=3 vs 4.1 ms/pair at g=10, i.e. a
fixed ~40 ms/group term). A min-of-single-diffs statistic therefore
fabricates fast readings whenever T(g1) catches a slow sync and T(g2) a
fast one (−40 ms / (g2−g1) ≈ −3 ms/call at the bench sizes): this is
exactly the round-4 "device fast mode" (8.6–9.5 ms sightings at a true
~12.5 ms pair). The estimator now samples each group size ``trials``
times and differences the MEDIANS, which both sit on the majority sync
mode, so the constant cancels without mismatched pairings. ``minimum``
(min over per-trial diffs, the old statistic) is kept for comparison
with older recorded numbers; it is downward-biased and must not be used
for decisions or headlines.

Used by bench.py, scripts/sweep.py and scripts/measure_batch.py so every
number recorded in BENCHMARKS.md comes from the same estimator.
"""

from __future__ import annotations

import dataclasses
import math
import statistics
from typing import Callable


@dataclasses.dataclass(frozen=True)
class DiffEstimate:
    """Result of :func:`diff_estimate_seconds`. ``label`` describes the
    methodology that ACTUALLY produced ``seconds`` (so benchmark logs
    cannot silently diverge from the estimator). ``seconds`` is the
    sync-robust median-difference statistic; ``median`` aliases it for
    callers that were already using the robust companion for threshold
    tuning (round-3 advisor finding). ``minimum`` is the legacy
    min-of-single-diffs value — downward-biased by sync-mode mismatch
    (see module docstring), reported only for continuity."""

    seconds: float
    spread: float
    fallback: bool
    label: str
    median: float = math.nan
    minimum: float = math.nan

    def __iter__(self):  # (seconds, spread, fallback) unpacking
        return iter((self.seconds, self.spread, self.fallback))


def diff_estimate_seconds(run_group: Callable[[int], float],
                          reps: int = 30,
                          trials: int = 4) -> DiffEstimate:
    """Estimate seconds per call from pipelined groups.

    Args:
      run_group: ``run_group(g)`` runs g pipelined calls, ends with ONE
        hard sync, and returns the wall seconds for the whole group.
      reps: sizing knob — group sizes are ``g1 = max(1, reps // 6)`` and
        ``g2 = max(g1 + 1, reps - g1)``.
      trials: samples per group size; the estimate is
        ``(median T(g2) - median T(g1)) / (g2 - g1)``.

    Returns:
      A :class:`DiffEstimate` (iterates as ``(seconds, spread,
      fallback)``). When the median difference is non-positive (the
      per-call time is below the sync-cost noise — tiny workloads),
      falls back to the plain pipelined mean of one g2 group, which
      re-includes sync_cost/g2; ``fallback`` is True and ``label`` says
      so.
    """
    g1 = max(1, reps // 6)
    g2 = max(g1 + 1, reps - g1)
    # alternate sizes so slow drift (if any) hits both groups equally
    t1s, t2s = [], []
    for _ in range(trials):
        t2s.append(run_group(g2))
        t1s.append(run_group(g1))
    # median_high, not median: with an even sample count a plain median
    # AVERAGES the two middle samples — a 2-2 fast/slow sync split would
    # put one group's median between the modes while the other sits on a
    # mode, re-introducing the mismatch bias. median_high is always a
    # real sample and lands on the majority (slow) mode whenever at
    # least half the samples do, so both group medians cancel exactly.
    med = (statistics.median_high(t2s)
           - statistics.median_high(t1s)) / (g2 - g1)
    diffs = [(t2 - t1) / (g2 - g1) for t1, t2 in zip(t1s, t2s)]
    positive = [d for d in diffs if d > 0]
    minimum = min(positive) if positive else math.nan
    if med > 0:
        spread = ((max(positive) - min(positive)) / med
                  if len(positive) > 1 else 0.0)
        return DiffEstimate(
            med, spread, False,
            f"sync-robust median estimator ((medT({g2})-medT({g1}))/"
            f"{g2 - g1}, {trials} samples/size, per-trial spread "
            f"{spread * 100:.1f}%)", med, minimum)
    # below the sync noise floor: the per-call time is smaller than the
    # sync jitter. Reuse the samples already collected (no fresh group —
    # it would cost another ~100 ms sync for ONE unreplicated sample).
    t = statistics.median_high(t2s) / g2
    return DiffEstimate(t, math.nan, True,
                        f"pipelined median of {trials}x{g2} "
                        f"(diff estimator below noise)", t, minimum)
